"""Pallas NIC kernel parity vs the jnp formulation (interpret mode on CPU).

RETIRED with the kernel (2026-07-29, docs/DESIGN.md): attic/ is not a
package and is outside pytest's testpaths. To revive, restore
nic_pallas.py under nhd_tpu/ and point this import at it.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from nic_pallas import BN, nic_any_first, nic_any_first_reference  # noqa: E402


def make_case(rng, T, N, U, K, C, A):
    UK, CA = U * K, C * A
    free_rx = rng.uniform(-1, 90, (N, UK)).astype(np.float32)
    free_tx = rng.uniform(-1, 90, (N, UK)).astype(np.float32)
    dem_rx = rng.uniform(0, 50, (T, CA, UK)).astype(np.float32)
    dem_tx = rng.uniform(0, 50, (T, CA, UK)).astype(np.float32)
    unchosen = rng.random((CA, UK)) < 0.5
    dem_rx[np.broadcast_to(unchosen, (T, CA, UK))] = 0.0
    dem_tx[np.broadcast_to(unchosen, (T, CA, UK))] = 0.0
    valid = rng.random((N, CA)) < 0.8
    pci_ok = rng.random((N, CA)) < 0.7
    map_pci = (rng.random(T) < 0.5).astype(np.int32)
    return (free_rx, free_tx, dem_rx, dem_tx, unchosen, valid, pci_ok, map_pci)


@pytest.mark.parametrize("shape", [(2, BN, 2, 2, 4, 4), (3, 2 * BN, 2, 4, 4, 16)])
def test_pallas_matches_reference(shape):
    T, N, U, K, C, A = shape
    rng = np.random.default_rng(7)
    args = make_case(rng, T, N, U, K, C, A)
    dims = dict(U=U, K=K, C=C, A=A)
    any_p, first_p, count_p = nic_any_first(*args, **dims, interpret=True)
    any_r, first_r, count_r = nic_any_first_reference(*args, **dims)
    np.testing.assert_array_equal(np.asarray(any_p), np.asarray(any_r))
    # first_a only meaningful where any is True
    mask = np.asarray(any_r)
    np.testing.assert_array_equal(
        np.asarray(first_p)[mask], np.asarray(first_r)[mask]
    )
    # real pick counts (the multi-claim capacity hint) must match too
    np.testing.assert_array_equal(np.asarray(count_p), np.asarray(count_r))
    assert (np.asarray(count_p) > 0).sum() == mask.sum()


@pytest.mark.parametrize("shape", [(8, 2 * BN, 2, 7, 8, 4), (1, BN, 2, 2, 4, 4)])
def test_pallas_lowers_for_tpu(shape):
    """Regression: the Mosaic (TPU) lowering runs at trace time, so a CPU
    host can validate it via jax.export with platforms=["tpu"] — interpret
    mode skips exactly the block-shape/dtype rules that broke twice on the
    real chip (rank-1 span rule in r2; (8,128) divisibility on the (1,1)
    map_pci block and the float32-only argmax caught on hardware in r3).
    """
    import functools

    import jax
    from jax import export as jexport

    T, N, U, K, C, A = shape
    rng = np.random.default_rng(3)
    args = make_case(rng, T, N, U, K, C, A)
    fn = functools.partial(
        nic_any_first, U=U, K=K, C=C, A=A, interpret=False
    )
    exp = jexport.export(jax.jit(fn), platforms=["tpu"])(
        *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args]
    )
    assert len(exp.serialize()) > 0
