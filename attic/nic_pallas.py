"""Pallas TPU kernel for the NIC feasibility predicate.

The NIC check is the solver's deepest lattice — the reference's innermost
deepcopy-per-combination nest (Matcher.py:242-268) becomes, in tensor form,
``fit[T, N, C, A] = all_(u,k)( unchosen | (dem ≤ free) )`` reduced to
``nic_any[T, N, C]`` and the first feasible pick ``first_a[T, N, C]``.

XLA already fuses this well (kernel.py), so the Pallas version is an
*optional* path (NHD_TPU_PALLAS=1): it streams node blocks through VMEM and
never materializes the [T, N, C, A] intermediate in HBM, which matters when
C·A grows (many groups × many NICs). The unrolled u/k loop is static and
small (≤ U·K ≤ 16 for real topologies).

Correctness is pinned against the jnp formulation in
tests/test_nic_pallas.py (interpret mode on CPU; compiled on TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

BN = 128  # node block per grid step


def _kernel(U, K, C, A,
            free_rx_ref, free_tx_ref, dem_rx_ref, dem_tx_ref,
            unchosen_ref, valid_ref, pci_ok_ref, map_pci_ref,
            any_ref, first_ref, count_ref):
    CA = C * A
    fit = jnp.ones((BN, CA), dtype=jnp.bool_)
    # static unroll over the (numa, nic) slots
    for uk in range(U * K):
        dem_rx = dem_rx_ref[0, :, uk]        # [CA]
        dem_tx = dem_tx_ref[0, :, uk]
        free_rx = free_rx_ref[:, uk]         # [BN]
        free_tx = free_tx_ref[:, uk]
        ok = (dem_rx[None, :] <= free_rx[:, None]) & (
            dem_tx[None, :] <= free_tx[:, None]
        )
        fit = fit & (unchosen_ref[:, uk][None, :] | ok)

    is_pci = map_pci_ref[pl.program_id(0), 0] != 0
    fit = fit & valid_ref[:, :] & (pci_ok_ref[:, :] | ~is_pci)

    fit3 = fit.reshape(BN, C, A)
    any_ref[0] = jnp.any(fit3, axis=-1)
    # Mosaic's argmax lowering is float32-only; 0.0/1.0 keeps bool-argmax
    # semantics exactly (first True, else 0)
    first_ref[0] = jnp.argmax(
        fit3.astype(jnp.float32), axis=-1
    ).astype(jnp.int32)
    # real per-combo pick counts: the batch scheduler's multi-claim
    # capacity hint (kernel.py n_picks) — without this the pallas path
    # degraded the hint to 1 and paid extra rounds (VERDICT r1 weak-2)
    count_ref[0] = jnp.sum(fit3.astype(jnp.int32), axis=-1)


@functools.partial(jax.jit, static_argnames=("U", "K", "C", "A", "interpret"))
def nic_any_first(
    free_rx,      # [N, U*K] f32 — per-node NIC rx headroom, -1 where absent
    free_tx,      # [N, U*K] f32
    dem_rx,       # [T, C*A, U*K] f32 — demand each pick places on each slot
    dem_tx,       # [T, C*A, U*K] f32
    unchosen,     # [C*A, U*K] bool — slot not used by this pick (static)
    valid,        # [N, C*A] bool — chosen ordinals exist on the node
    pci_ok,       # [N, C*A] bool — PCI-switch GPUs available
    map_pci,      # [T] int32 — pod type uses PCI map mode
    *, U: int, K: int, C: int, A: int, interpret: bool = False,
):
    """Returns (nic_any[T, N, C] bool, first_a[T, N, C] int32,
    n_picks[T, N, C] int32)."""
    T, N = dem_rx.shape[0], free_rx.shape[0]
    assert N % BN == 0, f"node axis must be padded to {BN}"
    grid = (T, N // BN)

    # Mosaic block-shape rules: rank-1 blocks must span the whole array,
    # and the last two dims of rank-2+ blocks must be divisible by (8, 128)
    # or equal the array dims. A (1, 1) block over [T, 1] violates the
    # sublane rule whenever T > 1, so the per-type scalar rides as the
    # FULL [T, 1] array (tiny) and the kernel indexes it by program_id(0).
    map_pci = map_pci.reshape(T, 1)

    kernel = functools.partial(_kernel, U, K, C, A)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((BN, U * K), lambda t, nb: (nb, 0)),   # free_rx
            pl.BlockSpec((BN, U * K), lambda t, nb: (nb, 0)),   # free_tx
            pl.BlockSpec((1, C * A, U * K), lambda t, nb: (t, 0, 0)),  # dem_rx
            pl.BlockSpec((1, C * A, U * K), lambda t, nb: (t, 0, 0)),  # dem_tx
            pl.BlockSpec((C * A, U * K), lambda t, nb: (0, 0)),  # unchosen
            pl.BlockSpec((BN, C * A), lambda t, nb: (nb, 0)),   # valid
            pl.BlockSpec((BN, C * A), lambda t, nb: (nb, 0)),   # pci_ok
            pl.BlockSpec((T, 1), lambda t, nb: (0, 0)),         # map_pci
        ],
        out_specs=[
            pl.BlockSpec((1, BN, C), lambda t, nb: (t, nb, 0)),
            pl.BlockSpec((1, BN, C), lambda t, nb: (t, nb, 0)),
            pl.BlockSpec((1, BN, C), lambda t, nb: (t, nb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, N, C), jnp.bool_),
            jax.ShapeDtypeStruct((T, N, C), jnp.int32),
            jax.ShapeDtypeStruct((T, N, C), jnp.int32),
        ],
        interpret=interpret,
    )(free_rx, free_tx, dem_rx, dem_tx, unchosen, valid, pci_ok, map_pci)


def nic_any_first_reference(
    free_rx, free_tx, dem_rx, dem_tx, unchosen, valid, pci_ok, map_pci,
    *, U, K, C, A,
):
    """The jnp formulation (matches kernel.py's inline math) for parity."""
    ok = (dem_rx[:, None] <= free_rx[None, :, None, :]) & (
        dem_tx[:, None] <= free_tx[None, :, None, :]
    )  # [T, N, CA, UK]
    fit = jnp.all(unchosen[None, None] | ok, axis=-1)  # [T, N, CA]
    fit = fit & valid[None] & (pci_ok[None] | ~(map_pci[:, None, None] != 0))
    fit3 = fit.reshape(*fit.shape[:2], C, A)
    return (
        jnp.any(fit3, -1),
        jnp.argmax(fit3, -1).astype(jnp.int32),
        jnp.sum(fit3.astype(jnp.int32), -1),
    )
