"""Prometheus metrics endpoint tests — deliberately grpc-free: the exporter
is stdlib-only and must keep working without the optional cluster extras.
Covers the histogram families that replaced the lossy last_* gauges
(PR 3), the exposition-format exactness rules, and the flight-recorder
HTTP views (/decisions, /explain, /trace)."""

import json
import queue
import re
import threading
import urllib.error
import urllib.request

import pytest

import nhd_tpu.obs as obs
from nhd_tpu.obs.histo import Histogram, reset_all
from nhd_tpu.rpc.metrics import MetricsServer, render_metrics
from tests.test_scheduler import make_backend, make_scheduler, pod_cfg


@pytest.fixture
def metrics_stack():
    reset_all()  # histogram registry is process-global; isolate counts
    backend = make_backend(n_nodes=2)
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()

    stop = threading.Event()

    def pump():
        while not stop.is_set():
            try:
                item = sched.rpcq.get(timeout=0.05)
            except queue.Empty:
                continue
            sched._parse_rpc_req(*item)

    threading.Thread(target=pump, daemon=True).start()
    server = MetricsServer(sched.rpcq, port=0, backend=backend)
    server.start()
    yield server
    server.stop()
    stop.set()


def _get(server, path: str) -> str:
    return urllib.request.urlopen(
        f"http://localhost:{server.port}{path}", timeout=5
    ).read().decode()


def test_metrics_endpoint(metrics_stack):
    body = _get(metrics_stack, "/metrics")
    assert "nhd_failed_schedule_total 0" in body
    assert 'nhd_node_pods{node="node0"} 1' in body
    assert 'nhd_node_active{node="node1"} 1' in body
    assert 'dir="rx"' in body
    # solver-phase counters from the scheduled batch
    assert "nhd_batches_total 1" in body
    assert "nhd_scheduled_total 1" in body
    assert "nhd_solve_seconds_total" in body
    # PR 3 gap fixes: queue depth, uptime, trace-ring occupancy
    assert "nhd_event_queue_depth 0" in body
    assert "nhd_uptime_seconds" in body
    assert "nhd_trace_ring_spans 0" in body
    assert "nhd_trace_enabled 0" in body
    # JIT program accounting from the batch's solves
    assert "nhd_jit_compiles_total" in body
    assert 'nhd_jit_shape_uses_total{shape="' in body


def test_metrics_histogram_families(metrics_stack):
    """Acceptance: >= 4 histogram families with correct cumulative
    buckets serve on /metrics."""
    body = _get(metrics_stack, "/metrics")
    families = set(re.findall(r"# TYPE (nhd_\w+) histogram", body))
    assert {
        "nhd_bind_latency_seconds", "nhd_queue_wait_seconds",
        "nhd_solve_phase_seconds", "nhd_select_phase_seconds",
        "nhd_assign_phase_seconds", "nhd_api_call_seconds",
    } <= families
    assert len(families) >= 4
    # the fixture's one batch observed exactly one phase sample and one
    # bind; cumulative buckets must be monotone and end at the count
    for fam in ("nhd_solve_phase_seconds", "nhd_bind_latency_seconds"):
        counts = [
            int(m) for m in re.findall(
                fam + r'_bucket\{le="[^"]+"\} (\d+)', body
            )
        ]
        assert counts == sorted(counts), f"{fam} buckets not cumulative"
        total = int(re.search(fam + r"_count (\d+)", body).group(1))
        assert counts[-1] == total == 1
    # the lossy last_* gauges are gone
    assert "nhd_last_batch_pods" not in body
    assert "nhd_last_bind_p99_seconds" not in body


def test_histogram_buckets_exact():
    h = Histogram("t_seconds", "test histogram", (0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 5.0, 50.0):
        h.observe(v)
    cum, total_sum, count = h.snapshot()
    # le is inclusive: the 0.1 observation lands in the 0.1 bucket
    assert cum == [2, 3, 4, 5]
    assert count == 5 and total_sum == 55.65
    lines = h.render()
    assert "# TYPE nhd_t_seconds histogram" in lines
    assert 'nhd_t_seconds_bucket{le="0.1"} 2' in lines
    assert 'nhd_t_seconds_bucket{le="1"} 3' in lines
    assert 'nhd_t_seconds_bucket{le="10"} 4' in lines
    assert 'nhd_t_seconds_bucket{le="+Inf"} 5' in lines
    # exact (non-:g) rendering for sum and count
    assert "nhd_t_seconds_sum 55.65" in lines
    assert "nhd_t_seconds_count 5" in lines


def test_histogram_rejects_bad_buckets():
    with pytest.raises(ValueError):
        Histogram("x", "h", ())
    with pytest.raises(ValueError):
        Histogram("x", "h", (1.0, 0.5))
    with pytest.raises(ValueError):
        Histogram("x", "h", (1.0, 1.0))


def test_histogram_large_counts_render_exactly():
    h = Histogram("big_seconds", "exactness", (1.0,))
    h._counts[0] = 10_000_019  # > 1e6: the :g precision-loss regime
    h._count = 10_000_019
    lines = h.render()
    assert 'nhd_big_seconds_bucket{le="1"} 10000019' in lines
    assert "nhd_big_seconds_count 10000019" in lines


def test_metrics_query_string_ok(metrics_stack):
    """Prometheus params add a query string; still a valid scrape."""
    body = _get(metrics_stack, "/metrics?collect=node")
    assert "nhd_node_free_cpus" in body


def test_metrics_404(metrics_stack):
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"http://localhost:{metrics_stack.port}/nope", timeout=5
        )


def test_explain_endpoint(metrics_stack):
    """GET /explain?pod= reuses solver/explain.py through the scheduler
    thread (the single owner of the node mirror)."""
    out = json.loads(_get(metrics_stack, "/explain?pod=default/triad-0"))
    assert out["pod"] == "default/triad-0"
    assert "schedulable" in out["summary"] or out["summary"]
    assert isinstance(out["verdicts"], list) and len(out["verdicts"]) == 2
    # bare pod name defaults to the default namespace
    out2 = json.loads(_get(metrics_stack, "/explain?pod=triad-0"))
    assert out2["pod"] == "default/triad-0"


def test_explain_endpoint_errors(metrics_stack):
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        _get(metrics_stack, "/explain?pod=default/ghost")
    assert exc_info.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        _get(metrics_stack, "/explain")
    assert exc_info.value.code == 400


def test_decisions_endpoint_recorder_off(metrics_stack):
    out = json.loads(_get(metrics_stack, "/decisions"))
    assert out == {"enabled": False, "decisions": []}


def test_decisions_and_trace_endpoints_recorder_on(metrics_stack):
    rec = obs.enable(capacity=256)
    try:
        rec.record("solve", 1.0, 0.5, cat="pod", corr="c-x")
        rec.record_decision({
            "pod": "p0", "ns": "default", "corr": "c-x",
            "outcome": "scheduled", "node": "node0", "phases": {},
        })
        out = json.loads(_get(metrics_stack, "/decisions?n=5"))
        assert out["enabled"] and out["decisions"][0]["pod"] == "p0"
        trace = json.loads(_get(metrics_stack, "/trace"))
        assert obs.validate_chrome_trace(trace) == []
        body = _get(metrics_stack, "/metrics")
        assert "nhd_trace_enabled 1" in body
        assert "nhd_trace_ring_spans 1" in body
        assert "nhd_trace_ring_capacity 256" in body
    finally:
        obs.disable()


def test_trace_endpoint_recorder_off(metrics_stack):
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        _get(metrics_stack, "/trace")
    assert exc_info.value.code == 404


def test_stop_releases_port(metrics_stack):
    port = metrics_stack.port
    metrics_stack.stop()          # fixture teardown will re-stop: idempotent
    # rebinding the same fixed port must succeed immediately
    server2 = MetricsServer(queue.Queue(), port=port)
    server2.stop()                # never started: must not block


def test_render_escapes_nothing_unexpected():
    out = render_metrics(
        [{"name": "n0", "freecpu": 1, "freegpu": 2, "freehuge_gb": -3,
          "totalpods": 0, "active": False, "nicstats": [[1.5, 0.0]]}],
        failed_count=7,
    )
    assert "nhd_failed_schedule_total 7" in out
    assert 'nhd_node_free_hugepages_gb{node="n0"} 0' in out  # clamped
    assert 'nhd_node_active{node="n0"} 0' in out


# ---------------------------------------------------------------------------
# ISSUE 7: SLO families + per-(phase, shape) attribution on /metrics
# ---------------------------------------------------------------------------

def test_slo_families_exposed(metrics_stack):
    body = _get(metrics_stack, "/metrics")
    assert "# TYPE nhd_slo_bind_target_seconds gauge" in body
    assert "nhd_slo_bind_observations_total" in body
    assert 'nhd_slo_bind_burn_rate{window="5m"}' in body
    assert 'nhd_slo_bind_burn_rate{window="1h"}' in body
    # the batch the fixture scheduled was observed against the SLO
    # (creation -> bound on the backend clock)
    assert "nhd_time_to_bind_seconds_bucket" in body


def test_round_phase_attribution_exposed(metrics_stack):
    body = _get(metrics_stack, "/metrics")
    # the labeled histogram family: one child per solver round phase
    assert "# TYPE nhd_round_phase_seconds histogram" in body
    # 'encode' runs on every path; 'solve' only on batches big enough to
    # dodge the fast-join shortcut, so pin the always-present phase
    assert 'nhd_round_phase_seconds_bucket{phase="encode"' in body
    # the per-(phase, shape-bucket) counter from the jit-stats table
    assert "# TYPE nhd_jit_phase_seconds_total counter" in body
    assert re.search(
        r'nhd_jit_phase_seconds_total\{phase="encode",'
        r'shape="U\d+_K\d+_N\d+"\}',
        body,
    )


# ---------------------------------------------------------------------------
# ISSUE 9: incremental device-resident state families
# ---------------------------------------------------------------------------

def test_device_state_families_exposed(metrics_stack):
    """The nhd_device_state_* counters ride the ApiCounters.KNOWN loop
    (pre-seeded to 0, visible from process start) and the labeled
    rebuild-reason family renders from the bounded-vocabulary registry."""
    from nhd_tpu.solver.encode import _count_rebuild

    _count_rebuild("compaction")
    body = _get(metrics_stack, "/metrics")
    for fam, kind in (
        ("nhd_device_state_events_total", "counter"),
        ("nhd_device_state_deltas_total", "counter"),
        ("nhd_device_state_rows_uploaded_total", "counter"),
        ("nhd_device_state_full_rebuilds_total", "counter"),
        ("nhd_device_state_resident_age_seconds", "gauge"),
    ):
        assert f"# TYPE {fam} {kind}" in body, fam
    assert "# TYPE nhd_device_state_rebuilds_total counter" in body
    assert re.search(
        r'nhd_device_state_rebuilds_total\{reason="compaction"\} \d+', body
    )


def test_device_state_rebuild_reason_vocabulary_is_bounded():
    """Novel reasons fold into 'other' — the NHD603 cardinality stance."""
    from nhd_tpu.solver.encode import (
        REBUILD_REASONS,
        _count_rebuild,
        rebuild_reasons_snapshot,
        reset_delta_metrics,
    )

    reset_delta_metrics()
    _count_rebuild("totally-made-up-reason")
    _count_rebuild("new-group")
    snap = rebuild_reasons_snapshot()
    assert snap.get("other") == 1
    assert snap.get("new-group") == 1
    assert set(snap) <= set(REBUILD_REASONS) | {"other"}
    reset_delta_metrics()


def test_labeled_histogram_render_exact():
    from nhd_tpu.obs.histo import LabeledHistogram

    lh = LabeledHistogram("x_seconds", "phase", "help", buckets=(0.1, 1.0))
    assert lh.render() == []  # no children yet: family stays silent
    lh.observe("solve", 0.05)
    lh.observe("solve", 0.5)
    lh.observe("select", 2.0)
    lines = lh.render()
    assert 'nhd_x_seconds_bucket{phase="solve",le="0.1"} 1' in lines
    assert 'nhd_x_seconds_bucket{phase="solve",le="+Inf"} 2' in lines
    assert 'nhd_x_seconds_count{phase="select"} 1' in lines
    assert 'nhd_x_seconds_bucket{phase="select",le="1"} 0' in lines
    lh.reset()
    assert lh.render() == []


def test_labeled_histogram_observe_unregistered_raises():
    from nhd_tpu.obs.histo import observe_labeled

    with pytest.raises(KeyError):
        observe_labeled("no_such_family", "solve", 1.0)
