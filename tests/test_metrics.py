"""Prometheus metrics endpoint tests — deliberately grpc-free: the exporter
is stdlib-only and must keep working without the optional cluster extras."""

import queue
import threading
import urllib.error
import urllib.request

import pytest

from nhd_tpu.rpc.metrics import MetricsServer, render_metrics
from tests.test_scheduler import make_backend, make_scheduler, pod_cfg


@pytest.fixture
def metrics_stack():
    backend = make_backend(n_nodes=2)
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()

    stop = threading.Event()

    def pump():
        while not stop.is_set():
            try:
                item = sched.rpcq.get(timeout=0.05)
            except queue.Empty:
                continue
            sched._parse_rpc_req(item[0], item[1])

    threading.Thread(target=pump, daemon=True).start()
    server = MetricsServer(sched.rpcq, port=0)
    server.start()
    yield server
    server.stop()
    stop.set()


def test_metrics_endpoint(metrics_stack):
    body = urllib.request.urlopen(
        f"http://localhost:{metrics_stack.port}/metrics", timeout=5
    ).read().decode()
    assert "nhd_failed_schedule_total 0" in body
    assert 'nhd_node_pods{node="node0"} 1' in body
    assert 'nhd_node_active{node="node1"} 1' in body
    assert 'dir="rx"' in body
    # solver-phase counters from the scheduled batch
    assert "nhd_batches_total 1" in body
    assert "nhd_scheduled_total 1" in body
    assert "nhd_solve_seconds_total" in body
    assert "nhd_last_bind_p99_seconds" in body


def test_metrics_query_string_ok(metrics_stack):
    """Prometheus params add a query string; still a valid scrape."""
    body = urllib.request.urlopen(
        f"http://localhost:{metrics_stack.port}/metrics?collect=node", timeout=5
    ).read().decode()
    assert "nhd_node_free_cpus" in body


def test_metrics_404(metrics_stack):
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(
            f"http://localhost:{metrics_stack.port}/nope", timeout=5
        )


def test_stop_releases_port(metrics_stack):
    port = metrics_stack.port
    metrics_stack.stop()          # fixture teardown will re-stop: idempotent
    # rebinding the same fixed port must succeed immediately
    server2 = MetricsServer(queue.Queue(), port=port)
    server2.stop()                # never started: must not block


def test_render_escapes_nothing_unexpected():
    out = render_metrics(
        [{"name": "n0", "freecpu": 1, "freegpu": 2, "freehuge_gb": -3,
          "totalpods": 0, "active": False, "nicstats": [[1.5, 0.0]]}],
        failed_count=7,
    )
    assert "nhd_failed_schedule_total 7" in out
    assert 'nhd_node_free_hugepages_gb{node="n0"} 0' in out  # clamped
    assert 'nhd_node_active{node="n0"} 0' in out
