"""HostNode label parsing + resource accounting tests (reference: Node.py)."""

from nhd_tpu.core.node import HostNode
from nhd_tpu.core.topology import SmtMode
from nhd_tpu.sim import SynthNodeSpec, make_node, make_node_labels


def default_node(**kw):
    return make_node(SynthNodeSpec(**kw))


def test_core_layout_smt():
    node = default_node(phys_cores=8, sockets=2, smt=True, reserved_cores=2)
    assert node.numa_nodes == 2
    assert len(node.cores) == 16
    # siblings: c <-> c+8
    assert node.cores[3].sibling == 11
    assert node.cores[11].sibling == 3
    # socket blocks: 0-3 socket0, 4-7 socket1 (and same for siblings)
    assert node.cores[2].socket == 0
    assert node.cores[6].socket == 1
    assert node.cores[10].socket == 0
    # reserved: cores 0,1 and siblings 8,9 are used
    assert node.cores[0].used and node.cores[8].used
    assert not node.cores[2].used
    # free physical cores: socket0 lost 2, socket1 intact
    assert node.free_cpu_cores_per_numa() == [2, 4]


def test_core_layout_no_smt():
    node = default_node(phys_cores=8, sockets=2, smt=False, reserved_cores=1)
    assert len(node.cores) == 8
    assert node.cores[0].sibling == -1
    assert node.free_cpu_cores_per_numa() == [3, 4]


def test_partial_sibling_blocks_pair():
    node = default_node(phys_cores=8, sockets=2, smt=True, reserved_cores=0)
    assert node.free_cpu_cores_per_numa() == [4, 4]
    # claim one logical core: its physical core no longer counts as free
    node.cores[2].used = True
    assert node.free_cpu_cores_per_numa() == [3, 4]
    node.cores[10].used = True  # sibling of 2; no further change
    assert node.free_cpu_cores_per_numa() == [3, 4]


def test_nic_parsing_and_exclusions():
    spec = SynthNodeSpec(nics_per_numa=2, sriov_pfs=1, slow_nics=2)
    node = make_node(spec)
    # 2 per NUMA node schedulable; PFs and slow NICs excluded
    assert len(node.nics) == 4
    assert all(n.speed_gbps == 100.0 for n in node.nics)
    # per-NUMA ordinals assigned in order
    numa0 = [n for n in node.nics if n.numa_node == 0]
    assert [n.idx for n in numa0] == [0, 1]
    # MAC reformatted to colon form
    assert ":" in node.nics[0].mac and node.nics[0].mac == node.nics[0].mac.upper()


def test_nic_bw_sharing_disabled():
    node = default_node()
    nic = node.nics[0]
    assert nic.free_bw() == (90.0, 90.0)
    nic.pods_used = 1
    assert nic.free_bw() == (0.0, 0.0)


def test_gpu_parsing():
    node = default_node(gpus_per_numa=2)
    assert len(node.gpus) == 4
    assert node.free_gpus_per_numa() == [2, 2]
    by_sw = node.free_gpus_per_pciesw()
    assert sum(by_sw.values()) == 4
    node.gpus[0].used = True
    assert node.free_gpus_per_numa() == [1, 2]


def test_hugepages_reservation():
    node = make_node(SynthNodeSpec(hugepages_gb=64, reserved_hugepages_gb=4))
    assert node.mem.free_hugepages_gb == 60
    assert node.mem.ttl_hugepages_gb == 64


def test_free_cpu_batch_smt_pairing():
    node = default_node(phys_cores=8, sockets=2, smt=True, reserved_cores=0)
    got = node.free_cpu_batch(0, 4, SmtMode.ON)
    # pairs handed out together: core then sibling
    assert got == [0, 8, 1, 9]
    for c in got:
        node.cores[c].used = True
    got2 = node.free_cpu_batch(0, 2, SmtMode.OFF)
    # SMT-off takes one logical core per fully-free pair
    assert got2 == [2, 3]


def test_maintenance_label():
    labels = make_node_labels(SynthNodeSpec())
    labels["sigproc.viasat.io/maintenance"] = "cordoned"
    node = HostNode("m1")
    assert node.parse_labels(labels)
    assert node.maintenance
    labels["sigproc.viasat.io/maintenance"] = "not_scheduled"
    node2 = HostNode("m2")
    assert node2.parse_labels(labels)
    assert not node2.maintenance


def test_busy_window():
    node = default_node()
    node.set_busy(now=1000.0)
    assert node.is_busy(now=1010.0)
    assert not node.is_busy(now=1031.0)


def test_free_cpu_batch_no_duplicates_on_overask():
    """Over-asking returns a short list, never duplicate or sibling-shared
    cores (deviation from reference Node.py:502-519, which re-issues pairs)."""
    node = default_node(phys_cores=8, sockets=2, smt=True, reserved_cores=0)
    # leave only 2 free pairs on numa 0
    for c in (0, 1, 8, 9):
        node.cores[c].used = True
    got = node.free_cpu_batch(0, 6, SmtMode.ON)
    assert len(got) == len(set(got)) == 4  # short, not padded with dupes
    got2 = node.free_cpu_batch(0, 4, SmtMode.OFF)
    # SMT-averse request never receives both siblings of one physical core
    assert len(got2) == 2
    assert all(node.cores[c].sibling not in got2 for c in got2)


def test_nic_pods_used_symmetric_multi_pair():
    """Claim/release of a pod with two rx/tx pairs on one NIC keeps
    pods_used balanced (deviation from reference Node.py:569-631, which
    underflows)."""
    from nhd_tpu.sim import make_triad_config
    from nhd_tpu.config.triad import TriadCfgParser

    node = default_node()
    text = make_triad_config(nic_pairs_per_group=2, cpu_workers=0,
                             gpus_per_group=0)
    top = TriadCfgParser(text).to_topology(False)
    mac = node.nics[0].mac
    for pair in top.nic_pairs:
        pair.mac = mac
    for pg in top.proc_groups:
        for i, c in enumerate(pg.proc_cores):
            c.core = 2 + i
        for i, c in enumerate(pg.misc_cores):
            c.core = 6 + i
    for i, c in enumerate(top.misc_cores):
        c.core = 7 + i

    assert node.claim_from_topology(top)
    assert node.nics[0].pods_used == 1
    node.release_from_topology(top)
    assert node.nics[0].pods_used == 0


def test_claim_from_topology_rejects_bad_cores_atomically():
    from nhd_tpu.core.topology import Core, PodTopology

    node = default_node(phys_cores=8, sockets=2, smt=False, reserved_cores=0)
    top = PodTopology()
    top.misc_cores = [Core("a", core=2), Core("b", core=999)]
    before = [c.used for c in node.cores]
    assert not node.claim_from_topology(top)
    assert [c.used for c in node.cores] == before  # no partial claim
    top2 = PodTopology()
    top2.misc_cores = [Core("a", core=-1)]
    assert not node.claim_from_topology(top2)  # negative ids rejected


def test_reset_preserves_hugepage_reserve():
    node = make_node(SynthNodeSpec(hugepages_gb=64, reserved_hugepages_gb=4),
                     hugepage_free=60)
    # capacity 64, allocatable 60, reserve 4 -> free 56
    assert node.mem.free_hugepages_gb == 56
    node.mem.free_hugepages_gb -= 10
    node.reset_resources()
    assert node.mem.free_hugepages_gb == 56  # not raw capacity 64
