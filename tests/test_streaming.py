"""Streaming solver tests: tiling/chunking must not change placement
semantics, and the federation shape must run with bounded per-solve size
(small shapes here; bench.py runs the 100k × 10k config for real)."""

import copy
import random

import pytest

from nhd_tpu.sim import SynthNodeSpec, make_cluster
from nhd_tpu.solver import BatchItem, BatchScheduler, StreamingScheduler
from tests.test_batch import items, simple_request
from tests.test_jax_matcher import random_cluster, random_request


def _free_state(nodes):
    return sorted(
        (
            name,
            tuple(n.free_cpu_cores_per_numa()),
            n.free_gpu_count(),
            n.mem.free_hugepages_gb,
        )
        for name, n in nodes.items()
    )


def test_single_tile_single_chunk_equals_batch():
    """tile/chunk larger than the problem: StreamingScheduler is exactly
    BatchScheduler."""
    reqs = [simple_request(gpus=i % 2) for i in range(30)]
    nodes_s = make_cluster(4)
    nodes_b = copy.deepcopy(nodes_s)
    rs, ss = StreamingScheduler(respect_busy=False).schedule(
        nodes_s, items(reqs), now=0.0
    )
    rb, sb = BatchScheduler(respect_busy=False).schedule(
        nodes_b, items(reqs), now=0.0
    )
    assert [r.node for r in rs] == [r.node for r in rb]
    assert [r.mapping for r in rs] == [r.mapping for r in rb]
    assert ss.scheduled == sb.scheduled
    assert _free_state(nodes_s) == _free_state(nodes_b)


@pytest.mark.parametrize("tile,chunk", [(2, 7), (3, 100), (100, 5)])
def test_tiled_placement_first_fit_and_conserving(tile, chunk):
    """Any tiling: all pods place while capacity exists, earlier tiles
    fill first, and resource books balance."""
    n_nodes = 6
    reqs = [simple_request(gpus=i % 2) for i in range(24)]
    nodes = make_cluster(n_nodes)
    results, stats = StreamingScheduler(
        tile_nodes=tile, chunk_pods=chunk, respect_busy=False
    ).schedule(nodes, items(reqs), now=0.0)
    placed = [r.node for r in results if r.node]
    assert len(placed) == 24
    assert stats.scheduled == 24
    # first-fit: the used node set is a prefix of the name order
    used = sorted(set(placed))
    assert used == sorted(nodes.keys())[: len(used)]
    # bind latency helper works on the merged stats
    assert stats.bind_latency_percentile(results, 99) >= 0.0


def test_tiled_equals_untiled_on_homogeneous_cluster():
    """On a homogeneous unsaturated cluster tiling places the same total
    as the untiled scheduler (everything), with the tiled run keeping the
    first-fit prefix shape. Chunk boundaries change which node an
    individual pod of a contended gang lands on (the contention set per
    round differs), so per-pod equality is only asserted for totals."""
    nodes_t = make_cluster(9)
    nodes_u = copy.deepcopy(nodes_t)
    reqs = [
        simple_request(gpus=i % 2, proc=2 + 2 * (i % 3)) for i in range(24)
    ]
    rt, st = StreamingScheduler(
        tile_nodes=3, chunk_pods=11, respect_busy=False
    ).schedule(nodes_t, items(reqs), now=0.0)
    ru, su = BatchScheduler(respect_busy=False).schedule(
        nodes_u, items(reqs), now=0.0
    )
    assert st.scheduled == su.scheduled == 24
    used = sorted(set(r.node for r in rt))
    assert used == sorted(nodes_t.keys())[: len(used)]


def test_tiled_heterogeneous_is_valid_and_conserving():
    """On heterogeneous clusters tiling may trade the global gpuless
    preference for tile locality (documented in solver/streaming.py), so
    totals can differ from untiled — but every claim must still be valid:
    reported stats match results, and end-state free resources never go
    negative or exceed capacity."""
    rng = random.Random(5)
    reqs = [random_request(rng) for _ in range(40)]
    nodes = random_cluster(rng, 9)
    capacity = {name: n.total_gpus() for name, n in nodes.items()}
    results, stats = StreamingScheduler(
        tile_nodes=3, chunk_pods=11, respect_busy=False
    ).schedule(nodes, items(reqs), now=1010.0)
    assert stats.scheduled == sum(1 for r in results if r.node) > 0
    for name, n in nodes.items():
        assert 0 <= n.free_gpu_count() <= capacity[name]
        assert all(c >= 0 for c in n.free_cpu_cores_per_numa())
        assert n.mem.free_hugepages_gb >= 0
        for nic in n.nics:
            rx, tx = nic.free_bw()
            assert rx >= 0 and tx >= 0


def test_saturation_marks_unschedulable():
    nodes = make_cluster(1, SynthNodeSpec(gpus_per_numa=0))
    reqs = [simple_request(gpus=1) for _ in range(3)]
    results, stats = StreamingScheduler(
        tile_nodes=1, chunk_pods=2, respect_busy=False
    ).schedule(nodes, items(reqs), now=0.0)
    assert all(r.node is None for r in results)
    assert stats.scheduled == 0


def test_oversized_pods_take_serial_prepass():
    """A pod whose combo lattice exceeds the dense budget streams through
    the serial oracle against the full cluster, not a tile."""
    from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
    from nhd_tpu.core.topology import MapMode, SmtMode
    from nhd_tpu.solver import kernel

    big = PodRequest(
        groups=tuple(
            GroupRequest(CpuRequest(1, SmtMode.ON), CpuRequest(0, SmtMode.OFF),
                         0, 0.0, 0.0)
            for _ in range(3)
        ),
        misc=CpuRequest(0, SmtMode.OFF),
        hugepages_gb=0,
        map_mode=MapMode.NUMA,
    )
    orig = kernel.MAX_LATTICE
    kernel.MAX_LATTICE = 4  # force the 3-group pod onto the serial path
    try:
        nodes = make_cluster(4)
        reqs = [simple_request(), big, simple_request()]
        results, stats = StreamingScheduler(
            tile_nodes=2, chunk_pods=2, respect_busy=False
        ).schedule(nodes, items(reqs), now=0.0)
    finally:
        kernel.MAX_LATTICE = orig
    assert all(r.node for r in results)
    assert stats.scheduled == 3


def test_streaming_over_mesh_equals_single_device():
    """Streaming composes with the sharded batch path: tiles over time,
    nodes-within-tile over the 8-device mesh — totals and end state equal
    the forced single-device streaming run."""
    import jax

    assert len(jax.devices()) == 8
    reqs = [simple_request(gpus=i % 2, proc=2 + 2 * (i % 3))
            for i in range(30)]
    outs = {}
    for label, mesh in (("mesh", "auto"), ("single", None)):
        nodes = make_cluster(10)
        results, stats = StreamingScheduler(
            tile_nodes=4, chunk_pods=9, respect_busy=False, mesh=mesh
        ).schedule(nodes, items(reqs), now=0.0)
        outs[label] = (
            [r.node for r in results],
            stats.scheduled,
            _free_state(nodes),
        )
    assert outs["mesh"] == outs["single"]


def test_many_groups_fall_back_to_per_tile_interners():
    """A federation with more distinct node groups than the shared
    group-mask budget (48) must still schedule: the once-per-chunk
    encode disengages and each tile encodes its offers against its own
    interner, exactly like the pre-sharing behavior."""
    from dataclasses import replace

    n_groups = 60
    group_names = [f"region{i:02d}" for i in range(n_groups)]
    nodes = make_cluster(n_groups, groups=group_names)
    reqs = [
        replace(simple_request(gpus=i % 2),
                node_groups=frozenset({group_names[i % n_groups]}))
        for i in range(n_groups)
    ]
    results, stats = StreamingScheduler(
        tile_nodes=16, chunk_pods=25, respect_busy=False
    ).schedule(nodes, items(reqs), now=0.0)
    placed = [r for r in results if r.node]
    assert len(placed) == n_groups
    # each pod landed on a node carrying its group
    for r, req in zip(results, reqs):
        assert set(nodes[r.node].groups) & req.node_groups


def test_round_cap_does_not_certify_exhaustion(monkeypatch):
    """A max_rounds-capped sub-call can leave feasible pods unplaced
    mid-retry (with tile capacity remaining); that must NOT poison the
    tile's saturation certificate — a later chunk's pods still place.

    Forced deterministically: a 4x-overestimated capacity estimate aims a
    whole chunk at the tile's first node; the overflow claims are stale
    retries that the round cap cuts off while the second node is still
    completely free."""
    import numpy as np

    from nhd_tpu.solver.batch import BatchScheduler

    orig = BatchScheduler._capacity_at
    monkeypatch.setattr(
        BatchScheduler, "_capacity_at",
        lambda self, pods, rank: orig(self, pods, rank) * 4,
    )
    nodes = make_cluster(2)   # one tile of two nodes
    reqs = [simple_request(gpus=1) for _ in range(16)]
    results, stats = StreamingScheduler(
        tile_nodes=2, chunk_pods=8, respect_busy=False, max_rounds=1
    ).schedule(nodes, items(reqs), now=0.0)
    placed = [r.node for r in results if r.node]
    # one capped round places 2 pods (2 NIC picks per combo); chunk 1's
    # overflow returns unplaced/failed=False with capacity remaining. A
    # false certificate would skip the tile for chunk 2 entirely (total
    # 2); with the guard, chunk 2 is offered and places 2 more
    assert len(placed) == 4
    assert all(n == sorted(nodes)[0] for n in placed)


def test_bucket_cache_pins_requests_list():
    """Regression: FastCluster's demand-array cache is keyed by
    id(requests-list); each entry must PIN that list (strong ref) so a
    dead list's id can never be reused by a later bucket — id collisions
    served stale demand arrays (phantom -1/-2 failures, accounting
    drift) under the streaming chunk pattern."""
    nodes = make_cluster(2)
    sched = BatchScheduler(respect_busy=False)
    ctx = sched.make_context(nodes, now=0.0)
    sched.schedule(
        nodes, items([simple_request() for _ in range(3)]), context=ctx
    )
    assert ctx.fast._bucket_cache, "round path did not populate the cache"
    for key, (reqs_list, _arrays) in ctx.fast._bucket_cache.items():
        assert id(reqs_list) == key


def test_context_reuse_pays_once():
    """Repeated schedule() calls through one context reuse the encode; the
    claims of call 1 must be visible to call 2."""
    nodes = make_cluster(2)
    sched = BatchScheduler(respect_busy=False)
    ctx = sched.make_context(nodes, now=0.0)
    r1, _ = sched.schedule(
        nodes, items([simple_request(gpus=1) for _ in range(4)]),
        context=ctx,
    )
    free_after_1 = _free_state(nodes)
    r2, _ = sched.schedule(
        nodes, items([simple_request(gpus=1) for _ in range(4)]),
        context=ctx,
    )
    assert all(r.node for r in r1)
    assert all(r.node for r in r2)
    assert _free_state(nodes) != free_after_1  # second batch claimed more

    with pytest.raises(ValueError):
        sched.schedule(make_cluster(2), items([simple_request()]), context=ctx)


def test_routed_places_everything_capacity_matched():
    """Routed placement: pods pre-partition across tiles by estimated
    capacity and every pod still places on a capacity-matched cluster;
    resource accounting equals a first-fit run's totals."""
    reqs = [simple_request(gpus=i % 2) for i in range(32)]
    nodes_r = make_cluster(8)
    nodes_f = copy.deepcopy(nodes_r)
    rr, sr = StreamingScheduler(
        tile_nodes=2, chunk_pods=8, placement="routed", respect_busy=False
    ).schedule(nodes_r, items(reqs), now=0.0)
    rf, sf = StreamingScheduler(
        tile_nodes=2, chunk_pods=8, respect_busy=False
    ).schedule(nodes_f, items(reqs), now=0.0)
    assert sr.scheduled == sf.scheduled == 32
    assert all(r.node for r in rr)
    # same aggregate consumption even though the tile each pod landed on
    # may differ (routing is a placement policy, not a capacity change)
    assert sorted(
        (tuple(n.free_cpu_cores_per_numa()), n.free_gpu_count())
        for n in nodes_r.values()
    ) == sorted(
        (tuple(n.free_cpu_cores_per_numa()), n.free_gpu_count())
        for n in nodes_f.values()
    )


def test_routed_spill_wraps_to_earlier_tiles():
    """A pod routed to a late tile whose capacity estimate was wrong must
    wrap around and try EVERY tile, including earlier ones."""
    nodes = make_cluster(4)
    # consume the later tiles entirely so routed blocks land on full
    # tiles and must wrap to tile 0
    names = sorted(nodes)
    prefill = [simple_request(gpus=1)] * 100
    BatchScheduler(respect_busy=False).schedule(
        {n: nodes[n] for n in names[1:]}, items(prefill), now=0.0
    )
    reqs = [simple_request(gpus=1) for _ in range(2)]
    res, stats = StreamingScheduler(
        tile_nodes=1, chunk_pods=1, placement="routed", respect_busy=False
    ).schedule(nodes, items(reqs), now=0.0)
    placed = [r.node for r in res if r.node]
    assert placed and all(n == names[0] for n in placed)


def test_routed_rejects_bad_placement():
    with pytest.raises(ValueError, match="placement"):
        StreamingScheduler(placement="best-fit")


def test_persistent_tiles_survive_churn_and_equal_fresh():
    """ISSUE 9: a persistent StreamingScheduler reuses its tile contexts
    ACROSS schedule() calls, folding inter-call churn in as row deltas —
    and places exactly like a fresh scheduler handed the same mutated
    cluster."""
    reqs1 = [simple_request(gpus=i % 2) for i in range(12)]
    reqs2 = [simple_request(gpus=(i + 1) % 2) for i in range(12)]
    nodes_p = make_cluster(6)
    sched_p = StreamingScheduler(
        tile_nodes=2, respect_busy=False, persistent=True
    )
    r1, _ = sched_p.schedule(nodes_p, items(reqs1), now=0.0)
    assert sched_p._pstate is not None

    # inter-call churn: cordon one node, release one placed pod's worth
    # of resources via direct mutation, note both
    victim = next(r.node for r in r1 if r.node is not None)
    nodes_p[victim].active = False
    sched_p.note_nodes((victim,))

    nodes_f = copy.deepcopy(nodes_p)
    r2p, _ = sched_p.schedule(nodes_p, items(reqs2), now=1.0)
    r2f, _ = StreamingScheduler(
        tile_nodes=2, respect_busy=False
    ).schedule(nodes_f, items(reqs2), now=1.0)
    assert [r.node for r in r2p] == [r.node for r in r2f]
    assert _free_state(nodes_p) == _free_state(nodes_f)
    # tile deltas stayed bit-exact re-derivable
    for d in sched_p._pstate["deltas"]:
        if d is not None:
            assert d.parity_errors() == []
    # no cordoned-node placements
    assert all(r.node != victim for r in r2p if r.node)


def test_persistent_tiles_reset_on_membership_change():
    reqs = [simple_request() for _ in range(6)]
    nodes = make_cluster(4)
    sched = StreamingScheduler(
        tile_nodes=2, respect_busy=False, persistent=True
    )
    sched.schedule(nodes, items(reqs), now=0.0)
    first = sched._pstate
    assert first is not None
    # membership change: the persistent state must drop and rebuild
    from nhd_tpu.sim.synth import SynthNodeSpec, make_node

    spec = SynthNodeSpec(name="latecomer")
    nodes[spec.name] = make_node(spec)
    sched.note_nodes((spec.name,))
    r2, _ = sched.schedule(nodes, items(reqs), now=1.0)
    assert sched._pstate is not first
    for d in sched._pstate["deltas"]:
        if d is not None:
            assert d.parity_errors() == []


def test_empty_node_dict_reports_unschedulable():
    """An empty region (a multihost rank can own zero nodes under the
    ceil-division block layout) must degrade to all-unschedulable, not
    crash the tile pipeline."""
    res, stats = StreamingScheduler(tile_nodes=2, respect_busy=False).schedule(
        {}, items([simple_request()]), now=0.0
    )
    assert [r.node for r in res] == [None]
    assert stats.scheduled == 0
