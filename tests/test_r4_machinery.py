"""Pins for the round-4/5 hot-path machinery (VERDICT r4 task 4).

Three mechanisms got semantic rewrites without dedicated tests:
multi-copy speculative claims (the counts plane, per-node capacity caps,
balanced fill, and the r5 exact NIC-occupancy projection), the CPU
routing of small rounds (`use_cpu=True` dispatch branch — previously
unreachable in CI because the suite forces the CPU backend), and the
wholesale async re-upload that replaced per-row scatters
(update_rows → _rebuild_mutable). Each is named and pinned here.
"""

import copy
import random

import numpy as np
import pytest

from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
from nhd_tpu.core.topology import MapMode, SmtMode
from nhd_tpu.sim import SynthNodeSpec, make_node
from nhd_tpu.solver import BatchItem, BatchScheduler
from tests.test_batch import items
from tests.test_jax_matcher import random_cluster, random_request


def spec_scheduler(**kw):
    return BatchScheduler(
        respect_busy=False, register_pods=False, device_state=True,
        mesh=None, **kw,
    )


def uniform_cluster(n_nodes: int, **spec_kw):
    nodes = {}
    for i in range(n_nodes):
        spec = SynthNodeSpec(name=f"uni{i:03d}", **spec_kw)
        nodes[spec.name] = make_node(spec)
    return nodes


def plain_pod(cores: int = 2, gpus: int = 0, rx: float = 0.0,
              tx: float = 0.0, n_groups: int = 1) -> PodRequest:
    return PodRequest(
        groups=tuple(
            GroupRequest(
                proc=CpuRequest(cores, SmtMode.ON),
                misc=CpuRequest(0, SmtMode.ON),
                gpus=gpus, nic_rx_gbps=rx, nic_tx_gbps=tx,
            )
            for _ in range(n_groups)
        ),
        misc=CpuRequest(0, SmtMode.ON),
        hugepages_gb=0,
        map_mode=MapMode.NUMA,
    ).interned()


# ---------------------------------------------------------------------------
# (a) multi-copy claims: counts plane, capacity caps, balanced fill,
#     exact NIC occupancy
# ---------------------------------------------------------------------------


def test_multicopy_lands_a_gang_in_few_iterations(monkeypatch):
    """The counts plane must carry multiple copies per (iter, node): a
    gang far larger than iters × nodes can only land speculatively if
    cap(t, n) > 1 engages. iters=2, 2 nodes, 24 identical pods — the
    single-copy loop could claim at most 4 in round 0."""
    monkeypatch.setenv("NHD_TPU_SPECULATE", "1")
    monkeypatch.setenv("NHD_TPU_SPEC_ITERS", "2")
    nodes = uniform_cluster(2, phys_cores=32, gpus_per_numa=0,
                            nics_per_numa=2, hugepages_gb=64)
    reqs = [plain_pod(cores=2) for _ in range(24)]
    results, stats = spec_scheduler().schedule(nodes, items(reqs), now=0.0)
    placed_r0 = sum(1 for r in results if r.node and r.round_no == 0)
    assert placed_r0 == 24, (placed_r0, stats.counters)


def test_multicopy_balanced_fill_spreads_across_nodes(monkeypatch):
    """The per-node take is ceil(need / elected), not cap: a gang whose
    nodes could each absorb the whole batch must still spread evenly
    (the classic interleave's packing shape — an unbalanced fill
    concentrates types and costs placements on tight instances)."""
    monkeypatch.setenv("NHD_TPU_SPECULATE", "1")
    monkeypatch.setenv("NHD_TPU_SPEC_ITERS", "8")
    n_nodes, n_pods = 4, 8
    nodes = uniform_cluster(n_nodes, phys_cores=32, gpus_per_numa=0,
                            nics_per_numa=2, hugepages_gb=64)
    reqs = [plain_pod(cores=2) for _ in range(n_pods)]
    results, stats = spec_scheduler().schedule(nodes, items(reqs), now=0.0)
    from collections import Counter

    per_node = Counter(r.node for r in results if r.node)
    assert sum(per_node.values()) == n_pods
    assert set(per_node.values()) == {n_pods // n_nodes}, per_node


def test_nic_occupancy_counts_shared_nics_once(monkeypatch):
    """r5 regression pin: a two-NIC-group pod whose groups share one NIC
    (joint bandwidth fits) must be claimable speculatively even when
    free NICs per NUMA < NIC-needing groups. The pre-r5 projection
    charged one NIC per group and stranded exactly these pods into an
    extra classic round (observed as cfg4 rounds=2 on the capacity-
    matched bench)."""
    monkeypatch.setenv("NHD_TPU_SPECULATE", "1")
    monkeypatch.setenv("NHD_TPU_SPEC_ITERS", "8")
    # one NIC per NUMA: a 2-group NIC pod MUST share (cross-NUMA combos
    # also exist, so fill both NUMAs' NICs with single-group pods first
    # is fiddly — instead give the pod two groups whose joint bw fits
    # one NIC and make the node single-NUMA-ish by packing)
    nodes = uniform_cluster(1, phys_cores=16, gpus_per_numa=0,
                            nics_per_numa=1, hugepages_gb=64)
    # two NIC-needing groups, joint 15+7 Gbps on a 100G NIC: the node has
    # 2 NUMAs x 1 NIC. Two such pods exhaust both NICs only if sharing
    # is honored per pod (each pod fits on ONE numa's NIC or cross-numa);
    # four single-NIC-group pods then need the remaining NICs.
    two_group = plain_pod(cores=2, rx=10.0, tx=5.0, n_groups=2)
    reqs = [two_group, two_group]
    results, stats = spec_scheduler().schedule(nodes, items(reqs), now=0.0)
    placed = sum(1 for r in results if r.node)
    assert placed == 2, (placed, stats.counters)
    # the r5 projection lands both in the speculative round — no classic
    # retry round for a workload the native verify accepts outright
    assert stats.rounds == 1, stats.counters
    assert all(r.round_no == 0 for r in results if r.node)


@pytest.mark.parametrize("seed", range(8))
def test_multicopy_random_never_oversubscribes_and_matches_classic(
    seed, monkeypatch
):
    """Property sweep: on random degraded clusters the multi-copy
    speculative path (a) never oversubscribes any resource, and (b)
    places within greedy-packing noise of the classic rounds."""
    monkeypatch.setenv("NHD_TPU_SPECULATE", "1")
    monkeypatch.setenv("NHD_TPU_SPEC_ITERS", "8")
    rng = random.Random(1000 + seed)
    reqs = [random_request(rng) for _ in range(50)]
    nodes_s = random_cluster(rng, 10)
    nodes_c = copy.deepcopy(nodes_s)
    gpu_cap = {name: n.total_gpus() for name, n in nodes_s.items()}

    rs, ss = spec_scheduler().schedule(nodes_s, items(reqs), now=1010.0)
    rc, sc = BatchScheduler(
        respect_busy=False, register_pods=False, device_state=False,
        mesh=None,
    ).schedule(nodes_c, items(reqs), now=1010.0)

    for name, n in nodes_s.items():
        assert 0 <= n.free_gpu_count() <= gpu_cap[name]
        assert all(c >= 0 for c in n.free_cpu_cores_per_numa())
        assert n.mem.free_hugepages_gb >= 0
        for nic in n.nics:
            rx, tx = nic.free_bw()
            assert rx >= 0 and tx >= 0
    assert abs(ss.scheduled - sc.scheduled) <= max(2, sc.scheduled // 20), (
        f"speculative {ss.scheduled} vs classic {sc.scheduled}"
    )


# ---------------------------------------------------------------------------
# (b) CPU routing of small rounds: the use_cpu=True dispatch branch
# ---------------------------------------------------------------------------


def test_cpu_routed_round_runs_and_places(monkeypatch):
    """_route_cpu needs an accelerator default backend, which CI never
    has — monkeypatch the probe so the `use_cpu=True` branch (solving
    under jax.default_device against host arrays while device state is
    live) actually executes, and assert it both ran and placed
    everything the classic path places."""
    import nhd_tpu.solver.batch as batch_mod

    monkeypatch.setattr(batch_mod, "_accelerator_backend", lambda: True)
    monkeypatch.setenv("NHD_TPU_SPECULATE", "0")  # classic rounds only
    monkeypatch.setenv("NHD_TPU_CPU_SMALL", "1024")
    monkeypatch.setenv("NHD_TPU_CPU_SMALL_NODES", "1536")

    nodes = uniform_cluster(8, phys_cores=16, gpus_per_numa=1,
                            nics_per_numa=2, hugepages_gb=64)
    reqs = [plain_pod(cores=2, gpus=(i % 2)) for i in range(24)]
    results, stats = BatchScheduler(
        respect_busy=False, register_pods=False, device_state=True,
        mesh=None,
    ).schedule(nodes, items(reqs), now=0.0)
    assert stats.counters.get("cpu_routed_rounds", 0) >= 1, stats.counters
    placed = sum(1 for r in results if r.node)
    assert placed == 24, placed


def test_cpu_routed_after_speculative_round(monkeypatch):
    """The common production shape: a megaround places the bulk, the
    small leftover routes to the host CPU backend. Forcing iters=1
    guarantees a leftover, and the tail round must report cpu routing
    while still converging."""
    import nhd_tpu.solver.batch as batch_mod

    monkeypatch.setattr(batch_mod, "_accelerator_backend", lambda: True)
    monkeypatch.setenv("NHD_TPU_SPECULATE", "1")
    monkeypatch.setenv("NHD_TPU_SPEC_ITERS", "1")

    nodes = uniform_cluster(2, phys_cores=16, gpus_per_numa=0,
                            nics_per_numa=2, hugepages_gb=64)
    # two types => iters=1 can elect at most one type per node; with a
    # fair fill the leftover is nonzero and takes the CPU-routed tail
    reqs = [plain_pod(cores=2) for _ in range(8)] + [
        plain_pod(cores=4) for _ in range(8)
    ]
    results, stats = spec_scheduler().schedule(nodes, items(reqs), now=0.0)
    placed = sum(1 for r in results if r.node)
    assert placed == 16, (placed, stats.counters)
    assert stats.counters.get("cpu_routed_rounds", 0) >= 1, stats.counters


# ---------------------------------------------------------------------------
# (c) wholesale re-upload: update_rows / _rebuild_mutable convergence
# ---------------------------------------------------------------------------


def test_update_rows_converges_device_to_host_truth():
    """After host-side claims mutate the cluster arrays, update_rows
    must make the resident device state solve identically to a fresh
    encode — the wholesale async re-upload is the only coherence
    mechanism left since the row scatters were removed (r4)."""
    from nhd_tpu.solver.device_state import DeviceClusterState
    from nhd_tpu.solver.encode import encode_cluster, encode_pods
    from nhd_tpu.solver.kernel import solve_bucket

    nodes = uniform_cluster(6, phys_cores=16, gpus_per_numa=1,
                            nics_per_numa=2, hugepages_gb=64)
    cluster = encode_cluster(nodes, now=0.0)
    dev = DeviceClusterState(cluster)
    buckets = encode_pods([plain_pod(cores=2, gpus=1)], cluster.interner)
    (pods,) = buckets.values()

    # host-side mutation: consume most of nodes 0-2 directly in the
    # packed arrays (the FastCluster/native path writes these in place)
    cluster.cpu_free[0:3] = 1
    cluster.gpu_free[0:3] = 0
    cluster.hp_free[0:3] = 0
    dev.update_rows([0, 1, 2])

    got = dev.solve(pods)
    want = solve_bucket(cluster, pods)
    np.testing.assert_array_equal(
        np.asarray(got.cand), np.asarray(want.cand)
    )
    np.testing.assert_array_equal(
        np.asarray(got.best_c), np.asarray(want.best_c)
    )
    # the mutated rows must actually be infeasible now
    assert not np.asarray(got.cand)[:, 0:3].any()
    assert np.asarray(got.cand)[:, 3:6].any()


def test_update_rows_noop_on_empty_indices():
    """update_rows with no indices must not re-upload (the emptiness
    gate is what keeps claim-free rounds from paying an upload)."""
    from nhd_tpu.solver.device_state import DeviceClusterState
    from nhd_tpu.solver.encode import encode_cluster

    nodes = uniform_cluster(2, phys_cores=8)
    cluster = encode_cluster(nodes, now=0.0)
    dev = DeviceClusterState(cluster)
    before = {name: dev._dev[name] for name in dev._dev}
    dev.update_rows([])
    for name, arr in before.items():
        assert dev._dev[name] is arr
