"""Coverage for the less-traveled Triad parser paths: standalone nic_cores
modules (no dp_group), legacy deployed configs without rx_mbufs, and
pod-spec hugepage reservations overriding the config."""

from nhd_tpu.config import libconfig
from nhd_tpu.config.triad import TriadCfgParser
from nhd_tpu.core.request import PodRequest
from nhd_tpu.core.topology import NicDir

NIC_CORES_CFG = """
TopologyCfg : {
  cpu_arch = "SKYLAKE";
  ext_cores = [ "CtrlCores[0]" ];
  ext_cores_smt = false;
  kni_vlan = "KniVlan";
  map_type = "NUMA";
  mod_defs = ( {
    module = "routers";
    data_vlan = "vlan";
    nic_cores = [ "rx", "rx_speeds", "tx", "tx_speeds", true ];
  } );
};
routers = (
  { module = "r0"; vlan = 0;
    rx = [ -1, -1 ]; rx_speeds = [ 12.5, 12.5 ];
    tx = [ -1, -1 ]; tx_speeds = [ 7.5, 7.5 ]; },
  { module = "r1"; vlan = 0;
    rx = [ -1 ]; rx_speeds = [ 25.0 ];
    tx = [ -1 ]; tx_speeds = [ 10.0 ]; }
);
CtrlCores = [ -1 ];
KniVlan = 0;
Hugepages_GB = 2;
"""


def test_nic_cores_module_parses():
    """The reference's non-data-path NIC module form
    (TriadCfgParser.py:266-302): a 5-tuple naming rx/speeds/tx/speeds/smt."""
    p = TriadCfgParser(NIC_CORES_CFG)
    top = p.to_topology(False)
    assert top is not None
    assert len(top.proc_groups) == 2
    g0, g1 = top.proc_groups
    assert len(g0.proc_cores) == 4  # 2 rx + 2 tx
    assert len(g1.proc_cores) == 2
    assert len(top.nic_pairs) == 3

    req = PodRequest.from_topology(top)
    assert req.groups[0].nic_rx_gbps == 25.0
    assert req.groups[0].nic_tx_gbps == 15.0
    assert req.groups[1].nic_rx_gbps == 25.0

    rx = [c for c in g0.proc_cores if c.nic_dir == NicDir.RX]
    assert [c.nic_speed for c in rx] == [12.5, 12.5]


def test_nic_cores_roundtrip_and_legacy_replay():
    """Write-back and deployed-config replay for the nic_cores form; the
    replay also exercises the legacy no-rx_mbufs branch
    (TriadCfgParser.py:329-333)."""
    p = TriadCfgParser(NIC_CORES_CFG)
    top = p.to_topology(False)
    core_iter = iter(range(20, 40))
    for pg in top.proc_groups:
        pg.vlan.vlan = 7
        for c in pg.proc_cores:
            c.core = next(core_iter)
    for c in top.misc_cores:
        c.core = next(core_iter)
    top.ctrl_vlan.vlan = 7
    top.set_data_default_gw("10.9.0.1/32")
    for pair in top.nic_pairs:
        pair.mac = "AA:BB:CC:00:00:01"
    out = p.to_config()

    cfg = libconfig.loads(out)
    assert cfg.routers[0].rx == [20, 22]
    assert cfg.routers[1].rx == [24]
    assert len(cfg.Network_Config) == 1

    # strip rx_mbufs to simulate an old deployed config
    stripped = dict(cfg)
    net0 = dict(cfg.Network_Config[0])
    net0.pop("rx_mbufs")
    stripped["Network_Config"] = (net0,)
    legacy_text = libconfig.dumps(stripped)

    p2 = TriadCfgParser(legacy_text)
    top2 = p2.to_topology(True)
    assert top2 is not None
    assert all(pair.mac == "AA:BB:CC:00:00:01" for pair in top2.nic_pairs)
    assert all(pair.rx_ring_size == 4096 for pair in top2.nic_pairs)  # default kept


def test_pod_spec_hugepages_override():
    """Pod-spec hugepages-1Gi requests override the config value
    (reference: CfgTopology.py:146-149 via NHDScheduler.py:214-225)."""
    from nhd_tpu.scheduler.core import Scheduler
    from nhd_tpu.scheduler.events import WatchQueue
    from tests.test_scheduler import make_backend, pod_cfg
    import queue

    backend = make_backend()
    backend.create_pod(
        "hp-pod", cfg_text=pod_cfg(hugepages_gb=4),
        resources={"hugepages-1Gi": "8Gi"},
    )
    sched = Scheduler(backend, WatchQueue(), queue.Queue(), respect_busy=False)
    sched.build_initial_node_list()
    sched.check_pending_pods()
    pod = backend.pods[("default", "hp-pod")]
    assert pod.node is not None
    node = sched.nodes[pod.node]
    # 8 (spec) not 4 (config) got deducted
    assert node.mem.free_hugepages_gb == node.mem.ttl_hugepages_gb - 8
