"""Property tests: the batched JAX solver must agree with the serial oracle.

This is the parity contract from SURVEY §7: identical feasibility decisions,
identical node choice, identical mapping (combo / misc-NUMA / NIC pick) for
single-pod queries against any cluster state.
"""

import random

import pytest

from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
from nhd_tpu.core.topology import MapMode, SmtMode
from nhd_tpu.sim import SynthNodeSpec, make_node
from nhd_tpu.solver.jax_matcher import JaxMatcher
from nhd_tpu.solver.oracle import find_node


def random_cluster(rng: random.Random, n_nodes: int):
    nodes = {}
    for i in range(n_nodes):
        spec = SynthNodeSpec(
            name=f"node{i:03d}",
            sockets=2,
            phys_cores=rng.choice([8, 12, 16]),
            smt=rng.random() < 0.7,
            reserved_cores=rng.choice([0, 2]),
            nics_per_numa=rng.choice([1, 2, 3]),
            nic_speed_mbps=rng.choice([25000, 100000]),
            gpus_per_numa=rng.choice([0, 1, 2]),
            hugepages_gb=rng.choice([16, 64]),
            groups=rng.choice(["default", "default.edge", "edge"]),
        )
        node = make_node(spec)
        # degrade state randomly: claimed cores/GPUs/NICs/hugepages
        for core in node.cores:
            if rng.random() < 0.2:
                core.used = True
        for gpu in node.gpus:
            if rng.random() < 0.3:
                gpu.used = True
        for nic in node.nics:
            if rng.random() < 0.2:
                nic.pods_used = 1
        node.mem.free_hugepages_gb -= rng.choice([0, 0, 8])
        if rng.random() < 0.1:
            node.maintenance = True
        if rng.random() < 0.1:
            node.active = False
        if rng.random() < 0.2:
            node.set_busy(now=1000.0)
        nodes[node.name] = node
    return nodes


def random_request(rng: random.Random) -> PodRequest:
    n_groups = rng.choice([1, 1, 2, 3])

    def group():
        rx = rng.choice([0.0, 5.0, 20.0, 80.0])
        tx = rng.choice([0.0, 5.0, 20.0])
        # bandwidth implies an rx+tx core pair (inherent Triad format shape)
        proc_min = 2 if (rx or tx) else 1
        return GroupRequest(
            proc=CpuRequest(rng.randint(proc_min, 6), rng.choice(list(SmtMode))),
            misc=CpuRequest(rng.randint(0, 2), rng.choice(list(SmtMode))),
            gpus=rng.choice([0, 0, 1, 2]),
            nic_rx_gbps=rx,
            nic_tx_gbps=tx,
        )

    groups = tuple(group() for _ in range(n_groups))
    return PodRequest(
        groups=groups,
        misc=CpuRequest(rng.randint(0, 3), rng.choice(list(SmtMode))),
        hugepages_gb=rng.choice([0, 4, 16]),
        map_mode=rng.choice([MapMode.NUMA, MapMode.NUMA, MapMode.PCI]),
        node_groups=frozenset(rng.choice([["default"], ["edge"], ["default", "edge"]])),
    )


@pytest.mark.parametrize("seed", range(30))
def test_single_pod_parity(seed):
    rng = random.Random(seed)
    nodes = random_cluster(rng, rng.randint(1, 6))
    matcher = JaxMatcher()
    for _ in range(4):
        req = random_request(rng)
        want = find_node(nodes, req, now=1010.0)
        got = matcher.find_node(nodes, req, now=1010.0)
        if want is None:
            assert got is None, f"jax found {got}, oracle found nothing (req={req})"
        else:
            assert got is not None, f"oracle found {want}, jax found nothing (req={req})"
            assert got.node == want.node
            assert got.mapping == want.mapping


@pytest.mark.parametrize("seed", range(5))
def test_busy_toggle_parity(seed):
    rng = random.Random(100 + seed)
    nodes = random_cluster(rng, 3)
    matcher = JaxMatcher()
    req = random_request(rng)
    want = find_node(nodes, req, now=1010.0, respect_busy=False)
    got = matcher.find_node(nodes, req, now=1010.0, respect_busy=False)
    assert (want is None) == (got is None)
    if want:
        assert got.node == want.node and got.mapping == want.mapping


def test_batch_matches_singles():
    """find_nodes on a batch equals per-pod find_node on the same snapshot."""
    rng = random.Random(999)
    nodes = random_cluster(rng, 5)
    reqs = [random_request(rng) for _ in range(12)]
    matcher = JaxMatcher()
    batch = matcher.find_nodes(nodes, reqs, now=1010.0)
    for r, got in zip(reqs, batch):
        want = matcher.find_node(nodes, r, now=1010.0)
        assert (want is None) == (got is None)
        if want:
            assert got.node == want.node and got.mapping == want.mapping


@pytest.mark.parametrize("seed", range(10))
def test_feasible_set_parity(seed):
    """Beyond choice parity: the kernel's per-node candidacy and feasible
    NUMA-combo *counts* must equal the oracle's filter→intersect output on
    every node (SURVEY §7: property-test the feasible sets themselves)."""
    import numpy as np

    from nhd_tpu.core.request import PodRequest as PR
    from nhd_tpu.solver.encode import encode_cluster, encode_pods
    from nhd_tpu.solver.kernel import solve_bucket
    from nhd_tpu.solver.oracle import OracleMatcher

    rng = random.Random(3000 + seed)
    nodes = random_cluster(rng, rng.randint(2, 5))
    reqs = [random_request(rng) for _ in range(3)]
    matcher = OracleMatcher()

    cluster = encode_cluster(nodes, now=1010.0)
    for G, pods in encode_pods(reqs, cluster.interner).items():
        out = solve_bucket(cluster, pods)
        cand = np.asarray(out.cand)
        n_combos = np.asarray(out.n_combos)
        for t, pod_i in zip(pods.pod_type, pods.pod_index):
            req = reqs[int(pod_i)]
            filt = matcher.filter_pod_resources(nodes, req)
            filts = matcher.filter_numa_topology(filt, req, now=1010.0)
            matcher.intersect_resources(filt, filts, req.map_mode)
            oracle_counts = {
                name: len(filts.gpu[name]) for name in filts.candidates
            }
            for n_idx, name in enumerate(cluster.names):
                want = oracle_counts.get(name, 0)
                assert bool(cand[t, n_idx]) == (want > 0), (
                    f"seed {seed} pod {pod_i} node {name}: candidacy differs"
                )
                if want > 0:
                    assert int(n_combos[t, n_idx]) == want, (
                        f"seed {seed} pod {pod_i} node {name}: "
                        f"combo count {int(n_combos[t, n_idx])} != {want}"
                    )
