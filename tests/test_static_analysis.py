"""nhdlint: fixture tests per rule pack + the tier-1 gate.

The gate test at the bottom runs all four packs over ``nhd_tpu/`` and
fails on any unsuppressed, unbaselined finding — a recompile hazard or
off-lock mutation introduced by a future PR fails ``pytest`` the same as
a broken unit test.

Fixture files under tests/fixtures/analysis/ carry ``# EXPECT[RULE]``
markers on each line that must be flagged; the tests compare the exact
(rule, line) sets so a rule that drifts off its line, double-reports, or
goes silent is caught here.
"""

from __future__ import annotations

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from nhd_tpu.analysis import (
    ALL_PACK_NAMES,
    RULES,
    analyze_file,
    analyze_paths,
    load_baseline,
    subtract_baseline,
    write_baseline,
)
from nhd_tpu.analysis.cli import main as cli_main

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"

_EXPECT = re.compile(r"#\s*EXPECT\[([A-Z0-9,\s]+)\]")


def expected_of(path: Path) -> set:
    out = set()
    for lineno, line in enumerate(path.read_text().splitlines(), start=1):
        m = _EXPECT.search(line)
        if m:
            for rule in m.group(1).split(","):
                out.add((rule.strip(), lineno))
    return out


def found_of(path: Path, packs=None) -> set:
    report = analyze_file(path, packs)
    return {(f.rule, f.line) for f in report.findings}


# ---------------------------------------------------------------------------
# per-pack fixtures: exact rule ids at exact lines
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name,packs", [
    ("tracing_pos.py", ["tracing"]),
    ("tracing_neg.py", ["tracing"]),
    ("solver/hostsync_pos.py", ["tracing"]),
    ("solver/hostsync_neg.py", ["tracing"]),
    ("hostsync_out_of_scope.py", ["tracing"]),
    ("solver/encodehot_pos.py", ["tracing"]),
    ("solver/encodehot_neg.py", ["tracing"]),
    ("encodehot_out_of_scope.py", ["tracing"]),
    ("locks_pos.py", ["locks"]),
    ("locks_neg.py", ["locks"]),
    ("excepts_pos.py", ["excepts"]),
    ("excepts_neg.py", ["excepts"]),
    ("solver/det_pos.py", ["determinism"]),
    ("solver/det_neg.py", ["determinism"]),
    ("det_out_of_scope.py", ["determinism"]),
    ("scheduler/fence_pos.py", ["fencing"]),
    ("scheduler/fence_neg.py", ["fencing"]),
    ("scheduler/fence_controller_pos.py", ["fencing"]),
    ("scheduler/fence_controller_neg.py", ["fencing"]),
    ("fence_out_of_scope.py", ["fencing"]),
    ("lockgraph_pos.py", ["lockgraph"]),
    ("lockgraph_neg.py", ["lockgraph"]),
    ("metrics_pos.py", ["metrics"]),
    ("metrics_neg.py", ["metrics"]),
    ("solver/contract_pos.py", ["contract"]),
    ("solver/contract_neg.py", ["contract"]),
    ("contract_out_of_scope.py", ["contract"]),
    ("solver/contract_fp_pos.py", ["contract"]),
    ("solver/contract_fp_neg.py", ["contract"]),
    ("solver/donate_pos.py", ["contract"]),
    ("solver/donate_neg.py", ["contract"]),
    ("knobs_pos.py", ["contract"]),
    ("knobs_neg.py", ["contract"]),
    ("nhd_tpu/races_pos.py", ["races"]),
    ("nhd_tpu/races_neg.py", ["races"]),
    ("races_out_of_scope.py", ["races"]),
])
def test_fixture_exact_findings(name, packs):
    path = FIXTURES / name
    assert found_of(path, packs) == expected_of(path)


_POS_FIXTURES = ("tracing_pos.py", "locks_pos.py", "excepts_pos.py",
                 "solver/det_pos.py", "scheduler/fence_pos.py",
                 "lockgraph_pos.py", "metrics_pos.py",
                 "solver/contract_pos.py", "solver/contract_fp_pos.py",
                 "solver/donate_pos.py", "knobs_pos.py",
                 "nhd_tpu/races_pos.py")


def test_fixtures_have_positive_coverage_for_every_pack():
    """Every rule pack — per-file and project — has at least one
    deliberately injected violation that its fixture catches (the
    acceptance-criteria clause)."""
    seen_packs = set()
    for name in _POS_FIXTURES:
        for rule, _ in expected_of(FIXTURES / name):
            seen_packs.add(RULES[rule][0])
    assert seen_packs == set(ALL_PACK_NAMES)


def test_all_rule_ids_in_fixtures_are_registered():
    for name in _POS_FIXTURES:
        for rule, _ in expected_of(FIXTURES / name):
            assert rule in RULES


# ---------------------------------------------------------------------------
# suppression + skip-file behavior
# ---------------------------------------------------------------------------

def test_inline_suppressions():
    report = analyze_file(FIXTURES / "suppress.py", ["excepts"])
    # the file holds three violations: two properly suppressed (one by
    # rule id, one blanket), one whose directive lists the WRONG rule
    assert report.suppressed == 2
    assert [(f.rule) for f in report.findings] == ["NHD302"]


def test_wrong_rule_suppression_is_reported_unused():
    report = analyze_file(FIXTURES / "suppress.py", ["excepts"])
    # the ignore[NHD301] on the NHD302 line suppressed nothing
    assert len(report.unused_ignores) == 1


def test_unused_ignores_not_reported_for_packs_that_did_not_run(tmp_path):
    """A --packs subset must not tell people to delete suppressions that
    are load-bearing for the full run."""
    p = tmp_path / "cross_pack.py"
    p.write_text(
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:  # nhdlint: ignore[NHD302]\n"
        "        pass\n"
    )
    # excepts did not run: the NHD302 directive is unjudgeable, not unused
    assert analyze_file(p, ["locks"]).unused_ignores == []
    # excepts ran and the directive suppressed its finding: used
    assert analyze_file(p, ["excepts"]).unused_ignores == []
    # bare 'ignore' is judgeable only by a full-pack run
    q = tmp_path / "bare.py"
    q.write_text("x = 1  # nhdlint: ignore\n")
    assert analyze_file(q, ["locks"]).unused_ignores == []
    assert analyze_file(q).unused_ignores == [1]


def test_skip_file():
    report = analyze_file(FIXTURES / "skipfile.py")
    assert report.skipped
    assert report.findings == []


def test_skip_file_not_honored_mid_file(tmp_path):
    p = tmp_path / "late_skip.py"
    p.write_text(
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        pass\n"
        "# nhdlint: skip-file\n"
    )
    report = analyze_file(p, ["excepts"])
    assert not report.skipped
    assert [f.rule for f in report.findings] == ["NHD302"]


def test_directive_inside_docstring_is_not_honored(tmp_path):
    """Only real comments carry directives: documenting the syntax in a
    docstring must not opt the file (or a line) out of analysis."""
    p = tmp_path / "doc.py"
    p.write_text(
        '"""Usage: put \'# nhdlint: skip-file\' at the top.\n'
        "\n"
        "Or suppress one line:  # nhdlint: ignore[NHD302]\n"
        '"""\n'
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        pass\n"
    )
    report = analyze_file(p, ["excepts"])
    assert not report.skipped
    assert [f.rule for f in report.findings] == ["NHD302"]


def test_fingerprint_distinguishes_same_basename(tmp_path):
    """Baseline slots must not be shared between same-named files in
    different directories."""
    body = (
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        pass\n"
    )
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()
    (tmp_path / "a" / "util.py").write_text(body)
    (tmp_path / "b" / "util.py").write_text(body)
    fa = analyze_file(tmp_path / "a" / "util.py", ["excepts"]).findings
    fb = analyze_file(tmp_path / "b" / "util.py", ["excepts"]).findings
    assert fa[0].fingerprint() != fb[0].fingerprint()
    # baselining a/util.py must not cover b/util.py
    bl = tmp_path / "bl.json"
    write_baseline(fa, bl)
    new, baselined = subtract_baseline(fb, load_baseline(bl))
    assert baselined == 0 and len(new) == 1


def test_fingerprint_agrees_between_relative_and_absolute_paths(tmp_path):
    p = tmp_path / "pkg" / "mod.py"
    p.parent.mkdir()
    p.write_text(
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        pass\n"
    )
    import os
    cwd = os.getcwd()
    try:
        os.chdir(tmp_path)
        rel = analyze_file(Path("pkg") / "mod.py", ["excepts"]).findings
    finally:
        os.chdir(cwd)
    abs_ = analyze_file(p, ["excepts"]).findings
    assert rel[0].fingerprint() == abs_[0].fingerprint()


def test_syntax_error_reported_not_raised(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    report = analyze_file(p)
    assert [f.rule for f in report.findings] == ["NHD000"]


def test_skip_file_in_string_does_not_hide_syntax_error(tmp_path):
    """Even in the tokenize-fallback path (unterminated construct), a
    directive inside a string literal must not suppress NHD000."""
    p = tmp_path / "broken_with_string.py"
    p.write_text(
        'HELP = "use nhdlint: skip-file to opt out"\n'
        "def f(:\n"
    )
    report = analyze_file(p)
    assert not report.skipped
    assert [f.rule for f in report.findings] == ["NHD000"]


# ---------------------------------------------------------------------------
# baseline workflow
# ---------------------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    findings = [
        f for r in [analyze_file(FIXTURES / "excepts_pos.py", ["excepts"])]
        for f in r.findings
    ]
    assert findings
    bl_path = tmp_path / "baseline.json"
    write_baseline(findings, bl_path)
    baseline = load_baseline(bl_path)
    new, baselined = subtract_baseline(findings, baseline)
    assert new == [] and baselined == len(findings)


def test_baseline_survives_line_shift(tmp_path):
    src = (FIXTURES / "excepts_pos.py").read_text()
    p = tmp_path / "shifted.py"
    p.write_text(src)
    findings = analyze_file(p, ["excepts"]).findings
    bl = tmp_path / "baseline.json"
    write_baseline(findings, bl)
    # shift every finding down two lines: fingerprints must still match
    p.write_text("# pad\n# pad\n" + src)
    shifted = analyze_file(p, ["excepts"]).findings
    new, baselined = subtract_baseline(shifted, load_baseline(bl))
    assert new == [] and baselined == len(findings)


def test_baseline_does_not_cover_edited_lines(tmp_path):
    p = tmp_path / "edited.py"
    p.write_text(
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        pass\n"
    )
    findings = analyze_file(p, ["excepts"]).findings
    bl = tmp_path / "baseline.json"
    write_baseline(findings, bl)
    # a *different* offending line is a new finding, not grandfathered
    p.write_text(
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except (ValueError, Exception):\n"
        "        pass\n"
    )
    new, baselined = subtract_baseline(
        analyze_file(p, ["excepts"]).findings, load_baseline(bl)
    )
    assert baselined == 0 and len(new) == 1


def test_baseline_multiplicity(tmp_path):
    """Two identical offending lines consume two baseline slots; a third
    identical new one is NOT covered."""
    body = (
        "def f():\n"
        "    try:\n"
        "        pass\n"
        "    except Exception:\n"
        "        pass\n"
    )
    p = tmp_path / "multi.py"
    p.write_text(body * 2)
    bl = tmp_path / "baseline.json"
    write_baseline(analyze_file(p, ["excepts"]).findings, bl)
    p.write_text(body * 3)
    new, baselined = subtract_baseline(
        analyze_file(p, ["excepts"]).findings, load_baseline(bl)
    )
    assert baselined == 2 and len(new) == 1


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_json_output_and_exit_code(tmp_path, capsys):
    rc = cli_main([str(FIXTURES / "excepts_pos.py"), "--format", "json",
                   "--no-baseline", "--packs", "excepts"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["files"] == 1
    rules = {f["rule"] for f in out["findings"]}
    assert rules == {"NHD301", "NHD302"}
    for f in out["findings"]:
        assert set(f) >= {"rule", "path", "line", "col", "message",
                          "snippet", "fingerprint"}


def test_cli_clean_exit_zero(capsys):
    rc = cli_main([str(FIXTURES / "excepts_neg.py"), "--no-baseline",
                   "--packs", "excepts"])
    assert rc == 0
    assert "clean" in capsys.readouterr().out


def test_cli_write_then_use_baseline(tmp_path, capsys):
    target = str(FIXTURES / "excepts_pos.py")
    bl = str(tmp_path / "bl.json")
    assert cli_main([target, "--baseline", bl, "--write-baseline"]) == 0
    capsys.readouterr()
    rc = cli_main([target, "--baseline", bl])
    out = capsys.readouterr().out
    assert rc == 0
    assert "baselined" in out


def test_cli_write_baseline_refuses_pack_subset(tmp_path, capsys):
    """A subset write would drop every other pack's grandfathered
    entries from the baseline file."""
    rc = cli_main([str(FIXTURES / "excepts_pos.py"), "--packs", "excepts",
                   "--baseline", str(tmp_path / "bl.json"),
                   "--write-baseline"])
    assert rc == 2
    assert "requires all packs" in capsys.readouterr().err
    assert not (tmp_path / "bl.json").exists()


def test_cli_unknown_pack_is_usage_error(capsys):
    assert cli_main(["--packs", "nope"]) == 2


def test_cli_empty_packs_is_usage_error(capsys):
    """--packs "" (e.g. an unset CI variable) must not read as 'clean'
    with zero rules run."""
    assert cli_main([str(FIXTURES / "lockgraph_pos.py"),
                     "--packs", "", "--no-baseline"]) == 2
    assert "selected no packs" in capsys.readouterr().err


def test_cli_no_matching_files_is_usage_error(tmp_path, capsys):
    """A path typo must not read as 'clean' — that would silently turn
    the lint tier off in make lint / CI."""
    assert cli_main([str(tmp_path / "no_such_pkg")]) == 2
    assert "no Python files" in capsys.readouterr().err


def test_cli_reports_unused_ignores(capsys):
    rc = cli_main([str(FIXTURES / "suppress.py"), "--no-baseline",
                   "--packs", "excepts"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "unused 'nhdlint: ignore' directive" in out


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in RULES:
        assert rule in out


def test_module_entrypoint_runs_without_jax():
    """`python -m nhd_tpu.analysis` must stay stdlib-only so the gate can
    run in environments without the jax stack installed."""
    code = (
        "import sys\n"
        "sys.modules['jax'] = None  # poison: import jax -> TypeError\n"
        "sys.modules['numpy'] = None\n"
        "from nhd_tpu.analysis.cli import main\n"
        "raise SystemExit(main(['--list-rules']))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO,
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# the tier-1 gate
# ---------------------------------------------------------------------------

def test_gate_nhd_tpu_is_clean():
    """Every pack (incl. the interprocedural lockgraph) over the whole
    package: any new unsuppressed, unbaselined finding fails tier-1. To
    grandfather an existing finding deliberately, run:
    python -m nhd_tpu.analysis nhd_tpu --write-baseline
    (see docs/STATIC_ANALYSIS.md for when that is acceptable)."""
    reports = analyze_paths([REPO / "nhd_tpu"])
    # a refactor that points the gate at an empty/renamed dir must not
    # pass vacuously
    assert len(reports) > 40
    findings = [f for r in reports for f in r.findings]
    baseline = load_baseline(REPO / ".nhdlint-baseline.json")
    new, _ = subtract_baseline(findings, baseline)
    assert not new, (
        "nhdlint found new unsuppressed issues:\n" + "\n".join(
            f"  {f.path}:{f.line}: {f.rule} {f.message}" for f in new
        )
    )


def test_gate_tools_and_tests_are_clean():
    """make lint covers tools/ and tests/ too (deliberate-violation
    fixture files excluded) — this gate keeps that surface clean in
    tier-1, same contract as the package gate above. The package is in
    the ANALYZED set (exactly like make lint) because project packs
    resolve cross-module facts there — the metrics pack's registration
    registry lives in nhd_tpu/ while tests assert on the exposition
    lines — but only tools/tests findings are judged here (the package
    gate above owns the rest)."""
    reports = analyze_paths(
        [REPO / "nhd_tpu", REPO / "tools", REPO / "tests"],
        exclude=["tests/fixtures"],
    )
    reports = [
        r for r in reports
        if "/tools/" in r.path or "/tests/" in r.path
    ]
    assert len(reports) > 30
    assert not any("fixtures" in r.path for r in reports)
    findings = [f for r in reports for f in r.findings]
    baseline = load_baseline(REPO / ".nhdlint-baseline.json")
    new, _ = subtract_baseline(findings, baseline)
    assert not new, (
        "nhdlint found new unsuppressed issues:\n" + "\n".join(
            f"  {f.path}:{f.line}: {f.rule} {f.message}" for f in new
        )
    )


def _tool_available(mod: str) -> bool:
    import importlib.util
    return importlib.util.find_spec(mod) is not None


@pytest.mark.skipif(not _tool_available("ruff"), reason="ruff not installed")
def test_ruff_clean():
    """Second-tier lint (pycodestyle/pyflakes/bugbear subset, configured
    in pyproject.toml) — enforced wherever ruff is installed."""
    proc = subprocess.run(
        [sys.executable, "-m", "ruff", "check", "nhd_tpu"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.skipif(not _tool_available("mypy"), reason="mypy not installed")
def test_mypy_clean():
    """Scoped mypy (nhd_tpu/core + nhd_tpu/config, configured in
    pyproject.toml) — enforced wherever mypy is installed."""
    proc = subprocess.run(
        [sys.executable, "-m", "mypy"],
        cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


