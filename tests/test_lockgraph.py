"""Interprocedural lock-graph analysis (nhdlint pack 'lockgraph').

Single-file behavior is pinned by the EXPECT fixtures (wired into
test_static_analysis.py's fixture matrix); here: cross-module edges, the
graph export formats, and the baseline fingerprint-rotation guarantees
the grandfather workflow depends on.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from nhd_tpu.analysis import (
    analyze_file,
    analyze_paths,
    load_baseline,
    subtract_baseline,
    write_baseline,
)
from nhd_tpu.analysis.cli import main as cli_main

FIXTURES = Path(__file__).resolve().parent / "fixtures" / "analysis"

_MOD_A = '''\
import threading

from pkg.b import grab_b

_A = threading.Lock()


def hold_a_then_b():
    with _A:
        grab_b()


def grab_a():
    with _A:
        pass
'''

_MOD_B = '''\
import threading

from pkg.a import grab_a

_B = threading.Lock()


def grab_b():
    with _B:
        pass


def hold_b_then_a():
    with _B:
        grab_a()
'''


@pytest.fixture
def cross_module_pkg(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "a.py").write_text(_MOD_A)
    (pkg / "b.py").write_text(_MOD_B)
    return pkg


def test_cross_module_inversion_detected(cross_module_pkg):
    """The tentpole case: A→B lives in one module, B→A in another; only
    the whole-project call graph can see the cycle."""
    reports = analyze_paths([cross_module_pkg], ["lockgraph"])
    found = {
        (Path(f.path).name, f.rule, f.line)
        for r in reports for f in r.findings
    }
    # the witnesses sit at the call-under-lock lines in each module
    assert ("a.py", "NHD210", 10) in found, found
    assert ("b.py", "NHD210", 15) in found, found
    # and each module alone has no inversion to see
    for name in ("a.py", "b.py"):
        solo = analyze_file(cross_module_pkg / name, ["lockgraph"])
        assert solo.findings == [], solo.findings


def test_cross_module_inversion_suppressible_inline(cross_module_pkg):
    src = (cross_module_pkg / "a.py").read_text()
    src = src.replace(
        "        grab_b()",
        "        grab_b()  # nhdlint: ignore[NHD210]",
    )
    (cross_module_pkg / "a.py").write_text(src)
    reports = analyze_paths([cross_module_pkg], ["lockgraph"])
    by_name = {Path(r.path).name: r for r in reports}
    assert by_name["a.py"].findings == []
    assert by_name["a.py"].suppressed == 1
    # the b.py direction still reports
    assert [f.rule for f in by_name["b.py"].findings] == ["NHD210"]


def test_transitive_blocking_through_modules(tmp_path):
    """NHD211 follows the call graph across modules: the lock holder is
    two modules away from the queue.get."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "sink.py").write_text(
        "import queue\n"
        "_Q = queue.Queue()\n"
        "def drain():\n"
        "    return _Q.get()\n"
    )
    (pkg / "mid.py").write_text(
        "from pkg.sink import drain\n"
        "def relay():\n"
        "    return drain()\n"
    )
    (pkg / "top.py").write_text(
        "import threading\n"
        "from pkg.mid import relay\n"
        "_L = threading.Lock()\n"
        "def pump():\n"
        "    with _L:\n"
        "        return relay()\n"
    )
    reports = analyze_paths([pkg], ["lockgraph"])
    findings = [f for r in reports for f in r.findings]
    assert [f.rule for f in findings] == ["NHD211"]
    f = findings[0]
    assert Path(f.path).name == "top.py" and f.line == 6
    assert "drain" in f.message and "sink.py:4" in f.message


def test_exclude_patterns_skip_paths(tmp_path):
    (tmp_path / "keep.py").write_text("x = 1\n")
    sub = tmp_path / "generated"
    sub.mkdir()
    (sub / "junk.py").write_text("def f(:\n")     # would be NHD000
    reports = analyze_paths([tmp_path], exclude=["generated"])
    assert [Path(r.path).name for r in reports] == ["keep.py"]


# ---------------------------------------------------------------------------
# lock graph export
# ---------------------------------------------------------------------------

def test_lock_graph_json_and_dot_export(cross_module_pkg, tmp_path, capsys):
    out_json = tmp_path / "graph.json"
    out_dot = tmp_path / "graph.dot"
    rc = cli_main([
        str(cross_module_pkg), "--packs", "lockgraph", "--no-baseline",
        "--lock-graph-json", str(out_json),
        "--lock-graph-dot", str(out_dot),
    ])
    assert rc == 1          # the seeded inversion reports
    graph = json.loads(out_json.read_text())
    assert graph["version"] == 1
    keys = {l["key"] for l in graph["locks"]}
    assert any(k.endswith(":_A") for k in keys)
    assert any(k.endswith(":_B") for k in keys)
    for lock in graph["locks"]:
        assert set(lock) == {"key", "name", "kind", "site"}
        path, _, line = lock["site"].rpartition(":")
        assert path.endswith(".py") and line.isdigit()
    # both directions present as edges, and the pair is flagged inverted
    edges = {(e["from"].rsplit(":", 1)[1], e["to"].rsplit(":", 1)[1])
             for e in graph["edges"]}
    assert {("_A", "_B"), ("_B", "_A")} <= edges
    assert len(graph["inversions"]) == 1
    dot = out_dot.read_text()
    assert dot.startswith("digraph nhd_lock_order")
    assert "color=red" in dot   # the inverted pair is highlighted


def test_lock_graph_export_on_clean_tree(tmp_path, capsys):
    (tmp_path / "clean.py").write_text(
        "import threading\n"
        "_L = threading.Lock()\n"
        "def f():\n"
        "    with _L:\n"
        "        pass\n"
    )
    out = tmp_path / "g.json"
    rc = cli_main([str(tmp_path), "--no-baseline",
                   "--lock-graph-json", str(out)])
    assert rc == 0
    graph = json.loads(out.read_text())
    assert len(graph["locks"]) == 1
    assert graph["edges"] == [] and graph["inversions"] == []


# ---------------------------------------------------------------------------
# baseline fingerprint rotation (satellite): renames and line shifts must
# not resurrect grandfathered findings
# ---------------------------------------------------------------------------

def _baseline_of(path: Path, tmp_path: Path) -> Path:
    findings = analyze_file(path, ["lockgraph"]).findings
    assert findings, "fixture must produce findings to grandfather"
    bl = tmp_path / "bl.json"
    write_baseline(findings, bl)
    return bl


def test_baseline_survives_line_shift_for_lockgraph(tmp_path):
    src = (FIXTURES / "lockgraph_pos.py").read_text()
    p = tmp_path / "shifted.py"
    p.write_text(src)
    bl = _baseline_of(p, tmp_path)
    p.write_text("# pad\n# pad\n# pad\n" + src)
    shifted = analyze_file(p, ["lockgraph"]).findings
    new, baselined = subtract_baseline(shifted, load_baseline(bl))
    assert new == [] and baselined == len(shifted) > 0


def test_baseline_survives_unrelated_function_rename(tmp_path):
    """Renaming a function that is not on any offending line must not
    resurrect baselined findings (fingerprints key on the offending
    line's text, not on function or line identity)."""
    src = (FIXTURES / "lockgraph_pos.py").read_text()
    p = tmp_path / "renamed.py"
    p.write_text(src)
    bl = _baseline_of(p, tmp_path)
    # 'backward' owns the B->A direction; its def line is not a finding
    # line (the finding sits on the inner 'with _A:')
    assert "def backward" in src
    p.write_text(src.replace("def backward", "def reversed_order"))
    renamed = analyze_file(p, ["lockgraph"]).findings
    new, baselined = subtract_baseline(renamed, load_baseline(bl))
    assert new == [] and baselined == len(renamed) > 0


def test_baseline_rotation_detects_edited_offending_line(tmp_path):
    """Editing the offending line itself IS a fresh finding — rotation
    must not over-forgive."""
    p = tmp_path / "edited.py"
    src = (
        "import queue\n"
        "import threading\n"
        "_L = threading.Lock()\n"
        "_Q = queue.Queue()\n"
        "def f():\n"
        "    with _L:\n"
        "        _Q.get()\n"
    )
    p.write_text(src)
    bl = _baseline_of(p, tmp_path)
    p.write_text(src.replace("_Q.get()", "_Q.get()  # changed"))
    edited = analyze_file(p, ["lockgraph"]).findings
    new, baselined = subtract_baseline(edited, load_baseline(bl))
    assert baselined == 0 and len(new) == 1


def test_baseline_rename_of_offending_callee_is_fresh(tmp_path):
    """Renaming the function *called on* the offending line changes the
    line's text — by design a fresh finding, the same contract the
    PR 1 baseline documents for edited lines."""
    src = (FIXTURES / "lockgraph_pos.py").read_text()
    p = tmp_path / "callee_renamed.py"
    p.write_text(src)
    bl = _baseline_of(p, tmp_path)
    p.write_text(src.replace("_on_change", "_fire_callbacks"))
    renamed = analyze_file(p, ["lockgraph"]).findings
    new, _ = subtract_baseline(renamed, load_baseline(bl))
    assert any(f.rule == "NHD212" for f in new)
