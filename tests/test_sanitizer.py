"""nhdsan runtime deadlock sanitizer tests.

The live two-thread inversion here is the acceptance-criteria witness:
under instrumentation a real deadlock raises DeadlockError with a
wait-for-graph cycle instead of hanging the suite. The streaming-mesh
regression test reproduces the cycle *shape* that burned the tier-1
budget before solver/streaming.py serialized CPU-backend mesh solves
(two tile workers, each holding its own solve context while waiting on
a resource the other holds).
"""

import contextlib
import queue
import threading
import time

import pytest

from nhd_tpu.sanitizer import (
    DeadlockError,
    SanLock,
    Sanitizer,
    get_sanitizer,
    install,
    uninstall,
)


@contextlib.contextmanager
def _installed():
    """Globally installed sanitizer for the block. When the session
    already runs under NHD_SAN=1, reuse the session instance and leave
    it installed on exit."""
    existing = get_sanitizer()
    if existing is not None:
        yield existing
        return
    san = install()
    try:
        yield san
    finally:
        uninstall()


def _run_inversion(san: Sanitizer, a: SanLock, b: SanLock):
    """Drive a guaranteed A/B inversion; returns the DeadlockErrors the
    workers caught. Both threads must terminate (no hang)."""
    ready = threading.Barrier(2)
    errs = []

    def worker(first, second, tag):
        try:
            with first:
                ready.wait()
                with second:
                    pass
        except DeadlockError as exc:
            errs.append((tag, exc))

    t1 = threading.Thread(target=worker, args=(a, b, "ab"), name="san-ab")
    t2 = threading.Thread(target=worker, args=(b, a, "ba"), name="san-ba")
    t1.start()
    t2.start()
    t1.join(10)
    t2.join(10)
    assert not t1.is_alive() and not t2.is_alive(), "sanitizer failed to " \
        "break the deadlock — threads still hung"
    return errs


def test_live_two_thread_inversion_reports_cycle():
    """Acceptance: a live lock-order inversion produces a wait-for-graph
    cycle witness and a DeadlockError, not a hang."""
    san = Sanitizer(poll_interval=0.01)
    lock_a = san.Lock()
    lock_b = san.Lock()    # distinct line: distinct site in the witness
    errs = _run_inversion(san, lock_a, lock_b)
    assert errs, "at least one thread must observe the cycle"
    cycles = san.witnesses("cycle")
    assert cycles
    w = cycles[0]
    # the witness names both waited-for locks with their creation sites
    waited = {hop["waits_for"] for hop in w["cycle"]}
    assert len(waited) == 2
    assert all("test_sanitizer.py" in site for site in waited)
    assert w["held_by_thread"]


def test_streaming_mesh_cycle_shape_regression():
    """The pre-fix streaming-mesh deadlock shape: worker 0 holds tile 0's
    solve context and waits for the cross-tile rendezvous resource held
    by worker 1, which waits for tile 0's context. The product fix
    serializes CPU-backend mesh solves (solver/streaming.py
    _CPU_MESH_SOLVE_LOCK); this fixture pins the sanitizer's ability to
    catch the shape if it ever comes back."""
    san = Sanitizer(poll_interval=0.01)
    tile0_ctx = san.Lock()
    tile1_ctx = san.Lock()
    errs = _run_inversion(san, tile0_ctx, tile1_ctx)
    assert errs and san.witnesses("cycle")
    # with the witness recorded, the survivors completed: re-acquiring
    # in a single global order now succeeds
    with tile0_ctx:
        with tile1_ctx:
            pass
    assert len(san.witnesses("cycle")) >= 1


def test_same_thread_reacquire_of_lock_raises():
    """Re-acquiring a non-reentrant Lock the calling thread already owns
    is a one-edge self-cycle (the runtime NHD212): DeadlockError, not an
    eternal hang."""
    san = Sanitizer(poll_interval=0.01)
    lk = san.Lock()
    with lk:
        with pytest.raises(DeadlockError, match="re-entrant"):
            lk.acquire()
        # bounded and non-blocking forms degrade gracefully instead
        assert lk.acquire(timeout=0.05) is False
        assert lk.acquire(blocking=False) is False
    assert len(san.witnesses("cycle")) == 1
    # the lock is still usable after the witness
    with lk:
        pass


def test_rlock_reentrancy_is_not_a_cycle():
    san = Sanitizer(poll_interval=0.01)
    r = san.RLock()
    with r:
        with r:
            assert r._is_owned()
    assert san.witnesses() == []


def test_bounded_acquire_times_out_instead_of_raising():
    """A timeout-bounded waiter cannot deadlock — it must time out
    quietly even while a genuine inversion is in progress."""
    san = Sanitizer(poll_interval=0.01)
    a = san.Lock()
    got = []

    def holder():
        with a:
            time.sleep(0.5)

    t = threading.Thread(target=holder)
    t.start()
    time.sleep(0.1)
    got.append(a.acquire(timeout=0.05))
    t.join(5)
    assert got == [False]
    assert san.witnesses("cycle") == []


def test_condition_wait_notify_roundtrip():
    san = Sanitizer(poll_interval=0.01)
    cv = san.Condition()
    hits = []

    def waiter():
        with cv:
            if cv.wait(5):
                hits.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        cv.notify()
    t.join(5)
    assert hits == [1]
    assert san.witnesses("cycle") == []


def test_install_patches_and_uninstall_restores():
    if get_sanitizer() is not None:
        pytest.skip("session-level NHD_SAN install active")
    orig_lock = threading.Lock
    orig_get = queue.Queue.get
    san = install()
    try:
        assert threading.Lock is not orig_lock
        lk = threading.Lock()
        assert isinstance(lk, SanLock)
        cv = threading.Condition()
        assert isinstance(cv, threading.Condition)  # still a type
        with lk:
            pass
        assert get_sanitizer() is san
        # install is idempotent: second call returns the active instance
        assert install() is san
    finally:
        uninstall()
    assert threading.Lock is orig_lock
    assert queue.Queue.get is orig_get
    assert get_sanitizer() is None
    # locks created under instrumentation keep working after uninstall
    with lk:
        pass


def test_hold_while_blocking_witness_and_dedupe():
    with _installed() as san:
        before = {
            (w["blocking"], w["at"]): w["count"]
            for w in san.witnesses("hold_while_blocking")
        }
        lk = threading.Lock()
        q = queue.Queue()
        for _ in range(3):
            q.put(1)
            with lk:
                q.get()     # unbounded get with a lock held
    wits = [
        w for w in san.witnesses("hold_while_blocking")
        if "test_sanitizer.py" in w["at"]
        and (w["blocking"], w["at"]) not in before
    ]
    assert len(wits) == 1, wits      # deduped by site
    assert wits[0]["count"] == 3
    assert any("Lock@" in h for h in wits[0]["held"])


def test_witnesses_flow_into_flight_recorder_and_chrome_trace():
    from nhd_tpu.obs import chrome, recorder

    rec = recorder.enable(capacity=256)
    try:
        san = Sanitizer(poll_interval=0.01)
        _run_inversion(san, san.Lock(), san.Lock())
        spans = [s for s in rec.spans() if s.cat == "nhdsan"]
        assert spans, "cycle witness must mirror into the recorder"
        assert spans[0].name == "nhdsan.cycle"
        # standalone export path (recorder off in production runs)
        trace = san.chrome_trace()
        assert chrome.validate_chrome_trace(trace) == []
        names = {
            e["name"] for e in trace["traceEvents"] if e["ph"] == "X"
        }
        assert "nhdsan.cycle" in names
    finally:
        recorder.disable()


def test_streaming_schedule_runs_clean_under_instrumentation():
    """End-to-end: the real streaming pipeline under a global install
    completes with zero cycle witnesses (the tier-1 NHD_SAN acceptance,
    in miniature)."""
    with _installed() as san:
        from nhd_tpu.sim import make_cluster
        from nhd_tpu.solver import StreamingScheduler
        from tests.test_batch import items, simple_request

        nodes = make_cluster(4)
        reqs = [simple_request(gpus=i % 2) for i in range(12)]
        results, stats = StreamingScheduler(
            tile_nodes=2, chunk_pods=5, respect_busy=False
        ).schedule(nodes, items(reqs), now=0.0)
        assert stats.scheduled == 12
    assert san.witnesses("cycle") == []


def test_report_shape():
    san = Sanitizer(poll_interval=0.01)
    _run_inversion(san, san.Lock(), san.Lock())
    rep = san.report()
    assert rep["version"] == 1
    assert rep["cycles"] and isinstance(rep["cycles"][0]["cycle"], list)
    assert isinstance(rep["hold_while_blocking"], list)
    assert all(
        {"site", "kind", "acquisitions", "contended"} <= set(l)
        for l in rep["locks"]
    )


# ---------------------------------------------------------------------------
# nhdrace: the Eraser-style dynamic race layer (sanitizer/races.py).
# Every test builds a PRIVATE Sanitizer + RaceSanitizer pair — never the
# session globals: injecting a race into the session instance would (by
# design) fail the NHD_RACE=1 session teardown that `make sanitize` runs
# these very tests under.
# ---------------------------------------------------------------------------

from nhd_tpu.sanitizer import (  # noqa: E402  (grouped with the suite below)
    RaceSanitizer,
    field_key,
    get_race_sanitizer,
    maybe_watch,
)
from nhd_tpu.sanitizer.races import _InjectedRace, inject_race  # noqa: E402


def _race_pair():
    san = Sanitizer(poll_interval=0.01)
    return san, RaceSanitizer(san)


class _LockedCounter:
    """Benign concurrent writer: every mutation happens under one lock,
    so the candidate lockset never empties."""

    def __init__(self):
        self.value = 0


def test_injected_race_fires_with_joinable_key():
    """The negative control: two unsynchronized writers on a watched
    dummy MUST produce exactly one deduped race witness, keyed with the
    same `mod/label:Class.attr` identity the static pack uses — the
    static<->dynamic join."""
    san, rs = _race_pair()
    try:
        rep = inject_race(rs)
    finally:
        rs.unpatch_all()
    assert rep["races"], "injected race must be detected"
    assert len(rep["races"]) == 1, "witnesses dedupe per field key"
    race = rep["races"][0]
    assert race["key"] == field_key(_InjectedRace, "counter")
    assert race["key"] == "sanitizer/races:_InjectedRace.counter"
    assert len(race["threads"]) == 2
    assert race["allowed"] is False
    assert rep["suppressed"] == []
    assert race["key"] in rep["watched_fields"]
    # the witness mirrors into the nhdsan surfaces (report + trace)
    assert san.witnesses("race")
    names = {
        e["name"] for e in san.chrome_trace()["traceEvents"]
        if e["ph"] == "X"
    }
    assert "nhdsan.race" in names


def test_locked_concurrent_writes_stay_silent():
    """Two threads hammering a watched field under one common lock:
    candidate-lockset intersection keeps the lock, zero witnesses."""
    san, rs = _race_pair()
    obj = _LockedCounter()
    rs.watch(obj, ("value",))
    lk = san.Lock()
    gate = threading.Barrier(2)

    def spin():
        gate.wait(timeout=10)
        for _ in range(200):
            with lk:
                obj.value += 1

    try:
        threads = [threading.Thread(target=spin) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        rs.unpatch_all()
    rep = rs.report()
    assert rep["races"] == [] and rep["suppressed"] == []
    assert obj.value == 400      # instrumentation must not drop writes


def test_race_allow_glob_suppresses_but_records():
    """NHD_RACE_ALLOW is the dynamic mirror of a written-justification
    suppression: the witness is still recorded (auditable), the run
    stays green."""
    san = Sanitizer(poll_interval=0.01)
    rs = RaceSanitizer(san, allow="sanitizer/races:_InjectedRace.*")
    try:
        rep = inject_race(rs)
    finally:
        rs.unpatch_all()
    assert rep["races"] == []
    assert len(rep["suppressed"]) == 1
    assert rep["suppressed"][0]["allowed"] is True
    assert rep["suppressed"][0]["key"].endswith("_InjectedRace.counter")


def test_unpatch_restores_setattr():
    class _Plain:
        def __init__(self):
            self.x = 0

    _san, rs = _race_pair()
    obj = _Plain()
    rs.watch(obj, ("x",))
    assert "__setattr__" in _Plain.__dict__      # wrapper installed
    assert getattr(_Plain.__setattr__, "_nhdrace_wrapped", False)
    obj.x = 1                                    # instrumented write works
    rs.unpatch_all()
    assert "__setattr__" not in _Plain.__dict__  # slot wrapper restored
    obj.x = 2
    assert obj.x == 2


def test_maybe_watch_is_noop_without_install():
    if get_race_sanitizer() is not None:
        pytest.skip("session-level NHD_RACE install active")
    maybe_watch(_LockedCounter(), ("value",))    # must not raise/patch
    assert "__setattr__" not in _LockedCounter.__dict__
