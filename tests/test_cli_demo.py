"""Full-process smoke: the real three-thread harness (controller +
scheduler + RPC + watchdog) in a subprocess, bounded by --run-seconds —
the closest hermetic analog of `bin/nhd` actually running."""

import subprocess
import sys

from tests.conftest import subprocess_env


def test_fake_demo_process_binds_triadset():
    r = subprocess.run(
        [sys.executable, "-m", "nhd_tpu.cli", "--fake",
         "--rpc-port", "0", "--run-seconds", "15"],
        capture_output=True, text=True, timeout=120,
        env=subprocess_env(JAX_PLATFORMS="cpu"),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "demo summary:" in r.stdout
    summary = [l for l in r.stdout.splitlines() if "demo summary" in l][0]
    # the 6-replica TriadSet reconciles; with the live default busy
    # back-off (one GPU pod per node per 30 s window, reference
    # Matcher.py:103-111) exactly one pod binds per node inside a 15 s
    # run — the remaining two wait out the window (15 s leaves wide
    # margin for subprocess jax import + first compile on a slow host)
    assert "4/6 pods bound across 4 nodes" in summary, summary


def test_watch_event_wakes_scheduler_promptly():
    """Event-driven loop pin (r5): a pod created through the backend must
    bind in well under the 0.5 s queue-block window. The pre-r5 loop
    blocked on the RPC queue and polled the watch queue non-blocking
    (and the controller slept a fixed 0.1 s between backend polls), so
    create→bind latency was quantized at ~0.5-0.6 s; the event-driven
    scheduler wait + controller blocking poll bring it down to solver
    time. The bound here (2 s total for 5 binds) fails decisively if
    either quantized wait regresses while staying robust to CI load."""
    import time

    from nhd_tpu.cli import build_threads, make_fake_backend
    from nhd_tpu.sim import make_triad_config

    backend = make_fake_backend()
    threads, _ = build_threads(
        backend, rpc_port=45702, metrics_port=0, respect_busy=False
    )
    for t in threads:
        t.start()
    try:
        total = 0.0
        for i in range(5):
            name = f"wake-{i}"
            t0 = time.perf_counter()
            backend.create_pod(name, cfg_text=make_triad_config())
            deadline = t0 + 10
            while time.perf_counter() < deadline:
                p = backend.pods.get(("default", name))
                if p is not None and p.node:
                    break
                time.sleep(0.002)
            else:
                raise AssertionError(f"{name} never bound")
            total += time.perf_counter() - t0
            backend.delete_pod(name, emit_watch=True)
        # 5 binds through watch+controller+scheduler: pre-r5 floor was
        # ~3 s (5 x ~0.6 s of queue latency); event-driven is ~50 ms
        assert total < 2.0, f"5 binds took {total:.2f}s — queue-latency regression?"
    finally:
        for t in threads:
            stop = getattr(t, "stop", None)
            if stop is not None:
                stop()
