"""Full-process smoke: the real three-thread harness (controller +
scheduler + RPC + watchdog) in a subprocess, bounded by --run-seconds —
the closest hermetic analog of `bin/nhd` actually running."""

import subprocess
import sys

from tests.conftest import subprocess_env


def test_fake_demo_process_binds_triadset():
    r = subprocess.run(
        [sys.executable, "-m", "nhd_tpu.cli", "--fake",
         "--rpc-port", "0", "--run-seconds", "15"],
        capture_output=True, text=True, timeout=120,
        env=subprocess_env(JAX_PLATFORMS="cpu"),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "demo summary:" in r.stdout
    summary = [l for l in r.stdout.splitlines() if "demo summary" in l][0]
    # the 6-replica TriadSet reconciles; with the live default busy
    # back-off (one GPU pod per node per 30 s window, reference
    # Matcher.py:103-111) exactly one pod binds per node inside a 15 s
    # run — the remaining two wait out the window (15 s leaves wide
    # margin for subprocess jax import + first compile on a slow host)
    assert "4/6 pods bound across 4 nodes" in summary, summary
