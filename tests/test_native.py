"""Native (C++) assignment core parity vs the pure-numpy path."""

import copy
import random

import pytest

from nhd_tpu import native
from nhd_tpu.solver.encode import encode_cluster
from nhd_tpu.solver.fast_assign import FastCluster
from nhd_tpu.solver.jax_matcher import JaxMatcher
from tests.test_fast_assign import state_fingerprint
from tests.test_jax_matcher import random_cluster, random_request

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native assignment core not built"
)


def run_path(nodes, plans, use_native: bool):
    arrays = encode_cluster(nodes, now=1010.0)
    fast = FastCluster(nodes, arrays.U, arrays.K, arrays=arrays)
    if not use_native:
        fast._lib = None
    recs = []
    for m, req in plans:
        n = arrays.names.index(m.node)
        try:
            recs.append(fast.assign(n, m.mapping, req))
        except Exception as exc:
            recs.append(("FAIL", type(exc).__name__))
    fast.sync_to_nodes()
    return recs, state_fingerprint(nodes)


def rec_essence(r):
    if isinstance(r, tuple):
        return r
    return (
        r.node_name,
        [(g.numa, g.group_cpus, g.helper_cpus, g.gpu_devids, g.nic_uk,
          g.nic_flat, g.gpu_rows) for g in r.groups],
        r.misc_cpus,
        r.nic_list,
    )


def _tiny_request():
    """A 1-group CPU-only request that fits any node with two free cores."""
    from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
    from nhd_tpu.core.topology import MapMode, SmtMode

    return PodRequest(
        groups=(GroupRequest(CpuRequest(1, SmtMode.ANY),
                             CpuRequest(0, SmtMode.OFF), 0, 0.0, 0.0),),
        misc=CpuRequest(0, SmtMode.OFF),
        hugepages_gb=0,
        map_mode=MapMode.NUMA,
        node_groups=frozenset({"default", "edge"}),
    )


@pytest.mark.parametrize("seed", range(12))
def test_native_matches_numpy(seed):
    """Every seed must exercise the path: keep drawing requests until at
    least 4 feasible plans exist; if the degraded random cluster can't fit
    even the tiny fallback request, revive one node (VERDICT r1 weak-3:
    no seed may silently skip)."""
    rng = random.Random(1000 + seed)
    nodes_a = random_cluster(rng, 4)
    matcher = JaxMatcher()

    def draw_plans():
        plans = []
        for _ in range(60):
            if len(plans) >= 4:
                break
            req = random_request(rng)
            m = matcher.find_node(nodes_a, req, now=1010.0, respect_busy=False)
            if m is not None:
                plans.append((m, req))
        return plans

    plans = draw_plans()
    if not plans:
        tiny = _tiny_request()
        m = matcher.find_node(nodes_a, tiny, now=1010.0, respect_busy=False)
        if m is None:
            # pathological cluster: revive the first node and retry
            node = next(iter(nodes_a.values()))
            node.active, node.maintenance = True, False
            for c in node.cores:
                c.used = False
            m = matcher.find_node(nodes_a, tiny, now=1010.0, respect_busy=False)
        assert m is not None, "tiny request must fit a revived node"
        plans = [(m, tiny)]
    nodes_b = copy.deepcopy(nodes_a)

    recs_native, fp_native = run_path(nodes_a, plans, use_native=True)
    recs_numpy, fp_numpy = run_path(nodes_b, plans, use_native=False)

    assert [rec_essence(r) for r in recs_native] == [
        rec_essence(r) for r in recs_numpy
    ]
    assert fp_native == fp_numpy
