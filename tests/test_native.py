"""Native (C++) assignment core parity vs the pure-numpy path."""

import copy
import random

import pytest

from nhd_tpu import native
from nhd_tpu.solver.encode import encode_cluster
from nhd_tpu.solver.fast_assign import FastCluster
from nhd_tpu.solver.jax_matcher import JaxMatcher
from tests.test_fast_assign import state_fingerprint
from tests.test_jax_matcher import random_cluster, random_request

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native assignment core not built"
)


def run_path(nodes, plans, use_native: bool):
    arrays = encode_cluster(nodes, now=1010.0)
    fast = FastCluster(nodes, arrays.U, arrays.K, arrays=arrays)
    if not use_native:
        fast._lib = None
    recs = []
    for m, req in plans:
        n = arrays.names.index(m.node)
        try:
            recs.append(fast.assign(n, m.mapping, req))
        except Exception as exc:
            recs.append(("FAIL", type(exc).__name__))
    fast.sync_to_nodes()
    return recs, state_fingerprint(nodes)


def rec_essence(r):
    if isinstance(r, tuple):
        return r
    return (
        r.node_name,
        [(g.numa, g.group_cpus, g.helper_cpus, g.gpu_devids, g.nic_uk,
          g.nic_flat, g.gpu_rows) for g in r.groups],
        r.misc_cpus,
        r.nic_list,
    )


@pytest.mark.parametrize("seed", range(12))
def test_native_matches_numpy(seed):
    rng = random.Random(1000 + seed)
    nodes_a = random_cluster(rng, 4)
    nodes_b = copy.deepcopy(nodes_a)
    matcher = JaxMatcher()
    plans = []
    for _ in range(6):
        req = random_request(rng)
        m = matcher.find_node(nodes_a, req, now=1010.0, respect_busy=False)
        if m is not None:
            plans.append((m, req))
    if not plans:
        pytest.skip("no feasible pods this seed")

    recs_native, fp_native = run_path(nodes_a, plans, use_native=True)
    recs_numpy, fp_numpy = run_path(nodes_b, plans, use_native=False)

    assert [rec_essence(r) for r in recs_native] == [
        rec_essence(r) for r in recs_numpy
    ]
    assert fp_native == fp_numpy
