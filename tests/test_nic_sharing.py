"""NIC-sharing mode (NHD_NIC_SHARING=1): cross-pod bandwidth accounting.

The reference hard-codes sharing off (Node.py:20); here it is a runtime
setting. With sharing on, a NIC's headroom is capacity minus booked
bandwidth rather than all-or-nothing.
"""

import copy
import random

import pytest

import nhd_tpu.core.node as node_mod
from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
from nhd_tpu.core.topology import MapMode, SmtMode
from nhd_tpu.sim import SynthNodeSpec, make_cluster
from nhd_tpu.solver import BatchItem, BatchScheduler, JaxMatcher, find_node
from tests.test_jax_matcher import random_cluster, random_request


@pytest.fixture
def sharing_on(monkeypatch):
    monkeypatch.setattr(node_mod, "ENABLE_NIC_SHARING", True)


def bw_req(rx):
    return PodRequest(
        groups=(GroupRequest(CpuRequest(2, SmtMode.ON), CpuRequest(0, SmtMode.OFF),
                             0, rx, 1.0),),
        misc=CpuRequest(0, SmtMode.OFF),
        hugepages_gb=0,
        map_mode=MapMode.NUMA,
    )


def test_two_pods_share_one_nic(sharing_on):
    nodes = make_cluster(1, SynthNodeSpec(nics_per_numa=1, sockets=2,
                                          phys_cores=24))
    sched = BatchScheduler(respect_busy=False)
    items = [BatchItem(("ns", f"p{i}"), bw_req(40.0)) for i in range(4)]
    results, stats = sched.schedule(nodes, items, now=0.0)
    placed = [r for r in results if r.node]
    # 2 NICs x 90 Gbps schedulable, 40 each -> 4 pods fit (2 per NIC);
    # with sharing OFF only 2 would
    assert len(placed) == 4
    # booked bandwidth adds up on the mirror
    total_rx = sum(n.speed_used[0] for nd in nodes.values() for n in nd.nics)
    assert total_rx == 160.0


def test_sharing_respects_headroom(sharing_on):
    nodes = make_cluster(1, SynthNodeSpec(nics_per_numa=1, sockets=2))
    sched = BatchScheduler(respect_busy=False)
    items = [BatchItem(("ns", f"p{i}"), bw_req(60.0)) for i in range(4)]
    results, _ = sched.schedule(nodes, items, now=0.0)
    # 60 + 60 > 90 per NIC -> one pod per NIC only
    assert sum(1 for r in results if r.node) == 2


@pytest.mark.parametrize("seed", range(8))
def test_sharing_parity_oracle_vs_jax(sharing_on, seed):
    rng = random.Random(500 + seed)
    nodes = random_cluster(rng, 3)
    # book some bandwidth so partial headroom exists
    for nd in nodes.values():
        for nic in nd.nics:
            if rng.random() < 0.4:
                nic.pods_used = 1
                nic.speed_used = [30.0, 10.0]
    matcher = JaxMatcher()
    for _ in range(3):
        req = random_request(rng)
        want = find_node(nodes, req, now=1010.0)
        got = matcher.find_node(nodes, req, now=1010.0)
        assert (want is None) == (got is None)
        if want:
            assert got.node == want.node and got.mapping == want.mapping
