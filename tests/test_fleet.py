"""Fleet observability integration (ISSUE 7): cross-replica trace
context, the federation journey merge, the SLO clock surviving replica
churn, demotion dumps, and the bench-artifact regression gate.

The acceptance pin lives here: under ``ChaosSim(federation=3)`` a pod
that spills across >= 2 shards yields ONE merged Chrome-trace journey —
a single corr ID with spans from >= 2 replicas — and the run's fleet
artifact validates with spillover-hop and SLO burn summaries.
"""

from __future__ import annotations

import json
import os
import queue
import subprocess
import sys
from pathlib import Path

import pytest

from nhd_tpu.k8s.fake import FakeClusterBackend
from nhd_tpu.k8s.interface import (
    TRACE_ANNOTATION,
    parse_trace_record,
    render_trace_record,
)
from nhd_tpu.k8s.lease import LeaderElector, ShardedElector
from nhd_tpu.obs.chrome import (
    journey_replicas,
    pod_journeys,
    validate_chrome_trace,
)
from nhd_tpu.obs.fleet import validate_fleet_artifact
from nhd_tpu.obs.recorder import FlightRecorder
from nhd_tpu.obs.slo import SloTracker
from nhd_tpu.scheduler.core import Scheduler
from nhd_tpu.scheduler.events import WatchQueue
from nhd_tpu.sim.chaos import ChaosSim
from nhd_tpu.sim.faults import FaultProfile, FaultyBackend
from tests.test_scheduler import make_backend, pod_cfg

REPO = Path(__file__).resolve().parent.parent


def _scheduler(backend, *, identity: str, slo=None) -> Scheduler:
    sched = Scheduler(
        backend, WatchQueue(), queue.Queue(), respect_busy=False,
        recorder=FlightRecorder(capacity=256, identity=identity), slo=slo,
    )
    sched.build_initial_node_list()
    sched.load_deployed_configs()
    return sched


# ---------------------------------------------------------------------------
# cross-replica trace context
# ---------------------------------------------------------------------------

def test_trace_record_roundtrip_and_garbage_tolerance():
    rec = {"corr": "c1", "origin": "rep-a", "t0": 5.0}
    assert parse_trace_record(render_trace_record(rec)) == rec
    assert parse_trace_record(None) is None
    assert parse_trace_record("") is None
    assert parse_trace_record("{not json") is None
    assert parse_trace_record('{"corr": ""}') is None  # empty ID = absent
    assert parse_trace_record('{"origin": "x"}') is None  # no corr at all


def test_corr_stamped_at_first_receipt_and_adopted_by_later_replica():
    """The annotation roundtrip on the fake backend: replica A stamps
    the pod's corr ID at first receipt; replica B (spillover claim,
    handoff, restart — any later receipt) ADOPTS it instead of minting
    its own, so the journey keeps ONE ID."""
    backend = make_backend(n_nodes=1)
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    a = _scheduler(backend, identity="rep-a")
    got_a = a._resolve_trace_corr("triad-0", "default", "c-from-a")
    assert got_a == "c-from-a"
    stamped = parse_trace_record(
        backend.pods[("default", "triad-0")].annotations[TRACE_ANNOTATION]
    )
    assert stamped["corr"] == "c-from-a"
    assert stamped["origin"] == a.replica_id

    b = _scheduler(backend, identity="rep-b")
    assert b._resolve_trace_corr("triad-0", "default", "c-from-b") == "c-from-a"
    # adoption is read-only: the stamp still names the origin replica
    stamped2 = parse_trace_record(
        backend.pods[("default", "triad-0")].annotations[TRACE_ANNOTATION]
    )
    assert stamped2 == stamped


def test_adoption_realiases_already_recorded_watch_leg():
    """The controller records the watch_event span BEFORE the scheduler
    can read the cluster-stamped corr (adoption happens at batch
    admission). When adoption changes the ID, the already-recorded
    receipt leg must be re-aliased into the pod's journey — not left as
    a one-span orphan corr that drops the queue-wait leg from the merge
    and inflates pods_traced."""
    backend = make_backend(n_nodes=1)
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    a = _scheduler(backend, identity="rep-a")
    assert a._resolve_trace_corr("triad-0", "default", "c-origin") == "c-origin"

    b = _scheduler(backend, identity="rep-b")
    rec = b._rec()
    # the watch-receipt leg, recorded under B's locally minted corr
    rec.record("watch_event", 0.0, 0.0, cat="event", corr="c-local",
               attrs={"pod": "default/triad-0"})
    b.attempt_scheduling_batch(
        [("triad-0", "default", "uid-0")],
        meta={("default", "triad-0"): ("c-local", 0.0)},
    )
    spans = rec.spans()
    assert all(s.corr != "c-local" for s in spans)
    watch = [s for s in spans if s.name == "watch_event"]
    assert watch and watch[0].corr == "c-origin"


def test_resolve_trace_corr_is_best_effort_on_missing_pod():
    backend = make_backend(n_nodes=1)
    a = _scheduler(backend, identity="rep-a")
    # no pod: the local corr survives, nothing raises
    assert a._resolve_trace_corr("ghost", "default", "c-x") == "c-x"


# ---------------------------------------------------------------------------
# the federation acceptance pin
# ---------------------------------------------------------------------------

def test_federation_spill_journey_merges_across_replicas():
    """ChaosSim(federation=3): find a pod whose spillover crossed >= 2
    shards AND >= 2 replicas, and assert its merged journey carries one
    corr ID with attributable spans from both. Seeds are searched
    deterministically so a scheduler change shifting one seed's churn
    doesn't flake the pin."""
    chosen = None
    for seed in range(3, 11):
        sim = ChaosSim(seed=seed, n_nodes=6, federation=3, n_replicas=3)
        sim.run(40)
        sim.quiesce()
        assert sim.stats.violations == []
        merged = sim.merged_trace()
        journeys = pod_journeys(merged)
        for corr, events in journeys.items():
            replicas = journey_replicas(merged, corr, journeys)
            shards = {
                ev["args"].get("shard")
                for ev in events
                if (ev.get("args") or {}).get("shard") is not None
            }
            if len(replicas) >= 2 and len(shards) >= 2:
                chosen = (sim, merged, corr, replicas, shards)
                break
        if chosen:
            break
    assert chosen is not None, "no cross-replica spill journey in 8 seeds"
    sim, merged, corr, replicas, shards = chosen
    assert validate_chrome_trace(merged) == []
    # ONE corr ID spans the whole journey: every span of the journey
    # carries it by construction of pod_journeys; the journey includes
    # both a spill leg and legs from another replica
    names = {ev["name"] for ev in pod_journeys(merged)[corr]}
    assert "spill" in names
    # the fleet artifact carries the spillover-hop and SLO burn summaries
    art = sim.fleet_artifact()
    assert validate_fleet_artifact(art) == []
    payload = art["payload"]
    assert payload["spillover"]["spill_events_total"] > 0
    assert payload["spillover"]["cross_replica_journeys"] >= 1
    assert "worst_burn_rates" in payload["slo"]


def test_fleet_artifact_captured_around_violation(tmp_path, monkeypatch):
    monkeypatch.setenv("NHD_FLEET_DIR", str(tmp_path))
    sim = ChaosSim(seed=0, n_nodes=4, federation=2, n_replicas=2)
    sim.run(5)
    sim.stats.violations.append("synthetic violation (capture test)")
    sim._maybe_capture_violation()
    path = sim.violation_artifact_path
    assert path is not None and os.path.exists(path)
    art = json.loads(Path(path).read_text())
    assert validate_fleet_artifact(art) == []
    assert art["payload"]["violations"] == [
        "synthetic violation (capture test)"
    ]
    # one-shot: a second violation doesn't clobber the first capture
    sim.stats.violations.append("second")
    sim._maybe_capture_violation()
    assert sim.violation_artifact_path == path


def test_fleet_views_degrade_outside_federation():
    """ha-mode _Replicas carry no recorder/SLO plane and their
    LeaderElector has no shard table — the fleet capture surface must
    degrade to identity + empty shards, not crash, so wiring fleet
    artifacts into the ha-chaos path stays a one-liner."""
    sim = ChaosSim(seed=0, n_nodes=4, ha=True)
    sim.run(3)
    views = sim.fleet_views()
    assert [v["replica"] for v in views] == ["sched-a", "sched-b"]
    assert all(v["shards"] == {} and v["trace"] is None for v in views)
    art = sim.fleet_artifact()
    assert art["payload"]["journeys"]["pods_traced"] == 0


def test_fleet_artifact_folds_private_elector_counters():
    """Federation replicas count handoffs/renewal failures into their
    own per-replica ApiCounters (so N replicas in one process don't
    fight over the leader gauges) — the fleet artifact must fold those
    monotonic totals in, including totals banked from incarnations
    killed mid-storm, or it reports 0 handoffs through a storm full of
    them."""
    sim = ChaosSim(seed=0, n_nodes=4, federation=2, n_replicas=2)
    sim.run(4)
    sim.replicas[0].counters.inc("shard_handoffs_total")
    sim.replicas[1].counters.inc("ha_renewal_failures_total")
    fencing = sim.fleet_artifact()["payload"]["fencing"]
    assert fencing["handoffs_total"] >= 1
    assert fencing["renewal_failures_total"] >= 1
    # a killed incarnation's totals survive its registry
    sim._replace_replica(0)
    fencing2 = sim.fleet_artifact()["payload"]["fencing"]
    assert fencing2["handoffs_total"] >= fencing["handoffs_total"]


# ---------------------------------------------------------------------------
# SLO clock vs replica churn
# ---------------------------------------------------------------------------

def test_slo_clock_survives_replica_restart():
    """A pod created at t=0 binds at t=50 through a FRESH scheduler
    incarnation (its local enqueue clock knows nothing before t=50):
    time-to-bind must still read ~50 s, because the origin stamp is the
    cluster's creationTimestamp, not any process-local stamp."""
    clock = {"t": 0.0}
    backend = make_backend(n_nodes=1)
    backend.clock = lambda: clock["t"]
    backend.create_pod("triad-0", cfg_text=pod_cfg())

    clock["t"] = 50.0  # the old incarnation died; a new one comes up
    slo = SloTracker(clock=lambda: clock["t"])
    sched = _scheduler(backend, identity="reborn", slo=slo)
    sched.check_pending_pods()
    assert backend.pods[("default", "triad-0")].node is not None
    snap = slo.snapshot()
    assert snap["observations_total"] == 1
    assert snap["max_seconds"] == pytest.approx(50.0)


def test_slo_clock_survives_kill_restart_wave():
    """Federation churn with kill/restart waves: every SLO observation
    across every incarnation obeys the physical clock-domain invariant
    (chaos' _check_slo_plane), and the trackers saw the binds the bind
    log recorded (retired incarnations included)."""
    sim = ChaosSim(seed=5, n_nodes=6, federation=3, n_replicas=3)
    sim.run(40)
    sim.quiesce()
    assert sim.stats.violations == []
    assert sim.stats.restarts > 0, "seed produced no restarts; repin"
    total_obs = sum(
        v["slo"]["observations_total"]
        for v in sim.fleet_views() if v.get("slo")
    )
    # every observation is a landed bind; faults can only lose (skip)
    # observations, never invent them
    assert 0 < total_obs <= len(sim.base.bind_log)


def test_slo_burn_stamps_in_tracker_clock_domain():
    """The bind duration is computed in the BACKEND's clock domain, but
    the burn-window stamp must come from the tracker's own clock —
    mixing domains (monotonic fake backend vs wall-clock tracker) left
    every burn-rate gauge at 0 forever on fake-backed runs."""
    backend_clock = {"t": 0.0}
    wall = {"t": 1.7e9}  # tracker domain, ~epoch seconds apart
    backend = make_backend(n_nodes=1)
    backend.clock = lambda: backend_clock["t"]
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    backend_clock["t"] = 50.0
    slo = SloTracker(target_sec=30.0, clock=lambda: wall["t"])
    sched = _scheduler(backend, identity="rep", slo=slo)
    sched.check_pending_pods()
    snap = slo.snapshot()
    assert snap["observations_total"] == 1
    assert snap["max_seconds"] == pytest.approx(50.0)
    # the 50 s bind breached the 30 s target: it must burn the window
    # rendered NOW, in the tracker's domain
    assert snap["burn_rates"]["5m"] > 0.0


def test_slo_burn_limit_profile_invariant():
    """A profile carrying slo_burn_limit turns budget burn into a chaos
    violation at quiesce."""
    profile = FaultProfile(name="strict-slo", slo_burn_limit=0.0)
    sim = ChaosSim(
        seed=0, n_nodes=4, federation=2, n_replicas=2, api_faults=profile,
    )
    sim.run(6)
    # inject one breach (31 s > the 30 s target, < sim elapsed so the
    # clock-domain invariant stays quiet)
    sim.replicas[0].slo.observe(31.0, now=sim._now)
    sim.quiesce()
    assert any("SLO burn rate" in v for v in sim.stats.violations)


def test_faulty_backend_delegates_slo_clock():
    """get_pod_created/clock_now are CONCRETE defaults on the
    ClusterBackend ABC, so FaultyBackend's __getattr__ never fires for
    them — without explicit delegation every faulted chaos cell reads
    the stubs (None / wall time) and the SLO plane is silently dead."""
    clock = {"t": 7.0}
    backend = make_backend(n_nodes=1)
    backend.clock = lambda: clock["t"]
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    wrapped = FaultyBackend(backend, FaultProfile(name="quiet"))
    assert wrapped.clock_now() == pytest.approx(7.0)
    assert wrapped.get_pod_created("triad-0", "default") == pytest.approx(
        backend.get_pod_created("triad-0", "default")
    )


# ---------------------------------------------------------------------------
# demotion dump hook (k8s/lease.py on_demote)
# ---------------------------------------------------------------------------

def test_leader_elector_fires_on_demote():
    calls = []
    backend = FakeClusterBackend()
    el = LeaderElector(
        backend, identity="a", ttl=10.0, on_demote=calls.append,
    )
    assert el.tick()  # acquires
    el.step_down()
    assert calls == ["voluntary step-down"]
    el.step_down()  # idempotent: no second transition, no second dump
    assert len(calls) == 1


def test_sharded_elector_qualifies_demotions_with_the_shard():
    calls = []
    backend = FakeClusterBackend()
    el = ShardedElector(
        backend, identity="a", peers=["a"], n_shards=2, ttl=10.0,
        on_demote=calls.append,
    )
    el.tick()
    assert set(el.owned_shards()) == {0, 1}
    el.step_down()
    assert sorted(calls) == [
        "shard 0: voluntary step-down", "shard 1: voluntary step-down",
    ]


def test_demote_callback_failure_never_breaks_the_election():
    def boom(why):
        raise RuntimeError("dump failed")

    backend = FakeClusterBackend()
    el = LeaderElector(backend, identity="a", ttl=10.0, on_demote=boom)
    assert el.tick()
    el.step_down()  # must not raise
    assert not el.is_leader
    assert el.tick()  # and the elector still works afterwards


# ---------------------------------------------------------------------------
# bench artifacts + the regression gate
# ---------------------------------------------------------------------------

def _mk_bench(tmp_path, name, solve):
    from nhd_tpu.obs.perf import build_bench_artifact, config_record

    art = build_bench_artifact(
        {
            "cfg4": config_record(
                wall_seconds=1.0, placed=100, speedup=10.0, rounds=3,
                phases={"solve": solve, "select": 0.1},
            )
        },
        headline={"metric": "pods_per_sec", "value": 100.0,
                  "unit": "pods/s", "vs_baseline": 10.0},
        platform="cpu", rev="testrev", created=1.0,
    )
    path = tmp_path / name
    path.write_text(json.dumps(art))
    return str(path)


def _bench_diff(*args):
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "bench_diff.py"), *args],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )


def test_bench_diff_fails_on_injected_solve_regression(tmp_path):
    old = _mk_bench(tmp_path, "old.json", solve=0.50)
    new = _mk_bench(tmp_path, "new.json", solve=0.57)  # +14%
    proc = _bench_diff(old, new)
    assert proc.returncode == 1, proc.stdout
    assert "REGRESSION" in proc.stdout
    # within threshold passes
    ok = _mk_bench(tmp_path, "ok.json", solve=0.52)  # +4%
    assert _bench_diff(old, ok).returncode == 0
    # and the threshold is a knob (+8% = +40ms: past the 1% threshold
    # AND the 30ms absolute phase floor — a +20ms blip alone no longer
    # fires, r9's jitter floor)
    knob = _mk_bench(tmp_path, "knob.json", solve=0.54)
    assert _bench_diff(old, knob).returncode == 0
    assert _bench_diff(old, knob, "--threshold", "0.01").returncode == 1
    # sub-floor growth is never fatal, whatever the percentage says
    assert _bench_diff(old, ok, "--threshold", "0.01").returncode == 0


def test_bench_diff_reads_legacy_driver_records():
    proc = _bench_diff("BENCH_r01.json", "BENCH_r01.json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "cfg" in proc.stdout  # per-config rows recovered from the tail


def test_legacy_bench_artifacts_all_load():
    from nhd_tpu.obs.perf import load_bench_artifact

    for i in range(1, 6):
        art = load_bench_artifact(str(REPO / f"BENCH_r0{i}.json"))
        assert art["schema_version"] == 0
        assert art["payload"]["headline"]["unit"] == "pods/s"
        assert art["payload"]["configs"], f"BENCH_r0{i}: no configs parsed"


def test_bench_artifact_validator_names_defects(tmp_path):
    from nhd_tpu.obs.perf import (
        load_bench_artifact,
        validate_bench_artifact,
    )

    good = json.loads(Path(_mk_bench(tmp_path, "g.json", 0.5)).read_text())
    assert validate_bench_artifact(good) == []
    assert validate_bench_artifact(dict(good, schema_version=99))
    bad = dict(good, payload={"platform": "cpu"})
    assert validate_bench_artifact(bad)
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    with pytest.raises(ValueError):
        load_bench_artifact(str(p))
