"""Solver data-plane fault tolerance (solver/guard.py, ISSUE 12).

The detect → degrade → repair ladder under injected faults: transient
XLA-style dispatch errors are absorbed by bounded round re-dispatches
(binds bit-identical to a fault-free run), the rung ladder walks
mesh → single-device → host and re-promotes after clean probe rounds,
the resident-state audit finds and repairs bit-flipped device rows from
host truth, a repeatedly-faulting shape key is quarantined
(AOT-artifact retirement included), and — the negative control — with
the guard DISABLED the same corruption demonstrably persists. The fast
device-faults chaos cell pins the `make device-chaos` acceptance
invariants in tier-1.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from nhd_tpu.k8s.retry import API_COUNTERS
from nhd_tpu.sim.workloads import cap_cluster, workload_mix
from nhd_tpu.solver import guard
from nhd_tpu.solver.batch import BatchItem, BatchScheduler
from nhd_tpu.solver.encode import ClusterDelta
from nhd_tpu.solver.guard import (
    GUARD,
    RUNG_HOST,
    RUNG_MESH,
    RUNG_SINGLE,
    DeviceCorruptionError,
    InjectedDeviceFault,
    classify_device_fault,
)


@pytest.fixture(autouse=True)
def _clean_guard(monkeypatch):
    """Every test starts at full fidelity with no injector installed
    and the resident-state path forced on (the CPU backend leaves it
    off by default)."""
    monkeypatch.setenv("NHD_TPU_DEVICE_STATE", "1")
    GUARD.reset()
    guard.set_fault_injector(None)
    yield
    guard.set_fault_injector(None)
    GUARD.reset()


def _items(n=8, seed_groups=("default",)):
    return [
        BatchItem(("ns", f"p{i}"), r)
        for i, r in enumerate(workload_mix(n, list(seed_groups)))
    ]


def _sched(**kw):
    kw.setdefault("respect_busy", False)
    kw.setdefault("register_pods", False)
    kw.setdefault("device_state", True)
    return BatchScheduler(**kw)


def _placements(results):
    return [r.node for r in results]


class _NShotInjector:
    """Raise at the first *n* calls matching *site*, then go quiet."""

    def __init__(self, n, site="dispatch"):
        self.left = n
        self.site = site
        self.calls = 0

    def __call__(self, site, detail=""):
        self.calls += 1
        if site == self.site and self.left > 0:
            self.left -= 1
            raise InjectedDeviceFault(f"injected at {site} ({detail})")


# ---------------------------------------------------------------------------
# detect: classification + screens
# ---------------------------------------------------------------------------


def test_classification_mirrors_retry_semantics():
    # substrate health → transient (the 5xx analog)
    assert classify_device_fault(InjectedDeviceFault("x"))
    assert classify_device_fault(DeviceCorruptionError("x"))
    assert classify_device_fault(OSError("tunnel reset"))
    assert classify_device_fault(MemoryError())
    # facts about the program/call → terminal (the 4xx analog)
    assert not classify_device_fault(ValueError("bad arg"))
    assert not classify_device_fault(TypeError("bad call"))
    assert not classify_device_fault(KeyError("k"))
    # XLA runtime errors: transient unless they carry a terminal marker
    try:
        from jax._src.lib import xla_client

        assert classify_device_fault(
            xla_client.XlaRuntimeError("RESOURCE_EXHAUSTED: oom")
        )
        assert not classify_device_fault(
            xla_client.XlaRuntimeError("INVALID_ARGUMENT: shape")
        )
    except ImportError:
        pass  # classification degrades to the stdlib set there


def test_screen_rank_value_domain():
    ok = np.zeros((9, 2, 4), np.int32)
    assert GUARD.screen_rank(ok, 8) is None
    bad_val = ok.copy()
    bad_val[0, 0, 0] = -3
    assert "negative" in GUARD.screen_rank(bad_val, 8)
    bad_idx = ok.copy()
    bad_idx[1, 1, 1] = 8  # == n_padded: out of the padded axis
    assert "outside" in GUARD.screen_rank(bad_idx, 8)
    assert "shape" in GUARD.screen_rank(np.zeros((3, 2), np.int32), 8)
    nan = np.zeros((9, 2, 4), np.float32)
    nan[2, 0, 0] = np.nan
    assert "finite" in GUARD.screen_rank(nan, 8)


# ---------------------------------------------------------------------------
# degrade + repair: the ladder
# ---------------------------------------------------------------------------


def test_transient_dispatch_fault_retries_with_identical_binds():
    """A one-shot injected dispatch fault costs one re-dispatch, not a
    bind: placements are bit-identical to the fault-free run and the
    floor never moves (retry budget not exhausted)."""
    items = _items(9)
    clean, _ = _sched().schedule(cap_cluster(6, ["default"]), items)

    GUARD.reset()
    inj = _NShotInjector(1)
    guard.set_fault_injector(inj)
    base = API_COUNTERS.snapshot()
    faulted, _ = _sched().schedule(cap_cluster(6, ["default"]), items)
    now = API_COUNTERS.snapshot()
    assert inj.left == 0  # the fault actually fired
    assert _placements(faulted) == _placements(clean)
    assert now["guard_faults_total"] - base["guard_faults_total"] == 1
    assert now["guard_retries_total"] - base["guard_retries_total"] == 1
    assert now["guard_repairs_total"] - base["guard_repairs_total"] >= 1
    assert GUARD.floor == RUNG_MESH


def test_ladder_degrades_to_host_and_repromotes(monkeypatch):
    """NHD_GUARD_RETRIES=1: one fault exhausts the single-device rung's
    budget → floor drops to host, the round completes there, and clean
    probe rounds walk the floor back to full fidelity (one rung per
    probe window)."""
    monkeypatch.setenv("NHD_GUARD_RETRIES", "1")
    monkeypatch.setenv("NHD_GUARD_PROBE_ROUNDS", "2")
    items = _items(9)
    # mesh=None: start the ladder at the single-device rung (conftest's
    # 8 virtual devices would otherwise auto-resolve a mesh)
    clean, _ = _sched(mesh=None).schedule(
        cap_cluster(6, ["default"]), items
    )

    GUARD.reset()
    guard.set_fault_injector(_NShotInjector(1))
    base = API_COUNTERS.snapshot()
    faulted, _ = _sched(mesh=None).schedule(
        cap_cluster(6, ["default"]), items
    )
    now = API_COUNTERS.snapshot()
    assert _placements(faulted) == _placements(clean)
    assert GUARD.floor == RUNG_HOST
    assert (
        now["guard_degradations_total"] - base["guard_degradations_total"]
        == 1
    )
    assert API_COUNTERS.get("guard_rung") == RUNG_HOST

    # clean batches at the degraded floor: the host rung still binds,
    # and every clean round counts toward re-promotion
    guard.set_fault_injector(None)
    promoted = []
    for _ in range(8):
        nodes = cap_cluster(6, ["default"])
        res, _ = _sched().schedule(nodes, _items(6))
        promoted.append(GUARD.floor)
        if GUARD.floor == RUNG_MESH:
            break
    assert GUARD.floor == RUNG_MESH, promoted
    assert API_COUNTERS.get("guard_promotions_total") >= 2
    assert API_COUNTERS.get("guard_rung") == RUNG_MESH


def test_terminal_fault_surfaces_unchanged():
    """A terminal fault (program fact) must propagate — the guard never
    retries what repetition cannot fix."""

    def _terminal(site, detail=""):
        if site == "dispatch":
            raise ValueError("deterministic program bug")

    guard.set_fault_injector(_terminal)
    base = API_COUNTERS.get("guard_giveups_total")
    with pytest.raises(ValueError):
        _sched().schedule(cap_cluster(4, ["default"]), _items(4))
    assert API_COUNTERS.get("guard_giveups_total") == base + 1


def test_ladder_exhaustion_raises(monkeypatch):
    """A fault storm that outlives every rung's budget surfaces the
    last exception instead of retrying forever."""
    monkeypatch.setenv("NHD_GUARD_RETRIES", "1")
    guard.set_fault_injector(_NShotInjector(50))
    base = API_COUNTERS.get("guard_giveups_total")
    with pytest.raises(InjectedDeviceFault):
        _sched().schedule(cap_cluster(4, ["default"]), _items(4))
    assert API_COUNTERS.get("guard_giveups_total") == base + 1
    assert GUARD.floor == RUNG_HOST


def test_mesh_rung_degrades_to_single_device(monkeypatch):
    """The top of the ladder: a faulting mesh megaround condemns the
    mesh and the round re-dispatches on ONE device, bit-identically."""
    from tests.test_spmd import _mesh, _require_mesh

    _require_mesh()
    monkeypatch.setenv("NHD_GUARD_RETRIES", "1")
    items = _items(9)
    clean, _ = _sched(mesh=_mesh()).schedule(
        cap_cluster(8, ["default"]), items
    )

    GUARD.reset()
    guard.set_fault_injector(_NShotInjector(1))
    faulted, _ = _sched(mesh=_mesh()).schedule(
        cap_cluster(8, ["default"]), items
    )
    assert _placements(faulted) == _placements(clean)
    assert GUARD.floor == RUNG_SINGLE
    assert not GUARD.allow_mesh() and GUARD.allow_device()

    # a persistent context built now comes up at the degraded rung
    nodes = cap_cluster(8, ["default"])
    ctx = _sched(mesh=_mesh()).make_context(nodes, now=0.0)
    assert ctx.dev is not None and ctx.dev.mesh is None


# ---------------------------------------------------------------------------
# the resident-state audit + negative control
# ---------------------------------------------------------------------------


def _delta_ctx(n_nodes=6):
    nodes = cap_cluster(n_nodes, ["default"])
    sched = _sched()
    delta = ClusterDelta(nodes, now=0.0, respect_busy=False)
    ctx = sched.make_context(nodes, now=0.0, delta=delta)
    assert ctx.dev is not None
    return nodes, sched, delta, ctx


def _flip_row(dev, name="smt", row=1):
    cur = np.asarray(dev._dev[name][row])
    bad = ~cur if cur.dtype == np.bool_ else cur + np.ones_like(cur)
    dev._dev[name] = dev._dev[name].at[row].set(bad)


def test_audit_detects_and_repairs_bit_flip(monkeypatch):
    """A corrupted resident row is found by the batch-start audit and
    repaired from host truth BEFORE any solve reads it — binds stay
    bit-identical to a clean run."""
    monkeypatch.setenv("NHD_GUARD_AUDIT_INTERVAL", "1")
    monkeypatch.setenv("NHD_GUARD_AUDIT_ROWS", "0")
    items = _items(6)
    n0, s0, d0, c0 = _delta_ctx()
    clean, _ = s0.schedule(c0.nodes, items, context=c0)

    GUARD.reset()
    nodes, sched, delta, ctx = _delta_ctx()
    _flip_row(ctx.dev, "smt", 1)   # a static array no claim touches
    _flip_row(ctx.dev, "cpu_free", 3)
    assert guard.audit_device_rows(ctx.dev, range(ctx.dev.N)) != []
    base = API_COUNTERS.snapshot()
    faulted, stats = sched.schedule(ctx.nodes, items, context=ctx)
    now = API_COUNTERS.snapshot()
    assert _placements(faulted) == _placements(clean)
    assert now["guard_audits_total"] > base["guard_audits_total"]
    assert (
        now["guard_corruptions_total"] > base["guard_corruptions_total"]
    )
    assert now["guard_repairs_total"] > base["guard_repairs_total"]
    assert guard.audit_device_rows(ctx.dev, range(ctx.dev.N)) == []
    assert "guard_audit" in stats.phases


def test_negative_control_guard_disabled_corruption_persists(monkeypatch):
    """NHD_GUARD=0 (the chaos negative control): the same corruption is
    NOT audited or repaired — it persists across a whole batch, and the
    parity tripwire (audit_device_rows) demonstrably fires."""
    monkeypatch.setenv("NHD_GUARD", "0")
    monkeypatch.setenv("NHD_GUARD_AUDIT_INTERVAL", "1")
    monkeypatch.setenv("NHD_GUARD_AUDIT_ROWS", "0")
    nodes, sched, delta, ctx = _delta_ctx()
    _flip_row(ctx.dev, "smt", 1)
    base = API_COUNTERS.snapshot()
    sched.schedule(ctx.nodes, _items(6), context=ctx)
    now = API_COUNTERS.snapshot()
    assert now["guard_audits_total"] == base["guard_audits_total"]
    errs = guard.audit_device_rows(ctx.dev, range(ctx.dev.N))
    assert errs and "smt" in errs[0]


def test_audit_budget_rotates_to_full_coverage(monkeypatch):
    """A bounded audit budget still reaches every row over successive
    audits (rotating window, no RNG)."""
    monkeypatch.setenv("NHD_GUARD_AUDIT_ROWS", "2")
    nodes, sched, delta, ctx = _delta_ctx(6)
    _flip_row(ctx.dev, "hp_free", 5)  # the last row
    found = 0
    for _ in range(4):  # ceil(6/2) windows cover every row
        if GUARD.run_audit(ctx.dev):
            found += 1
            ctx.dev.rebuild_resident()
    assert found == 1
    assert guard.audit_device_rows(ctx.dev, range(ctx.dev.N)) == []


# ---------------------------------------------------------------------------
# shape quarantine
# ---------------------------------------------------------------------------


def test_poisoned_aot_program_quarantined_end_to_end(
    tmp_path, monkeypatch
):
    """A prewarmed program that faults on every call is quarantined
    after NHD_GUARD_SHAPE_FAULTS faults: its artifact moves to
    quarantine/, dispatches re-trace live, and the batch still binds."""
    from nhd_tpu.solver import aot
    from nhd_tpu.solver.kernel import ranked_shape_key

    monkeypatch.setenv("NHD_GUARD_SHAPE_FAULTS", "2")
    monkeypatch.setenv("NHD_GUARD_RETRIES", "2")
    items = _items(6)
    clean, _ = _sched().schedule(cap_cluster(6, ["default"]), items)

    # seed the disk cache with REAL artifacts for these shapes
    aot.reset()
    aot.configure(directory=str(tmp_path), save=True)
    try:
        GUARD.reset()
        _sched().schedule(cap_cluster(6, ["default"]), items)
        aot.AOT.drain()
        aot.reset()
        aot.configure(directory=str(tmp_path), save=False)
        summary = aot.prewarm()
        assert summary["loaded"] >= 1

        # poison ONE installed program: it raises like a miscompiled
        # kernel would
        key = sorted(aot.AOT._programs, key=lambda k: k.name())[0]
        key_str = ranked_shape_key(
            key.G, key.U, key.K, key.R, key.Tp, key.Np, key.mesh
        )

        def _poisoned(*a, **k):
            raise InjectedDeviceFault(f"poisoned program {key.name()}")

        aot.AOT._programs[key] = _poisoned
        GUARD.reset()
        faulted, _ = _sched().schedule(cap_cluster(6, ["default"]), items)
        assert _placements(faulted) == _placements(clean)
        assert GUARD.shape_quarantined(key_str)
        assert API_COUNTERS.get("guard_quarantined_shapes") == 1
        assert aot.lookup(key) is None
        qdir = os.path.join(str(tmp_path), "quarantine")
        assert os.path.exists(
            os.path.join(qdir, f"{key.name()}.stablehlo.bin")
        )
        # later batches dispatch the shape live, no further faults
        again, _ = _sched().schedule(cap_cluster(6, ["default"]), items)
        assert _placements(again) == _placements(clean)
    finally:
        aot.reset()


# ---------------------------------------------------------------------------
# observability surfaces
# ---------------------------------------------------------------------------


def test_guard_counters_on_metrics_and_fleet_payload():
    from nhd_tpu.obs.fleet import build_fleet_payload
    from nhd_tpu.rpc.metrics import render_metrics

    out = render_metrics([], 0)
    for name in (
        "nhd_guard_rung", "nhd_guard_faults_total",
        "nhd_guard_audits_total", "nhd_guard_repairs_total",
        "nhd_guard_quarantined_shapes", "nhd_aot_export_failures_total",
    ):
        assert name in out
    payload = build_fleet_payload(
        [], counters={"guard_rung": 1, "guard_faults_total": 3,
                      "guard_repairs_total": 2},
    )
    g = payload["device_state"]["guard"]
    assert g["rung"] == 1
    assert g["faults_total"] == 3
    assert g["repairs_total"] == 2


# ---------------------------------------------------------------------------
# the device-faults chaos cell (the `make device-chaos` acceptance pin)
# ---------------------------------------------------------------------------


def _device_chaos_env(monkeypatch):
    monkeypatch.setenv("NHD_TPU_DEVICE_STATE", "1")
    monkeypatch.setenv("NHD_GUARD_AUDIT_INTERVAL", "1")
    monkeypatch.setenv("NHD_GUARD_AUDIT_ROWS", "0")


def test_device_chaos_binds_bit_identical_to_fault_free(monkeypatch):
    """Injected mid-round dispatch failures AND bit-flipped resident
    rows both end in a bound set bit-identical to a fault-free run of
    the same seed — zero process restarts, every corruption repaired
    in-process (end-state audit bit-exact), zero guard giveups."""
    from nhd_tpu.sim.chaos import ChaosSim
    from nhd_tpu.sim.faults import PROFILES

    _device_chaos_env(monkeypatch)
    total_faults = 0
    for seed in (0, 1):
        GUARD.reset()
        control = ChaosSim(seed=seed, api_faults=None)
        control.run(steps=25)
        control.quiesce()

        GUARD.reset()
        base_giveups = API_COUNTERS.get("guard_giveups_total")
        sim = ChaosSim(seed=seed, api_faults=PROFILES["device-faults"])
        sim.run(steps=25)
        sim.quiesce()
        assert sim.stats.violations == []
        assert sim.stuck_pods() == []
        assert sim.bound_set() == control.bound_set(), seed
        assert sim.device_audit_errors() == []
        assert API_COUNTERS.get("guard_giveups_total") == base_giveups
        faults = sim.fault_totals()
        total_faults += (
            faults["device_dispatch_errors"]
            + faults["device_upload_errors"] + faults["device_bit_flips"]
        )
    assert total_faults > 0  # the storm was real, not vacuous


def test_device_chaos_negative_control_violates_parity(monkeypatch):
    """The corruption storm with the guard DISABLED: bit-flipped
    resident rows reach the end state — the device audit reports
    divergent rows (or the bound set itself diverges), proving the
    guard was the repairing agent in the positive cell. Flips-only
    profile: an unabsorbed dispatch exception would crash the sim's
    drive loop itself, which is the OTHER thing the guard prevents."""
    from nhd_tpu.sim.chaos import ChaosSim
    from nhd_tpu.sim.faults import FaultProfile

    _device_chaos_env(monkeypatch)
    monkeypatch.setenv("NHD_GUARD", "0")
    flips = FaultProfile(name="flips-only", device_bit_flip=0.5)
    GUARD.reset()
    control = ChaosSim(seed=0, api_faults=None)
    control.run(steps=25)
    control.quiesce()

    GUARD.reset()
    base_repairs = API_COUNTERS.get("guard_repairs_total")
    sim = ChaosSim(seed=0, api_faults=flips)
    audit_fired = 0
    for _ in range(25):
        flips_before = sim.stats.bit_flips
        sim.step()
        if sim.stats.bit_flips > flips_before and (
            sim.device_audit_errors()
        ):
            # the corruption SURVIVED the whole step's control-plane
            # drive: nothing repaired it (with the guard on, the
            # batch-start audit would have, before any solve)
            audit_fired += 1
    sim.quiesce()
    assert sim.stats.bit_flips > 0
    assert API_COUNTERS.get("guard_repairs_total") == base_repairs
    assert audit_fired > 0, (
        "guard-disabled corruption never survived a step — the "
        "negative control is vacuous"
    )


def test_device_profile_refuses_vacuous_posture(monkeypatch):
    """A device storm against no resident state would pass vacuously —
    the sim fails loud instead."""
    from nhd_tpu.sim.chaos import ChaosSim
    from nhd_tpu.sim.faults import PROFILES

    monkeypatch.delenv("NHD_TPU_DEVICE_STATE", raising=False)
    with pytest.raises(ValueError, match="resident-state"):
        ChaosSim(seed=0, api_faults=PROFILES["device-faults"])
    with pytest.raises(ValueError, match="solo"):
        ChaosSim(
            seed=0, api_faults=PROFILES["device-faults"], ha=True
        )


# ---------------------------------------------------------------------------
# chaos_storm per-cell timeout (ISSUE 12 satellite)
# ---------------------------------------------------------------------------


def test_chaos_storm_cell_timeout_reports_and_fails(tmp_path, monkeypatch):
    import importlib.util
    import json

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "chaos_storm_under_test", os.path.join(root, "tools", "chaos_storm.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    def _hang(args, profile, seed):
        time.sleep(5.0)
        return {"ok": True}

    monkeypatch.setattr(mod, "_run_cell", _hang)
    out = tmp_path / "matrix.json"
    rc = mod.main([
        "--seeds", "1", "--steps", "1", "--profiles", "light",
        "--cell-timeout", "0.3", "--json-out", str(out),
    ])
    assert rc == 1
    summary = json.loads(out.read_text())
    assert summary["cells_failed"] == 1
    cell = summary["cells"][0]
    assert cell["timeout"] is True
    assert cell["profile"] == "light" and cell["seed"] == 0
    assert "timed out" in cell["violations"][0]


def test_hard_down_device_condemns_build_to_host_rung(monkeypatch):
    """Review finding: on a fully dead device even REBUILDING resident
    state faults (the device_put itself raises). The guard must condemn
    the device plane straight to the host rung and keep binding — not
    crash the batch from inside its own recovery path."""
    from nhd_tpu.solver.device_state import DeviceClusterState

    items = _items(6)
    clean, _ = _sched(mesh=None).schedule(
        cap_cluster(6, ["default"]), items
    )

    GUARD.reset()
    orig = DeviceClusterState._put

    def _dead(self, padded):
        raise InjectedDeviceFault("device_put: tunnel down")

    monkeypatch.setattr(DeviceClusterState, "_put", _dead)
    base = API_COUNTERS.snapshot()
    faulted, _ = _sched(mesh=None).schedule(
        cap_cluster(6, ["default"]), items
    )
    now = API_COUNTERS.snapshot()
    assert _placements(faulted) == _placements(clean)
    assert GUARD.floor == RUNG_HOST
    assert now["guard_degradations_total"] > base["guard_degradations_total"]

    # the device heals: clean probe rounds re-promote as usual
    monkeypatch.setattr(DeviceClusterState, "_put", orig)
    monkeypatch.setenv("NHD_GUARD_PROBE_ROUNDS", "1")
    for _ in range(6):
        _sched(mesh=None).schedule(cap_cluster(6, ["default"]), _items(4))
        if GUARD.floor == RUNG_MESH:
            break
    assert GUARD.floor == RUNG_MESH
