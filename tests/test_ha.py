"""HA layer (k8s/lease.py + the fenced commit path): leader election with
an injected clock (the tests/test_retry.py pattern — zero real waiting),
fenced-commit stale-epoch rejection incl. an epoch bumped mid-commit,
the stall watchdog, standby→promotion replay equivalence, restart
state equivalence, and the split-brain chaos matrix (two schedulers, one
cluster, lease faults on).

Plus the sharded federation (ShardedElector + scheduler federation
routing): rendezvous determinism, bounded shard handoff, dead-member
rebalance, per-shard fencing, cross-shard spillover (claim/place and
explicit exhaustion), scoped promotion replay, the federation chaos
matrix, and the S=1 wire-equivalence regression pin."""

import queue

import pytest

from nhd_tpu.k8s.fake import FakeClusterBackend
from nhd_tpu.k8s.interface import (
    CFG_ANNOTATION,
    LEASE_NAME,
    StaleLeaseError,
)
from nhd_tpu.k8s.lease import LeaderElector, StallWatchdog
from nhd_tpu.k8s.retry import API_COUNTERS, ApiCounters
from nhd_tpu.rpc.metrics import render_metrics
from nhd_tpu.scheduler.core import PodStatus, Scheduler
from nhd_tpu.scheduler.events import WatchItem, WatchQueue, WatchType
from nhd_tpu.sim.chaos import ChaosSim
from nhd_tpu.sim.faults import PROFILES, FaultProfile, FaultyBackend
from nhd_tpu.sim.synth import SynthNodeSpec, make_node_labels, make_triad_config


class StepClock:
    """Injected clock shared by backend + electors (no real sleeps)."""

    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


def _cluster(n_nodes=2):
    clock = StepClock()
    backend = FakeClusterBackend()
    backend.clock = clock
    for i in range(n_nodes):
        spec = SynthNodeSpec(name=f"node{i}")
        backend.add_node(
            spec.name, make_node_labels(spec), hugepages_gb=spec.hugepages_gb
        )
    return backend, clock


def _elector(backend, clock, ident, ttl=30.0):
    return LeaderElector(
        backend, identity=ident, ttl=ttl, clock=clock, counters=ApiCounters()
    )


def _scheduler(backend, elector=None):
    sched = Scheduler(
        backend, WatchQueue(), queue.Queue(), respect_busy=False,
        elector=elector,
    )
    sched.build_initial_node_list()
    sched.load_deployed_configs()
    return sched


# ---------------------------------------------------------------------------
# election (acquire / renew / step-down / expiry, injected clock)
# ---------------------------------------------------------------------------


def test_first_tick_acquires_with_epoch_one():
    backend, clock = _cluster(0)
    a = _elector(backend, clock, "a")
    assert a.tick() is True
    assert a.is_leader and a.epoch == 1
    assert a.fencing_epoch() == 1
    view = backend.lease_read(LEASE_NAME)
    assert view.holder == "a" and view.epoch == 1


def test_follower_stays_follower_while_lease_live():
    backend, clock = _cluster(0)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    assert b.tick() is False
    assert b.fencing_epoch() is None


def test_renew_extends_and_keeps_epoch():
    backend, clock = _cluster(0)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    for _ in range(5):
        clock.advance(20)        # ttl is 30: renewals must keep it alive
        assert a.tick() is True
        assert b.tick() is False
    assert a.epoch == 1          # renewals never bump the fencing token


def test_expired_lease_hands_over_with_higher_epoch():
    backend, clock = _cluster(0)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    clock.advance(31)            # a never renews: expiry
    assert b.tick() is True
    assert b.epoch == 2          # acquisition bumped the token
    assert a.tick() is False     # a's renew CAS fails: demoted


def test_step_down_hands_over_without_waiting_out_ttl():
    backend, clock = _cluster(0)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    a.step_down()
    assert not a.is_leader
    assert b.tick() is True      # no clock advance needed
    assert b.epoch == 2


def test_renew_error_tolerated_within_grace_then_demotes():
    backend, clock = _cluster(0)
    faulty = FaultyBackend(
        backend, FaultProfile(name="t", lease_renew_error=1.0)
    )
    a = LeaderElector(
        faulty, identity="a", ttl=30.0, clock=clock, counters=ApiCounters()
    )
    a.tick()
    clock.advance(10)
    assert a.tick() is True      # renew errored, but grace holds
    clock.advance(25)            # 35s since the last SUCCESSFUL renewal
    assert a.tick() is False     # grace spent: voluntary demotion
    # and leadership is reacquirable once the fault clears
    faulty.enabled = False
    clock.advance(1)
    assert a.tick() is True and a.epoch == 2


def test_renew_conflict_demotes_immediately():
    backend, clock = _cluster(0)
    faulty = FaultyBackend(
        backend, FaultProfile(name="t", lease_renew_conflict=1.0)
    )
    a = LeaderElector(
        faulty, identity="a", ttl=30.0, clock=clock, counters=ApiCounters()
    )
    a.tick()
    assert a.tick() is False     # CAS lost: no grace applies


def test_reacquire_after_restart_gets_fresh_epoch():
    """A replica that crashed while leading and came back under the same
    identity must NOT resume the old epoch: its pre-crash in-flight
    writes have to be fenceable against its own new leadership."""
    backend, clock = _cluster(0)
    a = _elector(backend, clock, "a")
    a.tick()
    a2 = _elector(backend, clock, "a")     # the restarted incarnation
    assert a2.tick() is True
    assert a2.epoch == 2


# ---------------------------------------------------------------------------
# fencing at the backend seam
# ---------------------------------------------------------------------------


def test_stale_epoch_write_rejected_atomically():
    backend, clock = _cluster(1)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    backend.create_pod("p1", cfg_text=make_triad_config())
    clock.advance(31)
    b.tick()                     # epoch 2 now rules
    with pytest.raises(StaleLeaseError):
        backend.bind_pod_to_node("p1", "node0", "default", epoch=1)
    with pytest.raises(StaleLeaseError):
        backend.annotate_pod_config("default", "p1", "cfg", epoch=1)
    with pytest.raises(StaleLeaseError):
        backend.annotate_pod_gpu_map("default", "p1", {"nvidia0": 0}, epoch=1)
    with pytest.raises(StaleLeaseError):
        backend.add_nad_to_pod("p1", "default", "n@n", epoch=1)
    assert backend.pods[("default", "p1")].node is None
    assert backend.bind_log == []
    # the live epoch still lands
    assert backend.bind_pod_to_node("p1", "node0", "default", epoch=2)
    assert backend.bind_log[0][4] == 2


def test_deposed_leader_batch_rejected_mid_commit():
    """THE split-brain acceptance case: the epoch is bumped between a
    batch's annotate and its bind — the deposed leader's bind must be
    rejected by the backend and the pod must take the requeue path
    (unwound claim, no terminal failure), never land."""
    backend, clock = _cluster(2)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    sched = _scheduler(backend, elector=a)
    assert sched.poll_leadership() is True
    backend.create_pod("p1", cfg_text=make_triad_config())

    orig = backend.annotate_pod_config

    def bump_after_annotate(ns, pod, cfg, *, epoch=None):
        ok = orig(ns, pod, cfg, epoch=epoch)
        clock.advance(31)        # a's lease expires mid-commit...
        b.tick()                 # ...and b acquires epoch 2
        return ok

    backend.annotate_pod_config = bump_after_annotate
    before = API_COUNTERS.get("ha_stale_writes_rejected_total")
    sched.check_pending_pods()
    backend.annotate_pod_config = orig

    pod = backend.pods[("default", "p1")]
    assert pod.node is None                      # the bind never landed
    assert backend.bind_log == []                # provably rejected
    assert API_COUNTERS.get("ha_stale_writes_rejected_total") > before
    # requeue path, not terminal failure: state popped, claim unwound,
    # pod back on the queue for the NEW leader's tenure
    assert sched.pod_state.get(("default", "p1")) is None
    assert sched.failed_schedule_count == 0
    assert not sched.nqueue.empty()
    assert all(not n.pod_info for n in sched.nodes.values())


def test_locally_known_deposition_spends_no_api_calls():
    """A replica that already KNOWS it lost the lease fails the commit
    locally (fencing_epoch is None -> StaleLeaseError before any backend
    write)."""
    backend, clock = _cluster(2)
    a = _elector(backend, clock, "a")
    a.tick()
    sched = _scheduler(backend, elector=a)
    sched.poll_leadership()
    backend.create_pod("p1", cfg_text=make_triad_config())
    a.step_down()                # demoted, but _acting not yet synced
    sched.check_pending_pods()
    assert backend.pods[("default", "p1")].node is None
    assert backend.bind_log == []


# ---------------------------------------------------------------------------
# standby / promotion replay
# ---------------------------------------------------------------------------


def _claims(sched):
    return {
        (ns, pod): name
        for name, node in sched.nodes.items()
        for (pod, ns) in node.pod_info
    }


def test_standby_watches_but_does_not_act_until_elected():
    backend, clock = _cluster(2)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    leader = _scheduler(backend, elector=a)
    assert leader.poll_leadership() is True
    standby = _scheduler(backend, elector=b)
    assert standby.poll_leadership() is False

    # leader binds the workload
    backend.create_pod("p1", cfg_text=make_triad_config())
    backend.create_pod("p2", cfg_text=make_triad_config())
    leader.check_pending_pods()
    leader_claims = _claims(leader)
    assert len(leader_claims) == 2

    # a pod event reaching the STANDBY is not acted on
    backend.create_pod("p3", cfg_text=make_triad_config(), emit_watch=False)
    standby.nqueue.put(WatchItem(
        WatchType.TRIAD_POD_CREATE,
        pod={"ns": "default", "name": "p3", "uid": "u3", "cfg": "", "node": ""},
    ))
    standby.run_once()
    assert backend.pods[("default", "p3")].node is None

    # but a node event keeps the standby's mirror warm
    standby.nqueue.put(WatchItem(WatchType.NODE_CORDON, node="node0"))
    standby.run_once()
    assert standby.nodes["node0"].active is False
    backend.cordon_node("node0", False)
    standby.nqueue.put(WatchItem(WatchType.NODE_UNCORDON, node="node0"))
    standby.run_once()

    # watchdog-style demotion -> standby promotion: the promoted replica
    # replays annotations to the SAME claim state, then schedules what
    # the old leader left pending
    a.step_down()
    assert b.tick() is True
    assert standby.poll_leadership() is True
    promoted_claims = _claims(standby)
    assert {
        k: v for k, v in promoted_claims.items() if k != ("default", "p3")
    } == leader_claims
    assert backend.pods[("default", "p3")].node is not None  # scan caught it
    # resource accounting equivalence on the shared claims
    for name in leader.nodes:
        assert (
            standby.nodes[name].mem.free_hugepages_gb
            <= leader.nodes[name].mem.free_hugepages_gb
        )


def test_failed_promotion_replay_releases_the_lease():
    """Promotion keeps the crash-only contract: a replica whose replay
    fails (API outage mid-promotion) must NOT lead with an empty or
    partial mirror — it releases the lease so a healthy replica can win,
    instead of holding it with a live-but-stateless loop the watchdog
    would never catch."""
    from nhd_tpu.k8s.interface import TransientBackendError

    backend, clock = _cluster(2)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    sched = _scheduler(backend, elector=a)

    real_get_nodes = backend.get_nodes
    backend.get_nodes = lambda: (_ for _ in ()).throw(
        TransientBackendError("outage mid-promotion")
    )
    assert sched.poll_leadership() is False   # replay failed: stepped down
    assert a.is_leader is False
    assert sched._acting is False
    backend.get_nodes = real_get_nodes

    # the healthy standby wins and schedules; the failed replica can
    # also recover on a later, successful promotion
    assert b.tick() is True and b.epoch == 2
    backend.create_pod("p1", cfg_text=make_triad_config())
    other = _scheduler(backend, elector=b)
    assert other.poll_leadership() is True
    assert backend.pods[("default", "p1")].node is not None


def test_demoted_leader_stops_scanning():
    backend, clock = _cluster(2)
    a = _elector(backend, clock, "a")
    a.tick()
    sched = _scheduler(backend, elector=a)
    sched.poll_leadership()
    a.step_down()
    assert sched.poll_leadership() is False
    backend.create_pod("p1", cfg_text=make_triad_config())
    # idle path reaching the periodic-scan threshold must not scan
    from nhd_tpu.scheduler.core import IDLE_CNT_THRESH

    idle = sched.run_once(idle_count=IDLE_CNT_THRESH - 1)
    assert idle == 0
    assert backend.pods[("default", "p1")].node is None


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------


def test_watchdog_fires_on_wedged_loop_and_releases_lease():
    backend, clock = _cluster(0)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    exits = []
    beat = [0.0]
    wd = StallWatchdog(
        lambda: beat[0], stall_after=120.0, elector=a,
        exit_fn=exits.append, clock=clock, counters=ApiCounters(),
    )
    clock.advance(100)
    assert wd.check() is False        # within budget
    beat[0] = 100.0                   # a healthy heartbeat resets the age
    clock.advance(100)
    assert wd.check() is False
    clock.advance(121)                # loop wedged: no beat for 121s
    assert wd.check() is True
    assert exits == [2]               # crash-only exit requested
    assert not a.is_leader            # lease released...
    assert b.tick() is True           # ...so the standby takes over NOW
    assert b.epoch == 2
    assert wd.check() is True and exits == [2]   # fires once


def test_watchdog_quiet_on_healthy_loop():
    backend, clock = _cluster(0)
    exits = []
    wd = StallWatchdog(
        clock, stall_after=10.0, exit_fn=exits.append, clock=clock,
        counters=ApiCounters(),
    )
    for _ in range(5):
        clock.advance(5)
        assert wd.check() is False
    assert exits == []


# ---------------------------------------------------------------------------
# restart state equivalence (the ChaosSim.stats.restarts fix, pinned)
# ---------------------------------------------------------------------------


def test_restart_replay_reconstructs_equivalent_state():
    sim = ChaosSim(seed=3, n_nodes=3)
    sim.run(steps=30)
    sim._act_restart()               # force one regardless of the dice
    assert sim.stats.restarts >= 1
    assert sim.stats.violations == []


def test_restart_equivalence_detects_divergence():
    """The equivalence check must actually bite: corrupt one bound pod's
    solved-config annotation and the replayed state no longer matches
    the cluster."""
    sim = ChaosSim(seed=0, n_nodes=3)
    for _ in range(6):
        sim._act_create()
    sim._drive_control_plane()
    bound = [p for p in sim.backend.pods.values() if p.node]
    assert bound
    bound[0].annotations[CFG_ANNOTATION] = "garbage {"
    sim._act_restart()
    assert any("restart replay diverged" in v for v in sim.stats.violations)


# ---------------------------------------------------------------------------
# split-brain chaos: two schedulers, one cluster, lease faults on
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(3))
def test_split_brain_chaos_storm(seed):
    """The acceptance matrix cell: lease-renewal faults force leadership
    churn across two replicas; the run must end with zero double-epoch
    binds, zero invariant violations, zero stuck pods, and bounded
    leadership gaps."""
    sim = ChaosSim(
        seed=seed, n_nodes=4, ha=True, api_faults=PROFILES["ha-storm"]
    )
    stats = sim.run(steps=40)
    assert stats.violations == []
    # the storm actually churned leadership
    assert stats.lease_epoch >= 2
    fs = sim.backend.fault_stats
    assert fs["lease_renew_errors"] + fs["lease_renew_conflicts"] > 0
    # faults off -> the election and the cluster must both converge
    sim.quiesce()
    assert stats.violations == []
    assert sim.stuck_pods() == []
    assert any(r.elector.is_leader for r in sim.replicas)
    # every landed bind carries exactly one epoch per pod incarnation
    per_uid = {}
    for ns, pod, uid, node, epoch, lease in sim.backend.bind_log:
        per_uid.setdefault(uid, set()).add(epoch)
    assert all(len(eps) == 1 for eps in per_uid.values())


def test_split_brain_exercises_fencing():
    """At least one seed of the matrix must drive an actual stale-epoch
    rejection (a deposed leader tried to commit and was fenced off) —
    otherwise the invariant above is vacuous."""
    API_COUNTERS.reset()
    sim = ChaosSim(seed=0, n_nodes=4, ha=True, api_faults=PROFILES["ha-storm"])
    stats = sim.run(steps=40)
    assert stats.violations == []
    assert API_COUNTERS.get("ha_stale_writes_rejected_total") > 0


def test_ha_light_profile_bounded_gaps():
    sim = ChaosSim(seed=1, n_nodes=4, ha=True, api_faults=PROFILES["ha-light"])
    stats = sim.run(steps=40)
    sim.quiesce()
    assert stats.violations == []
    assert sim.stuck_pods() == []
    assert stats.max_leader_gap <= int(sim.lease_ttl / 10.0) + 8


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


def test_ha_metrics_exported():
    out = render_metrics([], failed_count=0)
    for name, kind in (
        ("nhd_ha_is_leader", "gauge"),
        ("nhd_ha_epoch", "gauge"),
        ("nhd_ha_transitions_total", "counter"),
        ("nhd_ha_renewals_total", "counter"),
        ("nhd_ha_stale_writes_rejected_total", "counter"),
        ("nhd_ha_watchdog_stalls_total", "counter"),
        ("nhd_ha_watchdog_loop_age_seconds", "gauge"),
    ):
        assert f"# TYPE {name} {kind}" in out


def test_commit_path_unfenced_without_elector():
    """Single-replica mode is byte-identical to pre-HA behavior: no
    elector, no epoch on the wire, pods bind."""
    backend, _ = _cluster(2)
    sched = _scheduler(backend)
    assert sched.poll_leadership() is True
    backend.create_pod("p1", cfg_text=make_triad_config())
    sched.check_pending_pods()
    assert backend.pods[("default", "p1")].node is not None
    assert backend.bind_log[0][4] is None     # unfenced write
    assert sched.pod_state[("default", "p1")]["state"] is PodStatus.SCHEDULED


# ---------------------------------------------------------------------------
# shard federation (k8s/lease.py ShardedElector + scheduler federation
# routing + fed chaos matrix; docs/RESILIENCE.md "Federation")
# ---------------------------------------------------------------------------

from nhd_tpu.k8s.interface import (  # noqa: E402
    SPILLOVER_ANNOTATION,
    parse_spill_record,
)
from nhd_tpu.k8s.lease import (  # noqa: E402
    ShardedElector,
    presence_lease_name,
    rendezvous_owner,
    shard_for_group,
    shard_lease_name,
)

FED_IDS = ["fed-a", "fed-b", "fed-c"]


def _sharded(backend, clock, ident, peers=None, n_shards=3, ttl=30.0):
    return ShardedElector(
        backend, identity=ident, peers=peers or FED_IDS, n_shards=n_shards,
        ttl=ttl, clock=clock, counters=ApiCounters(),
    )


def _fed_scheduler(backend, sharded, clock):
    sched = Scheduler(
        backend, WatchQueue(), queue.Queue(), respect_busy=False,
        sharded=sharded, clock=clock,
    )
    sched.build_initial_node_list()
    sched.load_deployed_configs()
    return sched


def _converge(els, clock, rounds=8, advance=2.0):
    for _ in range(rounds):
        for el in els:
            el.tick()
        clock.advance(advance)


def _group_for_shard(shard, n_shards, prefix="a"):
    """A deterministic group name homing to ``shard`` that sorts before
    'default' (so a node carrying {g, default} re-homes to g's shard)."""
    for i in range(512):
        g = f"{prefix}{i}"
        if shard_for_group(g, n_shards) == shard:
            return g
    raise AssertionError("no group found")  # pragma: no cover


def test_rendezvous_deterministic_and_minimal_reshuffle():
    owners = {s: rendezvous_owner(s, FED_IDS) for s in range(8)}
    # membership order never matters (hashlib, not hash())
    assert owners == {
        s: rendezvous_owner(s, list(reversed(FED_IDS))) for s in range(8)
    }
    # removing one member reassigns ONLY its shards
    survivors = [i for i in FED_IDS if i != "fed-b"]
    for s in range(8):
        if owners[s] != "fed-b":
            assert rendezvous_owner(s, survivors) == owners[s]
    # group → shard covers every shard id over a realistic name pool
    assert {shard_for_group(f"g{i}", 3) for i in range(64)} == {0, 1, 2}
    assert shard_lease_name(0, 1) == LEASE_NAME    # S=1 degenerates


def test_federation_converges_each_shard_one_owner():
    backend, clock = _cluster(0)
    els = {i: _sharded(backend, clock, i) for i in FED_IDS}
    _converge(els.values(), clock)
    owned = {i: set(el.owned_shards()) for i, el in els.items()}
    assert sorted(s for ss in owned.values() for s in ss) == [0, 1, 2]
    # ...and exactly the deterministic rendezvous assignment
    for ident, ss in owned.items():
        for s in ss:
            assert rendezvous_owner(s, FED_IDS) == ident


def test_shard_handoff_bounded_one_per_tick():
    backend, clock = _cluster(0)
    a = _sharded(backend, clock, "fed-a")
    _converge([a], clock, rounds=2)
    assert set(a.owned_shards()) == {0, 1, 2}   # alone: owns the fleet
    b = _sharded(backend, clock, "fed-b")
    c = _sharded(backend, clock, "fed-c")
    b.tick()
    c.tick()                                    # presence beacons land
    handed_total = 0
    for _ in range(6):
        before = set(a.owned_shards())
        a.tick()
        handed = before - set(a.owned_shards())
        assert len(handed) <= 1                 # bounded handoff
        handed_total += len(handed)
        b.tick()
        c.tick()
        clock.advance(2)
    # converged to the rendezvous assignment, one release at a time
    assert handed_total == sum(
        1 for s in range(3) if rendezvous_owner(s, FED_IDS) != "fed-a"
    )
    for s in range(3):
        view = backend.lease_read(shard_lease_name(s, 3))
        assert view.holder == rendezvous_owner(s, FED_IDS)


def test_dead_member_shards_rebalance_within_ttl_plus_patience():
    backend, clock = _cluster(0)
    els = {i: _sharded(backend, clock, i) for i in FED_IDS}
    _converge(els.values(), clock)
    dead = next(i for i in FED_IDS if els[i].owned_shards())
    lost = set(els[dead].owned_shards())
    survivors = [els[i] for i in FED_IDS if i != dead]
    clock.advance(31)                           # dead's leases all expire
    _converge(survivors, clock, rounds=4)
    held = set()
    for el in survivors:
        held |= set(el.owned_shards())
    assert lost <= held                         # every orphan re-homed
    assert held == {0, 1, 2}


def test_clean_step_down_rebalances_without_waiting_out_ttl():
    backend, clock = _cluster(0)
    els = {i: _sharded(backend, clock, i) for i in FED_IDS}
    _converge(els.values(), clock)
    leaver = next(i for i in FED_IDS if els[i].owned_shards())
    els[leaver].step_down()
    assert els[leaver].owned_shards() == {}
    # presence beacon released too: peers see the member gone NOW
    assert backend.lease_live(presence_lease_name(leaver)) == ""
    survivors = [els[i] for i in FED_IDS if i != leaver]
    _converge(survivors, clock, rounds=3, advance=2.0)  # << ttl
    held = set()
    for el in survivors:
        held |= set(el.owned_shards())
    assert held == {0, 1, 2}


def test_fencing_is_per_shard():
    """A stale epoch on ONE shard fences exactly that shard's writes;
    sibling shards' tokens stay valid."""
    backend, clock = _cluster(1)
    backend.create_pod("p1", cfg_text=make_triad_config())
    a = _sharded(backend, clock, "fed-a", peers=["fed-a"], n_shards=2)
    a.tick()
    assert set(a.owned_shards()) == {0, 1}
    # a rival takes over shard 0 only (epoch 2 there)
    backend.lease_release(shard_lease_name(0, 2), "fed-a", 1)
    backend.lease_try_acquire(shard_lease_name(0, 2), "rival", 30.0)
    with pytest.raises(StaleLeaseError):
        backend.bind_pod_to_node(
            "p1", "node0", "default",
            epoch=1, fence_lease=shard_lease_name(0, 2),
        )
    assert backend.bind_log == []
    # the untouched shard's token still lands writes
    assert backend.bind_pod_to_node(
        "p1", "node0", "default",
        epoch=1, fence_lease=shard_lease_name(1, 2),
    )
    assert backend.bind_log[0][5] == shard_lease_name(1, 2)


def _fed_cluster(node_groups, clock=None):
    """Fake cluster whose nodes carry the given NHD_GROUP strings."""
    clock = clock or StepClock()
    backend = FakeClusterBackend()
    backend.clock = clock
    for i, groups in enumerate(node_groups):
        spec = SynthNodeSpec(name=f"n{i}")
        spec.groups = groups
        backend.add_node(
            spec.name, make_node_labels(spec), hugepages_gb=spec.hugepages_gb
        )
    return backend, clock


def test_spillover_cross_shard_claim_and_place():
    """The headline spillover path: the home shard has no candidate, the
    pod spills, ANOTHER shard's owner claims it and binds under ITS
    shard epoch — instead of the pod pending forever."""
    n_shards = 3
    home = shard_for_group("default", n_shards)
    els_probe = {s: rendezvous_owner(s, FED_IDS) for s in range(n_shards)}
    other = next(
        s for s in range(n_shards)
        if s != home and els_probe[s] != els_probe[home]
    )
    g = _group_for_shard(other, n_shards)
    assert g < "default"     # so {g, default} homes to g's shard
    # n0: home shard, will be cordoned; n1: carries 'default' too but
    # homes to `other` — the cross-shard candidate
    backend, clock = _fed_cluster(["default", f"{g}.default"])
    els = {i: _sharded(backend, clock, i) for i in FED_IDS}
    scheds = {i: _fed_scheduler(backend, els[i], clock) for i in FED_IDS}
    _converge(els.values(), clock)
    owner_of = {s: i for i in FED_IDS for s in els[i].owned_shards()}
    assert owner_of[home] != owner_of[other]
    backend.cordon_node("n0", True)
    for i in FED_IDS:
        scheds[i].poll_leadership()
    backend.create_pod("p1", cfg_text=make_triad_config())
    scheds[owner_of[home]].check_pending_pods()
    pod = backend.pods[("default", "p1")]
    rec = parse_spill_record(pod.annotations.get(SPILLOVER_ANNOTATION))
    assert pod.node is None and home in rec["tried"]
    assert rec["since"] is not None
    # the receiving shard's owner claims the spill and places it
    scheds[owner_of[other]].check_pending_pods()
    assert pod.node == "n1"
    assert backend.bind_log[-1][5] == shard_lease_name(other, n_shards)


def test_spillover_exhausts_with_explicit_verdict():
    """A pod NO shard can place gets its explicit unschedulable verdict
    once every shard has tried (never silently pending forever), and the
    record resets for a fresh cycle."""
    backend, clock = _fed_cluster(["default"])
    els = {i: _sharded(backend, clock, i) for i in FED_IDS}
    scheds = {i: _fed_scheduler(backend, els[i], clock) for i in FED_IDS}
    _converge(els.values(), clock)
    for i in FED_IDS:
        scheds[i].poll_leadership()
    # requests a group no node carries: unplaceable fleet-wide
    backend.create_pod("p1", cfg_text=make_triad_config(), groups="zz")

    def verdicts():
        return [
            e for e in backend.events
            if e.pod == "p1" and e.reason == "FailedScheduling"
            and "in any shard" in e.message
        ]

    for _ in range(4):
        for i in FED_IDS:
            scheds[i].check_pending_pods()
            if verdicts():
                break
        if verdicts():
            break
        clock.advance(1)
    pod = backend.pods[("default", "p1")]
    assert pod.node is None
    assert verdicts(), "no explicit shards-exhausted verdict"
    # the record was reset with the verdict: the NEXT cycle starts fresh
    assert parse_spill_record(
        pod.annotations.get(SPILLOVER_ANNOTATION)
    )["tried"] == set()


def test_scoped_promotion_replay_on_shard_gain():
    """A replica gaining shards replays THOSE shards' slice from the
    cluster before acting — and its claims agree with the cluster's
    bound set for the gained slice."""
    backend, clock = _fed_cluster(["default", "default", "edge"])
    peers = ["fed-a", "fed-b"]
    a = _sharded(backend, clock, "fed-a", peers=peers)
    sched_a = _fed_scheduler(backend, a, clock)
    _converge([a], clock, rounds=2)
    assert set(a.owned_shards()) == {0, 1, 2}
    assert sched_a.poll_leadership() is True
    backend.create_pod("p1", cfg_text=make_triad_config())
    backend.create_pod("p2", cfg_text=make_triad_config())
    sched_a.check_pending_pods()
    bound = {
        (p.namespace, p.name): p.node
        for p in backend.pods.values() if p.node
    }
    assert len(bound) == 2
    # fed-b joins; a hands every shard over (b is rendezvous-preferred
    # for all of them in this pair), one per tick
    b = _sharded(backend, clock, "fed-b", peers=peers)
    sched_b = _fed_scheduler(backend, b, clock)
    _converge([a, b], clock, rounds=6)
    assert set(b.owned_shards()) == {0, 1, 2}
    assert sched_b.poll_leadership() is True
    assert _claims(sched_b) == bound     # scoped replays == cluster truth
    # the old owner's in-flight writes are fenced off now
    assert sched_a.poll_leadership() is False
    with pytest.raises(StaleLeaseError):
        sched_a._commit_write(
            backend.bind_pod_to_node, "px", "n0", "default", node="n0"
        )


def test_failed_scoped_replay_releases_gained_shards():
    """The crash-only contract holds per shard: a gained shard whose
    scoped replay fails is handed back, never led stateless."""
    from nhd_tpu.k8s.interface import TransientBackendError

    backend, clock = _fed_cluster(["default", "edge"])
    a = _sharded(backend, clock, "fed-a", peers=["fed-a"])
    sched = _fed_scheduler(backend, a, clock)
    a.tick()
    real_get_nodes = backend.get_nodes
    backend.get_nodes = lambda: (_ for _ in ()).throw(
        TransientBackendError("outage mid-replay")
    )
    assert sched.poll_leadership() is False
    assert a.owned_shards() == {}        # gained shards released
    backend.get_nodes = real_get_nodes
    a.tick()                             # re-acquire on a later tick...
    assert sched.poll_leadership() is True   # ...and replay succeeds
    assert set(a.owned_shards()) == {0, 1, 2}


# ---------------------------------------------------------------------------
# federation chaos matrix (the acceptance cells; `make fed-chaos` runs
# the full seeds × profiles sweep)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(3))
def test_federation_chaos_storm(seed):
    """S=3 shards × 3 replicas under per-shard lease faults, asymmetric
    partitions and kill/restart waves: no pod uid bound under two shard
    epochs, per-shard leadership gaps bounded, no spillover orphan past
    the window, and the cluster converges once the storm lifts."""
    sim = ChaosSim(
        seed=seed, n_nodes=6, federation=3, n_replicas=3,
        api_faults=PROFILES["fed-storm"],
    )
    stats = sim.run(steps=40)
    assert stats.violations == []
    # the storm actually churned shard leadership
    assert max(stats.shard_epochs.values()) >= 2
    totals = sim.fault_totals()
    assert totals["lease_renew_errors"] + totals["lease_renew_conflicts"] > 0
    sim.quiesce()
    assert stats.violations == []
    assert sim.stuck_pods() == []
    # every shard converges onto exactly one live owner
    for s in range(3):
        holders = [
            r.ident for r in sim.replicas
            if s in r.elector.owned_shards()
        ]
        assert len(holders) == 1
    # no pod uid bound under two shard epochs (the bind log records the
    # fencing lease of every landed bind)
    per_uid = {}
    for ns, pod, uid, node, epoch, lease in sim.base.bind_log:
        per_uid.setdefault(uid, set()).add((lease, epoch))
    assert all(len(v) == 1 for v in per_uid.values())


def test_federation_light_profile_spillover_and_gaps():
    sim = ChaosSim(
        seed=0, n_nodes=6, federation=3, n_replicas=3,
        api_faults=PROFILES["fed-light"],
    )
    stats = sim.run(steps=40)
    sim.quiesce()
    assert stats.violations == []
    assert sim.stuck_pods() == []
    from nhd_tpu.k8s.lease import SHARD_PATIENCE_TICKS
    from nhd_tpu.sim.chaos import KILL_DOWN_MAX_STEPS, STEP_SEC
    bound = (
        int(sim.lease_ttl / STEP_SEC) + SHARD_PATIENCE_TICKS
        + PROFILES["fed-light"].partition_steps + KILL_DOWN_MAX_STEPS + 6
    )
    assert stats.max_shard_gap <= bound


def test_single_shard_federation_is_wire_equivalent_to_ha():
    """The S=1 regression pin: a one-shard federation competes for
    exactly the PR 5 single lease on the wire (plus presence beacons),
    fences every bind with it, and passes the same split-brain storm
    invariants as ``ha=True`` — federation strictly generalizes HA."""
    sim = ChaosSim(
        seed=0, n_nodes=4, federation=1, n_replicas=2,
        api_faults=PROFILES["ha-storm"],
    )
    stats = sim.run(steps=40)
    sim.quiesce()
    assert stats.violations == []
    assert sim.stuck_pods() == []
    presence = {
        presence_lease_name(r.ident) for r in sim.replicas
    }
    assert set(sim.base.leases) <= {LEASE_NAME} | presence
    for ns, pod, uid, node, epoch, lease in sim.base.bind_log:
        if epoch is not None:
            assert lease == LEASE_NAME    # byte-identical fence lease
    assert stats.lease_epoch >= 2         # the storm churned leadership


def test_shard_metrics_exported():
    from nhd_tpu.k8s.lease import publish_shard_status

    publish_shard_status("fed-a", 3, {0: 4, 2: 7})
    try:
        out = render_metrics([], failed_count=0)
        for name, kind in (
            ("nhd_shard_owned_count", "gauge"),
            ("nhd_shard_acquisitions_total", "counter"),
            ("nhd_shard_handoffs_total", "counter"),
            ("nhd_shard_spillover_claims_total", "counter"),
            ("nhd_shard_spillover_spilled_total", "counter"),
            ("nhd_shard_spillover_exhausted_total", "counter"),
            ("nhd_shard_spillover_depth", "gauge"),
            ("nhd_shard_spillover_oldest_age_seconds", "gauge"),
            ("nhd_shard_spillover_orphan_age_max_seconds", "gauge"),
            ("nhd_shard_epoch", "gauge"),
        ):
            assert f"# TYPE {name} {kind}" in out
        assert 'nhd_shard_epoch{shard="0"} 4' in out
        assert 'nhd_shard_epoch{shard="2"} 7' in out
        assert 'nhd_shard_epoch{shard="1"}' not in out   # not held
    finally:
        publish_shard_status("", 0, {})
