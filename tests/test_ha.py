"""HA layer (k8s/lease.py + the fenced commit path): leader election with
an injected clock (the tests/test_retry.py pattern — zero real waiting),
fenced-commit stale-epoch rejection incl. an epoch bumped mid-commit,
the stall watchdog, standby→promotion replay equivalence, restart
state equivalence, and the split-brain chaos matrix (two schedulers, one
cluster, lease faults on)."""

import queue

import pytest

from nhd_tpu.k8s.fake import FakeClusterBackend
from nhd_tpu.k8s.interface import (
    CFG_ANNOTATION,
    LEASE_NAME,
    StaleLeaseError,
)
from nhd_tpu.k8s.lease import LeaderElector, StallWatchdog
from nhd_tpu.k8s.retry import API_COUNTERS, ApiCounters
from nhd_tpu.rpc.metrics import render_metrics
from nhd_tpu.scheduler.core import PodStatus, Scheduler
from nhd_tpu.scheduler.events import WatchItem, WatchQueue, WatchType
from nhd_tpu.sim.chaos import ChaosSim
from nhd_tpu.sim.faults import PROFILES, FaultProfile, FaultyBackend
from nhd_tpu.sim.synth import SynthNodeSpec, make_node_labels, make_triad_config


class StepClock:
    """Injected clock shared by backend + electors (no real sleeps)."""

    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


def _cluster(n_nodes=2):
    clock = StepClock()
    backend = FakeClusterBackend()
    backend.clock = clock
    for i in range(n_nodes):
        spec = SynthNodeSpec(name=f"node{i}")
        backend.add_node(
            spec.name, make_node_labels(spec), hugepages_gb=spec.hugepages_gb
        )
    return backend, clock


def _elector(backend, clock, ident, ttl=30.0):
    return LeaderElector(
        backend, identity=ident, ttl=ttl, clock=clock, counters=ApiCounters()
    )


def _scheduler(backend, elector=None):
    sched = Scheduler(
        backend, WatchQueue(), queue.Queue(), respect_busy=False,
        elector=elector,
    )
    sched.build_initial_node_list()
    sched.load_deployed_configs()
    return sched


# ---------------------------------------------------------------------------
# election (acquire / renew / step-down / expiry, injected clock)
# ---------------------------------------------------------------------------


def test_first_tick_acquires_with_epoch_one():
    backend, clock = _cluster(0)
    a = _elector(backend, clock, "a")
    assert a.tick() is True
    assert a.is_leader and a.epoch == 1
    assert a.fencing_epoch() == 1
    view = backend.lease_read(LEASE_NAME)
    assert view.holder == "a" and view.epoch == 1


def test_follower_stays_follower_while_lease_live():
    backend, clock = _cluster(0)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    assert b.tick() is False
    assert b.fencing_epoch() is None


def test_renew_extends_and_keeps_epoch():
    backend, clock = _cluster(0)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    for _ in range(5):
        clock.advance(20)        # ttl is 30: renewals must keep it alive
        assert a.tick() is True
        assert b.tick() is False
    assert a.epoch == 1          # renewals never bump the fencing token


def test_expired_lease_hands_over_with_higher_epoch():
    backend, clock = _cluster(0)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    clock.advance(31)            # a never renews: expiry
    assert b.tick() is True
    assert b.epoch == 2          # acquisition bumped the token
    assert a.tick() is False     # a's renew CAS fails: demoted


def test_step_down_hands_over_without_waiting_out_ttl():
    backend, clock = _cluster(0)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    a.step_down()
    assert not a.is_leader
    assert b.tick() is True      # no clock advance needed
    assert b.epoch == 2


def test_renew_error_tolerated_within_grace_then_demotes():
    backend, clock = _cluster(0)
    faulty = FaultyBackend(
        backend, FaultProfile(name="t", lease_renew_error=1.0)
    )
    a = LeaderElector(
        faulty, identity="a", ttl=30.0, clock=clock, counters=ApiCounters()
    )
    a.tick()
    clock.advance(10)
    assert a.tick() is True      # renew errored, but grace holds
    clock.advance(25)            # 35s since the last SUCCESSFUL renewal
    assert a.tick() is False     # grace spent: voluntary demotion
    # and leadership is reacquirable once the fault clears
    faulty.enabled = False
    clock.advance(1)
    assert a.tick() is True and a.epoch == 2


def test_renew_conflict_demotes_immediately():
    backend, clock = _cluster(0)
    faulty = FaultyBackend(
        backend, FaultProfile(name="t", lease_renew_conflict=1.0)
    )
    a = LeaderElector(
        faulty, identity="a", ttl=30.0, clock=clock, counters=ApiCounters()
    )
    a.tick()
    assert a.tick() is False     # CAS lost: no grace applies


def test_reacquire_after_restart_gets_fresh_epoch():
    """A replica that crashed while leading and came back under the same
    identity must NOT resume the old epoch: its pre-crash in-flight
    writes have to be fenceable against its own new leadership."""
    backend, clock = _cluster(0)
    a = _elector(backend, clock, "a")
    a.tick()
    a2 = _elector(backend, clock, "a")     # the restarted incarnation
    assert a2.tick() is True
    assert a2.epoch == 2


# ---------------------------------------------------------------------------
# fencing at the backend seam
# ---------------------------------------------------------------------------


def test_stale_epoch_write_rejected_atomically():
    backend, clock = _cluster(1)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    backend.create_pod("p1", cfg_text=make_triad_config())
    clock.advance(31)
    b.tick()                     # epoch 2 now rules
    with pytest.raises(StaleLeaseError):
        backend.bind_pod_to_node("p1", "node0", "default", epoch=1)
    with pytest.raises(StaleLeaseError):
        backend.annotate_pod_config("default", "p1", "cfg", epoch=1)
    with pytest.raises(StaleLeaseError):
        backend.annotate_pod_gpu_map("default", "p1", {"nvidia0": 0}, epoch=1)
    with pytest.raises(StaleLeaseError):
        backend.add_nad_to_pod("p1", "default", "n@n", epoch=1)
    assert backend.pods[("default", "p1")].node is None
    assert backend.bind_log == []
    # the live epoch still lands
    assert backend.bind_pod_to_node("p1", "node0", "default", epoch=2)
    assert backend.bind_log[0][4] == 2


def test_deposed_leader_batch_rejected_mid_commit():
    """THE split-brain acceptance case: the epoch is bumped between a
    batch's annotate and its bind — the deposed leader's bind must be
    rejected by the backend and the pod must take the requeue path
    (unwound claim, no terminal failure), never land."""
    backend, clock = _cluster(2)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    sched = _scheduler(backend, elector=a)
    assert sched.poll_leadership() is True
    backend.create_pod("p1", cfg_text=make_triad_config())

    orig = backend.annotate_pod_config

    def bump_after_annotate(ns, pod, cfg, *, epoch=None):
        ok = orig(ns, pod, cfg, epoch=epoch)
        clock.advance(31)        # a's lease expires mid-commit...
        b.tick()                 # ...and b acquires epoch 2
        return ok

    backend.annotate_pod_config = bump_after_annotate
    before = API_COUNTERS.get("ha_stale_writes_rejected_total")
    sched.check_pending_pods()
    backend.annotate_pod_config = orig

    pod = backend.pods[("default", "p1")]
    assert pod.node is None                      # the bind never landed
    assert backend.bind_log == []                # provably rejected
    assert API_COUNTERS.get("ha_stale_writes_rejected_total") > before
    # requeue path, not terminal failure: state popped, claim unwound,
    # pod back on the queue for the NEW leader's tenure
    assert sched.pod_state.get(("default", "p1")) is None
    assert sched.failed_schedule_count == 0
    assert not sched.nqueue.empty()
    assert all(not n.pod_info for n in sched.nodes.values())


def test_locally_known_deposition_spends_no_api_calls():
    """A replica that already KNOWS it lost the lease fails the commit
    locally (fencing_epoch is None -> StaleLeaseError before any backend
    write)."""
    backend, clock = _cluster(2)
    a = _elector(backend, clock, "a")
    a.tick()
    sched = _scheduler(backend, elector=a)
    sched.poll_leadership()
    backend.create_pod("p1", cfg_text=make_triad_config())
    a.step_down()                # demoted, but _acting not yet synced
    sched.check_pending_pods()
    assert backend.pods[("default", "p1")].node is None
    assert backend.bind_log == []


# ---------------------------------------------------------------------------
# standby / promotion replay
# ---------------------------------------------------------------------------


def _claims(sched):
    return {
        (ns, pod): name
        for name, node in sched.nodes.items()
        for (pod, ns) in node.pod_info
    }


def test_standby_watches_but_does_not_act_until_elected():
    backend, clock = _cluster(2)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    leader = _scheduler(backend, elector=a)
    assert leader.poll_leadership() is True
    standby = _scheduler(backend, elector=b)
    assert standby.poll_leadership() is False

    # leader binds the workload
    backend.create_pod("p1", cfg_text=make_triad_config())
    backend.create_pod("p2", cfg_text=make_triad_config())
    leader.check_pending_pods()
    leader_claims = _claims(leader)
    assert len(leader_claims) == 2

    # a pod event reaching the STANDBY is not acted on
    backend.create_pod("p3", cfg_text=make_triad_config(), emit_watch=False)
    standby.nqueue.put(WatchItem(
        WatchType.TRIAD_POD_CREATE,
        pod={"ns": "default", "name": "p3", "uid": "u3", "cfg": "", "node": ""},
    ))
    standby.run_once()
    assert backend.pods[("default", "p3")].node is None

    # but a node event keeps the standby's mirror warm
    standby.nqueue.put(WatchItem(WatchType.NODE_CORDON, node="node0"))
    standby.run_once()
    assert standby.nodes["node0"].active is False
    backend.cordon_node("node0", False)
    standby.nqueue.put(WatchItem(WatchType.NODE_UNCORDON, node="node0"))
    standby.run_once()

    # watchdog-style demotion -> standby promotion: the promoted replica
    # replays annotations to the SAME claim state, then schedules what
    # the old leader left pending
    a.step_down()
    assert b.tick() is True
    assert standby.poll_leadership() is True
    promoted_claims = _claims(standby)
    assert {
        k: v for k, v in promoted_claims.items() if k != ("default", "p3")
    } == leader_claims
    assert backend.pods[("default", "p3")].node is not None  # scan caught it
    # resource accounting equivalence on the shared claims
    for name in leader.nodes:
        assert (
            standby.nodes[name].mem.free_hugepages_gb
            <= leader.nodes[name].mem.free_hugepages_gb
        )


def test_failed_promotion_replay_releases_the_lease():
    """Promotion keeps the crash-only contract: a replica whose replay
    fails (API outage mid-promotion) must NOT lead with an empty or
    partial mirror — it releases the lease so a healthy replica can win,
    instead of holding it with a live-but-stateless loop the watchdog
    would never catch."""
    from nhd_tpu.k8s.interface import TransientBackendError

    backend, clock = _cluster(2)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    sched = _scheduler(backend, elector=a)

    real_get_nodes = backend.get_nodes
    backend.get_nodes = lambda: (_ for _ in ()).throw(
        TransientBackendError("outage mid-promotion")
    )
    assert sched.poll_leadership() is False   # replay failed: stepped down
    assert a.is_leader is False
    assert sched._acting is False
    backend.get_nodes = real_get_nodes

    # the healthy standby wins and schedules; the failed replica can
    # also recover on a later, successful promotion
    assert b.tick() is True and b.epoch == 2
    backend.create_pod("p1", cfg_text=make_triad_config())
    other = _scheduler(backend, elector=b)
    assert other.poll_leadership() is True
    assert backend.pods[("default", "p1")].node is not None


def test_demoted_leader_stops_scanning():
    backend, clock = _cluster(2)
    a = _elector(backend, clock, "a")
    a.tick()
    sched = _scheduler(backend, elector=a)
    sched.poll_leadership()
    a.step_down()
    assert sched.poll_leadership() is False
    backend.create_pod("p1", cfg_text=make_triad_config())
    # idle path reaching the periodic-scan threshold must not scan
    from nhd_tpu.scheduler.core import IDLE_CNT_THRESH

    idle = sched.run_once(idle_count=IDLE_CNT_THRESH - 1)
    assert idle == 0
    assert backend.pods[("default", "p1")].node is None


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------


def test_watchdog_fires_on_wedged_loop_and_releases_lease():
    backend, clock = _cluster(0)
    a, b = _elector(backend, clock, "a"), _elector(backend, clock, "b")
    a.tick()
    exits = []
    beat = [0.0]
    wd = StallWatchdog(
        lambda: beat[0], stall_after=120.0, elector=a,
        exit_fn=exits.append, clock=clock, counters=ApiCounters(),
    )
    clock.advance(100)
    assert wd.check() is False        # within budget
    beat[0] = 100.0                   # a healthy heartbeat resets the age
    clock.advance(100)
    assert wd.check() is False
    clock.advance(121)                # loop wedged: no beat for 121s
    assert wd.check() is True
    assert exits == [2]               # crash-only exit requested
    assert not a.is_leader            # lease released...
    assert b.tick() is True           # ...so the standby takes over NOW
    assert b.epoch == 2
    assert wd.check() is True and exits == [2]   # fires once


def test_watchdog_quiet_on_healthy_loop():
    backend, clock = _cluster(0)
    exits = []
    wd = StallWatchdog(
        clock, stall_after=10.0, exit_fn=exits.append, clock=clock,
        counters=ApiCounters(),
    )
    for _ in range(5):
        clock.advance(5)
        assert wd.check() is False
    assert exits == []


# ---------------------------------------------------------------------------
# restart state equivalence (the ChaosSim.stats.restarts fix, pinned)
# ---------------------------------------------------------------------------


def test_restart_replay_reconstructs_equivalent_state():
    sim = ChaosSim(seed=3, n_nodes=3)
    sim.run(steps=30)
    sim._act_restart()               # force one regardless of the dice
    assert sim.stats.restarts >= 1
    assert sim.stats.violations == []


def test_restart_equivalence_detects_divergence():
    """The equivalence check must actually bite: corrupt one bound pod's
    solved-config annotation and the replayed state no longer matches
    the cluster."""
    sim = ChaosSim(seed=0, n_nodes=3)
    for _ in range(6):
        sim._act_create()
    sim._drive_control_plane()
    bound = [p for p in sim.backend.pods.values() if p.node]
    assert bound
    bound[0].annotations[CFG_ANNOTATION] = "garbage {"
    sim._act_restart()
    assert any("restart replay diverged" in v for v in sim.stats.violations)


# ---------------------------------------------------------------------------
# split-brain chaos: two schedulers, one cluster, lease faults on
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", range(3))
def test_split_brain_chaos_storm(seed):
    """The acceptance matrix cell: lease-renewal faults force leadership
    churn across two replicas; the run must end with zero double-epoch
    binds, zero invariant violations, zero stuck pods, and bounded
    leadership gaps."""
    sim = ChaosSim(
        seed=seed, n_nodes=4, ha=True, api_faults=PROFILES["ha-storm"]
    )
    stats = sim.run(steps=40)
    assert stats.violations == []
    # the storm actually churned leadership
    assert stats.lease_epoch >= 2
    fs = sim.backend.fault_stats
    assert fs["lease_renew_errors"] + fs["lease_renew_conflicts"] > 0
    # faults off -> the election and the cluster must both converge
    sim.quiesce()
    assert stats.violations == []
    assert sim.stuck_pods() == []
    assert any(r.elector.is_leader for r in sim.replicas)
    # every landed bind carries exactly one epoch per pod incarnation
    per_uid = {}
    for ns, pod, uid, node, epoch in sim.backend.bind_log:
        per_uid.setdefault(uid, set()).add(epoch)
    assert all(len(eps) == 1 for eps in per_uid.values())


def test_split_brain_exercises_fencing():
    """At least one seed of the matrix must drive an actual stale-epoch
    rejection (a deposed leader tried to commit and was fenced off) —
    otherwise the invariant above is vacuous."""
    API_COUNTERS.reset()
    sim = ChaosSim(seed=0, n_nodes=4, ha=True, api_faults=PROFILES["ha-storm"])
    stats = sim.run(steps=40)
    assert stats.violations == []
    assert API_COUNTERS.get("ha_stale_writes_rejected_total") > 0


def test_ha_light_profile_bounded_gaps():
    sim = ChaosSim(seed=1, n_nodes=4, ha=True, api_faults=PROFILES["ha-light"])
    stats = sim.run(steps=40)
    sim.quiesce()
    assert stats.violations == []
    assert sim.stuck_pods() == []
    assert stats.max_leader_gap <= int(sim.lease_ttl / 10.0) + 8


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


def test_ha_metrics_exported():
    out = render_metrics([], failed_count=0)
    for name, kind in (
        ("nhd_ha_is_leader", "gauge"),
        ("nhd_ha_epoch", "gauge"),
        ("nhd_ha_transitions_total", "counter"),
        ("nhd_ha_renewals_total", "counter"),
        ("nhd_ha_stale_writes_rejected_total", "counter"),
        ("nhd_ha_watchdog_stalls_total", "counter"),
        ("nhd_ha_watchdog_loop_age_seconds", "gauge"),
    ):
        assert f"# TYPE {name} {kind}" in out


def test_commit_path_unfenced_without_elector():
    """Single-replica mode is byte-identical to pre-HA behavior: no
    elector, no epoch on the wire, pods bind."""
    backend, _ = _cluster(2)
    sched = _scheduler(backend)
    assert sched.poll_leadership() is True
    backend.create_pod("p1", cfg_text=make_triad_config())
    sched.check_pending_pods()
    assert backend.pods[("default", "p1")].node is not None
    assert backend.bind_log[0][4] is None     # unfenced write
    assert sched.pod_state[("default", "p1")]["state"] is PodStatus.SCHEDULED
