"""Unschedulability explainer: per-node verdicts must agree with the
matcher (a node is 'schedulable' iff the oracle can place the pod there),
and each forced failure mode must surface its own reason."""

import random

import pytest

from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
from nhd_tpu.core.topology import MapMode, SmtMode
from nhd_tpu.sim import SynthNodeSpec, make_cluster, make_node
from nhd_tpu.solver import find_node
from nhd_tpu.solver.explain import (
    R_BUSY,
    R_CPU,
    R_GPU,
    R_GROUPS,
    R_HUGEPAGES,
    R_INACTIVE,
    R_MAINTENANCE,
    R_NIC,
    R_OK,
    explain,
)
from tests.test_batch import simple_request
from tests.test_jax_matcher import random_cluster, random_request


def verdict_of(report, node):
    return next(v for v in report.verdicts if v.node == node).reason


def test_each_failure_mode_has_its_reason():
    nodes = make_cluster(8)
    names = sorted(nodes)
    nodes[names[0]].active = False
    nodes[names[1]].maintenance = True
    nodes[names[2]].mem.free_hugepages_gb = 0
    nodes[names[3]].set_groups("other")
    nodes[names[4]].set_busy(now=1000.0)
    for gpu in nodes[names[5]].gpus:
        gpu.used = True
    for core in nodes[names[6]].cores:
        core.used = True

    req = simple_request(gpus=1)
    report = explain(nodes, req, now=1010.0)
    assert verdict_of(report, names[0]) == R_INACTIVE
    assert verdict_of(report, names[1]) == R_MAINTENANCE
    assert verdict_of(report, names[2]) == R_HUGEPAGES
    assert verdict_of(report, names[3]) == R_GROUPS
    assert verdict_of(report, names[4]) == R_BUSY
    assert verdict_of(report, names[5]) == R_GPU
    assert verdict_of(report, names[6]) == R_CPU
    assert verdict_of(report, names[7]) == R_OK
    assert report.schedulable_nodes == [names[7]]
    assert report.summary[R_OK] == 1

    text = report.render()
    assert R_GPU in text and names[5] in text


def test_nic_exhaustion_reason():
    nodes = make_cluster(1)
    node = next(iter(nodes.values()))
    for nic in node.nics:
        nic.pods_used = 1   # sharing disabled: zero headroom
    report = explain(nodes, simple_request())
    assert report.verdicts[0].reason == R_NIC
    assert not report.schedulable_nodes
    assert "UNSCHEDULABLE" in report.render()


@pytest.mark.parametrize("seed", range(10))
def test_explain_agrees_with_matcher(seed):
    """A node reads 'schedulable' iff the oracle would place the pod on it
    when offered that node alone."""
    rng = random.Random(8000 + seed)
    nodes = random_cluster(rng, 6)
    for _ in range(3):
        req = random_request(rng)
        report = explain(nodes, req, now=1010.0)
        for v in report.verdicts:
            alone = {v.node: nodes[v.node]}
            m = find_node(alone, req, now=1010.0)
            assert (m is not None) == (v.reason == R_OK), (
                f"seed {seed} node {v.node}: explain={v.reason} "
                f"matcher={'hit' if m else 'miss'}"
            )


def test_invalid_map_mode_reported():
    """The matcher refuses unknown map modes outright; explain must say
    so, not report per-node feasibility (iff-contract with the oracle)."""
    import dataclasses

    from nhd_tpu.solver.explain import R_INVALID_MODE

    nodes = make_cluster(2)
    req = dataclasses.replace(simple_request(), map_mode=MapMode.INVALID)
    report = explain(nodes, req)
    assert all(v.reason == R_INVALID_MODE for v in report.verdicts)
    assert not report.schedulable_nodes
    assert find_node(nodes, req) is None


def test_cli_explain(tmp_path, capsys):
    from nhd_tpu.cli import main
    from nhd_tpu.sim import make_triad_config

    cfg = tmp_path / "pod.cfg"
    cfg.write_text(make_triad_config(gpus_per_group=1, hugepages_gb=4))
    rc = main(["--fake", "--explain", str(cfg)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "schedulable on 4 node(s)" in out


def test_cli_explain_pod_live(capsys):
    """--explain-pod diagnoses a pod stuck in the cluster using its own
    ConfigMap and node-group annotation."""
    import argparse

    from nhd_tpu.cli import explain_main
    from nhd_tpu.k8s.fake import FakeClusterBackend
    from nhd_tpu.sim import SynthNodeSpec, make_node_labels, make_triad_config

    backend = FakeClusterBackend()
    for i in range(2):
        spec = SynthNodeSpec(name=f"n{i}")
        backend.add_node(spec.name, make_node_labels(spec), hugepages_gb=64)
    backend.create_pod(
        "stuck-0", cfg_text=make_triad_config(hugepages_gb=500)
    )
    args = argparse.Namespace(
        fake=True, explain=None, explain_pod="default/stuck-0",
        groups="default",
    )
    rc = explain_main(args, backend=backend)
    out = capsys.readouterr().out
    assert rc == 0
    assert "insufficient-hugepages" in out
    assert "UNSCHEDULABLE" in out

    args.explain_pod = "default/ghost"
    assert explain_main(args, backend=backend) == 1
    assert "not found" in capsys.readouterr().out

    # pod-spec hugepages reservation folds in like the scheduler's
    # _prepare_item: config says 4 GiB, pod spec requests 500Gi → fail
    backend.create_pod(
        "res-0", cfg_text=make_triad_config(hugepages_gb=4),
        resources={"hugepages-1Gi": "500G"},
    )
    args.explain_pod = "default/res-0"
    assert explain_main(args, backend=backend) == 0
    out = capsys.readouterr().out
    assert "insufficient-hugepages" in out
    assert "500" in out


def test_cli_explain_json_cfg_type(tmp_path, capsys):
    from nhd_tpu.cli import main
    from tests.test_jsoncfg import json_cfg

    cfg = tmp_path / "pod.json"
    cfg.write_text(json_cfg(hugepages_gb=999))
    rc = main(["--fake", "--explain", str(cfg), "--cfg-type", "json"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "insufficient-hugepages" in out


def test_cli_explain_unparseable_config(tmp_path, capsys):
    """A broken config is itself the diagnosis — no traceback."""
    from nhd_tpu.cli import main

    cfg = tmp_path / "broken.cfg"
    cfg.write_text("this is { not libconfig")
    rc = main(["--fake", "--explain", str(cfg)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "does not parse" in out
