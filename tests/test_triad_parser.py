"""Triad config ⇄ topology round-trip tests (reference: TriadCfgParser.py)."""

from nhd_tpu.config import libconfig
from nhd_tpu.config.parser import get_cfg_parser
from nhd_tpu.config.triad import TriadCfgParser
from nhd_tpu.core.request import PodRequest
from nhd_tpu.core.topology import MapMode, NicDir, SmtMode
from nhd_tpu.sim import make_triad_config


def parse(text):
    p = TriadCfgParser(text)
    top = p.to_topology(False)
    assert top is not None
    return p, top


def test_basic_parse():
    text = make_triad_config(
        n_groups=2,
        nic_pairs_per_group=1,
        rx_gbps=10.0,
        tx_gbps=5.0,
        cpu_workers=2,
        gpus_per_group=1,
        feeders_per_gpu=2,
        helpers_per_group=1,
        ext_cores=2,
        hugepages_gb=8,
    )
    _, top = parse(text)
    assert len(top.proc_groups) == 2
    assert top.hugepages_gb == 8
    assert top.map_mode == MapMode.NUMA
    assert len(top.misc_cores) == 2
    assert top.ctrl_vlan.name == "KniVlan"

    pg = top.proc_groups[0]
    # 2 NIC cores (rx+tx) + 2 cpu workers; gpu feeders live on the GPU
    assert len(pg.proc_cores) == 4
    assert len(pg.gpus) == 1
    assert len(pg.gpus[0].cpu_cores) == 2
    assert len(pg.misc_cores) == 1
    assert pg.proc_smt == SmtMode.ON

    rx = [c for c in pg.proc_cores if c.nic_dir == NicDir.RX]
    tx = [c for c in pg.proc_cores if c.nic_dir == NicDir.TX]
    assert len(rx) == 1 and rx[0].nic_speed == 10.0
    assert len(tx) == 1 and tx[0].nic_speed == 5.0
    assert len(top.nic_pairs) == 2  # one per group


def test_request_extraction():
    text = make_triad_config(
        n_groups=1,
        nic_pairs_per_group=2,
        rx_gbps=10.0,
        tx_gbps=5.0,
        cpu_workers=1,
        gpus_per_group=2,
        feeders_per_gpu=1,
        helpers_per_group=3,
        ext_cores=2,
        hugepages_gb=4,
    )
    _, top = parse(text)
    req = PodRequest.from_topology(top)
    assert req.n_groups == 1
    g = req.groups[0]
    # proc = 2 rx + 2 tx + 1 worker + 2 gpu feeders = 7
    assert g.proc.count == 7
    assert g.misc.count == 3
    assert g.gpus == 2
    assert g.nic_rx_gbps == 20.0
    assert g.nic_tx_gbps == 10.0
    assert req.misc.count == 2
    assert req.hugepages_gb == 4
    # SMT-on proc request on an SMT node: ceil(7/2) + ceil(3/2) = 4 + 2
    assert g.cpu_physical(node_smt=True) == 6
    assert g.cpu_physical(node_smt=False) == 10
    assert req.cpu_slot_counts(True) == [6, 1]


def test_mandatory_field_enforcement():
    text = make_triad_config().replace('cpu_arch = "ANY";', "")
    p = TriadCfgParser(text)
    assert p.to_topology(False) is None


def test_registry_default():
    text = make_triad_config()
    p = get_cfg_parser(None, text)
    assert isinstance(p, TriadCfgParser)
    p2 = get_cfg_parser("triad", text)
    assert p2.to_topology(False) is not None


def test_write_back_roundtrip():
    """Solve-side write-back: fill physical IDs, serialize, re-parse with
    parse_net=True, and check the deployed-config path reloads the same
    assignment (reference round trip: TriadCfgParser.py:337-380 ⇄ 413-459)."""
    text = make_triad_config(
        n_groups=1,
        nic_pairs_per_group=1,
        cpu_workers=1,
        gpus_per_group=1,
        feeders_per_gpu=1,
        helpers_per_group=1,
        ext_cores=1,
    )
    p, top = parse(text)

    # simulate the scheduler's assignment
    next_core = iter(range(10, 40))
    for pg in top.proc_groups:
        pg.vlan.vlan = 812
        for c in pg.proc_cores:
            c.core = next(next_core)
        for c in pg.misc_cores:
            c.core = next(next_core)
        for gpu in pg.gpus:
            gpu.device_id = 1
            for c in gpu.cpu_cores:
                c.core = next(next_core)
    for c in top.misc_cores:
        c.core = next(next_core)
    top.ctrl_vlan.vlan = 812
    top.set_data_default_gw("10.1.0.1/32")
    for pair in top.nic_pairs:
        pair.mac = "0C:42:A1:00:00:00"

    out = p.to_config()
    cfg = libconfig.loads(out)

    # all placeholders replaced
    assert -1 not in cfg.CtrlCores
    assert cfg.KniVlan == 812
    assert cfg.mods[0].vlan == 812
    dp = cfg.mods[0].dp[0]
    assert all(c >= 10 for c in dp.rx_cores + dp.tx_cores + dp.cpu_workers)
    assert dp.gpu_map[0][1] == 1

    # Network_Config synthesized per MAC
    assert len(cfg.Network_Config) == 1
    net = cfg.Network_Config[0]
    assert net.mac == "0C:42:A1:00:00:00"
    assert net.gwIps == ["10.1.0.1/32"]

    # deployed-config replay parses and reloads the NIC pairing
    p2 = TriadCfgParser(out)
    top2 = p2.to_topology(True)
    assert top2 is not None
    assert top2.nic_pairs[0].mac == "0C:42:A1:00:00:00"
    assert [c.core for c in top2.misc_cores] == [c.core for c in top.misc_cores]


def test_gpu_map_annotation():
    text = make_triad_config(gpus_per_group=2, feeders_per_gpu=1, n_groups=1)
    p, top = parse(text)
    for i, gpu in enumerate(top.proc_groups[0].gpus):
        gpu.device_id = 5 + i
    assert p.to_gpu_map() == {"nvidia0": 5, "nvidia1": 6}


def test_gpu_map_annotation_multi_group():
    """nvidia<i> index runs across proc groups (deviation from reference
    TriadCfgParser.py:403, which overwrote earlier groups' entries)."""
    text = make_triad_config(n_groups=2, gpus_per_group=1, feeders_per_gpu=1)
    p, top = parse(text)
    top.proc_groups[0].gpus[0].device_id = 2
    top.proc_groups[1].gpus[0].device_id = 3
    assert p.to_gpu_map() == {"nvidia0": 2, "nvidia1": 3}
