"""JSON config format: the plugin seam proven end to end — parse, solve,
write-back, GPU map, scheduler lifecycle via cfg_type=json, restart
replay. The reference ships one format behind its ABC; this is format #2
with zero scheduler changes."""

import json
import queue

from nhd_tpu.config.parser import get_cfg_parser
from nhd_tpu.k8s.interface import CFG_ANNOTATION
from nhd_tpu.scheduler.core import Scheduler
from nhd_tpu.scheduler.events import WatchQueue
from nhd_tpu.solver import find_node
from tests.test_scheduler import make_backend


def json_cfg(**kw):
    doc = {
        "map_mode": kw.get("map_mode", "NUMA"),
        "hugepages_gb": kw.get("hugepages_gb", 2),
        "misc_cores": {"count": 1, "smt": True},
        "groups": [
            {
                "proc_cores": {"count": 4, "smt": True},
                "helper_cores": {"count": 1, "smt": True},
                "gpus": kw.get("gpus", 1),
                "nic": {"rx_gbps": 10.0, "tx_gbps": 5.0,
                        "rx_ring_size": 2048},
            }
        ],
    }
    if kw.get("second_group"):
        doc["groups"].append(
            {"proc_cores": {"count": 2, "smt": True}, "gpus": 0,
             "nic": {"rx_gbps": 5.0, "tx_gbps": 2.0}}
        )
    return json.dumps(doc)


def test_parse_solve_writeback_roundtrip():
    from nhd_tpu.sim import make_cluster

    nodes = make_cluster(2)
    parser = get_cfg_parser("json", json_cfg(second_group=True))
    top = parser.to_topology(False)
    assert top is not None
    assert len(top.proc_groups) == 2
    assert top.nic_pairs[0].rx_core.nic_speed == 10.0

    m = find_node(nodes, top, respect_busy=False)
    assert m is not None
    nic_list = nodes[m.node].assign_physical_ids(m.mapping, top)
    # the scheduler claims NIC occupancy after assignment
    # (reference: NHDScheduler.py:304)
    nodes[m.node].claim_nic_pods(sorted({x[0] for x in nic_list}))
    solved = parser.to_config()

    doc = json.loads(solved)
    asg = doc["groups"][0]["assigned"]
    assert all(c >= 0 for c in asg["proc_core_ids"])
    assert len(asg["proc_core_ids"]) == 4
    assert asg["gpu_device_ids"][0] >= 0
    assert asg["nic_mac"]
    assert all(c >= 0 for c in doc["assigned_misc_cores"])
    # solved VLANs and gateway written back (assign_physical_ids fills
    # them from the node's DATA_PLANE_VLAN / DATA_DEFAULT_GW labels)
    assert doc["groups"][0]["vlan"] == nodes[m.node].data_vlan
    assert doc["data_default_gw"] == nodes[m.node].gwip

    # restart-replay reload: parse the solved doc, claim on a fresh mirror
    fresh = make_cluster(2)
    p2 = get_cfg_parser("json", solved)
    top2 = p2.to_topology(True)
    assert top2.nic_pairs[0].mac == asg["nic_mac"]
    assert fresh[m.node].claim_from_topology(top2)
    assert fresh[m.node].free_cpu_cores_per_numa() == \
        nodes[m.node].free_cpu_cores_per_numa()
    assert fresh[m.node].free_gpu_count() == nodes[m.node].free_gpu_count()
    assert fresh[m.node].mem.free_hugepages_gb == \
        nodes[m.node].mem.free_hugepages_gb
    # NIC bandwidth too: claim_from_topology restores it best-effort (a
    # silently-lost nic_mac would leak the rx/tx claim on replay)
    assert [
        (nic.speed_used[0], nic.speed_used[1], nic.pods_used)
        for nic in fresh[m.node].nics
    ] == [
        (nic.speed_used[0], nic.speed_used[1], nic.pods_used)
        for nic in nodes[m.node].nics
    ]


def test_gpu_map_indexes_across_groups():
    doc = json.loads(json_cfg(second_group=True))
    doc["groups"][1]["gpus"] = 1
    parser = get_cfg_parser("json", json.dumps(doc))
    top = parser.to_topology(False)
    top.proc_groups[0].gpus[0].device_id = 3
    top.proc_groups[1].gpus[0].device_id = 0
    parser.top = top
    assert parser.to_gpu_map() == {"nvidia0": 3, "nvidia1": 0}


def test_scheduler_lifecycle_with_json_cfg_type():
    """Pending json-typed pod → parse → solve → annotate → bind, then a
    fresh scheduler replays the claims — zero scheduler changes."""
    backend = make_backend()
    backend.create_pod("web-0", cfg_text=json_cfg(), cfg_type="json")
    sched = Scheduler(backend, WatchQueue(), queue.Queue(),
                      respect_busy=False)
    sched.build_initial_node_list()
    sched.check_pending_pods()

    pod = backend.pods[("default", "web-0")]
    assert pod.node is not None
    solved = json.loads(pod.annotations[CFG_ANNOTATION])
    assert all(c >= 0
               for c in solved["groups"][0]["assigned"]["proc_core_ids"])

    state1 = {n: (sum(v.free_cpu_cores_per_numa()), v.free_gpu_count())
              for n, v in sched.nodes.items()}
    sched2 = Scheduler(backend, WatchQueue(), queue.Queue(),
                       respect_busy=False)
    sched2.build_initial_node_list()
    sched2.load_deployed_configs()
    state2 = {n: (sum(v.free_cpu_cores_per_numa()), v.free_gpu_count())
              for n, v in sched2.nodes.items()}
    assert state1 == state2
    assert sched2.nodes[pod.node].total_pods() == 1


def test_nic_without_core_pair_is_a_parse_error():
    """A group asking for bandwidth with <2 proc cores must fail the pod
    loudly, never bind it with no network resources."""
    doc = json.loads(json_cfg())
    doc["groups"][0]["proc_cores"]["count"] = 1
    doc["groups"][0]["gpus"] = 0
    parser = get_cfg_parser("json", json.dumps(doc))
    assert parser.to_topology(False) is None


def test_malformed_json_fails_pod_not_scheduler():
    backend = make_backend(1)
    backend.create_pod("bad-0", cfg_text="{not json", cfg_type="json")
    backend.create_pod("good-0", cfg_text=json_cfg(), cfg_type="json")
    sched = Scheduler(backend, WatchQueue(), queue.Queue(),
                      respect_busy=False)
    sched.build_initial_node_list()
    sched.check_pending_pods()
    assert backend.pods[("default", "bad-0")].node is None
    assert backend.pods[("default", "good-0")].node is not None
    reasons = [e.reason for e in backend.events]
    assert "FailedCfgParse" in reasons
