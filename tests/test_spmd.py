"""Tier-1 SPMD parity: the mesh-sharded production path vs single device.

Promotes the MULTICHIP dryrun-harness assertions into the suite: the
fused ranked megaround over an 8-host-device mesh (conftest forces the
virtual devices) must be BIT-EXACT with the single-device program —
through the device-resident state, the per-shard delta scatters, staged
in-batch claims, and the sharded AOT export/prewarm cycle. A host that
cannot run in-process sharded programs skips cleanly (same capability-
probe pattern as tests/test_distributed.py)."""

from __future__ import annotations

import functools
import os
from typing import Optional

import numpy as np
import pytest

from nhd_tpu.solver.encode import ClusterDelta, encode_cluster, encode_pods
from nhd_tpu.solver.kernel import solve_bucket_ranked


@functools.lru_cache(maxsize=1)
def _mesh_unsupported_reason() -> Optional[str]:
    """None when this host can run an in-process 8-way sharded jit;
    otherwise the reason to skip (environmental, not a regression)."""
    import jax

    if len(jax.devices()) < 8:
        return f"needs 8 devices, host exposes {len(jax.devices())}"
    try:
        import jax.numpy as jnp

        from nhd_tpu.parallel.sharding import make_mesh
        from nhd_tpu.solver.kernel import mesh_shardings

        mesh = make_mesh(jax.devices()[:8])
        node, repl = mesh_shardings(mesh)
        out = jax.jit(
            lambda a: jnp.sum(a), in_shardings=(node,), out_shardings=repl
        )(np.ones(16, np.float32))
        assert float(out) == 16.0
    except Exception as exc:  # environmental: no sharded CPU execution
        return f"sharded jit unavailable: {exc}"
    return None


def _require_mesh() -> None:
    reason = _mesh_unsupported_reason()
    if reason is not None:
        pytest.skip(f"in-process SPMD unavailable: {reason}")


def _mesh():
    import jax

    from nhd_tpu.parallel.sharding import make_mesh

    return make_mesh(jax.devices()[:8])


def _cluster(n_nodes: int, seed: int = 0):
    from tests.test_jax_matcher import random_cluster
    import random

    return random_cluster(random.Random(seed), n_nodes)


def _requests(n: int, seed: int = 0):
    from tests.test_jax_matcher import random_request
    import random

    rng = random.Random(seed)
    return [random_request(rng) for _ in range(n)]


@pytest.mark.parametrize("seed,n_nodes", [(0, 8), (1, 13), (2, 21)])
def test_device_state_mesh_solve_ranked_bit_exact(seed, n_nodes):
    """The production mesh dispatch (DeviceClusterState.solve_ranked →
    kernel.get_ranked_solver_mesh) vs the host fused program, even and
    uneven node splits — the dryrun harness's exact-parity assertion."""
    _require_mesh()
    from nhd_tpu.solver.device_state import DeviceClusterState

    nodes = _cluster(n_nodes, seed)
    cluster = encode_cluster(nodes, now=1010.0)
    dev = DeviceClusterState(cluster, _mesh())
    for G, pods in sorted(
        encode_pods(_requests(8, seed), cluster.interner).items()
    ):
        got = np.asarray(dev.solve_ranked(pods, 16))
        want = np.asarray(solve_bucket_ranked(cluster, pods, 16))
        # single-device pads N to its own pow-2 bucket; the mesh pads to
        # a multiple of the device count — compare at the common width
        R = min(got.shape[2], want.shape[2])
        np.testing.assert_array_equal(got[:, :, :R], want[:, :, :R])


def test_mesh_respect_busy_split_parity():
    """Busy-marked rows (the respect-busy dryrun split) survive the
    shard boundary bit-exactly."""
    _require_mesh()
    from nhd_tpu.solver.device_state import DeviceClusterState

    nodes = _cluster(12, 3)
    cluster = encode_cluster(nodes, now=1010.0)
    cluster.busy[::3] = True
    dev = DeviceClusterState(cluster, _mesh())
    for G, pods in sorted(
        encode_pods(_requests(6, 3), cluster.interner).items()
    ):
        got = np.asarray(dev.solve_ranked(pods, 8))
        want = np.asarray(solve_bucket_ranked(cluster, pods, 8))
        R = min(got.shape[2], want.shape[2])
        np.testing.assert_array_equal(got[:, :, :R], want[:, :, :R])


def test_mesh_scatter_rows_o_changed_rows_and_bit_exact():
    """The PR 9 open item closed: churn rows reach mesh-sharded resident
    arrays as per-shard delta scatters — counters tick O(changed rows),
    zero wholesale fallbacks, and every resident array equals the padded
    host mirror bit-for-bit afterwards."""
    _require_mesh()
    from nhd_tpu.k8s.retry import API_COUNTERS
    from nhd_tpu.solver.device_state import (
        DeviceClusterState, _ARG_ORDER, _pad_own,
    )

    nodes = _cluster(11, 5)
    cluster = encode_cluster(nodes, now=1010.0)
    dev = DeviceClusterState(cluster, _mesh())
    # churn-shaped host mutations across several shards
    cluster.active[1] = False
    cluster.maintenance[4] = True
    cluster.cpu_free[7] = 0
    cluster.hp_free[9] = 0
    c0 = API_COUNTERS.snapshot()
    dev.scatter_rows(np.asarray([1, 4, 7, 9], np.int64))
    c1 = API_COUNTERS.snapshot()
    assert c1["device_state_rows_uploaded_total"] - (
        c0["device_state_rows_uploaded_total"]
    ) == 4
    assert c1["mesh_rows_uploaded_total"] - (
        c0["mesh_rows_uploaded_total"]
    ) == 4
    assert c1["mesh_wholesale_uploads_total"] == (
        c0["mesh_wholesale_uploads_total"]
    )
    for name in _ARG_ORDER:
        np.testing.assert_array_equal(
            np.asarray(dev._dev[name]),
            _pad_own(getattr(cluster, name), dev.Np),
            err_msg=name,
        )


def test_mesh_staged_claims_scatter_matches_wholesale():
    """Staged in-batch claims (stage_rows) take the per-shard scatter on
    a mesh and the next solve sees exactly the host-mirror truth — the
    same answer a wholesale re-upload (NHD_DEVICE_DELTA=0) produces."""
    _require_mesh()
    from nhd_tpu.solver.device_state import DeviceClusterState

    nodes = _cluster(10, 7)
    cluster = encode_cluster(nodes, now=1010.0)
    buckets = encode_pods(_requests(5, 7), cluster.interner)

    outs = {}
    for mode in ("delta", "wholesale"):
        os.environ["NHD_DEVICE_DELTA"] = "1" if mode == "delta" else "0"
        try:
            dev = DeviceClusterState(cluster, _mesh())
            cluster.busy[2] = True
            cluster.gpu_free[6] = 0
            dev.stage_rows([2, 6])
            outs[mode] = {
                G: np.asarray(dev.solve_ranked(pods, 8))
                for G, pods in sorted(buckets.items())
            }
        finally:
            os.environ.pop("NHD_DEVICE_DELTA", None)
            cluster.busy[2] = False
    for G in outs["delta"]:
        np.testing.assert_array_equal(
            outs["delta"][G], outs["wholesale"][G]
        )


def test_delta_context_churn_on_mesh_pays_changed_rows():
    """refresh_context over a delta-built MESH context: noted churn
    reaches the sharded resident arrays as row scatters (not the
    wholesale re-upload the mesh used to force), and the delta parity
    invariant holds throughout."""
    _require_mesh()
    from nhd_tpu.k8s.retry import API_COUNTERS
    from nhd_tpu.solver.batch import BatchItem, BatchScheduler
    from nhd_tpu.solver.device_state import _ARG_ORDER, _pad_own

    nodes = _cluster(12, 9)
    sched = BatchScheduler(
        respect_busy=False, register_pods=False,
        device_state=True, mesh=_mesh(),
    )
    delta = ClusterDelta(nodes, now=0.0, respect_busy=False)
    ctx = sched.make_context(nodes, now=0.0, delta=delta)
    assert ctx.dev is not None and ctx.dev.mesh is not None
    items = [
        BatchItem(("ns", f"p{i}"), r) for i, r in enumerate(_requests(6, 9))
    ]
    sched.schedule(ctx.nodes, items, context=ctx)

    # inter-batch churn: two nodes flip, noted like watch events
    names = list(nodes)
    nodes[names[0]].active = not nodes[names[0]].active
    nodes[names[5]].maintenance = True
    delta.note(names[0])
    delta.note(names[5])
    c0 = API_COUNTERS.snapshot()
    sched.refresh_context(ctx, now=0.0)
    c1 = API_COUNTERS.snapshot()
    up = c1["device_state_rows_uploaded_total"] - (
        c0["device_state_rows_uploaded_total"]
    )
    assert 0 < up <= 4, up  # the two noted rows (+ staged claim rows)
    assert c1["mesh_wholesale_uploads_total"] == (
        c0["mesh_wholesale_uploads_total"]
    )
    assert delta.parity_errors() == []
    for name in _ARG_ORDER:
        np.testing.assert_array_equal(
            np.asarray(ctx.dev._dev[name]),
            _pad_own(getattr(ctx.cluster, name), ctx.dev.Np),
            err_msg=name,
        )


def test_mesh_aot_export_prewarm_compiles_flat(tmp_path):
    """Sharded programs export to the AOT cache under mesh-qualified
    keys, prewarm back, and the next mesh dispatch is a cache HIT
    serving bit-identical results (the zero-recompile invariant for the
    multi-chip posture)."""
    _require_mesh()
    from nhd_tpu.obs.jitstats import JIT_STATS
    from nhd_tpu.solver import aot, kernel
    from nhd_tpu.solver.device_state import DeviceClusterState

    aot.reset()
    aot.configure(directory=str(tmp_path), save=True)
    try:
        nodes = _cluster(9, 11)
        cluster = encode_cluster(nodes, now=1010.0)
        buckets = encode_pods(_requests(6, 11), cluster.interner)
        dev = DeviceClusterState(cluster, _mesh())
        outs = {
            G: np.asarray(dev.solve_ranked(pods, 8))
            for G, pods in sorted(buckets.items())
        }
        aot.AOT.drain()
        mesh_artifacts = [
            f for f in os.listdir(tmp_path)
            if f.endswith(".json") and "_mnodes8" in f
        ]
        assert mesh_artifacts, sorted(os.listdir(tmp_path))

        # restart-equivalent: live programs dropped, disk is the source
        kernel.get_ranked_solver_mesh.cache_clear()
        kernel.get_ranked_solver.cache_clear()
        JIT_STATS.reset()
        aot.reset()
        aot.configure(directory=str(tmp_path), save=False)
        summary = aot.prewarm()
        assert summary["quarantined"] == 0
        assert any("_mnodes8" in k for k in summary["keys"]), summary
        warm = JIT_STATS.snapshot()

        dev2 = DeviceClusterState(cluster, _mesh())
        for G, pods in sorted(buckets.items()):
            got = np.asarray(dev2.solve_ranked(pods, 8))
            np.testing.assert_array_equal(got, outs[G])
        steady = JIT_STATS.snapshot()
        escaped = sorted(
            k for k in steady["shapes"]
            if k.startswith("solve_ranked:")
            and k not in warm["shapes"]
        )
        assert escaped == [], escaped
    finally:
        aot.reset()


def test_mesh_prewarm_skips_oversized_mesh_artifacts(tmp_path):
    """An artifact exported on a BIGGER slice (more devices than this
    host) is skipped — neither loaded nor quarantined: it is not stale,
    just inapplicable here."""
    import json

    from nhd_tpu.solver import aot

    aot.reset()
    aot.configure(directory=str(tmp_path), save=False)
    try:
        meta = {
            "aot_schema": aot.AOT_SCHEMA_VERSION,
            "kind": "ranked", "G": 1, "U": 2, "K": 2, "R": 8,
            "Tp": 8, "Np": 64, "mesh": "nodes64",
            **aot._versions(),
            "platforms": ["cpu", "tpu"],
        }
        base = tmp_path / "ranked_g1_u2_k2_r8_t8_n64_mnodes64"
        (tmp_path / f"{base.name}.json").write_text(json.dumps(meta))
        (tmp_path / f"{base.name}.stablehlo.bin").write_bytes(b"\x00")
        summary = aot.prewarm()
        assert summary["loaded"] == 0
        assert summary["quarantined"] == 0
        assert summary["skipped"] == 1
        # left in place for the host that CAN run it
        assert (tmp_path / f"{base.name}.stablehlo.bin").exists()
    finally:
        aot.reset()


def _mesh_delta_context(n_nodes: int, seed: int):
    """A delta-built MESH ScheduleContext over a live node dict — the
    structural-fallback tests' shared scaffold (ISSUE 12 satellite:
    tombstone-readd and compaction were only exercised end-to-end on the
    single-device path before)."""
    from nhd_tpu.solver.batch import BatchScheduler

    nodes = _cluster(n_nodes, seed)
    sched = BatchScheduler(
        respect_busy=False, register_pods=False,
        device_state=True, mesh=_mesh(),
    )
    delta = ClusterDelta(nodes, now=0.0, respect_busy=False)
    ctx = sched.make_context(nodes, now=0.0, delta=delta)
    assert ctx.dev is not None and ctx.dev.mesh is not None
    return nodes, sched, delta, ctx


def _assert_mesh_ctx_rederived(ctx):
    """After a structural fallback: parity holds, the resident arrays
    equal the padded host mirror bit-for-bit, and a mesh solve matches
    the host fused program on a from-scratch encode of the live dict."""
    from nhd_tpu.solver.device_state import _ARG_ORDER, _pad_own

    assert ctx.delta.parity_errors() == []
    for name in _ARG_ORDER:
        np.testing.assert_array_equal(
            np.asarray(ctx.dev._dev[name]),
            _pad_own(getattr(ctx.cluster, name), ctx.dev.Np),
            err_msg=name,
        )
    live = {n: ctx.delta.nodes[n] for n in ctx.delta.nodes}
    fresh = encode_cluster(live, now=0.0)
    fresh.busy[:] = False
    for G, pods in sorted(
        encode_pods(_requests(4, 11), fresh.interner).items()
    ):
        got = np.asarray(
            ctx.dev.solve_ranked(
                # encode against the CONTEXT's interner so group-mask
                # bit positions match the resident arrays
                encode_pods(pods.requests, ctx.cluster.interner)[G], 8
            )
        )
        want = np.asarray(solve_bucket_ranked(fresh, pods, 8))
        R = min(got.shape[2], want.shape[2])
        # tombstoned rows live only in the context's padded axis; the
        # ranked node INDICES can differ between the two row spaces, so
        # compare the selection values per type instead of raw indices
        np.testing.assert_array_equal(
            (got[0, :, :R] > 0).sum(axis=1),
            (want[0, :, :R] > 0).sum(axis=1),
        )


def test_mesh_delta_tombstone_readd_rebuilds_and_stays_bit_exact():
    """Removing a node then re-adding the SAME name while its tombstone
    still occupies a mid-array slot forces the sanctioned
    tombstone-readd rebuild — and with the MESH-resident path active the
    rebuilt context must re-derive bit-exactly (sharded resident arrays
    included)."""
    _require_mesh()
    from nhd_tpu.solver.encode import rebuild_reasons_snapshot

    nodes, sched, delta, ctx = _mesh_delta_context(12, 11)
    victim = list(nodes)[3]
    node_obj = nodes.pop(victim)
    delta.note(victim)
    sched.refresh_context(ctx, now=0.0)  # tombstones in place
    assert victim in delta._tombstones
    assert ctx.dev is not None and ctx.dev.mesh is not None

    r0 = rebuild_reasons_snapshot().get("tombstone-readd", 0)
    node_obj.active = True
    nodes[victim] = node_obj  # re-insert: live dict appends at the END
    delta.note(victim)
    sched.refresh_context(ctx, now=0.0)
    assert rebuild_reasons_snapshot().get("tombstone-readd", 0) == r0 + 1
    assert ctx.dev is not None and ctx.dev.mesh is not None
    _assert_mesh_ctx_rederived(ctx)


def test_mesh_delta_compaction_rebuilds_and_stays_bit_exact():
    """Tombstoning past the occupancy threshold triggers the compaction
    rebuild; the mesh-resident context re-derives wholesale (fresh
    capacity bucket, fresh shard layout) and stays bit-exact."""
    _require_mesh()
    from nhd_tpu.solver.encode import rebuild_reasons_snapshot

    nodes, sched, delta, ctx = _mesh_delta_context(16, 13)
    r0 = rebuild_reasons_snapshot().get("compaction", 0)
    for victim in list(nodes)[2:8]:  # > max(4, 16//8) tombstones
        nodes.pop(victim)
        delta.note(victim)
    sched.refresh_context(ctx, now=0.0)
    assert rebuild_reasons_snapshot().get("compaction", 0) == r0 + 1
    assert delta._tombstones == set()
    assert ctx.dev is not None and ctx.dev.mesh is not None
    _assert_mesh_ctx_rederived(ctx)
