"""KubeClusterBackend over REAL HTTP against a stub API server (VERDICT
r2 item 4): every request the backend makes is serialized onto a socket,
parsed by an in-process API server (k8s/apistub.py), and asserted at the
payload level — binding bodies byte-for-byte, strategic-merge patch
content types, event shapes, watch reconnects, and the V1Binding
client-quirk path the reference codes around (K8SMgr.py:468-492).

The mocked-module tests (test_kube.py) cover the client-object surface;
this file covers the wire."""

import json
import sys
import time

import pytest

from nhd_tpu.k8s.apistub import StubApiServer, make_pod
from nhd_tpu.k8s.interface import (
    CFG_ANNOTATION,
    EventType,
    GROUPS_ANNOTATION,
    NAD_ANNOTATION,
)


class _BlockKubernetesImport:
    """meta_path finder that makes `import kubernetes` fail even when the
    real package is installed — these tests must exercise the restclient
    fallback, not whatever client happens to be available."""

    def find_spec(self, name, path=None, target=None):
        if name == "kubernetes" or name.startswith("kubernetes."):
            raise ImportError("kubernetes blocked: restclient contract test")
        return None


@pytest.fixture()
def stub(monkeypatch):
    """Stub API server + env pointing the restclient fallback at it."""
    # the mocked-module suite (test_kube.py) leaves a fake `kubernetes`
    # in sys.modules; remove it AND block fresh imports so kube.py takes
    # the restclient fallback regardless of the environment
    monkeypatch.delitem(sys.modules, "kubernetes", raising=False)
    blocker = _BlockKubernetesImport()
    sys.meta_path.insert(0, blocker)
    srv = StubApiServer().start()
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "127.0.0.1")
    monkeypatch.setenv("KUBERNETES_SERVICE_PORT", str(srv.port))
    monkeypatch.setenv("KUBERNETES_SERVICE_SCHEME", "http")
    monkeypatch.setenv("NHD_K8S_TOKEN_FILE", "/nonexistent-token")
    try:
        yield srv
    finally:
        sys.meta_path.remove(blocker)
        srv.stop()


def _backend(**kw):
    from nhd_tpu.k8s.kube import KubeClusterBackend
    from nhd_tpu.k8s.restclient import ApiException
    from nhd_tpu.k8s.retry import RetryPolicy

    # real retry semantics, millisecond backoff (suite wall-clock)
    kw.setdefault("retry_policy", RetryPolicy(
        base_delay=0.002, max_delay=0.01, exc_class=ApiException
    ))
    return KubeClusterBackend(start_watches=False, **kw)


# ---------------------------------------------------------------------------
# node reads
# ---------------------------------------------------------------------------


def test_node_reads_over_http(stub):
    stub.add_node("n1", internal_ip="10.1.2.3")
    stub.add_node("n2", ready=False)
    stub.add_node("n3", taint=False)
    stub.add_node("n4", unschedulable=True)
    b = _backend()
    assert b.get_nodes() == ["n1", "n3", "n4"]  # KubeletReady filter
    assert b.is_node_active("n1") is True
    assert b.is_node_active("n3") is False      # no scheduler taint
    assert b.is_node_active("n4") is False      # cordoned
    assert b.get_node_addr("n1") == "10.1.2.3"
    assert b.get_node_hugepage_resources("n1") == (64, 60)
    stub.add_node("n5", labels={"a": "1"})
    assert b.get_node_labels("n5") == {"a": "1"}
    # the reads actually went over the wire
    paths = [p for (m, p, _, _) in stub.requests if m == "GET"]
    assert "/api/v1/nodes" in paths and "/api/v1/nodes/n1" in paths


# ---------------------------------------------------------------------------
# pod reads
# ---------------------------------------------------------------------------


def test_pod_reads_and_filters(stub):
    stub.add_pod("p1", annotations={GROUPS_ANNOTATION: "grpA.grpB"},
                 requests={"cpu": "4", "hugepages-1Gi": "8Gi"})
    stub.add_pod("p2", scheduler="default-scheduler")
    stub.add_pod("p3", node="n1", phase="Running", uid="uid-3")
    b = _backend()
    assert b.pod_exists("p1", "default") is True
    assert b.pod_exists("nope", "default") is False
    assert b.get_pod_node("p3", "default") == "n1"
    assert b.get_pod_node_groups("p1", "default") == ["grpA", "grpB"]
    assert b.get_pod_node_groups("p3", "default") == ["default"]
    assert b.get_requested_pod_resources("p1", "default") == {
        "cpu": "4", "hugepages-1Gi": "8Gi"
    }
    # scheduler-name filtering happens on real list responses
    assert b.get_scheduled_pods("nhd-scheduler") == [
        ("p3", "default", "uid-3", "Running")
    ]
    sp = b.service_pods("nhd-scheduler")
    assert set(sp) == {("default", "p1", "uid-1"), ("default", "p3", "uid-3")}
    assert sp[("default", "p3", "uid-3")] == ("Running", "n1")


def test_cfg_map_resolution_over_http(stub):
    stub.add_pod("p1", configmap="cm1")
    stub.add_configmap("cm1", "default", {"triad.cfg": "cfg-text"})
    stub.add_pod("p2", configmap="missing-cm")
    b = _backend()
    assert b.get_cfg_map("p1", "default") == ("cm1", "cfg-text")
    # missing ConfigMap: 404 travels back as ApiException, pod fails soft
    assert b.get_cfg_map("p2", "default") == (None, None)


# ---------------------------------------------------------------------------
# writes: annotations (strategic-merge PATCH)
# ---------------------------------------------------------------------------


def test_annotation_patch_wire_format(stub):
    stub.add_pod("p1")
    b = _backend()
    assert b.annotate_pod_config("default", "p1", "solved-cfg") is True
    method, path, ctype, body = next(
        r for r in stub.requests if r[0] == "PATCH"
    )
    assert path == "/api/v1/namespaces/default/pods/p1"
    assert ctype == "application/strategic-merge-patch+json"
    # byte-level: exactly the strategic-merge shape, nothing else
    assert body == json.dumps(
        {"metadata": {"annotations": {CFG_ANNOTATION: "solved-cfg"}}}
    ).encode()
    # round-trip through the server's merge
    assert b.get_cfg_annotations("p1", "default") == "solved-cfg"


def test_nad_and_gpu_map_round_trip(stub):
    stub.add_pod("p1")
    b = _backend()
    assert b.add_nad_to_pod("p1", "default", "sriov-a@net1") is True
    assert b.annotate_pod_gpu_map("default", "p1", {"nvidia0": 1}) is True
    annots = b.get_pod_annotations("p1", "default")
    assert annots[NAD_ANNOTATION] == "sriov-a@net1"
    assert annots["sigproc.viasat.io/nhd_gpu_devices.nvidia0"] == "1"


def test_patch_failure_raises_transient(stub):
    """A persistent 500 from the API server exhausts the retry policy and
    surfaces as TransientBackendError (scheduler requeues the pod); a 404
    — terminal — still returns False."""
    from nhd_tpu.k8s.interface import TransientBackendError

    stub.add_pod("p1")
    stub.fail_patches = True
    b = _backend()
    with pytest.raises(TransientBackendError):
        b.annotate_pod_config("default", "p1", "cfg")
    stub.fail_patches = False
    assert b.annotate_pod_config("default", "ghost", "cfg") is False


# ---------------------------------------------------------------------------
# writes: binding (the schedule commit point)
# ---------------------------------------------------------------------------


def test_bind_payload_and_client_quirk(stub):
    stub.add_pod("p1")
    b = _backend()
    # the stub answers with a Status object (what real API servers do),
    # which makes the client raise ValueError — the quirk path must still
    # report success (reference: K8SMgr.py:487-491)
    assert b.bind_pod_to_node("p1", "n1", "default") is True
    method, path, ctype, body = next(
        r for r in stub.requests if r[0] == "POST"
    )
    assert path == "/api/v1/namespaces/default/pods/p1/binding"
    assert ctype == "application/json"
    assert body == json.dumps(
        {
            "metadata": {"name": "p1"},
            "target": {
                "apiVersion": "v1", "kind": "Node",
                "name": "n1", "namespace": "default",
            },
        }
    ).encode()
    # the server really applied it
    assert stub.pods[("default", "p1")]["spec"]["nodeName"] == "n1"
    assert b.get_pod_node("p1", "default") == "n1"


def test_bind_conflict_returns_false(stub):
    stub.add_pod("p1")
    stub.fail_bindings = True
    b = _backend()
    assert b.bind_pod_to_node("p1", "n1", "default") is False
    assert stub.pods[("default", "p1")]["spec"]["nodeName"] is None


# ---------------------------------------------------------------------------
# events
# ---------------------------------------------------------------------------


def test_event_wire_shape(stub):
    stub.add_pod("p1", uid="uid-ev")
    b = _backend()
    b.generate_pod_event(
        "p1", "default", "StartedScheduling", EventType.NORMAL, "scheduling p1"
    )
    assert len(stub.events) == 1
    ev = stub.events[0]
    assert ev["message"] == "NHD: scheduling p1"          # NHD: prefix
    assert ev["reason"] == "StartedScheduling"
    assert ev["type"] == "Normal"
    assert ev["count"] == 1
    assert ev["involvedObject"] == {
        "apiVersion": "v1", "kind": "Pod", "name": "p1",
        "namespace": "default", "uid": "uid-ev",
    }
    assert ev["source"] == {"component": "nhd-scheduler"}
    assert ev["metadata"] == {"generateName": "p1.nhd."}
    # RFC3339 timestamps
    assert ev["firstTimestamp"].endswith("Z") or "+" in ev["firstTimestamp"]
    # missing pod: no event, no crash
    b.generate_pod_event("ghost", "default", "X", EventType.WARNING, "m")
    assert len(stub.events) == 1


# ---------------------------------------------------------------------------
# TriadSets (CRD)
# ---------------------------------------------------------------------------


def test_triadset_crd_over_http(stub):
    template = {
        "metadata": {"annotations": {"sigproc.viasat.io/cfg_type": "triad"}},
        "spec": {"schedulerName": "nhd-scheduler", "containers": []},
    }
    stub.add_triadset("ts1", "default", replicas=3, service_name="svc",
                      template=template)
    stub.add_pod("svc-0")
    stub.add_pod("svc-x")  # non-ordinal: not a member
    b = _backend()
    sets = b.list_triadsets()
    assert sets == [{
        "name": "ts1", "ns": "default", "replicas": 3,
        "service_name": "svc", "template": template,
    }]
    assert b.list_pods_of_triadset(sets[0]) == ["svc-0"]
    assert b.create_pod_for_triadset(sets[0], 1) is True
    created = stub.pods[("default", "svc-1")]
    assert created["spec"]["hostname"] == "svc-1"
    assert created["spec"]["subdomain"] == "svc"
    assert created["metadata"]["annotations"] == template["metadata"][
        "annotations"
    ]
    # scale-subresource status patch
    assert b.update_triadset_status(sets[0], 2) is True
    method, path, ctype, body = [r for r in stub.requests if r[0] == "PATCH"][-1]
    assert path == (
        "/apis/sigproc.viasat.io/v1/namespaces/default/triadsets/ts1/status"
    )
    assert ctype == "application/merge-patch+json"
    assert body == b'{"status": {"replicas": 2}}'
    assert stub.triadsets[("default", "ts1")]["status"] == {"replicas": 2}


# ---------------------------------------------------------------------------
# watch plane: real streams, real reconnects
# ---------------------------------------------------------------------------


def test_watch_stream_and_reconnect(stub):
    stub.queue_watch_event(
        "/api/v1/pods", "ADDED",
        make_pod("w1", annotations={"k": "v"}, uid="uid-w1"),
    )
    b = _backend()
    b._watch_backoff = 0.05
    b._start_watches()
    try:
        deadline = time.time() + 5
        events = []
        while time.time() < deadline and not events:
            events = [
                e for e in b.poll_watch_events(timeout=0.1)
                if e.kind == "pod_create"
            ]
        assert events, "pod watch event never arrived"
        ev = events[0]
        assert ev.name == "w1" and ev.namespace == "default"
        assert ev.uid == "uid-w1"
        assert ev.annotations == {"k": "v"}
        assert ev.scheduler_name == "nhd-scheduler"

        # second batch arrives only via a NEW connection — proves the
        # reconnect loop survives server-side stream termination
        first_connects = stub.watch_connects.get("/api/v1/pods", 0)
        stub.queue_watch_event(
            "/api/v1/pods", "DELETED", make_pod("w2", uid="uid-w2")
        )
        deadline = time.time() + 5
        events = []
        while time.time() < deadline and not events:
            events = [
                e for e in b.poll_watch_events(timeout=0.1)
                if e.kind == "pod_delete"
            ]
        assert events and events[0].name == "w2"
        assert stub.watch_connects["/api/v1/pods"] > first_connects
    finally:
        b.stop_watches()


def test_node_watch_translation(stub):
    from nhd_tpu.k8s.apistub import make_node

    stub.queue_watch_event(
        "/api/v1/nodes", "MODIFIED",
        make_node("n1", unschedulable=True, labels={"NHD_GROUP": "a"}),
    )
    b = _backend()
    b._watch_backoff = 0.05
    b._start_watches()
    try:
        deadline = time.time() + 5
        events = []
        while time.time() < deadline and not events:
            events = [
                e for e in b.poll_watch_events(timeout=0.1)
                if e.kind == "node_update"
            ]
        assert events
        ev = events[0]
        assert ev.name == "n1"
        assert ev.unschedulable is True
        assert ev.labels == {"NHD_GROUP": "a"}
        assert "sigproc.viasat.io/nhd_scheduler" in ev.taints
    finally:
        b.stop_watches()


# ---------------------------------------------------------------------------
# auth
# ---------------------------------------------------------------------------


def test_bearer_token_sent(stub, monkeypatch, tmp_path):
    token_file = tmp_path / "token"
    token_file.write_text("sekrit-token\n")
    monkeypatch.setenv("NHD_K8S_TOKEN_FILE", str(token_file))
    stub.token = "sekrit-token"
    stub.add_node("n1")
    b = _backend()
    assert b.get_nodes() == ["n1"]  # 401 would raise / return []

    # and without the right token the server rejects us
    from nhd_tpu.k8s.restclient import ApiException, CoreV1Api, _set_config, Configuration

    _set_config(Configuration(f"http://127.0.0.1:{stub.port}", token="wrong"))
    with pytest.raises(ApiException) as ei:
        CoreV1Api().read_node("n1")
    assert ei.value.status == 401


def test_watch_resource_version_tracking_and_410_reset(stub):
    """The watch resumes from the last seen resourceVersion on reconnect,
    and a 410 Gone resets it so the next reconnect starts fresh."""
    from nhd_tpu.k8s.restclient import (
        ApiException, Configuration, CoreV1Api, Watch, _set_config,
    )

    _set_config(Configuration(f"http://127.0.0.1:{stub.port}"))
    api = CoreV1Api()
    pod = make_pod("w1", uid="uid-w1")
    pod["metadata"]["resourceVersion"] = "42"
    stub.queue_watch_event("/api/v1/pods", "ADDED", pod)

    w = Watch()
    events = list(w.stream(api.list_pod_for_all_namespaces))
    assert [e["object"].metadata.name for e in events] == ["w1"]
    assert w.resource_version == "42"

    # the reconnect carries resourceVersion=42 on the wire
    list(w.stream(api.list_pod_for_all_namespaces))
    watch_paths = [p for (m, p, _, _) in stub.requests if "watch=true" in p]
    assert watch_paths[-1].endswith("resourceVersion=42")

    # a 410 Gone (simulated via the exception path) must clear the rv
    def gone(**kw):
        raise ApiException(status=410, reason="Gone")

    with pytest.raises(ApiException):
        list(w.stream(gone))
    assert w.resource_version is None


def test_token_rotation_reread_per_request(stub, monkeypatch, tmp_path):
    """Bound SA tokens rotate on disk; the client re-reads the file per
    request so a long-lived scheduler never sends a stale token."""
    token_file = tmp_path / "token"
    token_file.write_text("token-v1")
    monkeypatch.setenv("NHD_K8S_TOKEN_FILE", str(token_file))
    stub.token = "token-v1"
    stub.add_node("n1")
    b = _backend()
    assert b.get_nodes() == ["n1"]

    # rotate: server now only accepts v2; the client must follow
    token_file.write_text("token-v2")
    stub.token = "token-v2"
    assert b.get_nodes() == ["n1"]
