"""Speculative on-device multi-round (solver/speculate.py): the packed
claim words round-trip, and the end state after a speculative batch is a
valid execution — all-placed on capacity-matched clusters, conservation
on random ones — even though placement may differ from the classic
rounds (claims are re-verified natively either way)."""

import copy
import random

import numpy as np
import pytest

from nhd_tpu.core.topology import MapMode
from nhd_tpu.sim import make_cluster
from nhd_tpu.solver import BatchItem, BatchScheduler
from tests.test_batch import items, simple_request
from tests.test_jax_matcher import random_cluster, random_request


def spec_scheduler(**kw):
    """Speculation needs the device-state path; force it on under CPU."""
    return BatchScheduler(
        respect_busy=False, register_pods=False, device_state=True,
        mesh=None, **kw,
    )


@pytest.fixture(autouse=True)
def _force_spec(monkeypatch):
    monkeypatch.setenv("NHD_TPU_SPECULATE", "1")
    # small loop depth keeps the CPU-side solves cheap; leftovers take
    # classic rounds, which is itself part of the path under test
    monkeypatch.setenv("NHD_TPU_SPEC_ITERS", "8")


def test_pack_roundtrip():
    """decode_claims inverts the device word encoding, per bucket."""
    from nhd_tpu.solver.speculate import _T_SHIFT, decode_claims
    from nhd_tpu.solver.combos import get_tables

    U, K = 2, 3
    shapes = ((1, 8), (2, 8))  # (G, Tp) buckets
    keys = (1, 2)
    a1 = get_tables(1, U, K).A
    a2 = get_tables(2, U, K).A
    claims = np.full((2, 4), -1, np.int32)
    # iteration 0: node 1 gets (bucket 1, local t=2, c=1, m=0, a=2)
    claims[0, 1] = 2 * (1 << _T_SHIFT) + (1 * U + 0) * a1 + 2
    # iteration 1: node 3 gets (bucket 2, local t=1, c=3, m=1, a=5)
    tg = 8 + 1
    claims[1, 3] = tg * (1 << _T_SHIFT) + (3 * U + 1) * a2 + 5
    out = decode_claims(claims, shapes, keys, U, K)
    assert out[1] == {2: [(1, 1, 0, 2)]}
    assert out[2] == {1: [(3, 3, 1, 5)]}


def test_speculative_places_all_on_capacity_matched():
    """The headline shape in miniature: every pod places, and almost all
    of them in the speculative round 0 (no classic retries needed)."""
    from nhd_tpu.sim.workloads import cap_cluster, workload_mix

    nodes = cap_cluster(32, ["default", "edge", "batch"])
    reqs = workload_mix(300, ["default", "edge", "batch"])
    results, stats = spec_scheduler().schedule(nodes, items(reqs), now=0.0)
    placed = sum(1 for r in results if r.node)
    assert placed == 300
    assert stats.failed == 0
    in_round0 = sum(1 for r in results if r.node and r.round_no == 0)
    assert in_round0 >= 250, f"only {in_round0}/300 placed speculatively"


def test_speculative_end_state_is_valid_and_conserving():
    """Random heterogeneous cluster: whatever the speculation proposes,
    the natively-verified end state never oversubscribes a resource.
    Totals may deviate from the classic rounds by greedy-packing noise
    (measured ±2 over 20 seeds at 60 pods, net -0.25% — documented in
    solver/speculate.py), but never materially."""
    rng = random.Random(11)
    reqs = [random_request(rng) for _ in range(60)]
    nodes_s = random_cluster(rng, 12)
    nodes_c = copy.deepcopy(nodes_s)
    capacity = {name: n.total_gpus() for name, n in nodes_s.items()}

    rs, ss = spec_scheduler().schedule(nodes_s, items(reqs), now=1010.0)
    rc, sc = BatchScheduler(
        respect_busy=False, register_pods=False, device_state=False,
        mesh=None,
    ).schedule(nodes_c, items(reqs), now=1010.0)

    assert ss.scheduled == sum(1 for r in rs if r.node)
    assert abs(ss.scheduled - sc.scheduled) <= max(2, sc.scheduled // 20), (
        f"speculative {ss.scheduled} vs classic {sc.scheduled}"
    )
    for name, n in nodes_s.items():
        assert 0 <= n.free_gpu_count() <= capacity[name]
        assert all(c >= 0 for c in n.free_cpu_cores_per_numa())
        assert n.mem.free_hugepages_gb >= 0
        for nic in n.nics:
            rx, tx = nic.free_bw()
            assert rx >= 0 and tx >= 0


def test_pci_pods_speculate_with_numa_pods():
    """PCI-map-mode pods join the megaround (r5): a mixed NUMA+PCI batch
    places entirely in the speculative round 0 — the loop projects
    per-switch GPU consumption through the static slot→switch map and
    the native verify re-picks PCI-aware against live state."""
    from dataclasses import replace

    nodes = make_cluster(4)
    reqs = [simple_request(gpus=1) for _ in range(6)]
    # PodRequest is frozen; rebuild with PCI map mode
    pci = [replace(r, map_mode=MapMode.PCI) for r in reqs[:3]]
    mixed = reqs[:3] + pci
    results, stats = spec_scheduler().schedule(nodes, items(mixed), now=0.0)
    placed = sum(1 for r in results if r.node)
    assert placed == len(mixed)
    assert {r.round_no for r in results if r.node} == {0}, [
        r.round_no for r in results
    ]
    assert stats.counters.get("rejects_r0", 0) == 0, stats.counters


def test_pci_speculation_respects_switch_capacity():
    """A PCI gang bigger than one node's switch-GPU supply must spread:
    the gpu_free_sw projection inside the loop prevents over-election on
    one node. Asserted at SWITCH granularity: no PCIe switch ever goes
    negative, every placed PCI pod's GPU shares a switch with one of its
    claimed NICs (the PCI-mode contract), and the speculative total
    matches the classic scheduler's on a copy of the cluster."""
    import copy
    from collections import Counter
    from dataclasses import replace

    nodes = make_cluster(3)
    nodes_c = copy.deepcopy(nodes)
    # per-switch capacity before any claim
    sw_cap = {
        name: Counter(g.pciesw for g in n.gpus)
        for name, n in nodes.items()
    }
    reqs = [
        replace(simple_request(gpus=1), map_mode=MapMode.PCI)
        for _ in range(9)
    ]
    results, _ = spec_scheduler().schedule(nodes, items(reqs), now=0.0)
    rc, _ = BatchScheduler(
        respect_busy=False, register_pods=False, device_state=False,
        mesh=None,
    ).schedule(nodes_c, items(reqs), now=0.0)
    placed = sum(1 for r in results if r.node)
    assert placed == sum(1 for r in rc if r.node), (
        placed, sum(1 for r in rc if r.node)
    )
    for name, n in nodes.items():
        used = Counter(g.pciesw for g in n.gpus if g.used)
        for sw, k in used.items():
            assert k <= sw_cap[name][sw], (name, sw, k, sw_cap[name])
    # PCI contract: each placed pod's GPUs sit on a switch one of its
    # claimed NICs also sits on
    for r in results:
        if not r.node or not r.nic_list:
            continue
        n = nodes[r.node]
        nic_sws = {n.nics[i].pciesw for i, _, _ in r.nic_list}
        # mapping carries numa-level info; verify via the node's used
        # GPUs instead: at least one used GPU shares a claimed NIC's
        # switch (gang-level check on a 1-GPU-per-pod workload)
        assert any(
            g.used and g.pciesw in nic_sws for g in n.gpus
        ), (r.node, nic_sws)


def test_speculative_mesh_equals_single_device():
    """The megaround runs SPMD over the 8-device mesh (GSPMD partitions
    the while_loop; the election's node-axis reductions become
    collectives) with placements BIT-IDENTICAL to the single-device
    speculative run — the multi-chip production path speculates too."""
    from nhd_tpu.sim.workloads import cap_cluster, workload_mix

    reqs = workload_mix(200, ["default", "edge", "batch"])
    outs = {}
    for label, mesh in (("mesh", "auto"), ("single", None)):
        nodes = cap_cluster(16, ["default", "edge", "batch"])
        results, stats = BatchScheduler(
            respect_busy=False, register_pods=False, device_state=True,
            mesh=mesh,
        ).schedule(nodes, items(reqs), now=0.0)
        outs[label] = (
            [(r.node, r.mapping, r.round_no) for r in results],
            stats.scheduled,
        )
    assert outs["mesh"] == outs["single"]
    assert outs["mesh"][1] == sum(
        1 for n, _, _ in outs["mesh"][0] if n
    ) > 0


def test_respect_busy_one_gpu_pod_per_node():
    """With the busy back-off on, the speculative loop must respect the
    one-GPU-pod-per-node-per-window rule exactly like classic rounds
    (reference Matcher.py:103-111)."""
    from collections import Counter

    nodes = make_cluster(3)
    reqs = [simple_request(gpus=1) for _ in range(9)]
    sched = BatchScheduler(
        respect_busy=True, register_pods=False, device_state=True,
        mesh=None,
    )
    results, stats = sched.schedule(nodes, items(reqs), now=0.0)
    per_node = Counter(r.node for r in results if r.node)
    assert all(v == 1 for v in per_node.values()), per_node
    assert sum(per_node.values()) == 3  # one per node, rest deferred


def test_saturation_certificate_matches_classic_verdict():
    """On a saturated all-NUMA cluster with uniform NIC caps, the
    megaround's no-candidate exit certifies the leftovers unschedulable
    without a classic confirmation round — and the verdict must match
    the classic scheduler's placements AND failures exactly (the
    certificate's soundness claim: projected state upper-bounds true
    state under its preconditions)."""
    import copy

    from nhd_tpu.sim.workloads import bench_cluster, workload_mix

    groups = ["default", "edge", "batch"]
    reqs = workload_mix(300, groups)
    nodes_s = bench_cluster(16, groups)   # NIC-saturated shape
    nodes_c = copy.deepcopy(nodes_s)

    rs, ss = spec_scheduler().schedule(nodes_s, items(reqs), now=0.0)
    rc, sc = BatchScheduler(
        respect_busy=False, register_pods=False, device_state=False,
        mesh=None,
    ).schedule(nodes_c, items(reqs), now=0.0)
    placed_s = sum(1 for r in rs if r.node)
    placed_c = sum(1 for r in rc if r.node)
    certified = ss.counters.get("certified_unschedulable", 0)
    # the certificate engaged and killed the confirmation round
    assert certified > 0, ss.counters
    assert ss.rounds == 1, (ss.rounds, ss.counters)
    # soundness: nothing the classic rounds can place was certified away
    assert placed_s == placed_c, (placed_s, placed_c, ss.counters)
    assert certified == 300 - placed_s


def test_saturation_certificate_disabled_on_nonuniform_nic_caps():
    """A node whose NICs have different speeds voids the certificate's
    free-NIC-count argument: the dispatch must fall back to the classic
    confirmation round instead of certifying."""
    from nhd_tpu.sim import SynthNodeSpec, make_node
    from nhd_tpu.sim.workloads import workload_mix

    nodes = {}
    for i in range(4):
        spec = SynthNodeSpec(name=f"mix{i}", nics_per_numa=2)
        node = make_node(spec)
        node.nics[0].speed_gbps = node.nics[0].speed_gbps / 2  # mixed caps
        nodes[spec.name] = node
    reqs = workload_mix(120, ["default"])
    results, stats = spec_scheduler().schedule(nodes, items(reqs), now=0.0)
    assert "certified_unschedulable" not in stats.counters, stats.counters
    # the saturated leftovers took (at least) a confirmation round
    if sum(1 for r in results if r.node) < 120:
        assert stats.rounds >= 2
