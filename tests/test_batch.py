"""Batch scheduler tests: serializability, gang spread, oracle agreement."""

import copy
import random

import pytest

from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
from nhd_tpu.core.topology import MapMode, SmtMode
from nhd_tpu.sim import SynthNodeSpec, make_cluster
from nhd_tpu.sim.requests import request_to_topology
from nhd_tpu.solver import BatchItem, BatchScheduler, find_node


def simple_request(gpus=0, rx=10.0, proc=4) -> PodRequest:
    return PodRequest(
        groups=(
            GroupRequest(
                proc=CpuRequest(proc, SmtMode.ON),
                misc=CpuRequest(1, SmtMode.ON),
                gpus=gpus,
                nic_rx_gbps=rx,
                nic_tx_gbps=5.0,
            ),
        ),
        misc=CpuRequest(1, SmtMode.ON),
        hugepages_gb=2,
        map_mode=MapMode.NUMA,
    )


def items(reqs):
    return [BatchItem(("ns", f"pod{i}"), r) for i, r in enumerate(reqs)]


def test_single_item_matches_oracle():
    nodes = make_cluster(4)
    ref_nodes = copy.deepcopy(nodes)
    req = simple_request(gpus=1)
    sched = BatchScheduler(respect_busy=False)
    results, stats = sched.schedule(nodes, items([req]), now=0.0)
    want = find_node(ref_nodes, req, now=0.0, respect_busy=False)
    assert results[0].node == want.node
    assert results[0].mapping == want.mapping
    assert stats.scheduled == 1


def test_sequential_agreement_identical_pods():
    """A gang of identical pods scheduled in batch lands the same total as
    the strict sequential oracle loop on an identical cluster."""
    batch_nodes = make_cluster(4)
    seq_nodes = copy.deepcopy(batch_nodes)
    reqs = [simple_request(gpus=1) for _ in range(40)]

    sched = BatchScheduler(respect_busy=False)
    results, stats = sched.schedule(batch_nodes, items(reqs), now=0.0)
    batch_count = sum(1 for r in results if r.node)

    seq_count = 0
    for r in reqs:
        m = find_node(seq_nodes, r, now=0.0, respect_busy=False)
        if m is None:
            continue
        top = request_to_topology(r)
        seq_nodes[m.node].assign_physical_ids(m.mapping, top)
        nidx = sorted({i for i, n in enumerate(seq_nodes[m.node].nics)
                       if n.mac in {p.mac for p in top.nic_pairs}})
        seq_nodes[m.node].claim_nic_pods(nidx)
        seq_count += 1

    assert batch_count == seq_count > 0
    # end-state resource totals agree cluster-wide
    batch_free = sorted(
        (sum(n.free_cpu_cores_per_numa()), n.free_gpu_count())
        for n in batch_nodes.values()
    )
    seq_free = sorted(
        (sum(n.free_cpu_cores_per_numa()), n.free_gpu_count())
        for n in seq_nodes.values()
    )
    assert batch_free == seq_free


def test_gang_packs_capacity_per_round():
    """Identical pods pack each candidate node up to the per-round capacity
    estimate before spilling to the next — the reference's first-fit
    packing shape (sequential Matcher always returns the first feasible
    node), realized a round at a time."""
    nodes = make_cluster(8)
    reqs = [simple_request() for _ in range(8)]
    sched = BatchScheduler(respect_busy=False)
    results, stats = sched.schedule(nodes, items(reqs), now=0.0)
    placed = [r.node for r in results]
    assert all(placed)
    assert stats.rounds == 1
    # multiple pods per node, filling early nodes first
    used = sorted(set(placed))
    assert len(used) < 8
    assert used == sorted(nodes.keys())[: len(used)]


def test_round_path_equals_per_pod_path():
    """The native round call and the per-pod assignment path must place a
    contended gang identically (both re-select NIC picks live)."""
    import copy

    from nhd_tpu.solver import fast_assign

    reqs = [simple_request(gpus=i % 2, proc=2 + 2 * (i % 3)) for i in range(30)]
    nodes_round = make_cluster(3)
    nodes_pp = copy.deepcopy(nodes_round)

    r1, s1 = BatchScheduler(respect_busy=False).schedule(
        nodes_round, items(reqs), now=0.0
    )
    orig = fast_assign.FastCluster.round_ok_for
    fast_assign.FastCluster.round_ok_for = lambda self, pods: False
    try:
        r2, s2 = BatchScheduler(respect_busy=False).schedule(
            nodes_pp, items(reqs), now=0.0
        )
    finally:
        fast_assign.FastCluster.round_ok_for = orig

    assert [r.node for r in r1] == [r.node for r in r2]
    assert [r.mapping for r in r1] == [r.mapping for r in r2]
    assert s1.scheduled == s2.scheduled


@pytest.mark.parametrize("seed", range(15))
def test_pci_single_pod_batch_superset_of_oracle(seed):
    """PCI-mode batch parity (docs/PARITY.md 'Batch-mode extensions'):
    for single-pod batches the batch must place everything the oracle
    places (same node), must never invent feasibility the oracle lacks,
    and may additionally place pods the oracle match-then-fails on (the
    PCI quirk) — the documented strict improvement."""
    import dataclasses

    from nhd_tpu.core.node import AssignmentError
    from nhd_tpu.core.topology import MapMode
    from nhd_tpu.sim.requests import request_to_topology
    from tests.test_jax_matcher import random_cluster, random_request

    rng = random.Random(7000 + seed)
    base = random_cluster(rng, 5)
    for _ in range(6):
        req = dataclasses.replace(random_request(rng), map_mode=MapMode.PCI)

        nodes_o = copy.deepcopy(base)
        m = find_node(nodes_o, req, now=1010.0, respect_busy=False)
        oracle_outcome = None
        if m is not None:
            try:
                top = request_to_topology(req)
                nodes_o[m.node].assign_physical_ids(m.mapping, top)
                oracle_outcome = m.node
            except (AssignmentError, ValueError):
                oracle_outcome = "QUIRK_FAIL"

        nodes_b = copy.deepcopy(base)
        results, _ = BatchScheduler(respect_busy=False).schedule(
            nodes_b, items([req]), now=1010.0
        )
        got = results[0].node

        if m is None:
            assert got is None, (
                f"batch invented feasibility the oracle lacks: {req}"
            )
        elif oracle_outcome == "QUIRK_FAIL":
            # improvement allowed, not required; placements must be sound
            if got is not None:
                n = nodes_b[got]
                assert n.free_gpu_count() >= 0
                assert all(c >= 0 for c in n.free_cpu_cores_per_numa())
        else:
            assert got == oracle_outcome, (
                f"oracle placed on {oracle_outcome}, batch on {got}"
            )


def test_busy_backoff_limits_gpu_pods_per_node():
    nodes = make_cluster(2)
    reqs = [simple_request(gpus=1) for _ in range(6)]
    sched = BatchScheduler(respect_busy=True)
    results, _ = sched.schedule(nodes, items(reqs), now=0.0)
    placed = [r.node for r in results if r.node]
    # one GPU pod per node per busy window
    assert len(placed) == 2
    assert len(set(placed)) == 2


def test_no_double_booking_under_pressure():
    """Saturate a small cluster with a mixed batch; core/GPU books must
    balance exactly (each core at most one owner)."""
    rng = random.Random(7)
    nodes = make_cluster(3, SynthNodeSpec(phys_cores=16, hugepages_gb=32))
    reqs = []
    for _ in range(60):
        reqs.append(
            simple_request(
                gpus=rng.choice([0, 1]),
                rx=rng.choice([5.0, 20.0]),
                proc=rng.choice([2, 4, 6]),
            )
        )
    sched = BatchScheduler(respect_busy=False)
    batch_items = [
        BatchItem(("ns", f"p{i}"), r, request_to_topology(r))
        for i, r in enumerate(reqs)
    ]
    results, _ = sched.schedule(nodes, batch_items, now=0.0)

    # every scheduled pod's cores are disjoint and within bounds per node
    per_node_cores = {}
    for item, res in zip(batch_items, results):
        if not res.node:
            continue
        cores = [c.core for pg in item.topology.proc_groups
                 for c in pg.proc_cores + pg.misc_cores]
        cores += [c.core for pg in item.topology.proc_groups
                  for g in pg.gpus for c in g.cpu_cores]
        cores += [c.core for c in item.topology.misc_cores]
        seen = per_node_cores.setdefault(res.node, set())
        assert len(cores) == len(set(cores))
        assert not (seen & set(cores)), "core double-booked across pods"
        seen.update(cores)

    # node mirrors agree with the sum of handed-out cores
    for name, node in nodes.items():
        used = {c.core for c in node.cores if c.used and c.core not in node.reserved_cores}
        assert per_node_cores.get(name, set()) == used


def test_unschedulable_marked_none():
    nodes = make_cluster(1, SynthNodeSpec(gpus_per_numa=0))
    reqs = [simple_request(gpus=1)]
    sched = BatchScheduler(respect_busy=False)
    results, stats = sched.schedule(nodes, items(reqs), now=0.0)
    assert results[0].node is None
    assert stats.scheduled == 0


def test_dry_run_reports_snapshot_matches():
    """apply=False: every pod reports its snapshot match — identical pods
    all name the same node, and nothing is mutated."""
    nodes = make_cluster(2)
    before = {k: sum(n.free_cpu_cores_per_numa()) for k, n in nodes.items()}
    reqs = [simple_request() for _ in range(5)]
    results, _ = BatchScheduler(respect_busy=False).schedule(
        nodes, items(reqs), now=0.0, apply=False
    )
    assert all(r.node == results[0].node for r in results)
    assert results[0].node is not None
    after = {k: sum(n.free_cpu_cores_per_numa()) for k, n in nodes.items()}
    assert before == after


def test_unrepresentable_request_fails_cleanly():
    """A 1-proc-core group with NIC bandwidth can't synthesize a topology;
    the pod must fail alone, not crash the batch."""
    from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
    from nhd_tpu.core.topology import MapMode, SmtMode

    weird = PodRequest(
        groups=(
            GroupRequest(CpuRequest(1, SmtMode.ON), CpuRequest(0, SmtMode.OFF),
                         0, 5.0, 0.0),
        ),
        misc=CpuRequest(0, SmtMode.OFF),
        hugepages_gb=0,
        map_mode=MapMode.NUMA,
    )
    nodes = make_cluster(2)
    reqs = [simple_request(), weird, simple_request()]
    results, stats = BatchScheduler(respect_busy=False).schedule(
        nodes, items(reqs), now=0.0
    )
    assert results[0].node and results[2].node
    # the weird pod is still *scheduled* on the fast path (claims applied);
    # only its bookkeeping registration is skipped
    assert results[1].node is not None


def test_device_state_path_equivalent():
    """The four device-state/mesh corners must agree exactly: host arrays
    (device_state off, mesh off), forced single-device resident arrays, and
    the sharded mesh path (the 8-device suite default)."""
    import pytest

    reqs = [simple_request(gpus=i % 2) for i in range(40)]
    outs = {}
    for label, kw in (
        ("host", dict(device_state=False, mesh=None)),
        ("resident", dict(device_state=True, mesh=None)),
        ("mesh", dict(device_state="auto", mesh="auto")),
    ):
        nodes = make_cluster(4)
        results, stats = BatchScheduler(
            respect_busy=False, **kw
        ).schedule(nodes, items(reqs), now=0.0)
        outs[label] = (
            [r.node for r in results],
            [r.mapping for r in results],
            stats.scheduled,
        )
    assert outs["host"] == outs["resident"] == outs["mesh"]

    with pytest.raises(ValueError):
        BatchScheduler(device_state="true")


def test_headless_round_path_preserves_busy_and_niclist():
    """register_pods=False + no topologies (the benchmark shape): scheduled
    pods must still stamp their nodes busy on the HostNode mirror and carry
    a consumed-NIC list."""
    nodes = make_cluster(2)
    reqs = [simple_request(gpus=1) for _ in range(2)]
    sched = BatchScheduler(respect_busy=True, register_pods=False)
    results, _ = sched.schedule(nodes, items(reqs), now=1000.0)
    placed = [r for r in results if r.node]
    assert len(placed) == 2
    for r in placed:
        assert r.nic_list, "consumed-NIC list missing in headless mode"
        assert nodes[r.node].is_busy(now=1010.0), "node not stamped busy"
    # a second GPU batch inside the busy window schedules nothing
    results2, _ = sched.schedule(
        nodes, [BatchItem(("ns", "late"), simple_request(gpus=1))], now=1010.0
    )
    assert results2[0].node is None


def test_rank_cap_exhaustion_only_costs_rounds(monkeypatch):
    """A type needing more candidate nodes than the rank width R still
    places everything — exhausted candidates roll to later rounds
    (kernel.rank_cap's correctness claim)."""
    monkeypatch.setenv("NHD_TPU_RANK_CAP", "64")
    from nhd_tpu.sim import make_cluster

    nodes = make_cluster(128)
    reqs = [simple_request() for _ in range(400)]
    results, stats = BatchScheduler(
        respect_busy=False, register_pods=False
    ).schedule(nodes, items(reqs), now=0.0)
    assert sum(1 for r in results if r.node) == 400
    # more than 64 distinct nodes were needed overall
    assert len({r.node for r in results}) > 64
