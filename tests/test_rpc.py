"""gRPC stats plane tests: real server + client over localhost.

Replaces the reference's manual live-cluster script (test/RPCTest.py) with
an asserting, hermetic round trip: fake cluster → scheduler → gRPC server
→ client.
"""

import json
import queue
import threading

import pytest

grpc = pytest.importorskip("grpc")

from nhd_tpu.rpc.server import NHDControlClient, StatsRpcServer
from nhd_tpu.rpc import nhd_stats_pb2 as pb
from tests.test_scheduler import make_backend, make_scheduler, pod_cfg


@pytest.fixture
def stack():
    backend = make_backend(n_nodes=2)
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()

    # scheduler loop thread answering RPC queue requests
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            try:
                item = sched.rpcq.get(timeout=0.05)
            except queue.Empty:
                continue
            sched._parse_rpc_req(*item)

    pump_thread = threading.Thread(target=pump, daemon=True)
    pump_thread.start()

    server = StatsRpcServer(sched.rpcq, port=0)  # ephemeral port
    server.start()
    client = NHDControlClient(f"localhost:{server.bound_port}")
    yield backend, sched, client
    client.close()
    server.stop()
    stop.set()


def test_basic_node_stats(stack):
    backend, sched, client = stack
    reply = client.get_basic_node_stats()
    assert reply.status == pb.NHD_STATUS_OK
    assert len(reply.info) == 2
    by_name = {i.name: i for i in reply.info}
    n0 = by_name["node0"]
    assert n0.total_pods == 1
    assert n0.used_gpus == 1
    assert n0.used_hugepages == 4
    assert n0.active
    assert len(n0.nic_info) == 4
    assert sum(i.used_rx for i in n0.nic_info) == 10  # 10 Gbps rx claimed


def test_scheduler_stats(stack):
    _, sched, client = stack
    reply = client.get_scheduler_stats()
    assert reply.status == pb.NHD_STATUS_OK
    assert reply.failed_schedule_count == 0


def test_pod_stats(stack):
    backend, sched, client = stack
    reply = client.get_pod_stats()
    assert reply.status == pb.NHD_STATUS_OK
    assert len(reply.info) == 1
    info = reply.info[0]
    assert info.name == "triad-0"
    assert info.node == "node0"
    assert info.hugepages == 4
    assert len(info.gpus) == 1
    assert all(c >= 0 for c in info.proc_cores)
    assert any("nhd_config" in k for k in info.annotations)


def test_detailed_node_stats(stack):
    _, _, client = stack
    reply = client.get_detailed_node_stats("node0")
    assert reply.status == pb.NHD_STATUS_OK
    assert reply.name == "node0"
    assert len(reply.podinfo) == 1
    empty = client.get_detailed_node_stats("node1")
    assert empty.status == pb.NHD_STATUS_OK
    assert len(empty.podinfo) == 0


def test_recent_decisions_roundtrip(stack):
    """The flight-recorder decisions view over the gRPC plane (JSON-over-
    bytes generic method — no generated stubs on this image)."""
    import nhd_tpu.obs as obs

    _, _, client = stack
    out = client.get_recent_decisions()
    assert out == {"enabled": False, "decisions": []}
    rec = obs.enable(capacity=64)
    try:
        rec.record_decision({
            "pod": "p0", "ns": "default", "corr": "c-grpc",
            "outcome": "scheduled", "node": "node0", "phases": {},
        })
        rec.record_decision({
            "pod": "p1", "ns": "default", "corr": "c-grpc2",
            "outcome": "unschedulable", "node": None, "phases": {},
        })
        out = client.get_recent_decisions(n=1)
        assert out["enabled"] is True
        assert [d["pod"] for d in out["decisions"]] == ["p1"]  # newest
        # malformed "n" degrades to the default instead of erroring
        raw = client._calls["GetRecentDecisions"](b'{"n": null}')
        assert len(json.loads(raw.decode())["decisions"]) == 2
    finally:
        obs.disable()


def test_scheduler_unresponsive_returns_err(monkeypatch):
    """A dead scheduler thread yields NHD_STATUS_ERR, not a hang
    (reference behavior: 5s reply timeout, NHDRpcServer.py:58)."""
    import nhd_tpu.rpc as rpc_pkg

    monkeypatch.setattr(rpc_pkg, "RPC_TIMEOUT_SEC", 0.2)
    # a handler pointed at a queue nobody drains
    dead = StatsRpcServer(queue.Queue(), port=0)
    dead.start()
    try:
        c = NHDControlClient(f"localhost:{dead.bound_port}")
        grpc.channel_ready_future(c.channel).result(timeout=5)
        reply = c.get_basic_node_stats()
        assert reply.status == pb.NHD_STATUS_ERR
        reply2 = c.get_scheduler_stats()
        assert reply2.status == pb.NHD_STATUS_ERR
        c.close()
    finally:
        dead.stop()
