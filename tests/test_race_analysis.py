"""nhdrace static pack (NHD81x): project-level behaviors.

Complements tests/test_static_analysis.py (which owns the per-fixture
EXPECT comparisons and the tier-1 gate): the tests here mutate one
module of a consistent multi-module fixture project and assert the
finding blames the right field, root, and rule — including the
cross-module ctor-callable edge (heartbeat bound at construction) that
makes the pipe worker a second writer of loop state. The live-tree
tests pin the model facts the heartbeat fix (Scheduler._hb_lock) and
the dynamic sanitizer's witness join depend on.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List

from nhd_tpu.analysis.core import ModuleSource
from nhd_tpu.analysis.ownership import build_model
from nhd_tpu.analysis.rules_races import check_project

REPO = Path(__file__).resolve().parent.parent
PROJECT = Path(__file__).resolve().parent / "fixtures" / "analysis" \
    / "nhd_tpu" / "races_project"


def _load_project(overrides: Dict[str, tuple] | None = None) -> List[ModuleSource]:
    """The races fixture project, optionally with per-file text
    replacements applied (old -> new, must hit exactly once)."""
    overrides = overrides or {}
    modules = []
    for path in sorted(PROJECT.glob("*.py")):
        src = path.read_text()
        if path.name in overrides:
            old, new = overrides[path.name]
            assert src.count(old) == 1, f"ambiguous mutation in {path.name}"
            src = src.replace(old, new)
        modules.append(ModuleSource(path.as_posix(), src, ast.parse(src)))
    return modules


def _only(findings, rule):
    return [f for f in findings if f.rule == rule]


def test_project_is_consistent_as_shipped():
    assert check_project(_load_project()) == []


# ---------------------------------------------------------------------------
# model facts the rules build on
# ---------------------------------------------------------------------------

def test_model_resolves_ctor_bound_heartbeat_across_modules():
    """Pipe(heartbeat=self._beat) + self._hb() on the worker thread must
    make Loop._beat reachable from the pipe root — the exact shape of
    the real CommitPipeline binding."""
    model = build_model(_load_project())
    beat = next(q for q in model.analysis.funcs if q.endswith("Loop._beat"))
    roots = model.roots_of[beat]
    assert any(r.endswith("pipe_like:Pipe._run") for r in roots), roots
    assert any(r.endswith("sched_like:Loop.run") for r in roots), roots


def test_model_inventories_all_roots():
    model = build_model(_load_project())
    kinds = {rid: r.kind for rid, r in model.roots.items()}
    assert any(k.endswith("Loop.run") for k in kinds)
    assert any(k.endswith("Loop._janitor") for k in kinds)
    assert any(k.endswith("Pipe._run") for k in kinds)
    assert any(k.endswith("StatsHandler.do_GET") for k in kinds)


def test_handler_instance_state_is_not_shared():
    """do_GET runs once per connection on its own handler instance:
    close_connection must not enter the shared-field registry (the
    apistub false positive, pinned)."""
    model = build_model(_load_project())
    assert not any(
        k.endswith("StatsHandler.close_connection")
        for k in model.shared_fields()
    )


def test_locked_globals_share_a_consistent_lockset():
    model = build_model(_load_project())
    shared = model.shared_fields()
    hits = next(k for k in shared if k.endswith("stats_like:HITS"))
    held = [a.held for a in shared[hits]]
    assert all(held), held          # every access under stats LOCK


# ---------------------------------------------------------------------------
# one injected defect at a time, each blaming the right site
# ---------------------------------------------------------------------------

def test_unlocking_the_heartbeat_is_nhd812_naming_the_pipe_root():
    findings = check_project(_load_project({
        "sched_like.py": (
            "        with self.hb_lock:\n            self.last_beat += 1.0",
            "        self.last_beat += 1.0",
        ),
    }))
    hits = _only(findings, "NHD812")
    assert len(hits) == 1, findings
    assert "Loop.last_beat" in hits[0].message
    assert "Pipe._run" in hits[0].message


def test_non_owner_write_is_nhd811():
    findings = check_project(_load_project({
        "sched_like.py": (
            "        # owner-only bookkeeping advances here",
            "        self.mirror_epoch = 0",
        ),
    }))
    hits = _only(findings, "NHD811")
    assert len(hits) == 1, findings
    assert "Loop.mirror_epoch" in hits[0].message
    assert "Pipe._run" in hits[0].message
    # the owner's own unlocked write in run() stays exempt
    assert not _only(findings, "NHD810")


def test_unlocked_global_write_is_nhd810():
    findings = check_project(_load_project({
        "stats_like.py": (
            "    with LOCK:\n        LAST_STATUS = status",
            "    LAST_STATUS = status",
        ),
    }))
    hits = _only(findings, "NHD810")
    assert len(hits) == 1, findings
    assert "stats_like:LAST_STATUS" in hits[0].message


def test_unlocked_global_increment_is_nhd812():
    findings = check_project(_load_project({
        "stats_like.py": (
            "    with LOCK:\n        HITS += 1",
            "    HITS += 1",
        ),
    }))
    hits = _only(findings, "NHD812")
    assert len(hits) == 1, findings
    assert "stats_like:HITS" in hits[0].message


def test_raw_publish_of_mutable_table_is_nhd813():
    findings = check_project(_load_project({
        "sched_like.py": (
            "args=(dict(self.table),)",
            "args=(self.table,)",
        ),
    }))
    hits = _only(findings, "NHD813")
    assert len(hits) == 1, findings
    assert "Loop.table" in hits[0].message


# ---------------------------------------------------------------------------
# live tree: the model facts behind the shipped fix and the runtime join
# ---------------------------------------------------------------------------

def _load_live() -> List[ModuleSource]:
    modules = []
    for path in sorted((REPO / "nhd_tpu").rglob("*.py")):
        src = path.read_text()
        modules.append(ModuleSource(path.as_posix(), src, ast.parse(src)))
    return modules


def test_live_tree_heartbeat_facts():
    """Pins the chain behind the Scheduler._hb_lock fix: the commitpipe
    worker reaches _beat through the ctor binding, last_heartbeat is in
    the shared registry, and every write now holds the lock (regressing
    any of these reopens the NHD811)."""
    model = build_model(_load_live())
    beat = "scheduler/core:Scheduler._beat"
    assert "scheduler/commitpipe:CommitPipeline._run" in model.roots_of[beat]
    key = "scheduler/core:Scheduler.last_heartbeat"
    shared = model.shared_fields()
    assert key in shared
    writes = [a for a in shared[key] if a.flavor != "read"]
    assert writes and all(
        any(h.endswith("Scheduler._hb_lock") for h in a.held) for a in writes
    ), writes


def test_live_tree_field_keys_join_runtime_keys():
    """Static field keys are 'mod/label:Class.attr' — the exact strings
    nhd_tpu.sanitizer.races.field_key() emits, so a runtime witness
    names its static registry entry."""
    from nhd_tpu.sanitizer.races import field_key
    from nhd_tpu.scheduler.core import Scheduler
    model = build_model(_load_live())
    key = field_key(Scheduler, "last_heartbeat")
    assert key == "scheduler/core:Scheduler.last_heartbeat"
    assert key in model.fields
