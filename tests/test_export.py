"""AOT export round-trip: the TPU program artifact (tools/export_tpu.py)
deserializes, carries both platforms, and — because the artifact includes
a CPU lowering alongside the TPU one — executes on CPU bit-identically
to the live jitted solver. Pins the artifact contract for the day the
wedged tunnel (docs/TPU_STATUS.md) comes back."""

import json
import os

import numpy as np
import pytest

from tools.export_tpu import (
    build_headline_buckets,
    export_ranked_solver,
    export_solver,
    register_solveout_serialization,
)


@pytest.fixture(scope="module")
def exported_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("artifacts")
    buckets = build_headline_buckets()
    metas = export_solver(str(out), buckets)
    ranked = export_ranked_solver(str(out), buckets)
    return out, metas, ranked


def test_export_metadata(exported_dir):
    out, metas, ranked = exported_dir
    assert metas and ranked, "no buckets exported"
    for meta in metas + ranked:
        assert meta["platforms"] == ["cpu", "tpu"]
        assert meta["bytes"] > 0
        path = out / meta["artifact"]
        assert path.exists() and path.stat().st_size == meta["bytes"]
        side = json.loads((out / meta["artifact"].replace(
            ".stablehlo.bin", ".json")).read_text())
        assert side["bucket"] == meta["bucket"]


def test_roundtrip_executes_and_matches_live_solver(exported_dir):
    from jax import export as jexport

    from nhd_tpu.solver.kernel import get_solver

    out, metas, _ = exported_dir
    register_solveout_serialization()
    buckets = {tuple(m["bucket"].values()): m for m in metas}
    for args, meta in build_headline_buckets():
        b = meta["bucket"]
        blob = (out / buckets[(b["G"], b["U"], b["K"])]["artifact"]).read_bytes()
        exported = jexport.deserialize(bytearray(blob))
        got = exported.call(*args)
        want = get_solver(b["G"], b["U"], b["K"])(*args)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.array(g), np.array(w))


def test_repo_artifacts_committed():
    """The checked-in artifacts/ copies deserialize and match the current
    solver's bucket inventory (regenerate via tools/export_tpu.py)."""
    art = os.path.join(os.path.dirname(os.path.dirname(__file__)), "artifacts")
    metas = [f for f in os.listdir(art) if f.endswith(".json")]
    bins = [f for f in os.listdir(art) if f.endswith(".stablehlo.bin")]
    assert metas and len(metas) == len(bins)
    register_solveout_serialization()
    from jax import export as jexport

    for m in metas:
        meta = json.load(open(os.path.join(art, m)))
        blob = open(os.path.join(art, meta["artifact"]), "rb").read()
        exported = jexport.deserialize(bytearray(blob))
        assert list(exported.platforms) == ["cpu", "tpu"]


def test_ranked_roundtrip_matches_live_ranked_solver(exported_dir):
    """The PRODUCTION artifact (solve fused with on-device top-R ranking)
    executes on CPU bit-identically to the live fused program — pins the
    free-array argument indices and the RankOut serialization."""
    from jax import export as jexport

    from nhd_tpu.solver.kernel import get_ranked_solver

    out, _, ranked = exported_dir
    by_bucket = {tuple(m["bucket"].values()): m for m in ranked}
    for args, meta in build_headline_buckets():
        b = meta["bucket"]
        m = by_bucket[(b["G"], b["U"], b["K"])]
        blob = (out / m["artifact"]).read_bytes()
        exported = jexport.deserialize(bytearray(blob))
        got = exported.call(*args)
        want = get_ranked_solver(b["G"], b["U"], b["K"], m["rank_width"])(
            *args
        )
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.array(g), np.array(w))
