"""FastCluster assignment must match HostNode.assign_physical_ids exactly."""

import copy
import random

from nhd_tpu.sim import SynthNodeSpec, make_cluster
from nhd_tpu.sim.requests import request_to_topology
from nhd_tpu.solver import BatchItem, BatchScheduler, find_node
from nhd_tpu.solver.encode import encode_cluster
from nhd_tpu.solver.fast_assign import FastCluster, apply_record_to_topology
from tests.test_jax_matcher import random_cluster, random_request


def state_fingerprint(nodes):
    out = {}
    for name, n in nodes.items():
        out[name] = (
            tuple(c.used for c in n.cores),
            tuple(g.used for g in n.gpus),
            tuple((tuple(x.speed_used), x.pods_used) for x in n.nics),
            n.mem.free_hugepages_gb,
        )
    return out


def test_fast_assign_matches_object_path():
    rng = random.Random(42)
    for trial in range(15):
        nodes_a = random_cluster(rng, 4)
        nodes_b = copy.deepcopy(nodes_a)
        req = random_request(rng)
        m = find_node(nodes_a, req, now=1010.0, respect_busy=False)
        if m is None:
            continue

        # object path (assign + the scheduler's separate NIC pod claim,
        # reference NHDScheduler.py:292-304)
        top_a = request_to_topology(req)
        try:
            nic_list = nodes_a[m.node].assign_physical_ids(m.mapping, top_a)
            nodes_a[m.node].claim_nic_pods(sorted({x[0] for x in nic_list}))
            a_failed = False
        except Exception:
            a_failed = True

        # fast path on the clone
        arrays = encode_cluster(nodes_b, now=1010.0)
        fast = FastCluster(nodes_b, arrays.U, arrays.K)
        n_idx = arrays.names.index(m.node)
        top_b = request_to_topology(req)
        try:
            rec = fast.assign(n_idx, m.mapping, req)
            b_failed = False
        except Exception:
            b_failed = True

        assert a_failed == b_failed, f"trial {trial}: divergent failure"
        if a_failed:
            continue
        fast.sync_to_nodes()
        apply_record_to_topology(rec, top_b)

        fp_a = state_fingerprint(nodes_a)
        fp_b = state_fingerprint(nodes_b)
        assert fp_a == fp_b, f"trial {trial}: node state diverged"

        def ids(top):
            return (
                [[c.core for c in pg.proc_cores] for pg in top.proc_groups],
                [[c.core for c in pg.misc_cores] for pg in top.proc_groups],
                [[(g.device_id, [c.core for c in g.cpu_cores]) for g in pg.gpus]
                 for pg in top.proc_groups],
                [c.core for c in top.misc_cores],
                [p.mac for p in top.nic_pairs],
                top.data_default_gw,
            )

        assert ids(top_a) == ids(top_b), f"trial {trial}: topology fill diverged"


def test_batch_fast_vs_object_paths_agree():
    """Whole-batch outcomes identical between fast and object assignment.

    Uses GPU pods under the busy back-off so every round claims at most one
    pod per node: in that regime the object path (which keeps the
    reference's snapshot NIC pick, no live re-selection) is defined to
    behave identically to the fast paths."""
    from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
    from nhd_tpu.core.topology import MapMode, SmtMode

    def gpu_req(i):
        return PodRequest(
            groups=(GroupRequest(CpuRequest(2 + (i % 3), SmtMode.ON),
                                 CpuRequest(1, SmtMode.ON), 1, 10.0, 5.0),),
            misc=CpuRequest(1, SmtMode.ON), hugepages_gb=2,
            map_mode=MapMode.NUMA,
        )

    reqs = [gpu_req(i) for i in range(10)]
    nodes_fast = make_cluster(4, SynthNodeSpec(phys_cores=16))
    nodes_obj = copy.deepcopy(nodes_fast)
    items_f = [BatchItem(("ns", f"p{i}"), r) for i, r in enumerate(reqs)]
    items_o = [BatchItem(("ns", f"p{i}"), r) for i, r in enumerate(reqs)]

    rf, sf = BatchScheduler(respect_busy=True, use_fast=True).schedule(
        nodes_fast, items_f, now=0.0
    )
    ro, so = BatchScheduler(respect_busy=True, use_fast=False).schedule(
        nodes_obj, items_o, now=0.0
    )
    assert [r.node for r in rf] == [r.node for r in ro]
    assert [r.mapping for r in rf] == [r.mapping for r in ro]
    assert state_fingerprint(nodes_fast) == state_fingerprint(nodes_obj)
    assert sf.scheduled == so.scheduled
