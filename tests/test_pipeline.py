"""Round-pipelining determinism + overlapped-commit pipeline (r14).

Three surfaces:

1. **Pipeline determinism** — seeded property tests pinning bit-exact
   placements (node, mapping, NIC list, round, failure verdict) for
   ``NHD_PIPELINE=1`` vs ``=0`` across the classic, speculative,
   mesh-sharded and streaming postures: prelaunching round r+1's solves
   before round r's host phases must be a pure reordering.
2. **Device-faults × pipelining** — the `make device-chaos` extension:
   a fault landing mid-prelaunch (the guard's prelaunch boundary) still
   ends in a bound set bit-identical to a fault-free NHD_PIPELINE=0 run
   of the same seed.
3. **Overlapped fenced commit** (scheduler/commitpipe.py,
   NHD_ASYNC_COMMIT): binds land through the bounded in-order pipeline,
   outcomes are processed on the single-writer thread at drain points,
   transient failures still requeue, per-node order is preserved, and
   the watchdog heartbeat advances per drained commit.
"""

import queue
import threading
import time

from nhd_tpu.k8s.fake import FakeClusterBackend
from nhd_tpu.k8s.interface import TransientBackendError
from nhd_tpu.k8s.retry import API_COUNTERS
from nhd_tpu.scheduler.commitpipe import CommitPipeline, CommitUnit
from nhd_tpu.scheduler.controller import Controller
from nhd_tpu.scheduler.core import PodStatus, Scheduler
from nhd_tpu.scheduler.events import WatchQueue
from nhd_tpu.sim import SynthNodeSpec, make_node_labels, make_triad_config
from nhd_tpu.sim.workloads import cap_cluster, workload_mix
from nhd_tpu.solver import BatchItem, BatchScheduler
from nhd_tpu.solver.guard import GUARD

GROUPS = ["default", "edge"]


def _placements(results):
    return [
        (
            r.key, r.node,
            None if r.mapping is None else dict(r.mapping),
            tuple(r.nic_list or ()), r.round_no, r.failed,
        )
        for r in results
    ]


def _schedule_once(pipeline, monkeypatch, *, posture, n_pods=96, n_nodes=12):
    """One deterministic gang schedule under the given pipeline setting
    and solver posture; returns the placement fingerprint."""
    monkeypatch.setenv("NHD_PIPELINE", pipeline)
    nodes = cap_cluster(n_nodes, GROUPS)
    reqs = workload_mix(n_pods, GROUPS)
    items = [BatchItem(("ns", f"p{i}"), r) for i, r in enumerate(reqs)]
    if posture == "classic":
        monkeypatch.setenv("NHD_TPU_SPECULATE", "0")
        sched = BatchScheduler(
            respect_busy=False, register_pods=False, device_state=False,
        )
        results, stats = sched.schedule(nodes, items, now=0.0)
    elif posture == "speculative":
        monkeypatch.setenv("NHD_TPU_SPECULATE", "1")
        sched = BatchScheduler(
            respect_busy=False, register_pods=False, device_state=True,
            mesh=None,
        )
        results, stats = sched.schedule(nodes, items, now=0.0)
    elif posture == "mesh":
        import jax

        from nhd_tpu.parallel.sharding import make_mesh

        monkeypatch.setenv("NHD_TPU_SPECULATE", "0")
        sched = BatchScheduler(
            respect_busy=False, register_pods=False, device_state=True,
            mesh=make_mesh(jax.devices()[:2]),
        )
        results, stats = sched.schedule(nodes, items, now=0.0)
    elif posture == "streaming":
        from nhd_tpu.solver.streaming import StreamingScheduler

        monkeypatch.setenv("NHD_TPU_SPECULATE", "0")
        sched = StreamingScheduler(
            tile_nodes=4, chunk_pods=48, placement="first-fit",
            respect_busy=False, register_pods=False, persistent=True,
        )
        results, stats = sched.schedule(nodes, items, now=0.0)
    else:  # pragma: no cover - test bug
        raise AssertionError(posture)
    assert stats.scheduled > 0  # the posture actually placed pods
    if pipeline == "1" and posture != "streaming":
        # the pipeline genuinely engaged (multi-round workloads only;
        # one-round batches have nothing to prelaunch)
        assert (
            stats.rounds <= 1
            or stats.counters.get("prelaunched_rounds", 0) > 0
        )
    return _placements(results)


def test_pipeline_parity_classic(monkeypatch):
    a = _schedule_once("1", monkeypatch, posture="classic")
    b = _schedule_once("0", monkeypatch, posture="classic")
    assert a == b


def test_pipeline_parity_speculative(monkeypatch):
    a = _schedule_once("1", monkeypatch, posture="speculative")
    b = _schedule_once("0", monkeypatch, posture="speculative")
    assert a == b


def test_pipeline_parity_mesh(monkeypatch):
    a = _schedule_once("1", monkeypatch, posture="mesh")
    b = _schedule_once("0", monkeypatch, posture="mesh")
    assert a == b


def test_pipeline_parity_streaming(monkeypatch):
    a = _schedule_once("1", monkeypatch, posture="streaming")
    b = _schedule_once("0", monkeypatch, posture="streaming")
    assert a == b


def test_pipeline_parity_contended_seeds(monkeypatch):
    """Property sweep: saturated clusters (contention → rejects, multi-
    round retries) stay bit-exact across several seeds. Uses a small
    cluster so claims genuinely conflict."""
    import random

    for seed in (1, 2, 3):
        rng = random.Random(seed)
        n_nodes = rng.choice((4, 6, 8))
        n_pods = rng.choice((64, 96))
        a = _schedule_once(
            "1", monkeypatch, posture="classic",
            n_pods=n_pods, n_nodes=n_nodes,
        )
        b = _schedule_once(
            "0", monkeypatch, posture="classic",
            n_pods=n_pods, n_nodes=n_nodes,
        )
        assert a == b, (seed, n_nodes, n_pods)


# ---------------------------------------------------------------------------
# device-faults × pipelining (the `make device-chaos` extension)
# ---------------------------------------------------------------------------


def test_device_chaos_with_pipelining_binds_identical(monkeypatch):
    """A dispatch/upload fault landing while the pipeline has a
    prelaunched round in flight (the guard's "faulted batch never
    prelaunches" boundary) still ends in a bound set bit-identical to a
    fault-free NHD_PIPELINE=0 control of the same seed."""
    from nhd_tpu.sim.chaos import ChaosSim
    from nhd_tpu.sim.faults import PROFILES

    monkeypatch.setenv("NHD_TPU_DEVICE_STATE", "1")
    monkeypatch.setenv("NHD_GUARD_AUDIT_INTERVAL", "1")
    monkeypatch.setenv("NHD_GUARD_AUDIT_ROWS", "0")

    seed = 1
    GUARD.reset()
    monkeypatch.setenv("NHD_PIPELINE", "0")
    control = ChaosSim(seed=seed, api_faults=None)
    control.run(steps=25)
    control.quiesce()

    GUARD.reset()
    monkeypatch.setenv("NHD_PIPELINE", "1")
    base_giveups = API_COUNTERS.get("guard_giveups_total")
    sim = ChaosSim(seed=seed, api_faults=PROFILES["device-faults"])
    sim.run(steps=25)
    sim.quiesce()
    assert sim.stats.violations == []
    assert sim.stuck_pods() == []
    assert sim.bound_set() == control.bound_set()
    assert sim.device_audit_errors() == []
    assert API_COUNTERS.get("guard_giveups_total") == base_giveups
    faults = sim.fault_totals()
    assert (
        faults["device_dispatch_errors"]
        + faults["device_upload_errors"]
        + faults["device_bit_flips"]
    ) > 0  # the storm was real, not vacuous


# ---------------------------------------------------------------------------
# overlapped fenced commit (scheduler/commitpipe.py, NHD_ASYNC_COMMIT)
# ---------------------------------------------------------------------------


def _stack(n_nodes=2):
    backend = FakeClusterBackend()
    for i in range(n_nodes):
        spec = SynthNodeSpec(name=f"node{i}")
        backend.add_node(
            spec.name, make_node_labels(spec), hugepages_gb=spec.hugepages_gb
        )
    sched = Scheduler(backend, WatchQueue(), queue.Queue(), respect_busy=False)
    ctrl = Controller(backend, sched.nqueue)
    sched.build_initial_node_list()
    return backend, sched, ctrl


def _drive(sched, ctrl, rounds=8):
    for _ in range(rounds):
        ctrl.run_once(now=0.0)
        while not sched.nqueue.empty():
            sched.run_once()
        sched._drain_commits(block=True)


def test_async_commit_defaults():
    """Off on the fake backend, on for kube (env overrides both)."""
    backend, sched, _ = _stack()
    assert sched._async_commit is False
    from nhd_tpu.k8s.interface import ClusterBackend
    from nhd_tpu.k8s.kube import KubeClusterBackend

    assert ClusterBackend.ASYNC_COMMIT_DEFAULT is False
    assert KubeClusterBackend.ASYNC_COMMIT_DEFAULT is True


def test_async_commit_binds_through_pipeline(monkeypatch):
    monkeypatch.setenv("NHD_ASYNC_COMMIT", "1")
    backend, sched, ctrl = _stack()
    assert sched._async_commit is True
    for i in range(5):
        backend.create_pod(f"p{i}", cfg_text=make_triad_config())
    _drive(sched, ctrl)
    for i in range(5):
        assert backend.pods[("default", f"p{i}")].node is not None, i
        assert (
            sched.pod_state[("default", f"p{i}")]["state"]
            is PodStatus.SCHEDULED
        )
    assert sched.perf["scheduled_total"] == 5
    # the pipeline (not the sync path) carried the commits
    assert sched._commitpipe is not None
    assert sched._commitpipe.inflight_keys() == set()


def test_async_commit_transient_failure_requeues(monkeypatch):
    """A transient commit fault drained from the pipeline unwinds and
    requeues through the PR 2 path, then lands on the retry."""
    from tests.test_faults import FaultProfile, FaultyBackend

    monkeypatch.setenv("NHD_ASYNC_COMMIT", "1")
    backend, sched, ctrl = _stack()
    faulty = FaultyBackend(
        backend, FaultProfile(name="t", transient_bind=1.0)
    )
    sched.backend = faulty
    backend.create_pod("p1", cfg_text=make_triad_config())
    _drive(sched, ctrl)
    pod = backend.pods[("default", "p1")]
    assert pod.node is not None
    assert faulty.fault_stats["transient_binds"] == 1
    assert sched.failed_schedule_count == 0
    assert sched.pod_state[("default", "p1")]["state"] is PodStatus.SCHEDULED
    assert sched._requeue_attempts == {}


def test_async_commit_preserves_order(monkeypatch):
    """Strict FIFO: binds reach the backend in submission order even
    across batches — per-node commit order is a sub-order of it."""
    monkeypatch.setenv("NHD_ASYNC_COMMIT", "1")
    backend, sched, ctrl = _stack()
    order = []
    real_bind = backend.bind_pod_to_node

    def spy_bind(pod, node, ns):
        order.append(pod)
        return real_bind(pod, node, ns)

    backend.bind_pod_to_node = spy_bind
    for i in range(6):
        backend.create_pod(f"p{i}", cfg_text=make_triad_config())
        ctrl.run_once(now=0.0)
        while not sched.nqueue.empty():
            sched.run_once()
    sched._drain_commits(block=True)
    bound = [p for p in order]
    assert bound == sorted(bound, key=lambda p: int(p[1:]))


def test_commit_pipeline_bounded_and_in_order():
    """Unit level: depth bounds in-flight work (submit backpressures),
    results drain in submission order, and the heartbeat ticks per
    drained commit."""
    beats = []
    pipe = CommitPipeline(depth=2, heartbeat=lambda: beats.append(1))
    gate = threading.Event()
    ran = []

    def work(i):
        def run():
            gate.wait(5.0)
            ran.append(i)
            return ("ok", i)
        return run

    try:
        pipe.submit([CommitUnit(("ns", "a"), work(0), 0)])
        pipe.submit([CommitUnit(("ns", "b"), work(1), 1)])
        assert pipe.inflight_keys() == {("ns", "a"), ("ns", "b")}
        # third submit must block until the worker frees a slot
        blocked = threading.Event()

        def late_submit():
            pipe.submit([CommitUnit(("ns", "c"), work(2), 2)])
            blocked.set()

        t = threading.Thread(target=late_submit, daemon=True)
        t.start()
        time.sleep(0.05)
        assert not blocked.is_set()  # backpressure while full
        gate.set()
        t.join(5.0)
        pairs = pipe.drain_all()
        assert ran == [0, 1, 2]
        assert [u.ctx for u, _ in pairs] == [0, 1, 2]
        assert [r for _, r in pairs] == [("ok", 0), ("ok", 1), ("ok", 2)]
        assert len(beats) == 3
        assert pipe.inflight_keys() == set()
    finally:
        gate.set()
        pipe.stop()


def test_commit_pipeline_surfaces_closure_raise():
    """A raising closure (contract violation) must not hang drain_all:
    the exception becomes the unit's result."""
    pipe = CommitPipeline(depth=4)
    try:
        pipe.submit([CommitUnit(
            ("ns", "x"), lambda: (_ for _ in ()).throw(RuntimeError("boom")),
            None,
        )])
        pairs = pipe.drain_all()
        assert len(pairs) == 1
        assert isinstance(pairs[0][1], RuntimeError)
    finally:
        pipe.stop()


def test_async_commit_delete_event_barriers(monkeypatch):
    """A delete watch event for a pod whose commit is in flight drains
    the pipeline first — the outcome lands before the release runs (the
    single-writer race the barrier exists for)."""
    monkeypatch.setenv("NHD_ASYNC_COMMIT", "1")
    backend, sched, ctrl = _stack()
    slow = threading.Event()
    real_bind = backend.bind_pod_to_node

    def slow_bind(pod, node, ns):
        slow.wait(5.0)
        return real_bind(pod, node, ns)

    backend.bind_pod_to_node = slow_bind
    backend.create_pod("p1", cfg_text=make_triad_config())
    ctrl.run_once(now=0.0)
    while not sched.nqueue.empty():
        sched.run_once()
    assert ("default", "p1") in sched._commitpipe.inflight_keys()
    # the delete event arrives while the bind is still in flight
    backend.bind_pod_to_node = real_bind
    backend.delete_pod("p1", emit_watch=True)
    ctrl.run_once(now=0.0)   # forward the delete watch event
    threading.Timer(0.05, slow.set).start()
    while not sched.nqueue.empty():
        sched.run_once()   # handles the delete AFTER draining the bind
    assert sched._commitpipe.inflight_keys() == set()
    # the bind outcome was processed (pod reached SCHEDULED or was
    # released by the delete); either way no claim leaks on the mirror
    assert ("default", "p1") not in sched.pod_state or (
        sched.pod_state[("default", "p1")]["state"] is not None
    )


def test_bench_diff_gates_host_phases():
    """tools/bench_diff.py: the r14 host phases gate with the same
    relative-threshold + 30 ms absolute-floor stance as solve."""
    import sys
    sys.path.insert(0, "tools")
    from tools.bench_diff import PHASE_FLOOR, WATCHED_PHASES, diff_artifacts

    for phase in ("select", "assign", "materialize", "final_sync"):
        assert phase in WATCHED_PHASES
    assert PHASE_FLOOR == 0.03

    def art(assign):
        return {
            "git_rev": "x",
            "payload": {
                "configs": {
                    "cfg2": {
                        "wall_seconds": 1.0, "placed": 10,
                        "phases": {"solve": 0.1, "assign": assign},
                    },
                },
                "headline": {},
            },
        }

    # +50% AND +50ms: fatal
    _, regressions = diff_artifacts(
        art(0.10), art(0.15), threshold=0.10, floor=0.005,
    )
    assert any("assign" in r for r in regressions)
    # +50% but only +5ms growth: under the 30 ms absolute floor — noise
    _, regressions = diff_artifacts(
        art(0.010), art(0.015), threshold=0.10, floor=0.005,
    )
    assert regressions == []


def test_drain_all_timeout_is_a_deadline():
    """drain_all's timeout bounds the WHOLE wait: a worker that keeps
    completing (and notifying) must not restart the budget, and 0 is a
    genuinely non-blocking probe."""
    pipe = CommitPipeline(depth=8)
    gate = threading.Event()
    try:
        pipe.submit([CommitUnit(("ns", "slow"), lambda: gate.wait(10.0), 0)])
        t0 = time.monotonic()
        out = pipe.drain_all(timeout=0)      # non-blocking probe
        assert time.monotonic() - t0 < 1.0
        assert out == []
        t0 = time.monotonic()
        out = pipe.drain_all(timeout=0.2)    # bounded barrier
        dt = time.monotonic() - t0
        assert 0.1 < dt < 2.0
        assert out == []
    finally:
        gate.set()
        pipe.stop()


def test_async_commit_yields_to_commit_workers(monkeypatch):
    """An explicit NHD_COMMIT_WORKERS>1 wins over the async default:
    the thread-pool path keeps intra-batch commit parallelism."""
    import nhd_tpu.scheduler.core as core_mod

    monkeypatch.setenv("NHD_ASYNC_COMMIT", "1")
    monkeypatch.setattr(core_mod, "COMMIT_WORKERS", 4)
    backend, sched, ctrl = _stack()
    backend.create_pod("p1", cfg_text=make_triad_config())
    ctrl.run_once(now=0.0)
    while not sched.nqueue.empty():
        sched.run_once()
    # the sync/pool path committed before returning: no pipeline built
    assert sched._commitpipe is None
    assert backend.pods[("default", "p1")].node is not None


def test_async_commit_node_remove_barriers_and_requeues(monkeypatch):
    """A NODE_REMOVE racing an in-flight commit: the watch handler
    barriers first, and a commit whose target node is ALREADY gone maps
    to a transient requeue (fresh solve against the current mirror),
    never a worker-thread KeyError."""
    from nhd_tpu.scheduler.core import CommitOutcome

    monkeypatch.setenv("NHD_ASYNC_COMMIT", "1")
    backend, sched, ctrl = _stack()
    backend.create_pod("p1", cfg_text=make_triad_config())
    _drive(sched, ctrl)
    bound_node = backend.pods[("default", "p1")].node
    assert bound_node is not None
    # direct contract check: a commit draining after its node left the
    # mirror is RETRY, not a raise
    item_key_node = sched.nodes.pop(bound_node)
    try:
        class R:
            node = bound_node
            nic_list = ()

        item = BatchItem(("default", "p1"), None)
        outcome = sched._commit_pod_calls(None, item, R())
        assert outcome is CommitOutcome.RETRY
    finally:
        sched.nodes[bound_node] = item_key_node


def test_unique_rows_handles_negative_sentinels():
    """The packed-key uniquing behind the batch-decoded materialize must
    stay injective with the native core's -1 no-NIC sentinel in a
    column (a collision hands a pod another row's consumed-NIC tuple)
    — each column shifts by its own minimum before packing."""
    import numpy as np

    from nhd_tpu.solver.batch import _unique_rows

    cols = (np.array([0, 0]), np.array([2, 1]), np.array([-1, 3]))
    rows, inv = _unique_rows(cols)
    assert len(rows) == 2
    assert rows[np.asarray(inv).ravel()[0]].tolist() == [0, 2, -1]
    assert rows[np.asarray(inv).ravel()[1]].tolist() == [0, 1, 3]
    # ground truth across shapes, sentinels included
    rng = np.random.default_rng(7)
    for _ in range(50):
        mat = rng.integers(
            -2, 9, size=(int(rng.integers(1, 40)), int(rng.integers(1, 5)))
        ).astype(np.int64)
        got_rows, got_inv = _unique_rows(
            tuple(mat[:, j] for j in range(mat.shape[1]))
        )
        want_rows, want_inv = np.unique(mat, axis=0, return_inverse=True)
        assert np.array_equal(got_rows, want_rows)
        assert np.array_equal(
            np.asarray(got_inv).ravel(), np.asarray(want_inv).ravel()
        )


def test_async_commit_env_words(monkeypatch):
    """NHD_ASYNC_COMMIT parses the same word sets as NHD_PIPELINE
    ('true'/'on' enable — they must never silently disable), and a
    typo fails loud at construction."""
    import pytest

    for word, want in (
        ("true", True), ("on", True), ("1", True),
        ("false", False), ("off", False), ("0", False), ("auto", False),
    ):
        monkeypatch.setenv("NHD_ASYNC_COMMIT", word)
        _backend, sched, _ctrl = _stack()
        assert sched._async_commit is want, word
    monkeypatch.setenv("NHD_ASYNC_COMMIT", "yes-please")
    with pytest.raises(ValueError):
        _stack()
