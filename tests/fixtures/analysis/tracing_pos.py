# nhdlint fixture: every tracing-pack hazard, one per line.
# Flagged lines carry EXPECT markers the fixture tests parse; this file
# is analyzed as text only, never imported.
import time

import jax
import numpy as np
from functools import partial


def kernel(x, y):
    if x > 0:  # EXPECT[NHD102]
        y = y + 1
    n = int(x)  # EXPECT[NHD101]
    z = np.asarray(y)  # EXPECT[NHD103]
    while y:  # EXPECT[NHD102]
        break
    assert x  # EXPECT[NHD102]
    return z + n


solver = jax.jit(kernel)  # marks kernel as jit-traced


@jax.jit
def decorated(a):
    b = a * 2
    return float(b)  # EXPECT[NHD101]


@jax.jit
def timed_kernel(a):
    t0 = time.perf_counter()  # EXPECT[NHD106] — trace-time constant
    b = a * 2
    dt = time.time() - t0  # EXPECT[NHD106]
    return b, dt


def helper(c):
    return bool(c)  # EXPECT[NHD101] — traced via the chained() call graph


@partial(jax.jit, donate_argnums=(0,))
def chained(c):
    return helper(c)


def make_solver(shape):
    def fn(v):
        return v * 2

    return jax.jit(fn)  # EXPECT[NHD104] — fresh wrapper per call


def looper(fns):
    out = []
    for f in fns:
        out.append(jax.jit(f))  # EXPECT[NHD104] — jit inside a loop
    return out


def statics(data, cfg=[1, 2]):
    return data


jitted = jax.jit(statics, static_argnames="cfg")  # EXPECT[NHD105]
