# nhdlint fixture: every violation here carries an inline suppression —
# the analyzer must report zero findings and count the suppressions.


def risky():
    raise ValueError("x")


def swallow_suppressed():
    try:
        risky()
    except Exception:  # nhdlint: ignore[NHD302]
        pass


def bare_suppressed_all_rules():
    try:
        risky()
    except:  # nhdlint: ignore
        pass


def swallow_wrong_rule_listed():
    try:
        risky()
    except Exception:  # nhdlint: ignore[NHD301]
        pass  # suppresses the WRONG rule: NHD302 must still fire here
