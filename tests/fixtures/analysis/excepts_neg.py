# nhdlint fixture: exception handling that must NOT be flagged.
import logging

logger = logging.getLogger(__name__)


def risky():
    raise ValueError("x")


def narrow():
    try:
        risky()
    except ValueError:
        pass              # narrow type: caller chose what to ignore


def logs():
    try:
        risky()
    except Exception as exc:
        logger.error(f"risky failed: {exc}")


def reraises():
    try:
        risky()
    except Exception:
        raise


def returns_sentinel():
    try:
        risky()
    except Exception:
        return False      # the caller observes the failure
    return True


def records_state(out):
    try:
        risky()
    except Exception as exc:
        out["error"] = str(exc)


def breaks_out():
    while True:
        try:
            risky()
        except Exception:
            break
