# nhdlint fixture: lock-discipline violations.
import threading


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []
        self.count = 0
        self.table = {}

    def add(self, x):
        with self._lock:
            self.items.append(x)
            self.count += 1
            self.table["n"] = self.count

    def sneaky_assign(self):
        self.count = 0  # EXPECT[NHD201]

    def sneaky_mutate(self, x):
        self.items.append(x)  # EXPECT[NHD201]

    def sneaky_subscript(self):
        self.table["n"] = -1  # EXPECT[NHD201]

    def manual_acquire(self):
        self._lock.acquire()  # EXPECT[NHD202]
        try:
            self.count += 1  # EXPECT[NHD201] — acquire() isn't modeled
        finally:
            self._lock.release()


class ClassLevelLock:
    _lock = threading.Lock()
    active = False

    @classmethod
    def set_on(cls):
        with cls._lock:
            cls.active = True

    @classmethod
    def set_off(cls):
        cls.active = False  # EXPECT[NHD201]


class ConditionAlias:
    def __init__(self):
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._queue = []

    def put(self, x):
        with self._cv:
            self._queue.append(x)

    def bad_put(self, x):
        self._queue.append(x)  # EXPECT[NHD201]
