# nhdlint fixture: the same host-sync shapes OUTSIDE a solver path — the
# NHD107 pack is path-scoped and must stay silent here (tools, tests and
# obs code pull results synchronously by design).
import numpy as np
import jax


def scrape(dev, pods):
    out = dev.solve_ranked(pods, 64)
    arr = np.asarray(out)
    out.block_until_ready()
    host = jax.device_get(out)
    return arr, host
