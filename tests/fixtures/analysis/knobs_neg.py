"""Registered env knobs (NHD720 negative): every NHD_* read appears in
the registry; non-NHD reads are out of the rule's scope entirely."""

import os

from nhd_tpu.config.knobs import Knob

KNOBS = (
    Knob("NHD_DOCUMENTED", "1", "present in the registry"),
    Knob("NHD_ALSO_DOCUMENTED", "0", "also present"),
)

A = os.environ.get("NHD_DOCUMENTED", "1")
B = os.environ["NHD_ALSO_DOCUMENTED"]
HOME = os.environ.get("HOME", "/root")
PATH = os.environ["PATH"]
