"""Deliberate NHD6xx violations; EXPECT markers pin rule ids to lines.

Analyzed as a one-module project, so registrations (where a case needs
one) live in this file too.
"""

lines = []

# NHD601: TYPE-declared family with uppercase characters
lines.append("# TYPE NHD_Bad_Name counter")  # EXPECT[NHD601]

# NHD601: uppercase family emitted as a sample line (malformed names are
# not ALSO reported unregistered — one defect, one finding)
lines.append('NHD_Upper_Total{shard="1"} 3')  # EXPECT[NHD601]

# NHD602: emitted but registered nowhere in the project
depth = 4
lines.append(f"nhd_orphan_family_depth {depth}")  # EXPECT[NHD602]

# NHD603: registered family, but the label is a correlation ID — one
# time series per pod ever traced
lines.append("# TYPE nhd_span_cardinality_total counter")
corr = "c0001"
lines.append(f'nhd_span_cardinality_total{{corr="{corr}"}} 1')  # EXPECT[NHD603]

# NHD603: pod identity as a label value
lines.append("# TYPE nhd_pod_bind_total counter")
pod = "default/p1"
lines.append(f'nhd_pod_bind_total{{pod="{pod}"}} 1')  # EXPECT[NHD603]


class LabeledHistogram:
    """Stand-in for obs/histo.py's family type (the pack keys on the
    constructor name)."""

    def __init__(self, name, label, help_text):
        self.name = name
        self.label = label


# NHD603: a per-pod-uid child histogram is a cardinality bomb by
# construction
H = LabeledHistogram("per_pod_seconds", "pod_uid", "per-pod wall")  # EXPECT[NHD603]

# NHD603: the keyword form must not escape the rule
H2 = LabeledHistogram("per_corr_seconds", label="corr", help_text="per-corr")  # EXPECT[NHD603]
