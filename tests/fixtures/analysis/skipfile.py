# nhdlint: skip-file — generated-style file, opted out wholesale.


def swallow():
    try:
        raise ValueError("x")
    except Exception:
        pass
