"""Out-of-scope shapes the contract pack must stay silent on:

* env reads with no knob registry in the analyzed project (NHD720 is
  judgeable only when both sides of the contract are visible);
* non-NHD env reads next to a registry-shaped tuple;
* stride math on a base that is not the speculate pod_args block;
* .index() into a non-contract tuple;
* a span expression passed to a kwarg that is not in_shardings.
"""

import os

FLAG = os.environ.get("NHD_SOME_FLAG", "0")  # no registry: out of scope
HOME = os.environ.get("HOME", "/root")

OTHER_ORDER = ("a", "b")
I = OTHER_ORDER.index("zzz")  # not a contract tuple

spec = object()


def jit(fn, **kw):
    return fn


def misc(fn):
    # out_shardings is not the solve-signature input span
    return jit(fn, out_shardings=(spec,) * 4 + (spec,) * 2)


def windows(samples, b):
    # not pod_args: stride math on unrelated buffers is fine
    return samples[3 * b : 3 * b + 3]
