"""Clean counterparts to races_pos.py — the pack must stay silent.

Each class is one exoneration path: a consistent lockset, declared
single-writer discipline honored, copy-on-publish, init-only writes,
and main-thread-only code (no root reaches it).
"""
import threading


class LockedPipeline:
    """Every access under one lock: consistent lockset, clean."""

    def __init__(self):
        self.lock = threading.Lock()
        self.status = "idle"
        self.counter = 0
        self.t1 = None
        self.t2 = None

    def start(self):
        self.t1 = threading.Thread(target=self._producer)
        self.t2 = threading.Thread(target=self._consumer)
        self.t1.start()
        self.t2.start()

    def _producer(self):
        with self.lock:
            self.status = "busy"
            self.counter += 1

    def _consumer(self):
        with self.lock:
            if self.status == "busy":
                self.counter += 1


class OwnedMirror:
    """Single-writer discipline honored: only the owner writes; the
    other root just reads (staleness-tolerant by declaration)."""

    _NHD_RACE_OWNER = {"epoch": "*races_neg:OwnedMirror._tick"}

    def __init__(self):
        self.epoch = 0
        self.t = None
        self.w = None

    def start(self):
        self.t = threading.Thread(target=self._tick)
        self.w = threading.Thread(target=self._reader)
        self.t.start()
        self.w.start()

    def _tick(self):
        self.epoch += 1

    def _reader(self):
        return self.epoch


class CopyPublisher:
    """Mutable state handed to the worker as a copy, not the live ref."""

    def __init__(self):
        self.items = []
        self.t = None

    def start(self):
        self.t = threading.Thread(target=self._work, args=(list(self.items),))
        self.t.start()

    def _work(self, snapshot):
        self.items = snapshot       # single root: no sharing
        return len(snapshot)


class MainThreadOnly:
    """No thread root ever reaches these accesses: not shared."""

    def __init__(self):
        self.hits = 0

    def bump(self):
        self.hits += 1

    def read(self):
        return self.hits
