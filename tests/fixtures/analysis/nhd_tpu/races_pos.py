"""Deliberate NHD81x violations — every flagged line carries EXPECT.

The 'nhd_tpu' fixture directory puts these in the races pack's path
scope (production packages only); races_out_of_scope.py at the fixtures
root holds the same shapes and must stay silent.
"""
import threading


class Pipeline:
    """Two spawned workers sharing unguarded instance state."""

    def __init__(self):
        self.lock = threading.Lock()
        self.status = "idle"        # init writes are exempt (pre-publish)
        self.counter = 0
        self.cache = None
        self.items = []
        self.t1 = None
        self.t2 = None
        self.t3 = None

    def start(self):
        self.t1 = threading.Thread(target=self._producer)
        self.t2 = threading.Thread(target=self._consumer)
        self.t3 = threading.Thread(target=self._indexer, args=(self.items,))  # EXPECT[NHD813]
        self.t1.start()
        self.t2.start()
        self.t3.start()

    def _producer(self):
        self.status = "busy"        # EXPECT[NHD810]
        self.counter += 1           # EXPECT[NHD812]
        if self.cache is None:
            self.cache = {"warm": True}  # EXPECT[NHD812]
        self.items.append(1)

    def _consumer(self):
        if self.status == "busy":
            self.counter += 1       # EXPECT[NHD812]
        return self.cache

    def _indexer(self, items):
        return len(items)


class Mirror:
    """Declared single-writer state written from a second root."""

    _NHD_RACE_OWNER = {"epoch": "*races_pos:Mirror._tick"}

    def __init__(self):
        self.epoch = 0
        self.t = None
        self.w = None

    def start(self):
        self.t = threading.Thread(target=self._tick)
        self.w = threading.Thread(target=self._poker)
        self.t.start()
        self.w.start()

    def _tick(self):
        self.epoch += 1             # owner's own write: the discipline

    def _poker(self):
        self.epoch = 99             # EXPECT[NHD811]
