"""Races project fixture, scheduler-loop module — consistent as
shipped; test_race_analysis.py injects one defect at a time.

Mirrors the live architecture in miniature: a loop thread owning mirror
state, a heartbeat callback handed to the pipe's constructor (so the
pipe worker becomes a second caller of _beat), and a janitor thread
that receives a *copy* of the mutable table.
"""
import threading

from pipe_like import Pipe
from stats_like import bump, set_status

_NHD_RACE_OWNER = {"Loop.mirror_epoch": "*sched_like:Loop.run"}


class Loop:
    def __init__(self):
        self.hb_lock = threading.Lock()
        self.last_beat = 0.0
        self.mirror_epoch = 0
        self.table = {}
        self.pipe = Pipe(heartbeat=self._beat)
        self.t = None
        self.j = None

    def _beat(self):
        with self.hb_lock:
            self.last_beat += 1.0
        # owner-only bookkeeping advances here

    def start(self):
        self.t = threading.Thread(target=self.run)
        self.j = threading.Thread(target=self._janitor,
                                  args=(dict(self.table),))
        self.t.start()
        self.j.start()

    def run(self):
        self._beat()
        self.mirror_epoch += 1
        self.table["epoch"] = self.mirror_epoch
        bump()
        set_status("loop")

    def _janitor(self, snapshot):
        return len(snapshot)
