"""Races project fixture, HTTP-views module: per-connection handler
threads are roots, but their own instance state (close_connection) is
thread-local by construction and must not read as shared.
"""
import stats_like


class StatsHandler:
    def do_GET(self):
        stats_like.bump()
        self.close_connection = True
