"""Races project fixture, commit-pipe module: a worker thread that
invokes the heartbeat callback bound at construction (keyword-only, like
the real CommitPipeline) — the cross-module ctor-callable edge the
ownership model must resolve.
"""
import threading

from stats_like import bump, set_status


class Pipe:
    def __init__(self, *, heartbeat=None):
        self._hb = heartbeat
        self.lock = threading.Lock()
        self.outcomes = []
        self.w = None

    def start(self):
        self.w = threading.Thread(target=self._run)
        self.w.start()

    def _run(self):
        if self._hb is not None:
            self._hb()
        with self.lock:
            self.outcomes.append("ok")
        bump()
        set_status("drain")
