"""Races project fixture, shared-counters module: module globals
reached from every root, guarded by one lock — the consistent-lockset
exoneration path for globals (cf. class fields in sched/pipe).
"""
import threading

LOCK = threading.Lock()
HITS = 0
LAST_STATUS = ""


def bump():
    global HITS
    with LOCK:
        HITS += 1


def set_status(status):
    global LAST_STATUS
    with LOCK:
        LAST_STATUS = status
