# nhdlint fixture: exception-hygiene violations.


def risky():
    raise ValueError("x")


def bare():
    try:
        risky()
    except:  # EXPECT[NHD301]
        pass


def swallow_pass():
    try:
        risky()
    except Exception:  # EXPECT[NHD302]
        pass


def swallow_continue(items):
    for _ in items:
        try:
            risky()
        except Exception:  # EXPECT[NHD302]
            continue


def swallow_tuple():
    try:
        risky()
    except (ValueError, Exception):  # EXPECT[NHD302]
        pass


def swallow_baseexception():
    try:
        risky()
    except BaseException:  # EXPECT[NHD302]
        pass
