"""Unregistered env knobs (NHD720): a knob registry exists in this
project, so every NHD_* read must appear in it."""

import os

from nhd_tpu.config.knobs import Knob

KNOBS = (
    Knob("NHD_DOCUMENTED", "1", "present in the registry"),
)

GOOD = os.environ.get("NHD_DOCUMENTED", "1")
BAD = os.environ.get("NHD_SECRET_TOGGLE", "0")  # EXPECT[NHD720]
WORSE = os.environ["NHD_RAW_SUBSCRIPT"]  # EXPECT[NHD720]
ALSO = os.getenv("NHD_VIA_GETENV")  # EXPECT[NHD720]
