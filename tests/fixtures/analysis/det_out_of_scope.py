# nhdlint fixture: same calls as solver/det_pos.py but OUTSIDE the
# solver path — the determinism pack must stay silent here (sim/ seeds
# its own generators and is allowed to roll dice).
import random
import time


def pick(nodes):
    return random.choice(nodes)


def stamp():
    return time.time()
