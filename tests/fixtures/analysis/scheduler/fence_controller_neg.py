"""NHD501 negatives, controller scope: the sanctioned coordinator-write
shapes stay clean."""


class GatedController:
    def __init__(self, backend, elector=None):
        self.backend = backend
        self.elector = elector

    def _coordinator_write(self, fn, *args):
        # THE chokepoint: direct TriadSet mutator calls are allowed only
        # here, with coordinatorship re-checked at the write
        if self.elector is not None and not self.elector.is_leader:
            return False
        return bool(fn(*args))

    def reconcile(self, ts, ordinal, observed):
        # bound-method ARGUMENTS are not call expressions — sanctioned
        ok = self._coordinator_write(
            self.backend.create_pod_for_triadset, ts, ordinal
        )
        if not ok:
            return False
        return self._coordinator_write(
            self.backend.update_triadset_status, ts, observed
        )

    def observe(self):
        # reads stay out of the rule's scope
        sets = self.backend.list_triadsets()
        return [self.backend.list_pods_of_triadset(ts) for ts in sets]
