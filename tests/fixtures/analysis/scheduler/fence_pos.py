"""NHD501 positives: raw commit-path mutators in scheduler-scoped code.

Each flagged line calls one of the four fenced mutators directly on a
``*.backend`` attribute outside the ``_commit_write`` helper — the hole
a deposed leader's in-flight batch could land through.
"""


class LeakyScheduler:
    def __init__(self, backend):
        self.backend = backend

    def commit(self, pod, ns, node, cfg, gpu_map, nad):
        self.backend.add_nad_to_pod(pod, ns, nad)            # EXPECT[NHD501]
        self.backend.annotate_pod_gpu_map(ns, pod, gpu_map)  # EXPECT[NHD501]
        self.backend.annotate_pod_config(ns, pod, cfg)       # EXPECT[NHD501]
        return self.backend.bind_pod_to_node(pod, node, ns)  # EXPECT[NHD501]

    def helper_named_wrong(self, pod, ns, node):
        # a helper by any other name is not THE fenced chokepoint
        return self.backend.bind_pod_to_node(pod, node, ns)  # EXPECT[NHD501]


def free_function(sched, pod, ns, node):
    # module-level code in scheduler scope is just as unfenced
    return sched.backend.bind_pod_to_node(pod, node, ns)     # EXPECT[NHD501]


def bare_backend_param(backend, pod, ns, node):
    # a helper taking the backend directly must not evade the rule
    return backend.bind_pod_to_node(pod, node, ns)           # EXPECT[NHD501]


def raw_eviction(sched, pod, ns):
    # policy preemption: an unfenced eviction is the preemption analog
    # of the double-bind hole — a deposed leader evicting a victim the
    # new leader just placed
    return sched.backend.evict_pod(pod, ns)                  # EXPECT[NHD501]
