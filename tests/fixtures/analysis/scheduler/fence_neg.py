"""NHD501 negatives: the sanctioned fenced-commit shapes stay clean."""


class FencedScheduler:
    def __init__(self, backend, elector=None):
        self.backend = backend
        self.elector = elector

    def _fence_epoch(self):
        return None if self.elector is None else self.elector.fencing_epoch()

    def _commit_write(self, fn, *args):
        # THE chokepoint: direct mutator calls are allowed only here
        epoch = self._fence_epoch()
        if epoch is None:
            return fn(*args)
        return fn(*args, epoch=epoch)

    def commit(self, pod, ns, node, cfg):
        # bound-method ARGUMENTS are not call expressions — sanctioned
        ok = self._commit_write(self.backend.annotate_pod_config, ns, pod, cfg)
        if not ok:
            return False
        return self._commit_write(self.backend.bind_pod_to_node, pod, node, ns)

    def preempt(self, pod, ns):
        # the policy engine's eviction rides the same chokepoint
        return self._commit_write(self.backend.evict_pod, pod, ns)

    def observe(self, pod, ns):
        # reads and the idempotent audit trail are out of the rule's scope
        self.backend.generate_pod_event(pod, ns, "Scheduling", None, "msg")
        self.backend.pod_exists(pod, ns)
        return self.backend.get_pod_node(pod, ns)
