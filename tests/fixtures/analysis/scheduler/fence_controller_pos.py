"""NHD501 positives, controller scope: raw TriadSet mutators in
scheduler-scoped code.

The controller's reconciliation writes (pod creation, scale-status
patches) are gated on coordinatorship PER WRITE through
``_coordinator_write`` — a raw call keeps writing after a mid-pass
deposition, racing the new coordinator's reconciliation.
"""


class LeakyController:
    def __init__(self, backend, elector=None):
        self.backend = backend
        self.elector = elector

    def reconcile(self, ts, ordinal, observed):
        self.backend.create_pod_for_triadset(ts, ordinal)    # EXPECT[NHD501]
        return self.backend.update_triadset_status(ts, observed)  # EXPECT[NHD501]

    def gated_at_the_pass_only(self, ts, ordinal):
        # a leadership check at the TOP of the pass is not enough — the
        # write itself must re-check (deposition lands mid-pass)
        if self.elector is None or self.elector.is_leader:
            return self.backend.create_pod_for_triadset(ts, ordinal)  # EXPECT[NHD501]


def free_function(ctrl, ts, observed):
    # module-level code in scheduler scope is just as ungated
    return ctrl.backend.update_triadset_status(ts, observed)  # EXPECT[NHD501]


def bare_backend_param(backend, ts, ordinal):
    # a helper taking the backend directly must not evade the rule
    return backend.create_pod_for_triadset(ts, ordinal)      # EXPECT[NHD501]
