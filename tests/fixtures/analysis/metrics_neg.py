"""Clean metrics idioms — the NHD6xx pack must stay silent here."""

lines = []

# literal TYPE/HELP declaration + static sample
lines += [
    "# HELP nhd_good_total A well-formed counter",
    "# TYPE nhd_good_total counter",
]
n = 3
lines.append(f"nhd_good_total {n}")

# bounded label keys on a registered family
lines.append('nhd_good_total{shard="0",window="5m"} 1')

# the name/kind/help table-row idiom (rpc/metrics.py): the row registers
# the family; the dynamic f-string render is skipped by design
for name, kind, help_text in (
    ("table_registered_total", "counter", "registered by the row idiom"),
):
    lines += [
        f"# HELP nhd_{name} {help_text}",
        f"# TYPE nhd_{name} {kind}",
        f"nhd_{name} 1",
    ]


class Histogram:
    """Stand-in for obs/histo.py's registry type."""

    def __init__(self, name, help_text):
        self.name = name


# constructor registration covers the family and its histogram children
H = Histogram("neg_latency_seconds", "bounded")
le = "0.1"
count = 2
lines.append(f'nhd_neg_latency_seconds_bucket{{le="{le}"}} {count}')

# the *FAMILIES* list idiom (obs/slo.py METRIC_FAMILIES)
METRIC_FAMILIES = ("listed_total",)
lines.append("nhd_listed_total 1")

# the name -> (kind, help) dict idiom (k8s/retry.py ApiCounters.KNOWN)
KNOWN = {"known_total": ("counter", "registered by the dict idiom")}
lines.append("nhd_known_total 7")

# prose, paths and bare family references are not emissions
DOC = "nhd_tpu/rpc/metrics.py renders the nhd_tpu exposition surface"
USAGE = "nhd-tpu --fake  # demo harness"
BARE = "nhd_good_total"
MSG = f"NHD: {n} pods rescheduled"
