"""Patterns the lockgraph pack must NOT flag.

Consistent lock order, bounded waits, condition-wait on the lock it
releases, RLock re-entrancy, and blocking calls with no lock held.
"""

import queue
import threading

_A = threading.Lock()
_B = threading.Lock()
_Q = queue.Queue()
_COND = threading.Condition(_B)


def module_condition_wait():
    with _B:
        _COND.wait()  # releases _B (module-level Condition aliases it)


def nested_consistent_one():
    with _A:
        with _B:  # same order everywhere: no inversion
            pass


def nested_consistent_two():
    with _A:
        with _B:
            pass


def bounded_wait_under_lock():
    with _A:
        return _Q.get(timeout=1.0)  # bounded: not a deadlock


def nonblocking_get_under_lock():
    with _A:
        return _Q.get(block=False)


def blocking_without_lock():
    return _Q.get()  # blocking, but nothing held


def dict_get_under_lock(d):
    with _A:
        return d.get("key")  # has a positional arg: dict.get, not a wait


class Worker:
    def __init__(self):
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._jobs = []

    def wait_for_job(self):
        with self._cv:
            while not self._jobs:
                self._cv.wait()  # releases its own lock: canonical
            return self._jobs.pop()

    def reenter(self):
        with self._lock:
            self._helper()  # RLock: re-entry is legal

    def _helper(self):
        with self._lock:
            return list(self._jobs)


def make_callback():
    with _A:
        # DEFINING a closure under the lock is not calling it: the
        # blocking body runs later, lock-free
        def cb():
            return _Q.get()

    return cb


def local_lock_worker():
    import threading as _t

    lock = _t.Lock()    # function-local: no cross-call identity, out of
    with lock:          # scope for the static layer (nhdsan covers it)
        return _Q.get(timeout=1.0)
