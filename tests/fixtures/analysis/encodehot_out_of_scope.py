# nhdlint fixture: the same full-re-encode shapes OUTSIDE solver /
# scheduler paths — the NHD108 pack is path-scoped and must stay silent
# here (tools, tests and sim code re-encode one-shot by design).
from nhd_tpu.solver.encode import encode_cluster


def per_round_reencode(nodes, rounds):
    for _ in range(rounds):
        cluster = encode_cluster(nodes)
    return cluster


def helper(nodes):
    return encode_cluster(nodes, now=0.0)
