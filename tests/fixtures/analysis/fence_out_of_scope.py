"""Outside nhd_tpu/scheduler/ the fencing pack stays silent: backends,
sims and tests call the raw mutators legitimately (the fake backend IS
the mutator; chaos drives it directly)."""


class SimDriver:
    def __init__(self, backend):
        self.backend = backend

    def force_bind(self, pod, ns, node):
        # raw mutator call, but this file is not scheduler-scoped
        return self.backend.bind_pod_to_node(pod, node, ns)
