# nhdlint fixture: lock patterns that must NOT be flagged.
import threading


class SingleWriter:
    """Owns no lock: the single-writer pattern is out of the pack's
    scope by design (scheduler/core.py)."""

    def __init__(self):
        self.state = {}

    def mutate(self):
        self.state["k"] = 1


class Disciplined:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []   # __init__ runs before publication: fine

    def add(self, x):
        with self._lock:
            self.items.append(x)

    def swap(self):
        with self._lock:
            self.items = []

    def read(self):
        return len(self.items)   # reads are never flagged


class UnguardedAttrs:
    """Owns a lock but never mutates 'hits' under it — 'hits' is not
    inferred as guarded, so plain writes stay legal."""

    def __init__(self):
        self._lock = threading.Lock()
        self.guarded = 0
        self.hits = 0

    def inc(self):
        with self._lock:
            self.guarded += 1

    def bump(self):
        self.hits += 1


class NestedDefNotHeld:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def work(self):
        with self._lock:
            self.n += 1

            def cb():
                return None

            return cb
