"""Same shapes as nhd_tpu/races_pos.py, but outside the races pack's
path scope (no nhd_tpu path component): must produce zero findings —
tools/ and tests/ harnesses spawn threads around fixtures freely.
"""
import threading


class Pipeline:
    def __init__(self):
        self.status = "idle"
        self.counter = 0
        self.items = []
        self.t1 = None
        self.t2 = None
        self.t3 = None

    def start(self):
        self.t1 = threading.Thread(target=self._producer)
        self.t2 = threading.Thread(target=self._consumer)
        self.t3 = threading.Thread(target=self._indexer, args=(self.items,))
        self.t1.start()
        self.t2.start()
        self.t3.start()

    def _producer(self):
        self.status = "busy"
        self.counter += 1
        self.items.append(1)

    def _consumer(self):
        if self.status == "busy":
            self.counter += 1

    def _indexer(self, items):
        return len(items)
