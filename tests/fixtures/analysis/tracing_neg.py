# nhdlint fixture: tracing-pack patterns that must NOT be flagged.
import time

import jax
import jax.numpy as jnp
import numpy as np
from functools import lru_cache


def plain_host_function(x):
    # not jit-reachable: host coercion and branching are fine here
    if x > 0:
        return int(x)
    return np.asarray(x)


def host_timing(acc, x):
    # not jit-reachable: wall-clock timing on the host is the normal
    # pattern (utils/tracing.py phase does exactly this)
    t0 = time.perf_counter()
    y = plain_host_function(x)
    acc["stage"] = time.perf_counter() - t0
    return y


@jax.jit
def good(x, y):
    n = x.shape[0]         # shapes are static under trace
    if n > 4:
        y = y + 1
    m = int(x.shape[1])    # coercing a static shape is fine
    k = len(y.shape)
    z = jnp.asarray(y)     # jnp stays in the program
    return z * m * k


@lru_cache(maxsize=None)
def get_solver(shape):
    # the repo idiom: one cached wrapper per bucket shape
    def fn(v):
        return jnp.sum(v)

    return jax.jit(fn)


def hashable_statics(data, cfg=(1, 2)):
    return data


jitted = jax.jit(hashable_statics, static_argnames="cfg")

