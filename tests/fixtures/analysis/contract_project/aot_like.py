"""Drift-injection project, AOT layer: the program fingerprint hashes
every module whose source defines placement semantics."""

import hashlib
import inspect

import combos_like
import kernel_like


def program_fingerprint():
    h = hashlib.sha256()
    for mod in (kernel_like, combos_like):
        h.update(inspect.getsource(mod).encode())
    return h.hexdigest()
