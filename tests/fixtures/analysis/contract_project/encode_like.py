"""Drift-injection project, delta layer: DELTA_FIELDS mirrors
kernel_like._ARG_ORDER exactly (same set, same order)."""

DELTA_FIELDS = (
    "cpu",
    "mem",
    "nic",
    "busy",
)
