"""Drift-injection project, kernel layer: the signature tuples, the
donation partition and the mesh sharding spans. Consistent as shipped;
tests mutate copies of these modules to prove each drift is caught."""

node_spec = object()
repl_spec = object()


def jit(fn, **kw):
    return fn


_ARG_ORDER = (
    "cpu",
    "mem",
    "nic",
    "busy",
)
_POD_ARG_ORDER = ("p_cpu", "p_mem", "p_nic")
_MUTABLE = ("cpu", "busy")
_STATIC = ("mem", "nic")


def solve(args):
    return args


def get_solver():
    in_shardings = (node_spec,) * len(_ARG_ORDER) \
        + (repl_spec,) * len(_POD_ARG_ORDER)
    return jit(solve, in_shardings=in_shardings)
