"""Drift-injection project, speculate layer: flattened pod-block stride
math and the positional unpack, both spanning _POD_ARG_ORDER."""

from kernel_like import _POD_ARG_ORDER


def pod_block(pod_args, b):
    return pod_args[3 * b : 3 * b + 3]


def unpack_block(pod_args, b):
    p_cpu, p_mem, p_nic = pod_args[3 * b : 3 * b + 3]
    return p_cpu, p_mem, p_nic
