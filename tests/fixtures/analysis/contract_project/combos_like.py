"""Drift-injection project, combo-table layer: defines get_tables, so
the AOT fingerprint must hash this module's source."""


def get_tables(u, k):
    return [(u, k)]
