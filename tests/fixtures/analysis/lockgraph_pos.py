"""Deliberate lockgraph violations; every flagged line carries EXPECT.

Two-function lock-order inversion (NHD210), a blocking queue get under a
lock reached through a call (NHD211, direct and interprocedural), and a
non-reentrant Lock re-acquired through a callback path (NHD212).
"""

import queue
import threading

_A = threading.Lock()
_B = threading.Lock()
_Q = queue.Queue()


def forward():
    with _A:
        with _B:  # EXPECT[NHD210]
            pass


def backward():
    with _B:
        with _A:  # EXPECT[NHD210]
            pass


def drain():
    # no lock held here: the violation belongs to the caller
    return _Q.get()


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def flush(self):
        with self._lock:
            self._items.clear()
            _Q.get()  # EXPECT[NHD211]

    def flush_indirect(self):
        with self._lock:
            drain()  # EXPECT[NHD211]

    def _on_change(self):
        with self._lock:
            return len(self._items)

    def mutate(self):
        with self._lock:
            self._items["k"] = 1
            self._on_change()  # EXPECT[NHD212]


def spawn_worker():
    # closures get their own summaries: the blocking call lives in the
    # nested def, the violation at the call-under-lock site
    def worker():
        return _Q.get()

    with _A:
        return worker()  # EXPECT[NHD211]
