# nhdlint fixture: determinism violations. Lives under a 'solver/'
# directory because the pack is path-scoped to solver/encode code.
import datetime
import random
import time

import numpy as np
from random import shuffle


def pick(nodes):
    return random.choice(nodes)  # EXPECT[NHD401]


def jitter():
    return np.random.rand()  # EXPECT[NHD401]


def mix(items):
    shuffle(items)  # EXPECT[NHD401]


def stamp():
    return time.time()  # EXPECT[NHD402]


def stamp_dt():
    return datetime.datetime.now()  # EXPECT[NHD402]
