# nhdlint fixture: determinism patterns that must NOT be flagged, inside
# the solver scope.
import time

import numpy as np
from random import Random
from numpy.random import default_rng


def seeded_constructors(seed):
    # the rule's own recommended remedy: explicit seeded generators
    return Random(seed).random() + default_rng(seed).random()


def durations():
    # monotonic clocks measure, they don't decide
    return time.monotonic() + time.perf_counter()


def seeded():
    rng = np.random.default_rng(42)   # explicit seeded generator
    return rng.random()


def caller_passed(now):
    return now + 1.0
