# nhdlint fixture: NHD107 negatives — sanctioned transfer patterns and
# plain host numpy that must NOT flag inside solver scope.
import numpy as np


def batched_round_pull(dev, pods):
    out = dev.solve_ranked(pods, 64)
    # async prefetch is the sanctioned pattern: starts the flush without
    # blocking the host
    out.copy_to_host_async()
    return out


def host_only_math(items):
    # np on plain host data: no device value involved
    pending = np.asarray([i for i in range(len(items))], np.int64)
    blocked = np.array([1, 2, 3], np.int64)
    caps = np.copy(blocked)
    return pending, blocked, caps


def suppressed_flush(dev, pods):
    out = dev.solve_ranked(pods, 64)
    # an intentional single-flush site carries an inline suppression
    arr = np.asarray(out)  # nhdlint: ignore[NHD107]
    return arr
