"""Consistent solve-signature contract: the same consumer layers as
contract_pos.py, all in step — must produce zero NHD7xx findings."""

node_spec = object()
repl_spec = object()


def jit(fn, **kw):
    return fn


_ARG_ORDER = (
    "cpu",
    "mem",
    "nic",
)
_POD_ARG_ORDER = ("p_cpu", "p_mem")
_MUTABLE = ("cpu", "nic")
_STATIC = ("mem",)
DELTA_FIELDS = ("cpu", "mem", "nic")

CPU_I = _ARG_ORDER.index("cpu")


def solve(args):
    return args


# symbolic spans derived from the right tuples are always in step
SOLVER = jit(
    solve,
    in_shardings=(node_spec,) * len(_ARG_ORDER)
    + (repl_spec,) * len(_POD_ARG_ORDER),
)


def unpack_blocks(pod_args, b):
    return pod_args[2 * b : 2 * b + 2]


def unpack_names(pod_args, b):
    p_cpu, p_mem = pod_args[2 * b : 2 * b + 2]
    return p_cpu, p_mem
