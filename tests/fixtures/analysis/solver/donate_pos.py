"""The PR 9 `_pad_own` donated-alias double-claim bug, pinned (NHD710).

`_pad_rows_to` passes its argument through unpadded (`return a`), so a
host-mirror array read with `getattr()` reaches the donated position of
the row-scatter dispatch as a zero-copy `jnp.asarray` view — the donated
program then mutates the live host mirror in place.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _pad_rows_to(a, size):
    if a.shape[0] == size:
        return a  # aliasing passthrough — the historical bug
    out = np.zeros((size,) + a.shape[1:], a.dtype)
    out[: a.shape[0]] = a
    return out


def _row_scatter(dst, idx, rows):
    return dst.at[idx].set(rows)


def _get_row_scatter(donate):
    kwargs = {"donate_argnums": (0,)} if donate else {}
    return jax.jit(_row_scatter, **kwargs)


class DeviceState:
    def __init__(self, cluster, names, size):
        self._dev = {}
        for name in names:
            self._dev[name] = jnp.asarray(
                _pad_rows_to(getattr(cluster, name), size)
            )

    def scatter_rows(self, name, idx, rows):
        fn = _get_row_scatter(True)
        self._dev[name] = fn(self._dev[name], idx, rows)  # EXPECT[NHD710]
