"""AOT fingerprint-source omission (NHD703): this module defines both
_ARG_ORDER and get_tables — placement semantics — but the program
fingerprint hashes only the helper module, so editing this file would
not invalidate cached artifacts."""

import hashlib
import inspect

import combos_like as combos

_ARG_ORDER = ("cpu", "mem")
_POD_ARG_ORDER = ("p_cpu",)


def get_tables(u, k):
    return [(u, k)]


def _program_fingerprint():
    h = hashlib.sha256()
    for mod in (combos,):  # EXPECT[NHD703]
        h.update(inspect.getsource(mod).encode())
    return h.hexdigest()
