# nhdlint fixture: NHD108 full cluster re-encode on a per-event /
# per-round hot path (this file sits under a "solver" path segment, so
# the pack is in scope). Flagged lines carry EXPECT markers; analyzed as
# text only.
from nhd_tpu.solver.encode import encode_cluster
from nhd_tpu.solver import encode


def per_round_reencode(nodes, rounds):
    for _ in range(rounds):
        cluster = encode_cluster(nodes)  # EXPECT[NHD108]
    return cluster


def per_event_reencode(nodes, event):
    nodes[event.node].active = False
    return encode.encode_cluster(nodes, now=0.0)  # EXPECT[NHD108]


class Loop:
    def handle(self, nodes, interner):
        self.cluster = encode_cluster(  # EXPECT[NHD108]
            nodes, interner=interner
        )


def make_context(nodes):
    # the sanctioned one-shot context builder: silent
    return encode_cluster(nodes)


def _rebuild(nodes):
    # the delta layer's rebuild chokepoint shape: silent
    return encode_cluster(nodes)


def suppressed_one_shot(nodes):
    return encode_cluster(nodes)  # nhdlint: ignore[NHD108]
