"""Deliberate solve-signature drift — every NHD701/NHD702 shape.

Single-module contract project: this file defines the contract tuples
AND the out-of-step consumers, so analyze_file's one-module project
exercises the cross-layer checks.
"""

node_spec = object()
repl_spec = object()


def jit(fn, **kw):
    return fn


_ARG_ORDER = (  # EXPECT[NHD701]
    # 'nic' is in neither _MUTABLE nor _STATIC: the partition drops it
    "cpu",
    "mem",
    "nic",
)
_POD_ARG_ORDER = ("p_cpu", "p_mem")
_MUTABLE = ("cpu", "ghost")  # EXPECT[NHD701]
_STATIC = ("mem", "cpu")  # EXPECT[NHD702]
DELTA_FIELDS = ("cpu", "mem")  # EXPECT[NHD701]

CPU_I = _ARG_ORDER.index("gpu")  # EXPECT[NHD701]


def solve(args):
    return args


# node span literal 4 != len(_ARG_ORDER) == 3
SOLVER = jit(
    solve,
    in_shardings=(node_spec,) * 4 + (repl_spec,) * 2,  # EXPECT[NHD701]
)


def unpack_blocks(pod_args, b):
    # stride 3 != len(_POD_ARG_ORDER) == 2: every block after the first
    # is misaligned
    chunk = pod_args[3 * b : 3 * b + 3]  # EXPECT[NHD701]
    return chunk


def unpack_names(pod_args, b):
    p_cpu, p_mem, p_ghost = pod_args[2 * b : 2 * b + 2]  # EXPECT[NHD701]
    return p_cpu, p_mem, p_ghost
