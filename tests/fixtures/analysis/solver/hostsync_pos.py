# nhdlint fixture: NHD107 host-sync hazards in solver hot-path modules
# (this file sits under a "solver" path segment, so the pack is in
# scope). Flagged lines carry EXPECT markers; analyzed as text only.
import numpy as np
import jax
from jax import device_get as dg


def round_pull(dev, pods):
    out = dev.solve_ranked(pods, 64)
    arr = np.asarray(out)  # EXPECT[NHD107]
    out.block_until_ready()  # EXPECT[NHD107]
    host = jax.device_get(out)  # EXPECT[NHD107]
    host2 = dg(out)  # EXPECT[NHD107]
    return arr, host, host2


def megaround_pull(dev):
    claims, counts, need, it = dev.megaround([], [], True)
    c = np.array(claims)  # EXPECT[NHD107]
    n = int(np.asarray(need).sum())  # EXPECT[NHD107]
    k = int(it)  # EXPECT[NHD107] — direct scalar concretization
    f = float(need)  # EXPECT[NHD107]
    s = counts.item()  # EXPECT[NHD107]
    return c, n, k, f, s


def annotated_assign(dev, pods):
    out: object = dev.solve_ranked(pods, 64)
    return np.asarray(out)  # EXPECT[NHD107] — AnnAssign propagates taint


def chained_taint(cluster, pods):
    # taint must survive name-to-name assignment and loop unpacking
    launched = _dispatch_solves(cluster, pods)
    prelaunched = launched
    for G, out in prelaunched:
        arr = np.asarray(out)  # EXPECT[NHD107]
    return arr


def _dispatch_solves(cluster, pods):
    return [(1, object())]
