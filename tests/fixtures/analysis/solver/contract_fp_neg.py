"""AOT fingerprint sources all present: the fingerprint hashes the
module that defines _ARG_ORDER/get_tables (this one) — clean."""

import hashlib
import inspect

import contract_fp_neg

_ARG_ORDER = ("cpu", "mem")
_POD_ARG_ORDER = ("p_cpu",)


def get_tables(u, k):
    return [(u, k)]


def _program_fingerprint():
    h = hashlib.sha256()
    for mod in (contract_fp_neg,):
        h.update(inspect.getsource(mod).encode())
    return h.hexdigest()
