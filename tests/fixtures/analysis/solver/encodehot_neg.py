# nhdlint fixture: NHD108 negatives — delta-path idioms inside solver
# scope that must stay silent.
from nhd_tpu.solver.encode import ClusterDelta, refresh_node_row


def per_event_delta(delta, event):
    # the sanctioned hot-path shape: note + refresh (row patches)
    delta.note(event.node)
    delta.refresh(0.0)
    return delta.drain_dirty()


def per_round_patch(arrays, i, node):
    # a single-row re-projection is exactly the delta the rule wants
    refresh_node_row(arrays, i, node, now=0.0)


def build_delta(nodes):
    # constructing the delta (its init runs the one sanctioned rebuild)
    return ClusterDelta(nodes, now=0.0)


def parity_errors(delta):
    # the continuous re-derivability check re-encodes by design
    from nhd_tpu.solver.encode import encode_cluster

    return encode_cluster(delta.nodes, dims=delta.dims)
