"""The fixed `_pad_own` shape: every path into device-resident arrays
takes an owning copy before a donated dispatch can see it — clean."""

import jax
import jax.numpy as jnp
import numpy as np


def _pad_rows(a, size):
    out = np.zeros((size,) + a.shape[1:], a.dtype)
    out[: a.shape[0]] = a
    return out


def _pad_own(a, size):
    # every return is a call result: an ownership boundary by design
    if a.shape[0] == size:
        return a.copy()
    return _pad_rows(a, size)


def _row_scatter(dst, idx, rows):
    return dst.at[idx].set(rows)


def _get_row_scatter(donate):
    kwargs = {"donate_argnums": (0,)} if donate else {}
    return jax.jit(_row_scatter, **kwargs)


class DeviceState:
    def __init__(self, cluster, names, size):
        self._dev = {}
        for name in names:
            self._dev[name] = jnp.asarray(
                _pad_own(getattr(cluster, name), size)
            )

    def scatter_rows(self, name, idx, rows):
        fn = _get_row_scatter(True)
        self._dev[name] = fn(
            self._dev[name], idx, np.ascontiguousarray(rows)
        )
