"""API-fault chaos (sim/faults.py + the recovery machinery it exercises).

The acceptance story for the fault-tolerance layer: a seeded ChaosSim run
with API-fault injection (dropped/poisoned watch events, transient
bind/annotate failures) must end with zero conservation-invariant
violations and a converged cluster once the faults stop — while the same
storm demonstrably kills an unhardened (reference-stance) stack. The
layer's own counters must be visible through the Prometheus plane.
"""

import queue

import pytest

from nhd_tpu.k8s.fake import FakeClusterBackend
from nhd_tpu.k8s.interface import TransientBackendError
from nhd_tpu.k8s.retry import API_COUNTERS
from nhd_tpu.rpc.metrics import render_metrics
from nhd_tpu.scheduler.controller import Controller
from nhd_tpu.scheduler.core import REQUEUE_MAX, PodStatus, Scheduler
from nhd_tpu.scheduler.events import WatchQueue
from nhd_tpu.sim.chaos import ChaosSim
from nhd_tpu.sim.faults import PROFILES, FaultProfile, FaultyBackend
from nhd_tpu.sim.synth import SynthNodeSpec, make_node_labels, make_triad_config


# ---------------------------------------------------------------------------
# the tier-1 fault-storm case (fast: one seed, short storm; the full
# seeds × profiles matrix runs via `make chaos`, tools/chaos_storm.py)
# ---------------------------------------------------------------------------


def test_chaos_api_fault_storm_converges():
    sim = ChaosSim(seed=1, n_nodes=4, api_faults=PROFILES["storm"])
    stats = sim.run(steps=30)
    assert stats.violations == []
    # the storm actually stormed the API layer
    fs = sim.backend.fault_stats
    assert fs["dropped_events"] > 0
    assert fs["poisoned_events"] > 0
    assert fs["transient_binds"] > 0
    # faults off → the cluster must converge: invariants still clean and
    # no pod stranded by an API fault
    sim.quiesce()
    assert stats.violations == []
    assert sim.stuck_pods() == []
    # backend state == scheduler view
    bound = {
        (p.namespace, p.name): p.node
        for p in sim.backend.pods.values() if p.node
    }
    mirrored = {
        (ns, pod): name
        for name, node in sim.sched.nodes.items()
        for (pod, ns) in node.pod_info
    }
    assert bound == mirrored


def test_chaos_churn_profile_keeps_delta_parity():
    """ISSUE 9 acceptance: the churn profile (heavy event loss/poisoning
    + transient commits + structural node flaps) may cost the
    incremental cluster state full rebuilds, but NEVER a divergent
    resident state — ClusterDelta.parity_errors runs as a per-step sim
    invariant and must stay empty through storm and quiesce."""
    sim = ChaosSim(seed=2, n_nodes=4, api_faults=PROFILES["churn"])
    stats = sim.run(steps=40)
    assert stats.violations == []
    fs = sim.backend.fault_stats
    assert fs["dropped_events"] > 0 or fs["poisoned_events"] > 0
    # the incremental path actually engaged (the parity invariant is
    # not vacuous): the scheduler holds a delta-built context
    if sim.sched._delta is None:
        sim.backend.create_pod("probe", cfg_text=make_triad_config())
        sim._drive_control_plane()
    assert sim.sched._delta is not None
    assert sim.sched._delta.parity_errors() == []
    sim.quiesce()
    assert stats.violations == []
    assert sim.stuck_pods() == []


def test_chaos_delta_parity_invariant_fires_on_divergence():
    """Negative control: corrupt one resident row behind the delta's
    back — the next invariant sweep must report it (a silent invariant
    would make every churn cell vacuously green)."""
    sim = ChaosSim(seed=3, n_nodes=4)
    sim.run(steps=10)
    if sim.sched._delta is None:
        # a restart can land on the final step; one driven batch
        # re-derives the incremental context
        sim.backend.create_pod("probe", cfg_text=make_triad_config())
        sim._drive_control_plane()
    delta = sim.sched._delta
    assert delta is not None
    delta.arrays.hp_free[0] += 1  # divergence no event can explain
    sim.check_invariants()
    assert any("resident-state parity" in v for v in sim.stats.violations)


def test_chaos_heavy_profile_still_conserves():
    sim = ChaosSim(seed=5, n_nodes=4, api_faults=PROFILES["heavy"])
    stats = sim.run(steps=25)
    sim.quiesce()
    assert stats.violations == []
    assert sim.stuck_pods() == []


def test_unhardened_stack_dies_in_the_same_storm():
    """The reference's crash-only stance (no per-event isolation) cannot
    survive a poisoned watch event: the identical seeded storm that the
    hardened stack absorbs kills the controller loop."""
    profile = FaultProfile(name="poison", poison_watch_event=1.0)
    sim = ChaosSim(seed=1, n_nodes=4, api_faults=profile, hardened=False)
    with pytest.raises(TypeError):
        sim.run(steps=10)
    # sanity: hardened, the same storm is survivable
    sim2 = ChaosSim(seed=1, n_nodes=4, api_faults=profile, hardened=True)
    stats = sim2.run(steps=10)
    assert stats.violations == []
    assert sim2.backend.fault_stats["poisoned_events"] >= 10


def test_fault_counters_visible_via_metrics_plane():
    API_COUNTERS.reset()
    sim = ChaosSim(seed=1, n_nodes=4, api_faults=PROFILES["storm"])
    sim.run(steps=30)
    sim.quiesce()
    out = render_metrics([], failed_count=0)
    # the layer's own observability rides the same exposition format
    assert "# TYPE nhd_bind_requeues_total counter" in out
    assert "# TYPE nhd_controller_event_errors_total counter" in out
    assert "# TYPE nhd_api_circuit_state gauge" in out
    snap = API_COUNTERS.snapshot()
    assert snap["bind_requeues_total"] > 0
    assert snap["controller_event_errors_total"] > 0
    assert f"nhd_bind_requeues_total {snap['bind_requeues_total']}" in out


# ---------------------------------------------------------------------------
# transient-commit requeue semantics (scheduler/core.py)
# ---------------------------------------------------------------------------


def _stack(n_nodes=2):
    backend = FakeClusterBackend()
    for i in range(n_nodes):
        spec = SynthNodeSpec(name=f"node{i}")
        backend.add_node(
            spec.name, make_node_labels(spec), hugepages_gb=spec.hugepages_gb
        )
    sched = Scheduler(backend, WatchQueue(), queue.Queue(), respect_busy=False)
    ctrl = Controller(backend, sched.nqueue)
    sched.build_initial_node_list()
    return backend, sched, ctrl


def _drive(sched, ctrl, rounds=8):
    for _ in range(rounds):
        ctrl.run_once(now=0.0)
        while not sched.nqueue.empty():
            sched.run_once()


def test_transient_bind_requeues_and_lands():
    backend, sched, ctrl = _stack()
    faulty = FaultyBackend(
        backend, FaultProfile(name="t", transient_bind=1.0)
    )
    sched.backend = faulty  # scheduler commits through the fault shim
    backend.create_pod("p1", cfg_text=make_triad_config())
    _drive(sched, ctrl)
    pod = backend.pods[("default", "p1")]
    assert pod.node is not None              # second attempt bound it
    assert faulty.fault_stats["transient_binds"] == 1
    assert sched.failed_schedule_count == 0  # never marked failed
    assert sched.pod_state[("default", "p1")]["state"] is PodStatus.SCHEDULED
    assert sched._requeue_attempts == {}     # budget cleared on success


def test_requeue_budget_exhaustion_fails_the_pod():
    """A backend that NEVER stops failing transiently must not spin the
    scheduler forever: after REQUEUE_MAX requeues the pod takes the
    terminal-failure path (and the periodic reconcile still owns later
    retries at its own cadence)."""
    backend, sched, ctrl = _stack()

    class AlwaysTransient(FaultyBackend):
        def bind_pod_to_node(self, pod, node, ns):
            self.fault_stats["transient_binds"] += 1
            raise TransientBackendError("injected: permanently flaky")

    faulty = AlwaysTransient(backend, FaultProfile(name="t"))
    sched.backend = faulty
    backend.create_pod("p1", cfg_text=make_triad_config())
    _drive(sched, ctrl, rounds=REQUEUE_MAX + 4)
    assert backend.pods[("default", "p1")].node is None
    assert sched.pod_state[("default", "p1")]["state"] is PodStatus.FAILED
    assert sched.failed_schedule_count >= 1
    # attempts: 1 initial + REQUEUE_MAX requeues, then the budget tripped
    assert faulty.fault_stats["transient_binds"] == REQUEUE_MAX + 1


def test_transient_annotate_also_requeues():
    backend, sched, ctrl = _stack()
    faulty = FaultyBackend(
        backend, FaultProfile(name="t", transient_annotate=1.0)
    )
    sched.backend = faulty
    backend.create_pod("p1", cfg_text=make_triad_config())
    _drive(sched, ctrl)
    assert backend.pods[("default", "p1")].node is not None
    assert faulty.fault_stats["transient_annotates"] == 1
    assert sched.failed_schedule_count == 0


def test_scheduler_loop_survives_backend_outage():
    """An ApiException that survives the retry layer (outage past the
    deadline / open circuit) escaping the periodic scan must not kill the
    scheduler loop; the mirror is rebuilt once the backend recovers."""
    from nhd_tpu.k8s.restclient import ApiException
    import nhd_tpu.scheduler.core as core_mod

    API_COUNTERS.reset()
    backend, sched, ctrl = _stack()
    backend.create_pod("p1", cfg_text=make_triad_config())
    _drive(sched, ctrl)
    assert backend.pods[("default", "p1")].node is not None

    def down(scheduler):
        raise ApiException(status=0, reason="circuit breaker open")

    backend.service_pods = down  # total outage on the list path
    # idle path reaches the periodic scan with the backend down — the
    # pass is isolated instead of propagating out of run_once
    idle = sched.run_once(idle_count=core_mod.IDLE_CNT_THRESH - 1)
    assert idle == 0
    assert API_COUNTERS.get("scheduler_loop_errors_total") == 1
    assert sched._mirror_dirty is True

    del backend.service_pods  # the API server comes back
    backend.create_pod("p2", cfg_text=make_triad_config())
    _drive(sched, ctrl)
    # the loop kept running, rebuilt the mirror, and scheduling resumed
    assert backend.pods[("default", "p2")].node is not None
    assert sched._mirror_dirty is False
    assert sched.nodes[backend.pods[("default", "p1")].node].pod_present(
        "p1", "default"
    )


# ---------------------------------------------------------------------------
# controller event isolation
# ---------------------------------------------------------------------------


def test_poisoned_event_is_isolated_and_counted():
    API_COUNTERS.reset()
    backend, sched, ctrl = _stack()
    faulty = FaultyBackend(
        backend, FaultProfile(name="p", poison_watch_event=1.0)
    )
    ctrl.backend = faulty
    backend.create_pod("p1", cfg_text=make_triad_config())
    _drive(sched, ctrl, rounds=2)
    # the poisoned event was dropped, the real create event still landed
    assert backend.pods[("default", "p1")].node is not None
    assert API_COUNTERS.get("controller_event_errors_total") >= 1


def test_unisolated_controller_raises():
    backend, sched, _ = _stack()
    ctrl = Controller(backend, sched.nqueue, isolate_events=False)
    faulty = FaultyBackend(
        backend, FaultProfile(name="p", poison_watch_event=1.0)
    )
    ctrl.backend = faulty
    backend.create_pod("p1", cfg_text=make_triad_config())
    with pytest.raises(TypeError):
        ctrl.run_once(now=0.0)


# ---------------------------------------------------------------------------
# solver data-plane injector (sim/faults.py DeviceFaultInjector, ISSUE 12)
# ---------------------------------------------------------------------------


def test_device_injector_sites_and_step_budget():
    """Exceptions route by site under the per-step budget; slow
    dispatches sleep without consuming it."""
    import random

    from nhd_tpu.sim.faults import DeviceFaultInjector
    from nhd_tpu.solver.guard import InjectedDeviceFault

    sleeps = []
    inj = DeviceFaultInjector(
        FaultProfile(
            name="d", device_dispatch_error=1.0, device_upload_error=1.0,
            device_slow_dispatch=1.0, slow_seconds=0.01,
            device_faults_per_step=2,
        ),
        random.Random(0), sleep=sleeps.append,
    )
    with pytest.raises(InjectedDeviceFault, match="dispatch"):
        inj("dispatch", "G1")
    with pytest.raises(InjectedDeviceFault, match="upload"):
        inj("upload", "scatter")
    # budget spent: further calls are quiet (the guard's bounded
    # retries then provably absorb the step)
    inj("dispatch", "G1")
    inj("megaround", "B1")
    assert inj.stats["dispatch_errors"] == 1
    assert inj.stats["upload_errors"] == 1
    # slow dispatches fired on every call, budget-independent
    assert len(sleeps) == 4 and all(s == 0.01 for s in sleeps)
    inj.begin_step()
    with pytest.raises(InjectedDeviceFault):
        inj("megaround", "B1")
    # unknown sites and disabled injectors never raise
    inj.begin_step()
    inj("unknown-site", "x")
    inj.enabled = False
    inj("dispatch", "G1")
    assert inj.stats["dispatch_errors"] == 1


def test_device_profile_classification_and_registry():
    """The device-faults preset storms ONLY the data plane (API-fault
    fields zero — bind parity with a fault-free run depends on it) and
    its injected exception classifies transient."""
    from nhd_tpu.sim.faults import PROFILES
    from nhd_tpu.solver.guard import (
        InjectedDeviceFault, classify_device_fault,
    )

    p = PROFILES["device-faults"]
    assert p.has_device_faults()
    assert p.drop_watch_event == p.poison_watch_event == 0.0
    assert p.transient_bind == p.transient_annotate == 0.0
    assert not FaultProfile(name="api", transient_bind=0.5).has_device_faults()
    assert classify_device_fault(InjectedDeviceFault("x"))
