"""nhdlint contract pack (NHD7xx): drift injection, donation taint,
knob registry, differential mode and SARIF output.

Complements tests/test_static_analysis.py (which owns the per-fixture
EXPECT comparisons and the tier-1 gate): the tests here exercise the
*project-level* behaviors — mutate one consumer layer of a consistent
multi-module fixture project and assert the finding names the specific
layer that fell out of step, exactly the acceptance shape of ISSUE 16.
"""

from __future__ import annotations

import ast
import json
import subprocess
import sys
from pathlib import Path
from typing import Dict, List

import pytest

from nhd_tpu.analysis.core import ModuleSource
from nhd_tpu.analysis.rules_contract import check_project
from nhd_tpu.analysis.cli import main as cli_main

REPO = Path(__file__).resolve().parent.parent
PROJECT = Path(__file__).resolve().parent / "fixtures" / "analysis" \
    / "contract_project"


def _load_project(overrides: Dict[str, str] | None = None) -> List[ModuleSource]:
    """The drift fixture project, optionally with per-file text
    replacements applied (old -> new, must hit exactly once)."""
    overrides = overrides or {}
    modules = []
    for path in sorted(PROJECT.glob("*.py")):
        src = path.read_text()
        if path.name in overrides:
            old, new = overrides[path.name]
            assert src.count(old) == 1, f"ambiguous mutation in {path.name}"
            src = src.replace(old, new)
        modules.append(ModuleSource(path.as_posix(), src, ast.parse(src)))
    return modules


def _messages(findings, rule):
    return [f.message for f in findings if f.rule == rule]


def test_project_is_consistent_as_shipped():
    assert check_project(_load_project()) == []


# ---------------------------------------------------------------------------
# drift injection: remove one array from one consumer layer, assert the
# finding names that specific layer
# ---------------------------------------------------------------------------

def test_drift_delta_fields_names_the_delta_layer():
    findings = check_project(_load_project({
        "encode_like.py": ('    "nic",\n', ""),
    }))
    msgs = _messages(findings, "NHD701")
    assert any(
        "'nic'" in m and "missing from DELTA_FIELDS" in m
        and "delta layer" in m
        for m in msgs
    ), msgs


def test_drift_delta_order_is_nhd702():
    findings = check_project(_load_project({
        "encode_like.py": ('"cpu",\n    "mem"', '"mem",\n    "cpu"'),
    }))
    msgs = _messages(findings, "NHD702")
    assert any("order diverges from _ARG_ORDER" in m for m in msgs), msgs


def test_drift_mesh_sharding_names_the_sharding_layer():
    findings = check_project(_load_project({
        "kernel_like.py": ("(node_spec,) * len(_ARG_ORDER)",
                           "(node_spec,) * 3"),
    }))
    msgs = _messages(findings, "NHD701")
    assert any(
        "in_shardings" in m and "mesh sharding layer" in m for m in msgs
    ), msgs


def test_drift_speculate_stride_names_the_stride_layer():
    findings = check_project(_load_project({
        "speculate_like.py": ("def pod_block(pod_args, b):\n"
                              "    return pod_args[3 * b : 3 * b + 3]",
                              "def pod_block(pod_args, b):\n"
                              "    return pod_args[4 * b : 4 * b + 4]"),
    }))
    msgs = _messages(findings, "NHD701")
    assert any(
        "stride" in m and "speculate stride layer" in m for m in msgs
    ), msgs


def test_drift_unpack_arity():
    findings = check_project(_load_project({
        "speculate_like.py": ("p_cpu, p_mem, p_nic = ",
                              "p_cpu, p_mem = "),
    }))
    msgs = _messages(findings, "NHD701")
    assert any("unpacks 2 names" in m for m in msgs), msgs


def test_drift_fingerprint_source_names_the_module():
    findings = check_project(_load_project({
        "aot_like.py": ("for mod in (kernel_like, combos_like):",
                        "for mod in (kernel_like,):"),
    }))
    msgs = _messages(findings, "NHD703")
    assert any(
        "'combos_like'" in m and "defines get_tables" in m for m in msgs
    ), msgs


def test_drift_partition_drop():
    findings = check_project(_load_project({
        "kernel_like.py": ('_MUTABLE = ("cpu", "busy")',
                           '_MUTABLE = ("cpu",)'),
    }))
    msgs = _messages(findings, "NHD701")
    assert any(
        "'busy'" in m and "neither _MUTABLE nor _STATIC" in m for m in msgs
    ), msgs


def test_conflicting_redefinition_is_nhd702():
    findings = check_project(_load_project({
        "encode_like.py": (
            '"busy",\n)',
            '"busy",\n)\n\nDELTA_FIELDS = ("cpu", "mem")',
        ),
    }))
    msgs = _messages(findings, "NHD702")
    assert any("conflicting definition of DELTA_FIELDS" in m for m in msgs), \
        msgs


def test_test_modules_are_outside_the_contract_model(tmp_path):
    """A test_*.py or conftest.py module never contributes definitions
    or consumers — its scratch tuples must not poison the project."""
    src = 'DELTA_FIELDS = ("bogus",)\n'
    modules = _load_project() + [
        ModuleSource((tmp_path / "test_scratch.py").as_posix(), src,
                     ast.parse(src)),
        ModuleSource((tmp_path / "conftest.py").as_posix(), src,
                     ast.parse(src)),
    ]
    assert check_project(modules) == []


# ---------------------------------------------------------------------------
# baseline rotation for the contract pack
# ---------------------------------------------------------------------------

def test_contract_findings_rotate_through_the_baseline(tmp_path, capsys):
    proj = tmp_path / "proj"
    proj.mkdir()
    for path in PROJECT.glob("*.py"):
        text = path.read_text()
        if path.name == "encode_like.py":
            text = text.replace('    "nic",\n', "")  # inject drift
        (proj / path.name).write_text(text)
    baseline = tmp_path / "bl.json"

    # drift present, no baseline: fails
    assert cli_main([str(proj), "--baseline", str(baseline)]) == 1
    # grandfather it
    assert cli_main([str(proj), "--baseline", str(baseline),
                     "--write-baseline"]) == 0
    # same drift is now baselined, exit clean
    assert cli_main([str(proj), "--baseline", str(baseline)]) == 0
    capsys.readouterr()
    # fixing the drift leaves a stale baseline entry, still exit 0
    (proj / "encode_like.py").write_text(
        (PROJECT / "encode_like.py").read_text()
    )
    assert cli_main([str(proj), "--baseline", str(baseline)]) == 0


# ---------------------------------------------------------------------------
# --diff-base differential mode + --sarif
# ---------------------------------------------------------------------------

def _git(cwd, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=cwd, check=True, capture_output=True,
    )


@pytest.fixture()
def diff_repo(tmp_path, monkeypatch):
    """A throwaway git repo holding one committed clean module."""
    repo = tmp_path / "repo"
    repo.mkdir()
    _git(repo, "init", "-q")
    (repo / "mod.py").write_text(
        "import os\n"
        'KNOBS = ()\n'
        'A = os.environ.get("NHD_OLD_UNREGISTERED", "0")\n'
    )
    _git(repo, "add", "mod.py")
    _git(repo, "commit", "-qm", "seed")
    monkeypatch.chdir(repo)
    return repo


def test_diff_base_gates_only_changed_lines(diff_repo, capsys):
    # grow the file: the NEW unregistered read is on a changed line, the
    # pre-existing one is not
    (diff_repo / "mod.py").write_text(
        "import os\n"
        'KNOBS = ()\n'
        'A = os.environ.get("NHD_OLD_UNREGISTERED", "0")\n'
        'B = os.environ.get("NHD_NEW_UNREGISTERED", "0")\n'
    )
    rc = cli_main(["mod.py", "--packs", "contract", "--no-baseline",
                   "--diff-base", "HEAD"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "NHD_NEW_UNREGISTERED" in out
    assert "advisory: NHD720" in out  # the old one: visible, not gating


def test_diff_base_passes_with_only_preexisting_findings(diff_repo, capsys):
    rc = cli_main(["mod.py", "--packs", "contract", "--no-baseline",
                   "--diff-base", "HEAD"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "1 off-diff advisory" in out


def test_diff_base_bad_rev_is_a_usage_error(diff_repo):
    assert cli_main(["mod.py", "--packs", "contract", "--no-baseline",
                     "--diff-base", "no-such-rev"]) == 2


def test_sarif_output(tmp_path, capsys):
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "mod.py").write_text(
        "import os\n"
        "KNOBS = ()\n"
        'A = os.environ.get("NHD_UNREGISTERED", "0")\n'
    )
    sarif = tmp_path / "out" / "lint.sarif"
    rc = cli_main([str(proj), "--packs", "contract", "--no-baseline",
                   "--sarif", str(sarif)])
    capsys.readouterr()
    assert rc == 1
    doc = json.loads(sarif.read_text())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "nhdlint"
    [rule] = [r for r in run["tool"]["driver"]["rules"]
              if r["id"] == "NHD720"]
    assert rule["properties"]["pack"] == "contract"
    [result] = run["results"]
    assert result["ruleId"] == "NHD720"
    assert result["locations"][0]["physicalLocation"]["region"][
        "startLine"] == 3
    assert result["partialFingerprints"]["nhdlintFingerprint/v1"]


# ---------------------------------------------------------------------------
# knob registry <-> OPERATIONS.md lockstep
# ---------------------------------------------------------------------------

def test_knobs_registry_validates():
    from nhd_tpu.config import knobs

    assert knobs.validate() == []
    assert len(knobs.registered_names()) == len(knobs.KNOBS)


def test_operations_table_is_in_sync_with_registry():
    """What `make check` runs; failing here means someone edited the
    table by hand or registered a knob without --write."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "knobs_sync.py"), "--check"],
        cwd=REPO, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_live_tree_is_contract_clean():
    """The acceptance gate: nhd_tpu/ + tools/ carry zero NHD7xx
    findings (no baseline, no suppressions needed)."""
    from nhd_tpu.analysis import analyze_paths

    reports = analyze_paths(
        [str(REPO / "nhd_tpu"), str(REPO / "tools")], ["contract"]
    )
    findings = [f for r in reports for f in r.findings]
    assert findings == [], [
        (f.rule, f.path, f.line, f.message) for f in findings
    ]
