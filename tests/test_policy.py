"""Policy engine (ISSUE 15): heterogeneity scoring, tiers, preemption.

The standing contracts:

* ``NHD_POLICY=0`` is INERT — score rows are all-zero, the fused ranking
  value reduces bit-exactly to the pre-policy formula, and placements
  match the serial oracle across solve postures (classic host,
  device-resident + speculative, mesh-sharded) exactly as the pre-policy
  suites pin them.
* a uniform matrix is placement-NEUTRAL by construction (constant
  per-type shift of the ranking value cannot reorder nodes);
* a non-uniform matrix reorders placements toward the fast class, and
  flipping the matrix flips the placement;
* preemption victim selection is deterministic under a fixed seed,
  never exceeds the round/tenant budgets, and never selects a victim at
  or above the preemptor's tier;
* every eviction rides the fenced ``_commit_write`` chokepoint — a
  deposed leader's in-flight preemption is fenced out (the HA cell);
* the policy-chaos invariant checkers actually FIRE (negative control).
"""

from __future__ import annotations

import queue

import numpy as np
import pytest

from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
from nhd_tpu.core.topology import MapMode, SmtMode
from nhd_tpu.k8s.fake import FakeClusterBackend
from nhd_tpu.k8s.interface import LEASE_NAME
from nhd_tpu.obs.recorder import FlightRecorder
from nhd_tpu.policy import (
    preempt_pairs,
    reset_policy_metrics,
)
from nhd_tpu.policy.preempt import (
    PreemptBudget,
    plan_preemption,
    round_budget,
)
from nhd_tpu.policy.scoring import score_row, set_matrix
from nhd_tpu.scheduler.core import Scheduler
from nhd_tpu.scheduler.events import WatchQueue
from nhd_tpu.sim.synth import (
    SynthNodeSpec,
    make_cluster,
    make_node_labels,
    make_triad_config,
)
from nhd_tpu.solver.batch import BatchItem, BatchScheduler
from nhd_tpu.solver.oracle import find_node


def _req(gpus=1, proc=4, hp=2, tier=0, groups=frozenset({"default"})):
    return PodRequest(
        groups=(GroupRequest(
            proc=CpuRequest(proc, SmtMode.ON),
            misc=CpuRequest(1, SmtMode.ON),
            gpus=gpus, nic_rx_gbps=10.0, nic_tx_gbps=5.0,
        ),),
        misc=CpuRequest(1, SmtMode.ON),
        hugepages_gb=hp, map_mode=MapMode.NUMA,
        node_groups=groups, tier=tier,
    ).interned()


def _mixed_cluster(n=6):
    """Small fleet whose classes cycle gen-a/gen-b/gen-c."""
    nodes = {}
    for i in range(n):
        spec = SynthNodeSpec(
            name=f"node{i:03d}",
            node_class=("gen-a", "gen-b", "gen-c")[i % 3],
        )
        from nhd_tpu.sim.synth import make_node

        nodes[spec.name] = make_node(spec)
    return nodes


# ---------------------------------------------------------------------------
# NHD_POLICY=0: inert by construction
# ---------------------------------------------------------------------------

def test_score_rows_zero_with_policy_off(monkeypatch):
    monkeypatch.delenv("NHD_POLICY", raising=False)
    assert not score_row(_req()).any()
    monkeypatch.setenv("NHD_POLICY", "0")
    assert not score_row(_req()).any()


@pytest.mark.parametrize("posture", ["classic", "spec", "mesh"])
def test_policy_off_matches_oracle_across_postures(monkeypatch, posture):
    """With the policy off, single-pod placements on a mixed-class fleet
    match the serial oracle — the node_class/class_score arrays ride the
    25-array signature without perturbing a single decision."""
    monkeypatch.setenv("NHD_POLICY", "0")
    kwargs = {}
    if posture == "spec":
        monkeypatch.setenv("NHD_TPU_DEVICE_STATE", "1")
        monkeypatch.setenv("NHD_TPU_SPECULATE", "1")
    elif posture == "mesh":
        import jax

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual mesh")
        from nhd_tpu.parallel.sharding import make_mesh

        kwargs = {"mesh": make_mesh(jax.devices()[:8]),
                  "device_state": True}
    reqs = [_req(gpus=g, proc=p) for g, p in ((1, 4), (0, 6), (1, 2))]
    for r in reqs:
        nodes = _mixed_cluster()
        expect = find_node(nodes, r, now=0.0, respect_busy=False)
        sched = BatchScheduler(
            respect_busy=False, register_pods=False, **kwargs
        )
        results, _stats = sched.schedule(
            _mixed_cluster(), [BatchItem(("ns", "p"), r)], now=0.0
        )
        assert results[0].node == (expect.node if expect else None)


def test_uniform_matrix_is_placement_neutral(monkeypatch):
    """NHD_POLICY=1 with the uniform matrix must place identically to
    the policy-off run: a constant per-type score shift cannot reorder
    nodes."""
    reqs = [_req(gpus=i % 2, proc=3 + i % 3) for i in range(12)]
    items = [BatchItem(("ns", f"p{i}"), r) for i, r in enumerate(reqs)]

    monkeypatch.setenv("NHD_POLICY", "0")
    base, _ = BatchScheduler(respect_busy=False).schedule(
        _mixed_cluster(), items, now=0.0
    )
    monkeypatch.setenv("NHD_POLICY", "1")
    set_matrix({})
    try:
        uni, _ = BatchScheduler(respect_busy=False).schedule(
            _mixed_cluster(), items, now=0.0
        )
    finally:
        set_matrix(None)
    assert [r.node for r in base] == [r.node for r in uni]


# ---------------------------------------------------------------------------
# matrix scoring reorders placements
# ---------------------------------------------------------------------------

def test_matrix_scoring_prefers_fast_class_and_flips(monkeypatch):
    monkeypatch.setenv("NHD_POLICY", "1")
    r = _req()
    try:
        set_matrix({"gpu": {"gen-a": 0.3, "gen-b": 1.0}})
        nodes = _mixed_cluster(2)  # node000=gen-a, node001=gen-b
        res, _ = BatchScheduler(respect_busy=False).schedule(
            nodes, [BatchItem(("ns", "p"), r)], now=0.0
        )
        assert res[0].node == "node001"
        set_matrix({"gpu": {"gen-a": 1.0, "gen-b": 0.3}})
        nodes = _mixed_cluster(2)
        res, _ = BatchScheduler(respect_busy=False).schedule(
            nodes, [BatchItem(("ns", "p"), r)], now=0.0
        )
        assert res[0].node == "node000"
    finally:
        set_matrix(None)


def test_explain_reports_policy_scores(monkeypatch):
    monkeypatch.setenv("NHD_POLICY", "1")
    try:
        set_matrix({"gpu": {"gen-a": 1.0, "gen-b": 0.5}})
        from nhd_tpu.solver.explain import explain

        rep = explain(_mixed_cluster(3), _req(tier=2), respect_busy=False)
        assert rep.policy is not None
        assert rep.policy["tier"] == 2
        assert rep.policy["score_mode"] == 2
        classes = {s["class"] for s in rep.policy["scores"].values()}
        assert "gen-a" in classes
        assert "policy:" in rep.render()
    finally:
        set_matrix(None)


# ---------------------------------------------------------------------------
# preemption planning: deterministic, budgeted, tier-safe
# ---------------------------------------------------------------------------

def _filled_mirror(seed=0):
    """A small saturated mirror: tier-0 pods bound via the batch path
    (register_pods fills node.pod_info, which the planner releases)."""
    import random

    rng = random.Random(seed)
    nodes = make_cluster(
        3, SynthNodeSpec(phys_cores=8, gpus_per_numa=1, hugepages_gb=8)
    )
    sched = BatchScheduler(respect_busy=False, register_pods=True)
    items = [
        BatchItem(("t" + str(rng.randrange(2)), f"low{i}"), _req(hp=4, gpus=0))
        for i in range(6)
    ]
    results, _ = sched.schedule(nodes, items, now=0.0)
    pod_tiers = {}
    for it, r in zip(items, results):
        if r.node is not None:
            pod_tiers[it.key] = (0, float(rng.randrange(100)))
    return nodes, pod_tiers


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_plan_preemption_deterministic_and_budgeted(seed):
    nodes, pod_tiers = _filled_mirror(seed)
    req = _req(hp=4, gpus=0, tier=2)
    budget = PreemptBudget.fresh()
    before = {
        name: (n.mem.free_hugepages_gb,
               sum(1 for c in n.cores if c.used))
        for name, n in nodes.items()
    }
    plan1, why1 = plan_preemption(
        nodes, req, 2, pod_tiers, budget, respect_busy=False
    )
    plan2, why2 = plan_preemption(
        nodes, req, 2, pod_tiers, PreemptBudget.fresh(), respect_busy=False
    )
    # planning is pure: the probe released and re-claimed exactly
    after = {
        name: (n.mem.free_hugepages_gb,
               sum(1 for c in n.cores if c.used))
        for name, n in nodes.items()
    }
    assert before == after
    assert why1 == why2
    if plan1 is None:
        assert plan2 is None
        return
    assert plan1.node == plan2.node
    assert plan1.victims == plan2.victims
    assert len(plan1.victims) <= round_budget()
    per_ns = {}
    for ns, _pod, tier in plan1.victims:
        assert tier < 2
        per_ns[ns] = per_ns.get(ns, 0) + 1
    assert all(v <= budget.tenant_cap for v in per_ns.values())


def test_budget_refusal_reports_exhausted():
    nodes, pod_tiers = _filled_mirror(0)
    req = _req(hp=4, gpus=0, tier=2)
    # a zero budget refuses every plan — and says WHY
    plan, why = plan_preemption(
        nodes, req, 2, pod_tiers,
        PreemptBudget(round_left=0, tenant_cap=0), respect_busy=False,
    )
    assert plan is None
    assert why == "budget-exhausted"


# ---------------------------------------------------------------------------
# scheduler end-to-end: fenced evict, unwind, requeue, corr journey
# ---------------------------------------------------------------------------

def _policy_sched(n_nodes=1, recorder=None, elector=None):
    backend = FakeClusterBackend()
    for i in range(n_nodes):
        spec = SynthNodeSpec(
            name=f"pn{i}", phys_cores=8, gpus_per_numa=1, hugepages_gb=8,
            node_class="gen-a",
        )
        backend.add_node(
            spec.name, make_node_labels(spec), hugepages_gb=8
        )
    sched = Scheduler(
        backend, WatchQueue(), queue.Queue(), respect_busy=False,
        recorder=recorder, elector=elector,
    )
    sched.build_initial_node_list()
    return backend, sched


def test_preempt_end_to_end_corr_journey(monkeypatch):
    monkeypatch.setenv("NHD_POLICY", "1")
    reset_policy_metrics()
    rec = FlightRecorder(capacity=512, identity="t")
    backend, sched = _policy_sched(recorder=rec)
    cfg = make_triad_config(cpu_workers=2, hugepages_gb=4)
    low = backend.create_pod("low", cfg_text=cfg, tier=0)
    sched.attempt_scheduling_batch([(low.name, low.namespace, low.uid)])
    assert backend.pods[("default", "low")].node == "pn0"

    high = backend.create_pod("high", cfg_text=cfg, tier=2)
    sched.attempt_scheduling_batch([(high.name, high.namespace, high.uid)])
    # the fenced eviction landed and was logged
    assert [e[:2] for e in backend.evict_log] == [("default", "low")]
    # drain: preemptor binds FIRST (FIFO — a victim requeued ahead of it
    # would re-take the freed capacity), then the victim resolves
    for _ in range(12):
        if sched.nqueue.empty():
            break
        sched.run_once()
    assert backend.pods[("default", "high")].node == "pn0"
    assert backend.pods[("default", "low")].node is None
    # explicit verdict for the victim (cluster full: unschedulable)
    assert any(
        e.pod == "low" and e.reason == "FailedScheduling"
        for e in backend.events
    )
    # one corr ID per journey: the victim's scheduled → preempted →
    # verdict decisions all carry the corr its first bind recorded
    decs = rec.recent_decisions(100)
    low_corrs = {
        d["corr"] for d in decs if d["pod"] == "low" and d["corr"]
    }
    assert len(low_corrs) == 1
    outcomes = [d["outcome"] for d in decs if d["pod"] == "low"]
    assert "scheduled" in outcomes and "preempted" in outcomes
    # the preemptor's decision carries the victim set + budget state
    pre = [d for d in decs if d["outcome"] == "preempt-requeued"]
    assert pre and pre[0]["victims"][0]["pod"] == "default/low"
    assert "round_left" in pre[0]["budget"]
    assert preempt_pairs() == [(2, 0)]


def test_gpu_preemptor_rebinds_under_busy_backoff(monkeypatch):
    """The freed node must be immediately claimable by a GPU preemptor
    under respect_busy=True: the victim release does NOT stamp the node
    busy (a stamped node is infeasible for GPU pods for MIN_BUSY_SECS —
    evicting victims and then hiding the freed capacity from the pod it
    was freed for would self-defeat the whole path)."""
    monkeypatch.setenv("NHD_POLICY", "1")
    reset_policy_metrics()
    backend = FakeClusterBackend()
    spec = SynthNodeSpec(
        name="pn0", phys_cores=8, gpus_per_numa=1, hugepages_gb=8,
        node_class="gen-a",
    )
    backend.add_node(spec.name, make_node_labels(spec), hugepages_gb=8)
    sched = Scheduler(
        backend, WatchQueue(), queue.Queue(), respect_busy=True,
    )
    sched.build_initial_node_list()
    cfg = make_triad_config(cpu_workers=2, hugepages_gb=4, gpus_per_group=1)
    low = backend.create_pod("low", cfg_text=cfg, tier=0)
    sched.attempt_scheduling_batch([(low.name, low.namespace, low.uid)])
    assert backend.pods[("default", "low")].node == "pn0"
    # age out the bind-time busy stamp (the reference's placement
    # rate-limit, not the preemption path under test)
    for n in sched.nodes.values():
        n._busy_time = float("-inf")
    high = backend.create_pod("high", cfg_text=cfg, tier=2)
    sched.attempt_scheduling_batch([(high.name, high.namespace, high.uid)])
    assert [e[:2] for e in backend.evict_log] == [("default", "low")]
    for _ in range(12):
        if sched.nqueue.empty():
            break
        sched.run_once()
    # the GPU preemptor landed on the freed node IMMEDIATELY — no
    # MIN_BUSY_SECS window hid the capacity
    assert backend.pods[("default", "high")].node == "pn0"


def test_preempt_tier_ordering_never_evicts_equal_or_higher(monkeypatch):
    monkeypatch.setenv("NHD_POLICY", "1")
    reset_policy_metrics()
    backend, sched = _policy_sched()
    cfg = make_triad_config(cpu_workers=2, hugepages_gb=4)
    mid = backend.create_pod("mid", cfg_text=cfg, tier=2)
    sched.attempt_scheduling_batch([(mid.name, mid.namespace, mid.uid)])
    same = backend.create_pod("same", cfg_text=cfg, tier=2)
    sched.attempt_scheduling_batch([(same.name, same.namespace, same.uid)])
    # equal tier: no eviction, plain unschedulable verdict
    assert not backend.evict_log
    assert any(
        e.pod == "same" and e.reason == "FailedScheduling"
        for e in backend.events
    )


def test_preempt_budget_bounds_one_batch(monkeypatch):
    monkeypatch.setenv("NHD_POLICY", "1")
    monkeypatch.setenv("NHD_POLICY_PREEMPT_ROUND_BUDGET", "1")
    reset_policy_metrics()
    backend, sched = _policy_sched(n_nodes=2)
    cfg = make_triad_config(cpu_workers=2, hugepages_gb=4)
    batch = []
    for i in range(4):
        p = backend.create_pod(f"low{i}", cfg_text=cfg, tier=0)
        batch.append((p.name, p.namespace, p.uid))
    sched.attempt_scheduling_batch(batch)
    bound_before = len(backend.bind_log)
    assert bound_before >= 2
    batch = []
    for i in range(3):
        p = backend.create_pod(f"high{i}", cfg_text=cfg, tier=2)
        batch.append((p.name, p.namespace, p.uid))
    sched.attempt_scheduling_batch(batch)
    # ONE batch may evict at most the round budget
    assert len(backend.evict_log) <= 1


def test_deposed_leader_preemption_is_fenced_out(monkeypatch):
    """The HA cell: a deposed leader's in-flight preemption must not
    land — the backend rejects the stale-epoch evict, the victim keeps
    its binding AND its mirror claims."""
    from nhd_tpu.k8s.lease import LeaderElector

    monkeypatch.setenv("NHD_POLICY", "1")
    reset_policy_metrics()
    backend = FakeClusterBackend()
    spec = SynthNodeSpec(
        name="pn0", phys_cores=8, gpus_per_numa=1, hugepages_gb=8,
        node_class="gen-a",
    )
    backend.add_node(spec.name, make_node_labels(spec), hugepages_gb=8)
    elector = LeaderElector(backend, identity="a", ttl=60.0)
    elector.tick()
    assert elector.is_leader
    sched = Scheduler(
        backend, WatchQueue(), queue.Queue(), respect_busy=False,
        elector=elector,
    )
    sched.build_initial_node_list()
    cfg = make_triad_config(cpu_workers=2, hugepages_gb=4)
    low = backend.create_pod("low", cfg_text=cfg, tier=0)
    sched.attempt_scheduling_batch([(low.name, low.namespace, low.uid)])
    assert backend.pods[("default", "low")].node == "pn0"
    # a rival acquisition bumps the epoch behind this replica's back —
    # the replica still BELIEVES it leads (the split-brain window)
    backend.leases[LEASE_NAME].epoch += 1
    high = backend.create_pod("high", cfg_text=cfg, tier=2)
    sched.attempt_scheduling_batch([(high.name, high.namespace, high.uid)])
    # the eviction was fenced out: no log entry, victim still bound,
    # mirror claims intact
    assert not backend.evict_log
    assert backend.pods[("default", "low")].node == "pn0"
    assert sched.nodes["pn0"].pod_present("low", "default")
    assert not preempt_pairs()


# ---------------------------------------------------------------------------
# chaos cells: fast positive + the negative control
# ---------------------------------------------------------------------------

def test_policy_chaos_fast_cell(monkeypatch):
    monkeypatch.setenv("NHD_POLICY", "1")
    from nhd_tpu.sim.chaos import ChaosSim

    sim = ChaosSim(seed=3, n_nodes=4, policy="mixed-gen")
    sim.run(steps=15)
    sim.quiesce()
    assert sim.stats.violations == []
    assert sim.stuck_pods() == []
    assert sim.policy_victims_unresolved() == []


def test_policy_chaos_control_cell(monkeypatch):
    monkeypatch.setenv("NHD_POLICY", "0")
    from nhd_tpu.sim.chaos import ChaosSim

    sim = ChaosSim(seed=3, n_nodes=4, policy="mixed-gen", policy_off=True)
    sim.run(steps=15)
    sim.quiesce()
    assert sim.stats.violations == []
    assert sim.base.evict_log == []


def test_policy_invariants_fire_negative_control(monkeypatch):
    """The checkers must DETECT violations, not just pass clean runs:
    an over-budget eviction burst, a cascade, and a tier inversion each
    trip their invariant."""
    monkeypatch.setenv("NHD_POLICY", "1")
    from nhd_tpu import policy as pol
    from nhd_tpu.sim.chaos import (
        POLICY_CASCADE_BOUND,
        POLICY_PASSES_PER_STEP,
        ChaosSim,
    )
    from nhd_tpu.policy.preempt import round_budget as rb

    reset_policy_metrics()
    sim = ChaosSim(seed=0, n_nodes=3, policy="mixed-gen")
    # per-step bound: a burst past round_budget × passes trips
    burst = rb() * POLICY_PASSES_PER_STEP + 1
    sim.base.evict_log.extend(
        ("default", f"x{i}", f"u{i}", "node0", None, None)
        for i in range(burst)
    )
    sim._check_policy_invariants()
    assert any("per-step bound" in v for v in sim.stats.violations)
    # cascade: one pod evicted past the bound
    sim.stats.violations.clear()
    sim.base.evict_log[:] = [
        ("default", "same", "u", "node0", None, None)
    ] * (POLICY_CASCADE_BOUND + 1)
    sim._check_policy_invariants()
    assert any("cascade" in v for v in sim.stats.violations)
    # tier inversion: victim tier >= preemptor tier
    sim.stats.violations.clear()
    sim.base.evict_log.clear()
    sim._evicts_seen = 0
    pol.note_preemption(1, 2)
    sim._check_policy_invariants()
    assert any("tier inversion" in v for v in sim.stats.violations)
    reset_policy_metrics()


# ---------------------------------------------------------------------------
# metrics + fleet payload
# ---------------------------------------------------------------------------

def test_policy_metrics_render_and_fleet_payload(monkeypatch):
    monkeypatch.setenv("NHD_POLICY", "1")
    from nhd_tpu import policy as pol
    from nhd_tpu.rpc.metrics import render_metrics

    reset_policy_metrics()
    pol.note_preemption(2, 0)
    pol.note_preemption(2, 1)
    try:
        set_matrix({"gpu": {"gen-a": 1.0}})
        text = render_metrics([], 0)
    finally:
        set_matrix(None)
    assert "nhd_policy_preemptions_total" in text
    assert 'nhd_policy_preemptions_by_tier_total{tier="0"} 1' in text
    assert 'nhd_policy_preemptions_by_tier_total{tier="1"} 1' in text
    assert "nhd_policy_score_mode 2" in text

    from nhd_tpu.obs.fleet import build_fleet_artifact, replica_view

    art = build_fleet_artifact(
        [replica_view("r1")],
        counters={"policy_preemptions_total": 3, "policy_score_mode": 2},
    )
    assert art["payload"]["policy"]["preemptions_total"] == 3
    assert art["payload"]["policy"]["score_mode"] == 2
    reset_policy_metrics()


def test_tier_label_vocabulary_is_bounded():
    from nhd_tpu.policy import MAX_TIER_LABEL, preempt_tier_snapshot

    reset_policy_metrics()
    from nhd_tpu import policy as pol

    pol.note_preemption(99, 42)
    snap = preempt_tier_snapshot()
    assert set(snap) == {MAX_TIER_LABEL}
    reset_policy_metrics()


# ---------------------------------------------------------------------------
# encode/delta: node_class rides the incremental state
# ---------------------------------------------------------------------------

def test_node_class_rides_delta_parity(monkeypatch):
    """A class-labeled node patched through the delta layer stays
    bit-exact with a from-scratch encode (node_class is a DELTA_FIELDS
    member like every other per-row array)."""
    from nhd_tpu.solver.encode import ClusterDelta

    nodes = _mixed_cluster(4)
    delta = ClusterDelta(nodes, respect_busy=False)
    assert delta.parity_errors() == []
    # label reparse re-classes a node → generation rebuild, still exact
    name = next(iter(nodes))
    spec = SynthNodeSpec(name=name, node_class="gen-z")
    nodes[name].parse_labels(make_node_labels(spec))
    delta.note(name)
    delta.refresh()
    assert delta.parity_errors() == []
    from nhd_tpu.policy.classes import CLASSES

    row = delta.arrays.names.index(name)
    assert delta.arrays.node_class[row] == CLASSES.index("gen-z")
