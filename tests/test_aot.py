"""AOT StableHLO program cache (solver/aot.py): export-on-first-trace,
versioned cache keys with quarantine, prewarm serving parity, and the
zero-recompile invariant under a seeded chaos storm.

The cache is process-global (like the jit cache it fronts), so every
test runs against a fresh tmp directory via the ``aot_cache`` fixture
and resets the program table afterwards."""

from __future__ import annotations

import json
import logging
import os

import numpy as np
import pytest

from nhd_tpu.obs.jitstats import JIT_STATS
from nhd_tpu.solver import aot
from nhd_tpu.solver.kernel import (
    get_ranked_solver,
    get_solver,
    solve_bucket_ranked,
)


@pytest.fixture
def aot_cache(tmp_path):
    aot.reset()
    aot.configure(directory=str(tmp_path), save=True)
    yield str(tmp_path)
    aot.reset()


def _small_problem(n_nodes=16, n_pods=24):
    from nhd_tpu.sim.workloads import cap_cluster, workload_mix
    from nhd_tpu.solver.encode import encode_cluster, encode_pods

    nodes = cap_cluster(n_nodes, ["default"])
    reqs = workload_mix(n_pods, ["default"])
    cluster = encode_cluster(nodes, now=0.0)
    return cluster, encode_pods(reqs, cluster.interner)


def _seed_cache(aot_cache):
    """Run the live path with saving on; returns {G: packed tensor}."""
    cluster, buckets = _small_problem()
    outs = {
        G: np.asarray(solve_bucket_ranked(cluster, pods, 64))
        for G, pods in sorted(buckets.items())
    }
    aot.AOT.drain()
    return cluster, buckets, outs


def test_export_on_first_trace_writes_versioned_artifacts(aot_cache):
    _seed_cache(aot_cache)
    metas = sorted(f for f in os.listdir(aot_cache) if f.endswith(".json"))
    bins = sorted(
        f for f in os.listdir(aot_cache) if f.endswith(".stablehlo.bin")
    )
    assert metas and len(metas) == len(bins)
    for fname in metas:
        meta = json.load(open(os.path.join(aot_cache, fname)))
        # the versioned cache key: jax/jaxlib versions + platform list +
        # program fingerprint + every specializing dim
        import jax

        assert meta["jax_version"] == jax.__version__
        assert meta["fingerprint"] == aot.program_fingerprint()
        assert "cpu" in meta["platforms"]
        for dim in ("G", "U", "K", "R", "Tp", "Np"):
            assert isinstance(meta[dim], int)


def test_prewarm_serves_bit_identical_results(aot_cache):
    cluster, buckets, outs = _seed_cache(aot_cache)
    # fresh program table: disk is now the only source
    aot.reset()
    aot.configure(directory=aot_cache, save=False)
    summary = aot.prewarm()
    assert summary["loaded"] == len(outs)
    assert summary["quarantined"] == 0
    for G, pods in sorted(buckets.items()):
        got = np.asarray(solve_bucket_ranked(cluster, pods, 64))
        assert np.array_equal(got, outs[G])


def test_stale_artifact_quarantined_not_deleted(aot_cache):
    cluster, buckets, outs = _seed_cache(aot_cache)
    # a jaxlib upgrade happened: every meta reports the old version
    metas = [f for f in os.listdir(aot_cache) if f.endswith(".json")]
    for fname in metas:
        path = os.path.join(aot_cache, fname)
        meta = json.load(open(path))
        meta["jax_version"] = "0.0.0-stale"
        json.dump(meta, open(path, "w"))
    aot.reset()
    aot.configure(directory=aot_cache, save=False)
    # nhd loggers don't propagate to root (caplog-invisible): capture
    # with a handler on the module logger itself
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    logger = logging.getLogger("nhd_tpu.solver.aot")
    logger.addHandler(handler)
    try:
        summary = aot.prewarm()
    finally:
        logger.removeHandler(handler)
    assert summary["loaded"] == 0
    assert summary["quarantined"] == len(metas)
    # quarantined, never deleted: both files of every pair moved intact
    qdir = os.path.join(aot_cache, "quarantine")
    moved = sorted(os.listdir(qdir))
    assert len(moved) == 2 * len(metas)
    assert not any(f.endswith(".json") for f in os.listdir(aot_cache))
    # exactly ONE warning covers the whole stale set
    warnings = [
        r for r in records
        if r.levelno >= logging.WARNING and "quarantined" in r.getMessage()
    ]
    assert len(warnings) == 1
    # and serving falls back to a live re-trace, bit-identical
    for G, pods in sorted(buckets.items()):
        got = np.asarray(solve_bucket_ranked(cluster, pods, 64))
        assert np.array_equal(got, outs[G])


def test_fingerprint_mismatch_and_corrupt_blob_quarantined(aot_cache):
    _seed_cache(aot_cache)
    metas = sorted(f for f in os.listdir(aot_cache) if f.endswith(".json"))
    # artifact 0: solver code changed under the artifact
    p0 = os.path.join(aot_cache, metas[0])
    meta = json.load(open(p0))
    meta["fingerprint"] = "deadbeefdeadbeef"
    json.dump(meta, open(p0, "w"))
    if len(metas) > 1:
        # artifact 1: truncated blob (deserialize must fail gracefully)
        b1 = os.path.join(
            aot_cache, metas[1].replace(".json", ".stablehlo.bin")
        )
        open(b1, "wb").write(b"\x00\x01not-stablehlo")
    aot.reset()
    aot.configure(directory=aot_cache, save=False)
    summary = aot.prewarm()
    assert summary["loaded"] == 0
    assert summary["quarantined"] == len(metas)


def test_zero_recompile_invariant_under_chaos(aot_cache, monkeypatch):
    """The acceptance pin: with prewarm on, a seeded ChaosSim storm
    dispatches ONLY prewarmed shapes — the nhd_jit_* compile counters
    stay flat after warmup, and any shape-bucket escape fails the test
    NAMING the escaped shape key."""
    from nhd_tpu.sim.chaos import ChaosSim
    from nhd_tpu.sim.faults import PROFILES

    # the production CPU-daemon posture: single-device host solves (the
    # conftest's 8-virtual-device mesh would route to the SPMD path,
    # which a real CPU daemon never takes)
    monkeypatch.setenv("NHD_TPU_DEVICE_STATE", "0")

    # warmup/seed phase: the same seeded profile (and step span) the
    # steady-state phase replays — every bucketed shape it produces gets
    # traced AND exported to the AOT cache. Identical seed + span means
    # an escape below is a prewarm coverage hole, never workload drift.
    sim = ChaosSim(seed=11, n_nodes=4, api_faults=PROFILES["light"])
    sim.run(60)
    sim.quiesce()
    aot.AOT.drain()
    assert any(f.endswith(".stablehlo.bin") for f in os.listdir(aot_cache))

    # restart-equivalent: drop every live program, then prewarm from the
    # artifact cache alone (this is what `nhd-tpu --prewarm` does)
    get_ranked_solver.cache_clear()
    get_solver.cache_clear()
    JIT_STATS.reset()
    aot.reset()
    aot.configure(directory=aot_cache, save=False)
    summary = aot.prewarm()
    assert summary["loaded"] > 0
    warm = JIT_STATS.snapshot()
    warm_shapes = set(warm["shapes"])

    # steady state: more storm + convergence against the same sim
    sim2 = ChaosSim(seed=11, n_nodes=4, api_faults=PROFILES["light"])
    sim2.run(60)
    sim2.quiesce()
    steady = JIT_STATS.snapshot()
    escaped = sorted(set(steady["shapes"]) - warm_shapes)
    assert steady["compiles_total"] == warm["compiles_total"], (
        f"shape-bucket escape at steady state: {escaped} "
        f"(prewarmed: {sorted(warm_shapes)})"
    )
    # and the storm actually dispatched (hits, not silence)
    assert steady["cache_hits_total"] > warm["cache_hits_total"]


def test_bench_diff_gates_first_bind_phases():
    """The perf pipeline wiring: a first_bind_prewarmed regression past
    the (doubled) latency threshold fails the diff; an improvement or
    in-band drift passes."""
    from nhd_tpu.obs.perf import build_bench_artifact, config_record
    from tools.bench_diff import WATCHED_PHASES, diff_artifacts

    assert "first_bind_prewarmed" in WATCHED_PHASES
    assert "prewarm" in WATCHED_PHASES

    def artifact(first_bind):
        return build_bench_artifact(
            {
                "first-bind": config_record(
                    wall_seconds=2.5, placed=1, speedup=10.0, rounds=1,
                    phases={
                        "first_bind_cold": 2.5,
                        "prewarm": 1.0,
                        "first_bind_prewarmed": first_bind,
                    },
                ),
            },
            headline={"metric": "m", "value": 1.0, "unit": "pods/s"},
            platform="cpu",
        )

    old = artifact(0.100)
    _, regressions = diff_artifacts(
        old, artifact(0.300), threshold=0.10, floor=0.005,
        phases=WATCHED_PHASES,
    )
    assert any("first_bind_prewarmed" in r for r in regressions)
    # 15% drift on a latency config stays under the doubled threshold,
    # and the cold wall (subprocess compile jitter) is never gated
    _, regressions = diff_artifacts(
        old, artifact(0.115), threshold=0.10, floor=0.005,
        phases=WATCHED_PHASES,
    )
    assert regressions == []
    _, regressions = diff_artifacts(
        old, artifact(0.050), threshold=0.10, floor=0.005,
        phases=WATCHED_PHASES,
    )
    assert regressions == []


def test_bench_diff_wall_gate_absolute_and_relative():
    """A wall regression is fatal only past BOTH bounds: a jitter-scale
    blip on a tiny config passes, a sub-floor baseline blowing up to
    seconds fails."""
    from nhd_tpu.obs.perf import build_bench_artifact, config_record
    from tools.bench_diff import diff_artifacts

    def artifact(wall):
        return build_bench_artifact(
            {"cfg1:100x32": config_record(
                wall_seconds=wall, placed=100, speedup=1.0,
                phases={"solve": 0.002},
            )},
            headline={"metric": "m", "value": 1.0, "unit": "pods/s"},
            platform="cpu",
        )

    # +21% on a 13 ms wall = 3 ms growth: under the absolute floor
    _, regressions = diff_artifacts(
        artifact(0.013), artifact(0.0158), threshold=0.10, floor=0.005,
    )
    assert regressions == []
    # 45 ms -> 5 s: sub-floor baseline, but the growth is real
    _, regressions = diff_artifacts(
        artifact(0.045), artifact(5.0), threshold=0.10, floor=0.005,
    )
    assert any("wall regressed" in r for r in regressions)


def test_prewarm_progress_called_per_artifact(aot_cache):
    """The stall-watchdog grace hook (ISSUE 12 satellite): prewarm
    invokes ``progress`` once per artifact processed — loaded AND
    quarantined — so a long multi-artifact compile advances the loop
    heartbeat artifact by artifact."""
    _seed_cache(aot_cache)
    # one stale artifact rides along: progress must tick for it too
    stale = sorted(
        f for f in os.listdir(aot_cache) if f.endswith(".json")
    )[0]
    meta = json.load(open(os.path.join(aot_cache, stale)))
    meta["fingerprint"] = "0" * 16
    path = os.path.join(aot_cache, "zz_stale.json")
    with open(path, "w") as fh:
        json.dump(meta, fh)
    aot.reset()
    aot.configure(directory=aot_cache, save=False)
    beats = []
    summary = aot.prewarm(progress=lambda: beats.append(1))
    assert summary["loaded"] >= 1 and summary["quarantined"] >= 1
    assert len(beats) == (
        summary["loaded"] + summary["quarantined"] + summary["skipped"]
    )


def test_prewarm_progress_keeps_watchdog_quiet_on_slow_compiles():
    """Regression (injected slow compile): with per-artifact heartbeats
    a prewarm whose every compile eats most of the stall budget never
    trips the watchdog; without them the same timeline fires it."""
    from nhd_tpu.k8s.lease import StallWatchdog

    for with_progress, expect_fired in ((True, False), (False, True)):
        clock = {"t": 0.0}
        stamp = {"t": 0.0}
        fired = []
        dog = StallWatchdog(
            lambda: stamp["t"], stall_after=10.0,
            exit_fn=lambda code: fired.append(code),
            clock=lambda: clock["t"],
        )
        for _ in range(4):  # four artifacts, 8s of compile each
            clock["t"] += 8.0
            if with_progress:
                stamp["t"] = clock["t"]  # aot.prewarm(progress=_beat)
            dog.check()
        assert bool(fired) == expect_fired, (with_progress, fired)


def test_export_failure_counted_and_logged_once(aot_cache, monkeypatch):
    """The background export worker's failures were invisible (ISSUE 12
    satellite): a failing serialize now ticks
    nhd_aot_export_failures_total per failure and logs once per run
    with the shape key."""
    import jax.export as jexport

    from nhd_tpu.k8s.retry import API_COUNTERS

    def _boom(*a, **k):
        raise RuntimeError("injected serialize failure")

    monkeypatch.setattr(jexport, "export", _boom)
    base = API_COUNTERS.get("aot_export_failures_total")
    key1 = aot.ShapeKey("ranked", 1, 2, 2, 8, 8, 16)
    key2 = aot.ShapeKey("ranked", 2, 2, 2, 8, 8, 16)
    fn = get_ranked_solver(1, 2, 2, 8)
    args = [np.zeros(4, np.int32)]
    aot.maybe_export(key1, fn, args)
    aot.maybe_export(key2, fn, args)
    aot.AOT.drain()
    assert API_COUNTERS.get("aot_export_failures_total") == base + 2
    # no artifact landed for either key
    assert not [
        f for f in os.listdir(aot_cache) if f.endswith(".stablehlo.bin")
    ]


def test_forget_retires_program_and_quarantines_artifact(aot_cache):
    """aot.forget (the solver guard's poisoned-program hook): the
    installed program is dropped and the on-disk pair moves to
    quarantine/ — never deleted."""
    _seed_cache(aot_cache)
    aot.reset()
    aot.configure(directory=aot_cache, save=False)
    summary = aot.prewarm()
    assert summary["loaded"] >= 1
    name = summary["keys"][0]
    key = next(k for k in aot.AOT._programs if k.name() == name)
    aot.forget(key)
    assert aot.lookup(key) is None
    qdir = os.path.join(aot_cache, "quarantine")
    assert os.path.exists(os.path.join(qdir, f"{name}.stablehlo.bin"))
    assert not os.path.exists(
        os.path.join(aot_cache, f"{name}.stablehlo.bin")
    )
