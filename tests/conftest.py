"""Test harness: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import — pytest imports conftest first, so setting
the env here guarantees every test module sees 8 virtual CPU devices,
giving a multi-chip sharding story without TPU hardware. This *overrides*
any inherited JAX_PLATFORMS (the dev box exports a TPU backend by default;
unit tests must not depend on, or be slowed by, real hardware).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# persistent compile cache: the suite compiles ~a dozen solver shapes; repeat
# runs hit the cache instead of recompiling each (G, U, K) bucket. This jax
# build ignores the JAX_COMPILATION_CACHE_DIR env var, so configure via API.
import jax  # noqa: E402  (env vars above must be set first)

jax.config.update("jax_compilation_cache_dir", "/tmp/nhd_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

# Unit tests must never depend on TPU tunnel health — the shared helper
# drops the tunnel-backed plugin factory and pins jax_platforms=cpu
# (see nhd_tpu/utils/platform.py for why both legs are needed)
from nhd_tpu.utils import force_cpu_backend  # noqa: E402

force_cpu_backend(jax)


def subprocess_env(**extra):
    """Environment for subprocess tests: repo root PREPENDED to
    PYTHONPATH, never overwriting it (the sitecustomize plugin lives
    there — see the TPU environment notes). Shared by every test that
    spawns a python child."""
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root]
        + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    )
    env.update(extra)
    return env
