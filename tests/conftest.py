"""Test harness: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import — pytest imports conftest first, so setting
the env here guarantees every test module sees 8 virtual CPU devices,
giving a multi-chip sharding story without TPU hardware. This *overrides*
any inherited JAX_PLATFORMS (the dev box exports a TPU backend by default;
unit tests must not depend on, or be slowed by, real hardware).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# persistent compile cache: the suite compiles ~a dozen solver shapes; repeat
# runs hit the cache instead of recompiling each (G, U, K) bucket. This jax
# build ignores the JAX_COMPILATION_CACHE_DIR env var, so configure via API.
import jax  # noqa: E402  (env vars above must be set first)

jax.config.update("jax_compilation_cache_dir", "/tmp/nhd_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

# Unit tests must never depend on TPU tunnel health — the shared helper
# drops the tunnel-backed plugin factory and pins jax_platforms=cpu
# (see nhd_tpu/utils/platform.py for why both legs are needed)
from nhd_tpu.utils import force_cpu_backend  # noqa: E402

force_cpu_backend(jax)


def subprocess_env(**extra):
    """Environment for subprocess tests: repo root PREPENDED to
    PYTHONPATH, never overwriting it (the sitecustomize plugin lives
    there — see the TPU environment notes). Shared by every test that
    spawns a python child."""
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root]
        + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    )
    env.update(extra)
    return env


# ---------------------------------------------------------------------------
# nhdsan: NHD_SAN=1 runs the whole session under the runtime deadlock
# sanitizer (docs/OBSERVABILITY.md). Installed at conftest IMPORT time —
# before pytest collection imports any test module — so module-level
# locks in nhd_tpu (created while tests import, e.g. solver/streaming's
# _CPU_MESH_SOLVE_LOCK) are instrumented too. Only jax internals and the
# stdlib machinery imported above stay raw, by design.
# ---------------------------------------------------------------------------

import json  # noqa: E402

import pytest  # noqa: E402

if os.environ.get("NHD_SAN") == "1":
    from nhd_tpu.sanitizer import install as _nhd_san_install

    _nhd_san_install()

# NHD_RACE=1 layers the Eraser-style race sanitizer on top (installing
# nhdsan implicitly — locksets come from its instrumented locks). Same
# import-time rule: product objects constructed during collection get
# their maybe_watch() registrations instrumented. NHD_RACE_INJECT=1
# makes install run the injected-race negative control, so this session
# MUST then fail the race assertion below — the detection proof.
if os.environ.get("NHD_RACE") == "1":
    from nhd_tpu.sanitizer import install_races as _nhd_race_install

    _nhd_race_install()


@pytest.fixture(autouse=True, scope="session")
def nhd_san_session():
    """When NHD_SAN=1 the sanitizer was installed at conftest import
    (above); this fixture owns the teardown: dump the witness report
    (NHD_SAN_REPORT, default /tmp/nhd_san_report.json) and fail the
    session if any wait-for-graph cycle was observed — a deadlock the
    per-test layer converted into a DeadlockError, or one recorded by a
    thread whose test had already moved on."""
    if os.environ.get("NHD_SAN") != "1" and os.environ.get("NHD_RACE") != "1":
        yield
        return
    from nhd_tpu.sanitizer import (
        get_race_sanitizer,
        get_sanitizer,
        uninstall,
        uninstall_races,
    )

    san = get_sanitizer()
    assert san is not None, "NHD_SAN/NHD_RACE set but install did not run"
    race_san = get_race_sanitizer()
    try:
        yield
    finally:
        race_report = None
        if race_san is not None:
            uninstall_races()
            race_report = race_san.report()
        uninstall()
        report = san.report()
        out = os.environ.get("NHD_SAN_REPORT", "/tmp/nhd_san_report.json")
        try:
            with open(out, "w") as fh:
                json.dump(
                    {"report": report, "races": race_report,
                     "trace": san.chrome_trace()},
                    fh, indent=2,
                )
        except OSError:
            pass
    assert not report["cycles"], (
        f"nhdsan observed {len(report['cycles'])} wait-for-graph "
        f"cycle(s); full witnesses in {out}"
    )
    if race_report is not None:
        assert not race_report["races"], (
            f"nhdrace observed {len(race_report['races'])} unsuppressed "
            f"data race(s) on watched shared state "
            f"({[r['key'] for r in race_report['races']]}); full report "
            f"in {out} — fix the race or allowlist the key via "
            f"NHD_RACE_ALLOW with a written justification"
        )
