"""Test harness: force JAX onto a virtual 8-device CPU mesh.

Must run before any jax import — pytest imports conftest first, so setting
the env here guarantees every test module sees 8 virtual CPU devices,
giving a multi-chip sharding story without TPU hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
