"""The sharded fused megaround must agree with the single-device fused
program bit-for-bit on an 8-device virtual CPU mesh (conftest forces
xla_force_host_platform_device_count=8). The mesh variant is the SAME
program text (kernel.get_ranked_solver_mesh) re-partitioned by GSPMD, so
parity is the contract, not a tolerance."""

import random

import jax
import numpy as np
import pytest

from nhd_tpu.solver.encode import encode_cluster, encode_pods
from nhd_tpu.solver.kernel import solve_bucket_ranked
from nhd_tpu.parallel.sharding import (
    make_mesh,
    resolve_mesh_spec,
    solve_bucket_ranked_sharded,
)
from tests.test_jax_matcher import random_cluster, random_request


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sharded_ranked_matches_single_device(seed):
    rng = random.Random(seed)
    nodes = random_cluster(rng, rng.randint(3, 12))
    reqs = [random_request(rng) for _ in range(8)]
    cluster = encode_cluster(nodes, now=1010.0)
    mesh = make_mesh()
    for G, pods in encode_pods(reqs, cluster.interner).items():
        plain = np.asarray(solve_bucket_ranked(cluster, pods, 16))
        sharded = solve_bucket_ranked_sharded(cluster, pods, 16, mesh)
        np.testing.assert_array_equal(plain, sharded)


def test_sharded_solve_with_node_count_not_divisible():
    """N not divisible by the mesh size pads cleanly (the mesh pads to a
    multiple of the device count; padded rows are inactive)."""
    rng = random.Random(99)
    nodes = random_cluster(rng, 13)
    reqs = [random_request(rng) for _ in range(3)]
    cluster = encode_cluster(nodes, now=1010.0)
    for G, pods in encode_pods(reqs, cluster.interner).items():
        plain = np.asarray(solve_bucket_ranked(cluster, pods, 8))
        sharded = solve_bucket_ranked_sharded(cluster, pods, 8)
        np.testing.assert_array_equal(plain, sharded)


def test_resolve_mesh_spec():
    """The NHD_MESH / --mesh operator knob: auto passes through, off
    forces single-device, N builds an explicit mesh, and asking for more
    devices than exist is a refused misconfiguration."""
    assert resolve_mesh_spec("auto") == "auto"
    assert resolve_mesh_spec(None) == "auto"
    assert resolve_mesh_spec("off") is None
    assert resolve_mesh_spec("0") is None
    assert resolve_mesh_spec("none") is None
    assert resolve_mesh_spec("1") is None  # one device = no mesh
    mesh = resolve_mesh_spec("4")
    assert mesh.devices.size == 4 and mesh.axis_names == ("nodes",)
    # an existing Mesh passes through untouched
    assert resolve_mesh_spec(mesh) is mesh
    with pytest.raises(ValueError):
        resolve_mesh_spec("9999")
    with pytest.raises(ValueError):
        resolve_mesh_spec("bogus")


def _cluster_free_state(nodes):
    return sorted(
        (
            name,
            tuple(n.free_cpu_cores_per_numa()),
            n.free_gpu_count(),
            n.mem.free_hugepages_gb,
            tuple(nic.free_bw() for nic in n.nics),
        )
        for name, n in nodes.items()
    )


@pytest.mark.parametrize("seed", [0, 1])
def test_batch_scheduler_mesh_equals_single_device(seed):
    """The PRODUCTION path: BatchScheduler over the 8-device mesh must place
    a mixed contended batch (multi-bucket, NUMA+PCI, GPU and CPU-only pods)
    identically to the forced single-device path — same nodes, same
    mappings, same end cluster state."""
    import copy

    from nhd_tpu.solver.batch import BatchItem, BatchScheduler

    rng = random.Random(400 + seed)
    base_nodes = random_cluster(rng, 11)
    reqs = [random_request(rng) for _ in range(24)]
    items = [BatchItem(("ns", f"p{i}"), r) for i, r in enumerate(reqs)]

    outs = {}
    for label, mesh in (("mesh", make_mesh()), ("single", None)):
        nodes = copy.deepcopy(base_nodes)
        sched = BatchScheduler(respect_busy=False, mesh=mesh)
        results, stats = sched.schedule(nodes, items, now=1010.0)
        outs[label] = (
            [r.node for r in results],
            [r.mapping for r in results],
            stats.scheduled,
            _cluster_free_state(nodes),
        )
    assert outs["mesh"] == outs["single"]
