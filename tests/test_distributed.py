"""True multi-process distributed tests (simulated multi-host).

Two separate Python processes jax.distributed.initialize against a local
coordinator and exercise both documented federation patterns
(nhd_tpu/parallel/multihost.py):

1. region-independent: each process schedules its own node shard
   (multihost.local_nodes) with its local devices — no cross-process
   collectives;
2. global SPMD: both processes participate in ONE sharded solve over a
   global mesh (one device per process), with cross-process collectives
   (Gloo on the CPU backend), and the result must equal the local
   single-device solve bit-for-bit.

This is the closest a single host gets to the reference's multi-node
story (SURVEY §5.8) without a cluster.
"""

import os
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    # the virtual 8-device mesh of the parent suite must not leak in:
    # each process contributes exactly one device to the global mesh
    os.environ["XLA_FLAGS"] = ""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)

    rank = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
    scenario = sys.argv[4]
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nproc, process_id=rank)
    assert jax.process_count() == nproc

    import numpy as np
    from nhd_tpu.sim import make_cluster
    from tests.test_batch import simple_request

    if scenario == "regions":
        from nhd_tpu.parallel import multihost
        from nhd_tpu.solver import BatchItem, StreamingScheduler

        all_nodes = make_cluster(6)
        mine = multihost.local_nodes(all_nodes)
        items = [BatchItem(("ns", f"r{rank}-p{i}"), simple_request())
                 for i in range(4)]
        res, st = StreamingScheduler(
            tile_nodes=2, respect_busy=False
        ).schedule(mine, items, now=0.0)
        assert st.scheduled == 4, st
        assert all(r.node in mine for r in res)
    elif scenario == "spmd":
        from nhd_tpu.parallel.sharding import make_mesh, solve_bucket_sharded
        from nhd_tpu.solver.encode import encode_cluster, encode_pods
        from nhd_tpu.solver.kernel import solve_bucket

        nodes = make_cluster(8)
        cluster = encode_cluster(nodes, now=0.0)
        pods = encode_pods([simple_request(gpus=1)], cluster.interner)[1]
        mesh = make_mesh(jax.devices())   # global: one device per process
        assert mesh.devices.size == nproc
        out = solve_bucket_sharded(cluster, pods, mesh)
        ref = solve_bucket(cluster, pods)
        np.testing.assert_array_equal(out.cand, np.asarray(ref.cand))
        np.testing.assert_array_equal(out.pref, np.asarray(ref.pref))
        np.testing.assert_array_equal(out.best_c, np.asarray(ref.best_c))
        np.testing.assert_array_equal(out.best_a, np.asarray(ref.best_a))
    else:
        raise SystemExit(f"unknown scenario {scenario}")
    print(f"OK rank {rank} {scenario}")
""")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_pair(scenario: str) -> None:
    from tests.conftest import subprocess_env

    port = _free_port()
    env = subprocess_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(rank), "2", str(port),
             scenario],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"{scenario}: worker timed out")
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"{scenario} rank {rank} failed:\n{out[-2000:]}"
        )
        assert f"OK rank {rank} {scenario}" in out


def test_two_process_region_scheduling():
    _run_pair("regions")


def test_two_process_global_spmd_solve():
    _run_pair("spmd")
