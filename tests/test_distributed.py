"""True multi-process distributed tests (simulated multi-host).

Separate Python processes (2 and 4 ranks) jax.distributed.initialize
against a local coordinator and exercise the documented federation
patterns (nhd_tpu/parallel/multihost.py):

1. region-independent: each process schedules its own node shard
   (multihost.local_nodes) with its local devices — no cross-process
   collectives;
2. global SPMD: all processes participate in ONE sharded solve over a
   global mesh (one device per process), with cross-process collectives
   (Gloo on the CPU backend), and the result must equal the local
   single-device solve bit-for-bit;
3. rank failure (VERDICT r2 item 5): one rank dies abruptly mid-run; the
   survivors' region scheduling completes unaffected (the
   region-independent pattern has no collective to hang on), and rank 0
   performs elastic takeover of the dead rank's region — scheduling its
   pods onto the orphaned shard with exact-cover disjointness asserted.

This is the closest a single host gets to the reference's multi-node
story (SURVEY §5.8) without a cluster.
"""

import functools
import os
import subprocess
import sys
import textwrap
from typing import Optional

import pytest

# Minimal cross-process SPMD capability probe: 2 processes rendezvous and
# run ONE jitted reduction over a globally sharded array. Some jaxlib
# builds reject this outright ("Multiprocess computations aren't
# implemented on the CPU backend") — an environmental limitation, not a
# regression, so the SPMD tests skip with that reason instead of failing.
_PROBE = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = ""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    rank = int(sys.argv[1]); port = sys.argv[2]
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=2, process_id=rank)
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(jax.devices(), ("d",))
    arr = jax.make_array_from_single_device_arrays(
        (2,), NamedSharding(mesh, P("d")),
        [jax.device_put(jnp.ones((1,)), jax.local_devices()[0])],
    )
    out = jax.jit(jnp.sum)(arr)
    assert float(out) == 2.0, out
    print("SPMD_OK", flush=True)
""")


@functools.lru_cache(maxsize=1)
def _spmd_unsupported_reason() -> Optional[str]:
    """None when this host can run cross-process SPMD collectives on the
    CPU backend; otherwise the reason to skip. Probed once per session;
    infra-flavored probe failures retry on a fresh port before being
    believed."""
    from tests.conftest import subprocess_env

    last = "probe never ran"
    for _ in range(3):
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _PROBE, str(rank), str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=subprocess_env(),
            )
            for rank in (0, 1)
        ]
        outs = []
        timed_out = False
        for p in procs:
            try:
                out, _ = p.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                timed_out = True
                out = ""
            outs.append(out)
        if timed_out:
            last = "capability probe timed out (coordinator rendezvous)"
            continue
        if all(p.returncode == 0 and "SPMD_OK" in o
               for p, o in zip(procs, outs)):
            return None
        tail = ""
        for o in outs:
            for line in o.splitlines():
                if "Error" in line or "implemented" in line:
                    tail = line.strip()[-200:]
        last = tail or (
            f"capability probe failed "
            f"(rc={[p.returncode for p in procs]})"
        )
        if "implemented" in last:    # deterministic: no point retrying
            return last
    return last


def _require_spmd() -> None:
    reason = _spmd_unsupported_reason()
    if reason is not None:
        pytest.skip(
            f"cross-process SPMD unavailable on this host: {reason}"
        )

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    # the virtual 8-device mesh of the parent suite must not leak in:
    # each process contributes exactly dev_per_proc devices (argv[5],
    # default one) to the global mesh — the multi-device-per-process
    # shape is a real TPU host's (several chips per process)
    dev_per_proc = int(sys.argv[5]) if len(sys.argv) > 5 else 1
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={dev_per_proc}"
        if dev_per_proc > 1 else ""
    )
    import jax
    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)

    rank = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
    scenario = sys.argv[4]
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nproc, process_id=rank)
    assert jax.process_count() == nproc

    import numpy as np
    from nhd_tpu.sim import make_cluster
    from tests.test_batch import simple_request

    if scenario == "regions":
        from nhd_tpu.parallel import multihost
        from nhd_tpu.solver import BatchItem, StreamingScheduler

        all_nodes = make_cluster(2 * nproc + 2)
        mine = multihost.local_nodes(all_nodes)
        items = [BatchItem(("ns", f"r{rank}-p{i}"), simple_request())
                 for i in range(4)]
        res, st = StreamingScheduler(
            tile_nodes=2, respect_busy=False
        ).schedule(mine, items, now=0.0)
        assert st.scheduled == 4, st
        assert all(r.node in mine for r in res)
    elif scenario == "failure":
        from nhd_tpu.parallel import multihost
        from nhd_tpu.solver import BatchItem, StreamingScheduler

        all_nodes = make_cluster(2 * nproc)
        mine = multihost.local_nodes(all_nodes)
        if rank == nproc - 1:
            # die abruptly mid-schedule: no shutdown handshake, no
            # coordinator goodbye (SIGKILL-equivalent)
            print(f"DYING rank {rank}", flush=True)
            os._exit(17)
        items = [BatchItem(("ns", f"r{rank}-p{i}"), simple_request())
                 for i in range(4)]
        res, st = StreamingScheduler(
            tile_nodes=2, respect_busy=False
        ).schedule(mine, items, now=0.0)
        assert st.scheduled == 4, st
        assert all(r.node in mine for r in res)
        if rank == 0:
            # elastic takeover: adopt the dead rank's region and schedule
            # its orphaned pods there. Regions are an exact cover, so the
            # adopted shard is disjoint from every survivor's own.
            dead = multihost.region_nodes(all_nodes, nproc - 1, nproc)
            assert not (set(dead) & set(mine)), "regions must be disjoint"
            orphans = [
                BatchItem(("ns", f"orphan-p{i}"), simple_request())
                for i in range(4)
            ]
            res2, st2 = StreamingScheduler(
                tile_nodes=2, respect_busy=False
            ).schedule(dead, orphans, now=0.0)
            assert st2.scheduled == 4, st2
            assert all(r.node in dead for r in res2)
            # conservation: takeover must not have touched survivor nodes
            assert all(r.node not in mine for r in res2)
        print(f"OK rank {rank} {scenario}", flush=True)
        os._exit(0)  # skip the distributed shutdown barrier: one rank is
        #              dead and a clean shutdown would wait for it
    elif scenario == "spmd":
        from nhd_tpu.parallel.sharding import (
            make_mesh, solve_bucket_ranked_sharded,
        )
        from nhd_tpu.solver.encode import encode_cluster, encode_pods
        from nhd_tpu.solver.kernel import solve_bucket_ranked

        nodes = make_cluster(8)
        cluster = encode_cluster(nodes, now=0.0)
        pods = encode_pods([simple_request(gpus=1)], cluster.interner)[1]
        mesh = make_mesh(jax.devices())   # global: all devices, all processes
        assert mesh.devices.size == nproc * dev_per_proc
        # the PRODUCTION mesh program: the fused solve+rank megaround,
        # sharded — bit-identical to the local single-device fused solve
        out = solve_bucket_ranked_sharded(cluster, pods, 8, mesh)
        ref = np.asarray(solve_bucket_ranked(cluster, pods, 8))
        np.testing.assert_array_equal(out, ref)
    else:
        raise SystemExit(f"unknown scenario {scenario}")
    print(f"OK rank {rank} {scenario}")
""")


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_procs_once(
    scenario: str, nproc: int, dead_rank: int, dev_per_proc: int = 1
) -> Optional[str]:
    """One orchestration attempt; returns an error description or None."""
    from tests.conftest import subprocess_env

    port = _free_port()
    env = subprocess_env()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(rank), str(nproc), str(port),
             scenario, str(dev_per_proc)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for rank in range(nproc)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            return f"{scenario}: worker timed out"
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        if rank == dead_rank:
            if p.returncode != 17 or f"DYING rank {rank}" not in out:
                return (
                    f"{scenario} rank {rank} should have died "
                    f"(rc={p.returncode}):\n{out[-2000:]}"
                )
            continue
        if p.returncode != 0 or f"OK rank {rank} {scenario}" not in out:
            return (
                f"{scenario} rank {rank} failed (rc={p.returncode}):\n"
                f"{out[-2000:]}"
            )
    return None


def _run_procs(
    scenario: str, nproc: int, dead_rank: int = -1, dev_per_proc: int = 1
) -> None:
    """Run the scenario, retrying with a fresh coordinator port — but
    ONLY for infrastructure-flavored failures: the bind-then-release
    port probe (_free_port) can race another process grabbing the same
    ephemeral port before the coordinator rebinds it, and on a loaded
    single-core host the multi-process coordinator handshake can miss
    its window — rare flakes observed only when the full suite runs
    back-to-back. Assertion failures (e.g. a sharded-vs-reference
    divergence) fail immediately: retrying them would mask
    nondeterministic real regressions."""

    def _is_flaky(err: str) -> bool:
        low = err.lower()
        return any(
            p in low
            for p in (
                "timed out", "coordinator", "coordination", "barrier",
                "connect", "unavailable", "deadline", "bind",
                "already in use",
            )
        )

    err = None
    for _ in range(3):
        err = _run_procs_once(scenario, nproc, dead_rank, dev_per_proc)
        if err is None:
            return
        if not _is_flaky(err):
            break
    pytest.fail(err)


@pytest.mark.parametrize("nproc", [2, 4])
def test_multi_process_region_scheduling(nproc):
    _run_procs("regions", nproc)


@pytest.mark.parametrize("nproc", [2, 4])
def test_multi_process_global_spmd_solve(nproc):
    _require_spmd()
    _run_procs("spmd", nproc)


def test_multi_process_multi_device_spmd_solve():
    """2 processes × 4 virtual devices each — the real TPU-host shape
    (several chips per process) for the global SPMD solve: an 8-device
    mesh whose shards live in two OS processes, cross-process collectives
    included, bit-identical to the local single-device solve (VERDICT r4
    item 6: no multi-device-per-process leg existed)."""
    _require_spmd()
    _run_procs("spmd", 2, dev_per_proc=4)


def test_rank_failure_survivors_and_takeover():
    """Kill rank 3 of 4 mid-run: ranks 0-2 still schedule their regions,
    and rank 0 adopts the dead region (SURVEY §5.3 elastic recovery for
    the scheduler's own distributed leg)."""
    _run_procs("failure", 4, dead_rank=3)
