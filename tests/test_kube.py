"""KubeClusterBackend against a mocked kubernetes client (VERDICT r1
item 5): node/pod reads, annotation round-trips, ConfigMap resolution,
bind + event posting, TriadSet CRD calls, watch-event translation, and
ApiException failure injection — the reference's API-server surface
(K8SMgr.py:55-559) exercised without a cluster or the kubernetes package."""

import sys
import types
from types import SimpleNamespace as NS

import pytest


# ---------------------------------------------------------------------------
# a minimal fake `kubernetes` package
# ---------------------------------------------------------------------------

class ApiException(Exception):
    def __init__(self, status=404, reason="NotFound"):
        super().__init__(f"({status}) {reason}")
        self.status = status


def _node(name, ready=True, taint=True, unschedulable=False, labels=None,
          capacity="64Gi", allocatable="60Gi"):
    conds = [NS(reason="KubeletReady", status="True" if ready else "False")]
    taints = (
        [NS(key="sigproc.viasat.io/nhd_scheduler", effect="NoSchedule")]
        if taint else []
    )
    return NS(
        metadata=NS(name=name, labels=labels or {}),
        status=NS(
            conditions=conds,
            addresses=[NS(type="Hostname", address=name),
                       NS(type="InternalIP", address=f"10.0.0.{len(name)}")],
            capacity={"hugepages-1Gi": capacity},
            allocatable={"hugepages-1Gi": allocatable},
        ),
        spec=NS(taints=taints, unschedulable=unschedulable),
    )


def _pod(name, ns="default", scheduler="nhd-scheduler", node=None,
         phase="Pending", uid="uid-1", annotations=None, volumes=None,
         requests=None):
    return NS(
        metadata=NS(name=name, namespace=ns, uid=uid,
                    annotations=annotations or {}),
        spec=NS(
            scheduler_name=scheduler, node_name=node,
            volumes=volumes or [],
            containers=[NS(resources=NS(requests=requests or {}))],
        ),
        status=NS(phase=phase),
    )


class FakeCoreV1Api:
    def __init__(self, state):
        self.state = state

    # nodes
    def list_node(self):
        return NS(items=list(self.state["nodes"].values()))

    def read_node(self, name):
        try:
            return self.state["nodes"][name]
        except KeyError:
            raise ApiException()

    # pods
    def read_namespaced_pod(self, pod, ns):
        try:
            return self.state["pods"][(ns, pod)]
        except KeyError:
            raise ApiException()

    def list_pod_for_all_namespaces(self):
        return NS(items=list(self.state["pods"].values()))

    def list_namespaced_pod(self, ns):
        return NS(items=[p for (n, _), p in self.state["pods"].items()
                         if n == ns])

    def read_namespaced_config_map(self, name, ns):
        try:
            return self.state["configmaps"][(ns, name)]
        except KeyError:
            raise ApiException()

    def patch_namespaced_pod(self, pod, ns, body):
        if (ns, pod) in self.state["fail_patch"]:
            raise ApiException(500, "ServerError")
        obj = self.read_namespaced_pod(pod, ns)
        obj.metadata.annotations.update(body["metadata"]["annotations"])

    def create_namespaced_pod_binding(self, pod, ns, body):
        if (ns, pod) in self.state["fail_bind"]:
            raise ApiException(409, "Conflict")
        self.state["bindings"].append((ns, pod, body.target.name))
        # the real client chokes on the empty 201 response body
        raise ValueError("Invalid value for `target`")

    def create_namespaced_event(self, ns, body):
        if self.state.get("fail_events"):
            raise ApiException(500, "ServerError")
        self.state["events"].append((ns, body))

    def create_namespaced_pod(self, ns, body):
        name = body["metadata"]["name"]
        if (ns, name) in self.state["fail_create"]:
            raise ApiException(403, "Forbidden")
        self.state["created_pods"].append((ns, body))


class FakeCrdApi:
    def __init__(self, state):
        self.state = state

    def list_cluster_custom_object(self, group, version, plural):
        if self.state.get("fail_crd"):
            raise ApiException(404, "NotFound")
        return {"items": self.state["triadsets"]}

    def patch_namespaced_custom_object_status(self, group, version, ns,
                                              plural, name, body):
        if self.state.get("fail_crd_status"):
            raise ApiException(500, "ServerError")
        self.state["status_patches"].append((ns, name, body))


class FakeWatch:
    """Yields canned event batches; raises KeyboardInterrupt when drained
    so the backend's forever-loop exits (KeyboardInterrupt is a
    BaseException, deliberately not caught by the restart handler)."""

    batches = []

    def stream(self, fn):
        if not FakeWatch.batches:
            raise KeyboardInterrupt()
        return FakeWatch.batches.pop(0)


@pytest.fixture()
def backend():
    state = {
        "nodes": {}, "pods": {}, "configmaps": {}, "bindings": [],
        "events": [], "created_pods": [], "triadsets": [],
        "status_patches": [], "fail_patch": set(), "fail_bind": set(),
        "fail_create": set(),
    }

    client_mod = types.ModuleType("kubernetes.client")
    client_mod.CoreV1Api = lambda: FakeCoreV1Api(state)
    client_mod.CustomObjectsApi = lambda: FakeCrdApi(state)
    client_mod.exceptions = NS(ApiException=ApiException)
    client_mod.V1Binding = lambda metadata, target: NS(
        metadata=metadata, target=target
    )
    client_mod.V1ObjectMeta = lambda **kw: NS(**kw)
    client_mod.V1ObjectReference = lambda **kw: NS(**kw)
    client_mod.CoreV1Event = lambda **kw: NS(**kw)
    client_mod.V1EventSource = lambda **kw: NS(**kw)

    config_mod = types.ModuleType("kubernetes.config")

    def _no_cluster():
        raise RuntimeError("not in cluster")

    config_mod.load_incluster_config = _no_cluster
    config_mod.load_kube_config = lambda: None

    watch_mod = types.ModuleType("kubernetes.watch")
    watch_mod.Watch = FakeWatch

    kube_mod = types.ModuleType("kubernetes")
    kube_mod.client = client_mod
    kube_mod.config = config_mod
    kube_mod.watch = watch_mod

    saved = {k: sys.modules.get(k) for k in
             ("kubernetes", "kubernetes.client", "kubernetes.config",
              "kubernetes.watch")}
    sys.modules["kubernetes"] = kube_mod
    sys.modules["kubernetes.client"] = client_mod
    sys.modules["kubernetes.config"] = config_mod
    sys.modules["kubernetes.watch"] = watch_mod
    try:
        from nhd_tpu.k8s.kube import KubeClusterBackend
        from nhd_tpu.k8s.retry import RetryPolicy

        b = KubeClusterBackend(start_watches=False, retry_policy=RetryPolicy(
            base_delay=0.002, max_delay=0.01, exc_class=ApiException
        ))
        b._test_state = state
        yield b
    finally:
        for k, v in saved.items():
            if v is None:
                sys.modules.pop(k, None)
            else:
                sys.modules[k] = v


# ---------------------------------------------------------------------------
# node reads
# ---------------------------------------------------------------------------

def test_get_nodes_filters_kubelet_ready(backend):
    s = backend._test_state
    s["nodes"]["n1"] = _node("n1", ready=True)
    s["nodes"]["n2"] = _node("n2", ready=False)
    assert backend.get_nodes() == ["n1"]


def test_is_node_active_taint_and_cordon(backend):
    s = backend._test_state
    s["nodes"]["tainted"] = _node("tainted", taint=True)
    s["nodes"]["plain"] = _node("plain", taint=False)
    s["nodes"]["cordoned"] = _node("cordoned", taint=True, unschedulable=True)
    assert backend.is_node_active("tainted")
    assert not backend.is_node_active("plain")
    assert not backend.is_node_active("cordoned")


def test_node_addr_and_hugepages(backend):
    s = backend._test_state
    s["nodes"]["n1"] = _node("n1", capacity="64Gi", allocatable="60Gi")
    assert backend.get_node_addr("n1").startswith("10.0.0.")
    assert backend.get_node_hugepage_resources("n1") == (64, 60)


def test_node_labels_copied(backend):
    s = backend._test_state
    s["nodes"]["n1"] = _node("n1", labels={"NHD_GROUP": "edge"})
    labels = backend.get_node_labels("n1")
    labels["NHD_GROUP"] = "mutated"
    assert s["nodes"]["n1"].metadata.labels["NHD_GROUP"] == "edge"


# ---------------------------------------------------------------------------
# pod reads
# ---------------------------------------------------------------------------

def test_pod_reads_and_missing_pod(backend):
    s = backend._test_state
    s["pods"][("default", "p1")] = _pod(
        "p1", node="n1",
        annotations={"sigproc.viasat.io/cfg_type": "triad",
                     "sigproc.viasat.io/nhd_groups": "default.edge"},
        requests={"hugepages-1Gi": "4Gi"},
    )
    assert backend.pod_exists("p1", "default")
    assert not backend.pod_exists("ghost", "default")
    assert backend.get_pod_node("p1", "default") == "n1"
    assert backend.get_pod_node("ghost", "default") is None
    assert backend.get_cfg_type("p1", "default") == "triad"
    assert backend.get_pod_node_groups("p1", "default") == ["default", "edge"]
    assert backend.get_pod_node_groups("ghost", "default") == ["default"]
    assert backend.get_requested_pod_resources("p1", "default") == {
        "hugepages-1Gi": "4Gi"
    }


def test_scheduled_and_service_pods_filter_scheduler(backend):
    s = backend._test_state
    s["pods"][("default", "ours")] = _pod("ours", node="n1", phase="Running",
                                          uid="u1")
    s["pods"][("default", "theirs")] = _pod("theirs", scheduler="default",
                                            node="n1")
    s["pods"][("default", "pending")] = _pod("pending", uid="u2")
    assert backend.get_scheduled_pods("nhd-scheduler") == [
        ("ours", "default", "u1", "Running")
    ]
    sp = backend.service_pods("nhd-scheduler")
    assert sp == {
        ("default", "ours", "u1"): ("Running", "n1"),
        ("default", "pending", "u2"): ("Pending", None),
    }


def test_cfg_map_resolution_and_missing_map(backend):
    s = backend._test_state
    vol_missing = NS(config_map=NS(name="ghost-cm"))
    vol_empty = NS(config_map=None)
    vol_good = NS(config_map=NS(name="cm1"))
    s["pods"][("default", "p1")] = _pod(
        "p1", volumes=[vol_empty, vol_missing, vol_good]
    )
    s["configmaps"][("default", "cm1")] = NS(data={"app.cfg": "the-config"})
    # missing ConfigMap logged + skipped, good one wins
    assert backend.get_cfg_map("p1", "default") == ("cm1", "the-config")
    # pod without any resolvable map
    s["pods"][("default", "p2")] = _pod("p2", volumes=[vol_missing])
    assert backend.get_cfg_map("p2", "default") == (None, None)
    assert backend.get_cfg_map("ghost", "default") == (None, None)


# ---------------------------------------------------------------------------
# writes
# ---------------------------------------------------------------------------

def test_annotation_round_trip(backend):
    s = backend._test_state
    s["pods"][("default", "p1")] = _pod("p1")
    assert backend.add_nad_to_pod("p1", "default", "eth2@eth2")
    assert backend.annotate_pod_config("default", "p1", "solved cfg")
    assert backend.annotate_pod_gpu_map("default", "p1", {"nvidia0": 2})
    annots = backend.get_pod_annotations("p1", "default")
    assert annots["k8s.v1.cni.cncf.io/networks"] == "eth2@eth2"
    assert backend.get_cfg_annotations("p1", "default") == "solved cfg"
    assert annots["sigproc.viasat.io/nhd_gpu_devices.nvidia0"] == "2"


def test_annotation_failure_injection(backend):
    """A persistent 500 on the patch path is a *transient* (server-health)
    failure: once the retry policy gives up it surfaces as
    TransientBackendError so the scheduler requeues the pod instead of
    failing it. A missing pod (404) stays a plain False."""
    from nhd_tpu.k8s.interface import TransientBackendError

    s = backend._test_state
    s["pods"][("default", "p1")] = _pod("p1")
    s["fail_patch"].add(("default", "p1"))
    with pytest.raises(TransientBackendError):
        backend.annotate_pod_config("default", "p1", "cfg")
    with pytest.raises(TransientBackendError):
        backend.add_nad_to_pod("p1", "default", "x@x")
    # terminal: patching a pod that doesn't exist returns False
    assert not backend.annotate_pod_config("default", "ghost", "cfg")


def test_bind_swallows_client_valueerror(backend):
    s = backend._test_state
    s["pods"][("default", "p1")] = _pod("p1")
    assert backend.bind_pod_to_node("p1", "n1", "default")
    assert s["bindings"] == [("default", "p1", "n1")]


def test_bind_api_failure_returns_false(backend):
    s = backend._test_state
    s["pods"][("default", "p1")] = _pod("p1")
    s["fail_bind"].add(("default", "p1"))
    assert not backend.bind_pod_to_node("p1", "n1", "default")
    assert s["bindings"] == []


def test_pod_event_prefix_and_failure_paths(backend):
    from nhd_tpu.k8s.interface import EventType

    s = backend._test_state
    s["pods"][("default", "p1")] = _pod("p1", uid="u9")
    backend.generate_pod_event("p1", "default", "Scheduled",
                               EventType.NORMAL, "assigned")
    ns, body = s["events"][0]
    assert ns == "default"
    assert body.message == "NHD: assigned"
    assert body.involved_object.uid == "u9"
    assert body.type == "Normal"
    # missing pod: silently skipped
    backend.generate_pod_event("ghost", "default", "X", EventType.WARNING, "m")
    assert len(s["events"]) == 1
    # API failure: logged, not raised
    s["fail_events"] = True
    backend.generate_pod_event("p1", "default", "X", EventType.WARNING, "m")
    assert len(s["events"]) == 1


# ---------------------------------------------------------------------------
# TriadSets
# ---------------------------------------------------------------------------

def test_triadset_listing_and_pod_create(backend):
    s = backend._test_state
    s["triadsets"] = [{
        "metadata": {"name": "ts1", "namespace": "default"},
        "spec": {"replicas": 2, "serviceName": "svc",
                 "template": {"metadata": {}, "spec": {"containers": []}}},
    }]
    ts_list = backend.list_triadsets()
    assert ts_list[0]["service_name"] == "svc"
    assert ts_list[0]["replicas"] == 2

    s["pods"][("default", "svc-0")] = _pod("svc-0")
    s["pods"][("default", "svc-x")] = _pod("svc-x")   # non-ordinal suffix
    assert backend.list_pods_of_triadset(ts_list[0]) == ["svc-0"]

    assert backend.create_pod_for_triadset(ts_list[0], 1)
    ns, body = s["created_pods"][0]
    assert body["metadata"]["name"] == "svc-1"
    assert body["spec"]["hostname"] == "svc-1"
    assert body["spec"]["subdomain"] == "svc"

    s["fail_create"].add(("default", "svc-2"))
    assert not backend.create_pod_for_triadset(ts_list[0], 2)

    assert backend.update_triadset_status(ts_list[0], 2)
    assert s["status_patches"][0][2] == {"status": {"replicas": 2}}
    s["fail_crd_status"] = True
    assert not backend.update_triadset_status(ts_list[0], 3)

    s["fail_crd"] = True
    assert backend.list_triadsets() == []


# ---------------------------------------------------------------------------
# watch translation
# ---------------------------------------------------------------------------

def test_pod_watch_translation(backend):
    FakeWatch.batches = [[
        {"type": "ADDED", "object": _pod("p1", uid="u1", node=None)},
        {"type": "MODIFIED", "object": _pod("p1", uid="u1")},  # dropped
        {"type": "DELETED", "object": _pod(
            "p1", uid="u1", node="n1",
            annotations={"sigproc.viasat.io/nhd_config": "solved"})},
    ]]
    with pytest.raises(KeyboardInterrupt):
        backend._watch_pods()
    events = list(backend.poll_watch_events())
    assert [e.kind for e in events] == ["pod_create", "pod_delete"]
    assert events[0].scheduler_name == "nhd-scheduler"
    assert events[1].node == "n1"
    assert events[1].annotations["sigproc.viasat.io/nhd_config"] == "solved"


def test_node_watch_diff_tracking(backend):
    n_before = _node("n1", labels={"NHD_GROUP": "default"})
    n_cordoned = _node("n1", labels={"NHD_GROUP": "edge"}, unschedulable=True)
    FakeWatch.batches = [
        [{"type": "MODIFIED", "object": n_before}],
        [{"type": "MODIFIED", "object": n_cordoned}],
    ]
    with pytest.raises(KeyboardInterrupt):
        backend._watch_nodes()
    first, second = list(backend.poll_watch_events())
    # first sighting: old == new (no spurious diff)
    assert first.old_labels == first.labels
    assert first.was_unschedulable == first.unschedulable is False
    # second: diff against the tracked previous state
    assert second.old_labels == {"NHD_GROUP": "default"}
    assert second.labels == {"NHD_GROUP": "edge"}
    assert second.was_unschedulable is False
    assert second.unschedulable is True
