"""Overload-robust front door (ISSUE 20): per-tenant admission lanes,
weighted deficit-round-robin dequeue, and the explicit load-shed ladder.

The standing contracts:

* ``NHD_ADMIT=0`` is INERT — the queue is a pure pass-through FIFO
  (everything rides the control lane in arrival order, nothing is ever
  deferred or shed), the negative-control posture of the tenant-storm
  chaos cells;
* DRR dequeue is fair at every granularity: one tenant's deep backlog
  cannot make consecutive pops (the rotation-stall regression that
  starved the chaos victim), and weights buy proportional share;
* the ladder is monotonic and explicit: over-rate traffic defers at the
  middle rung (tier-exempt), sheds at the top, and EVERY refusal yields
  exactly one shed record → one AdmissionShed event + one decision
  record + one /explain reason — never a silent drop;
* recovery is real: parked pods re-enter their lane once pressure drops,
  and parked work reads as backlog (qsize) but not as drainable (empty);
* requeue traffic (transient-bind retry, preemption) bypasses rate/defer
  — its first admission already paid them — but still respects the hard
  lane cap, with exactly one refusal record when it bounces;
* knobs fail loud: a typo'd NHD_ADMIT or a non-monotonic fill pair is a
  construction-time ValueError, not a silently disabled ladder;
* the batched controller decode flushes crash-only (items around a
  poisoned event still land, in order) and stamps the pod tier the
  defer rung spares;
* per-tenant SLO views are bounded (TENANT_LABEL_MAX then "other") and
  render NHD603-clean metric families;
* the tenant-storm chaos cell holds end to end: one abusive tenant at
  10x must not move the victim's p99 time-to-bind, and the NHD_ADMIT=0
  control cell must demonstrably violate that bound (falsifiability).
"""

from __future__ import annotations

import json
import os
import queue

import pytest

from nhd_tpu.ingress import (
    RUNG_ADMIT,
    RUNG_DEFER,
    RUNG_SHED,
    AdmissionQueue,
    TokenBucket,
)
from nhd_tpu.ingress.admission import parse_weights
from nhd_tpu.k8s.fake import FakeClusterBackend
from nhd_tpu.obs.recorder import FlightRecorder
from nhd_tpu.obs.slo import TENANT_LABEL_MAX, SloTracker
from nhd_tpu.scheduler.controller import Controller
from nhd_tpu.scheduler.core import Scheduler
from nhd_tpu.scheduler.events import WatchItem, WatchType
from nhd_tpu.sim.synth import SynthNodeSpec, make_node_labels, make_triad_config


def _create(ns, name, tier=0, uid=None):
    return WatchItem(
        WatchType.TRIAD_POD_CREATE,
        pod={"ns": ns, "name": name, "uid": uid or f"uid-{ns}-{name}",
             "cfg": "", "node": "", "tier": str(tier)},
        corr=f"corr-{ns}-{name}",
    )


def _delete(ns, name):
    return WatchItem(
        WatchType.TRIAD_POD_DELETE,
        pod={"ns": ns, "name": name, "uid": "", "cfg": "", "node": ""},
    )


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _queue(monkeypatch, clock=None, pressure=None, **env):
    for k in ("NHD_ADMIT", "NHD_ADMIT_BATCH", "NHD_ADMIT_TENANT_CAP",
              "NHD_ADMIT_RATE", "NHD_ADMIT_BURST", "NHD_ADMIT_WEIGHTS",
              "NHD_ADMIT_DEFER_FILL", "NHD_ADMIT_SHED_FILL"):
        monkeypatch.delenv(k, raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    return AdmissionQueue(
        clock=clock or _Clock(),
        pressure_fn=(lambda: pressure) if pressure is not None else None,
    )


# ---------------------------------------------------------------------------
# pass-through posture (the negative-control cell)
# ---------------------------------------------------------------------------


def test_disabled_is_pure_fifo(monkeypatch):
    q = _queue(monkeypatch, NHD_ADMIT="0", NHD_ADMIT_RATE="0.1",
               NHD_ADMIT_TENANT_CAP="1", pressure=1.0)
    items = [_create("a", "p1"), _delete("a", "p0"), _create("b", "p2"),
             _create("b", "p3"), _create("b", "p4")]
    for it in items:
        q.put(it)
    # over cap, over rate, max pressure — and still: FIFO, nothing shed
    assert q.rung() == RUNG_ADMIT
    got = [q.get(block=False) for _ in range(len(items))]
    assert got == items
    assert q.stats["shed"] == 0 and q.stats["deferred"] == 0
    assert q.drain_shed() == []
    with pytest.raises(queue.Empty):
        q.get(block=False)


def test_typoed_admit_fails_loud(monkeypatch):
    monkeypatch.setenv("NHD_ADMIT", "yes")
    with pytest.raises(ValueError, match="NHD_ADMIT"):
        AdmissionQueue()


def test_non_monotonic_fill_pair_fails_loud(monkeypatch):
    with pytest.raises(ValueError, match="SHED_FILL"):
        _queue(monkeypatch, NHD_ADMIT_DEFER_FILL="0.8",
               NHD_ADMIT_SHED_FILL="0.4")


def test_parse_weights_loud():
    assert parse_weights("a=2, b=0.5") == {"a": 2.0, "b": 0.5}
    for bad in ("a", "a=", "a=zero", "a=0", "a=-1"):
        with pytest.raises(ValueError):
            parse_weights(bad)


# ---------------------------------------------------------------------------
# DRR fairness
# ---------------------------------------------------------------------------


def test_drr_interleaves_deep_and_shallow_lanes(monkeypatch):
    """Regression: the rotation must advance after a spent credit. The
    original dequeue stuck on the first non-empty lane until it emptied,
    so an abuser's standing backlog starved every other tenant (the
    chaos victim's lane grew monotonically while its p99 pinned at the
    histogram ceiling)."""
    q = _queue(monkeypatch)
    for i in range(50):
        q.put(_create("abuser", f"a{i}"))
    q.put(_create("victim", "v0"))
    # the victim's single pod must surface within one round of the
    # rotation, not behind 50 abuser pops
    first_two = [q.get(block=False).pod["ns"] for _ in range(2)]
    assert "victim" in first_two


def test_drr_weights_buy_proportional_share(monkeypatch):
    q = _queue(monkeypatch, NHD_ADMIT_WEIGHTS="gold=2")
    for i in range(20):
        q.put(_create("gold", f"g{i}"))
        q.put(_create("iron", f"i{i}"))
    got = [q.get(block=False).pod["ns"] for _ in range(12)]
    assert got.count("gold") == 8 and got.count("iron") == 4


def test_get_creates_folds_in_drr_order(monkeypatch):
    q = _queue(monkeypatch)
    for i in range(4):
        q.put(_create("a", f"a{i}"))
        q.put(_create("b", f"b{i}"))
    first = q.get(block=False)
    rest = q.get_creates(limit=3)
    batch_ns = [first.pod["ns"]] + [it.pod["ns"] for it in rest]
    # one fold never double-serves a lane while another waits
    assert batch_ns.count("a") == 2 and batch_ns.count("b") == 2
    # control traffic never rides the create fold
    q.put(_delete("a", "a0"))
    assert all(it.type == WatchType.TRIAD_POD_CREATE
               for it in q.get_creates(limit=10))


# ---------------------------------------------------------------------------
# the ladder: defer, shed, recovery
# ---------------------------------------------------------------------------


def test_token_bucket_clock_semantics():
    clk = _Clock()
    b = TokenBucket(rate=1.0, burst=2.0, clock=clk)
    assert b.take() and b.take() and not b.take()
    clk.t += 1.0
    assert b.take() and not b.take()
    assert TokenBucket(rate=0.0, burst=1.0, clock=clk).take()


def test_defer_then_recover(monkeypatch):
    clk = _Clock()
    press = [0.6]  # DEFER rung
    q = _queue(monkeypatch, clock=clk, NHD_ADMIT_RATE="1",
               NHD_ADMIT_BURST="1")
    q.pressure_fn = lambda: press[0]
    q.put(_create("t", "p0"))            # in-rate: admitted
    q.put(_create("t", "p1"))            # over-rate tier-0: parked
    q.put(_create("t", "p2", tier=1))    # over-rate tier-1: spared
    assert q.stats == {"admitted": 2, "deferred": 1, "readmitted": 0,
                       "shed": 0, "requeue_refusals": 0}
    # parked work is backlog but not drainable: qsize sees it, empty()
    # and the blocking get don't spin on it
    assert q.qsize() == 3 and q.depths()["deferred"] == 1
    assert [q.get(block=False).pod["name"] for _ in range(2)] == ["p0", "p2"]
    assert q.empty()
    with pytest.raises(queue.Empty):
        q.get(block=False, timeout=0.01)
    # pressure drops -> the parked pod re-enters its lane
    press[0] = 0.0
    assert not q.empty()
    assert q.get(block=False).pod["name"] == "p1"
    assert q.stats["readmitted"] == 1


def test_shed_rung_refuses_over_rate_with_record(monkeypatch):
    clk = _Clock()
    q = _queue(monkeypatch, clock=clk, pressure=0.9, NHD_ADMIT_RATE="1",
               NHD_ADMIT_BURST="1")
    q.put(_create("t", "p0"))          # burst token
    q.put(_create("t", "p1", tier=1))  # over-rate: tier does NOT spare shed
    assert q.stats["shed"] == 1 and q.stats["admitted"] == 1
    (rec,) = q.drain_shed()
    assert rec["ns"] == "t" and rec["pod"] == "p1"
    assert "shed rung" in rec["reason"] and rec["requeued"] is False
    assert q.drain_shed() == []  # drained exactly once


def test_hard_cap_refuses_even_in_rate(monkeypatch):
    q = _queue(monkeypatch, NHD_ADMIT_TENANT_CAP="2")
    q.put(_create("t", "p0"))
    q.put(_create("t", "p1"))
    q.put(_create("t", "p2"))
    assert q.stats["shed"] == 1
    (rec,) = q.drain_shed()
    assert "lane full" in rec["reason"]


def test_requeue_bypasses_rate_but_not_cap(monkeypatch):
    q = _queue(monkeypatch, pressure=0.9, NHD_ADMIT_RATE="1",
               NHD_ADMIT_BURST="1", NHD_ADMIT_TENANT_CAP="2")
    q.put(_create("t", "p0"))               # takes the burst token
    q.put_requeue(_create("t", "p1"))       # over-rate at SHED: still in
    assert q.stats["admitted"] == 2 and q.stats["shed"] == 0
    q.put_requeue(_create("t", "p2"))       # lane full: refused
    assert q.stats["shed"] == 1 and q.stats["requeue_refusals"] == 1
    (rec,) = q.drain_shed()
    assert rec["requeued"] is True and "on requeue" in rec["reason"]


def test_control_lane_never_shed_and_drains_first(monkeypatch):
    q = _queue(monkeypatch, pressure=1.0, NHD_ADMIT_RATE="1",
               NHD_ADMIT_BURST="1", NHD_ADMIT_TENANT_CAP="1")
    q.put(_create("t", "p0"))
    for i in range(5):
        q.put(_delete("t", f"d{i}"))
    assert q.stats["shed"] == 0
    got = [q.get(block=False) for _ in range(6)]
    assert [it.type for it in got[:5]] == [WatchType.TRIAD_POD_DELETE] * 5
    assert got[5].type == WatchType.TRIAD_POD_CREATE


def test_batch_limit_tracks_rung(monkeypatch):
    press = [0.0]
    q = _queue(monkeypatch, NHD_ADMIT_BATCH="8")
    q.pressure_fn = lambda: press[0]
    assert q.rung() == RUNG_ADMIT and q.batch_limit() == 8
    press[0] = 0.6
    assert q.rung() == RUNG_DEFER and q.batch_limit() == 4
    press[0] = 0.9
    assert q.rung() == RUNG_SHED and q.batch_limit() == 1


def test_broken_pressure_probe_does_not_kill_the_door(monkeypatch):
    q = _queue(monkeypatch)
    q.pressure_fn = lambda: (_ for _ in ()).throw(RuntimeError("probe"))
    q.put(_create("t", "p0"))
    assert q.get(block=False).pod["name"] == "p0"


# ---------------------------------------------------------------------------
# scheduler integration: verdicts, explain, depth gauges, requeue
# ---------------------------------------------------------------------------


def _sched_with_admission(monkeypatch, n_nodes=2, pressure=None, **env):
    for k in ("NHD_ADMIT", "NHD_ADMIT_BATCH", "NHD_ADMIT_TENANT_CAP",
              "NHD_ADMIT_RATE", "NHD_ADMIT_BURST"):
        monkeypatch.delenv(k, raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    backend = FakeClusterBackend()
    for i in range(n_nodes):
        spec = SynthNodeSpec(name=f"node{i}")
        backend.add_node(spec.name, make_node_labels(spec),
                         hugepages_gb=spec.hugepages_gb)
    q = AdmissionQueue(
        clock=_Clock(),
        pressure_fn=(lambda: pressure) if pressure is not None else None,
    )
    sched = Scheduler(backend, q, queue.Queue(), respect_busy=False,
                      recorder=FlightRecorder(identity="t-ingress"))
    sched.build_initial_node_list()
    return backend, q, sched


def test_shed_verdict_event_decision_and_explain(monkeypatch):
    backend, q, sched = _sched_with_admission(
        monkeypatch, pressure=0.9, NHD_ADMIT_RATE="1", NHD_ADMIT_BURST="1")
    q.put(_create("tenant-x", "keep"))
    q.put(_create("tenant-x", "dropme"))
    assert q.stats["shed"] == 1
    sched._publish_shed_verdicts()
    # one pod event
    evs = [e for e in backend.events if e.reason == "AdmissionShed"]
    assert len(evs) == 1 and evs[0].pod == "dropme"
    # one decision record with the admission-shed outcome
    decs = [d for d in sched._recorder.recent_decisions(100)
            if d.get("outcome") == "admission-shed"]
    assert len(decs) == 1 and decs[0]["pod"] == "dropme"
    # /explain answers "why": the shed reason plus the door's state
    out = {}
    sched._attach_admission_explain(out, "tenant-x/dropme")
    assert "shed rung" in out["admission"]["shed"]
    assert out["admission"]["depths"]["rung"] == RUNG_SHED
    # a second publish issues nothing more (no double verdicts)
    sched._publish_shed_verdicts()
    assert len([e for e in backend.events
                if e.reason == "AdmissionShed"]) == 1


def test_requeue_refusal_yields_exactly_one_verdict(monkeypatch):
    backend, q, sched = _sched_with_admission(
        monkeypatch, NHD_ADMIT_TENANT_CAP="1")
    q.put(_create("t", "p0"))
    sched._requeue_put(_create("t", "retry"))
    assert q.stats["requeue_refusals"] == 1
    sched._publish_shed_verdicts()
    sched._publish_shed_verdicts()
    evs = [e for e in backend.events if e.reason == "AdmissionShed"]
    assert len(evs) == 1 and evs[0].pod == "retry"


def test_shed_pod_recovers_via_reconcile_scan(monkeypatch):
    """Composition with the scan net: a refusal at the front door is not
    a death sentence — the periodic reconcile scan (which bypasses the
    queue, like spillover claims do) picks the still-Pending pod up and
    binds it, while the shed verdict stays exactly one (never lost to
    the recovery, never re-issued by it)."""
    backend, q, sched = _sched_with_admission(
        monkeypatch, NHD_ADMIT_TENANT_CAP="1")
    controller = Controller(backend, q)
    cfg = make_triad_config(n_groups=1, gpus_per_group=0, cpu_workers=1,
                            hugepages_gb=2)
    backend.create_pod("first", "t", cfg_text=cfg)
    backend.create_pod("refused", "t", cfg_text=cfg)
    controller.decode_batch(list(backend.poll_watch_events()))
    assert q.stats["shed"] == 1
    sched._publish_shed_verdicts()
    while not q.empty():
        sched.run_once()
    assert backend.pods[("t", "first")].node
    assert backend.pods[("t", "refused")].node is None
    sched.check_pending_pods()
    assert backend.pods[("t", "refused")].node
    sched._publish_shed_verdicts()
    evs = [e for e in backend.events if e.reason == "AdmissionShed"]
    assert len(evs) == 1 and evs[0].pod == "refused"
    decs = [d for d in sched._recorder.recent_decisions(100)
            if d.get("outcome") == "admission-shed"]
    assert len(decs) == 1


def test_admitted_batch_drains_and_binds(monkeypatch):
    backend, q, sched = _sched_with_admission(monkeypatch, n_nodes=4)
    controller = Controller(backend, q)
    cfg = make_triad_config(n_groups=1, gpus_per_group=0, cpu_workers=1,
                            hugepages_gb=2)
    for ns in ("tenant-a", "tenant-b"):
        for i in range(3):
            backend.create_pod(f"{ns}-p{i}", ns, cfg_text=cfg)
    controller.decode_batch(list(backend.poll_watch_events()))
    assert q.qsize() == 6
    while not q.empty():
        sched.run_once()
    assert sum(1 for p in backend.pods.values() if p.node) == 6
    assert q.stats["shed"] == 0


def test_depth_gauges_consistent(monkeypatch):
    _backend, q, _sched = _sched_with_admission(monkeypatch)
    for i in range(3):
        q.put(_create("a", f"a{i}"))
    q.put(_create("b", "b0"))
    q.put(_delete("a", "gone"))
    d = q.depths()
    # one consistent read: the summed total IS qsize (the
    # event_queue_depth gauge and the fleet payload can't disagree)
    assert d["total"] == q.qsize() == 5
    assert d["max_tenant"] == 3 and d["control"] == 1
    assert d["tenants"] == {"a": 3, "b": 1}


# ---------------------------------------------------------------------------
# controller batched decode
# ---------------------------------------------------------------------------


def test_decode_batch_isolates_poison_and_flushes(monkeypatch):
    backend = FakeClusterBackend()
    q = AdmissionQueue(clock=_Clock())
    controller = Controller(backend, q)
    cfg = make_triad_config(n_groups=1, gpus_per_group=0, cpu_workers=1,
                            hugepages_gb=2)
    backend.create_pod("before", "t", cfg_text=cfg, tier=1)
    backend.create_pod("after", "t", cfg_text=cfg)
    events = list(backend.poll_watch_events())
    # annotation-less object: the pod translator crashes on it, and the
    # isolation handler's own log line still has kind/name to report
    poison = type("Ev", (), {"kind": "pod_create", "name": "poison"})()
    emitted = controller.decode_batch([events[0], poison, events[1]])
    # the poisoned event cost itself only; order preserved around it
    assert emitted == 2
    got = [q.get(block=False) for _ in range(2)]
    assert [it.pod["name"] for it in got] == ["before", "after"]
    # the tier annotation rides to the front door (the defer rung's input)
    assert got[0].pod["tier"] == "1" and got[1].pod["tier"] == "0"


# ---------------------------------------------------------------------------
# per-tenant SLO views
# ---------------------------------------------------------------------------


def test_slo_tenant_views_bounded_and_rendered():
    clk = _Clock(100.0)
    slo = SloTracker(clock=clk)
    for _ in range(50):
        slo.observe(0.01, tenant="victim")
    slo.observe(20.0, tenant="abuser")
    assert slo.tenant_p99("victim") < 1.0
    assert slo.tenant_p99("abuser") > 10.0
    assert slo.tenant_p99("never-seen") == 0.0
    # bounded label set: tenant #33+ aggregates as "other"
    for i in range(TENANT_LABEL_MAX + 5):
        slo.observe(0.01, tenant=f"flood-{i}")
    snap = slo.snapshot()["tenants"]
    assert len(snap) <= TENANT_LABEL_MAX + 1 and "other" in snap
    text = "\n".join(slo.render())
    assert 'nhd_slo_tenant_p99_seconds{tenant="victim"}' in text
    assert "nhd_slo_tenant_observations_total" in text


# ---------------------------------------------------------------------------
# the tenant-storm chaos cell (fast CI subset of `make tenant-chaos`)
# ---------------------------------------------------------------------------


def test_tenant_storm_isolation_fast_cell(tmp_path, monkeypatch):
    """One seed of the acceptance matrix end to end: calm baseline,
    10x abuser storm (victim p99 within 10% of calm, real shedding AND
    real re-admission, exact verdict accounting), and the NHD_ADMIT=0
    negative control that must VIOLATE the bound — all three cells via
    the same driver `make tenant-chaos` runs."""
    import importlib.util

    for k in ("NHD_ADMIT", "NHD_ADMIT_BATCH", "NHD_ADMIT_TENANT_CAP",
              "NHD_ADMIT_RATE"):
        monkeypatch.delenv(k, raising=False)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "chaos_storm_for_tenant", os.path.join(root, "tools", "chaos_storm.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = tmp_path / "tenant.json"
    rc = mod.main([
        "--tenant", "--seeds", "1", "--steps", "30", "--json-out", str(out),
    ])
    assert rc == 0
    summary = json.loads(out.read_text())
    (cell,) = summary["cells"]
    assert cell["ok"] and cell["violations"] == []
    storm, calm = cell["cells"]["storm"], cell["cells"]["calm"]
    control = cell["cells"]["control"]
    bound = calm["victim_p99_seconds"] * 1.10 + 1e-9
    assert storm["victim_p99_seconds"] <= bound
    assert storm["shed"] > 0 and storm["readmitted"] > 0
    # falsifiability: FIFO under the same storm starves the victim
    assert control["victim_p99_seconds"] > bound
