"""RetryPolicy unit tests (k8s/retry.py): failure classification, backoff
jitter bounds, per-call deadlines, Retry-After, and the circuit breaker's
open→half-open→close lifecycle — all against an injected clock and
recorded sleeps, zero real waiting."""

import random

import pytest

from nhd_tpu.k8s.restclient import ApiException
from nhd_tpu.k8s.retry import (
    CIRCUIT_CLOSED,
    CIRCUIT_HALF_OPEN,
    CIRCUIT_OPEN,
    ApiCounters,
    CircuitOpenError,
    RetryingApi,
    RetryPolicy,
    classify,
    retryable,
)


class FakeClock:
    def __init__(self):
        self.now = 1000.0
        self.sleeps = []

    def __call__(self):
        return self.now

    def sleep(self, seconds):
        self.sleeps.append(seconds)
        self.now += seconds

    def advance(self, seconds):
        self.now += seconds


def make_policy(**kw):
    clock = FakeClock()
    counters = ApiCounters()
    kw.setdefault("rng", random.Random(7))
    policy = RetryPolicy(
        clock=clock, sleep=clock.sleep, counters=counters, **kw
    )
    return policy, clock, counters


class Flaky:
    """Fails with the given exceptions in order, then returns 'ok'."""

    def __init__(self, *excs):
        self.excs = list(excs)
        self.calls = 0

    def __call__(self, *a, **kw):
        self.calls += 1
        if self.excs:
            raise self.excs.pop(0)
        return "ok"


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("status,want", [
    (429, True), (500, True), (502, True), (503, True), (504, True),
    (0, True),                       # restclient maps URLError to status-0
    (400, False), (403, False), (404, False), (409, False), (410, False),
    (501, False),                    # Not Implemented never improves
])
def test_classify_by_status(status, want):
    assert retryable(ApiException(status=status, reason="x")) is want


def test_classify_statusless_network_error_is_retryable():
    # the real kubernetes client raises bare network exceptions with no
    # .status attribute at all
    assert retryable(ConnectionResetError("peer reset")) is True


def test_classify_clientside_bug_is_terminal():
    # statusless exceptions are only retryable when they are genuine
    # transport failures; a deterministic client-side bug must surface
    # immediately instead of burning backoff and feeding the breaker
    assert retryable(TypeError("unexpected keyword argument")) is False
    assert retryable(KeyError("missing")) is False
    assert retryable(AttributeError("nope")) is False


def test_classify_valueerror_is_terminal():
    # the V1Binding deserialization quirk: a ValueError after a 2xx MEANS
    # SUCCESS and must reach the caller untouched (K8SMgr.py:487-491)
    assert retryable(ValueError("Invalid value for `target`")) is False


def test_classify_429_retry_after_header():
    exc = ApiException(status=429, reason="TooManyRequests",
                       headers={"Retry-After": "1.5"})
    assert classify(exc) == (True, 1.5)


def test_classify_retry_after_garbage_ignored():
    exc = ApiException(status=429, reason="x",
                       headers={"Retry-After": "Wed, 21 Oct"})
    assert classify(exc) == (True, None)


# ---------------------------------------------------------------------------
# the call loop
# ---------------------------------------------------------------------------


def test_success_passes_through():
    policy, clock, counters = make_policy()
    assert policy.call(lambda: 42) == 42
    assert clock.sleeps == []
    assert counters.get("api_calls_total") == 1


def test_transient_failures_then_success():
    policy, clock, counters = make_policy(attempts=4)
    fn = Flaky(ApiException(status=503), ApiException(status=500))
    assert policy.call(fn) == "ok"
    assert fn.calls == 3
    assert len(clock.sleeps) == 2
    assert counters.get("api_retries_total") == 2
    assert counters.get("api_giveups_total") == 0


def test_terminal_failure_raises_immediately():
    policy, clock, _ = make_policy()
    fn = Flaky(ApiException(status=404, reason="NotFound"))
    with pytest.raises(ApiException) as ei:
        policy.call(fn)
    assert ei.value.status == 404
    assert fn.calls == 1 and clock.sleeps == []


def test_valueerror_propagates_and_counts_as_success():
    policy, _, _ = make_policy(breaker_threshold=1)
    with pytest.raises(ValueError):
        policy.call(Flaky(ValueError("quirk")))
    # the wire call succeeded: the breaker must not have moved
    assert policy.circuit_state == CIRCUIT_CLOSED


def test_attempt_budget_exhaustion():
    policy, clock, counters = make_policy(attempts=3)
    fn = Flaky(*[ApiException(status=503)] * 10)
    with pytest.raises(ApiException):
        policy.call(fn)
    assert fn.calls == 3                       # 1 try + 2 retries
    assert counters.get("api_giveups_total") == 1


def test_deadline_expiry_stops_retries():
    # deadline shorter than one backoff step: a single failure gives up
    # even though the attempt budget would allow more
    policy, clock, counters = make_policy(
        attempts=100, base_delay=1.0, max_delay=1.0, deadline=0.5
    )
    fn = Flaky(*[ApiException(status=503)] * 10)
    with pytest.raises(ApiException):
        policy.call(fn)
    assert fn.calls == 1
    assert counters.get("api_giveups_total") == 1


def test_jitter_bounds_seeded():
    # decorrelated jitter: every sleep within [base, cap], reproducible
    # for a fixed seed
    policy, clock, _ = make_policy(
        attempts=6, base_delay=0.1, max_delay=2.0, deadline=1e9,
        rng=random.Random(42),
    )
    fn = Flaky(*[ApiException(status=500)] * 5)
    assert policy.call(fn) == "ok"
    assert len(clock.sleeps) == 5
    for s in clock.sleeps:
        assert 0.1 <= s <= 2.0
    # and the sequence is deterministic for the seed
    policy2, clock2, _ = make_policy(
        attempts=6, base_delay=0.1, max_delay=2.0, deadline=1e9,
        rng=random.Random(42),
    )
    policy2.call(Flaky(*[ApiException(status=500)] * 5))
    assert clock2.sleeps == clock.sleeps


def test_retry_after_floors_the_backoff():
    policy, clock, _ = make_policy(
        attempts=2, base_delay=0.01, max_delay=5.0, deadline=1e9
    )
    fn = Flaky(ApiException(status=429, headers={"Retry-After": "1.25"}))
    assert policy.call(fn) == "ok"
    assert clock.sleeps[0] >= 1.25


def test_retry_after_beyond_max_delay_is_honored():
    """A throttling server's Retry-After wins over max_delay (re-hitting
    inside the window it asked us to stay away defeats the point); only
    the per-call deadline bounds it."""
    policy, clock, _ = make_policy(
        attempts=3, base_delay=0.01, max_delay=2.0, deadline=60.0
    )
    fn = Flaky(ApiException(status=429, headers={"Retry-After": "10"}))
    assert policy.call(fn) == "ok"
    assert clock.sleeps[0] >= 10.0


def test_half_open_wedged_probe_times_out():
    """If the half-open probe never reports back (hung socket, thread
    unwound by BaseException), a fresh probe is admitted after another
    cooldown instead of rejecting everyone forever."""
    policy, clock, _ = make_policy(
        attempts=1, breaker_threshold=1, breaker_cooldown=10.0
    )
    with pytest.raises(ApiException):
        policy.call(Flaky(ApiException(status=500)))
    clock.advance(10.1)
    assert policy._admit() is True       # probe 1 admitted… and vanishes
    assert policy._admit() is False      # still in flight: others wait
    clock.advance(10.1)
    assert policy._admit() is True       # presumed dead: new probe
    assert policy.circuit_state == CIRCUIT_HALF_OPEN


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


def test_circuit_opens_after_consecutive_failures():
    policy, clock, counters = make_policy(
        attempts=1, breaker_threshold=3, breaker_cooldown=30.0
    )
    for _ in range(3):
        with pytest.raises(ApiException):
            policy.call(Flaky(ApiException(status=503)))
    assert policy.circuit_state == CIRCUIT_OPEN
    assert counters.get("api_circuit_open_total") == 1
    # while open: instant rejection, the function never runs
    fn = Flaky()
    with pytest.raises(CircuitOpenError):
        policy.call(fn)
    assert fn.calls == 0
    assert counters.get("api_circuit_rejections_total") == 1


def test_circuit_half_opens_after_cooldown_and_closes_on_success():
    policy, clock, _ = make_policy(
        attempts=1, breaker_threshold=2, breaker_cooldown=10.0
    )
    for _ in range(2):
        with pytest.raises(ApiException):
            policy.call(Flaky(ApiException(status=500)))
    assert policy.circuit_state == CIRCUIT_OPEN
    clock.advance(10.1)
    # the probe is admitted and succeeds → closed
    assert policy.call(Flaky()) == "ok"
    assert policy.circuit_state == CIRCUIT_CLOSED


def test_half_open_probe_failure_reopens():
    policy, clock, counters = make_policy(
        attempts=1, breaker_threshold=2, breaker_cooldown=10.0
    )
    for _ in range(2):
        with pytest.raises(ApiException):
            policy.call(Flaky(ApiException(status=500)))
    clock.advance(10.1)
    with pytest.raises(ApiException):
        policy.call(Flaky(ApiException(status=502)))
    assert policy.circuit_state == CIRCUIT_OPEN
    assert counters.get("api_circuit_open_total") == 2
    # and the cooldown restarted: still rejecting before it lapses
    clock.advance(5.0)
    with pytest.raises(CircuitOpenError):
        policy.call(Flaky())


def test_half_open_admits_exactly_one_probe():
    policy, clock, _ = make_policy(
        attempts=1, breaker_threshold=1, breaker_cooldown=10.0
    )
    with pytest.raises(ApiException):
        policy.call(Flaky(ApiException(status=500)))
    clock.advance(10.1)
    assert policy._admit() is True          # the probe slot
    assert policy.circuit_state == CIRCUIT_HALF_OPEN
    assert policy._admit() is False         # everyone else waits


def test_half_open_probe_with_terminal_error_closes_the_circuit():
    """A terminal 4xx IS a server response: a half-open probe answered
    404 proves the server is back and must close the breaker, not wedge
    it in HALF_OPEN rejecting every later call."""
    policy, clock, _ = make_policy(
        attempts=1, breaker_threshold=2, breaker_cooldown=10.0
    )
    for _ in range(2):
        with pytest.raises(ApiException):
            policy.call(Flaky(ApiException(status=500)))
    assert policy.circuit_state == CIRCUIT_OPEN
    clock.advance(10.1)
    # the probe reaches the server, which answers 404 (terminal)
    with pytest.raises(ApiException):
        policy.call(Flaky(ApiException(status=404)))
    assert policy.circuit_state == CIRCUIT_CLOSED
    # and ordinary calls flow again
    assert policy.call(Flaky()) == "ok"


def test_terminal_failures_do_not_feed_the_breaker():
    policy, clock, _ = make_policy(attempts=1, breaker_threshold=2)
    for _ in range(10):
        with pytest.raises(ApiException):
            policy.call(Flaky(ApiException(status=404)))
    assert policy.circuit_state == CIRCUIT_CLOSED


def test_open_circuit_uses_wired_exception_class():
    class MyExc(Exception):
        def __init__(self, status=0, reason=""):
            self.status, self.reason = status, reason

    policy, clock, _ = make_policy(
        attempts=1, breaker_threshold=1, exc_class=MyExc
    )
    with pytest.raises(ApiException):
        policy.call(Flaky(ApiException(status=500)))
    with pytest.raises(MyExc):
        policy.call(Flaky())


# ---------------------------------------------------------------------------
# RetryingApi proxy
# ---------------------------------------------------------------------------


class _Api:
    def __init__(self):
        self.fail_reads = 0
        self.watch_calls = 0

    def read_thing(self):
        if self.fail_reads:
            self.fail_reads -= 1
            raise ApiException(status=503)
        return "thing"

    def list_thing(self, watch=False):
        if watch:
            self.watch_calls += 1
            raise ApiException(status=503)
        return ["thing"]

    not_callable = "just-data"


def test_retrying_api_wraps_calls():
    policy, clock, counters = make_policy(attempts=3)
    api = RetryingApi(_Api(), policy)
    api._api.fail_reads = 2
    assert api.read_thing() == "thing"
    assert counters.get("api_retries_total") == 2


def test_retrying_api_passes_watch_through():
    # the watch plane owns its own reconnect backoff; the policy must not
    # double-retry stream establishment
    policy, clock, counters = make_policy(attempts=5)
    api = RetryingApi(_Api(), policy)
    with pytest.raises(ApiException):
        api.list_thing(watch=True)
    assert api._api.watch_calls == 1
    assert clock.sleeps == []


def test_retrying_api_exposes_data_attributes():
    policy, _, _ = make_policy()
    api = RetryingApi(_Api(), policy)
    assert api.not_callable == "just-data"
