"""Edge-shape coverage: >2 NUMA nodes, oversized combo lattices."""

import random

from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
from nhd_tpu.core.topology import MapMode, SmtMode
from nhd_tpu.sim import SynthNodeSpec, make_cluster
from nhd_tpu.solver import BatchItem, BatchScheduler, JaxMatcher, find_node


def quad_numa_cluster(n=2):
    """A 4-socket (4-NUMA) node shape — beyond the reference's 2-socket
    Intel assumption, exercised through the same label path."""
    return make_cluster(
        n, SynthNodeSpec(sockets=4, phys_cores=32, nics_per_numa=1,
                         gpus_per_numa=1, hugepages_gb=64),
    )


def gpu_req(n_groups=1, gpus=1):
    return PodRequest(
        groups=tuple(
            GroupRequest(CpuRequest(4, SmtMode.ON), CpuRequest(1, SmtMode.ON),
                         gpus, 10.0, 5.0)
            for _ in range(n_groups)
        ),
        misc=CpuRequest(1, SmtMode.ON),
        hugepages_gb=2,
        map_mode=MapMode.NUMA,
    )


def test_quad_numa_parity():
    nodes = quad_numa_cluster()
    matcher = JaxMatcher()
    for n_groups in (1, 2, 3):
        req = gpu_req(n_groups=n_groups)
        want = find_node(nodes, req, now=0.0, respect_busy=False)
        got = matcher.find_node(nodes, req, now=0.0, respect_busy=False)
        assert (want is None) == (got is None), f"G={n_groups}"
        if want:
            assert got.node == want.node and got.mapping == want.mapping


def test_quad_numa_gpu_spread():
    """4 GPU groups on a 4-NUMA node with 1 GPU each → all four NUMA nodes."""
    nodes = quad_numa_cluster(1)
    req = gpu_req(n_groups=4)
    m = find_node(nodes, req, now=0.0, respect_busy=False)
    assert m is not None
    assert sorted(m.mapping["gpu"]) == [0, 1, 2, 3]
    got = JaxMatcher().find_node(nodes, req, now=0.0, respect_busy=False)
    assert got.mapping == m.mapping


def test_oversized_bucket_falls_back_to_oracle(monkeypatch):
    """A pod whose U^G * K^G lattice exceeds the budget still schedules —
    via the serial oracle — in both matcher and batch paths. The budget is
    shrunk so a 3-group pod counts as oversized (a real 10-group pod takes
    the same path, just slowly on both sides)."""
    from nhd_tpu.solver import kernel

    monkeypatch.setattr(kernel, "MAX_LATTICE", 16)
    nodes = quad_numa_cluster()
    big = gpu_req(n_groups=3, gpus=0)
    assert not kernel.bucket_tractable(3, 4, 1)

    got = JaxMatcher().find_node(nodes, big, now=0.0, respect_busy=False)
    want = find_node(nodes, big, now=0.0, respect_busy=False)
    assert (want is None) == (got is None)
    if want:
        assert got.node == want.node and got.mapping == want.mapping

    sched = BatchScheduler(respect_busy=False)
    mixed = [
        BatchItem(("ns", "small"), gpu_req()),          # tractable path
        BatchItem(("ns", "big"), big),                  # serial pre-pass
    ]
    results, stats = sched.schedule(nodes, mixed, now=0.0)
    assert results[0].node is not None
    assert results[1].node is not None
    assert stats.scheduled == 2
    total_used = sum(
        1 for node in nodes.values() for c in node.cores
        if c.used and c.core not in node.reserved_cores
    )
    assert total_used > 0


def test_many_group_pod_single_numa_no_overflow():
    """A 33-group pod on a single-NUMA cluster stays tractable (lattice =
    1) but exceeds the native fixed buffers — it must take the numpy path
    and schedule without memory corruption (previously heap-overflowed)."""
    nodes = make_cluster(
        1, SynthNodeSpec(sockets=1, phys_cores=96, nics_per_numa=1,
                         gpus_per_numa=0, hugepages_gb=64),
    )
    big = PodRequest(
        groups=tuple(
            GroupRequest(CpuRequest(2, SmtMode.ON), CpuRequest(0, SmtMode.OFF),
                         0, 0.5, 0.2)
            for _ in range(33)
        ),
        misc=CpuRequest(1, SmtMode.ON),
        hugepages_gb=1,
        map_mode=MapMode.NUMA,
    )
    results, stats = BatchScheduler(respect_busy=False).schedule(
        nodes, [BatchItem(("ns", "huge"), big)], now=0.0
    )
    assert results[0].node == "node00000"
    node = nodes["node00000"]
    used = sum(1 for c in node.cores
               if c.used and c.core not in node.reserved_cores)
    assert used > 33  # all groups' cores actually claimed


def test_single_socket_nodes_schedule_and_match_oracle():
    """U=1 clusters (single-socket nodes) never occur in the randomized
    generators (always sockets=2); pin the degenerate combo lattice."""
    import copy

    from nhd_tpu.solver import find_node
    from tests.test_batch import items, simple_request

    nodes = make_cluster(
        3, SynthNodeSpec(sockets=1, phys_cores=16, gpus_per_numa=2,
                         nics_per_numa=3),
    )
    ref = copy.deepcopy(nodes)
    reqs = [simple_request(gpus=i % 2) for i in range(8)]
    results, stats = BatchScheduler(respect_busy=False).schedule(
        nodes, items(reqs), now=0.0
    )
    assert stats.scheduled == 8 and stats.failed == 0
    want = find_node(ref, reqs[0], now=0.0, respect_busy=False)
    assert results[0].node == want.node


def test_mixed_socket_counts_pad_cleanly():
    """A heterogeneous cluster mixing U=1 and U=2 nodes: single-socket
    rows are padded to the cluster-wide U and must never be selected for
    a NUMA index they don't have."""
    from tests.test_batch import items, simple_request

    nodes = {}
    nodes.update(make_cluster(
        2, SynthNodeSpec(sockets=1, phys_cores=8, gpus_per_numa=1,
                         nics_per_numa=2)))
    two = make_cluster(
        2, SynthNodeSpec(sockets=2, phys_cores=24, gpus_per_numa=2,
                         nics_per_numa=2))
    for name, node in two.items():
        nodes[f"big-{name}"] = node
    reqs = [simple_request(gpus=1) for _ in range(10)]
    results, stats = BatchScheduler(respect_busy=False).schedule(
        nodes, items(reqs), now=0.0
    )
    assert stats.failed == 0
    assert stats.scheduled >= 6
    single_socket = {n for n in nodes if not n.startswith("big-")}
    placed_on_small = 0
    for r in results:
        if r.node in single_socket and r.mapping is not None:
            placed_on_small += 1
            # the padded NUMA index 1 must never be chosen on a U=1 node
            assert all(u == 0 for u in r.mapping["gpu"])
            assert all(u == 0 for u in r.mapping["cpu"])
            assert all(u == 0 for u, _ in r.mapping["nic"])
    assert placed_on_small > 0, "no pod exercised the padded U=1 rows"


def test_device_state_update_rows_matches_reupload():
    """Targeted: after claims, the resident arrays patched by the donated
    row scatters must equal a fresh full upload — on one device and on
    the 8-device mesh."""
    import numpy as np

    from nhd_tpu.parallel.sharding import make_mesh
    from nhd_tpu.solver.device_state import _ARG_ORDER, DeviceClusterState
    from nhd_tpu.solver.encode import encode_cluster, refresh_node_row
    from tests.test_batch import items, simple_request

    for mesh in (None, make_mesh()):
        nodes = make_cluster(6)
        cluster = encode_cluster(nodes, now=0.0)
        dev = DeviceClusterState(cluster, mesh)

        # mutate some rows on the host mirror, refresh, scatter
        touched = [0, 2, 5]
        for i, name in enumerate(nodes):
            if i in touched:
                for gpu in nodes[name].gpus[:2]:
                    gpu.used = True
                nodes[name].mem.free_hugepages_gb -= 8
                refresh_node_row(cluster, i, nodes[name], now=0.0)
        dev.update_rows(touched)

        fresh = DeviceClusterState(cluster, mesh)
        for name in _ARG_ORDER:
            np.testing.assert_array_equal(
                np.asarray(dev._dev[name]), np.asarray(fresh._dev[name]),
                err_msg=f"{name} diverged (mesh={mesh is not None})",
            )


def test_staged_rows_fuse_into_solve_dispatch():
    """Targeted: stage_rows defers the row scatter into the next
    solve_ranked call (the single-dispatch-per-round path for the
    tunnel-attached TPU). The fused program's RankOut AND its post-scatter
    resident arrays must match a fresh full upload's."""
    import numpy as np

    from nhd_tpu.solver.device_state import _ARG_ORDER, DeviceClusterState
    from nhd_tpu.solver.encode import (
        encode_cluster, encode_pods, refresh_node_row,
    )
    from tests.test_batch import simple_request

    nodes = make_cluster(6)
    cluster = encode_cluster(nodes, now=0.0)
    dev = DeviceClusterState(cluster)  # single device: the fused path

    touched = [1, 3, 4]
    for i, name in enumerate(nodes):
        if i in touched:
            for gpu in nodes[name].gpus[:2]:
                gpu.used = True
            nodes[name].mem.free_hugepages_gb -= 8
            refresh_node_row(cluster, i, nodes[name], now=0.0)
    dev.stage_rows(touched)
    # staged, not yet applied: the resident mutable rows still hold the
    # pre-claim values, not the mirror's current (post-claim) ones
    post = np.asarray(DeviceClusterState(cluster)._dev["gpu_free"])
    assert not np.array_equal(np.asarray(dev._dev["gpu_free"]), post)

    (pods,) = encode_pods(
        [simple_request(gpus=1)], cluster.interner
    ).values()
    got = dev.solve_ranked(pods, R=8)

    fresh = DeviceClusterState(cluster)
    want = fresh.solve_ranked(pods, R=8)
    from nhd_tpu.solver.kernel import RankOut

    for name, g, w in zip(
        RankOut._fields, np.asarray(got), np.asarray(want)
    ):
        np.testing.assert_array_equal(
            g, w, err_msg=f"RankOut row {name} diverged"
        )
    # and the scatter really landed on the resident arrays
    for name in _ARG_ORDER:
        np.testing.assert_array_equal(
            np.asarray(dev._dev[name]), np.asarray(fresh._dev[name]),
            err_msg=f"{name} diverged after fused scatter",
        )
    assert not dev._staged
