"""Multi-host helpers: the per-process node shard must exactly partition
the cluster (single-host dev image: process topology is mocked)."""

from unittest import mock

import pytest

from nhd_tpu.parallel import multihost
from nhd_tpu.sim import make_cluster


@pytest.mark.parametrize("n_proc,n_nodes", [(1, 5), (2, 10), (3, 10), (4, 3)])
def test_local_node_slices_partition(n_proc, n_nodes):
    nodes = make_cluster(n_nodes)
    shards = []
    for rank in range(n_proc):
        with mock.patch("jax.process_count", return_value=n_proc), \
             mock.patch("jax.process_index", return_value=rank):
            shards.append(multihost.local_nodes(nodes))
    seen = [name for s in shards for name in s]
    assert seen == list(nodes.keys())          # exact cover, stable order
    assert len(seen) == len(set(seen))         # no node owned twice
    # block layout: every shard is contiguous in name order
    names = list(nodes.keys())
    at = 0
    for s in shards:
        assert list(s.keys()) == names[at:at + len(s)]
        at += len(s)


def test_local_nodes_feed_streaming():
    """The documented multi-host pattern composes: a rank's shard goes
    straight into StreamingScheduler."""
    from nhd_tpu.solver import BatchItem, StreamingScheduler
    from tests.test_batch import simple_request

    nodes = make_cluster(6)
    with mock.patch("jax.process_count", return_value=2), \
         mock.patch("jax.process_index", return_value=1):
        mine = multihost.local_nodes(nodes)
    assert len(mine) == 3
    items = [BatchItem(("ns", f"p{i}"), simple_request()) for i in range(4)]
    results, stats = StreamingScheduler(
        tile_nodes=2, respect_busy=False
    ).schedule(mine, items, now=0.0)
    assert stats.scheduled == 4
    assert all(r.node in mine for r in results)
