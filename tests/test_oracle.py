"""Oracle matcher behavior tests (reference semantics: Matcher.py)."""

import pytest

from nhd_tpu.config.triad import TriadCfgParser
from nhd_tpu.core.node import AssignmentError
from nhd_tpu.core.request import CpuRequest, GroupRequest, PodRequest
from nhd_tpu.core.topology import MapMode, SmtMode
from nhd_tpu.sim import SynthNodeSpec, make_cluster, make_node, make_triad_config
from nhd_tpu.solver.oracle import OracleMatcher, find_node


def req(
    *,
    groups=(),
    misc=(0, SmtMode.OFF),
    hugepages=0,
    map_mode=MapMode.NUMA,
):
    gs = tuple(
        GroupRequest(
            proc=CpuRequest(g[0], g[1]),
            misc=CpuRequest(g[2], g[3]),
            gpus=g[4],
            nic_rx_gbps=g[5],
            nic_tx_gbps=g[6],
        )
        for g in groups
    )
    return PodRequest(
        groups=gs,
        misc=CpuRequest(*misc),
        hugepages_gb=hugepages,
        map_mode=map_mode,
    )


SIMPLE = ((4, SmtMode.ON, 2, SmtMode.ON, 0, 10.0, 5.0),)


def test_simple_placement():
    nodes = make_cluster(4)
    r = req(groups=SIMPLE, misc=(2, SmtMode.ON), hugepages=4)
    m = find_node(nodes, r)
    assert m is not None
    assert m.node == "node00000"
    assert len(m.mapping["gpu"]) == 1
    assert len(m.mapping["cpu"]) == 2  # group + trailing misc slot
    assert len(m.mapping["nic"]) == 1


def test_invalid_map_mode():
    nodes = make_cluster(1)
    assert find_node(nodes, req(groups=SIMPLE, map_mode=MapMode.INVALID)) is None


def test_hugepage_filter():
    nodes = make_cluster(2, SynthNodeSpec(hugepages_gb=8))
    assert find_node(nodes, req(groups=SIMPLE, hugepages=9)) is None
    assert find_node(nodes, req(groups=SIMPLE, hugepages=8)) is not None


def test_maintenance_filter():
    nodes = make_cluster(2)
    nodes["node00000"].maintenance = True
    m = find_node(nodes, req(groups=SIMPLE))
    assert m.node == "node00001"


def test_busy_backoff_gpu_pods_only():
    nodes = make_cluster(1)
    nodes["node00000"].set_busy(now=1000.0)
    gpu_req = req(groups=((2, SmtMode.ON, 0, SmtMode.OFF, 1, 10.0, 5.0),))
    cpu_req = req(groups=SIMPLE)
    # GPU pod blocked inside the window, allowed after
    assert find_node(nodes, gpu_req, now=1010.0) is None
    assert find_node(nodes, gpu_req, now=1031.0) is not None
    # CPU-only pod never blocked by busy
    assert find_node(nodes, cpu_req, now=1010.0) is not None


def test_cpu_only_pod_prefers_gpuless_node():
    specs = SynthNodeSpec(gpus_per_numa=2)
    nodes = make_cluster(2, specs)
    gpuless = make_node(SynthNodeSpec(name="cpunode", gpus_per_numa=0))
    nodes["cpunode"] = gpuless
    m = find_node(nodes, req(groups=SIMPLE))
    assert m.node == "cpunode"
    # ...but a GPU pod lands on a GPU node
    gm = find_node(nodes, req(groups=((2, SmtMode.ON, 0, SmtMode.OFF, 1, 10.0, 5.0),)))
    assert gm.node == "node00000"


def test_numa_colocation_constraint():
    """A group must fit on ONE numa node even when the node-wide total fits."""
    # 2 sockets × 4 free physical cores each after reservation
    nodes = {"n": make_node(SynthNodeSpec(name="n", phys_cores=12, reserved_cores=2))}
    # 8 SMT-off proc cores → needs 8 physical on one numa: impossible (4+6 split)
    r = req(groups=((8, SmtMode.OFF, 0, SmtMode.OFF, 0, 0.0, 0.0),))
    assert find_node(nodes, r) is None
    # SMT-on version needs ceil(8/2)=4 physical: fits numa0
    r2 = req(groups=((8, SmtMode.ON, 0, SmtMode.OFF, 0, 0.0, 0.0),))
    assert find_node(nodes, r2) is not None


def test_gpu_numa_spread():
    """Two groups of 2 GPUs must land on separate NUMA nodes when each node
    has only 2 free per NUMA."""
    nodes = make_cluster(1, SynthNodeSpec(gpus_per_numa=2))
    r = req(
        groups=(
            (2, SmtMode.ON, 0, SmtMode.OFF, 2, 10.0, 5.0),
            (2, SmtMode.ON, 0, SmtMode.OFF, 2, 10.0, 5.0),
        )
    )
    m = find_node(nodes, r)
    assert m is not None
    g = m.mapping["gpu"]
    assert set(g) == {0, 1}  # forced onto distinct NUMA nodes


def test_nic_bandwidth_exhaustion():
    nodes = make_cluster(1, SynthNodeSpec(nics_per_numa=1, nic_speed_mbps=20000))
    # 2 NICs (1/numa) with 18 Gbps schedulable each
    r = req(groups=((2, SmtMode.ON, 0, SmtMode.OFF, 0, 18.0, 0.0),))
    assert find_node(nodes, r) is not None
    r2 = req(groups=((2, SmtMode.ON, 0, SmtMode.OFF, 0, 18.1, 0.0),))
    assert find_node(nodes, r2) is None


def test_nic_sharing_within_pod():
    """Two groups may share one NIC when their joint demand fits."""
    nodes = make_cluster(
        1, SynthNodeSpec(nics_per_numa=1, sockets=2, nic_speed_mbps=100000)
    )
    r = req(
        groups=(
            (2, SmtMode.ON, 0, SmtMode.OFF, 0, 40.0, 40.0),
            (2, SmtMode.ON, 0, SmtMode.OFF, 0, 40.0, 40.0),
        )
    )
    m = find_node(nodes, r)
    assert m is not None
    # joint demand 80+80 on one NIC would NOT fit at 90 each direction if
    # both went to the same NIC... 40+40=80 <= 90 fits actually; check a
    # too-big joint demand forces separate NUMA nodes:
    r2 = req(
        groups=(
            (2, SmtMode.ON, 0, SmtMode.OFF, 0, 50.0, 0.0),
            (2, SmtMode.ON, 0, SmtMode.OFF, 0, 50.0, 0.0),
        )
    )
    m2 = find_node(nodes, r2)
    assert m2 is not None
    numas = [numa for numa, _ in m2.mapping["nic"]]
    assert numas[0] != numas[1]


def test_nic_used_by_other_pod_invisible():
    nodes = make_cluster(1, SynthNodeSpec(nics_per_numa=1))
    for nic in nodes["node00000"].nics:
        nic.pods_used = 1  # sharing disabled → zero headroom
    r = req(groups=((2, SmtMode.ON, 0, SmtMode.OFF, 0, 1.0, 0.0),))
    assert find_node(nodes, r) is None


def test_pci_mode_requires_gpu_on_nic_switch():
    # synth topology: NIC slot i and GPU slot i share switch numa*16+i
    nodes = make_cluster(1, SynthNodeSpec(nics_per_numa=2, gpus_per_numa=2))
    r = req(
        groups=((2, SmtMode.ON, 0, SmtMode.OFF, 1, 10.0, 5.0),),
        map_mode=MapMode.PCI,
    )
    m = find_node(nodes, r)
    assert m is not None
    # consume the GPU on switch of numa0/nic0 and numa1/nic0...
    node = nodes["node00000"]
    for gpu in node.gpus:
        gpu.used = True
    assert find_node(nodes, r) is None


def test_gpu_packing_skew_choice():
    """Mapping choice maximizes GPU packing skew (all groups on one NUMA
    when possible) — reference GetNumaGroupIdx (Matcher.py:423-452)."""
    nodes = make_cluster(1, SynthNodeSpec(gpus_per_numa=2))
    r = req(
        groups=(
            (1, SmtMode.ON, 0, SmtMode.OFF, 1, 5.0, 0.0),
            (1, SmtMode.ON, 0, SmtMode.OFF, 1, 5.0, 0.0),
        )
    )
    m = find_node(nodes, r)
    assert m is not None
    # both groups CAN fit on one numa (2 gpus free each) → skew-max combo
    assert m.mapping["gpu"] in ((0, 0), (1, 1))


def test_end_to_end_assignment():
    """Match → assign physical IDs → claim visible in free queries."""
    nodes = make_cluster(2)
    text = make_triad_config(
        n_groups=1, nic_pairs_per_group=1, cpu_workers=2,
        gpus_per_group=1, feeders_per_gpu=1, helpers_per_group=1,
        ext_cores=1, hugepages_gb=4,
    )
    parser = TriadCfgParser(text)
    top = parser.to_topology(False)
    m = find_node(nodes, top)
    assert m is not None
    node = nodes[m.node]
    free_before = node.free_cpu_cores_per_numa()
    gpu_before = node.free_gpu_count()
    nic_list = node.assign_physical_ids(m.mapping, top)
    assert all(c.core >= 0 for pg in top.proc_groups for c in pg.proc_cores)
    assert all(g.device_id >= 0 for pg in top.proc_groups for g in pg.gpus)
    assert node.free_gpu_count() == gpu_before - 1
    assert sum(node.free_cpu_cores_per_numa()) < sum(free_before)
    assert node.mem.free_hugepages_gb == node.mem.ttl_hugepages_gb - 4
    assert len(nic_list) == 2  # rx + tx entries
    # NIC pair got its MAC
    assert top.nic_pairs[0].mac != ""
    # config write-back now contains physical IDs
    out = parser.to_config()
    assert "-1" not in out.replace("e-1", "")  # no placeholders left


def test_assignment_unwind_on_shortfall():
    """If assignment cannot deliver promised cores, node state is restored."""
    nodes = make_cluster(1)
    node = nodes["node00000"]
    r = req(groups=((4, SmtMode.ON, 0, SmtMode.OFF, 0, 10.0, 5.0),))
    m = find_node(nodes, r)
    assert m is not None

    text = make_triad_config(n_groups=1, nic_pairs_per_group=1, cpu_workers=2)
    parser = TriadCfgParser(text)
    top = parser.to_topology(False)
    # sabotage: claim every core on the mapped numa behind the matcher's back
    numa = m.mapping["gpu"][0]
    snapshot = [c.used for c in node.cores]
    huge = node.mem.free_hugepages_gb
    for c in node.cores:
        if c.socket == numa:
            c.used = True
    pre = [c.used for c in node.cores]
    with pytest.raises(AssignmentError):
        node.assign_physical_ids(m.mapping, top)
    assert [c.used for c in node.cores] == pre
    assert node.mem.free_hugepages_gb == huge
    del snapshot


def test_oracle_feasible_sets_shape():
    """FilterNumaTopology produces product-order combos with misc slot."""
    matcher = OracleMatcher()
    nodes = make_cluster(1)
    r = req(groups=SIMPLE, misc=(1, SmtMode.ON))
    filt_nodes = matcher.filter_pod_resources(nodes, r)
    filts = matcher.filter_numa_topology(filt_nodes, r)
    name = "node00000"
    assert filts.candidates == [name]
    assert all(len(c) == 1 for c in filts.gpu[name])
    assert all(len(c) == 2 for c in filts.cpu[name])
    # product order: (0,0) before (0,1) before (1,0)...
    assert filts.cpu[name] == sorted(filts.cpu[name])


def test_node_group_and_active_filtering():
    """Pods only land on active nodes sharing a node group
    (reference: NHDScheduler.py:235-247, folded into the oracle)."""
    nodes = make_cluster(3, groups=["default", "edge", "edge"])
    r = req(groups=SIMPLE)
    edge = PodRequest(
        groups=r.groups, misc=r.misc, hugepages_gb=0,
        map_mode=MapMode.NUMA, node_groups=frozenset({"edge"}),
    )
    m = find_node(nodes, edge)
    assert m.node == "node00001"
    nodes["node00001"].active = False
    assert find_node(nodes, edge).node == "node00002"
    nowhere = PodRequest(
        groups=r.groups, misc=r.misc, hugepages_gb=0,
        map_mode=MapMode.NUMA, node_groups=frozenset({"nope"}),
    )
    assert find_node(nodes, nowhere) is None
