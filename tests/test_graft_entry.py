"""The driver entry points must stay importable and runnable."""

import jax

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    import numpy as np

    fn, args = graft.entry()
    mutable, claims, need_left = jax.jit(fn)(*args)
    # the megaround made real claims and consumed real need
    claims = np.asarray(claims)
    assert claims.ndim == 2 and (claims >= 0).sum() > 0
    assert int(np.asarray(need_left).sum()) < int(np.asarray(args[2]).sum())
    # the claimed state mutated (GPUs were consumed)
    assert not np.array_equal(
        np.asarray(mutable["gpu_free"]), np.asarray(args[0]["gpu_free"])
    )


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)
