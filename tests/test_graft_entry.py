"""The driver entry points must stay importable and runnable."""

import jax

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    import numpy as np

    fn, args = graft.entry()
    # the test compiles the entry exactly once; no wrapper cache to lose
    mutable, claims, counts, need_left, it = jax.jit(fn)(*args)  # nhdlint: ignore[NHD104]
    # the megaround made real claims and consumed real need
    claims = np.asarray(claims)
    counts = np.asarray(counts)
    assert claims.ndim == 2 and (claims >= 0).sum() > 0
    # every claim carries a positive copy count (multi-copy plane)
    assert (counts[claims >= 0] > 0).all()
    assert int(np.asarray(need_left).sum()) < int(np.asarray(args[2]).sum())
    # the exit-reason iteration counter is in range (saturation
    # certificate input, solver/speculate.py)
    assert 0 < int(np.asarray(it)) <= 8
    # the claimed state mutated (GPUs were consumed)
    assert not np.array_equal(
        np.asarray(mutable["gpu_free"]), np.asarray(args[0]["gpu_free"])
    )


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)
