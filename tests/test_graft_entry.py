"""The driver entry points must stay importable and runnable."""

import jax

import __graft_entry__ as graft


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.cand.shape[0] >= 1
    assert out.cand.shape == out.best_c.shape


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)
