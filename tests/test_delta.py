"""Incremental cluster state (solver/encode.py ClusterDelta): the
randomized delta-parity property test plus the forced-fallback and
device-row pins (ISSUE 9).

The contract under test is SURVEY §5.4's: host HostNode objects stay the
source of truth and the incrementally-maintained resident state must
remain RE-DERIVABLE — after every event batch, the delta's live rows are
bit-exact with a from-scratch ``encode_cluster`` of the same nodes, and
(with device state on) the resident device arrays are bit-exact with the
host arrays. Fallback events (new group bit, padding/capacity overflow,
tombstone re-add, compaction) may cost a logged full rebuild; they may
never cost parity.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from nhd_tpu.sim.requests import request_to_topology
from nhd_tpu.sim.synth import SynthNodeSpec, make_node, make_node_labels
from nhd_tpu.sim.workloads import make_cluster, workload_mix
from nhd_tpu.solver.batch import BatchItem, BatchScheduler
from nhd_tpu.solver.encode import (
    ClusterDelta,
    encode_cluster,
    rebuild_reasons_snapshot,
    reset_delta_metrics,
)
from nhd_tpu.solver.kernel import _ARG_ORDER

GROUPS = ["default", "edge", "batch"]


def _cluster(n=12, seed=0):
    return make_cluster(
        n, SynthNodeSpec(phys_cores=8, gpus_per_numa=1, nics_per_numa=1,
                         hugepages_gb=32),
        groups=GROUPS, seed=seed,
    )


def _assert_parity(delta, where):
    errs = delta.parity_errors()
    assert not errs, f"{where}: {errs}"


def _spec(i, **kw):
    kw.setdefault("phys_cores", 8)
    kw.setdefault("gpus_per_numa", 1)
    kw.setdefault("nics_per_numa", 1)
    kw.setdefault("hugepages_gb", 32)
    return SynthNodeSpec(name=f"fresh{i}", **kw)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_delta_parity_random_event_stream(seed):
    """Seeded event streams — claim/release-style mutations, cordon /
    maintenance / group flips, busy stamps, structural adds/removes, and
    FORCED fallback events — folded through the delta path; the arrays
    must be bit-exact with a from-scratch encode after every batch.

    The stream mutates HostNodes directly (claims through the solver are
    pinned separately below — parity is about host-state folding, and a
    solver dispatch per random shape would spend the tier-1 budget on
    XLA compiles, not on the property)."""
    rng = random.Random(seed)
    nodes = _cluster(10, seed=seed)
    delta = ClusterDelta(nodes, now=0.0, respect_busy=True)
    fresh_seq = 0
    now = 0.0

    for batch_no in range(40):
        now += 1.0
        for _ in range(rng.randint(1, 6)):
            ev = rng.random()
            name = rng.choice(list(nodes))
            node = nodes[name]
            if ev < 0.20:
                # claim-shaped mutation: burn a GPU + cores + pages,
                # stamp busy (what an applied assignment does)
                for gpu in node.gpus:
                    if not gpu.used:
                        gpu.used = True
                        break
                for core in node.cores:
                    if not core.used:
                        core.used = True
                        break
                node.mem.free_hugepages_gb = max(
                    node.mem.free_hugepages_gb - 2, 0
                )
                node.set_busy(now)
                delta.note(name)
            elif ev < 0.35:
                # release-shaped mutation
                for gpu in node.gpus:
                    if gpu.used:
                        gpu.used = False
                        break
                for core in node.cores:
                    if core.used:
                        core.used = False
                        break
                node.mem.free_hugepages_gb += 1
                node.set_busy(now)
                delta.note(name)
            elif ev < 0.50:
                node.active = not node.active
                delta.note(name)
            elif ev < 0.60:
                node.maintenance = not node.maintenance
                delta.note(name)
            elif ev < 0.72:
                node.set_groups(rng.choice(GROUPS))
                delta.note(name)
            elif ev < 0.80:
                # structural add within known dims
                fresh_seq += 1
                spec = _spec(fresh_seq)
                nodes[spec.name] = make_node(spec)
                delta.note(spec.name)
            elif ev < 0.90 and len(nodes) > 4:
                victim = rng.choice(list(nodes))
                del nodes[victim]
                delta.note(victim)
            elif ev < 0.96:
                # FORCED fallback: new group bit (uninterned name)
                node.set_groups(f"novel{batch_no}")
                delta.note(name)
            else:
                # FORCED fallback: padding overflow (more NUMA nodes /
                # NICs than the current U/K can hold)
                fresh_seq += 1
                spec = _spec(fresh_seq, sockets=4, nics_per_numa=3)
                nodes[spec.name] = make_node(spec)
                delta.note(spec.name)

        delta.refresh(now)
        _assert_parity(delta, f"seed {seed} batch {batch_no}")
        delta.drain_dirty()


def test_delta_parity_through_scheduled_batches():
    """Claims applied by the SOLVER (FastCluster maintaining the packed
    arrays in place) keep parity too — the fixed-membership pin, one
    compiled shape family."""
    nodes = _cluster(8)
    delta = ClusterDelta(nodes, now=0.0, respect_busy=True)
    sched = BatchScheduler(respect_busy=True, register_pods=True)
    ctx = sched.make_context(nodes, now=0.0, delta=delta)
    catalog = workload_mix(8, GROUPS)
    placed = []
    for batch_no in range(3):
        now = float(batch_no)
        sched.refresh_context(ctx, now=now)
        creates = [
            BatchItem(("t", f"p{batch_no}-{i}"), catalog[i],
                      topology=request_to_topology(catalog[i]))
            for i in range(4)
        ]
        results, _ = sched.schedule(ctx.nodes, creates, context=ctx)
        for item, r in zip(creates, results):
            if r.node is not None:
                placed.append((item.key, r.node, item.topology))
        _assert_parity(delta, f"batch {batch_no} post-solve")
        # release one placed pod between batches (the event path)
        if placed:
            key, node_name, top = placed.pop()
            node = ctx.nodes[node_name]
            node.release_from_topology(top)
            node.remove_scheduled_pod(key[1], key[0])
            node.set_busy(now)
            delta.note(node_name)
            delta.refresh(now + 0.5)
            _assert_parity(delta, f"batch {batch_no} post-release")


def test_delta_device_rows_bit_exact(monkeypatch):
    """With device-resident state on, the scattered device rows must be
    bit-exact with the host arrays after every refresh — including rows
    appended into padded-capacity slots."""
    monkeypatch.setenv("NHD_TPU_DEVICE_STATE", "1")
    rng = random.Random(3)
    nodes = _cluster(6)
    delta = ClusterDelta(nodes, now=0.0, respect_busy=False)
    sched = BatchScheduler(respect_busy=False, register_pods=False)
    ctx = sched.make_context(nodes, now=0.0, delta=delta)
    assert ctx.dev is not None
    catalog = workload_mix(16, GROUPS)

    def check_device():
        for arg in _ARG_ORDER:
            dev_rows = np.asarray(ctx.dev._dev[arg])[: delta.n_rows]
            host = getattr(ctx.cluster, arg)
            assert np.array_equal(dev_rows, host), f"{arg} diverged"

    for step in range(4):
        name = rng.choice(list(nodes))
        nodes[name].active = not nodes[name].active
        delta.note(name)
        if step == 2 and len(nodes) < delta.capacity:
            # padded-slot append must reach the device as a row scatter
            spec = _spec(100 + step)
            nodes[spec.name] = make_node(spec)
            delta.note(spec.name)
        sched.refresh_context(ctx, now=float(step))
        check_device()
        if step % 2 == 0:
            items = [
                BatchItem(("d", f"q{step}-{i}"), catalog[i])
                for i in range(3)
            ]
            sched.schedule(ctx.nodes, items, context=ctx)
            # claims stage rows; flush and compare the resident arrays
            sched.refresh_context(ctx, now=float(step) + 0.5)
            ctx.dev._flush_staged()
            check_device()
        assert not delta.parity_errors()


def test_forced_fallbacks_rebuild_with_reason():
    """Each fallback trigger rebuilds (never diverges) and records its
    bounded-vocabulary reason."""
    reset_delta_metrics()
    nodes = _cluster(6)
    delta = ClusterDelta(nodes, now=0.0, respect_busy=False)
    base = delta.rebuilds

    # new group bit
    name = list(nodes)[0]
    nodes[name].set_groups("brand-new-group")
    delta.note(name)
    delta.refresh(1.0)
    assert delta.rebuilds == base + 1
    assert not delta.parity_errors()

    # dims overflow (a node with more NUMA nodes than U)
    big = make_node(SynthNodeSpec(name="big", sockets=4, phys_cores=16,
                                  gpus_per_numa=1, nics_per_numa=3,
                                  hugepages_gb=32))
    nodes["big"] = big
    delta.note("big")
    delta.refresh(2.0)
    assert delta.rebuilds == base + 2
    assert not delta.parity_errors()

    # tombstone re-add: remove, flush, then re-add the same name
    del nodes["big"]
    delta.note("big")
    delta.refresh(3.0)
    assert delta.rebuilds == base + 2  # a remove is a patch, not a rebuild
    nodes["big"] = make_node(SynthNodeSpec(
        name="big", sockets=4, phys_cores=16, gpus_per_numa=1,
        nics_per_numa=3, hugepages_gb=32,
    ))
    delta.note("big")
    delta.refresh(4.0)
    assert delta.rebuilds == base + 3
    assert not delta.parity_errors()

    # capacity overflow: append past the power-of-two bucket (each
    # rebuild doubles the bucket, so gate on the recorded reason)
    cap_before = rebuild_reasons_snapshot().get("capacity", 0)
    added = 0
    while rebuild_reasons_snapshot().get("capacity", 0) == cap_before:
        added += 1
        assert added <= delta.capacity + 2, "capacity fallback never fired"
        spec = _spec(1000 + added)
        nodes[spec.name] = make_node(spec)
        delta.note(spec.name)
        delta.refresh(5.0 + added)
        assert not delta.parity_errors()
    assert delta.rebuilds > base + 3

    # generation change (label reparse rebuilds packed topology)
    name = list(nodes)[1]
    nodes[name].parse_labels(make_node_labels(SynthNodeSpec(
        name=name, phys_cores=8, gpus_per_numa=1, nics_per_numa=1,
        hugepages_gb=32,
    )))
    nodes[name].set_hugepages(32, 32)
    delta.note(name)
    pre = delta.rebuilds
    delta.refresh(20.0)
    assert delta.rebuilds == pre + 1
    assert not delta.parity_errors()

    reasons = rebuild_reasons_snapshot()
    for expected in ("new-group", "dims-overflow", "tombstone-readd",
                     "capacity", "generation"):
        assert reasons.get(expected, 0) >= 1, (expected, reasons)


def test_compaction_reclaims_tombstones():
    reset_delta_metrics()
    nodes = _cluster(12)
    delta = ClusterDelta(nodes, now=0.0, respect_busy=False)
    # remove enough nodes to cross the tombstone threshold
    victims = list(nodes)[:5]
    for v in victims:
        del nodes[v]
        delta.note(v)
    delta.refresh(1.0)
    assert rebuild_reasons_snapshot().get("compaction", 0) >= 1
    assert delta.n_rows == len(nodes)  # compacted: no tombstones left
    assert not delta.parity_errors()


def test_dirty_rows_are_exactly_the_changed_rows():
    nodes = _cluster(8)
    delta = ClusterDelta(nodes, now=0.0, respect_busy=False)
    delta.drain_dirty()
    names = list(nodes)
    nodes[names[2]].active = False
    nodes[names[5]].maintenance = True
    delta.note(names[2])
    delta.note(names[5])
    delta.refresh(1.0)
    assert delta.drain_dirty().tolist() == [2, 5]
    # a second drain is empty (no new changes)
    assert delta.drain_dirty().size == 0


def test_snapshot_matches_plain_encode_bit_for_bit():
    nodes = _cluster(9)
    delta = ClusterDelta(nodes, now=0.0, respect_busy=False)
    snap = delta.snapshot()
    ref = encode_cluster(nodes, now=0.0, interner=delta.interner,
                         dims=delta.dims)
    ref.busy[:] = False
    assert snap.names == ref.names
    from nhd_tpu.solver.encode import DELTA_FIELDS

    for f in DELTA_FIELDS:
        assert np.array_equal(getattr(snap, f), getattr(ref, f)), f
