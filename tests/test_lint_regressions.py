"""Regression tests for the real defects the nhdlint rule packs surfaced
(docs/STATIC_ANALYSIS.md "findings fixed in this PR"):

* GcPin.release published ``active = False`` outside the acquire lock
  (solver/batch.py, NHD201) — a racing acquire could freeze/disable gc
  while the releasing thread was still unfreezing;
* KubeClusterBackend registered Watch objects from watch threads with no
  lock, and a watcher registering after stop_watches() swept the list was
  never stopped (leaked stream); a watcher whose stop() raised aborted
  the sweep for every later watcher (k8s/kube.py, NHD201/NHD302);
* MetricsServer.stop() raced start(): the plain-bool handshake could skip
  shutdown() and leave the serve loop running forever (rpc/metrics.py);
* Scheduler.last_heartbeat was written by the loop thread AND the
  commitpipe worker (the ``heartbeat=`` ctor callback) with no common
  lock (scheduler/core.py, NHD811 via the races pack) — an interleaved
  stale store could roll the watchdog's liveness clock backwards; now
  every ``_beat()`` write holds ``_hb_lock``.
"""

from __future__ import annotations

import gc
import queue
import threading

import pytest

from nhd_tpu.solver.batch import GcPin


# ---------------------------------------------------------------------------
# GcPin
# ---------------------------------------------------------------------------

def test_gcpin_concurrent_acquire_release_leaves_gc_consistent():
    """Hammer acquire/release from many threads: afterwards the pin must
    be free, gc enabled, and a fresh acquire must succeed."""
    assert gc.isenabled(), "test precondition"
    errors = []

    def worker():
        try:
            for _ in range(200):
                token = GcPin.acquire()
                GcPin.release(token)
        except Exception as exc:  # pragma: no cover - failure path
            errors.append(exc)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    assert not GcPin.active
    assert gc.isenabled()
    token = GcPin.acquire()
    try:
        assert token is not None
    finally:
        GcPin.release(token)
    assert gc.isenabled()


# ---------------------------------------------------------------------------
# KubeClusterBackend watcher registration
# ---------------------------------------------------------------------------

class _FakeWatcher:
    def __init__(self, raise_on_stop: bool = False):
        self.stopped = False
        self.raise_on_stop = raise_on_stop

    def stop(self):
        self.stopped = True
        if self.raise_on_stop:
            raise RuntimeError("boom")


def _bare_backend():
    """A KubeClusterBackend with only the watch-plane attributes — the
    constructor needs a live API server, which these tests don't."""
    from nhd_tpu.k8s.kube import KubeClusterBackend
    from nhd_tpu.utils import get_logger

    be = KubeClusterBackend.__new__(KubeClusterBackend)
    be.logger = get_logger("test-kube-watch")
    be._watch_lock = threading.Lock()
    be._watchers = []
    be._watch_stop = threading.Event()
    return be


def test_watcher_registered_after_stop_is_stopped_immediately():
    be = _bare_backend()
    be.stop_watches()
    late = _FakeWatcher()
    be._register_watcher(late)
    assert late.stopped, (
        "a watcher registering after stop_watches' sweep must be stopped "
        "at registration, not leaked"
    )


def test_stop_watches_survives_a_raising_watcher():
    be = _bare_backend()
    first = _FakeWatcher(raise_on_stop=True)
    second = _FakeWatcher()
    be._register_watcher(first)
    be._register_watcher(second)
    be.stop_watches()
    assert first.stopped and second.stopped, (
        "one watcher's stop() raising must not abort the sweep"
    )


def test_concurrent_registration_and_stop_is_safe():
    be = _bare_backend()
    watchers = [_FakeWatcher() for _ in range(64)]
    start = threading.Barrier(3)

    def register(chunk):
        start.wait()
        for w in chunk:
            be._register_watcher(w)

    t1 = threading.Thread(target=register, args=(watchers[:32],))
    t2 = threading.Thread(target=register, args=(watchers[32:],))
    t1.start()
    t2.start()
    start.wait()
    be.stop_watches()
    t1.join(timeout=10)
    t2.join(timeout=10)
    # every watcher is stopped regardless of which side of the sweep's
    # snapshot it registered on
    assert all(w.stopped for w in watchers)


# ---------------------------------------------------------------------------
# MetricsServer stop/start race
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("attempt", range(5))
def test_metrics_stop_immediately_after_start(attempt):
    from nhd_tpu.rpc.metrics import MetricsServer

    server = MetricsServer(queue.Queue(), port=0)
    server.start()
    server.stop()   # may land before run() reaches serve_forever
    server.join(timeout=5)
    assert not server.is_alive(), (
        "stop() racing start() must still shut the serve loop down"
    )


def test_metrics_stop_without_start_releases_port():
    from nhd_tpu.rpc.metrics import MetricsServer

    server = MetricsServer(queue.Queue(), port=0)
    port = server.port
    server.stop()   # never started: must not hang in shutdown()
    # port is free again: a new server can bind it
    server2 = MetricsServer(queue.Queue(), port=port)
    server2.stop()


def test_metrics_stop_idempotent_under_concurrency():
    from nhd_tpu.rpc.metrics import MetricsServer

    server = MetricsServer(queue.Queue(), port=0)
    server.start()
    threads = [threading.Thread(target=server.stop) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5)
    server.join(timeout=5)
    assert not server.is_alive()


# ---------------------------------------------------------------------------
# Scheduler heartbeat: loop thread vs commitpipe worker (NHD811)
# ---------------------------------------------------------------------------

def _bare_scheduler():
    """A Scheduler with only the heartbeat plane — the constructor wants
    a backend; _beat() only needs the lock and the field."""
    import time as _time

    from nhd_tpu.scheduler.core import Scheduler

    sched = Scheduler.__new__(Scheduler)
    sched.last_heartbeat = _time.monotonic()
    sched._hb_lock = threading.Lock()
    return sched


def test_heartbeat_concurrent_beats_run_race_free():
    """The fixed shape under the dynamic detector: two threads driving
    _beat() — the loop thread and the commitpipe worker's per-drain
    callback — produce ZERO race witnesses because every write holds
    _hb_lock. Uses a private sanitizer pair so the check also runs (and
    stays meaningful) outside NHD_RACE=1 sessions."""
    from nhd_tpu.sanitizer import RaceSanitizer, Sanitizer

    san = Sanitizer(poll_interval=0.01)
    rs = RaceSanitizer(san)
    sched = _bare_scheduler()
    # the lock must be one of THIS sanitizer's instrumented locks, or
    # held_snapshot can't see it in the writers' locksets
    sched._hb_lock = san.Lock()
    rs.watch(sched, ("last_heartbeat",))
    gate = threading.Barrier(2)

    def hammer():
        gate.wait(timeout=10)
        for _ in range(200):
            sched._beat()

    try:
        threads = [
            threading.Thread(target=hammer, name=f"hb-{i}") for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        rs.unpatch_all()
    rep = rs.report()
    assert rep["races"] == [] and rep["suppressed"] == []
    assert "scheduler/core:Scheduler.last_heartbeat" in rep["watched_fields"]


def test_heartbeat_prefix_shape_would_be_caught():
    """Counterfactual pin: the PRE-fix shape (raw unlocked stores to
    last_heartbeat from two threads) trips the detector — proof this
    regression test would fail if the lock were ever removed."""
    from nhd_tpu.sanitizer import RaceSanitizer, Sanitizer, field_key
    from nhd_tpu.scheduler.core import Scheduler

    san = Sanitizer(poll_interval=0.01)
    rs = RaceSanitizer(san)
    sched = _bare_scheduler()
    rs.watch(sched, ("last_heartbeat",))
    gate = threading.Barrier(2)

    def raw_beat():     # what _beat() was before _hb_lock
        import time as _time

        gate.wait(timeout=10)
        for _ in range(200):
            sched.last_heartbeat = _time.monotonic()

    try:
        threads = [threading.Thread(target=raw_beat) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        rs.unpatch_all()
    rep = rs.report()
    assert [r["key"] for r in rep["races"]] == [
        field_key(Scheduler, "last_heartbeat")
    ]
