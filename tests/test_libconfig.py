"""libconfig reader/writer tests."""

import pytest

from nhd_tpu.config import libconfig
from nhd_tpu.config.libconfig import ConfigDict, ConfigError


def test_scalars():
    cfg = libconfig.loads(
        """
        a = 1;
        b = -2;
        c = 3.5;
        d = true;
        e = false;
        f = "hello world";
        g = 0x1A;
        h = 10L;
        i = 1e3;
        """
    )
    assert cfg.a == 1
    assert cfg.b == -2
    assert cfg.c == 3.5
    assert cfg.d is True
    assert cfg.e is False
    assert cfg.f == "hello world"
    assert cfg.g == 26
    assert cfg.h == 10
    assert cfg.i == 1000.0


def test_colon_assignment_and_comma_terminator():
    cfg = libconfig.loads("grp : { x = 1, y = 2 };")
    assert cfg.grp.x == 1 and cfg.grp.y == 2


def test_group_list_array_types():
    cfg = libconfig.loads(
        """
        grp = { inner = { v = 7; }; };
        lst = ( 1, "two", { three = 3; } );
        arr = [ 1, 2, 3 ];
        empty_lst = ( );
        empty_arr = [ ];
        """
    )
    assert isinstance(cfg.grp, ConfigDict)
    assert cfg.grp.inner.v == 7
    assert isinstance(cfg.lst, tuple)
    assert cfg.lst[0] == 1 and cfg.lst[1] == "two" and cfg.lst[2].three == 3
    assert isinstance(cfg.arr, list) and cfg.arr == [1, 2, 3]
    assert cfg.empty_lst == ()
    assert cfg.empty_arr == []


def test_comments_and_string_concat():
    cfg = libconfig.loads(
        """
        // line comment
        # hash comment
        /* block
           comment */
        s = "ab" "cd";
        t = "esc\\n\\"q\\"";
        """
    )
    assert cfg.s == "abcd"
    assert cfg.t == 'esc\n"q"'


def test_nested_tuples():
    cfg = libconfig.loads("gpu_map = ( ( -1, 0 ), ( -1, 1 ) );")
    assert cfg.gpu_map == ((-1, 0), (-1, 1))


def test_roundtrip():
    src = """
    TopologyCfg : {
      cpu_arch = "ANY";
      ext_cores = [ "CtrlCores[0]" ];
      nested = ( { a = 1; b = [ 1, 2 ]; }, 2.5, "x" );
    };
    Hugepages_GB = 16;
    flag = true;
    """
    cfg = libconfig.loads(src)
    text = libconfig.dumps(cfg)
    cfg2 = libconfig.loads(text)
    assert cfg == cfg2
    # a second round trip is byte-stable
    assert libconfig.dumps(cfg2) == text


def test_attribute_write():
    cfg = libconfig.loads("a = { b = 1; };")
    cfg.a.b = 5
    assert cfg["a"]["b"] == 5


def test_errors():
    with pytest.raises(ConfigError):
        libconfig.loads("a = ;")
    with pytest.raises(ConfigError):
        libconfig.loads("a = { b = 1;")
    with pytest.raises(ConfigError):
        libconfig.loads("= 3;")
