"""Chaos soak: randomized churn must never violate conservation invariants."""

import pytest

from nhd_tpu.sim.chaos import ChaosSim


@pytest.mark.parametrize("seed", range(4))
def test_chaos_soak(seed):
    sim = ChaosSim(seed=seed, n_nodes=4)
    stats = sim.run(steps=60)
    assert stats.violations == []
    # the storm actually exercised the lifecycle
    assert stats.created > 10
    assert stats.deleted + stats.cordons + stats.maint_flips > 5


def test_chaos_with_restarts_replays_consistently():
    sim = ChaosSim(seed=99, n_nodes=3)
    stats = sim.run(steps=80)
    assert stats.violations == []
    assert stats.restarts >= 1
