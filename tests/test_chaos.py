"""Chaos soak: randomized churn must never violate conservation invariants."""

import pytest

from nhd_tpu.sim.chaos import ChaosSim


@pytest.mark.parametrize("seed", range(4))
def test_chaos_soak(seed):
    sim = ChaosSim(seed=seed, n_nodes=4)
    stats = sim.run(steps=60)
    assert stats.violations == []
    # the storm actually exercised the lifecycle
    assert stats.created > 10
    assert stats.deleted + stats.cordons + stats.maint_flips > 5


def test_chaos_with_restarts_replays_consistently():
    sim = ChaosSim(seed=99, n_nodes=3)
    stats = sim.run(steps=80)
    assert stats.violations == []
    assert stats.restarts >= 1


def test_chaos_through_speculative_device_path(monkeypatch):
    """The same churn storm with the resident-device-state AND the
    speculative on-device multi-round forced on (the accelerator
    production path, driven on CPU): every conservation invariant must
    hold — speculative claims are natively re-verified, so chaos-driven
    drift/rollback must behave exactly like the classic rounds."""
    monkeypatch.setenv("NHD_TPU_DEVICE_STATE", "1")
    monkeypatch.setenv("NHD_TPU_SPECULATE", "1")
    monkeypatch.setenv("NHD_TPU_SPEC_ITERS", "6")
    sim = ChaosSim(seed=13, n_nodes=4)
    stats = sim.run(steps=60)
    assert stats.violations == []
    assert stats.created > 10


def test_chaos_through_streaming_scheduler_path(monkeypatch):
    """Same churn storm with every scheduler batch routed through the
    streaming tiler (NHD_STREAM_NODES forced to 1) — the federation-scale
    production path must satisfy the same conservation invariants."""
    from nhd_tpu.scheduler import core as core_mod

    monkeypatch.setattr(core_mod, "STREAM_NODE_THRESH", 1)
    sim = ChaosSim(seed=7, n_nodes=4)
    stats = sim.run(steps=60)
    assert stats.violations == []
    assert sim.sched._stream is not None, "streaming path never engaged"
    assert stats.created > 10


def test_chaos_churn_with_mesh_resident_path(monkeypatch):
    """ISSUE 11: the `churn` fault profile (heavy drop/poison/transient
    commits + structural node flaps) with the MESH-sharded resident path
    active — the ClusterDelta.parity_errors invariant runs every step
    while per-shard delta scatters maintain the sharded device arrays,
    so a scatter that diverges from the host mirror fails the storm."""
    from nhd_tpu.sim.faults import PROFILES

    monkeypatch.setenv("NHD_TPU_DEVICE_STATE", "1")
    sim = ChaosSim(seed=17, n_nodes=4, api_faults=PROFILES["churn"])
    stats = sim.run(steps=50)
    assert stats.violations == []
    assert stats.created > 10
    # the mesh path actually engaged (conftest's 8 virtual devices)
    ctx = sim.sched._delta_ctx
    assert ctx is not None and ctx.dev is not None
    assert ctx.dev.mesh is not None, "mesh resident path never engaged"


def test_chaos_churn_mesh_negative_control(monkeypatch):
    """Negative control: injected divergence between the delta's packed
    arrays and the live mirror must FIRE the parity invariant under the
    mesh cell — proves the green run above is not vacuous."""
    from nhd_tpu.sim.faults import PROFILES

    monkeypatch.setenv("NHD_TPU_DEVICE_STATE", "1")
    sim = ChaosSim(seed=18, n_nodes=4, api_faults=PROFILES["churn"])
    sim.run(steps=12)
    assert sim.stats.violations == []
    delta = sim.sched._delta
    assert delta is not None
    delta.arrays.hp_free[0] += 7  # corrupt one packed row behind its back
    sim.check_invariants()
    assert any("parity" in v for v in sim.stats.violations), (
        sim.stats.violations
    )


def test_chaos_through_routed_streaming(monkeypatch):
    """The routed (capacity-partitioned, concurrent-tile) streaming path
    must satisfy the same conservation invariants under churn."""
    from nhd_tpu.scheduler import core as core_mod

    monkeypatch.setattr(core_mod, "STREAM_NODE_THRESH", 1)
    monkeypatch.setattr(core_mod, "STREAM_PLACEMENT", "routed")
    sim = ChaosSim(seed=21, n_nodes=4)
    stats = sim.run(steps=60)
    assert stats.violations == []
    assert sim.sched._stream is not None, "streaming path never engaged"
    assert sim.sched._stream.placement == "routed"
    assert stats.created > 10
