"""Config-path get/set tests (the magicattr-equivalent indirection layer)."""

import pytest

from nhd_tpu.config import libconfig
from nhd_tpu.config.paths import PathError, path_get, path_set

SRC = """
mods = (
  { module = "m0";
    dp = ( { rx_cores = [ -1, -1 ]; gpu_map = ( ( -1, 0 ) ); } );
  }
);
CtrlCores = [ -1, -1 ];
KniVlan = 0;
"""


def test_get():
    cfg = libconfig.loads(SRC)
    assert path_get(cfg, "KniVlan") == 0
    assert path_get(cfg, "CtrlCores[1]") == -1
    assert path_get(cfg, "mods[0].module") == "m0"
    assert path_get(cfg, "mods[0].dp[0].rx_cores[1]") == -1
    assert path_get(cfg, "mods[0].dp[0].gpu_map[0][1]") == 0


def test_set_scalar_and_array():
    cfg = libconfig.loads(SRC)
    path_set(cfg, "KniVlan", 42)
    path_set(cfg, "CtrlCores[0]", 7)
    assert cfg.KniVlan == 42
    assert cfg.CtrlCores == [7, -1]


def test_set_inside_tuple_rebuilds():
    cfg = libconfig.loads(SRC)
    path_set(cfg, "mods[0].dp[0].rx_cores[0]", 9)
    assert path_get(cfg, "mods[0].dp[0].rx_cores[0]") == 9
    # sibling values untouched
    assert path_get(cfg, "mods[0].dp[0].rx_cores[1]") == -1
    assert path_get(cfg, "mods[0].module") == "m0"


def test_set_nested_tuple_element():
    cfg = libconfig.loads(SRC)
    path_set(cfg, "mods[0].dp[0].gpu_map[0][0]", 3)
    assert path_get(cfg, "mods[0].dp[0].gpu_map[0]") == (3, 0)


def test_set_whole_key():
    cfg = libconfig.loads(SRC)
    path_set(cfg, "Network_Config", ({"mac": "AA"},))
    assert cfg.Network_Config[0]["mac"] == "AA"


def test_errors():
    cfg = libconfig.loads(SRC)
    with pytest.raises(PathError):
        path_get(cfg, "nope.deeper")
    with pytest.raises(PathError):
        path_get(cfg, "CtrlCores[9]")
