"""Flight-recorder tests: ring semantics, Chrome trace export (golden),
cross-thread recording, and the end-to-end correlation-ID pipeline
(watch-event receipt → queue → solve/select/assign → bind)."""

import json
import threading
from pathlib import Path

import pytest

import nhd_tpu.obs as obs
from nhd_tpu.obs import (
    FlightRecorder,
    Span,
    chrome_trace_of,
    correlate,
    validate_chrome_trace,
)
from nhd_tpu.scheduler.controller import Controller
from nhd_tpu.utils.logging import JsonFormatter
from tests.test_scheduler import make_backend, make_scheduler, pod_cfg

GOLDEN = Path(__file__).resolve().parent / "fixtures" / "obs"


@pytest.fixture
def recorder():
    rec = obs.enable(capacity=4096)
    yield rec
    obs.disable()


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

def test_ring_bounds_and_drop_accounting():
    rec = FlightRecorder(capacity=8, decision_capacity=4)
    for i in range(20):
        rec.record(f"s{i}", float(i), 0.5)
    assert rec.occupancy() == 8
    assert rec.dropped() == 12
    names = [s.name for s in rec.spans()]
    assert names == [f"s{i}" for i in range(12, 20)]  # oldest evicted
    for i in range(6):
        rec.record_decision({"pod": f"p{i}", "outcome": "scheduled"})
    got = rec.recent_decisions(10)
    assert [d["pod"] for d in got] == ["p5", "p4", "p3", "p2"]  # newest first
    rec.clear()
    assert rec.occupancy() == 0 and rec.dropped() == 0


def test_span_context_manager_and_disabled_noop():
    obs.disable()
    with obs.span("never"):
        pass  # recorder off: must not raise, must not record anywhere
    rec = obs.enable(capacity=16)
    try:
        with correlate("c-test"):
            with obs.span("timed", cat="unit", attrs={"k": 1}):
                pass
        (s,) = rec.spans()
        assert s.name == "timed" and s.cat == "unit"
        assert s.corr == "c-test" and s.attrs == {"k": 1}
        assert s.dur >= 0.0
    finally:
        obs.disable()


def test_corr_ids_unique_and_context_bound():
    a, b = obs.new_corr_id(), obs.new_corr_id()
    assert a != b
    assert obs.current_corr_id() is None
    with correlate(a):
        assert obs.current_corr_id() == a
        with correlate(b):
            assert obs.current_corr_id() == b
        assert obs.current_corr_id() == a
    assert obs.current_corr_id() is None


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------

def _golden_spans():
    """A deterministic one-pod pipeline (exact binary-fraction durations,
    so the µs conversion is lossless across platforms)."""
    pod = {"pod": "default/triad-0"}
    return [
        Span("watch_event", 1.0, 0.0, cat="event", corr="c000001",
             thread="nhd-controller",
             attrs={"kind": "pod_create", "pod": "default/triad-0"}),
        Span("queue_wait", 1.0, 0.25, cat="pod", corr="c000001",
             thread="nhd-scheduler", attrs=pod),
        Span("batch", 1.25, 1.1875, cat="batch", corr="c000002",
             thread="nhd-scheduler", attrs={"pods": 1, "rounds": 1}),
        Span("solve", 1.25, 0.5, cat="pod", corr="c000001",
             thread="nhd-scheduler", attrs=pod),
        Span("select", 1.75, 0.125, cat="pod", corr="c000001",
             thread="nhd-scheduler", attrs=pod),
        Span("assign", 1.875, 0.0625, cat="pod", corr="c000001",
             thread="nhd-scheduler", attrs=pod),
        Span("bind", 1.9375, 0.5, cat="pod", corr="c000001",
             thread="nhd-scheduler",
             attrs={"pod": "default/triad-0", "node": "node0",
                    "outcome": "OK"}),
    ]


def test_chrome_trace_golden():
    """The serialized export is pinned byte-for-byte: viewers are lenient,
    diffs are not — any drift in event shape must be a conscious change
    (regenerate with `python tools/trace_demo.py --regen-golden`)."""
    got = json.dumps(
        chrome_trace_of(_golden_spans()), indent=2, sort_keys=True
    ) + "\n"
    golden = (GOLDEN / "golden_trace.json").read_text()
    assert got == golden


def test_chrome_trace_validates_and_orders():
    trace = chrome_trace_of(_golden_spans())
    assert validate_chrome_trace(trace) == []
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)
    # thread metadata rows exist for both producing threads
    meta = {e["args"]["name"] for e in trace["traceEvents"]
            if e["ph"] == "M"}
    assert meta == {"nhd-controller", "nhd-scheduler"}


def test_validator_rejects_malformed():
    assert validate_chrome_trace([]) != []
    assert validate_chrome_trace({"traceEvents": "nope"}) != []
    assert validate_chrome_trace(
        {"traceEvents": [{"ph": "X", "name": "a", "pid": 1, "tid": 1,
                          "ts": -5, "dur": 0}]}
    ) != []
    assert validate_chrome_trace(
        {"traceEvents": [{"ph": "Q", "name": "a", "pid": 1, "tid": 1}]}
    ) != []


# ---------------------------------------------------------------------------
# concurrency: spans from multiple threads never interleave corruptly
# ---------------------------------------------------------------------------

def test_concurrent_recording_is_uncorrupted():
    rec = FlightRecorder(capacity=1000)
    n_threads, per_thread = 4, 2000
    start = threading.Barrier(n_threads)

    def worker(tid: int):
        start.wait()
        for i in range(per_thread):
            rec.record(
                f"t{tid}", float(i), 0.001, cat="conc",
                corr=f"c-t{tid}-{i}", thread=f"worker-{tid}",
            )

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = rec.spans()
    assert len(spans) == 1000  # exactly capacity — no loss accounting drift
    assert rec.dropped() == n_threads * per_thread - 1000
    for s in spans:
        # every span is internally consistent: its corr names its own
        # producing thread and iteration (a torn write would mismatch)
        tid = s.name[1:]
        assert s.thread == f"worker-{tid}"
        assert s.corr.startswith(f"c-t{tid}-")
        assert s.cat == "conc" and s.dur == 0.001
    assert validate_chrome_trace(chrome_trace_of(spans)) == []


# ---------------------------------------------------------------------------
# end-to-end: the correlation ID threads watch receipt → bind
# ---------------------------------------------------------------------------

def _drain(sched):
    while not sched.nqueue.empty():
        sched.run_once()


def test_watch_to_bind_shares_one_corr_id(recorder):
    backend = make_backend(n_nodes=2)
    sched = make_scheduler(backend)
    ctrl = Controller(backend, sched.nqueue)
    backend.create_pod("triad-0", cfg_text=pod_cfg())  # emits watch event
    ctrl.run_once()
    _drain(sched)
    assert backend.pods[("default", "triad-0")].node is not None

    by_corr = {}
    for s in recorder.spans():
        by_corr.setdefault(s.corr, set()).add(s.name)
    pod_corrs = [
        corr for corr, names in by_corr.items()
        if {"watch_event", "queue_wait", "solve", "select", "assign",
            "bind"} <= names
    ]
    assert pod_corrs, f"no corr carries the full pipeline: {by_corr}"

    # the queue-wait histogram saw the event→admission gap
    from nhd_tpu.obs.histo import HISTOGRAMS

    assert HISTOGRAMS["queue_wait_seconds"].snapshot()[2] >= 1

    # decisions view: the pod is there, newest first, with phases
    (d,) = [d for d in recorder.recent_decisions(10)
            if d["pod"] == "triad-0"]
    assert d["outcome"] == "scheduled" and d["node"] is not None
    assert d["corr"] in pod_corrs
    assert {"solve", "select", "assign", "bind"} <= set(d["phases"])

    # and the whole ring exports a loadable trace
    assert validate_chrome_trace(obs.chrome_trace(recorder)) == []


def test_requeued_pod_keeps_its_corr_id(recorder):
    """A transient bind failure requeues the pod; the retry's spans and
    decision stay under the ORIGINAL correlation ID (one ID per pod
    across fault-recovery retries)."""
    from nhd_tpu.sim.faults import FaultProfile, FaultyBackend

    backend = make_backend(n_nodes=2)
    sched = make_scheduler(backend)
    ctrl = Controller(backend, sched.nqueue)
    faulty = FaultyBackend(
        backend, FaultProfile(name="t", transient_bind=1.0)
    )
    sched.backend = faulty  # scheduler commits through the fault shim
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    for _ in range(8):
        ctrl.run_once(now=0.0)
        _drain(sched)
    assert backend.pods[("default", "triad-0")].node is not None
    decisions = [d for d in recorder.recent_decisions(20)
                 if d["pod"] == "triad-0"]
    outcomes = {d["outcome"] for d in decisions}
    assert {"requeued", "scheduled"} <= outcomes
    assert len({d["corr"] for d in decisions}) == 1
    bind_corrs = {s.corr for s in recorder.spans() if s.name == "bind"}
    assert bind_corrs == {decisions[0]["corr"]}  # both attempts, one ID


def test_unschedulable_decision_carries_explain_reasons(recorder):
    backend = make_backend(n_nodes=2)
    sched = make_scheduler(backend)
    backend.create_pod(
        "greedy-0", cfg_text=pod_cfg(hugepages_gb=100000)
    )
    sched.check_pending_pods()
    (d,) = [d for d in recorder.recent_decisions(10)
            if d["pod"] == "greedy-0"]
    assert d["outcome"] == "unschedulable"
    assert d["reasons"].get("insufficient-hugepages") == 2


def test_chaos_run_with_tracing_produces_valid_trace(recorder):
    """Acceptance: a sim run with tracing enabled produces a Chrome trace
    that loads, with solve/select/assign/bind spans sharing one corr ID
    per pod."""
    from nhd_tpu.sim.chaos import ChaosSim

    sim = ChaosSim(seed=3, n_nodes=4)
    stats = sim.run(steps=15)
    assert stats.violations == []
    trace = obs.chrome_trace(recorder)
    assert validate_chrome_trace(trace) == []
    by_corr = {}
    for s in recorder.spans():
        by_corr.setdefault(s.corr, set()).add(s.name)
    assert any(
        {"solve", "select", "assign", "bind"} <= names
        for names in by_corr.values()
    ), "no pod corr carries solve/select/assign/bind"
    assert recorder.recent_decisions(5)


# ---------------------------------------------------------------------------
# JSON logging joins the trace via the corr id
# ---------------------------------------------------------------------------

def test_json_log_formatter_stamps_corr_id():
    import logging

    fmt = JsonFormatter()
    record = logging.LogRecord(
        "nhd.test", logging.WARNING, __file__, 1, "bind failed for %s",
        ("default/p0",), None,
    )
    with correlate("c-log-1"):
        line = fmt.format(record)
    out = json.loads(line)
    assert out["corr"] == "c-log-1"
    assert out["msg"] == "bind failed for default/p0"
    assert out["level"] == "WARNING" and out["logger"] == "nhd.test"
    # outside any correlate block the field is null, never absent
    out2 = json.loads(fmt.format(record))
    assert out2["corr"] is None


def test_json_log_formatter_env_switch(monkeypatch):
    from nhd_tpu.utils import logging as nhd_logging

    monkeypatch.setenv("NHD_LOG_JSON", "1")
    assert isinstance(nhd_logging._pick_formatter(), JsonFormatter)
    monkeypatch.delenv("NHD_LOG_JSON")
    assert not isinstance(nhd_logging._pick_formatter(), JsonFormatter)
