"""Flight-recorder tests: ring semantics, Chrome trace export (golden),
cross-thread recording, and the end-to-end correlation-ID pipeline
(watch-event receipt → queue → solve/select/assign → bind)."""

import json
import threading
from pathlib import Path

import pytest

import nhd_tpu.obs as obs
from nhd_tpu.obs import (
    FlightRecorder,
    Span,
    chrome_trace_of,
    correlate,
    validate_chrome_trace,
)
from nhd_tpu.scheduler.controller import Controller
from nhd_tpu.utils.logging import JsonFormatter
from tests.test_scheduler import make_backend, make_scheduler, pod_cfg

GOLDEN = Path(__file__).resolve().parent / "fixtures" / "obs"


@pytest.fixture
def recorder():
    rec = obs.enable(capacity=4096)
    yield rec
    obs.disable()


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

def test_ring_bounds_and_drop_accounting():
    rec = FlightRecorder(capacity=8, decision_capacity=4)
    for i in range(20):
        rec.record(f"s{i}", float(i), 0.5)
    assert rec.occupancy() == 8
    assert rec.dropped() == 12
    names = [s.name for s in rec.spans()]
    assert names == [f"s{i}" for i in range(12, 20)]  # oldest evicted
    for i in range(6):
        rec.record_decision({"pod": f"p{i}", "outcome": "scheduled"})
    got = rec.recent_decisions(10)
    assert [d["pod"] for d in got] == ["p5", "p4", "p3", "p2"]  # newest first
    rec.clear()
    assert rec.occupancy() == 0 and rec.dropped() == 0


def test_span_context_manager_and_disabled_noop():
    obs.disable()
    with obs.span("never"):
        pass  # recorder off: must not raise, must not record anywhere
    rec = obs.enable(capacity=16)
    try:
        with correlate("c-test"):
            with obs.span("timed", cat="unit", attrs={"k": 1}):
                pass
        (s,) = rec.spans()
        assert s.name == "timed" and s.cat == "unit"
        assert s.corr == "c-test" and s.attrs == {"k": 1}
        assert s.dur >= 0.0
    finally:
        obs.disable()


def test_corr_ids_unique_and_context_bound():
    a, b = obs.new_corr_id(), obs.new_corr_id()
    assert a != b
    assert obs.current_corr_id() is None
    with correlate(a):
        assert obs.current_corr_id() == a
        with correlate(b):
            assert obs.current_corr_id() == b
        assert obs.current_corr_id() == a
    assert obs.current_corr_id() is None


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------

def _golden_spans():
    """A deterministic one-pod pipeline (exact binary-fraction durations,
    so the µs conversion is lossless across platforms)."""
    pod = {"pod": "default/triad-0"}
    return [
        Span("watch_event", 1.0, 0.0, cat="event", corr="c000001",
             thread="nhd-controller",
             attrs={"kind": "pod_create", "pod": "default/triad-0"}),
        Span("queue_wait", 1.0, 0.25, cat="pod", corr="c000001",
             thread="nhd-scheduler", attrs=pod),
        Span("batch", 1.25, 1.1875, cat="batch", corr="c000002",
             thread="nhd-scheduler", attrs={"pods": 1, "rounds": 1}),
        Span("solve", 1.25, 0.5, cat="pod", corr="c000001",
             thread="nhd-scheduler", attrs=pod),
        Span("select", 1.75, 0.125, cat="pod", corr="c000001",
             thread="nhd-scheduler", attrs=pod),
        Span("assign", 1.875, 0.0625, cat="pod", corr="c000001",
             thread="nhd-scheduler", attrs=pod),
        Span("bind", 1.9375, 0.5, cat="pod", corr="c000001",
             thread="nhd-scheduler",
             attrs={"pod": "default/triad-0", "node": "node0",
                    "outcome": "OK"}),
    ]


def test_chrome_trace_golden():
    """The serialized export is pinned byte-for-byte: viewers are lenient,
    diffs are not — any drift in event shape must be a conscious change
    (regenerate with `python tools/trace_demo.py --regen-golden`)."""
    got = json.dumps(
        chrome_trace_of(_golden_spans()), indent=2, sort_keys=True
    ) + "\n"
    golden = (GOLDEN / "golden_trace.json").read_text()
    assert got == golden


def test_chrome_trace_validates_and_orders():
    trace = chrome_trace_of(_golden_spans())
    assert validate_chrome_trace(trace) == []
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert [e["ts"] for e in xs] == sorted(e["ts"] for e in xs)
    # thread metadata rows exist for both producing threads
    meta = {e["args"]["name"] for e in trace["traceEvents"]
            if e["ph"] == "M"}
    assert meta == {"nhd-controller", "nhd-scheduler"}


def test_validator_rejects_malformed():
    assert validate_chrome_trace([]) != []
    assert validate_chrome_trace({"traceEvents": "nope"}) != []
    assert validate_chrome_trace(
        {"traceEvents": [{"ph": "X", "name": "a", "pid": 1, "tid": 1,
                          "ts": -5, "dur": 0}]}
    ) != []
    assert validate_chrome_trace(
        {"traceEvents": [{"ph": "Q", "name": "a", "pid": 1, "tid": 1}]}
    ) != []


# ---------------------------------------------------------------------------
# concurrency: spans from multiple threads never interleave corruptly
# ---------------------------------------------------------------------------

def test_concurrent_recording_is_uncorrupted():
    rec = FlightRecorder(capacity=1000)
    n_threads, per_thread = 4, 2000
    start = threading.Barrier(n_threads)

    def worker(tid: int):
        start.wait()
        for i in range(per_thread):
            rec.record(
                f"t{tid}", float(i), 0.001, cat="conc",
                corr=f"c-t{tid}-{i}", thread=f"worker-{tid}",
            )

    threads = [
        threading.Thread(target=worker, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = rec.spans()
    assert len(spans) == 1000  # exactly capacity — no loss accounting drift
    assert rec.dropped() == n_threads * per_thread - 1000
    for s in spans:
        # every span is internally consistent: its corr names its own
        # producing thread and iteration (a torn write would mismatch)
        tid = s.name[1:]
        assert s.thread == f"worker-{tid}"
        assert s.corr.startswith(f"c-t{tid}-")
        assert s.cat == "conc" and s.dur == 0.001
    assert validate_chrome_trace(chrome_trace_of(spans)) == []


# ---------------------------------------------------------------------------
# end-to-end: the correlation ID threads watch receipt → bind
# ---------------------------------------------------------------------------

def _drain(sched):
    while not sched.nqueue.empty():
        sched.run_once()


def test_watch_to_bind_shares_one_corr_id(recorder):
    backend = make_backend(n_nodes=2)
    sched = make_scheduler(backend)
    ctrl = Controller(backend, sched.nqueue)
    backend.create_pod("triad-0", cfg_text=pod_cfg())  # emits watch event
    ctrl.run_once()
    _drain(sched)
    assert backend.pods[("default", "triad-0")].node is not None

    by_corr = {}
    for s in recorder.spans():
        by_corr.setdefault(s.corr, set()).add(s.name)
    pod_corrs = [
        corr for corr, names in by_corr.items()
        if {"watch_event", "queue_wait", "solve", "select", "assign",
            "bind"} <= names
    ]
    assert pod_corrs, f"no corr carries the full pipeline: {by_corr}"

    # the queue-wait histogram saw the event→admission gap
    from nhd_tpu.obs.histo import HISTOGRAMS

    assert HISTOGRAMS["queue_wait_seconds"].snapshot()[2] >= 1

    # decisions view: the pod is there, newest first, with phases
    (d,) = [d for d in recorder.recent_decisions(10)
            if d["pod"] == "triad-0"]
    assert d["outcome"] == "scheduled" and d["node"] is not None
    assert d["corr"] in pod_corrs
    assert {"solve", "select", "assign", "bind"} <= set(d["phases"])

    # and the whole ring exports a loadable trace
    assert validate_chrome_trace(obs.chrome_trace(recorder)) == []


def test_requeued_pod_keeps_its_corr_id(recorder):
    """A transient bind failure requeues the pod; the retry's spans and
    decision stay under the ORIGINAL correlation ID (one ID per pod
    across fault-recovery retries)."""
    from nhd_tpu.sim.faults import FaultProfile, FaultyBackend

    backend = make_backend(n_nodes=2)
    sched = make_scheduler(backend)
    ctrl = Controller(backend, sched.nqueue)
    faulty = FaultyBackend(
        backend, FaultProfile(name="t", transient_bind=1.0)
    )
    sched.backend = faulty  # scheduler commits through the fault shim
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    for _ in range(8):
        ctrl.run_once(now=0.0)
        _drain(sched)
    assert backend.pods[("default", "triad-0")].node is not None
    decisions = [d for d in recorder.recent_decisions(20)
                 if d["pod"] == "triad-0"]
    outcomes = {d["outcome"] for d in decisions}
    assert {"requeued", "scheduled"} <= outcomes
    assert len({d["corr"] for d in decisions}) == 1
    bind_corrs = {s.corr for s in recorder.spans() if s.name == "bind"}
    assert bind_corrs == {decisions[0]["corr"]}  # both attempts, one ID


def test_unschedulable_decision_carries_explain_reasons(recorder):
    backend = make_backend(n_nodes=2)
    sched = make_scheduler(backend)
    backend.create_pod(
        "greedy-0", cfg_text=pod_cfg(hugepages_gb=100000)
    )
    sched.check_pending_pods()
    (d,) = [d for d in recorder.recent_decisions(10)
            if d["pod"] == "greedy-0"]
    assert d["outcome"] == "unschedulable"
    assert d["reasons"].get("insufficient-hugepages") == 2


def test_chaos_run_with_tracing_produces_valid_trace(recorder):
    """Acceptance: a sim run with tracing enabled produces a Chrome trace
    that loads, with solve/select/assign/bind spans sharing one corr ID
    per pod."""
    from nhd_tpu.sim.chaos import ChaosSim

    sim = ChaosSim(seed=3, n_nodes=4)
    stats = sim.run(steps=15)
    assert stats.violations == []
    trace = obs.chrome_trace(recorder)
    assert validate_chrome_trace(trace) == []
    by_corr = {}
    for s in recorder.spans():
        by_corr.setdefault(s.corr, set()).add(s.name)
    assert any(
        {"solve", "select", "assign", "bind"} <= names
        for names in by_corr.values()
    ), "no pod corr carries solve/select/assign/bind"
    assert recorder.recent_decisions(5)


# ---------------------------------------------------------------------------
# JSON logging joins the trace via the corr id
# ---------------------------------------------------------------------------

def test_json_log_formatter_stamps_corr_id():
    import logging

    fmt = JsonFormatter()
    record = logging.LogRecord(
        "nhd.test", logging.WARNING, __file__, 1, "bind failed for %s",
        ("default/p0",), None,
    )
    with correlate("c-log-1"):
        line = fmt.format(record)
    out = json.loads(line)
    assert out["corr"] == "c-log-1"
    assert out["msg"] == "bind failed for default/p0"
    assert out["level"] == "WARNING" and out["logger"] == "nhd.test"
    # outside any correlate block the field is null, never absent
    out2 = json.loads(fmt.format(record))
    assert out2["corr"] is None


def test_json_log_formatter_env_switch(monkeypatch):
    from nhd_tpu.utils import logging as nhd_logging

    monkeypatch.setenv("NHD_LOG_JSON", "1")
    assert isinstance(nhd_logging._pick_formatter(), JsonFormatter)
    monkeypatch.delenv("NHD_LOG_JSON")
    assert not isinstance(nhd_logging._pick_formatter(), JsonFormatter)


# ---------------------------------------------------------------------------
# cross-replica journey merge + fleet observability units (ISSUE 7)
# ---------------------------------------------------------------------------

def _replica_ring(ident: str, epoch_offset: float) -> FlightRecorder:
    rec = FlightRecorder(capacity=64, identity=ident)
    rec.epoch_offset = epoch_offset  # injected wall anchor: deterministic
    return rec


def test_merge_chrome_traces_rebases_and_attributes():
    from nhd_tpu.obs.chrome import (
        chrome_trace,
        journey_replicas,
        merge_chrome_traces,
        pod_journeys,
    )

    a = _replica_ring("rep-a", 1000.0)
    b = _replica_ring("rep-b", 1000.5)  # same wall domain, skewed mono clock
    a.record("watch_event", 10.0, 0.0, corr="c1")
    a.record("spill", 11.0, 0.5, corr="c1", shard=0, epoch=2)
    b.record("bind", 10.0, 1.0, corr="c1", shard=1, epoch=3)
    merged = merge_chrome_traces([chrome_trace(a), chrome_trace(b)])
    assert validate_chrome_trace(merged) == []
    assert merged["nhdMeta"] == {"merged": True,
                                 "replicas": ["rep-a", "rep-b"]}
    journeys = pod_journeys(merged)
    assert set(journeys) == {"c1"}
    # one corr ID, spans attributable to BOTH replicas
    assert journey_replicas(merged, "c1") == ["rep-a", "rep-b"]
    evs = {(e["args"]["replica"], e["name"]): e for e in journeys["c1"]}
    # wall re-basing: both dumps' origin span starts at mono 10.0, but
    # rep-b's wall anchor is 0.5 s later — its legs shift right by 0.5 s
    assert (
        evs[("rep-b", "bind")]["ts"] - evs[("rep-a", "watch_event")]["ts"]
        == pytest.approx(0.5e6)
    )
    # federation coordinates survive the merge
    assert evs[("rep-a", "spill")]["args"]["shard"] == 0
    assert evs[("rep-b", "bind")]["args"]["epoch"] == 3


def test_merge_without_meta_degrades_to_shared_timeline():
    from nhd_tpu.obs.chrome import merge_chrome_traces

    legacy = chrome_trace_of([Span("x", 1.0, 0.5, corr="c")])
    assert "nhdMeta" not in legacy  # pre-federation export shape
    merged = merge_chrome_traces([legacy, legacy])
    assert validate_chrome_trace(merged) == []
    assert merged["nhdMeta"]["replicas"] == ["replica-0", "replica-1"]
    pids = {e["pid"] for e in merged["traceEvents"] if e["ph"] == "X"}
    assert pids == {1, 2}


def test_slo_tracker_windows_and_burn_rates():
    from nhd_tpu.obs.slo import SloTracker

    clock = {"t": 0.0}
    t = SloTracker(
        target_sec=30.0, good_fraction=0.9, windows=(("w", 100.0),),
        clock=lambda: clock["t"],
    )
    assert t.observe(10.0) is False
    assert t.observe(45.0) is True
    # 1 of 2 breached: ratio 0.5 against a 0.1 error budget = 5.0
    assert t.burn_rate(100.0) == pytest.approx(5.0)
    clock["t"] = 200.0  # both events age out of the window
    assert t.burn_rate(100.0) == 0.0
    snap = t.snapshot()
    assert snap["observations_total"] == 2
    assert snap["breaches_total"] == 1
    assert snap["max_seconds"] == 45.0
    lines = t.render()
    assert "nhd_slo_bind_breaches_total 1" in lines
    assert 'nhd_slo_bind_burn_rate{window="w"} 0.0' in lines
    t.reset()
    assert t.snapshot()["observations_total"] == 0


def test_slo_burn_window_coverage_is_rate_independent():
    """A breach storm 30 minutes ago must still burn the 1 h window no
    matter how much healthy traffic followed — a COUNT-capped event ring
    silently truncates the window at high bind rates, which is exactly
    when the page matters. Buckets make coverage rate-independent."""
    from nhd_tpu.obs.slo import SloTracker

    clock = {"t": 0.0}
    t = SloTracker(
        target_sec=1.0, good_fraction=0.9, windows=(("1h", 3600.0),),
        clock=lambda: clock["t"],
    )
    for _ in range(100):
        t.observe(5.0)  # the storm: 100 breaches at t=0
    clock["t"] = 1800.0
    for _ in range(20000):
        t.observe(0.5)  # healthy flood that would evict any event ring
    assert t.burn_rate(3600.0) == pytest.approx((100 / 20100) / 0.1)
    # ...and the storm ages out once the window moves past it
    clock["t"] = 3700.0
    assert t.burn_rate(1800.0) == 0.0


def test_scrape_replica_tolerates_non_json_decisions(monkeypatch):
    """A proxy answering /decisions with a 200 HTML error page (or an
    old build returning a bare list) must cost the decisions detail
    only, never the whole scrape — metrics alone still merge."""
    import io
    import urllib.request

    from nhd_tpu.obs import fleet

    def fake_urlopen(url, timeout=None):
        if "/metrics" in url:
            return io.BytesIO(b'nhd_shard_epoch{shard="0"} 2\n')
        return io.BytesIO(b"<html>502 Bad Gateway</html>")

    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    view = fleet.scrape_replica("http://replica:9464")
    assert view["decisions"] == []
    assert view["shards"] == {"0": 2}


def test_slo_tracker_rejects_bad_objective():
    from nhd_tpu.obs.slo import SloTracker

    with pytest.raises(ValueError):
        SloTracker(target_sec=0)
    with pytest.raises(ValueError):
        SloTracker(good_fraction=1.0)


def test_artifact_envelope_roundtrip(tmp_path):
    from nhd_tpu.obs import artifact

    env = artifact.make_envelope(
        "fleet", 1, {"x": 1}, seed=7, rev="abc", created=5.0
    )
    assert artifact.validate_envelope(env) == []
    path = artifact.write_artifact(env, str(tmp_path), "a.json")
    assert artifact.load_artifact(path) == env
    # every envelope defect is named, and the kind/version pins hold
    assert artifact.validate_envelope({"kind": "fleet"})
    assert artifact.validate_envelope(dict(env, schema_version="x"))
    assert artifact.validate_envelope(env, kind="bench")
    assert artifact.validate_envelope(env, schema_version=2)
    with pytest.raises(ValueError):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"not": "an artifact"}))
        artifact.load_artifact(str(bad))


def test_fleet_payload_from_replica_views():
    from nhd_tpu.obs import fleet
    from nhd_tpu.obs.slo import SloTracker

    a = _replica_ring("r1", 0.0)
    b = _replica_ring("r2", 0.0)
    a.record("spill", 1.0, 0.0, corr="p1", shard=0, epoch=1)
    b.record("bind", 2.0, 0.25, corr="p1", shard=1, epoch=2)
    slo = SloTracker(clock=lambda: 100.0)
    slo.observe(12.0)
    views = [
        fleet.replica_view("r1", recorder=a, slo=slo, shards={0: 1}),
        fleet.replica_view("r2", recorder=b, shards={1: 2}),
    ]
    art = fleet.build_fleet_artifact(views, seed=1)
    assert fleet.validate_fleet_artifact(art) == []
    p = art["payload"]
    assert p["journeys"] == {"pods_traced": 1, "cross_replica": 1}
    assert p["spillover"]["spill_events_total"] == 1
    assert p["spillover"]["by_shard"] == {"0": 1}
    assert p["spillover"]["cross_replica_journeys"] == 1
    assert p["per_shard"]["bind_latency"]["1"]["count"] == 1
    assert p["slo"]["observations_total"] == 1
    assert p["slo"]["worst_burn_rates"]
    assert p["leadership"]["shard_epochs"] == {"0": 1, "1": 2}


def test_corr_ids_scope_by_replica_identity():
    """Locally minted corr IDs are only process-unique counters: two
    replica PROCESSES both mint c000001, and an unscoped merge would
    fuse their unrelated pods into one journey. The identity scope
    makes minted IDs fleet-unique; adoption carries the full scoped ID
    through the annotation, so journeys still keep ONE ID."""
    a, b = obs.new_corr_id("rep-a"), obs.new_corr_id("rep-b")
    assert a.startswith("rep-a/c") and b.startswith("rep-b/c")
    assert a.split("/")[1] != b.split("/")[1]  # counter still monotonic
    assert obs.new_corr_id().startswith("c")  # unscoped legacy form


def test_pods_traced_excludes_watch_receipt_orphans():
    """Every replica (standbys included) records a watch_event under a
    locally minted corr; only the scheduling replica re-aliases its leg.
    The fleet pod tally must not count the leftover one-span receipt
    orphans — with 3 replicas that's a ~3x inflation."""
    from nhd_tpu.obs import fleet

    a = _replica_ring("r1", 0.0)
    b = _replica_ring("r2", 0.0)
    a.record("watch_event", 1.0, 0.0, cat="event", corr="r1/c1")
    a.record("bind", 2.0, 0.5, corr="r1/c1", shard=0)
    b.record("watch_event", 1.0, 0.0, cat="event", corr="r2/c1")  # orphan
    views = [
        fleet.replica_view("r1", recorder=a),
        fleet.replica_view("r2", recorder=b),
    ]
    p = fleet.build_fleet_payload(views)
    assert p["journeys"]["pods_traced"] == 1


def test_fleet_payload_sources_counters_from_scraped_metrics():
    """The scrape path has no in-process ApiCounters snapshot: the
    fencing/spillover totals must come from each replica's parsed
    exposition (summed across replicas), not silently read as zero —
    that's exactly the path tools/fleet_top.py serves operators."""
    from nhd_tpu.obs import fleet

    views = [
        {"replica": "r1", "metrics": {
            "nhd_ha_stale_writes_rejected_total": [({}, 17.0)],
            "nhd_shard_spillover_claims_total": [({}, 9.0)],
        }},
        {"replica": "r2", "metrics": {
            "nhd_ha_stale_writes_rejected_total": [({}, 3.0)],
            "nhd_shard_handoffs_total": [({}, 2.0)],
        }},
    ]
    p = fleet.build_fleet_payload(views)
    assert p["fencing"]["stale_writes_rejected_total"] == 20
    assert p["fencing"]["handoffs_total"] == 2
    assert p["spillover"]["claims_total"] == 9
    # an explicit producer snapshot still wins over the exposition
    p2 = fleet.build_fleet_payload(
        views, counters={"ha_stale_writes_rejected_total": 5}
    )
    assert p2["fencing"]["stale_writes_rejected_total"] == 5


def test_merge_mixed_anchored_and_legacy_never_rebases():
    """Re-basing is all-or-none: a legacy dump has no wall anchor, so
    mixing one into an anchored set must fall back to the shared raw
    timeline — otherwise the anchored dumps shift by absolute wall time
    (~epoch seconds) while the legacy one sits at 0, and the merged
    trace spans decades in the viewer."""
    from nhd_tpu.obs.chrome import chrome_trace, merge_chrome_traces

    a = _replica_ring("rep-a", 1.7e9)  # realistic wall anchor
    a.record("bind", 10.0, 1.0, corr="c1")
    legacy = chrome_trace_of([Span("x", 10.0, 0.5, corr="c2")])
    assert "nhdMeta" not in legacy
    merged = merge_chrome_traces([chrome_trace(a), legacy])
    ts = {e["name"]: e["ts"] for e in merged["traceEvents"]
          if e.get("ph") == "X"}
    # both dumps keep their raw relative timestamps (each export starts
    # at its own origin, ts=0) — no wall shift applied to either
    assert ts["bind"] == pytest.approx(0.0)
    assert ts["x"] == pytest.approx(0.0)


def test_fleet_writer_rejects_invalid(tmp_path):
    from nhd_tpu.obs import fleet

    with pytest.raises(ValueError):
        fleet.write_fleet_artifact({"kind": "fleet"}, str(tmp_path))


def test_parse_prometheus_exposition():
    from nhd_tpu.obs.fleet import parse_prometheus

    fams = parse_prometheus("\n".join([
        "# HELP nhd_x stuff",
        "# TYPE nhd_x counter",
        "nhd_x 3",
        "# TYPE nhd_y gauge",
        'nhd_y{shard="0",window="5m"} 1.5',
        "!! garbage the aggregator must tolerate",
        "nhd_bad notanumber",
    ]))
    assert fams["nhd_x"] == [({}, 3.0)]
    assert fams["nhd_y"] == [({"shard": "0", "window": "5m"}, 1.5)]
    assert "nhd_bad" not in fams


def test_quantile_from_buckets_interpolates():
    """The histogram-edge p99 fix (r14): the scrape-side quantile is
    linearly interpolated within the covering bucket, not the raw
    bucket upper edge — a regression inside a bucket moves the figure,
    and crossing an edge is continuous, not a cliff."""
    from nhd_tpu.obs.histo import quantile_from_buckets

    inf = float("inf")
    # 100 observations, all inside (0.25, 0.5]: the old edge scrape
    # reported 0.5 flat; interpolation places p99 near the bucket top
    buckets = [(0.25, 0), (0.5, 100), (inf, 100)]
    assert abs(quantile_from_buckets(buckets, 0.99) - 0.4975) < 1e-9
    # p50 of the same data sits mid-bucket, not at the edge
    assert abs(quantile_from_buckets(buckets, 0.5) - 0.375) < 1e-9
    # first bucket interpolates from 0
    assert abs(
        quantile_from_buckets([(0.5, 10), (inf, 10)], 0.5) - 0.25
    ) < 1e-9
    # quantile landing in +Inf: the last finite edge (PromQL stance)
    assert quantile_from_buckets([(0.5, 0), (inf, 10)], 0.99) == 0.5
    # no observations
    assert quantile_from_buckets([], 0.99) == 0.0
    assert quantile_from_buckets([(0.5, 0), (inf, 0)], 0.99) == 0.0


def test_fleet_bucketize_carries_interpolated_p99():
    from nhd_tpu.obs.fleet import _bucketize

    rec = _bucketize([0.3] * 99 + [0.4])
    assert 0.25 < rec["p99_seconds"] <= 0.5
    assert rec["p99_seconds"] != 0.5  # not the raw edge


def test_host_phase_rollup_and_config_split():
    """obs/perf.py r14: the attribution table rolls host phases up per
    shape bucket, and every config record carries the solve-vs-host
    split the acceptance metric tracks."""
    from nhd_tpu.obs.perf import config_record, host_phase_rollup

    rollup = host_phase_rollup({
        "materialize:U2_K2_N256": 0.2,
        "final_sync:U2_K2_N256": 0.1,
        "encode:U2_K7_N512": 0.05,
        "solve:U2_K2_N256": 9.9,       # not a host phase key
    })
    assert abs(rollup["U2_K2_N256"] - 0.3) < 1e-9
    assert abs(rollup["U2_K7_N512"] - 0.05) < 1e-9

    rec = config_record(
        wall_seconds=1.0, placed=10, speedup=2.0,
        phases={"solve": 0.5, "select": 0.1, "assign": 0.2,
                "materialize": 0.05, "final_sync": 0.01},
    )
    assert abs(rec["host_phases_seconds"] - 0.36) < 1e-9
