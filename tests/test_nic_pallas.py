"""Pallas NIC kernel parity vs the jnp formulation (interpret mode on CPU)."""

import numpy as np
import pytest

from nhd_tpu.ops.nic_pallas import BN, nic_any_first, nic_any_first_reference


def make_case(rng, T, N, U, K, C, A):
    UK, CA = U * K, C * A
    free_rx = rng.uniform(-1, 90, (N, UK)).astype(np.float32)
    free_tx = rng.uniform(-1, 90, (N, UK)).astype(np.float32)
    dem_rx = rng.uniform(0, 50, (T, CA, UK)).astype(np.float32)
    dem_tx = rng.uniform(0, 50, (T, CA, UK)).astype(np.float32)
    unchosen = rng.random((CA, UK)) < 0.5
    dem_rx[np.broadcast_to(unchosen, (T, CA, UK))] = 0.0
    dem_tx[np.broadcast_to(unchosen, (T, CA, UK))] = 0.0
    valid = rng.random((N, CA)) < 0.8
    pci_ok = rng.random((N, CA)) < 0.7
    map_pci = (rng.random(T) < 0.5).astype(np.int32)
    return (free_rx, free_tx, dem_rx, dem_tx, unchosen, valid, pci_ok, map_pci)


@pytest.mark.parametrize("shape", [(2, BN, 2, 2, 4, 4), (3, 2 * BN, 2, 4, 4, 16)])
def test_pallas_matches_reference(shape):
    T, N, U, K, C, A = shape
    rng = np.random.default_rng(7)
    args = make_case(rng, T, N, U, K, C, A)
    dims = dict(U=U, K=K, C=C, A=A)
    any_p, first_p, count_p = nic_any_first(*args, **dims, interpret=True)
    any_r, first_r, count_r = nic_any_first_reference(*args, **dims)
    np.testing.assert_array_equal(np.asarray(any_p), np.asarray(any_r))
    # first_a only meaningful where any is True
    mask = np.asarray(any_r)
    np.testing.assert_array_equal(
        np.asarray(first_p)[mask], np.asarray(first_r)[mask]
    )
    # real pick counts (the multi-claim capacity hint) must match too
    np.testing.assert_array_equal(np.asarray(count_p), np.asarray(count_r))
    assert (np.asarray(count_p) > 0).sum() == mask.sum()
