"""Fault-tolerance of the real HTTP path (restclient + kube over the stub
API server): retry absorption of injected 5xx, watch hung-socket and
malformed-line recovery, 410-replay dedupe, the full-relist resync net,
and the FaultyHttpClient storm shim end-to-end."""

import random
import sys
import time

import pytest

from nhd_tpu.k8s.apistub import StubApiServer, make_node, make_pod
from nhd_tpu.k8s.retry import API_COUNTERS
from nhd_tpu.sim.faults import FaultProfile, install_http_faults


class _BlockKubernetesImport:
    def find_spec(self, name, path=None, target=None):
        if name == "kubernetes" or name.startswith("kubernetes."):
            raise ImportError("kubernetes blocked: restclient contract test")
        return None


@pytest.fixture()
def stub(monkeypatch):
    monkeypatch.delitem(sys.modules, "kubernetes", raising=False)
    blocker = _BlockKubernetesImport()
    sys.meta_path.insert(0, blocker)
    srv = StubApiServer().start()
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "127.0.0.1")
    monkeypatch.setenv("KUBERNETES_SERVICE_PORT", str(srv.port))
    monkeypatch.setenv("KUBERNETES_SERVICE_SCHEME", "http")
    monkeypatch.setenv("NHD_K8S_TOKEN_FILE", "/nonexistent-token")
    try:
        yield srv
    finally:
        sys.meta_path.remove(blocker)
        srv.stop()


def _backend(**kw):
    from nhd_tpu.k8s.kube import KubeClusterBackend
    from nhd_tpu.k8s.restclient import ApiException
    from nhd_tpu.k8s.retry import RetryPolicy

    kw.setdefault("resync_interval", 0)  # resync driven by hand in tests
    # real retry semantics, millisecond backoff (suite wall-clock)
    kw.setdefault("retry_policy", RetryPolicy(
        base_delay=0.002, max_delay=0.01, exc_class=ApiException
    ))
    return KubeClusterBackend(start_watches=False, **kw)


# ---------------------------------------------------------------------------
# retry over the wire
# ---------------------------------------------------------------------------


def test_retry_absorbs_transient_503s(stub):
    stub.add_node("n1")
    b = _backend()
    before = API_COUNTERS.get("api_retries_total")
    stub.fail_gets = 2          # next two GETs answer 503
    assert b.get_nodes() == ["n1"]
    assert API_COUNTERS.get("api_retries_total") >= before + 2
    # three GETs total hit the wire for the one logical call
    assert len([r for r in stub.requests if r[0] == "GET"]) >= 3


def test_outage_reads_raise_transient_not_missing(stub):
    """When the retry budget is spent on a *retryable* failure, reads
    raise TransientBackendError: 'server unavailable' must never
    masquerade as 'pod does not exist' (which would mass-fail healthy
    pods with FailedCfgParse during an outage). A genuine 404 still
    reads as missing."""
    from nhd_tpu.k8s.interface import TransientBackendError

    stub.add_pod("p1")
    b = _backend()
    assert b.pod_exists("p1", "default") is True
    stub.fail_gets = 99
    with pytest.raises(TransientBackendError):
        b.pod_exists("p1", "default")
    stub.fail_gets = 0
    assert b.pod_exists("p1", "default") is True
    assert b.pod_exists("ghost", "default") is False  # real 404


# ---------------------------------------------------------------------------
# watch: hung socket + malformed lines (the two satellite hazards)
# ---------------------------------------------------------------------------


def test_hung_watch_ends_stream_instead_of_blocking(stub, monkeypatch):
    """timeout=None used to park the watch thread on a dead socket
    forever; the finite read timeout must end the stream normally so the
    reconnect loop takes over."""
    from nhd_tpu.k8s import restclient

    monkeypatch.setattr(restclient, "_WATCH_READ_TIMEOUT", 0.3)
    restclient._set_config(
        restclient.Configuration(f"http://127.0.0.1:{stub.port}")
    )
    api = restclient.CoreV1Api()
    stub.queue_watch_event("/api/v1/pods", "ADDED", make_pod("w1"))
    stub.watch_hang = 30.0      # stream stays open and silent after w1
    w = restclient.Watch()
    t0 = time.monotonic()
    events = list(w.stream(api.list_pod_for_all_namespaces))
    elapsed = time.monotonic() - t0
    # the queued event arrived, then the dead socket timed out quickly —
    # no exception escaped the generator, the caller just reconnects
    assert [e["object"].metadata.name for e in events] == ["w1"]
    assert elapsed < 5.0


def test_malformed_watch_line_drops_and_ends_stream(stub):
    from nhd_tpu.k8s import restclient

    restclient._set_config(
        restclient.Configuration(f"http://127.0.0.1:{stub.port}")
    )
    api = restclient.CoreV1Api()
    good = make_pod("w1", uid="uid-w1")
    good["metadata"]["resourceVersion"] = "7"
    stub.queue_watch_event("/api/v1/pods", "ADDED", good)
    stub.queue_watch_raw("/api/v1/pods", b'{"type": "ADDED", "obj\n')
    before = API_COUNTERS.get("watch_malformed_lines_total")
    w = restclient.Watch()
    events = list(w.stream(api.list_pod_for_all_namespaces))
    # events before the garbage arrive; the garbled line is dropped and
    # the stream ends normally — no JSONDecodeError out of the generator
    assert [e["object"].metadata.name for e in events] == ["w1"]
    assert API_COUNTERS.get("watch_malformed_lines_total") == before + 1
    # the reconnect works and resumes from the last GOOD resourceVersion
    stub.queue_watch_event("/api/v1/pods", "ADDED", make_pod("w2"))
    events = list(w.stream(api.list_pod_for_all_namespaces))
    assert [e["object"].metadata.name for e in events] == ["w2"]
    watch_paths = [p for (m, p, _, _) in stub.requests if "watch=true" in p]
    assert watch_paths[-1].endswith("resourceVersion=7")


# ---------------------------------------------------------------------------
# 410 full-replay dedupe (satellite regression)
# ---------------------------------------------------------------------------


def test_410_replay_does_not_double_emit_pod_create(stub):
    """After a 410 Gone the fresh watch re-delivers ADDED for every live
    object; the backend must upsert, not re-emit pod_create."""
    b = _backend()
    b._watch_backoff = 0.05
    b._start_watches()
    try:
        pod = make_pod("w1", uid="uid-w1",
                       annotations={"sigproc.viasat.io/cfg_type": "triad"})
        stub.queue_watch_event("/api/v1/pods", "ADDED", pod)
        deadline = time.time() + 5
        creates = []
        while time.time() < deadline and not creates:
            creates += [e for e in b.poll_watch_events(timeout=0.1)
                        if e.kind == "pod_create"]
        assert len(creates) == 1

        # the stub replays the same ADDED on the next connection — the
        # full-replay shape a post-410 watch produces
        before = API_COUNTERS.get("watch_dedup_replays_total")
        stub.queue_watch_event("/api/v1/pods", "ADDED", pod)
        deadline = time.time() + 3
        while (time.time() < deadline
               and API_COUNTERS.get("watch_dedup_replays_total") == before):
            creates += [e for e in b.poll_watch_events(timeout=0.1)
                        if e.kind == "pod_create"]
        assert API_COUNTERS.get("watch_dedup_replays_total") == before + 1
        assert len(creates) == 1            # still exactly one emission

        # a genuinely NEW incarnation (same name, new uid) does emit
        stub.queue_watch_event(
            "/api/v1/pods", "ADDED", make_pod("w1", uid="uid-w1-reborn")
        )
        deadline = time.time() + 5
        while time.time() < deadline and len(creates) < 2:
            creates += [e for e in b.poll_watch_events(timeout=0.1)
                        if e.kind == "pod_create"]
        assert len(creates) == 2
        assert creates[-1].uid == "uid-w1-reborn"
    finally:
        b.stop_watches()


# ---------------------------------------------------------------------------
# resync: the net under the watch plane
# ---------------------------------------------------------------------------


def test_inband_error_event_is_contained(stub):
    """An in-band ERROR watch event carries a Status, not a Pod: it must
    never be dereferenced as a pod, and it clears the tracked
    resourceVersion so the reconnect starts a fresh watch instead of
    replaying the same expired RV forever."""
    b = _backend()
    # the Status object would raise on any pod-shaped attribute access
    assert b._note_pod("ERROR", object()) is None
    assert b._note_pod("BOOKMARK", object()) is None

    class W:
        resource_version = "42"

    w = W()
    assert b._watch_error(w, {"type": "ERROR", "object": {}}) is True
    assert w.resource_version is None
    assert b._watch_error(w, {"type": "ADDED", "object": {}}) is False


def test_modified_for_unknown_pod_emits_the_missed_create(stub):
    """A MODIFIED for a pod we never saw ADDED means the create event was
    lost upstream — it must surface as pod_create, not silently mark the
    pod 'known' (which would also stop resync from ever repairing it)."""
    from nhd_tpu.k8s import restclient

    b = _backend()
    obj = restclient._wrap(make_pod("p1", uid="u1"))
    ev = b._note_pod("MODIFIED", obj)
    assert ev is not None and ev.kind == "pod_create" and ev.uid == "u1"
    # a second MODIFIED for the now-known pod is state-only
    assert b._note_pod("MODIFIED", obj) is None


def test_resync_emits_missed_create_and_delete(stub):
    b = _backend()
    # p1 appears with NO watch event delivered (stream was down)
    stub.add_pod("p1", uid="uid-p1",
                 annotations={"sigproc.viasat.io/cfg_type": "triad"})
    b.resync()
    evs = list(b.poll_watch_events())
    creates = [e for e in evs if e.kind == "pod_create"]
    assert [(e.namespace, e.name, e.uid) for e in creates] == [
        ("default", "p1", "uid-p1")
    ]
    assert creates[0].annotations == {"sigproc.viasat.io/cfg_type": "triad"}

    # steady state: nothing changed → nothing emitted
    b.resync()
    assert list(b.poll_watch_events()) == []

    # p1 vanishes, again with no watch event
    del stub.pods[("default", "p1")]
    b.resync()
    evs = list(b.poll_watch_events())
    deletes = [e for e in evs if e.kind == "pod_delete"]
    assert [(e.namespace, e.name, e.uid) for e in deletes] == [
        ("default", "p1", "uid-p1")
    ]
    # the synthetic delete carries the last-seen annotations (release
    # path needs them after the object is gone)
    assert deletes[0].annotations == {"sigproc.viasat.io/cfg_type": "triad"}


def test_resync_catches_delete_recreate_aliasing(stub):
    b = _backend()
    stub.add_pod("p1", uid="uid-old")
    b.resync()
    b.poll_watch_events()
    # delete + recreate under the same name while the watch was blind
    stub.add_pod("p1", uid="uid-new")
    b.resync()
    kinds = [(e.kind, e.uid) for e in b.poll_watch_events()]
    assert kinds == [("pod_delete", "uid-old"), ("pod_create", "uid-new")]


def test_resync_does_not_override_fresher_watch_state(stub):
    """A pod created while resync's relist is in flight is in the watch
    state but not in the (stale) listing — resync must NOT emit a
    spurious synthetic delete for it (the touch-sequence guard)."""
    from nhd_tpu.k8s import restclient

    b = _backend()
    stub.add_pod("p1", uid="u1")
    b.resync()
    b.poll_watch_events()  # baseline established

    real_list = b.v1._api.list_pod_for_all_namespaces

    def list_with_mid_flight_create(*a, **kw):
        resp = real_list(*a, **kw)          # stale: p2 not in it
        # the watch delivers p2's ADDED while the listing is in flight
        b._note_pod("ADDED", restclient._wrap(make_pod("p2", uid="u2")))
        return resp

    b.v1._wrapped["list_pod_for_all_namespaces"] = list_with_mid_flight_create
    b.resync()
    evs = list(b.poll_watch_events())
    assert not any(
        e.kind == "pod_delete" and e.name == "p2" for e in evs
    ), "resync deleted a pod the watch had just created"
    assert ("default", "p2") in b._known_pods


def test_resync_emits_missed_node_changes(stub):
    b = _backend()
    stub.add_node("n1")
    b.resync()
    assert [e.kind for e in b.poll_watch_events()] == []  # baseline only
    # cordon happens while the node watch is blind
    stub.nodes["n1"]["spec"]["unschedulable"] = True
    before = API_COUNTERS.get("resync_synthetic_events_total")
    b.resync()
    evs = [e for e in b.poll_watch_events() if e.kind == "node_update"]
    assert len(evs) == 1
    assert evs[0].unschedulable is True and evs[0].was_unschedulable is False
    assert API_COUNTERS.get("resync_synthetic_events_total") == before + 1
    # steady state again
    b.resync()
    assert [e for e in b.poll_watch_events() if e.kind == "node_update"] == []


# ---------------------------------------------------------------------------
# the HTTP fault shim end-to-end: storm in front, clean API behind
# ---------------------------------------------------------------------------


def test_http_fault_storm_absorbed_by_retry_layer(stub):
    stub.add_node("n1")
    stub.add_pod("p1")
    b = _backend()
    shim = install_http_faults(
        b,
        FaultProfile(name="t", http_error=0.4, http_conn_reset=0.1),
        random.Random(3),
    )
    # every logical call must succeed despite the storm (seeded, so the
    # injected fault sequence is fixed)
    for _ in range(10):
        assert b.get_nodes() == ["n1"]
        assert b.pod_exists("p1", "default") is True
    assert shim.stats["http_errors"] + shim.stats["conn_resets"] > 0


def test_watch_cut_recovers_via_resync(stub):
    """Mid-stream cuts LOSE events (the stub, like a real API server,
    doesn't replay what it already sent); the resync net must repair the
    gap from a full relist."""
    b = _backend()
    shim = install_http_faults(
        b, FaultProfile(name="t", watch_cut=0.5), random.Random(11)
    )
    b._watch_backoff = 0.05
    b._start_watches()
    try:
        for i in range(4):
            # the pod exists AND a watch event is queued — cut streams
            # may drop the event, but the relist always sees the pod
            stub.add_pod(f"w{i}", uid=f"uid-{i}")
            stub.queue_watch_event(
                "/api/v1/pods", "ADDED", make_pod(f"w{i}", uid=f"uid-{i}")
            )
        seen = set()
        deadline = time.time() + 10
        while time.time() < deadline and len(seen) < 4:
            for e in b.poll_watch_events(timeout=0.1):
                if e.kind == "pod_create":
                    seen.add(e.name)
            if len(seen) < 4:
                shim.enabled = False      # storm over; relist runs clean
                b.resync()
                shim.enabled = True
        assert seen == {"w0", "w1", "w2", "w3"}
        assert shim.stats["watch_cuts"] >= 1
    finally:
        b.stop_watches()


# ---------------------------------------------------------------------------
# leader election + fencing over the real HTTP path (coordination.k8s.io
# Lease objects on the stub, with resourceVersion optimistic concurrency)
# ---------------------------------------------------------------------------


def test_lease_election_over_http(stub):
    from nhd_tpu.k8s.interface import LEASE_NAME
    from nhd_tpu.k8s.lease import LeaderElector
    from nhd_tpu.k8s.retry import ApiCounters

    b = _backend()
    el = LeaderElector(b, identity="replica-1", ttl=30, counters=ApiCounters())
    assert el.tick() is True
    assert el.epoch == 1
    lease = stub.leases[("default", LEASE_NAME)]
    assert lease["spec"]["holderIdentity"] == "replica-1"
    assert lease["spec"]["leaseTransitions"] == 1
    assert el.tick() is True          # renew over the wire (PUT + new rv)
    assert int(lease_rv := stub.leases[("default", LEASE_NAME)]["metadata"]
               ["resourceVersion"]) >= 2
    # a rival sees the live lease and stays a follower
    el2 = LeaderElector(b, identity="replica-2", ttl=30,
                        counters=ApiCounters())
    assert el2.tick() is False


def test_renew_lost_to_rival_demotes_over_http(stub):
    """A rival acquisition landing on the server (holder and epoch
    moved) makes the next renewal report a genuine CAS loss — the
    elector must step down immediately, no grace."""
    from nhd_tpu.k8s.interface import LEASE_NAME
    from nhd_tpu.k8s.lease import LeaderElector
    from nhd_tpu.k8s.retry import ApiCounters

    b = _backend()
    el = LeaderElector(b, identity="replica-1", ttl=30, counters=ApiCounters())
    assert el.tick() is True
    lease = stub.leases[("default", LEASE_NAME)]
    lease["spec"]["holderIdentity"] = "rival"
    lease["spec"]["leaseTransitions"] = 2
    assert el.tick() is False
    assert el.is_leader is False
    assert el.fencing_epoch() is None


def test_self_conflict_on_renew_does_not_bounce_leadership(stub):
    """The stub's fail_lease_puts hook answers the renew replace with
    409 while the lease still shows (holder, epoch) == ours — the shape
    a retried PUT produces after its first send landed. The renew path
    must re-read and keep leading instead of demoting a healthy leader
    (and bumping the epoch) once per network blip."""
    from nhd_tpu.k8s.lease import LeaderElector
    from nhd_tpu.k8s.retry import ApiCounters

    b = _backend()
    el = LeaderElector(b, identity="replica-1", ttl=30, counters=ApiCounters())
    assert el.tick() is True
    stub.fail_lease_puts = 1
    assert el.tick() is True          # 409, re-read: still ours
    assert el.is_leader is True
    assert el.epoch == 1              # no spurious re-acquisition


def test_acquire_race_lost_over_http_stays_follower(stub):
    """409 on the acquisition replace (another replica won between our
    read and write) is a normal election outcome, not an error."""
    from nhd_tpu.k8s.lease import LeaderElector
    from nhd_tpu.k8s.retry import ApiCounters

    b = _backend()
    winner = LeaderElector(b, identity="winner", ttl=30,
                           counters=ApiCounters())
    assert winner.tick() is True
    # expire the winner's lease on the server so the loser's acquire
    # path takes the replace branch — then force that replace to 409
    from nhd_tpu.k8s.interface import LEASE_NAME
    stub.leases[("default", LEASE_NAME)]["spec"]["renewTime"] = (
        "2000-01-01T00:00:00.000000Z"
    )
    stub.fail_lease_puts = 1
    loser = LeaderElector(b, identity="loser", ttl=30, counters=ApiCounters())
    assert loser.tick() is False
    assert loser.is_leader is False


def test_fenced_write_rejected_over_http(stub):
    """kube.py's fence check reads the Lease before every fenced mutator:
    once the server-side epoch moves past the caller's, binds and
    annotates raise StaleLeaseError instead of landing."""
    import pytest as _pytest

    from nhd_tpu.k8s.interface import LEASE_NAME, StaleLeaseError
    from nhd_tpu.k8s.lease import LeaderElector
    from nhd_tpu.k8s.retry import ApiCounters

    stub.add_node("n1")
    stub.add_pod("p1")
    b = _backend()
    el = LeaderElector(b, identity="replica-1", ttl=30, counters=ApiCounters())
    assert el.tick() is True and el.epoch == 1
    # a rival leadership lands on the server (epoch 2)
    lease = stub.leases[("default", LEASE_NAME)]
    lease["spec"]["holderIdentity"] = "replica-2"
    lease["spec"]["leaseTransitions"] = 2
    with _pytest.raises(StaleLeaseError):
        b.bind_pod_to_node("p1", "n1", "default", epoch=1)
    with _pytest.raises(StaleLeaseError):
        b.annotate_pod_config("default", "p1", "cfg", epoch=1)
    assert stub.bindings == []            # nothing reached the bind route
    # the CURRENT epoch still lands over the wire
    assert b.bind_pod_to_node("p1", "n1", "default", epoch=2) is True
    assert len(stub.bindings) == 1


def test_bind_inside_fence_cache_window_caught_via_epoch_hwm(
    stub, monkeypatch
):
    """The NHD_FENCE_CACHE_SEC staleness pin: a fenced write whose lease
    view is still warm in the cache must STILL be rejected once this
    process has observed a rival acquisition through ANY lease operation
    — the per-lease epoch high-water mark closes the cache window the
    moment the rival leadership is seen (here: the elector's own failed
    renewal), instead of admitting stale binds for the rest of the TTL."""
    import pytest as _pytest

    from nhd_tpu.k8s import kube as kube_mod
    from nhd_tpu.k8s.interface import LEASE_NAME, StaleLeaseError
    from nhd_tpu.k8s.lease import LeaderElector
    from nhd_tpu.k8s.retry import ApiCounters

    stub.add_node("n1")
    stub.add_pod("p1")
    # a cache that never expires within the test: any rejection below is
    # provably the high-water mark, not a lucky cache miss
    monkeypatch.setattr(kube_mod, "_FENCE_CACHE_SEC", 300.0)
    b = _backend()
    el = LeaderElector(b, identity="replica-1", ttl=30, counters=ApiCounters())
    assert el.tick() is True and el.epoch == 1
    # warm the fence cache with a successful fenced write at epoch 1
    assert b.annotate_pod_config("default", "p1", "cfg", epoch=1) is True
    # a rival acquisition lands on the server (epoch 2); the cached
    # fence view still says epoch 1 and stays warm for 300 s
    lease = stub.leases[("default", LEASE_NAME)]
    lease["spec"]["holderIdentity"] = "rival"
    lease["spec"]["leaseTransitions"] = 2
    # the elector's next renewal observes the rival state (CAS loss) —
    # that observation advances the epoch high-water mark
    assert el.tick() is False
    with _pytest.raises(StaleLeaseError):
        b.bind_pod_to_node("p1", "n1", "default", epoch=1)
    assert stub.bindings == []


def test_federation_shard_leases_over_http(stub):
    """The sharded federation's lease table on the real HTTP path: S
    shard leases plus per-replica presence beacons as ordinary
    coordination.k8s.io Leases, converging to the deterministic
    rendezvous assignment with one holder per shard."""
    from nhd_tpu.k8s.lease import (
        ShardedElector,
        presence_lease_name,
        rendezvous_owner,
        shard_lease_name,
    )
    from nhd_tpu.k8s.retry import ApiCounters

    b = _backend()
    ids = ["replica-1", "replica-2"]
    els = [
        ShardedElector(
            b, identity=i, peers=ids, n_shards=3, ttl=30,
            counters=ApiCounters(),
        )
        for i in ids
    ]
    for _ in range(6):
        for el in els:
            el.tick()
    owned = {}
    for i, el in zip(ids, els):
        for s in el.owned_shards():
            assert s not in owned, "two holders for one shard"
            owned[s] = i
    assert sorted(owned) == [0, 1, 2]
    for s, i in owned.items():
        lease = stub.leases[("default", shard_lease_name(s, 3))]
        assert lease["spec"]["holderIdentity"] == i
        assert rendezvous_owner(s, ids) == i
    for i in ids:
        assert ("default", presence_lease_name(i)) in stub.leases


def test_lease_get_outage_is_transient_for_liveness(stub):
    """fail_lease_gets: a lease read outage surfaces as
    TransientBackendError once the retry budget is spent — the
    federation's liveness probes (lease_live) treat it as
    'unverifiable', never as a verdict."""
    import pytest as _pytest

    from nhd_tpu.k8s.interface import LEASE_NAME, TransientBackendError
    from nhd_tpu.k8s.lease import LeaderElector
    from nhd_tpu.k8s.retry import ApiCounters

    b = _backend()
    el = LeaderElector(b, identity="replica-1", ttl=30, counters=ApiCounters())
    assert el.tick() is True
    stub.fail_lease_gets = 50            # past any retry budget
    with _pytest.raises(TransientBackendError):
        b.lease_live(LEASE_NAME)
    stub.fail_lease_gets = 0
    assert b.lease_live(LEASE_NAME) == "replica-1"
