"""Record/replay journal tests: writer semantics + atomic finalize,
schema validation (including defect detection), the golden-journal
deterministic replay pin, divergence negative controls (dropped node,
flipped knob), capture-under-chaos round trip, journey input mode, and
the /journey + journal metrics surfaces."""

import json
import os
from pathlib import Path

import pytest

from nhd_tpu.obs import journal as journal_mod
from nhd_tpu.obs.journal import (
    JournalWriter,
    disable_journal,
    enable_journal,
    enable_journal_from_env,
    genesis_nodes,
    get_journal,
    journal_view,
    load_journal,
    merge_journals,
    read_journal,
    validate_journal,
)
from nhd_tpu.k8s.interface import WatchEvent

GOLDEN = (
    Path(__file__).resolve().parent
    / "fixtures" / "journal" / "golden_churn.journal.jsonl"
)


@pytest.fixture(autouse=True)
def _journal_off():
    """Every test starts and ends with the process-global journal off."""
    disable_journal(finalize=False)
    yield
    disable_journal(finalize=False)


def _fill(jnl: JournalWriter) -> None:
    jnl.genesis(
        [{"name": "n0", "labels": {"a": "1"}, "hugepages_gb": 64,
          "addr": "10.0.0.1"}],
        seed=7, mode="test", respect_busy=False,
    )
    jnl.watch_event(
        WatchEvent(kind="pod_create", name="p0", namespace="default"),
    )
    jnl.note_corr("c42")
    jnl.pod_spec("default", "p0", "cfg-text", groups=("g1",), tier=1)
    jnl.cluster_event("cordon_node", {"name": "n0", "cordon": True})
    jnl.fault_event("bind", "default", "p0")
    jnl.decision({
        "pod": "p0", "ns": "default", "corr": "c42",
        "outcome": "scheduled", "node": "n0", "phases": {}, "time": 1.0,
    })
    jnl.commit("p0", "default", "c42", "bound", node="n0")


# ---------------------------------------------------------------------------
# writer semantics
# ---------------------------------------------------------------------------

def test_writer_roundtrip_validates(tmp_path):
    path = str(tmp_path / "t.journal.jsonl")
    jnl = JournalWriter(path, identity="t", seed=7)
    _fill(jnl)
    assert jnl.finalize() == path
    header, events = load_journal(path)
    assert validate_journal(header, events) == []
    kinds = [e["ev"] for e in events]
    assert kinds == [
        "genesis", "watch", "pod_spec", "cluster", "fault", "decision",
        "commit",
    ]
    assert [e["seq"] for e in events] == list(range(1, 8))
    g = events[0]
    assert g["nodes"][0]["name"] == "n0"
    assert g["respect_busy"] is False
    assert "NHD_JOURNAL" in g["knobs"]
    # cluster op kwargs land under "args" (replay + journey read them)
    assert events[3]["op"] == "cordon_node"
    assert events[3]["args"] == {"name": "n0", "cordon": True}
    # note_corr back-annotated the buffered watch event
    assert events[1]["corr"] == "c42"


def test_finalize_is_atomic(tmp_path):
    path = str(tmp_path / "t.journal.jsonl")
    jnl = JournalWriter(path, identity="t")
    _fill(jnl)
    # until finalize, only the .part file exists
    assert not os.path.exists(path) and os.path.exists(path + ".part")
    jnl.finalize()
    assert os.path.exists(path) and not os.path.exists(path + ".part")
    n_events = len(read_journal(path)[1])
    # post-finalize captures are silent no-ops, not corruption
    jnl.decision({"pod": "late", "ns": "d", "outcome": "scheduled"})
    jnl.flush()
    assert len(read_journal(path)[1]) == n_events


def test_streaming_flush_bounds_memory(tmp_path):
    path = str(tmp_path / "t.journal.jsonl")
    jnl = JournalWriter(path, flush_every=4)
    for i in range(10):
        jnl.cluster_event("create_pod", {"name": f"p{i}"})
    # 8 of 10 events flushed to disk before finalize, buffer ≤ 4
    assert len(read_journal(path + ".part")[1]) == 8
    jnl.finalize()
    assert len(read_journal(path)[1]) == 10


def test_pod_spec_dedup_and_corr_index(tmp_path):
    path = str(tmp_path / "t.journal.jsonl")
    jnl = JournalWriter(path)
    jnl.pod_spec("d", "p", "cfg-a")
    jnl.pod_spec("d", "p", "cfg-a")   # same digest: deduped
    jnl.pod_spec("d", "p", "cfg-b")   # changed spec: recorded again
    jnl.watch_event(
        WatchEvent(kind="pod_create", name="p", namespace="d"), corr="c1",
    )
    assert jnl.corr_seqs("c1") == [3]  # deduped spec consumed no seq
    jnl.finalize()
    _, events = load_journal(path)
    assert [e["ev"] for e in events] == ["pod_spec", "pod_spec", "watch"]


# ---------------------------------------------------------------------------
# validator defects
# ---------------------------------------------------------------------------

def _valid_journal(tmp_path):
    path = str(tmp_path / "v.journal.jsonl")
    jnl = JournalWriter(path, identity="v", seed=1)
    _fill(jnl)
    jnl.finalize()
    return read_journal(path)


def test_validator_rejects_seq_regression(tmp_path):
    header, events = _valid_journal(tmp_path)
    events[3]["seq"] = 1
    assert any("seq" in e for e in validate_journal(header, events))


def test_validator_rejects_unknown_kind(tmp_path):
    header, events = _valid_journal(tmp_path)
    events[2]["ev"] = "telepathy"
    assert any("telepathy" in e for e in validate_journal(header, events))


def test_validator_rejects_double_genesis(tmp_path):
    header, events = _valid_journal(tmp_path)
    events.append(dict(events[0], seq=events[-1]["seq"] + 1))
    assert any("genesis" in e for e in validate_journal(header, events))


def test_validator_rejects_foreign_envelope(tmp_path):
    header, events = _valid_journal(tmp_path)
    bad = dict(header, kind="chrome-trace")
    assert validate_journal(bad, events)
    bad = dict(header)
    bad["payload"] = dict(header["payload"], body="csv")
    assert any("body" in e for e in validate_journal(bad, events))


def test_load_journal_fails_loud_on_defect(tmp_path):
    path = str(tmp_path / "v.journal.jsonl")
    jnl = JournalWriter(path)
    _fill(jnl)
    jnl.finalize()
    lines = Path(path).read_text().splitlines()
    lines.append(json.dumps({"seq": 1, "t": 0.0, "ev": "watch"}))
    Path(path).write_text("\n".join(lines) + "\n")
    with pytest.raises(ValueError):
        load_journal(path)


# ---------------------------------------------------------------------------
# process-global lifecycle + env gate
# ---------------------------------------------------------------------------

def test_enable_disable_and_view(tmp_path):
    path = str(tmp_path / "g.journal.jsonl")
    assert get_journal() is None
    assert journal_view() == {"enabled": False}
    jnl = enable_journal(path, identity="g")
    assert get_journal() is jnl
    jnl.cluster_event("create_pod", {"name": "p"})
    view = journal_view()
    assert view["enabled"] is True and view["path"] == path
    assert view["counts"]["cluster"] == 1
    assert disable_journal() == path
    assert get_journal() is None


def test_enable_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("NHD_JOURNAL", raising=False)
    assert enable_journal_from_env() is None
    monkeypatch.setenv("NHD_JOURNAL", "1")
    monkeypatch.setenv("NHD_JOURNAL_DIR", str(tmp_path))
    jnl = enable_journal_from_env(identity="envtest")
    assert jnl is not None
    assert jnl.path == str(tmp_path / "nhd-envtest.journal.jsonl")
    disable_journal()


def test_genesis_nodes_duck_typed():
    from tests.test_scheduler import make_backend

    backend = make_backend(n_nodes=2)
    nodes = genesis_nodes(backend)
    assert [n["name"] for n in nodes] == sorted(backend.get_nodes())
    assert all(
        isinstance(n["hugepages_gb"], int) and n["labels"] for n in nodes
    )


def test_merge_journals_interleaves(tmp_path):
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    ja = JournalWriter(pa, identity="a", created=100.0, clock=lambda: 0.0)
    ja.cluster_event("create_pod", {"name": "pa"})
    ja.finalize()
    jb = JournalWriter(pb, identity="b", created=100.5, clock=lambda: 0.0)
    jb.cluster_event("create_pod", {"name": "pb"})
    jb.finalize()
    headers, merged = merge_journals([pa, pb])
    assert [h["payload"]["identity"] for h in headers] == ["a", "b"]
    assert [e["args"]["name"] for e in merged] == ["pa", "pb"]
    assert [e["origin"] for e in merged] == [0, 1]
    assert merged[0]["t"] < merged[1]["t"]


# ---------------------------------------------------------------------------
# golden journal: deterministic replay pin + divergence controls
# ---------------------------------------------------------------------------

def test_golden_journal_is_valid():
    header, events = load_journal(str(GOLDEN))
    assert validate_journal(header, events) == []
    assert header["git_rev"] == "golden"
    g = next(e for e in events if e["ev"] == "genesis")
    assert g["mode"] == "chaos" and len(g["nodes"]) == 6
    kinds = {e["ev"] for e in events}
    assert {"genesis", "cluster", "watch", "decision", "commit"} <= kinds


def test_golden_replay_pin_deterministic():
    """THE replay pin: the committed churn journal re-drives the real
    scheduler with zero divergence, twice, bit-identically."""
    from nhd_tpu.sim.replay import _decision_sig, replay_journal

    r1 = replay_journal([str(GOLDEN)])
    assert r1.recorded, "golden journal recorded no decisions"
    assert not r1.diverged, r1.first_divergence
    assert r1.knob_drift == {}, r1.knob_drift
    r2 = replay_journal([str(GOLDEN)])
    sig = lambda r: [  # noqa: E731
        (d.get("ns"), d.get("pod"), _decision_sig(d)) for d in r.replayed
    ]
    assert sig(r1) == sig(r2)


def test_golden_replay_drop_node_diverges(tmp_path):
    """Negative control: perturbing genesis (node0 gone) must produce a
    divergence report naming the first divergent corr and the delta."""
    from nhd_tpu.sim.replay import replay_journal

    r = replay_journal([str(GOLDEN)], drop_nodes=["node0"])
    assert r.diverged
    assert r.dropped_nodes == ["node0"]
    fd = r.first_divergence
    assert fd["corr"] and fd["kind"] in (
        "decision-mismatch", "missing-decision", "extra-decision",
    )
    if fd["kind"] == "decision-mismatch":
        assert fd["recorded"] != fd["replayed"]
    out = r.write_report(str(tmp_path))
    report = json.loads(Path(out).read_text())
    assert report["kind"] == "replay-divergence"
    assert report["payload"]["divergences"][0]["corr"] == fd["corr"]


def test_golden_replay_knob_drift_named(monkeypatch):
    """Negative control: a flipped knob must be reported by name even
    before anyone inspects decisions."""
    from nhd_tpu.sim.replay import knob_drift

    genesis = next(
        e for e in load_journal(str(GOLDEN))[1] if e["ev"] == "genesis"
    )
    monkeypatch.setenv("NHD_POLICY", "flipped")
    drift = knob_drift(genesis["knobs"])
    assert drift["NHD_POLICY"] == {"recorded": None, "current": "flipped"}
    # the journal apparatus itself is exempt (it always differs)
    monkeypatch.setenv("NHD_JOURNAL", "1")
    assert "NHD_JOURNAL" not in knob_drift(genesis["knobs"])


# ---------------------------------------------------------------------------
# capture under chaos + journey input mode
# ---------------------------------------------------------------------------

def _run_churn(path, seed=99, steps=12, n_nodes=4, faults=False):
    from nhd_tpu.sim.chaos import ChaosSim
    from nhd_tpu.sim.faults import PROFILES

    enable_journal(path, identity="t", seed=seed)
    try:
        sim = ChaosSim(
            seed=seed, n_nodes=n_nodes,
            api_faults=PROFILES["churn"] if faults else None,
        )
        for _ in range(steps):
            sim.step()
        assert sim.stats.violations == []
        return sim
    finally:
        disable_journal()


def test_capture_under_chaos_replays_clean(tmp_path):
    """A journal captured under an API-fault storm replays with zero
    divergence — injected faults are scripted back at the same recorded
    instants."""
    from nhd_tpu.sim.replay import replay_journal

    path = str(tmp_path / "churn.journal.jsonl")
    _run_churn(path, faults=True)
    header, events = load_journal(path)
    assert validate_journal(header, events) == []
    r = replay_journal([path])
    assert r.recorded and not r.diverged, r.first_divergence
    assert r.faults_armed == sum(1 for e in events if e["ev"] == "fault")


def test_journey_mode_reproduces_storm(tmp_path):
    """ChaosSim(journey=...) re-drives a recorded storm: same pods
    created/deleted, same final bound set."""
    from nhd_tpu.sim.chaos import ChaosSim

    path = str(tmp_path / "src.journal.jsonl")
    src = _run_churn(path, steps=10)
    replayed = ChaosSim(seed=0, journey=path)
    for _ in range(10):
        replayed.step()
    assert replayed.stats.violations == []
    assert replayed.stats.created == src.stats.created
    assert replayed.stats.deleted == src.stats.deleted

    def bound(sim):
        return {key: pod.node for key, pod in sim.base.pods.items()}

    assert bound(replayed) == bound(src)


# ---------------------------------------------------------------------------
# /journey view + journal metrics + monotonic dropped counter
# ---------------------------------------------------------------------------

def test_journey_view_joins_ring_and_journal(tmp_path):
    import nhd_tpu.obs as obs
    from nhd_tpu.obs import journey_view

    assert journey_view("c1")["enabled"] is False
    rec = obs.enable(capacity=64)
    jnl = enable_journal(str(tmp_path / "j.jsonl"))
    try:
        with obs.correlate("cJV"):
            with obs.span("solve"):
                pass
        rec.record_decision({"pod": "p", "ns": "d", "corr": "cJV",
                             "outcome": "scheduled", "node": "n0"})
        jnl.watch_event(
            WatchEvent(kind="pod_create", name="p", namespace="d"),
            corr="cJV",
        )
        view = journey_view("cJV")
        assert view["enabled"] is True
        assert [s["name"] for s in view["spans"]] == ["solve"]
        assert view["decisions"][0]["outcome"] == "scheduled"
        assert view["journal"]["seqs"] == [1]
        assert view["journal"]["path"] == jnl.path
    finally:
        obs.disable()


def test_metrics_render_journal_families(tmp_path):
    from nhd_tpu.rpc.metrics import render_metrics

    out = render_metrics([], 0, api_stats={})
    assert "nhd_journal_enabled 0" in out
    assert "nhd_journal_events_total" not in out
    jnl = enable_journal(str(tmp_path / "m.jsonl"))
    jnl.cluster_event("create_pod", {"name": "p"})
    out = render_metrics([], 0, api_stats={})
    assert "nhd_journal_enabled 1" in out
    assert 'nhd_journal_events_total{ev="cluster"} 1' in out
    assert "nhd_journal_bytes_total" in out


def test_dropped_total_is_monotonic_across_generations():
    import nhd_tpu.obs as obs
    from nhd_tpu.obs.recorder import dropped_total

    base = dropped_total()
    rec = obs.enable(capacity=2)
    try:
        for i in range(5):
            rec.record(f"s{i}", float(i), 0.1)
        assert dropped_total() == base + 3
        rec.clear()  # ring wiped, but the monotonic total keeps the 3
        assert dropped_total() == base + 3
        for i in range(4):
            rec.record(f"r{i}", float(i), 0.1)
        assert dropped_total() == base + 5
    finally:
        obs.disable()
    assert dropped_total() == base + 5  # banked at disable


def test_journal_module_has_no_heavy_imports():
    """journal.py must stay import-light: producers import it on the
    hot path with journaling off."""
    import importlib

    mod = importlib.reload(journal_mod)
    assert not hasattr(mod, "jax")
    assert not hasattr(mod, "numpy")
