"""End-to-end scheduler tests against the fake cluster backend.

Covers the reference's full lifecycle (NHDScheduler.py + TriadController.py):
pending pod → parse → match → annotate → bind; deletion → release; restart
replay; cordon/maintenance/group events; TriadSet reconciliation; bind
failure unwind.
"""

import queue

import pytest

from nhd_tpu.config import libconfig
from nhd_tpu.k8s.fake import FakeClusterBackend
from nhd_tpu.k8s.interface import CFG_ANNOTATION, NAD_ANNOTATION
from nhd_tpu.scheduler.controller import Controller
from nhd_tpu.scheduler.core import PodStatus, RpcMsgType, Scheduler
from nhd_tpu.scheduler.events import WatchQueue
from nhd_tpu.sim import SynthNodeSpec, make_node_labels, make_triad_config


def make_backend(n_nodes=2, spec=None) -> FakeClusterBackend:
    backend = FakeClusterBackend()
    spec = spec or SynthNodeSpec()
    for i in range(n_nodes):
        s = SynthNodeSpec(**{**spec.__dict__, "name": f"node{i}"})
        backend.add_node(s.name, make_node_labels(s), hugepages_gb=s.hugepages_gb)
    return backend


def make_scheduler(backend) -> Scheduler:
    sched = Scheduler(backend, WatchQueue(), queue.Queue(), respect_busy=False)
    sched.build_initial_node_list()
    sched.load_deployed_configs()
    return sched


def pod_cfg(**kw):
    kw.setdefault("gpus_per_group", 1)
    kw.setdefault("cpu_workers", 2)
    kw.setdefault("hugepages_gb", 4)
    return make_triad_config(**kw)


def test_schedule_pending_pod_end_to_end():
    backend = make_backend()
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()

    pod = backend.pods[("default", "triad-0")]
    assert pod.node == "node0"
    assert pod.phase == "Running"
    # solved config annotated and parseable, placeholders replaced
    solved = pod.annotations[CFG_ANNOTATION]
    cfg = libconfig.loads(solved)
    assert all(c >= 0 for c in cfg.mods[0].dp[0].rx_cores)
    # NAD annotation names a host interface
    assert "eth" in pod.annotations[NAD_ANNOTATION]
    # audit trail events in reference order
    reasons = [e.reason for e in backend.events]
    assert reasons == [
        "StartedScheduling", "Scheduling", "PodCfgSuccess", "Scheduled"
    ]
    # node mirror claimed resources
    node = sched.nodes["node0"]
    assert node.total_pods() == 1
    assert node.free_gpu_count() == node.total_gpus() - 1
    assert node.mem.free_hugepages_gb == node.mem.ttl_hugepages_gb - 4


def test_gang_batch_via_check_pending():
    backend = make_backend(n_nodes=4)
    for i in range(8):
        backend.create_pod(f"triad-{i}", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()
    placed = [p.node for p in backend.pods.values()]
    assert all(placed)
    # spread across the 4 nodes (2 each: GPU-capacity per node is 4, but
    # rounds fan identical pods over distinct nodes)
    assert len(set(placed)) == 4


def test_delete_releases_resources():
    backend = make_backend()
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()
    node = sched.nodes["node0"]
    free_before = node.free_gpu_count()

    # drain watch events (create) then delete the pod
    list(backend.poll_watch_events())
    backend.delete_pod("triad-0")
    ctrl = Controller(backend, sched.nqueue)
    ctrl.run_once(now=100.0)
    sched.run_once()  # consumes the delete event

    assert node.total_pods() == 0
    assert node.free_gpu_count() == free_before + 1
    assert node.mem.free_hugepages_gb == node.mem.ttl_hugepages_gb


def test_restart_replay():
    """A new scheduler instance rebuilds claims from pod annotations
    (reference: NHDScheduler.py:161-172, README.md:85-87)."""
    backend = make_backend()
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    sched1 = make_scheduler(backend)
    sched1.check_pending_pods()
    state1 = {
        name: (sum(n.free_cpu_cores_per_numa()), n.free_gpu_count(),
               n.mem.free_hugepages_gb)
        for name, n in sched1.nodes.items()
    }

    sched2 = make_scheduler(backend)  # fresh instance, same cluster
    state2 = {
        name: (sum(n.free_cpu_cores_per_numa()), n.free_gpu_count(),
               n.mem.free_hugepages_gb)
        for name, n in sched2.nodes.items()
    }
    assert state1 == state2
    assert sched2.nodes["node0"].total_pods() == 1


def test_concurrent_commits_match_serial(monkeypatch):
    """NHD_COMMIT_WORKERS > 1 runs per-pod commit sequences on a pool:
    same binds, each pod's own event order preserved, and a bind failure
    still unwinds on the scheduler thread."""
    from nhd_tpu.scheduler import core as core_mod

    monkeypatch.setattr(core_mod, "COMMIT_WORKERS", 4)
    backend = make_backend(n_nodes=3)
    for i in range(6):
        backend.create_pod(f"gang-{i}", cfg_text=pod_cfg())
    backend.fail_bind_for.add(("default", "gang-3"))
    sched = make_scheduler(backend)
    sched.check_pending_pods()

    bound = {name: backend.pods[("default", name)].node
             for name in (f"gang-{i}" for i in range(6))}
    assert bound["gang-3"] is None          # failed bind
    assert sum(1 for n in bound.values() if n) == 5
    assert sched.perf["scheduled_total"] == 5
    assert sched.failed_schedule_count == 1
    # unwound: cluster books balance (5 pods' worth of claims only)
    assert sum(n.total_pods() for n in sched.nodes.values()) == 5
    # per-pod event sequence is still the reference order
    for i in (0, 1, 2, 4, 5):
        seq = [e.reason for e in backend.events if e.pod == f"gang-{i}"]
        assert seq == ["StartedScheduling", "Scheduling", "PodCfgSuccess",
                       "Scheduled"]


def test_scheduler_streams_past_node_threshold(monkeypatch):
    """Past NHD_STREAM_NODES the scheduler solves through the streaming
    tiler — same end result, bounded per-solve memory."""
    from nhd_tpu.scheduler import core as core_mod

    monkeypatch.setattr(core_mod, "STREAM_NODE_THRESH", 1)
    backend = make_backend(n_nodes=3)
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    backend.create_pod("triad-1", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()
    assert sched._stream is not None, "streaming path not engaged"
    for name in ("triad-0", "triad-1"):
        assert backend.pods[("default", name)].node is not None
    assert sched.perf["scheduled_total"] == 2


def test_missed_delete_reconciled_without_rescan():
    """Delete-safety (VERDICT r1 item 7): a pod deleted while the
    controller is down (no watch event) is released by the periodic
    mirror-vs-live diff — from the mirror's stored topology, without a
    full-cluster reset_resources."""
    backend = make_backend()
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    backend.create_pod("triad-1", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()
    node = sched.nodes["node0"]
    free_gpu_before = node.free_gpu_count()
    assert sched.nodes["node0"].total_pods() + sched.nodes["node1"].total_pods() == 2

    # controller down: the pod vanishes with no TRIAD_POD_DELETE event
    victim_node = backend.pods[("default", "triad-0")].node
    backend.delete_pod("triad-0", emit_watch=False)

    calls = []
    orig = sched.reset_resources
    sched.reset_resources = lambda: calls.append(1) or orig()
    # two-scan rule: the first scan only marks the vanished pod as a
    # suspect (a single listing may be transiently inconsistent on a
    # real API server); the second consecutive miss releases it
    sched.check_pending_pods()
    assert sched.nodes[victim_node].pod_present("triad-0", "default")
    assert ("default", "triad-0") in sched._missing_once
    sched.check_pending_pods()
    assert not calls, "reconcile fell back to a full rescan"

    vnode = sched.nodes[victim_node]
    assert not vnode.pod_present("triad-0", "default")
    assert ("default", "triad-0") not in sched.pod_state
    # claims actually freed (survivor still accounted)
    total_pods = sum(n.total_pods() for n in sched.nodes.values())
    assert total_pods == 1
    if victim_node == "node0":
        assert node.free_gpu_count() == free_gpu_before + 1


def test_missed_delete_and_recreate_same_name_reconciled():
    """Delete+recreate under the same name while the controller is down
    (TriadSet ordinal reuse): the uid diff releases the dead incarnation's
    claims AND lets the new Pending pod schedule in the same scan."""
    backend = make_backend()
    backend.create_pod("svc-0", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()
    old_node = backend.pods[("default", "svc-0")].node
    assert old_node is not None

    # silent delete + recreate: new uid, no watch events
    backend.delete_pod("svc-0", emit_watch=False)
    backend.create_pod("svc-0", cfg_text=pod_cfg(), emit_watch=False)

    sched.check_pending_pods()
    pod = backend.pods[("default", "svc-0")]
    assert pod.node is not None, "new incarnation stalled behind stale record"
    # exactly one incarnation's claims remain
    assert sum(n.total_pods() for n in sched.nodes.values()) == 1
    st = sched.pod_state[("default", "svc-0")]
    assert st["uid"] == pod.uid


def test_bind_failure_unwinds():
    backend = make_backend(n_nodes=1)
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    backend.fail_bind_for.add(("default", "triad-0"))
    sched = make_scheduler(backend)
    sched.check_pending_pods()

    pod = backend.pods[("default", "triad-0")]
    assert pod.node is None
    node = sched.nodes["node0"]
    assert node.total_pods() == 0
    assert node.free_gpu_count() == node.total_gpus()
    assert node.mem.free_hugepages_gb == node.mem.ttl_hugepages_gb
    assert sched.pod_state[("default", "triad-0")]["state"] == PodStatus.FAILED
    assert "FailedScheduling" in [e.reason for e in backend.events]


def test_cordon_and_maintenance_events():
    backend = make_backend()
    sched = make_scheduler(backend)
    ctrl = Controller(backend, sched.nqueue)

    backend.cordon_node("node0", True)
    ctrl.run_once(now=0.0)
    sched.run_once()
    assert not sched.nodes["node0"].active

    backend.cordon_node("node0", False)
    ctrl.run_once(now=0.1)
    sched.run_once()
    assert sched.nodes["node0"].active

    backend.update_node_labels(
        "node0", {"sigproc.viasat.io/maintenance": "draining"}
    )
    ctrl.run_once(now=0.2)
    sched.run_once()
    assert sched.nodes["node0"].maintenance

    backend.update_node_labels(
        "node0", {"sigproc.viasat.io/maintenance": "not_scheduled"}
    )
    ctrl.run_once(now=0.3)
    sched.run_once()
    assert not sched.nodes["node0"].maintenance


def test_group_update_event():
    backend = make_backend()
    sched = make_scheduler(backend)
    ctrl = Controller(backend, sched.nqueue)
    backend.update_node_labels("node0", {"NHD_GROUP": "edge.lab"})
    ctrl.run_once(now=0.0)
    sched.run_once()
    assert sched.nodes["node0"].groups == ["edge", "lab"]


def test_triadset_reconciliation():
    backend = make_backend(n_nodes=4)
    backend.add_triadset("ts1", "default", replicas=3,
                         service_name="triad", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    ctrl = Controller(backend, sched.nqueue)

    ctrl.run_once(now=10.0)  # creates triad-0..2
    assert {p.name for p in backend.pods.values()} == {
        "triad-0", "triad-1", "triad-2"
    }
    # pod-create watch events flow to the scheduler and get scheduled
    ctrl.run_once(now=20.0)
    for _ in range(3):
        sched.run_once()
    assert all(p.node for p in backend.pods.values())

    # killing one pod gets it recreated on the next timer pass
    backend.delete_pod("triad-1")
    ctrl.run_once(now=30.0)
    assert ("default", "triad-1") in backend.pods


def test_duplicate_create_event_ignored():
    backend = make_backend()
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    ctrl = Controller(backend, sched.nqueue)
    ctrl.run_once(now=0.0)
    sched.run_once()          # schedules from the create event
    pod = backend.pods[("default", "triad-0")]
    assert pod.node is not None
    node = sched.nodes[pod.node]
    gpu_free = node.free_gpu_count()

    # stale duplicate create with the same uid must be a no-op
    from nhd_tpu.scheduler.events import WatchItem, WatchType

    sched.nqueue.put(WatchItem(
        WatchType.TRIAD_POD_CREATE,
        pod={"ns": "default", "name": "triad-0", "uid": pod.uid},
    ))
    sched.run_once()
    assert node.free_gpu_count() == gpu_free
    assert node.total_pods() == 1


def test_rpc_stats_roundtrip():
    backend = make_backend()
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()

    reply: queue.Queue = queue.Queue()
    sched._parse_rpc_req(RpcMsgType.NODE_INFO, reply)
    stats = reply.get_nowait()
    assert len(stats) == 2
    assert stats[0]["totalpods"] + stats[1]["totalpods"] == 1

    sched._parse_rpc_req(RpcMsgType.POD_INFO, reply)
    pods = reply.get_nowait()
    assert len(pods) == 1
    assert pods[0]["podname"] == "triad-0"
    assert pods[0]["gpus"] and all(g >= 0 for g in pods[0]["gpus"])

    sched._parse_rpc_req(RpcMsgType.SCHEDULER_INFO, reply)
    assert reply.get_nowait() == 0


def test_unschedulable_pod_failed_count():
    backend = make_backend(n_nodes=1, spec=SynthNodeSpec(gpus_per_numa=0))
    backend.create_pod("triad-0", cfg_text=pod_cfg())  # wants a GPU
    sched = make_scheduler(backend)
    sched.check_pending_pods()
    assert backend.pods[("default", "triad-0")].node is None
    assert sched.failed_schedule_count == 1
    assert sched.pod_state[("default", "triad-0")]["state"] == PodStatus.FAILED


def test_foreign_scheduler_pods_ignored():
    """Pods naming another scheduler never reach the queue
    (reference: TriadController.py 'when' clauses)."""
    backend = make_backend()
    sched = make_scheduler(backend)
    ctrl = Controller(backend, sched.nqueue)
    backend.create_pod("other-0", cfg_text=pod_cfg(),
                       scheduler_name="default-scheduler")
    ctrl.run_once(now=0.0)
    assert sched.nqueue.empty()
    # and the periodic scan doesn't pick it up either
    sched.check_pending_pods()
    assert backend.pods[("default", "other-0")].node is None


def test_delete_release_is_targeted_not_full_rescan():
    """Deletes release via the event-carried config, not reset_resources."""
    backend = make_backend()
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    backend.create_pod("triad-1", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()
    resets = []
    sched.reset_resources = lambda: resets.append(1)  # sentinel

    list(backend.poll_watch_events())
    backend.delete_pod("triad-0")
    ctrl = Controller(backend, sched.nqueue)
    ctrl.run_once(now=100.0)
    sched.run_once()

    assert not resets, "delete fell back to a full cluster rescan"
    nodes_with_pods = [n for n in sched.nodes.values() if n.total_pods()]
    assert sum(n.total_pods() for n in nodes_with_pods) == 1


def test_uncordon_requires_scheduler_taint():
    backend = make_backend()
    # a foreign node without the scheduler taint
    from nhd_tpu.sim import SynthNodeSpec, make_node_labels

    spec = SynthNodeSpec(name="foreign")
    n = backend.add_node("foreign", make_node_labels(spec))
    n.taints = []  # not NHD-managed
    sched = make_scheduler(backend)
    assert not sched.nodes["foreign"].active
    ctrl = Controller(backend, sched.nqueue)
    backend.cordon_node("foreign", True)
    backend.cordon_node("foreign", False)
    ctrl.run_once(now=0.0)
    while not sched.nqueue.empty():
        sched.run_once()
    assert not sched.nodes["foreign"].active


def test_group_label_removal_resets_to_default():
    backend = make_backend()
    sched = make_scheduler(backend)
    ctrl = Controller(backend, sched.nqueue)
    backend.update_node_labels("node0", {"NHD_GROUP": "edge"})
    ctrl.run_once(now=0.0)
    sched.run_once()
    assert sched.nodes["node0"].groups == ["edge"]
    backend.update_node_labels("node0", {"NHD_GROUP": None})
    ctrl.run_once(now=0.1)
    sched.run_once()
    assert sched.nodes["node0"].groups == ["default"]


def test_kube_backend_config_gate(monkeypatch):
    """The real-cluster backend imports without the kubernetes package
    (it falls back to the in-repo restclient), but constructing it with
    no cluster to talk to raises a clear error naming the fix."""
    import pytest

    from nhd_tpu.k8s import kube

    try:
        import kubernetes  # noqa: F401
        pytest.skip("kubernetes installed; restclient gate not exercised")
    except ImportError:
        pass
    # neither in-cluster env nor a kubeconfig
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    monkeypatch.delenv("KUBERNETES_SERVICE_PORT", raising=False)
    monkeypatch.setenv("KUBECONFIG", "/nonexistent-kubeconfig")
    with pytest.raises(RuntimeError, match="no cluster configuration"):
        kube.KubeClusterBackend()


def test_threaded_scheduler_lifecycle():
    """The real thread entry points: scheduler + controller threads bind a
    pod end to end, then stop cleanly (reference process model, bin/nhd)."""
    import time as time_mod

    backend = make_backend(n_nodes=2)
    backend.add_triadset("ts", "default", replicas=2,
                         service_name="live", cfg_text=pod_cfg())
    sched = Scheduler(backend, WatchQueue(), queue.Queue(),
                      respect_busy=False)
    ctrl = Controller(backend, sched.nqueue, poll_interval=0.01)
    sched.start()
    ctrl.start()
    try:
        deadline = time_mod.time() + 20
        while time_mod.time() < deadline:
            pods = [p for p in backend.pods.values() if p.node]
            if len(pods) == 2:
                break
            time_mod.sleep(0.05)
        assert len([p for p in backend.pods.values() if p.node]) == 2
    finally:
        sched.stop()
        ctrl.stop()
        sched.join(timeout=5)
        ctrl.join(timeout=5)
    assert not sched.is_alive() and not ctrl.is_alive()


def test_triadset_status_updated():
    """The controller writes status.replicas for the scale subresource
    (declared but never updated in the reference)."""
    backend = make_backend(n_nodes=2)
    backend.add_triadset("ts1", "default", replicas=2,
                         service_name="st", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    ctrl = Controller(backend, sched.nqueue)
    ctrl.run_once(now=10.0)   # creates pods AND reports them immediately
    assert backend.triadsets[0]["status_replicas"] == 2


def test_run_once_serves_rpc_queue():
    """The main loop's RPC branch answers queued stats requests
    (reference: NHDScheduler.py:477-479)."""
    backend = make_backend()
    backend.create_pod("triad-0", cfg_text=pod_cfg())
    sched = make_scheduler(backend)
    sched.check_pending_pods()
    reply: queue.Queue = queue.Queue()
    sched.rpcq.put((RpcMsgType.SCHEDULER_INFO, reply))
    sched.run_once()
    assert reply.get_nowait() == 0
