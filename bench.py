#!/usr/bin/env python
"""Benchmark: batched TPU scheduling vs the serial per-pod matcher walk.

Headline config is BASELINE.json config 4: 10k pending pods × 1k nodes with
mixed node groups, scheduled as gang batches. The baseline is this repo's
serial oracle (a faithful reimplementation of the reference matcher loop,
solver/oracle.py) timed on a sample of the same workload and extrapolated —
the reference itself publishes no numbers (BASELINE.md).

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
Everything else (per-config detail, platform notes) goes to stderr.

Environment knobs:
    NHD_BENCH_PLATFORM=cpu    skip the TPU probe, run on CPU
    NHD_BENCH_STRETCH=1       also run the 100k × 10k federation config

Busy back-off (one GPU pod per node per 30 s, reference Matcher.py:103-111)
is disabled on BOTH sides: it is an operational rate limit, not solver
work, and with it on neither side can schedule more than one pod per node.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _pick_platform() -> str:
    """Probe TPU availability in a subprocess (a wedged tunnel must not hang
    the bench); fall back to CPU with a note."""
    if os.environ.get("NHD_BENCH_PLATFORM"):
        return os.environ["NHD_BENCH_PLATFORM"]
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=240,
        )
    except subprocess.TimeoutExpired:
        _log("bench: TPU probe timed out (tunnel wedged?); falling back to CPU")
        return "cpu"
    if probe.returncode == 0:
        plat = probe.stdout.strip().splitlines()[-1]
        _log(f"bench: TPU probe OK (platform={plat})")
        return "default"
    _log("bench: TPU backend unavailable; falling back to CPU\n"
         + probe.stderr.strip()[-300:])
    return "cpu"


def _init_jax(platform: str):
    import jax

    if platform == "cpu":
        try:
            from jax._src import xla_bridge as _xb

            for name in [k for k in _xb._backend_factories if k != "cpu"]:
                _xb._backend_factories.pop(name, None)
        except Exception:
            pass
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", "/tmp/nhd_tpu_jax_cache")
    return jax


def run_batch(nodes, reqs, *, warm: bool = True):
    from nhd_tpu.solver import BatchItem, BatchScheduler

    sched = BatchScheduler(respect_busy=False, register_pods=False)
    items = [BatchItem(("ns", f"p{i}"), r) for i, r in enumerate(reqs)]
    if warm:
        # compile warmup at the exact padded shapes: a dry-run round solves
        # the same buckets against the same cluster without mutating it
        sched.schedule(nodes, items, now=0.0, apply=False)
    t0 = time.perf_counter()
    results, stats = sched.schedule(nodes, items, now=0.0)
    wall = time.perf_counter() - t0
    placed = sum(1 for r in results if r.node)
    return wall, placed, stats


def run_serial_baseline(nodes, reqs, sample: int):
    """Seconds-per-pod of the serial oracle loop (match + physical
    assignment), measured on a sample of the same workload."""
    from nhd_tpu.sim.requests import request_to_topology
    from nhd_tpu.solver import find_node

    t0 = time.perf_counter()
    for r in reqs[:sample]:
        m = find_node(nodes, r, now=0.0, respect_busy=False)
        if m is None:
            continue
        top = request_to_topology(r)
        try:
            nodes[m.node].assign_physical_ids(m.mapping, top)
        except Exception:
            continue
    return (time.perf_counter() - t0) / max(sample, 1)


def bench_config(name, n_pods, n_nodes, groups, baseline_sample=40):
    from nhd_tpu.sim.workloads import bench_cluster, workload_mix

    reqs = workload_mix(n_pods, groups)
    wall, placed, stats = run_batch(bench_cluster(n_nodes, groups), reqs)

    per_pod = run_serial_baseline(bench_cluster(n_nodes, groups), reqs,
                                  baseline_sample)
    baseline_wall = per_pod * n_pods
    speedup = baseline_wall / wall if wall > 0 else 0.0
    _log(
        f"bench[{name}]: {n_pods} pods x {n_nodes} nodes -> "
        f"placed {placed} in {wall:.3f}s ({placed / wall:.0f} pods/s, "
        f"rounds={stats.rounds}, solve={stats.solve_seconds:.3f}s, "
        f"select={stats.select_seconds:.3f}s, assign={stats.assign_seconds:.3f}s); "
        f"serial baseline {per_pod * 1e3:.2f} ms/pod -> est {baseline_wall:.1f}s; "
        f"speedup {speedup:.0f}x"
    )
    return {"wall": wall, "placed": placed, "speedup": speedup}


def main() -> None:
    platform = _pick_platform()
    jax = _init_jax(platform)
    _log(f"bench platform: {jax.devices()[0].platform} "
         f"({len(jax.devices())} device(s))")

    bench_config("cfg1:100x32", 100, 32, ["default"], baseline_sample=30)
    bench_config("cfg2:1kx256", 1000, 256, ["default"], baseline_sample=30)

    from nhd_tpu.utils.tracing import profiler_trace

    with profiler_trace(os.environ.get("NHD_BENCH_PROFILE")):
        result = bench_config(
            "cfg3:10kx1k", 10_000, 1_000, ["default", "edge", "batch"],
            baseline_sample=40,
        )
    if os.environ.get("NHD_BENCH_STRETCH"):
        bench_config(
            "cfg4:100kx10k", 100_000, 10_000,
            ["default", "edge", "batch", "fed1", "fed2"], baseline_sample=10,
        )

    print(json.dumps({
        "metric": "pods_matched_per_sec_10k_pods_x_1k_nodes",
        "value": round(result["placed"] / result["wall"], 1),
        "unit": "pods/s",
        "vs_baseline": round(result["speedup"], 1),
    }))


if __name__ == "__main__":
    main()
