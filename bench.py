#!/usr/bin/env python
"""Benchmark: batched TPU scheduling vs the serial per-pod matcher walk.

Headline config is BASELINE.json config 4: 10k pending pods × 1k nodes with
mixed node groups, scheduled as gang batches — on a capacity-matched
cluster that absorbs every pod (cfg4), with the NIC-saturated variant
(cfg3) reported alongside as the contention benchmark. The 100k × 10k
federation config (BASELINE config 5) runs by default through the
streaming solver (solver/streaming.py). The baseline is this repo's
serial oracle (a faithful reimplementation of the reference matcher loop,
solver/oracle.py) timed on a sample of the same workload and extrapolated —
the reference itself publishes no numbers (BASELINE.md).

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
Everything else (per-config detail, platform notes) goes to stderr.

Environment knobs:
    NHD_BENCH_PLATFORM=cpu    skip the TPU probe, run on CPU
    NHD_BENCH_SKIP_FED=1      skip the 100k × 10k federation config

Busy back-off (one GPU pod per node per 30 s, reference Matcher.py:103-111)
is disabled on BOTH sides: it is an operational rate limit, not solver
work, and with it on neither side can schedule more than one pod per node.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _pick_platform() -> str:
    """Probe TPU availability in a subprocess (a wedged tunnel must not hang
    the bench), falling back to CPU on timeout.

    Runs FIRST in main() — before any jax work in this process — so the
    probe can't be poisoned by an earlier backend init, and a healthy
    tunnel is claimed by the real bench immediately after. No retry: a
    probe timeout IS the wedged-tunnel signature (once wedged, every
    claim blocks forever — observed >6h; healthy init takes single-digit
    seconds, so 90s has ample margin)."""
    if os.environ.get("NHD_BENCH_PLATFORM"):
        return os.environ["NHD_BENCH_PLATFORM"]
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=90,
        )
    except subprocess.TimeoutExpired:
        _log("bench: TPU probe timed out (tunnel wedged); falling back to "
             "CPU. Round-5 TPU evidence is preserved at "
             "docs/bench/BENCH_TPU_r5_*.log (cfg4 119 ms / 84.3k pods/s "
             "rounds=1; cfg5 1.86-2.70 s, p99 bind 1.2-1.5 s; daemon p99 "
             "8.6 ms)")
        return "cpu"
    if probe.returncode == 0:
        plat = probe.stdout.strip().splitlines()[-1]
        if plat == "tpu":
            _log(f"bench: TPU probe OK (platform={plat})")
            return "default"
        # a healthy probe on a TPU-less box reports its cpu backend
        _log(f"bench: probe OK but platform={plat}; running on CPU")
        return "cpu"
    _log("bench: TPU backend unavailable; falling back to CPU\n"
         + probe.stderr.strip()[-300:])
    return "cpu"


def _init_jax(platform: str):
    import jax

    if platform == "cpu":
        from nhd_tpu.utils import force_cpu_backend

        force_cpu_backend(jax)
    jax.config.update("jax_compilation_cache_dir", "/tmp/nhd_tpu_jax_cache")
    return jax


def run_batch(nodes, reqs, *, warm: bool = True):
    from nhd_tpu.solver import BatchItem, BatchScheduler

    sched = BatchScheduler(respect_busy=False, register_pods=False)
    items = [BatchItem(("ns", f"p{i}"), r) for i, r in enumerate(reqs)]
    if warm:
        # compile warmup by running the REAL schedule on the REAL cluster,
        # then resetting allocation state in place (the scheduler's own
        # drift-repair op, HostNode.reset_resources): a dry run
        # (apply=False) would warm the solves but never the donated row
        # scatters of the device-resident path, and a deepcopied warm
        # cluster would invalidate the id-keyed static caches
        # (EncodeStatic, FastCluster._build_static) that the production
        # scheduler — which holds one node set for its lifetime — always
        # hits. The measured batch is cold allocation state, warm process.
        sched.schedule(nodes, items, now=0.0)
        for n in nodes.values():
            n.reset_resources()
    t0 = time.perf_counter()
    results, stats = sched.schedule(nodes, items, now=0.0)
    wall = time.perf_counter() - t0
    placed = sum(1 for r in results if r.node)
    return wall, placed, stats, results


def run_serial_baseline(nodes, reqs, sample: int):
    """Seconds-per-pod of the serial oracle loop (match + physical
    assignment), measured on a sample of the same workload."""
    from nhd_tpu.sim.requests import request_to_topology
    from nhd_tpu.solver import find_node

    t0 = time.perf_counter()
    for r in reqs[:sample]:
        m = find_node(nodes, r, now=0.0, respect_busy=False)
        if m is None:
            continue
        top = request_to_topology(r)
        try:
            nodes[m.node].assign_physical_ids(m.mapping, top)
        except Exception:
            continue
    return (time.perf_counter() - t0) / max(sample, 1)


def run_stream(nodes, reqs, *, tile_nodes=None, chunk_pods=None,
               placement="routed"):
    """Schedule through the streaming solver (cfg5 federation path).

    tile_nodes is backend-dependent. On an accelerator it is an
    HBM-budget choice: a 16k-node tile's solve fits a 16 GB chip with
    room to spare, and every extra tile costs a relay flush plus a
    serialized host tail — the 10k-node federation in ONE tile (one
    megaround, one flush) measured 2.4 s / p99 1.2 s vs 2.9 s /
    p99 2.3 s for three 4096-node tiles (r5). On the CPU backend the
    giant tile INVERTS (12.3 s vs ~7 s): the host pays the solve
    compute directly, so smaller tiles with pipelined workers win.
    Smaller tiles also remain the right call for federations larger
    than device memory or per-region multi-host splits
    (solver/streaming.py docstring).
    chunk_pods is backend-dependent: an accelerator pays per-dispatch
    relay latency, so one big chunk minimizes (tile, chunk) sub-calls
    (measured 5.8 s vs 6.6 s on the tunnel TPU); on CPU a 50k chunk
    edges out one 100k chunk (6.0 s vs 6.3 s).

    A warmup pass on a tile-shaped throwaway cluster takes the solver
    compiles out of the timed run — same policy as cfg1-4, whose shapes
    are warmed by the earlier configs; true cold behavior is what
    bench[cold-start] reports. The warm cluster MUST be the same node
    family as the measured one: solver programs key on the (U, K)
    paddings, and cap_cluster's K=7 NIC shape is not bench_cluster's
    K=2 — warming the wrong family left every megaround compile inside
    the timed run (r4/r5: multi-second spec_dispatch).
    """
    import jax

    from nhd_tpu.sim.workloads import cap_cluster, workload_mix
    from nhd_tpu.solver import BatchItem, StreamingScheduler

    accel = jax.default_backend() != "cpu"
    if tile_nodes is None:
        tile_nodes = 16384 if accel else 4096
    if chunk_pods is None:
        chunk_pods = 100_000 if accel else 50_000
    sched = StreamingScheduler(
        tile_nodes=tile_nodes, chunk_pods=chunk_pods, placement=placement,
        respect_busy=False, register_pods=False,
    )

    # warm-cluster sizing must reproduce the REAL run's tile shapes (the
    # compiled programs key on the padded node count): one full tile plus
    # the real run's remainder tile, if any
    rem = len(nodes) % tile_nodes
    warm_n = min(len(nodes), tile_nodes + rem if rem else tile_nodes)
    warm_nodes = cap_cluster(
        warm_n, ["default", "edge", "batch", "fed1", "fed2"],
    )
    warm_reqs = workload_mix(4096, ["default", "edge", "batch", "fed1",
                                    "fed2"])
    StreamingScheduler(
        tile_nodes=tile_nodes, chunk_pods=chunk_pods, placement=placement,
        respect_busy=False, register_pods=False,
    ).schedule(
        warm_nodes, [BatchItem(("w", f"w{i}"), r)
                     for i, r in enumerate(warm_reqs)], now=0.0,
    )

    items = [BatchItem(("ns", f"p{i}"), r) for i, r in enumerate(reqs)]
    # heap pinning for the sweep lives in StreamingScheduler.schedule
    # itself (gc.freeze over the federation mirror) — the bench adds no
    # gc management of its own
    t0 = time.perf_counter()
    results, stats = sched.schedule(nodes, items, now=0.0)
    wall = time.perf_counter() - t0
    placed = sum(1 for r in results if r.node)
    return wall, placed, stats, results


def bench_churn(name, *, n_nodes, events_per_sec, sim_seconds,
                groups, tile_nodes=4096, round_dt=5.0, seed=7):
    """Sustained-churn leg (cfg7): *events_per_sec* × *sim_seconds* of
    simulated event stream — pod creates/deletes plus node cordon /
    maintenance / group flips — against an *n_nodes* cluster whose
    packed/device state is maintained INCREMENTALLY (ClusterDelta +
    persistent streaming tile contexts), not re-encoded per round.

    The stream is processed in rounds of ``round_dt`` simulated seconds:
    each round folds its node churn in as row deltas (refresh_context →
    row patches + device row scatters), then batch-schedules the round's
    creates through the persistent contexts. Binds/s and p99
    time-to-bind come from the existing bind-latency HISTOGRAM (each
    placed pod observes its batch-relative bind time — a sustained
    stream's steady-state figure, not a one-shot backlog drain), and the
    host per-round delta cost is asserted O(changed rows) via the
    nhd_device_state_* counters: a per-round wholesale re-encode/upload
    would tick rows_uploaded at rounds × n_nodes and fails the leg.
    """
    import random
    import re as re_mod

    from nhd_tpu.k8s.retry import API_COUNTERS
    from nhd_tpu.obs.histo import observe, render_all, reset_all
    from nhd_tpu.sim.requests import request_to_topology
    from nhd_tpu.sim.workloads import cap_cluster, workload_mix
    from nhd_tpu.solver import BatchItem, StreamingScheduler

    reset_all()
    rng = random.Random(seed)
    nodes = cap_cluster(n_nodes, groups)
    names = list(nodes)
    # routed placement: the federation posture (cfg5's production
    # setting) — tiles work concurrently, spill cascades
    sched = StreamingScheduler(
        tile_nodes=tile_nodes, chunk_pods=max(events_per_sec, 4096),
        placement="routed",
        persistent=True, respect_busy=False, register_pods=True,
        device_state=True,
    )
    # fixed request catalog (the workload mix), cycled per create — the
    # solver dedupes identical requests into types, so bucket shapes
    # stay stable round to round (no recompiles mid-stream)
    catalog = workload_mix(256, groups)

    # warm the solver compiles on a THROWAWAY same-shaped cluster (same
    # policy as every other leg: the measured stream is cold allocation
    # state, warm process — sustained-rate figures must not eat the
    # first-round trace+compile, which bench[cold-start] reports)
    warm_nodes = cap_cluster(n_nodes, groups)
    warm = StreamingScheduler(
        tile_nodes=tile_nodes, chunk_pods=max(events_per_sec, 4096),
        persistent=True, respect_busy=False, register_pods=True,
        device_state=True,
    )
    warm_n_pods = max(int(events_per_sec * round_dt) // 3, 8)
    for _ in range(2):
        warm.schedule(
            warm_nodes,
            [
                BatchItem(
                    ("warm", f"w{i}"), catalog[i % len(catalog)],
                    topology=request_to_topology(catalog[i % len(catalog)]),
                )
                for i in range(warm_n_pods)
            ],
            now=0.0,
        )
    del warm, warm_nodes

    c0 = API_COUNTERS.snapshot()

    total_events = events_per_sec * sim_seconds
    events_per_round = max(int(events_per_sec * round_dt), 1)

    # the event STREAM is pre-generated (its rng draws, BatchItems and
    # request topologies are the bench's INPUT, not the scheduler's
    # work); processing it — releases, row deltas, solves, binds — is
    # what the timed loop measures. Event mix: pod churn dominates
    # (creates 30% / deletes 30%), node events are the rest (cordon /
    # maintenance / group moves within the interned set — a NEW group
    # name is a legitimate fallback, but a 10k ev/s rebuild storm is not
    # this leg's claim).
    stream: list = []
    pod_seq = 0
    for _ in range(total_events):
        roll = rng.random()
        if roll < 0.30:
            pod_seq += 1
            req = catalog[pod_seq % len(catalog)]
            stream.append(("create", BatchItem(
                ("churn", f"c{pod_seq}"), req,
                topology=request_to_topology(req),
            )))
        elif roll < 0.60:
            stream.append(("delete", rng.random()))
        elif roll < 0.76:
            stream.append(("cordon", rng.choice(names)))
        elif roll < 0.92:
            stream.append(("maint", rng.choice(names)))
        else:
            stream.append(("group", rng.choice(names), rng.choice(groups)))

    placed_keys: list = []            # (key, node_name, topology)
    maint_state: dict = {}
    binds = 0
    events_done = 0
    sim_t = 0.0
    round_no = 0
    note = sched.note_nodes
    t0 = time.perf_counter()
    while events_done < total_events:
        round_no += 1
        sim_t += round_dt
        n_ev = min(events_per_round, total_events - events_done)
        creates = []
        for ev in stream[events_done : events_done + n_ev]:
            kind = ev[0]
            if kind == "create":
                creates.append(ev[1])
            elif kind == "delete":
                if not placed_keys:
                    continue  # stream no-op: nothing bound yet
                j = min(int(ev[1] * len(placed_keys)), len(placed_keys) - 1)
                placed_keys[j], placed_keys[-1] = (
                    placed_keys[-1], placed_keys[j]
                )
                key, node_name, top = placed_keys.pop()
                node = nodes[node_name]
                node.release_from_topology(top)
                node.remove_scheduled_pod(key[1], key[0])
                note((node_name,))
            elif kind == "cordon":
                nm = ev[1]
                nodes[nm].active = not nodes[nm].active
                note((nm,))
            elif kind == "maint":
                nm = ev[1]
                nodes[nm].maintenance = not maint_state.get(nm, False)
                maint_state[nm] = nodes[nm].maintenance
                note((nm,))
            else:
                nm = ev[1]
                nodes[nm].set_groups(ev[2])
                note((nm,))
        events_done += n_ev
        if creates:
            results, stats = sched.schedule(nodes, creates, now=sim_t)
            ends = stats.round_end_seconds
            for item, r in zip(creates, results):
                if r.node is None:
                    continue
                binds += 1
                placed_keys.append((item.key, r.node, item.topology))
                lat = (
                    ends[r.round_no]
                    if 0 <= r.round_no < len(ends) else 0.0
                )
                observe("bind_latency_seconds", lat)
    wall = time.perf_counter() - t0

    c1 = API_COUNTERS.snapshot()
    rows_up = c1["device_state_rows_uploaded_total"] - (
        c0["device_state_rows_uploaded_total"]
    )
    deltas = c1["device_state_deltas_total"] - c0["device_state_deltas_total"]
    rebuilds = c1["device_state_full_rebuilds_total"] - (
        c0["device_state_full_rebuilds_total"]
    )
    rows_per_round = rows_up / max(round_no, 1)
    # the O(changed rows) assertion: every uploaded row must be paid for
    # by an actual change — a row patch (node event, release) or a claim
    # (≤ one staged row per bind) — with a 2x slack for rows that change
    # twice per round, plus the full-row budget of any sanctioned
    # rebuild. A wholesale per-round re-upload (rounds × tiles × tile
    # rows, regardless of changes) blows through this by construction.
    changed_budget = (
        2 * (deltas + binds) + rebuilds * n_nodes + round_no * 64
    )
    if rows_up > changed_budget:
        raise RuntimeError(
            f"bench[{name}]: device upload is not O(changed rows): "
            f"{rows_up:.0f} rows uploaded vs a changed-row budget of "
            f"{changed_budget:.0f} ({deltas:.0f} patches + {binds} binds "
            f"+ {rebuilds:.0f} rebuilds) — the incremental state is not "
            "engaging"
        )

    # p99 time-to-bind scraped from the bind-latency histogram —
    # INTERPOLATED within the covering bucket (obs/histo.py
    # quantile_from_buckets): the raw bucket upper edge made any
    # regression inside a bucket invisible and crossing an edge read
    # as a cliff (a 251 ms p99 reported as 500.0)
    from nhd_tpu.obs.histo import quantile_from_buckets

    buckets = []
    for line in "\n".join(render_all()).splitlines():
        m = re_mod.match(
            r'nhd_bind_latency_seconds_bucket\{le="([^"]+)"\} (\d+)', line
        )
        if m:
            edge = (float("inf") if m.group(1) == "+Inf"
                    else float(m.group(1)))
            buckets.append((edge, int(m.group(2))))
    p99_ms = quantile_from_buckets(buckets, 0.99) * 1e3

    ev_rate = events_done / wall if wall > 0 else 0.0
    _log(
        f"bench[{name}]: {events_done} events ({events_per_sec}/s x "
        f"{sim_seconds}s simulated) over {n_nodes} nodes -> processed at "
        f"{ev_rate:.0f} events/s wall ({wall:.1f}s), {binds} binds "
        f"({binds / wall:.0f} binds/s), p99 bind <= {p99_ms:.1f}ms; "
        f"delta economy: {deltas:.0f} row patches, {rows_up:.0f} rows "
        f"uploaded ({rows_per_round:.0f}/round vs {n_nodes}/round "
        f"wholesale), {rebuilds:.0f} full rebuilds"
    )
    rec = {
        "wall": wall, "placed": binds,
        "speedup": 0.0, "rounds": round_no,
        "phases": {
            # seconds-shaped figures only (bench_diff's phase gate
            # compares relative): total churn wall attributed per round
            "churn_round_mean": wall / max(round_no, 1),
        },
        "p99_bind_ms": p99_ms,
        "churn": {
            "events_total": events_done,
            "events_per_sec_simulated": events_per_sec,
            "events_per_sec_sustained": round(ev_rate, 1),
            "sim_seconds": sim_seconds,
            "binds_per_sec": round(binds / wall, 1) if wall > 0 else 0.0,
            "rows_uploaded_total": rows_up,
            "rows_uploaded_per_round": round(rows_per_round, 1),
            "row_patches_total": deltas,
            "full_rebuilds": rebuilds,
        },
    }
    return rec


def bench_ingress(name, *, n_pods, n_nodes, waves=20, seed=11):
    """Ingress admission leg (cfg9, ISSUE 20): a pre-generated
    multi-tenant create stream — one abusive tenant at ~70% of the
    volume, three behaved tenants sharing the rest — pushed through the
    REAL front door: controller batched decode → AdmissionQueue (lanes,
    DRR, shed ladder) → the scheduler's batched admitted drain, on the
    fake backend with a deliberately scarce per-wave drain so the ladder
    actually escalates.

    Reports (a) the batched-decode micro-figure — µs per watch event
    through Controller.decode_batch, the satellite pin for the
    fold-N-events-per-wakeup decode path — and (b) the ladder economy:
    admitted/deferred/readmitted/shed counts and rates plus binds/s
    through the admitted path. ``verdictless_sheds`` (refusals without
    an AdmissionShed event) must be ZERO — tools/bench_diff.py gates it
    hard, alongside relative gates on decode cost and the rates.

    Full scale is NHD_SPMD_PODS/NODES-parameterized like cfg6; the
    smoke variant (ingress-smoke) runs a CPU-degraded fixed shape on
    every `make check`.
    """
    import queue as queue_mod
    import random

    from nhd_tpu.ingress import AdmissionQueue
    from nhd_tpu.k8s.fake import FakeClusterBackend
    from nhd_tpu.scheduler.controller import Controller
    from nhd_tpu.scheduler.core import Scheduler
    from nhd_tpu.sim import SynthNodeSpec, make_node_labels, make_triad_config

    #: the leg's overload posture (cf. chaos_storm._TENANT_CELL_ENV):
    #: shallow lanes + a low sustained rate, so the stream exercises
    #: every rung instead of admitting everything
    leg_env = {
        "NHD_ADMIT": "1",
        "NHD_ADMIT_BATCH": "8",
        "NHD_ADMIT_TENANT_CAP": "32",
        "NHD_ADMIT_RATE": "2",
    }
    prior = {k: os.environ.get(k) for k in leg_env}
    os.environ.update(leg_env)
    try:
        rng = random.Random(seed)
        backend = FakeClusterBackend()
        for i in range(n_nodes):
            spec = SynthNodeSpec(name=f"ing-node{i:04d}")
            backend.add_node(spec.name, make_node_labels(spec),
                             hugepages_gb=spec.hugepages_gb)
        simt = [0.0]
        q = AdmissionQueue(clock=lambda: simt[0])
        sched = Scheduler(backend, q, queue_mod.Queue(), respect_busy=False)
        sched.build_initial_node_list()
        controller = Controller(backend, q)
        # one fixed request shape: the solver dedupes identical requests
        # into one type, so the leg measures ingress + drain economy,
        # not recompiles (and the warm-up bind below pays the one trace)
        cfg = make_triad_config(
            n_groups=1, gpus_per_group=0, cpu_workers=1, hugepages_gb=2
        )
        backend.create_pod("ing-warm", cfg_text=cfg)
        controller.decode_batch(list(backend.poll_watch_events()))
        while not q.empty():
            sched.run_once()
        backend.delete_pod("ing-warm")
        controller.decode_batch(list(backend.poll_watch_events()))
        while not q.empty():
            sched.run_once()

        base_stats = dict(q.stats)  # warm-up traffic doesn't ride the rates
        tenants = ["tenant-abuse", "tenant-a", "tenant-b", "tenant-c"]
        per_wave = max(n_pods // waves, 4)
        pod_seq = 0
        events_total = 0
        decode_wall = 0.0
        drain_wall = 0.0
        for _ in range(waves):
            simt[0] += 1.0
            for _ in range(per_wave):
                pod_seq += 1
                ns = (tenants[0] if rng.random() < 0.7
                      else rng.choice(tenants[1:]))
                backend.create_pod(
                    f"ing-{pod_seq}", ns, cfg_text=cfg,
                    tier=1 if rng.random() < 0.1 else 0,
                )
            events = list(backend.poll_watch_events())
            events_total += len(events)
            t0 = time.perf_counter()
            controller.decode_batch(events)
            decode_wall += time.perf_counter() - t0
            # scarce drain: ONE scheduler turn per wave (folding up to
            # batch_limit() creates) — arrivals outpace it, which is
            # what walks the stream up the ladder
            t0 = time.perf_counter()
            if not q.empty():
                sched.run_once()
            sched._publish_shed_verdicts()
            drain_wall += time.perf_counter() - t0
            # short jobs between waves (untimed): bound pods complete
            # and their DELETE events drain through the control lane, so
            # capacity stays free and the leg measures queue economy,
            # not cluster saturation
            for p in [p for p in backend.pods.values() if p.node]:
                backend.delete_pod(p.name, p.namespace)
            controller.decode_batch(list(backend.poll_watch_events()))
            while q.depths()["control"] > 0:
                sched.run_once()
        # post-storm recovery (timed as drain): pressure falls, deferred
        # pods re-admit, the backlog drains
        t0 = time.perf_counter()
        simt[0] += 30.0
        while not q.empty():
            sched.run_once()
        sched._publish_shed_verdicts()
        drain_wall += time.perf_counter() - t0

        binds = sum(
            1 for (ns, _p, _u, _n, _e, _l) in backend.bind_log
            if ns.startswith("tenant-")
        )
        shed_events = sum(
            1 for e in backend.events if e.reason == "AdmissionShed"
        )
        stats = {k: v - base_stats.get(k, 0) for k, v in q.stats.items()}
        verdictless = stats["shed"] - shed_events
        wall = decode_wall + drain_wall
        decode_us = (decode_wall / events_total * 1e6) if events_total else 0.0
        _log(
            f"bench[{name}]: {pod_seq} creates over {len(tenants)} tenants "
            f"({waves} waves, {n_nodes} nodes) -> decode "
            f"{decode_us:.1f}us/event ({events_total} events), "
            f"{binds} binds ({binds / drain_wall:.0f}/s drain), ladder: "
            f"{stats['admitted']} admitted / {stats['deferred']} deferred "
            f"(+{stats['readmitted']} readmitted) / {stats['shed']} shed "
            f"({verdictless} verdictless)"
        )
        return {
            "wall": wall, "placed": binds, "speedup": 0.0, "rounds": waves,
            "phases": {
                "ingress_decode_per_event": (
                    decode_wall / events_total if events_total else 0.0
                ),
                "ingress_drain_mean": drain_wall / max(waves, 1),
            },
            "p99_bind_ms": 0.0,
            "ingress": {
                "creates_total": pod_seq,
                "events_total": events_total,
                "decode_us_per_event": round(decode_us, 2),
                "binds_per_sec": (
                    round(binds / drain_wall, 1) if drain_wall > 0 else 0.0
                ),
                "admitted": stats["admitted"],
                "deferred": stats["deferred"],
                "readmitted": stats["readmitted"],
                "shed": stats["shed"],
                "shed_rate": round(stats["shed"] / max(pod_seq, 1), 3),
                "admit_rate": round(stats["admitted"] / max(pod_seq, 1), 3),
                "verdictless_sheds": verdictless,
            },
        }
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def bench_config(name, n_pods, n_nodes, groups, baseline_sample=40,
                 cluster_fn=None, runner=run_batch):
    from nhd_tpu.sim.workloads import bench_cluster, workload_mix

    cluster_fn = cluster_fn or bench_cluster
    reqs = workload_mix(n_pods, groups)
    wall, placed, stats, results = runner(cluster_fn(n_nodes, groups), reqs)

    per_pod = run_serial_baseline(cluster_fn(n_nodes, groups), reqs,
                                  baseline_sample)
    baseline_wall = per_pod * n_pods
    speedup = baseline_wall / wall if wall > 0 else 0.0
    _log(
        f"bench[{name}]: {n_pods} pods x {n_nodes} nodes -> "
        f"placed {placed} in {wall:.3f}s ({placed / wall:.0f} pods/s, "
        f"rounds={stats.rounds}, solve={stats.solve_seconds:.3f}s, "
        f"select={stats.select_seconds:.3f}s, assign={stats.assign_seconds:.3f}s, "
        f"p99 bind {stats.bind_latency_percentile(results, 99) * 1e3:.0f}ms); "
        f"serial baseline {per_pod * 1e3:.2f} ms/pod -> est {baseline_wall:.1f}s; "
        f"speedup {speedup:.0f}x"
    )
    if stats.phases:
        # the overhead war's tracked metric: per-phase wall + a
        # device-utilization proxy (solve-active / wall), WALL-CLAMPED:
        # concurrent paths (streaming tile workers) sum solve_seconds as
        # thread time, which can exceed wall — an unclamped figure read
        # 108% exactly where the overhead war mattered most (r4). 100%
        # means "solves were in flight for the whole wall, overlapped".
        detail = " ".join(
            f"{k}={v * 1e3:.0f}ms" for k, v in sorted(stats.phases.items())
        )
        util = 100.0 * stats.solve_seconds / wall if wall > 0 else 0.0
        _log(
            f"bench[{name}]: phases {detail}; "
            f"solve-active/wall {min(util, 100.0):.0f}%"
        )
    return {
        "wall": wall, "placed": placed, "speedup": speedup,
        "rounds": stats.rounds,
        # the coarse solve/select/assign trio joins the fine-grained
        # phase names (disjoint key sets): tools/bench_diff.py gates on
        # "solve" and must find it in every artifact, legacy included
        "phases": {
            "solve": stats.solve_seconds,
            "select": stats.select_seconds,
            "assign": stats.assign_seconds,
            **stats.phases,
        },
        "p99_bind_ms": stats.bind_latency_percentile(results, 99) * 1e3,
    }


def _hetero_preempt_cell() -> int:
    """Tiered-preemption micro-cell for the hetero leg: saturate a tiny
    fake cluster with tier-0 pods, submit tier-2 pods, count the fenced
    evictions the policy engine executes. Returns the eviction count
    (bench artifact: hetero.preemptions — a zero means the preemption
    path went dead)."""
    from nhd_tpu.sim.synth import make_triad_config

    backend, sched = make_fake_sched(2, "pre", hugepages_gb=8)
    cfg = make_triad_config(cpu_workers=2, hugepages_gb=4)
    low = []
    for i in range(5):
        p = backend.create_pod(f"low{i}", cfg_text=cfg, tier=0)
        low.append((p.name, p.namespace, p.uid))
    sched.attempt_scheduling_batch(low)
    high = []
    for i in range(2):
        p = backend.create_pod(f"high{i}", cfg_text=cfg, tier=2)
        high.append((p.name, p.namespace, p.uid))
    sched.attempt_scheduling_batch(high)
    for _ in range(16):
        if sched.nqueue.empty():
            break
        sched.run_once()
    return len(backend.evict_log)


def bench_hetero(smoke: bool) -> dict:
    """cfg8-hetero / policy-smoke (ISSUE 15): heterogeneity-aware
    scoring on a mixed node-class fleet, measured as AGGREGATE PLACED
    THROUGHPUT — the sum over placed pods of the matrix throughput of
    (workload kind, landing node's class) — for the uniform (policy-off)
    run vs the matrix-scored run of the same fleet and workload, plus
    the tiered-preemption eviction count from a saturated micro-cell.

    The SLOW generation sits first in dict order, so the uniform
    ranking's low-node-index tiebreak prefers it: any improvement the
    policy run shows is the score term reordering placements, not
    iteration-order luck. The acceptance bar (gated by bench_diff):
    the matrix run strictly improves aggregate throughput."""
    from nhd_tpu.policy import scoring
    from nhd_tpu.policy.scoring import workload_kind
    from nhd_tpu.sim.synth import SynthNodeSpec, make_node
    from nhd_tpu.sim.workloads import workload_mix

    n_nodes = 32 if smoke else 256
    # under capacity on the fast half alone, so placement CHOICE (not
    # feasibility) decides the figure
    n_pods = 96 if smoke else 1536
    matrix = {
        "gpu": {"gen-a": 1.0, "gen-b": 0.5},
        "cpu": {"gen-a": 1.0, "gen-b": 0.5},
    }
    half = n_nodes // 2

    def fleet():
        base = SynthNodeSpec(
            phys_cores=64, gpus_per_numa=4, nics_per_numa=7,
            hugepages_gb=256,
        )
        nodes = {}
        for i in range(n_nodes):
            s = SynthNodeSpec(**{
                **base.__dict__, "name": f"het{i:05d}",
                "node_class": "gen-b" if i < half else "gen-a",
            })
            nodes[s.name] = make_node(s)
        return nodes

    reqs = workload_mix(n_pods, ["default"])

    def agg_tput(results):
        tot = 0.0
        for r, req in zip(results, reqs):
            if r.node:
                cls = "gen-b" if int(r.node[3:]) < half else "gen-a"
                tot += matrix[workload_kind(req)][cls]
        return tot

    prior_policy = os.environ.get("NHD_POLICY")
    try:
        os.environ["NHD_POLICY"] = "0"
        scoring.set_matrix(None)
        wall_u, placed_u, _stats_u, res_u = run_batch(fleet(), reqs)
        tput_u = agg_tput(res_u)

        os.environ["NHD_POLICY"] = "1"
        scoring.set_matrix(matrix)
        wall_p, placed_p, stats_p, res_p = run_batch(fleet(), reqs)
        tput_p = agg_tput(res_p)
        preemptions = _hetero_preempt_cell()
    finally:
        scoring.set_matrix(None)
        if prior_policy is None:
            os.environ.pop("NHD_POLICY", None)
        else:
            os.environ["NHD_POLICY"] = prior_policy

    improvement = (tput_p / tput_u - 1.0) if tput_u > 0 else 0.0
    name = "policy-smoke" if smoke else "cfg8:hetero"
    _log(
        f"bench[{name}]: {n_pods} pods x {n_nodes} mixed-class nodes -> "
        f"placed tput uniform {tput_u:.1f} (placed {placed_u}, "
        f"{wall_u:.3f}s) vs policy {tput_p:.1f} (placed {placed_p}, "
        f"{wall_p:.3f}s): {improvement:+.1%}; "
        f"preempt cell evictions {preemptions}"
    )
    return {
        "wall": wall_p, "placed": placed_p, "speedup": 0.0,
        "rounds": stats_p.rounds,
        "phases": {
            "solve": stats_p.solve_seconds,
            "select": stats_p.select_seconds,
            "assign": stats_p.assign_seconds,
        },
        "p99_bind_ms": stats_p.bind_latency_percentile(res_p, 99) * 1e3,
        "hetero": {
            "placed_tput_uniform": round(tput_u, 2),
            "placed_tput_policy": round(tput_p, 2),
            "improvement_pct": round(improvement * 100.0, 2),
            "placed_uniform": placed_u,
            "placed_policy": placed_p,
            "preemptions": preemptions,
        },
    }


def make_fake_sched(n_nodes: int, prefix: str, hugepages_gb: int = None):
    """Fake backend + initialized Scheduler — shared bench scaffolding."""
    import queue as queue_mod

    from nhd_tpu.k8s.fake import FakeClusterBackend
    from nhd_tpu.scheduler.core import Scheduler
    from nhd_tpu.scheduler.events import WatchQueue
    from nhd_tpu.sim import SynthNodeSpec, make_node_labels

    backend = FakeClusterBackend()
    for i in range(n_nodes):
        kw = {"name": f"{prefix}{i:04d}"}
        if hugepages_gb is not None:
            kw["hugepages_gb"] = hugepages_gb
        spec = SynthNodeSpec(**kw)
        backend.add_node(spec.name, make_node_labels(spec),
                         hugepages_gb=spec.hugepages_gb)
    sched = Scheduler(backend, WatchQueue(), queue_mod.Queue(),
                      respect_busy=False)
    sched.build_initial_node_list()
    return backend, sched


def bench_cold_start() -> float:
    """First pod create→bind after a scheduler (re)start, in THIS fresh
    process: includes config parse, solver trace and compile (or
    persistent-cache load — exactly what a crash-only restart pays).
    Must run before any other bench warms the jit caches."""
    from nhd_tpu.sim import make_triad_config

    backend, sched = make_fake_sched(8, "cold-node")
    backend.create_pod("cold-0", cfg_text=make_triad_config(gpus_per_group=1))
    t0 = time.perf_counter()
    sched.attempt_scheduling_batch([("cold-0", "default", "uid-cold")])
    dt = time.perf_counter() - t0
    bound = backend.pods[("default", "cold-0")].node
    _log(f"bench[cold-start]: first create→bind after restart = "
         f"{dt * 1e3:.0f}ms (bound to {bound}; includes first-solve "
         f"trace + compile/cache-load)")
    return dt


def bench_first_bind_aot(platform: str) -> dict:
    """Zero-cold-start serving (solver/aot.py): first create→bind in
    FRESH subprocesses — cold (full trace + compile), then with
    ``--prewarm`` over a cache a prior run seeded. Three probes: the
    cold measurement, an untimed seed run that exports the StableHLO
    artifacts, and the prewarmed measurement — exactly the restart
    sequence a crash-only daemon lives through. Returns a config record
    for the bench artifact; the ``first_bind_prewarmed`` phase is gated
    by tools/bench_diff.py."""
    import shutil
    import subprocess
    import tempfile

    cache = tempfile.mkdtemp(prefix="nhd-aot-bench-")
    env = dict(os.environ, NHD_AOT_DIR=cache)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    # the probe must measure THIS bench's backend: "default" leaves the
    # subprocess on its native (accelerator) platform, "cpu" forces the
    # CPU backend exactly like the rest of a NHD_BENCH_PLATFORM=cpu run
    base = [
        sys.executable, "-m", "nhd_tpu.solver.aot", "--first-bind-probe",
        "--platform", "cpu" if platform == "cpu" else "default",
    ]

    def probe(*flags):
        p = subprocess.run(
            base + list(flags), capture_output=True, text=True, env=env,
            timeout=600,
        )
        if p.returncode != 0:
            raise RuntimeError(
                f"first-bind probe failed: {p.stderr.strip()[-400:]}"
            )
        return json.loads(p.stdout.strip().splitlines()[-1])

    try:
        cold = probe()        # pure cold number (no export in the timing)
        probe("--save")       # untimed: seeds the AOT artifact cache
        warm = probe("--prewarm")
    finally:
        shutil.rmtree(cache, ignore_errors=True)
    _log(
        f"bench[first-bind]: cold {cold['first_bind_s'] * 1e3:.0f}ms -> "
        f"prewarmed {warm['first_bind_s'] * 1e3:.0f}ms "
        f"(prewarm load {warm['prewarm_s'] * 1e3:.0f}ms, "
        f"{warm['programs']} program(s) from the AOT cache)"
    )
    return {
        "wall": cold["first_bind_s"],
        "placed": 1,
        "speedup": cold["first_bind_s"] / max(warm["first_bind_s"], 1e-9),
        "rounds": 1,
        "phases": {
            "first_bind_cold": cold["first_bind_s"],
            "prewarm": warm["prewarm_s"],
            "first_bind_prewarmed": warm["first_bind_s"],
        },
        "p99_bind_ms": warm["first_bind_s"] * 1e3,
    }


def bench_spmd(platform: str, smoke: bool) -> tuple:
    """cfg6 SPMD leg (docs/PERFORMANCE.md "SPMD megaround"): the sharded
    fused megaround driven end to end in a FRESH subprocess — the probe
    forces a virtual N-device mesh via XLA_FLAGS, which must not leak
    into this process (with >1 visible device every other leg would
    silently go SPMD and stop being comparable to prior artifacts). On
    CPU CI the shape is scaled down; the tunnel runs it full-scale via
    NHD_SPMD_PODS/NODES/DEVICES. The probe itself asserts bit-exact
    parity vs the single-device solver, O(changed rows) mesh uploads
    with zero wholesale fallbacks, and a compiles-flat sharded prewarm —
    a violated claim is a probe failure, not a quietly worse number.
    Returns (config name, record)."""
    import subprocess

    n_dev = int(os.environ.get("NHD_SPMD_DEVICES", "8"))
    n_pods = int(os.environ.get(
        "NHD_SPMD_PODS", "512" if smoke else "4096"
    ))
    n_nodes = int(os.environ.get(
        "NHD_SPMD_NODES", "256" if smoke else "1024"
    ))
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={n_dev}"
            ).strip()
    p = subprocess.run(
        [sys.executable, "-m", "nhd_tpu.parallel.spmd_bench",
         "--pods", str(n_pods), "--nodes", str(n_nodes),
         "--devices", str(n_dev)],
        capture_output=True, text=True, env=env, timeout=2400,
    )
    if p.returncode != 0:
        raise RuntimeError(
            f"spmd probe failed: {p.stderr.strip()[-600:]}"
        )
    rec = json.loads(p.stdout.strip().splitlines()[-1])
    name = "spmd-smoke" if smoke else "cfg6:4kx1k-spmd"
    s = rec["spmd"]
    _log(
        f"bench[{name}]: {n_pods} pods x {n_nodes} nodes over a "
        f"{n_dev}-device mesh -> placed {rec['placed']} in "
        f"{rec['wall']:.3f}s (rounds={rec['rounds']}, "
        f"solve={rec['phases']['solve']:.3f}s); parity bit-exact; churn "
        f"upload {s['rows_uploaded']:.0f} rows vs budget "
        f"{s['upload_budget']:.0f} ({s['rows_per_round']}/round, "
        f"{s['wholesale_uploads']:.0f} wholesale); prewarm "
        f"{s['prewarm_loaded']} program(s), {s['mesh_programs_loaded']} "
        f"sharded, compiles flat"
    )
    return name, rec


def bench_daemon(n_pods: int = 150) -> None:
    """Daemon-mode steady-state create→bind latency: the REAL process
    harness — controller + scheduler + RPC + metrics threads from
    cli.build_threads, the reference's unit of delivery (bin/nhd:18-65)
    — on the fake backend, with pods arriving through the WATCH QUEUE
    (not a direct attempt_scheduling_batch call, which is what
    bench[bind-latency] measures). Reports measured create→bind
    p50/p99 plus a p99 upper bound read from the live /metrics
    nhd_bind_latency_seconds histogram (which replaced the lossy
    last_* gauges — PR 3)."""
    import re
    import urllib.request

    from nhd_tpu.obs.histo import reset_all

    # the histogram registry is process-global and bench_bind_latency's
    # direct-call binds already observed into it — reset so the scraped
    # p99 measures THIS daemon run, like the old last-batch gauge did
    reset_all()

    import numpy as np

    from nhd_tpu.cli import build_threads
    from nhd_tpu.k8s.fake import FakeClusterBackend
    from nhd_tpu.sim import SynthNodeSpec, make_node_labels, make_triad_config

    backend = FakeClusterBackend()
    for i in range(40):
        spec = SynthNodeSpec(name=f"dm-node{i:02d}", phys_cores=24,
                             hugepages_gb=256)
        backend.add_node(spec.name, make_node_labels(spec),
                         hugepages_gb=spec.hugepages_gb)
    metrics_port = 9109
    threads, _ = build_threads(
        backend, rpc_port=45698, metrics_port=metrics_port,
        respect_busy=False,
    )
    for t in threads:
        t.start()
    lat = []
    unbound = 0
    try:
        for i in range(n_pods):
            name = f"dm-{i}"
            cfg = make_triad_config(gpus_per_group=i % 2, cpu_workers=2,
                                    hugepages_gb=2)
            t0 = time.perf_counter()
            backend.create_pod(name, cfg_text=cfg)  # emits the watch event
            key = ("default", name)
            while True:
                p = backend.pods.get(key)
                if p is not None and p.node:
                    lat.append(time.perf_counter() - t0)
                    break
                if time.perf_counter() - t0 > 10:
                    unbound += 1
                    break
                time.sleep(0.0005)
            # steady state, not fill-up: release so the cluster never
            # saturates (delete event → scheduler reconciles the claim)
            backend.delete_pod(name, emit_watch=True)
        gauge = "scrape-failed"
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{metrics_port}/metrics", timeout=5
            ).read().decode()
            # p99 estimate from the cumulative histogram, interpolated
            # within the covering bucket (obs/histo.py
            # quantile_from_buckets — histogram_quantile() semantics,
            # not the raw bucket edge)
            from nhd_tpu.obs.histo import quantile_from_buckets

            buckets = []
            for line in body.splitlines():
                m = re.match(
                    r'nhd_bind_latency_seconds_bucket\{le="([^"]+)"\} (\d+)',
                    line,
                )
                if m:
                    edge = (float("inf") if m.group(1) == "+Inf"
                            else float(m.group(1)))
                    buckets.append((edge, int(m.group(2))))
            if buckets and buckets[-1][1] > 0:
                p99 = quantile_from_buckets(buckets, 0.99)
                gauge = f"~{p99 * 1e3:.1f}ms"
        except Exception as exc:
            gauge = f"scrape-failed ({exc})"
        lat_ms = np.asarray(lat[10:]) * 1e3  # drop warmup
        if lat_ms.size == 0:
            # the unbound count IS the diagnostic when binds fail; the
            # rest of the bench must still run
            _log(
                f"bench[daemon-mode]: no binds completed "
                f"({unbound} unbound of {n_pods}) — daemon path broken?"
            )
            return
        _log(
            f"bench[daemon-mode]: create→bind through the live daemon "
            f"(watch queue, {len(lat_ms)} binds, {unbound} unbound): "
            f"p50={np.percentile(lat_ms, 50):.2f}ms "
            f"p99={np.percentile(lat_ms, 99):.2f}ms "
            f"max={lat_ms.max():.2f}ms; "
            f"prometheus histogram bind_p99 {gauge}"
        )
    finally:
        for t in threads:
            stop = getattr(t, "stop", None)
            if stop is not None:
                stop()


def bench_restart_replay(n_nodes: int = 128, n_pods: int = 512) -> None:
    """Crash-only restart cost: rebuild the node mirror and re-claim every
    bound pod's resources from its solved-config annotation (reference:
    NHDScheduler.py:161-172) — the scheduler's real downtime after a crash
    or upgrade."""
    import queue as queue_mod

    from nhd_tpu.scheduler.core import Scheduler
    from nhd_tpu.scheduler.events import WatchQueue
    from nhd_tpu.sim import make_triad_config

    backend, sched = make_fake_sched(n_nodes, "rs-node", hugepages_gb=256)
    for i in range(n_pods):
        backend.create_pod(
            f"rs-{i}", cfg_text=make_triad_config(gpus_per_group=i % 2,
                                                  hugepages_gb=2),
        )
    sched.check_pending_pods()
    bound = sum(1 for p in backend.pods.values() if p.node)

    sched2 = Scheduler(backend, WatchQueue(), queue_mod.Queue(),
                       respect_busy=False)
    t0 = time.perf_counter()
    sched2.build_initial_node_list()
    sched2.load_deployed_configs()
    wall = time.perf_counter() - t0
    claimed = sum(n.total_pods() for n in sched2.nodes.values())
    _log(f"bench[restart-replay]: {claimed}/{bound} pods re-claimed over "
         f"{n_nodes} nodes in {wall:.2f}s ({wall / max(claimed, 1) * 1e3:.2f} "
         f"ms/pod; crash-only restart downtime)")


def bench_replay() -> dict:
    """cfg-replay: the record/replay determinism gate (ISSUE 18) — replay
    the committed golden churn journal through the real scheduler stack
    (sim/replay.py) and report decision throughput plus the divergence
    count. bench_diff hard-gates divergences at zero: any scheduler
    change that alters decisions for recorded traffic must show up as a
    bench failure, not a silent behavior drift."""
    from nhd_tpu.sim.replay import replay_journal

    journal = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "tests", "fixtures", "journal", "golden_churn.journal.jsonl",
    )
    t0 = time.perf_counter()
    result = replay_journal([journal])
    wall = time.perf_counter() - t0
    placed = sum(
        1 for d in result.replayed if d.get("outcome") == "scheduled"
    )
    _log(
        f"bench[cfg-replay]: {len(result.replayed)} decisions replayed vs "
        f"{len(result.recorded)} recorded in {wall:.2f}s, "
        f"{len(result.divergences)} divergence(s), "
        f"{len(result.knob_drift)} knob drift(s)"
    )
    return {
        "wall": wall, "placed": placed, "speedup": 1.0, "rounds": 1,
        "phases": {}, "p99_bind_ms": None,
        "replay": {
            "journal": "tests/fixtures/journal/golden_churn.journal.jsonl",
            "recorded": len(result.recorded),
            "replayed": len(result.replayed),
            "divergences": len(result.divergences),
            "knob_drift": len(result.knob_drift),
            "decisions_per_sec": round(len(result.replayed) / wall, 1)
            if wall > 0 else 0.0,
        },
    }


def bench_bind_latency(n_pods: int = 200) -> None:
    """Event-driven single-pod path latency (p50/p99): pod create → bound,
    through the full scheduler on the fake backend — config parse, batched
    solve of one, physical assignment, annotations, bind. The reference's
    north-star metric is p99 bind latency (BASELINE.md)."""
    import numpy as np

    from nhd_tpu.sim import make_triad_config

    backend, sched = make_fake_sched(32, "lat-node", hugepages_gb=256)

    lat = []
    failed = 0
    for i in range(n_pods):
        cfg = make_triad_config(gpus_per_group=i % 2, cpu_workers=2,
                                hugepages_gb=2)
        backend.create_pod(f"lat-{i}", cfg_text=cfg)
        t0 = time.perf_counter()
        sched.attempt_scheduling_batch([(f"lat-{i}", "default", f"uid{i}")])
        dt = time.perf_counter() - t0
        # only successful binds count toward the latency distribution; the
        # pod is then released so the cluster never saturates mid-run
        if backend.pods[("default", f"lat-{i}")].node is None:
            failed += 1
        else:
            lat.append(dt)
            sched.release_pod_resources(f"lat-{i}", "default")
        backend.delete_pod(f"lat-{i}", emit_watch=False)
        sched.pod_state.pop(("default", f"lat-{i}"), None)
    lat_ms = np.asarray(lat[10:]) * 1e3  # drop warmup
    _log(
        f"bench[bind-latency]: single-pod create→bind over {len(lat_ms)} "
        f"binds ({failed} unschedulable excluded): "
        f"p50={np.percentile(lat_ms, 50):.2f}ms "
        f"p99={np.percentile(lat_ms, 99):.2f}ms "
        f"max={lat_ms.max():.2f}ms"
    )


def main() -> None:
    platform = _pick_platform()
    # NHD_BENCH_SMOKE=1: the seconds-scale leg `make bench-smoke` runs on
    # every `make check` — cold-start + first-bind probes + cfg1/cfg2
    # only, so a solve-phase or first-bind regression fails fast without
    # the multi-minute cfg3-cfg5 sweep. The artifact it writes shares
    # cfg1/cfg2 (and the first-bind phases) with full-run artifacts, so
    # tools/bench_diff.py gates across both kinds.
    smoke = bool(os.environ.get("NHD_BENCH_SMOKE"))
    jax = _init_jax(platform)
    _log(f"bench platform: {jax.devices()[0].platform} "
         f"({len(jax.devices())} device(s))"
         + (" [smoke]" if smoke else ""))

    # NHD_JOURNAL=1 turns on record/replay capture for the whole run —
    # the A/B the ≤2% capture-cost bound is measured against
    # (docs/bench/BENCH_DIFF_r18.md): same legs, journal on vs off
    from nhd_tpu.obs.journal import enable_journal_from_env

    jnl = enable_journal_from_env(identity="bench")
    if jnl is not None:
        _log(f"bench: journal capture on -> {jnl.path}")

    configs = {}
    cold_dt = bench_cold_start()
    # first-bind probes run in subprocesses (fresh jit caches). In the
    # SMOKE leg a probe failure is fatal: the leg exists to gate the
    # zero-cold-start phases, and a silently missing config would sail
    # through bench_diff (configs absent from one side are not gated).
    # In the full bench it is reported but must not eat the other legs.
    try:
        configs["first-bind"] = bench_first_bind_aot(platform)
        # this process's cold-start figure rides along in the artifact
        # (observable/diffable; NOT a watched phase — trace+compile time
        # jitters far past any sane relative threshold)
        configs["first-bind"]["phases"]["cold_start_inproc"] = cold_dt
    except Exception as exc:
        if smoke:
            raise
        _log(f"bench[first-bind]: probe failed (leg skipped): {exc}")
    if not smoke:
        bench_bind_latency()
        bench_daemon()
        bench_restart_replay()

    from nhd_tpu.sim.workloads import cap_cluster

    configs["cfg1:100x32"] = bench_config(
        "cfg1:100x32", 100, 32, ["default"], baseline_sample=30
    )
    result = configs["cfg2:1kx256"] = bench_config(
        "cfg2:1kx256", 1000, 256, ["default"], baseline_sample=30
    )

    if smoke:
        # seconds-scale sustained-churn smoke: same incremental-state
        # machinery as cfg7-churn at a fraction of the scale, so the
        # `make check` gate catches a delta-path regression fast
        configs["churn-smoke"] = bench_churn(
            "churn-smoke", n_nodes=512, events_per_sec=2_000,
            sim_seconds=3, groups=["default", "edge"], tile_nodes=512,
            round_dt=1.0,
        )
        # seconds-scale SPMD smoke: parity + upload economy + sharded
        # prewarm of the mesh megaround, subprocess-isolated (a smoke
        # probe failure is fatal, same stance as first-bind)
        if not os.environ.get("NHD_BENCH_SKIP_SPMD"):
            name, rec = bench_spmd(platform, smoke=True)
            configs[name] = rec
        # seconds-scale policy smoke (ISSUE 15): heterogeneity scoring
        # must strictly improve aggregate placed throughput on a mixed
        # fleet, and the preemption micro-cell must evict — both gated
        # by tools/bench_diff.py's hetero gates on every `make check`
        configs["policy-smoke"] = bench_hetero(smoke=True)
        # record/replay determinism gate (ISSUE 18): seconds-scale, so
        # every `make check` proves recorded traffic still replays
        # decision-for-decision
        configs["cfg-replay"] = bench_replay()
        # seconds-scale ingress smoke (ISSUE 20): batched-decode cost per
        # event + the shed ladder's economy under a CPU-degraded
        # multi-tenant storm; verdictless sheds gated to zero by
        # tools/bench_diff.py's ingress gates
        configs["ingress-smoke"] = bench_ingress(
            "ingress-smoke", n_pods=240, n_nodes=32, waves=20,
        )

    if not smoke:
        # cfg3: NIC-saturated contention shape (places ~4k of 10k — the
        # cluster runs out of unshared NICs; throughput under heavy
        # infeasibility)
        configs["cfg3:10kx1k-sat"] = bench_config(
            "cfg3:10kx1k-sat", 10_000, 1_000, ["default", "edge", "batch"],
            baseline_sample=40,
        )

        # cfg4 (headline): capacity-matched — every pod places
        from nhd_tpu.utils.tracing import profiler_trace

        with profiler_trace(os.environ.get("NHD_BENCH_PROFILE")):
            result = bench_config(
                "cfg4:10kx1k-cap", 10_000, 1_000,
                ["default", "edge", "batch"],
                baseline_sample=40, cluster_fn=cap_cluster,
            )
        configs["cfg4:10kx1k-cap"] = result
        if result["placed"] < 10_000:
            _log(f"bench: WARNING cfg4 placed {result['placed']}/10000 "
                 "on the capacity-matched cluster")

        # cfg5: federation stretch through the streaming solver (default-on)
        if not os.environ.get("NHD_BENCH_SKIP_FED"):
            configs["cfg5:100kx10k-stream"] = bench_config(
                "cfg5:100kx10k-stream", 100_000, 10_000,
                ["default", "edge", "batch", "fed1", "fed2"],
                baseline_sample=10,
                cluster_fn=cap_cluster, runner=run_stream,
            )

        # cfg7: sustained churn — minutes of event stream against a 10k-
        # node cluster through the incremental device-resident state
        # (ClusterDelta + persistent streaming tiles); the headline proof
        # of the delta path (ISSUE 9): binds/s + p99 under a STREAM, not
        # a one-shot backlog, with per-round host/upload cost O(changed
        # rows) asserted via the nhd_device_state_* counters
        if not os.environ.get("NHD_BENCH_SKIP_CHURN"):
            configs["cfg7-churn"] = bench_churn(
                "cfg7-churn", n_nodes=10_000, events_per_sec=10_000,
                sim_seconds=60,
                groups=["default", "edge", "batch", "fed1", "fed2"],
            )

        # cfg6: the SPMD megaround leg (ISSUE 11) — sharded solve
        # parity, mesh delta-upload economy and sharded AOT prewarm in a
        # subprocess-isolated virtual mesh; full-scale shape for the
        # tunnel via NHD_SPMD_PODS/NODES/DEVICES. Reported-but-skipped
        # on failure like the other full-bench probe legs.
        if not os.environ.get("NHD_BENCH_SKIP_SPMD"):
            try:
                name, rec = bench_spmd(platform, smoke=False)
                configs[name] = rec
            except Exception as exc:
                _log(f"bench[cfg6-spmd]: probe failed (leg skipped): {exc}")

        # cfg8: the heterogeneity-policy leg (ISSUE 15) — mixed
        # node-class fleet at bench scale, tiered preemption counts;
        # aggregate placed throughput gated by bench_diff's hetero gates
        configs["cfg8:hetero"] = bench_hetero(smoke=False)

        # cfg-replay: same determinism gate as the smoke leg (same name,
        # so bench_diff gates across smoke and full artifacts alike)
        configs["cfg-replay"] = bench_replay()

        # cfg9: the ingress streaming leg (ISSUE 20) — the multi-tenant
        # create storm through the real front door (batched decode →
        # admission lanes → DRR drain), NHD_SPMD_PODS/NODES-parameterized
        # for the tunnel like cfg6; decode µs/event, binds/s and the
        # admit/defer/shed rates ride the artifact for bench_diff
        configs["cfg9:ingress-stream"] = bench_ingress(
            "cfg9:ingress-stream",
            n_pods=int(os.environ.get("NHD_SPMD_PODS", "2048")),
            n_nodes=int(os.environ.get("NHD_SPMD_NODES", "128")),
            waves=40,
        )

    headline = {
        # the smoke leg's headline is cfg2 under its own metric name, so
        # bench_diff never compares a smoke headline against a full one
        "metric": ("pods_matched_per_sec_1k_pods_x_256_nodes" if smoke
                   else "pods_matched_per_sec_10k_pods_x_1k_nodes"),
        "value": round(result["placed"] / result["wall"], 1),
        "unit": "pods/s",
        "vs_baseline": round(result["speedup"], 1),
    }

    # schema-versioned perf artifact (obs/perf.py): the run's per-config
    # walls, phase breakdowns and per-(phase, shape) attribution on disk,
    # so tools/bench_diff.py can gate the NEXT run against this one
    if not os.environ.get("NHD_BENCH_NO_ARTIFACT"):
        from nhd_tpu.obs.jitstats import JIT_STATS
        from nhd_tpu.obs.perf import (
            build_bench_artifact,
            config_record,
            write_bench_artifact,
        )

        jit = JIT_STATS.snapshot()
        artifact = build_bench_artifact(
            {
                name: config_record(
                    wall_seconds=r["wall"], placed=r["placed"],
                    speedup=r["speedup"], rounds=r["rounds"],
                    phases=r["phases"], p99_bind_ms=r["p99_bind_ms"],
                    extra={
                        k: r[k]
                        for k in ("churn", "hetero", "spmd", "replay",
                                  "ingress")
                        if k in r
                    } or None,
                )
                for name, r in configs.items()
            },
            headline=headline,
            platform=jax.devices()[0].platform,
            phase_attribution={
                "phase_seconds": jit["phase_seconds"],
                "phase_counts": jit["phase_counts"],
            },
        )
        # the artifact is a byproduct: a full disk or read-only FS must
        # not eat the headline line (the one-JSON-line stdout contract)
        # after a multi-minute bench run
        try:
            path = write_bench_artifact(
                artifact,
                os.environ.get("NHD_BENCH_ARTIFACT_DIR", "artifacts/bench"),
            )
            _log(f"bench artifact -> {path}")
        except (OSError, ValueError) as exc:
            _log(f"bench artifact write failed (run unaffected): {exc}")

    if jnl is not None:
        from nhd_tpu.obs.journal import disable_journal

        _log(f"bench: journal finalized -> {disable_journal()}")

    print(json.dumps(headline))


if __name__ == "__main__":
    main()
