// Native physical-assignment core.
//
// The per-winner hot loop of batch scheduling (FastCluster.assign,
// nhd_tpu/solver/fast_assign.py) spends most of its time in Python/numpy
// call overhead: ~40 small vector ops per pod. This translation unit does
// the whole pod assignment — first-fit core batches with SMT-pair
// semantics, PCIe-switch-preferring GPU picks — in one call over raw
// pointers into the FastCluster arrays, loaded via ctypes (no pybind11 in
// this image). Policies are bit-identical to the Python path and pinned by
// tests/test_native.py; the reference semantics they reproduce are
// HostNode.free_cpu_batch / free_pci_gpu_for_nic / next_free_gpu
// (reference Node.py:502-519,648-655,495-500).
//
// Build: make native   (g++ -O2 -shared -fPIC)

#include <cstdint>

namespace {

// First-fit core batch on one NUMA node against an overlay row.
// Mutates `used` for the cores handed out. Returns the number of core ids
// written to `out`, or -1 on shortfall (overlay untouched on failure).
int cpu_batch(uint8_t* used, const int8_t* socket, int P, int smt_enabled,
              int numa, int num, int smt_on_request, int32_t* out) {
  if (num == 0) return 0;
  int n_out = 0;
  if (smt_enabled) {
    if (smt_on_request) {
      int pairs = num / 2, odd = num % 2, got = 0;
      // gather candidates first so a shortfall leaves the overlay untouched
      for (int c = 0; c < P && got < pairs + odd; ++c) {
        if (socket[c] == numa && !used[c] && !used[c + P]) {
          if (got < pairs) {
            out[n_out++] = c;
            out[n_out++] = c + P;
          } else {
            out[n_out++] = c;  // odd single
          }
          ++got;
        }
      }
      if (got < pairs + odd) return -1;
    } else {
      int got = 0;
      for (int c = 0; c < P && got < num; ++c) {
        if (socket[c] == numa && !used[c] && !used[c + P]) {
          out[n_out++] = c;
          ++got;
        }
      }
      if (got < num) return -1;
    }
  } else {
    int got = 0;
    for (int c = 0; c < P && got < num; ++c) {
      if (socket[c] == numa && !used[c]) {
        out[n_out++] = c;
        ++got;
      }
    }
    if (got < num) return -1;
  }
  for (int i = 0; i < n_out; ++i) used[out[i]] = 1;
  return n_out;
}

// First free GPU on PCIe switch `sw`; NUMA fallback unless PCI mode.
int pick_gpu(const uint8_t* gpu_used, const int8_t* gpu_numa,
             const int64_t* gpu_sw, int n_gpus, int64_t sw, int numa,
             int pci_mode) {
  for (int j = 0; j < n_gpus; ++j)
    if (!gpu_used[j] && gpu_sw[j] == sw) return j;
  if (pci_mode) return -1;
  for (int j = 0; j < n_gpus; ++j)
    if (!gpu_used[j] && gpu_numa[j] == numa) return j;
  return -1;
}

}  // namespace

extern "C" {

// Assign one pod on one node. All picks resolve against the overlay rows
// (`core_used`, `gpu_used`), which the caller copies beforehand and commits
// afterwards — failure leaves real state untouched by construction.
//
// Outputs:
//   out_cores  — group 0 proc.., group 0 helpers.., group 1 ..., misc..
//   out_counts — per group: [proc_n, helper_n], then [misc_n]
//   out_gpus   — chosen GPU row indices, in group order
// Returns 0, or a negative stage code: -1 proc shortfall, -2 no GPU,
// -3 helper shortfall, -4 misc shortfall.
int nhd_assign_pod(
    uint8_t* core_used, const int8_t* core_socket, int P, int smt_enabled,
    uint8_t* gpu_used, const int8_t* gpu_numa, const int64_t* gpu_sw,
    int n_gpus,
    int n_groups,
    const int32_t* g_numa,      // [G] group NUMA assignment (mapping)
    const int64_t* g_nic_sw,    // [G] PCIe switch of the group's NIC (-1 none)
    const int32_t* g_proc, const int32_t* g_proc_smt,
    const int32_t* g_helpers, const int32_t* g_helper_smt,
    const int32_t* g_gpus,
    int misc_numa, int misc_count, int misc_smt, int pci_mode,
    int32_t* out_cores, int32_t* out_counts, int32_t* out_gpus) {
  int cores_at = 0, gpus_at = 0;
  for (int g = 0; g < n_groups; ++g) {
    int numa = g_numa[g];
    int n = cpu_batch(core_used, core_socket, P, smt_enabled, numa, g_proc[g],
                      g_proc_smt[g], out_cores + cores_at);
    if (n < 0) return -1;
    out_counts[2 * g] = n;
    cores_at += n;

    for (int k = 0; k < g_gpus[g]; ++k) {
      int j = pick_gpu(gpu_used, gpu_numa, gpu_sw, n_gpus, g_nic_sw[g], numa,
                       pci_mode);
      if (j < 0) return -2;
      gpu_used[j] = 1;
      out_gpus[gpus_at++] = j;
    }

    n = cpu_batch(core_used, core_socket, P, smt_enabled, numa, g_helpers[g],
                  g_helper_smt[g], out_cores + cores_at);
    if (n < 0) return -3;
    out_counts[2 * g + 1] = n;
    cores_at += n;
  }

  int n = cpu_batch(core_used, core_socket, P, smt_enabled, misc_numa,
                    misc_count, misc_smt, out_cores + cores_at);
  if (n < 0) return -4;
  out_counts[2 * n_groups] = n;
  return 0;
}

}  // extern "C"
