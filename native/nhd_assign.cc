// Native physical-assignment core.
//
// The per-winner hot loop of batch scheduling (FastCluster.assign,
// nhd_tpu/solver/fast_assign.py) spends most of its time in Python/numpy
// call overhead: ~40 small vector ops per pod. This translation unit does
// the whole pod assignment — first-fit core batches with SMT-pair
// semantics, PCIe-switch-preferring GPU picks — in one call over raw
// pointers into the FastCluster arrays, loaded via ctypes (no pybind11 in
// this image). Policies are bit-identical to the Python path and pinned by
// tests/test_native.py; the reference semantics they reproduce are
// HostNode.free_cpu_batch / free_pci_gpu_for_nic / next_free_gpu
// (reference Node.py:502-519,648-655,495-500).
//
// Build: make native   (g++ -O2 -shared -fPIC)

#include <cstdint>
#include <cstddef>

using std::size_t;

namespace {

// First-fit core batch on one NUMA node against an overlay row.
// Mutates `used` for the cores handed out. Returns the number of core ids
// written to `out`, or -1 on shortfall (overlay untouched on failure).
int cpu_batch(uint8_t* used, const int8_t* socket, int P, int smt_enabled,
              int numa, int num, int smt_on_request, int32_t* out) {
  if (num == 0) return 0;
  int n_out = 0;
  if (smt_enabled) {
    if (smt_on_request) {
      int pairs = num / 2, odd = num % 2, got = 0;
      // gather candidates first so a shortfall leaves the overlay untouched
      for (int c = 0; c < P && got < pairs + odd; ++c) {
        if (socket[c] == numa && !used[c] && !used[c + P]) {
          if (got < pairs) {
            out[n_out++] = c;
            out[n_out++] = c + P;
          } else {
            out[n_out++] = c;  // odd single
          }
          ++got;
        }
      }
      if (got < pairs + odd) return -1;
    } else {
      int got = 0;
      for (int c = 0; c < P && got < num; ++c) {
        if (socket[c] == numa && !used[c] && !used[c + P]) {
          out[n_out++] = c;
          ++got;
        }
      }
      if (got < num) return -1;
    }
  } else {
    int got = 0;
    for (int c = 0; c < P && got < num; ++c) {
      if (socket[c] == numa && !used[c]) {
        out[n_out++] = c;
        ++got;
      }
    }
    if (got < num) return -1;
  }
  for (int i = 0; i < n_out; ++i) used[out[i]] = 1;
  return n_out;
}

// First free GPU on PCIe switch `sw`; NUMA fallback unless PCI mode.
int pick_gpu(const uint8_t* gpu_used, const int8_t* gpu_numa,
             const int64_t* gpu_sw, int n_gpus, int64_t sw, int numa,
             int pci_mode) {
  for (int j = 0; j < n_gpus; ++j)
    if (!gpu_used[j] && gpu_sw[j] == sw) return j;
  if (pci_mode) return -1;
  for (int j = 0; j < n_gpus; ++j)
    if (!gpu_used[j] && gpu_numa[j] == numa) return j;
  return -1;
}

}  // namespace

extern "C" {

// Assign one pod on one node. All picks resolve against the overlay rows
// (`core_used`, `gpu_used`), which the caller copies beforehand and commits
// afterwards — failure leaves real state untouched by construction.
//
// Outputs:
//   out_cores  — group 0 proc.., group 0 helpers.., group 1 ..., misc..
//   out_counts — per group: [proc_n, helper_n], then [misc_n]
//   out_gpus   — chosen GPU row indices, in group order
// Returns 0, or a negative stage code: -1 proc shortfall, -2 no GPU,
// -3 helper shortfall, -4 misc shortfall.
int nhd_assign_pod(
    uint8_t* core_used, const int8_t* core_socket, int P, int smt_enabled,
    uint8_t* gpu_used, const int8_t* gpu_numa, const int64_t* gpu_sw,
    int n_gpus,
    int n_groups,
    const int32_t* g_numa,      // [G] group NUMA assignment (mapping)
    const int64_t* g_nic_sw,    // [G] PCIe switch of the group's NIC (-1 none)
    const int32_t* g_proc, const int32_t* g_proc_smt,
    const int32_t* g_helpers, const int32_t* g_helper_smt,
    const int32_t* g_gpus,
    int misc_numa, int misc_count, int misc_smt, int pci_mode,
    int32_t* out_cores, int32_t* out_counts, int32_t* out_gpus) {
  int cores_at = 0, gpus_at = 0;
  for (int g = 0; g < n_groups; ++g) {
    int numa = g_numa[g];
    int n = cpu_batch(core_used, core_socket, P, smt_enabled, numa, g_proc[g],
                      g_proc_smt[g], out_cores + cores_at);
    if (n < 0) return -1;
    out_counts[2 * g] = n;
    cores_at += n;

    for (int k = 0; k < g_gpus[g]; ++k) {
      int j = pick_gpu(gpu_used, gpu_numa, gpu_sw, n_gpus, g_nic_sw[g], numa,
                       pci_mode);
      if (j < 0) return -2;
      gpu_used[j] = 1;
      out_gpus[gpus_at++] = j;
    }

    n = cpu_batch(core_used, core_socket, P, smt_enabled, numa, g_helpers[g],
                  g_helper_smt[g], out_cores + cores_at);
    if (n < 0) return -3;
    out_counts[2 * g + 1] = n;
    cores_at += n;
  }

  int n = cpu_batch(core_used, core_socket, P, smt_enabled, misc_numa,
                    misc_count, misc_smt, out_cores + cores_at);
  if (n < 0) return -4;
  out_counts[2 * n_groups] = n;
  return 0;
}

// ---------------------------------------------------------------------------
// Round-level assignment: one call places every winner of a greedy round.
//
// Several winners may share a node (capacity-aware multi-claim); claims
// apply sequentially against LIVE arrays, with each claim's NIC pick
// re-selected (select_pick) since earlier same-node claims may have
// consumed the solver's snapshot choice. Mutates the FastCluster occupancy
// arrays AND the solver-visible ClusterArrays increments (the same deltas
// fast_assign._update_arrays applies), eliminating the per-winner Python
// round trips entirely.
//
// Combo decoding matches solver/combos.py: index digits base U, slot 0
// most significant. CPU physical-core demand replicates
// CpuRequest.physical_cores: ceil(n/2) for SMT-tolerant requests on SMT
// nodes, n otherwise.
//
// Per-winner status: 0 ok; -1 proc, -2 gpu, -3 helper, -4 misc shortfall;
// -5 hugepages; -6 missing NIC; -7 no feasible NIC pick against live
// state; -8 node already busied this round (GPU pod back-off) — the
// caller retries -7/-8-style stale failures next round. Failures leave
// all state untouched.

static inline int phys_cores(int count, int smt_req, int node_smt) {
  return (node_smt && smt_req) ? (count + 1) / 2 : count;
}

// Select the first NIC pick (product order, matching the solver/oracle
// tie-break) feasible against LIVE per-NIC state. Needed because several
// winners may share a node in one round: the solver's snapshot pick can be
// consumed by an earlier claim. In PCI map mode the pick also has to admit
// the GPU assignment (each GPU must come off the chosen NIC's PCIe switch),
// so that leg is simulated too. Returns the pick index, or -1.
static int select_pick(int G, int U, int K, const int* numa_of,
                       const int32_t* nic_flat, const int64_t* nic_sw,
                       const float* rx_dem, const float* tx_dem,
                       const double* nic_cap, const double* nic_rx_used,
                       const double* nic_tx_used, const int32_t* nic_pods,
                       int enable_sharing, int pci_mode,
                       const uint8_t* gpu_used, const int8_t* gpu_numa,
                       const int64_t* gpu_sw, int n_gpus,
                       const int32_t* gpus_dem, int* pick_out) {
  long A = 1;
  for (int g = 0; g < G; ++g) A *= K;
  double joint_rx[128], joint_tx[128];
  uint8_t gpu_sim[512];
  for (long a = 0; a < A; ++a) {
    // decode digits, check ordinal existence
    int pick[16];
    {
      long v = a;
      for (int g = G - 1; g >= 0; --g) { pick[g] = (int)(v % K); v /= K; }
    }
    int ok = 1;
    for (int g = 0; g < G && ok; ++g)
      if (nic_flat[numa_of[g] * K + pick[g]] < 0) ok = 0;
    if (!ok) continue;
    // joint demand per (numa, nic) — touch (and afterwards clear) only the
    // <= G slots this pick uses, keeping the scan O(A*G), not O(A*U*K)
    int touched[16];
    int n_touched = 0;
    for (int g = 0; g < G; ++g) {
      const int uk = numa_of[g] * K + pick[g];
      int seen = 0;
      for (int i = 0; i < n_touched; ++i)
        if (touched[i] == uk) seen = 1;
      if (!seen) {
        touched[n_touched++] = uk;
        joint_rx[uk] = 0.0;
        joint_tx[uk] = 0.0;
      }
      joint_rx[uk] += rx_dem[g];
      joint_tx[uk] += tx_dem[g];
    }
    for (int i = 0; i < n_touched && ok; ++i) {
      const int uk = touched[i];
      if (joint_rx[uk] <= 0.0 && joint_tx[uk] <= 0.0) continue;
      double free_rx, free_tx;
      if (enable_sharing) {
        free_rx = nic_cap[uk] - nic_rx_used[uk];
        free_tx = nic_cap[uk] - nic_tx_used[uk];
      } else if (nic_pods[uk] > 0) {
        free_rx = 0.0; free_tx = 0.0;
      } else {
        free_rx = nic_cap[uk]; free_tx = nic_cap[uk];
      }
      if (joint_rx[uk] > free_rx || joint_tx[uk] > free_tx) ok = 0;
    }
    if (ok && pci_mode) {
      // PCI mode: every GPU must come off the chosen NIC's switch —
      // simulate the sequential picks so the assignment cannot dead-end
      for (int i = 0; i < n_gpus; ++i) gpu_sim[i] = gpu_used[i];
      for (int g = 0; g < G && ok; ++g) {
        const int uk = numa_of[g] * K + pick[g];
        for (int j = 0; j < gpus_dem[g] && ok; ++j) {
          int gi = pick_gpu(gpu_sim, gpu_numa, gpu_sw, n_gpus, nic_sw[uk],
                            numa_of[g], 1);
          if (gi < 0) ok = 0;
          else gpu_sim[gi] = 1;
        }
      }
    }
    if (ok) {
      for (int g = 0; g < G; ++g) pick_out[g] = pick[g];
      return (int)a;
    }
  }
  return -1;
}

int nhd_assign_round(
    // FastCluster occupancy (mutated)
    uint8_t* core_used_all, const int8_t* core_socket_all,
    const int32_t* phys_all, const uint8_t* smt_all, int L,
    uint8_t* gpu_used_all, const int8_t* gpu_numa_all,
    const int64_t* gpu_sw_all, const int32_t* gpu_sw_dense_all,
    const int32_t* n_gpus_all, int GM,
    const int32_t* nic_flat_all, const int64_t* nic_sw_all,
    double* nic_rx_used_all, double* nic_tx_used_all, int32_t* nic_pods_all,
    const double* nic_cap_all, int U, int K,
    int64_t* hp_free_all,
    // solver-visible ClusterArrays (mutated incrementally)
    int32_t* cpu_free_all, int32_t* gpu_free_all, int32_t* gpu_free_sw_all,
    float* nic_free_all, int32_t* hp_free32_all, uint8_t* busy_all,
    int S, int set_busy, int enable_sharing,
    // bucket type data ([T, G] row-major; scalars [T])
    int G, const int32_t* t_proc, const int32_t* t_proc_smt,
    const int32_t* t_help, const int32_t* t_help_smt, const int32_t* t_gpus,
    const float* t_rx, const float* t_tx, const int32_t* t_misc,
    const int32_t* t_misc_smt, const int32_t* t_hp, const uint8_t* t_pci,
    // winners
    int W, const int32_t* w_node, const int32_t* w_type, const int32_t* w_c,
    const int32_t* w_m,
    // outputs ([W, MAXC] / [W, 2G+1] / [W, G] / [W, GMX] / [W])
    int32_t* out_status, int32_t* out_cores, int32_t* out_counts,
    int32_t* out_nic_flat, int32_t* out_gpus, int32_t* out_pick,
    int MAXC, int GMX) {
  const int UK = U * K;
  uint8_t core_overlay[4096];
  uint8_t gpu_overlay[512];
  // size guards — the Python caller (round_ok_for) checks the same limits
  // and falls back to the per-pod path; this is defense in depth
  if (L > 4096 || GM > 512 || G > 16 || UK > 128) return -100;

  for (int w = 0; w < W; ++w) {
    const int n = w_node[w];
    const int t = w_type[w];
    const int node_smt = smt_all[n];
    const int P = phys_all[n];
    uint8_t* core_used = core_used_all + (size_t)n * L;
    uint8_t* gpu_used = gpu_used_all + (size_t)n * GM;
    const int8_t* core_socket = core_socket_all + (size_t)n * L;
    const int8_t* gpu_numa = gpu_numa_all + (size_t)n * GM;
    const int64_t* gpu_sw = gpu_sw_all + (size_t)n * GM;
    const int32_t* gpu_sw_dense = gpu_sw_dense_all + (size_t)n * GM;
    const int n_gpus = n_gpus_all[n];
    const int32_t* nic_flat = nic_flat_all + (size_t)n * UK;
    const int64_t* nic_sw = nic_sw_all + (size_t)n * UK;

    int32_t* cores_row = out_cores + (size_t)w * MAXC;
    int32_t* counts_row = out_counts + (size_t)w * (2 * G + 1);
    int32_t* nic_row = out_nic_flat + (size_t)w * (G > 0 ? G : 1);
    int32_t* gpus_row = out_gpus + (size_t)w * GMX;

    if (t_hp[t] > hp_free_all[n]) { out_status[w] = -5; continue; }

    // multiple winners may share a node this round: a GPU pod arriving
    // after the node was stamped busy within the round is retryable
    // (-8); the snapshot-busy case never reaches here (solver filters it)
    if (set_busy && busy_all[n]) {
      int any_gpu = 0;
      for (int g = 0; g < G; ++g)
        if (t_gpus[(size_t)t * G + g] > 0) any_gpu = 1;
      if (any_gpu) { out_status[w] = -8; continue; }
    }

    for (int i = 0; i < L; ++i) core_overlay[i] = core_used[i];
    for (int i = 0; i < GM; ++i) gpu_overlay[i] = gpu_used[i];

    // decode the combo; re-select the NIC pick against live state (the
    // solver's pick is a snapshot an earlier same-node winner may have
    // consumed)
    int numa_of[16], pick_of[16];
    {
      int c = w_c[w];
      for (int g = G - 1; g >= 0; --g) { numa_of[g] = c % U; c /= U; }
    }
    {
      const double* nic_cap = nic_cap_all + (size_t)n * UK;
      const double* rx_used = nic_rx_used_all + (size_t)n * UK;
      const double* tx_used = nic_tx_used_all + (size_t)n * UK;
      const int32_t* pods_used = nic_pods_all + (size_t)n * UK;
      int a = select_pick(G, U, K, numa_of, nic_flat, nic_sw,
                          t_rx + (size_t)t * G, t_tx + (size_t)t * G,
                          nic_cap, rx_used, tx_used, pods_used,
                          enable_sharing, t_pci[t],
                          gpu_used, gpu_numa, gpu_sw, n_gpus,
                          t_gpus + (size_t)t * G, pick_of);
      if (a < 0) { out_status[w] = -7; continue; }
      out_pick[w] = a;
    }

    int status = 0, cores_at = 0, gpus_at = 0;
    for (int g = 0; g < G && status == 0; ++g) {
      const int numa = numa_of[g];
      const int uk = numa * K + pick_of[g];
      const int flat = nic_flat[uk];
      const float rx = t_rx[(size_t)t * G + g], tx = t_tx[(size_t)t * G + g];
      const int needs_nic = (rx > 0.0f) || (tx > 0.0f);
      const int gpus = t_gpus[(size_t)t * G + g];
      if (flat < 0 && (needs_nic || gpus)) { status = -6; break; }
      nic_row[g] = flat;

      int nres = cpu_batch(core_overlay, core_socket, P, node_smt, numa,
                           t_proc[(size_t)t * G + g],
                           t_proc_smt[(size_t)t * G + g],
                           cores_row + cores_at);
      if (nres < 0) { status = -1; break; }
      counts_row[2 * g] = nres;
      cores_at += nres;

      for (int j = 0; j < gpus; ++j) {
        const int64_t sw = flat >= 0 ? nic_sw[uk] : -1;
        int gi = pick_gpu(gpu_overlay, gpu_numa, gpu_sw, n_gpus, sw, numa,
                          t_pci[t]);
        if (gi < 0) { status = -2; break; }
        gpu_overlay[gi] = 1;
        gpus_row[gpus_at++] = gi;
      }
      if (status != 0) break;

      nres = cpu_batch(core_overlay, core_socket, P, node_smt, numa,
                       t_help[(size_t)t * G + g],
                       t_help_smt[(size_t)t * G + g], cores_row + cores_at);
      if (nres < 0) { status = -3; break; }
      counts_row[2 * g + 1] = nres;
      cores_at += nres;
    }
    if (status == 0) {
      int nres = cpu_batch(core_overlay, core_socket, P, node_smt, w_m[w],
                           t_misc[t], t_misc_smt[t], cores_row + cores_at);
      if (nres < 0) status = -4;
      else counts_row[2 * G] = nres;
    }
    out_status[w] = status;
    if (status != 0) continue;

    // ---- commit occupancy ----
    for (int i = 0; i < L; ++i) core_used[i] = core_overlay[i];
    for (int i = 0; i < GM; ++i) gpu_used[i] = gpu_overlay[i];
    hp_free_all[n] -= t_hp[t];

    // ---- solver-array increments (fast_assign._update_arrays) ----
    int32_t* cpu_free = cpu_free_all + (size_t)n * U;
    int32_t* gpu_free = gpu_free_all + (size_t)n * U;
    int32_t* gpu_free_sw = gpu_free_sw_all + (size_t)n * S;
    float* nic_free = nic_free_all + (size_t)n * UK * 2;
    double* nic_rx_used = nic_rx_used_all + (size_t)n * UK;
    double* nic_tx_used = nic_tx_used_all + (size_t)n * UK;
    int32_t* nic_pods = nic_pods_all + (size_t)n * UK;
    const double* nic_cap = nic_cap_all + (size_t)n * UK;

    for (int g = 0; g < G; ++g) {
      const int numa = numa_of[g];
      cpu_free[numa] -= phys_cores(t_proc[(size_t)t * G + g],
                                   t_proc_smt[(size_t)t * G + g], node_smt) +
                        phys_cores(t_help[(size_t)t * G + g],
                                   t_help_smt[(size_t)t * G + g], node_smt);
    }
    cpu_free[w_m[w]] -= phys_cores(t_misc[t], t_misc_smt[t], node_smt);
    for (int j = 0; j < gpus_at; ++j) {
      const int gi = gpus_row[j];
      gpu_free[gpu_numa[gi]] -= 1;
      gpu_free_sw[gpu_sw_dense[gi]] -= 1;
    }
    // NIC bandwidth: joint per (u,k); pods_used once per distinct claimed NIC
    for (int g = 0; g < G; ++g) {
      const float rx = t_rx[(size_t)t * G + g], tx = t_tx[(size_t)t * G + g];
      if (rx <= 0.0f && tx <= 0.0f) continue;
      const int uk = numa_of[g] * K + pick_of[g];
      nic_rx_used[uk] += rx;
      nic_tx_used[uk] += tx;
      int first = 1;  // claimed already this pod?
      for (int h = 0; h < g; ++h) {
        const float hrx = t_rx[(size_t)t * G + h], htx = t_tx[(size_t)t * G + h];
        if ((hrx > 0.0f || htx > 0.0f) &&
            numa_of[h] * K + pick_of[h] == uk) { first = 0; break; }
      }
      if (first) nic_pods[uk] += 1;
      if (enable_sharing) {
        nic_free[uk * 2] = (float)(nic_cap[uk] - nic_rx_used[uk]);
        nic_free[uk * 2 + 1] = (float)(nic_cap[uk] - nic_tx_used[uk]);
      } else {
        nic_free[uk * 2] = 0.0f;
        nic_free[uk * 2 + 1] = 0.0f;
      }
    }
    hp_free32_all[n] -= t_hp[t];
    if (set_busy) busy_all[n] = 1;
  }
  return 0;
}

}  // extern "C"
