# Scheduler image (reference: Makefile docker rules). The TPU backend is
# only needed where the solver runs; CPU-only deployments work out of the
# box with jax[cpu].
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/nhd-tpu
COPY pyproject.toml README.md ./
COPY nhd_tpu ./nhd_tpu
COPY native ./native

# compile the native core BEFORE install so the .so ships inside the
# installed package (pyproject package-data includes nhd_tpu/native/*.so)
RUN g++ -O2 -shared -fPIC -o nhd_tpu/native/_libnhd.so native/nhd_assign.cc \
    && pip install --no-cache-dir "jax[cpu]" kubernetes grpcio protobuf \
    && pip install --no-cache-dir .

EXPOSE 45655
ENTRYPOINT ["nhd-tpu"]
