"""Scheduling-policy engine: heterogeneity-aware scoring, priority
tiers, and bounded preemption (ROADMAP "Heterogeneity-aware scoring and
preemption as new workloads"; Gavel, PAPERS.md "Heterogeneity-Aware
Cluster Scheduling Policies for Deep Learning Workloads").

The feasibility solver PRs 1-14 built answers "where CAN this pod run";
this package answers "where SHOULD it run, and who yields when it
can't":

* **Node classes** (:mod:`nhd_tpu.policy.classes`) — fleet hardware
  generations, derived from node labels at encode time and interned to
  small ints exactly like node groups. Every node row carries its class
  index in the packed cluster arrays (``ClusterArrays.node_class``).
* **Throughput scoring** (:mod:`nhd_tpu.policy.scoring`) — a
  per-(workload-kind, node-class) throughput matrix projected into
  per-pod-type score rows (``PodTypeArrays.class_score``) that ride the
  fused solve+rank megaround as extra vmapped score terms: the ranking
  key becomes (score, gpuless-preference, low-node-index). With
  ``NHD_POLICY=0`` the rows are all-zero and placements are bit-exact
  with the pre-policy scheduler; a uniform matrix is placement-neutral
  by construction (a constant per-type shift cannot reorder nodes).
* **Bounded preemption** (:mod:`nhd_tpu.policy.preempt`) — pods carry a
  priority tier; when a higher-tier pod is unplaceable the planner
  picks a minimal victim set (lowest tier first, finish-time-fairness
  tiebreak) under per-round and per-tenant budgets. Execution lives in
  scheduler/core.py and rides the existing unwind+requeue path through
  the fenced ``_commit_write`` chokepoint — never an unfenced eviction.

Everything here is dormant until ``NHD_POLICY=1`` (read per call, so
tests and chaos cells toggle it without rebuilding schedulers);
docs/SCHEDULING_POLICIES.md is the operator story.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Tuple


def enabled() -> bool:
    """The policy master switch (``NHD_POLICY``, default off). Read at
    call time — the pinned bit-exactness contract is that everything in
    this package is inert when it reads false."""
    return os.environ.get("NHD_POLICY", "0") == "1"


def preemption_enabled() -> bool:
    """Preemption rides the master switch; ``NHD_POLICY_PREEMPT=0``
    keeps scoring while disabling eviction (scoring-only posture)."""
    return enabled() and os.environ.get("NHD_POLICY_PREEMPT", "1") == "1"


# ---------------------------------------------------------------------------
# policy counters — the labeled complement of the scalar
# nhd_policy_* families in k8s/retry.py ApiCounters (rendered as
# nhd_policy_preemptions_total{tier=...} by rpc/metrics.py)
# ---------------------------------------------------------------------------

#: tier label vocabulary bound (NHD603 stance: metric label sets must be
#: finite) — tiers at or past the bound fold into the top bucket
MAX_TIER_LABEL = 7

_LOCK = threading.Lock()
_PREEMPT_BY_TIER: Dict[int, int] = {}
#: (preemptor_tier, victim_tier) pairs — the chaos harness's
#: tier-inversion invariant reads these (every victim must be strictly
#: lower-tier than its preemptor)
_PREEMPT_PAIRS: List[Tuple[int, int]] = []


def note_preemption(preemptor_tier: int, victim_tier: int) -> None:
    """Record one executed eviction (called by the scheduler AFTER the
    fenced evict landed, never for planned-but-fenced-off ones)."""
    t = max(0, min(int(victim_tier), MAX_TIER_LABEL))
    with _LOCK:
        _PREEMPT_BY_TIER[t] = _PREEMPT_BY_TIER.get(t, 0) + 1
        if len(_PREEMPT_PAIRS) < 65536:  # bounded witness ring
            _PREEMPT_PAIRS.append((int(preemptor_tier), int(victim_tier)))


def preempt_tier_snapshot() -> Dict[int, int]:
    """{victim tier: evictions} this process executed."""
    with _LOCK:
        return dict(_PREEMPT_BY_TIER)


def preempt_pairs() -> List[Tuple[int, int]]:
    """(preemptor tier, victim tier) witness list (bounded)."""
    with _LOCK:
        return list(_PREEMPT_PAIRS)


def reset_policy_metrics() -> None:
    """Test/chaos-cell isolation: zero the policy registries."""
    with _LOCK:
        _PREEMPT_BY_TIER.clear()
        _PREEMPT_PAIRS.clear()
