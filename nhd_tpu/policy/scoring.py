"""Heterogeneity-aware scoring: the throughput matrix → score rows.

Gavel's core observation (PAPERS.md): on mixed hardware, placement
QUALITY is a per-(workload, accelerator-generation) throughput matrix,
not a boolean. This module owns that matrix and projects it into the
dense form the fused megaround consumes: one int32 row of
:data:`~nhd_tpu.policy.classes.MAX_CLASSES` quantized scores per pod
TYPE (``PodTypeArrays.class_score``), gathered against each node row's
class index (``ClusterArrays.node_class``) inside the jitted program —
the batch-scheduler-architecture stance (PAPERS.md): the policy layer
is vectorized terms inside the existing solve, never a host-side
re-rank.

Matrix source: ``NHD_POLICY_TPUT`` — inline JSON, or ``@/path`` to a
JSON file (the TriadSet/operator config hook) — shaped::

    {"gpu": {"gen-a": 1.0, "gen-b": 0.55}, "cpu": {"gen-a": 1.0}}

Outer keys are workload kinds (:func:`workload_kind`), inner keys node
classes; missing entries default to 1.0 (uniform). Scores quantize to
0..SCORE_QUANT relative to the kind's best class, so a uniform matrix
yields a CONSTANT row per type — a per-type constant shift of the
ranking value cannot reorder nodes, making "uniform" placement-neutral
by construction. With ``NHD_POLICY=0`` the rows are all-zero and the
ranking value is bit-identical to the pre-policy formula (the pinned
control).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

import numpy as np

from nhd_tpu.policy import enabled
from nhd_tpu.policy.classes import CLASSES, DEFAULT_CLASS, MAX_CLASSES

#: score quantization ceiling. The ranking value packs
#: (score * 3 + pref) * (Np + 1) into int32 (kernel._rank_body consumers)
#: — at 255 the node axis may reach ~2.7M rows before overflow, far past
#: the streaming tiler's per-solve tile bound.
SCORE_QUANT = 255

# score-mode constants (the nhd_policy_score_mode gauge)
MODE_OFF = 0
MODE_UNIFORM = 1
MODE_MATRIX = 2

_LOCK = threading.Lock()
#: the live matrix ({} = uniform); None = not loaded yet (env consulted)
_MATRIX: Optional[Dict[str, Dict[str, float]]] = None
#: the raw NHD_POLICY_TPUT string the cached matrix was parsed from —
#: a changed env re-parses at the next lookup (operators flip matrices
#: without a restart; /metrics' score_mode gauge would otherwise report
#: the first-seen posture forever). None = matrix came from set_matrix.
_MATRIX_RAW: Optional[str] = None
_MATRIX_GEN = 0
_ROW_CACHE: Dict[tuple, np.ndarray] = {}


def _load_env_matrix() -> Dict[str, Dict[str, float]]:
    raw = os.environ.get("NHD_POLICY_TPUT", "").strip()
    if not raw:
        return {}
    try:
        if raw.startswith("@"):
            with open(raw[1:]) as fh:
                data = json.load(fh)
        else:
            data = json.loads(raw)
        if not isinstance(data, dict):
            raise ValueError("matrix must be a JSON object")
        return {
            str(kind): {str(c): float(v) for c, v in (classes or {}).items()}
            for kind, classes in data.items()
        }
    except (OSError, ValueError) as exc:
        from nhd_tpu.utils import get_logger

        # a malformed matrix degrades to uniform scoring (feasibility
        # first — a config typo must never unschedule the fleet)
        get_logger(__name__).error(
            f"NHD_POLICY_TPUT unreadable ({exc}); using the uniform matrix"
        )
        return {}


def _matrix() -> Dict[str, Dict[str, float]]:
    global _MATRIX, _MATRIX_RAW, _MATRIX_GEN
    raw = os.environ.get("NHD_POLICY_TPUT", "").strip()
    with _LOCK:
        if _MATRIX is None or (
            _MATRIX_RAW is not None and raw != _MATRIX_RAW
        ):
            _MATRIX = _load_env_matrix()
            _MATRIX_RAW = raw
            _MATRIX_GEN += 1
            _ROW_CACHE.clear()
        return _MATRIX


def set_matrix(matrix: Optional[Dict[str, Dict[str, float]]]) -> None:
    """Install a throughput matrix programmatically (bench, chaos,
    tests) — a programmatic matrix pins itself (env changes ignored
    until re-armed). ``None`` re-arms the env load; ``{}`` forces
    uniform."""
    global _MATRIX, _MATRIX_RAW, _MATRIX_GEN
    with _LOCK:
        _MATRIX = matrix
        _MATRIX_RAW = None
        _MATRIX_GEN += 1
        _ROW_CACHE.clear()


def score_mode() -> int:
    """0 off / 1 uniform / 2 matrix — the nhd_policy_score_mode gauge."""
    if not enabled():
        return MODE_OFF
    return MODE_MATRIX if _matrix() else MODE_UNIFORM


def scoring_active() -> bool:
    """True when scoring can actually REORDER placements (a non-uniform
    matrix under NHD_POLICY=1). Gates the paths that cannot honor score
    terms — the speculative megaround falls back to classic rounds so
    round-0 claims never bypass the policy ranking."""
    return score_mode() == MODE_MATRIX


def workload_kind(req) -> str:
    """A PodRequest's throughput-matrix row key. Deliberately coarse
    (GPU-driven vs CPU-only — the axis generations actually differ on);
    finer keys can join later without touching the solver: the kind is
    host-side, the device only ever sees the projected row."""
    return "gpu" if req.needs_gpu else "cpu"


def _quantize(vals: Dict[str, float]) -> Dict[str, int]:
    """Relative quantization: the kind's best class scores SCORE_QUANT,
    the rest proportionally; missing classes score the default 1.0
    relative to that best."""
    best = max(list(vals.values()) + [1.0])
    return {
        c: max(0, min(SCORE_QUANT, round(v / best * SCORE_QUANT)))
        for c, v in vals.items()
    }


def score_row(req) -> np.ndarray:
    """The [MAX_CLASSES] int32 score row for one pod type (encode-time
    hook: solver/encode.py encode_pods calls this per DISTINCT type).
    All-zero with the policy off (the bit-exactness control); one cached
    row per (kind, matrix generation, interner generation) otherwise."""
    if not enabled():
        return np.zeros(MAX_CLASSES, np.int32)
    kind = workload_kind(req)
    key = (kind, _MATRIX_GEN, CLASSES.generation)
    with _LOCK:
        row = _ROW_CACHE.get(key)
        if row is not None:
            return row
    m = _matrix().get(kind, {})
    q = _quantize(m)
    default_q = q.get(DEFAULT_CLASS)
    if default_q is None:
        best = max(list(m.values()) + [1.0])
        default_q = max(0, min(SCORE_QUANT, round(1.0 / best * SCORE_QUANT)))
    row = np.full(MAX_CLASSES, default_q, np.int32)
    for i, name in enumerate(CLASSES.names()[:MAX_CLASSES]):
        row[i] = q.get(name, default_q)
    with _LOCK:
        if len(_ROW_CACHE) > 4096:
            _ROW_CACHE.clear()
        _ROW_CACHE[key] = row
    return row


def throughput(req_kind: str, class_name: str) -> float:
    """Raw (unquantized) matrix lookup — the bench's ground-truth
    aggregate-placed-throughput figure reads this."""
    return _matrix().get(req_kind, {}).get(class_name, 1.0)
