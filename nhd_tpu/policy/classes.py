"""Node classes: fleet hardware generations as interned small ints.

A real fleet mixes node generations (ROADMAP heterogeneity item); the
solver's packed cluster arrays carry each node's class as one int32 per
row (``ClusterArrays.node_class``) so the fused megaround can gather
per-(pod-type, class) throughput scores without any host re-rank.

Class names come off node labels at encode time (core/node.py stores
``HostNode.node_class`` at label parse: the explicit ``NHD_NODE_CLASS``
label when present, else a GPU-model-derived default, else ``cpu``) and
intern here — the same move as the node-group bitmask interner
(solver/encode.py GroupInterner), except class indices are meaningful
per NAME, not per position, so interning order never matters for
correctness and a new class mid-stream is a plain row patch, not a
delta-layer rebuild trigger.

The interner is process-global: node encodes and pod score rows
(policy/scoring.py) must agree on indices, and several live contexts
(streaming tiles, chaos replicas) share one process. The index space is
bounded at :data:`MAX_CLASSES` — the ``class_score`` tensor's fixed row
width, so the fused program shapes never re-specialize on fleet
diversity; classes past the bound fold into index 0 (scored as the
default class) with one warning.
"""

from __future__ import annotations

import threading
from typing import Dict, List

#: fixed width of the per-type score row (PodTypeArrays.class_score):
#: a compile-time constant so class diversity never re-traces programs
MAX_CLASSES = 16

#: index 0 is the default class — unlabeled nodes, and the overflow
#: bucket when a fleet exceeds MAX_CLASSES distinct classes
DEFAULT_CLASS = "default"


class ClassInterner:
    """Class name → stable small int (0 = the default class)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._idx: Dict[str, int] = {DEFAULT_CLASS: 0}
        self._names: List[str] = [DEFAULT_CLASS]
        #: bumps when a new name interns — scoring row caches key on it
        self.generation = 0
        self._warned_overflow = False

    def index(self, name: str) -> int:
        """The class's row index, interning on first sight. Past
        MAX_CLASSES distinct names, folds to 0 (default scoring)."""
        if not name:
            return 0
        with self._lock:
            i = self._idx.get(name)
            if i is not None:
                return i
            if len(self._names) >= MAX_CLASSES:
                if not self._warned_overflow:
                    self._warned_overflow = True
                    from nhd_tpu.utils import get_logger

                    get_logger(__name__).warning(
                        f"more than {MAX_CLASSES} distinct node classes; "
                        f"folding {name!r} (and any further classes) into "
                        "the default class for scoring"
                    )
                return 0
            i = len(self._names)
            self._idx[name] = i
            self._names.append(name)
            self.generation += 1
            return i

    def names(self) -> List[str]:
        with self._lock:
            return list(self._names)

    def name_of(self, i: int) -> str:
        with self._lock:
            return self._names[i] if 0 <= i < len(self._names) else DEFAULT_CLASS

    @property
    def n_classes(self) -> int:
        with self._lock:
            return len(self._names)


#: the process-global interner every encode and score row shares
CLASSES = ClassInterner()


def node_class_index(node) -> int:
    """The packed-row class index of one HostNode (encode-time hook:
    solver/encode.py calls this per row)."""
    return CLASSES.index(getattr(node, "node_class", DEFAULT_CLASS))
