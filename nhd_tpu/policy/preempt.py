"""Bounded preemption: minimal victim sets under explicit budgets.

When a higher-tier pod has no candidate node, the planner asks the only
question the feasibility solver can't: *who should yield?* The answer is
deliberately conservative (Gavel's policy stance, PAPERS.md):

* victims must be STRICTLY lower tier than the preemptor;
* lowest tier evicts first; within a tier the finish-time-fairness
  tiebreak prefers the most recently bound pod (least progress lost —
  the cheapest work to redo);
* the victim set is minimal per node (victims release one at a time and
  the single-node oracle re-judges feasibility after each — the first
  feasible prefix wins), and the chosen node is the one needing the
  fewest victims (ties: lowest victim-tier sum, then node order);
* per-round and per-tenant budgets bound every step of a storm: a
  planner that would exceed either returns "budget-exhausted" instead
  of a plan.

Planning is PURE with respect to cluster state: victims release on the
live mirror node only long enough to ask the oracle, then re-claim —
the scheduler thread owns the mirror, so the probe is invisible to
every other consumer. Execution (the fenced evict + unwind + requeue)
lives in scheduler/core.py; this module never touches a backend.

Determinism: given the same mirror, pod-state and budgets, the plan is
a pure function — node iteration order is the mirror's dict order,
victim order is (tier, -bound_at, name) — pinned by the property test
in tests/test_policy.py.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: candidate-node scan bound: preemption is an exceptional-path operator
#: action, not a hot path, but a federation-scale mirror must not pay an
#: O(nodes × victims) oracle walk per unplaceable pod — the first
#: PLAN_SCAN_MAX nodes holding eligible victims are considered
PLAN_SCAN_MAX = 64


def round_budget() -> int:
    """Max evictions one scheduling batch may execute
    (``NHD_POLICY_PREEMPT_ROUND_BUDGET``)."""
    return int(os.environ.get("NHD_POLICY_PREEMPT_ROUND_BUDGET", "4"))


def tenant_budget() -> int:
    """Max evictions one batch may charge a single tenant (namespace)
    (``NHD_POLICY_PREEMPT_TENANT_BUDGET``)."""
    return int(os.environ.get("NHD_POLICY_PREEMPT_TENANT_BUDGET", "2"))


def max_attempts() -> int:
    """Preemption attempts per pod before it takes the plain
    unschedulable verdict (``NHD_POLICY_PREEMPT_ATTEMPTS``) — the
    livelock bound: a pod that preempts and still can't place (races,
    fragmentation) stops burning victims."""
    return int(os.environ.get("NHD_POLICY_PREEMPT_ATTEMPTS", "2"))


@dataclass
class PreemptBudget:
    """One scheduling batch's remaining eviction allowance."""

    round_left: int
    tenant_cap: int
    tenant_used: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def fresh(cls) -> "PreemptBudget":
        return cls(round_left=round_budget(), tenant_cap=tenant_budget())

    def admits(self, victims: List[Tuple[str, str, int]]) -> bool:
        """Whether this victim list fits the remaining allowance."""
        if len(victims) > self.round_left:
            return False
        per_ns: Dict[str, int] = {}
        for ns, _pod, _tier in victims:
            per_ns[ns] = per_ns.get(ns, 0) + 1
        return all(
            self.tenant_used.get(ns, 0) + n <= self.tenant_cap
            for ns, n in per_ns.items()
        )

    def charge(self, victims: List[Tuple[str, str, int]]) -> None:
        self.round_left -= len(victims)
        for ns, _pod, _tier in victims:
            self.tenant_used[ns] = self.tenant_used.get(ns, 0) + 1

    def state(self) -> dict:
        """The budget snapshot decision records carry."""
        return {
            "round_left": self.round_left,
            "tenant_cap": self.tenant_cap,
            "tenant_used": dict(self.tenant_used),
        }


@dataclass
class PreemptionPlan:
    """A minimal victim set on one node, within budget."""

    node: str
    #: (ns, pod, tier) in eviction order
    victims: List[Tuple[str, str, int]]

    @property
    def tier_sum(self) -> int:
        return sum(t for _ns, _pod, t in self.victims)


def _eligible_victims(
    node, tier: int, pod_tiers: Dict[Tuple[str, str], Tuple[int, float]],
) -> List[Tuple[str, str, int]]:
    """Strictly-lower-tier pods on *node*, in eviction preference order:
    lowest tier first, then most recently bound (finish-time fairness —
    least progress lost), then name (the determinism pin)."""
    out = []
    for (pod, ns) in node.pod_info:
        vt, bound_at = pod_tiers.get((ns, pod), (0, 0.0))
        if vt < tier:
            out.append((vt, -bound_at, ns, pod))
    out.sort()
    return [(ns, pod, vt) for vt, _mb, ns, pod in out]


def _probe_node(
    node, name: str, req, victims: List[Tuple[str, str, int]],
    budget: PreemptBudget, *, now, respect_busy,
) -> Optional[List[Tuple[str, str, int]]]:
    """The minimal feasible victim PREFIX on one node, or None.

    Victims release on the live node one at a time; after each release
    the single-node oracle re-judges the preemptor. Whatever happens,
    every released topology re-claims before return — the probe must be
    invisible (the scheduler thread owns the mirror, so nothing can
    observe the window)."""
    from nhd_tpu.solver.oracle import find_node

    released: List[Tuple[Tuple[str, str], object]] = []
    single = {name: node}
    try:
        for i, (ns, pod, vt) in enumerate(victims):
            top = node.pod_info.get((pod, ns))
            if top is None:
                continue
            node.release_from_topology(top)
            released.append(((ns, pod), top))
            prefix = victims[: i + 1]
            if not budget.admits(prefix):
                return None
            if find_node(
                single, req, now=now, respect_busy=respect_busy
            ) is not None:
                return list(prefix)
        return None
    finally:
        # exact inverse, reverse order: claim_from_topology re-claims
        # the same physical IDs release_from_topology freed
        for (_key, top) in reversed(released):
            if not node.claim_from_topology(top):
                from nhd_tpu.utils import get_logger

                # should be unreachable (same IDs, same node); if the
                # mirror really can't re-claim, say so loudly — the
                # reconcile net repairs from the cluster
                get_logger(__name__).error(
                    f"preemption probe could not restore a claim on "
                    f"{name}; mirror may need a reconcile pass"
                )


def plan_preemption(
    nodes: Dict[str, "object"],
    req,
    tier: int,
    pod_tiers: Dict[Tuple[str, str], Tuple[int, float]],
    budget: PreemptBudget,
    *,
    now: Optional[float] = None,
    respect_busy: bool = True,
) -> Tuple[Optional[PreemptionPlan], str]:
    """The minimal-victim plan for one unplaceable pod, or (None, why).

    ``pod_tiers`` maps (ns, pod) → (tier, bound_at) for bound pods (the
    scheduler's pod_state projection). ``why`` is "ok", "no-plan"
    (no victim set makes the pod feasible) or "budget-exhausted" (a
    feasible set exists but the round/tenant budgets refuse it — the
    nhd_policy_preempt_budget_exhausted_total signal)."""
    if tier <= 0:
        return None, "no-plan"
    best: Optional[PreemptionPlan] = None
    saw_budget_refusal = False
    scanned = 0
    for name, node in nodes.items():
        if not node.active or node.maintenance:
            continue
        if not (req.node_groups & set(node.groups)):
            continue
        victims = _eligible_victims(node, tier, pod_tiers)
        if not victims:
            continue
        scanned += 1
        if scanned > PLAN_SCAN_MAX:
            break
        # budget-blind probe first: distinguishes "no plan exists" from
        # "a plan exists but the budget refuses it" (different verdicts,
        # different metrics)
        blind = PreemptBudget(round_left=len(victims), tenant_cap=len(victims))
        prefix = _probe_node(
            node, name, req, victims, blind,
            now=now, respect_busy=respect_busy,
        )
        if prefix is None:
            continue
        if not budget.admits(prefix):
            saw_budget_refusal = True
            continue
        plan = PreemptionPlan(node=name, victims=prefix)
        if (
            best is None
            or len(plan.victims) < len(best.victims)
            or (
                len(plan.victims) == len(best.victims)
                and plan.tier_sum < best.tier_sum
            )
        ):
            best = plan
            if len(best.victims) == 1 and best.tier_sum == 0:
                break  # cannot do better than one tier-0 victim
    if best is not None:
        return best, "ok"
    return None, ("budget-exhausted" if saw_budget_refusal else "no-plan")
