"""Solver JIT program accounting: cache hits vs. recompiles, bucket shapes.

Batch-solver throughput on accelerators lives or dies by compiled-program
reuse (the pow-2 shape bucketing, SURVEY §7 hard part 3) — and a recompile
storm is *silent*: the run just gets multi-second stalls wherever a new
(bucket, padded-dims) shape first appears (r4/r5 measured fresh megaround
traces at ~1 s each through the tunnel). This module makes reuse a
scrapeable signal.

Every solver dispatch site (kernel.py, solver/device_state.py) reports a
*shape key* — the dims XLA specializes on (bucket G/U/K, padded type and
node axes, rank width). A key seen for the first time is a compile;
every later use of the same key is a cache hit. That approximates XLA's
own cache exactly as long as keys include every specializing dim, which
is the contract dispatch sites uphold. Exported via /metrics
(rpc/metrics.py): hit/compile counters plus per-shape use counts — the
bucket-shape occupancy table.
"""

from __future__ import annotations

import threading
from typing import Dict


class JitStats:
    """Thread-safe dispatch/compile accounting keyed by shape."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._uses: Dict[str, int] = {}
        self._calls = 0
        self._compiles = 0
        # per-(phase, shape-bucket) wall seconds + event counts: the
        # round-phase attribution the perf-telemetry pipeline folds into
        # bench artifacts and /metrics (ISSUE 7). Keys are
        # "phase:shape"; shapes come from the solver's bucket keys, so
        # cardinality is bounded by the compiled-program table.
        self._phase_seconds: Dict[str, float] = {}
        self._phase_counts: Dict[str, int] = {}

    def record_use(self, kind: str, shape_key: str) -> None:
        """One solver dispatch of *kind* at *shape_key* (the dims the
        compiled program specializes on). First sighting = a compile."""
        key = f"{kind}:{shape_key}"
        with self._lock:
            self._calls += 1
            if key not in self._uses:
                self._compiles += 1
                self._uses[key] = 0
            self._uses[key] += 1

    def record_phase(self, phase: str, shape_key: str, seconds: float) -> None:
        """Attribute *seconds* of round wall time to *phase* at
        *shape_key* (the cluster/bucket shape the round ran at) — fed by
        BatchStats.phase_add, so every solver phase the overhead war
        tracks lands here with its shape context."""
        key = f"{phase}:{shape_key}"
        with self._lock:
            self._phase_seconds[key] = (
                self._phase_seconds.get(key, 0.0) + seconds
            )
            self._phase_counts[key] = self._phase_counts.get(key, 0) + 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "calls_total": self._calls,
                "compiles_total": self._compiles,
                "cache_hits_total": self._calls - self._compiles,
                "distinct_programs": len(self._uses),
                "shapes": dict(self._uses),
                "phase_seconds": dict(self._phase_seconds),
                "phase_counts": dict(self._phase_counts),
            }

    def reset(self) -> None:
        with self._lock:
            self._uses = {}
            self._calls = 0
            self._compiles = 0
            self._phase_seconds = {}
            self._phase_counts = {}


#: process-wide registry (one jit cache per process, one counter set)
JIT_STATS = JitStats()
