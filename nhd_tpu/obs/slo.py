"""SLO engine: true end-to-end time-to-bind + multi-window burn rates.

The latency story so far measures what one process saw: ``t_enqueue`` is
minted at watch receipt, so a pod that spilled across shards, rode a
handoff, or outlived a replica restart re-enters the clock at zero every
hop — the operator-facing "how long did this pod actually wait?" cannot
be answered from any one replica's histograms. This module measures from
the pod's **creationTimestamp** (``ClusterBackend.get_pod_created``),
which the cluster owns: the stamp survives every spill, handoff, and
crash, and every replica computes the same figure (ISSUE 7).

On top of the raw observations the tracker keeps **multi-window burn
rates** (the Google SRE workbook shape): with an objective of "fraction
``good_fraction`` of pods bind within ``target_sec``", the burn rate
over a window is ``breach_ratio / (1 - good_fraction)`` — 1.0 means the
error budget burns exactly at the sustainable rate, 14.4 over 1 h is the
classic page threshold. Exported as ``nhd_slo_*`` families
(rpc/metrics.py) and folded into the fleet artifact (obs/fleet.py).

Stdlib-only, one lock, bounded memory: observations aggregate into
fixed-width time buckets (720 per widest window), so coverage of the
full window is independent of bind rate — an event ring capped by COUNT
would silently truncate the 1 h window at anything past cap/3600
binds/s, under-reporting the burn exactly during the storm that should
page. ``clock`` is injectable so chaos runs drive the windows off the
sim's step clock.
"""

from __future__ import annotations

import os
import threading
import time
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from nhd_tpu.obs.histo import DEFAULT_BUCKETS, quantile_from_buckets

#: default objective: this fraction of pods bind within the target
SLO_BIND_TARGET_SEC = float(os.environ.get("NHD_SLO_BIND_SEC", "30"))
SLO_GOOD_FRACTION = float(os.environ.get("NHD_SLO_GOOD_FRACTION", "0.99"))

#: burn-rate windows, seconds (label, width) — the 5m/1h fast/slow pair
BURN_WINDOWS: Tuple[Tuple[str, float], ...] = (("5m", 300.0), ("1h", 3600.0))

#: metric family names this module renders (without the nhd_ prefix) —
#: also the lint registration source for the NHD6xx metrics pack
METRIC_FAMILIES = (
    "slo_bind_target_seconds",
    "slo_bind_good_fraction",
    "slo_bind_observations_total",
    "slo_bind_breaches_total",
    "slo_bind_max_seconds",
    "slo_bind_burn_rate",
    "slo_tenant_observations_total",
    "slo_tenant_breaches_total",
    "slo_tenant_max_seconds",
    "slo_tenant_p99_seconds",
)

#: cap on distinct tenant labels (NHD603: label sets must be bounded by
#: construction — namespaces are operator-created but not bounded, so
#: past the cap new tenants aggregate under "other" instead of growing
#: the family per namespace)
TENANT_LABEL_MAX = 32
TENANT_OVERFLOW = "other"


class SloTracker:
    """Thread-safe time-to-bind SLO accounting for one replica."""

    def __init__(
        self,
        *,
        target_sec: float = SLO_BIND_TARGET_SEC,
        good_fraction: float = SLO_GOOD_FRACTION,
        windows: Sequence[Tuple[str, float]] = BURN_WINDOWS,
        clock: Callable[[], float] = time.time,
    ):
        if target_sec <= 0:
            raise ValueError(f"target_sec must be > 0, got {target_sec}")
        if not 0.0 < good_fraction < 1.0:
            raise ValueError(
                f"good_fraction must be in (0, 1), got {good_fraction}"
            )
        self.target_sec = target_sec
        self.good_fraction = good_fraction
        self.windows = tuple(windows)
        self._clock = clock
        self._lock = threading.Lock()
        # time-bucketed (total, breached) aggregates: 720 buckets span
        # the widest window, so window coverage never depends on bind
        # rate; memory stays O(buckets) forever via lazy eviction
        self._max_window = max((w for _, w in self.windows), default=3600.0)
        self._bucket_sec = self._max_window / 720.0
        self._buckets: Dict[int, List[int]] = {}
        self._total = 0
        self._breaches = 0
        self._max_seen = 0.0
        # per-tenant views (ISSUE 20): tenant → [count, breaches, max,
        # latency bucket counts] over the shared DEFAULT_BUCKETS edges —
        # p99 comes from the same interpolated-quantile estimate every
        # scrape-side percentile uses (obs/histo.quantile_from_buckets).
        # Bounded at TENANT_LABEL_MAX; overflow aggregates as "other".
        self._tenant_edges = DEFAULT_BUCKETS
        self._tenants: Dict[str, list] = {}

    # -- producers ------------------------------------------------------

    def observe(
        self,
        tt_bind: float,
        now: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> bool:
        """One bound pod's creation→bind seconds; returns whether it
        breached the target. ``tenant`` (the pod's namespace) feeds the
        per-tenant view the tenant-storm isolation invariant gates on."""
        now = self._clock() if now is None else now
        breached = tt_bind > self.target_sec
        with self._lock:
            self._total += 1
            if breached:
                self._breaches += 1
            self._max_seen = max(self._max_seen, tt_bind)
            key = int(now // self._bucket_sec)
            bucket = self._buckets.get(key)
            if bucket is None:
                self._buckets[key] = bucket = [0, 0]
            bucket[0] += 1
            if breached:
                bucket[1] += 1
            # lazy eviction: only when the map outgrows ~2 windows'
            # worth of buckets, drop everything already aged out
            if len(self._buckets) > 1444:
                floor_key = int((now - self._max_window) // self._bucket_sec)
                self._buckets = {
                    k: v for k, v in self._buckets.items() if k >= floor_key
                }
            if tenant is not None:
                self._observe_tenant_locked(tenant, tt_bind, breached)
        return breached

    def _observe_tenant_locked(
        self, tenant: str, tt_bind: float, breached: bool
    ) -> None:
        if tenant not in self._tenants and len(self._tenants) >= TENANT_LABEL_MAX:
            tenant = TENANT_OVERFLOW
        state = self._tenants.get(tenant)
        if state is None:
            state = [0, 0, 0.0, [0] * (len(self._tenant_edges) + 1)]
            # _locked suffix contract: observe() holds _lock here
            self._tenants[tenant] = state  # nhdlint: ignore[NHD201]
        state[0] += 1
        if breached:
            state[1] += 1
        state[2] = max(state[2], tt_bind)
        state[3][bisect_left(self._tenant_edges, tt_bind)] += 1

    # -- consumers ------------------------------------------------------

    def burn_rate(self, window_sec: float, now: Optional[float] = None) -> float:
        """breach_ratio within the window / the error budget. 0.0 when
        the window saw no binds (no traffic burns no budget). A bucket
        counts while any of its span is inside the window (resolution:
        max_window/720 — 5 s at the default 1 h)."""
        now = self._clock() if now is None else now
        cutoff = now - window_sec
        with self._lock:
            total = bad = 0
            for key, (n, breached) in self._buckets.items():
                if (key + 1) * self._bucket_sec > cutoff:
                    total += n
                    bad += breached
        if total == 0:
            return 0.0
        return (bad / total) / (1.0 - self.good_fraction)

    def tenant_p99(self, tenant: str) -> float:
        """Interpolated p99 time-to-bind for one tenant (0.0 when the
        tenant never bound a pod) — the tenant-storm isolation
        invariant's measured quantity."""
        with self._lock:
            state = self._tenants.get(tenant)
            if state is None:
                return 0.0
            counts = list(state[3])
        return self._p99_from_counts(counts)

    def _p99_from_counts(self, counts: List[int]) -> float:
        pairs = []
        running = 0
        for edge, c in zip(self._tenant_edges, counts):
            running += c
            pairs.append((edge, running))
        pairs.append((float("inf"), running + counts[-1]))
        return quantile_from_buckets(pairs, 0.99)

    def snapshot(self, now: Optional[float] = None) -> dict:
        now = self._clock() if now is None else now
        with self._lock:
            total, breaches = self._total, self._breaches
            max_seen = self._max_seen
            tenants = {
                name: {
                    "observations_total": st[0],
                    "breaches_total": st[1],
                    "max_seconds": st[2],
                    "counts": list(st[3]),
                }
                for name, st in self._tenants.items()
            }
        for view in tenants.values():
            view["p99_seconds"] = self._p99_from_counts(view.pop("counts"))
        return {
            "target_sec": self.target_sec,
            "good_fraction": self.good_fraction,
            "observations_total": total,
            "breaches_total": breaches,
            "max_seconds": max_seen,
            "burn_rates": {
                label: self.burn_rate(width, now)
                for label, width in self.windows
            },
            "tenants": tenants,
        }

    def render(self, prefix: str = "nhd_") -> List[str]:
        """Prometheus text exposition for the nhd_slo_* families."""
        snap = self.snapshot()
        lines = []
        for name, kind, help_text, value in (
            ("slo_bind_target_seconds", "gauge",
             "Time-to-bind SLO target (creation to bound)",
             snap["target_sec"]),
            ("slo_bind_good_fraction", "gauge",
             "Fraction of binds that must meet the target",
             snap["good_fraction"]),
            ("slo_bind_observations_total", "counter",
             "Binds measured against the SLO (creationTimestamp clock)",
             snap["observations_total"]),
            ("slo_bind_breaches_total", "counter",
             "Binds that exceeded the SLO target",
             snap["breaches_total"]),
            ("slo_bind_max_seconds", "gauge",
             "Largest creation-to-bind seconds observed",
             snap["max_seconds"]),
        ):
            lines += [
                f"# HELP {prefix}{name} {help_text}",
                f"# TYPE {prefix}{name} {kind}",
                f"{prefix}{name} {value}",
            ]
        lines += [
            f"# HELP {prefix}slo_bind_burn_rate Error-budget burn rate "
            "(1.0 = burning exactly the sustainable rate)",
            f"# TYPE {prefix}slo_bind_burn_rate gauge",
        ]
        for label, rate in sorted(snap["burn_rates"].items()):
            lines.append(
                f'{prefix}slo_bind_burn_rate{{window="{label}"}} {rate}'
            )
        if snap["tenants"]:
            for name, kind, help_text, field in (
                ("slo_tenant_observations_total", "counter",
                 "Binds measured per tenant (namespace, bounded set)",
                 "observations_total"),
                ("slo_tenant_breaches_total", "counter",
                 "Per-tenant binds that exceeded the SLO target",
                 "breaches_total"),
                ("slo_tenant_max_seconds", "gauge",
                 "Per-tenant largest creation-to-bind seconds",
                 "max_seconds"),
                ("slo_tenant_p99_seconds", "gauge",
                 "Per-tenant interpolated p99 creation-to-bind seconds",
                 "p99_seconds"),
            ):
                lines += [
                    f"# HELP {prefix}{name} {help_text}",
                    f"# TYPE {prefix}{name} {kind}",
                ]
                for tenant in sorted(snap["tenants"]):
                    lines.append(
                        f'{prefix}{name}{{tenant="{tenant}"}} '
                        f'{snap["tenants"][tenant][field]}'
                    )
        return lines

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._total = 0
            self._breaches = 0
            self._max_seen = 0.0
            self._tenants.clear()


#: process-global tracker (one replica per process in production; chaos
#: injects per-replica trackers through Scheduler(slo=...))
SLO = SloTracker()
