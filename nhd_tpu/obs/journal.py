"""Record/replay journal: the lossless event log behind the flight recorder.

The span ring (recorder.py) is deliberately *bounded*: once a span is
evicted or the process exits, the traffic that produced a bug is gone.
The journal is the other half of the observability plane — a lossless,
append-only, schema-versioned event log that captures everything the
scheduler needs to re-drive a run (sim/replay.py):

* ``genesis``  — initial node inventory, knob snapshot, seed, git rev;
* ``watch``    — every watch event at controller receipt (full payload
  + digest + backend-clock timestamp + corr once minted);
* ``pod_spec`` — pod config text at prepare time (deduped), so replay
  can reconstruct configmaps recorded from a live cluster;
* ``cluster``  — scripted cluster mutations (node add/remove, pod
  create/delete, cordon, label updates) from a sim scenario source;
* ``fault``    — injected transient backend faults (sim/faults.py), so
  recorded fault timing replays exactly;
* ``decision`` — every per-pod scheduling decision record;
* ``commit``   — every commit outcome incl. fenced rejections/requeues.

File format: line 1 is the shared artifact envelope
(obs/artifact.py, ``kind="journal"``) whose payload declares the body
format; every following line is one JSON event object with a monotonic
``seq`` and a backend-clock ``t``. Writes stream to ``<path>.part``
(bounded memory — the buffer flushes every NHD_JOURNAL_FLUSH events) and
``finalize()`` atomically renames into place, so a reader never sees a
torn file and a crashed recording still leaves its flushed prefix.

Hot-path discipline mirrors the recorder: capture sites guard on
``get_journal() is None`` — journaling off costs one module-global read
(the bench_diff-gated ≤2 % budget, docs/bench/BENCH_DIFF_r18.md).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from nhd_tpu.obs.artifact import make_envelope, validate_envelope

#: artifact-envelope coordinates of a journal file's header line
JOURNAL_KIND = "journal"
JOURNAL_SCHEMA_VERSION = 1
#: body-format marker the header payload must carry (bump with format)
BODY_FORMAT = "jsonl-events-v1"

#: every event kind a v1 journal may contain
EVENT_KINDS = (
    "genesis", "watch", "pod_spec", "cluster", "fault", "decision", "commit",
)

#: corrs kept in the corr→seq index for /journey journal refs
_CORR_INDEX_MAX = 4096


def payload_digest(obj: Any) -> str:
    """Short stable digest of any JSON-able payload — lets divergence
    tooling compare watch payloads without byte-diffing full objects."""
    data = json.dumps(obj, sort_keys=True, default=str).encode()
    return hashlib.sha1(data).hexdigest()[:12]


def knob_snapshot() -> Dict[str, Optional[str]]:
    """Environment value (or None) of every registered NHD_* knob —
    recorded at genesis so replay can name configuration drift (the
    NHD_POLICY-flip negative control). Reads are driven off the registry
    so a new knob is snapshotted the day it is registered."""
    from nhd_tpu.config.knobs import KNOBS

    return {knob.name: os.environ.get(knob.name) for knob in KNOBS}


def genesis_nodes(backend) -> List[dict]:
    """Node inventory records for a genesis event, duck-typed off the
    backend's read API (works for FakeClusterBackend and any wrapper
    that delegates reads)."""
    nodes: List[dict] = []
    for name in sorted(backend.get_nodes()):
        cap_gb, _alloc_gb = backend.get_node_hugepage_resources(name)
        nodes.append({
            "name": name,
            "labels": dict(backend.get_node_labels(name) or {}),
            "hugepages_gb": int(cap_gb),
            "addr": backend.get_node_addr(name) or "",
        })
    return nodes


class JournalWriter:
    """Streaming JSONL journal writer. Thread-safe; every capture
    method is a no-op after ``finalize()`` so late producer threads
    cannot corrupt a sealed file."""

    def __init__(
        self,
        path: str,
        *,
        identity: str = "",
        seed: Optional[int] = None,
        flush_every: int = 64,
        clock=time.monotonic,
        rev: Optional[str] = None,
        created: Optional[float] = None,
    ):
        self.path = path
        self.identity = identity
        self.seed = seed
        self.flush_every = max(1, int(flush_every))
        #: timestamp source for event ``t`` — harnesses point this at
        #: the backend/sim clock so replay pacing follows the recorded
        #: domain, not the recorder host's wall clock
        self.clock = clock
        self._part = path + ".part"
        self._lock = threading.RLock()
        self._buf: List[dict] = []
        self._seq = 0
        self._finalized = False
        self._last_watch: Optional[dict] = None
        self._pod_spec_seen: set = set()
        self._corr_seqs: "OrderedDict[str, List[int]]" = OrderedDict()
        self.bytes_written = 0
        self.counts: Dict[str, int] = {k: 0 for k in EVENT_KINDS}
        header = make_envelope(
            JOURNAL_KIND, JOURNAL_SCHEMA_VERSION,
            {"identity": identity, "body": BODY_FORMAT},
            seed=seed, rev=rev, created=created,
        )
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fh = open(self._part, "w")
        line = json.dumps(header, sort_keys=True)
        self._fh.write(line + "\n")
        self._fh.flush()
        self.bytes_written += len(line) + 1

    # -- plumbing -------------------------------------------------------

    def _event(self, kind: str, fields: dict, *, track_watch: bool = False):
        with self._lock:
            if self._finalized:
                return None
            self._seq += 1
            rec: dict = {"seq": self._seq, "t": float(self.clock()), "ev": kind}
            rec.update(fields)
            self._buf.append(rec)
            self.counts[kind] = self.counts.get(kind, 0) + 1
            if track_watch:
                self._last_watch = rec
            if len(self._buf) >= self.flush_every:
                self._flush_locked()
            return rec

    def _flush_locked(self) -> None:
        # callers hold the RLock already; re-entering is free and keeps
        # the buffer mutations visibly under the lock
        with self._lock:
            if not self._buf:
                return
            data = "\n".join(
                json.dumps(r, sort_keys=True, default=str)
                for r in self._buf
            ) + "\n"
            self._fh.write(data)
            # push through Python's IO buffer: the flushed prefix must
            # be readable (and crash-survivable) from the .part file
            self._fh.flush()
            self.bytes_written += len(data)
            self._buf.clear()
            # flushed events are on disk — the corr back-annotation
            # window is closed
            self._last_watch = None

    def _index_corr(self, corr: str, seq: int) -> None:
        seqs = self._corr_seqs.get(corr)
        if seqs is None:
            while len(self._corr_seqs) >= _CORR_INDEX_MAX:
                self._corr_seqs.popitem(last=False)
            seqs = self._corr_seqs[corr] = []
        seqs.append(seq)

    # -- capture API ----------------------------------------------------

    def genesis(
        self,
        nodes: Sequence[dict],
        *,
        knobs: Optional[Dict[str, Optional[str]]] = None,
        seed: Optional[int] = None,
        mode: str = "",
        respect_busy: bool = False,
    ) -> None:
        """Record the initial cluster: node inventory + knob snapshot.
        ``mode`` names the producing harness (``chaos``, ``cli``, ...).
        ``respect_busy`` pins the recording scheduler's busy-window
        setting so replay reconstructs the same placement spread."""
        self._event("genesis", {
            "nodes": [dict(n) for n in nodes],
            "knobs": dict(knob_snapshot() if knobs is None else knobs),
            "seed": self.seed if seed is None else seed,
            "mode": mode,
            "respect_busy": bool(respect_busy),
        })

    def watch_event(self, ev, *, corr: Optional[str] = None) -> None:
        """Record one watch event at receipt. ``ev`` is a
        k8s.interface.WatchEvent (or an equivalent dict) — the FULL
        payload is kept (replay re-drives from it); the digest rides
        along for cheap cross-journal comparison."""
        we = dataclasses.asdict(ev) if dataclasses.is_dataclass(ev) else dict(ev)
        rec = self._event(
            "watch",
            {"we": we, "digest": payload_digest(we), "corr": corr},
            track_watch=True,
        )
        if rec is not None and corr:
            with self._lock:
                self._index_corr(corr, rec["seq"])

    def note_corr(self, corr: str) -> None:
        """Back-annotate the most recent (still-buffered) watch event
        with the corr minted for it — the controller records the event
        before the corr exists. Best-effort: once the event has flushed
        to disk the annotation is dropped (decision/commit events carry
        the corr authoritatively)."""
        with self._lock:
            rec = self._last_watch
            if rec is not None and rec.get("corr") is None:
                rec["corr"] = corr
                self._index_corr(corr, rec["seq"])

    def pod_spec(
        self,
        ns: str,
        pod: str,
        cfg_text: Optional[str],
        *,
        groups: Iterable[str] = (),
        tier: int = 0,
    ) -> None:
        """Record a pod's config text at prepare time (deduped per
        (ns, pod, cfg digest)) — the capture point that makes journals
        recorded from a live cluster self-contained."""
        key = (ns, pod, payload_digest(cfg_text or ""))
        with self._lock:
            if key in self._pod_spec_seen:
                return
            self._pod_spec_seen.add(key)
        self._event("pod_spec", {
            "ns": ns, "pod": pod, "cfg_text": cfg_text,
            "groups": sorted(groups), "tier": int(tier),
        })

    def cluster_event(self, op: str, payload: Optional[dict] = None) -> None:
        """Record one scripted cluster mutation (FakeClusterBackend
        scenario_sink): op name + the mutation's kwargs."""
        self._event("cluster", {"op": op, "args": dict(payload or {})})

    def fault_event(self, op: str, ns: str, pod: str) -> None:
        """Record one injected transient fault (FaultyBackend
        fault_sink) so replay re-injects it at the same call site."""
        self._event("fault", {"op": op, "ns": ns, "pod": pod})

    def decision(self, decision: dict) -> None:
        """Record one per-pod scheduling decision (the recorder's
        record_decision shape) — the divergence diff's ground truth."""
        rec = self._event("decision", {"d": dict(decision)})
        corr = decision.get("corr")
        if rec is not None and corr:
            with self._lock:
                self._index_corr(corr, rec["seq"])

    def commit(
        self,
        pod: str,
        ns: str,
        corr: Optional[str],
        outcome: str,
        *,
        node: Optional[str] = None,
    ) -> None:
        """Record one commit outcome (OK / RETRY incl. fenced
        rejections / FAILED) from _finish_commit."""
        rec = self._event("commit", {
            "pod": pod, "ns": ns, "corr": corr,
            "outcome": outcome, "node": node,
        })
        if rec is not None and corr:
            with self._lock:
                self._index_corr(corr, rec["seq"])

    # -- introspection --------------------------------------------------

    def corr_seqs(self, corr: str) -> List[int]:
        """Journal line seqs indexed for *corr* (bounded; newest corrs
        win) — the /journey view's journal refs."""
        with self._lock:
            return list(self._corr_seqs.get(corr, ()))

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "path": self.path,
                "seq": self._seq,
                "counts": dict(self.counts),
                "bytes": self.bytes_written,
                "finalized": self._finalized,
            }

    # -- lifecycle ------------------------------------------------------

    def flush(self) -> None:
        with self._lock:
            if not self._finalized:
                self._flush_locked()
                self._fh.flush()

    def finalize(self) -> str:
        """Flush, seal, and atomically rename ``.part`` into place.
        Idempotent; returns the final path."""
        with self._lock:
            if self._finalized:
                return self.path
            self._flush_locked()
            self._fh.flush()
            self._fh.close()
            os.replace(self._part, self.path)
            self._finalized = True
        return self.path


# ---------------------------------------------------------------------------
# process-global journal (None = journaling off; the common case)
# ---------------------------------------------------------------------------

_JOURNAL: Optional[JournalWriter] = None


def get_journal() -> Optional[JournalWriter]:
    """The active journal, or None when journaling is off. Capture
    sites must treat None as 'skip all journal work' — this read is the
    entire journal-off cost on the hot path."""
    return _JOURNAL


def enable_journal(
    path: str,
    *,
    identity: str = "",
    seed: Optional[int] = None,
    flush_every: int = 64,
    clock=time.monotonic,
    rev: Optional[str] = None,
    created: Optional[float] = None,
) -> JournalWriter:
    """Install (or replace) the process-global journal writer. A
    replaced writer is finalized first so its flushed prefix survives.
    ``rev``/``created`` pin the envelope header for byte-stable golden
    fixtures (tools/trace_replay.py --regen-golden)."""
    global _JOURNAL
    if _JOURNAL is not None:
        _JOURNAL.finalize()
    _JOURNAL = JournalWriter(
        path, identity=identity, seed=seed,
        flush_every=flush_every, clock=clock, rev=rev, created=created,
    )
    return _JOURNAL


def disable_journal(*, finalize: bool = True) -> Optional[str]:
    """Tear down the process-global journal; returns the finalized path
    (or None when journaling was off)."""
    global _JOURNAL
    jnl, _JOURNAL = _JOURNAL, None
    if jnl is None:
        return None
    if finalize:
        return jnl.finalize()
    return jnl.path


def enable_journal_from_env(
    *, identity: str = "", seed: Optional[int] = None,
) -> Optional[JournalWriter]:
    """Honour NHD_JOURNAL / NHD_JOURNAL_DIR / NHD_JOURNAL_FLUSH: when
    NHD_JOURNAL=1, enable a journal at
    ``$NHD_JOURNAL_DIR/nhd-<identity|pid>.journal.jsonl``."""
    if os.environ.get("NHD_JOURNAL", "0") != "1":
        return None
    out_dir = os.environ.get("NHD_JOURNAL_DIR", "artifacts/journal")
    try:
        flush_every = int(os.environ.get("NHD_JOURNAL_FLUSH", "64"))
    except ValueError:
        flush_every = 64
    tag = identity or str(os.getpid())
    path = os.path.join(out_dir, f"nhd-{tag}.journal.jsonl")
    return enable_journal(
        path, identity=identity, seed=seed, flush_every=flush_every,
    )


def journal_view() -> Dict[str, object]:
    """The journal status payload the metrics plane renders (one
    definition, like decisions_view)."""
    jnl = _JOURNAL
    if jnl is None:
        return {"enabled": False}
    out: Dict[str, object] = {"enabled": True}
    out.update(jnl.stats())
    return out


# ---------------------------------------------------------------------------
# reading side: load / validate / merge
# ---------------------------------------------------------------------------

def validate_journal(header: object, events: Sequence[object]) -> List[str]:
    """Structural schema errors of one parsed journal ([] = valid):
    envelope coordinates, body-format marker, monotonic seqs, known
    event kinds, numeric timestamps, at most one genesis."""
    errs = validate_envelope(
        header, kind=JOURNAL_KIND, schema_version=JOURNAL_SCHEMA_VERSION,
    )
    if not errs and isinstance(header, dict):
        body = header["payload"].get("body")
        if body != BODY_FORMAT:
            errs.append(f"body format is {body!r}, expected {BODY_FORMAT!r}")
    last_seq = 0
    genesis_count = 0
    for i, ev in enumerate(events):
        where = f"event {i}"
        if not isinstance(ev, dict):
            errs.append(f"{where}: must be a JSON object")
            continue
        seq = ev.get("seq")
        if not isinstance(seq, int) or seq <= last_seq:
            errs.append(f"{where}: seq {seq!r} not monotonically increasing")
        else:
            last_seq = seq
        kind = ev.get("ev")
        if kind not in EVENT_KINDS:
            errs.append(f"{where}: unknown event kind {kind!r}")
        elif kind == "genesis":
            genesis_count += 1
        if not isinstance(ev.get("t"), (int, float)):
            errs.append(f"{where}: timestamp 't' must be a number")
    if genesis_count > 1:
        errs.append(f"{genesis_count} genesis events (at most one allowed)")
    return errs


def read_journal(path: str) -> Tuple[dict, List[dict]]:
    """Parse one journal file (``.part`` prefixes read too) into
    (header, events) without schema validation; raises ValueError on
    unparseable lines."""
    header: Optional[dict] = None
    events: List[dict] = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                raise ValueError(f"{path}:{lineno}: unparseable JSON line")
            if header is None:
                header = obj
            else:
                events.append(obj)
    if header is None:
        raise ValueError(f"{path}: empty journal (no header line)")
    return header, events


def load_journal(path: str) -> Tuple[dict, List[dict]]:
    """Read + validate one journal; raises ValueError with the full
    error list on a malformed file (a truncated or foreign file must
    fail loud, not replay as an empty run)."""
    header, events = read_journal(path)
    errs = validate_journal(header, events)
    if errs:
        raise ValueError(f"{path}: " + "; ".join(errs))
    return header, events


def merge_journals(
    paths: Sequence[str],
) -> Tuple[List[dict], List[dict]]:
    """Load N fleet journals and merge their event streams onto one
    timeline, re-based like chrome.merge_chrome_traces: each journal's
    backend-clock ``t`` is anchored by its header's created_unix so
    concurrently recorded replicas interleave in wall order. Events gain
    an ``origin`` index into the returned header list."""
    loaded = [load_journal(p) for p in paths]
    if not loaded:
        raise ValueError("merge_journals: no journals given")
    anchor0 = min(h["created_unix"] for h, _ in loaded)
    merged: List[dict] = []
    for idx, (header, events) in enumerate(loaded):
        if not events:
            continue
        t0 = events[0]["t"]
        base = header["created_unix"] - anchor0
        for ev in events:
            rebased = dict(ev)
            rebased["t"] = base + (ev["t"] - t0)
            rebased["origin"] = idx
            merged.append(rebased)
    merged.sort(key=lambda e: (e["t"], e.get("origin", 0), e["seq"]))
    return [h for h, _ in loaded], merged
