"""Flight recorder: correlation IDs, spans, and the bounded in-memory ring.

The reference's only observability is verbose logs plus the gRPC stats
plane (SURVEY §5.1/§5.5); counters say *how often* but never *where the
time went* for one pod. The flight recorder answers that: every watch
event mints a correlation ID that rides the pipeline (event queue → batch
admission → solve → select → assign → bind commit), and each stage
records a span into a bounded ring. Export is Chrome trace-viewer JSON
(chrome://tracing / https://ui.perfetto.dev) plus a queryable
"recent decisions" view (rpc/metrics.py, rpc/server.py).

Design constraints, in order:

* **off means off** — every producer call sites guard on
  ``get_recorder() is None``; a disabled recorder costs one module-global
  read on the batch path (bench.py's ≤2 % overhead acceptance);
* **thread-safe by construction** — spans arrive concurrently from the
  controller, scheduler, commit-pool, and RPC threads; the ring is a
  ``deque(maxlen=...)`` guarded by one lock, and a span is immutable
  after ``record`` returns (sole exception: ``realias_corr`` rewrites
  corr under the ring lock when cross-replica adoption lands late);
* **bounded** — the ring evicts oldest-first and counts what it dropped
  (the ``nhd_trace_ring_dropped_total`` metric), so tracing can stay on
  in production without growing the heap.

Correlation IDs are a process-wide monotonic counter, not random tokens:
deterministic runs produce deterministic traces (golden-file tests), and
the IDs only need to be unique within one process's ring lifetime.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional

try:  # contextvars: per-thread in threads, carried across awaits in async
    from contextvars import ContextVar
except ImportError:  # pragma: no cover - py3.7+ always has it
    ContextVar = None  # type: ignore[assignment]

_corr_seq = itertools.count(1)
_CORR_VAR: "ContextVar[Optional[str]]" = ContextVar("nhd_corr", default=None)


def new_corr_id(scope: str = "") -> str:
    """Mint a fresh correlation ID (monotonic; unique within one
    process). ``scope`` — the minting replica's identity — makes the ID
    unique ACROSS processes too: every replica's counter restarts at 1,
    so two replicas' locally minted ``c000001`` would otherwise fuse
    unrelated pods into one journey when their dumps merge
    (chrome.merge_chrome_traces). Adopted corrs keep their origin's
    scope by construction (the annotation carries the full ID)."""
    n = next(_corr_seq)
    return f"{scope}/c{n:06d}" if scope else f"c{n:06d}"


def current_corr_id() -> Optional[str]:
    """The correlation ID bound to the calling context (or None)."""
    return _CORR_VAR.get()


@contextlib.contextmanager
def correlate(corr: Optional[str]) -> Iterator[None]:
    """Bind *corr* as the context correlation ID for the block — log
    records emitted inside (NHD_LOG_JSON=1) join against the trace."""
    token = _CORR_VAR.set(corr)
    try:
        yield
    finally:
        _CORR_VAR.reset(token)


class Span:
    """One recorded interval. Immutable after construction; __slots__
    because a gang-scale batch records tens of thousands of these.

    ``replica``/``shard``/``epoch`` are the federation coordinates
    (ISSUE 7): which replica produced the span, and — for spans on the
    fenced commit path — which shard lease and fencing epoch covered
    the work. ``replica`` is stamped by the recorder (every span a
    replica records is that replica's); shard/epoch only where the
    producer knows them, so a merged cross-replica journey shows which
    leadership each leg ran under."""

    __slots__ = (
        "name", "cat", "corr", "t0", "dur", "thread", "attrs",
        "replica", "shard", "epoch",
    )

    def __init__(
        self,
        name: str,
        t0: float,
        dur: float,
        *,
        cat: str = "span",
        corr: Optional[str] = None,
        thread: Optional[str] = None,
        attrs: Optional[dict] = None,
        replica: Optional[str] = None,
        shard: Optional[int] = None,
        epoch: Optional[int] = None,
    ):
        self.name = name
        self.t0 = t0
        self.dur = dur
        self.cat = cat
        self.corr = corr
        self.thread = thread or threading.current_thread().name
        self.attrs = attrs
        self.replica = replica
        self.shard = shard
        self.epoch = epoch

    def to_dict(self) -> dict:
        d = {
            "name": self.name, "cat": self.cat, "corr": self.corr,
            "t0": self.t0, "dur": self.dur, "thread": self.thread,
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        for key in ("replica", "shard", "epoch"):
            v = getattr(self, key)
            if v is not None:
                d[key] = v
        return d


class FlightRecorder:
    """Bounded, thread-safe span ring + decision log.

    ``capacity`` bounds the span ring; ``decision_capacity`` bounds the
    independent per-pod decision log (a much smaller, higher-value record
    that must not be evicted by span churn from one big batch).
    """

    def __init__(
        self,
        capacity: int = 16384,
        decision_capacity: int = 256,
        *,
        identity: str = "",
    ):
        if capacity < 1 or decision_capacity < 1:
            raise ValueError(
                f"capacities must be >= 1, got {capacity}/{decision_capacity}"
            )
        self.capacity = capacity
        self.decision_capacity = decision_capacity
        # federation coordinates: which replica this ring belongs to
        # (stamped onto every span), and the monotonic→wall anchor the
        # cross-replica merge uses to put N processes' spans on one
        # timeline (chrome.merge_chrome_traces). Captured once — the
        # pair drifts together, which is exactly what re-basing needs.
        self.identity = identity
        self.epoch_offset = time.time() - time.monotonic()
        self._lock = threading.Lock()
        self._spans: "deque[Span]" = deque(maxlen=capacity)
        self._decisions: "deque[dict]" = deque(maxlen=decision_capacity)
        self._dropped = 0

    # -- producers ------------------------------------------------------

    def record(
        self,
        name: str,
        t0: float,
        dur: float,
        *,
        cat: str = "span",
        corr: Optional[str] = None,
        thread: Optional[str] = None,
        attrs: Optional[dict] = None,
        shard: Optional[int] = None,
        epoch: Optional[int] = None,
    ) -> None:
        """Append one span (t0 on the time.monotonic() clock, seconds)."""
        span = Span(
            name, t0, dur, cat=cat,
            corr=corr if corr is not None else _CORR_VAR.get(),
            thread=thread, attrs=attrs,
            replica=self.identity or None, shard=shard, epoch=epoch,
        )
        with self._lock:
            if len(self._spans) == self.capacity:
                self._dropped += 1
            self._spans.append(span)

    def realias_corr(self, old: str, new: str) -> int:
        """Rewrite ring spans recorded under *old* to carry *new* —
        see realias_corrs. Returns the number of spans re-aliased."""
        return self.realias_corrs({old: new})

    def realias_corrs(self, mapping: Dict[str, str]) -> int:
        """Rewrite ring spans whose corr is a key of *mapping* to carry
        the mapped ID, in ONE ring pass.

        The watch-receipt leg is recorded before the scheduler can read
        the pod's cluster-stamped corr (adoption happens at batch
        admission, _resolve_trace_corr); when adoption changes IDs,
        this re-joins those already-recorded legs to their journeys
        instead of orphaning them as one-span corrs. Batched because the
        pass holds the ring lock every producer thread records under —
        one O(capacity) scan per BATCH, not per pod. The sole sanctioned
        mutation of a recorded span: corr only, under the ring lock.
        Returns the number of spans re-aliased."""
        mapping = {o: n for o, n in mapping.items() if o != n}
        if not mapping:
            return 0
        n = 0
        with self._lock:
            for s in self._spans:
                new = mapping.get(s.corr)
                if new is not None:
                    s.corr = new
                    n += 1
        return n

    def record_decision(self, decision: dict) -> None:
        """Append one per-pod scheduling decision (see scheduler/core.py
        for the record shape: pod, ns, corr, outcome, node, phases...)."""
        with self._lock:
            self._decisions.append(decision)

    # -- consumers ------------------------------------------------------

    def spans(self) -> List[Span]:
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._spans)

    def recent_decisions(self, n: int = 50) -> List[dict]:
        """The last *n* per-pod decisions, newest first."""
        with self._lock:
            out = list(self._decisions)
        out.reverse()
        return [dict(d) for d in out[: max(n, 0)]]

    def occupancy(self) -> int:
        with self._lock:
            return len(self._spans)

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        global _DROPPED_BANKED
        with self._lock:
            # the process-global ring's drop count banks into the
            # monotonic total before it resets — dropped_total() is a
            # Prometheus counter and must never move backwards
            if _RECORDER is self:
                _DROPPED_BANKED += self._dropped
            self._spans.clear()
            self._decisions.clear()
            self._dropped = 0


# ---------------------------------------------------------------------------
# process-global recorder (None = tracing off; the common case)
# ---------------------------------------------------------------------------

_RECORDER: Optional[FlightRecorder] = None

# spans dropped by process-global rings that have since been replaced
# (enable), torn down (disable), or cleared — the live ring's count adds
# on top in dropped_total(). Without this bank the exported
# nhd_trace_ring_dropped_total reset on every enable()/clear(), which a
# Prometheus counter must never do (rate() reads a reset as a huge
# negative spike and drops the window).
_DROPPED_BANKED = 0


def get_recorder() -> Optional[FlightRecorder]:
    """The active recorder, or None when tracing is off. Producers must
    treat None as 'skip all span work' — this read is the entire
    recorder-off cost on the hot path."""
    return _RECORDER


def enable(
    capacity: int = 16384, decision_capacity: int = 256, *,
    identity: str = "",
) -> FlightRecorder:
    """Install (or replace) the process-global recorder and return it.
    ``identity`` names this replica in every span it records — set it
    under HA/federation so merged cross-replica journeys attribute each
    leg (chrome.merge_chrome_traces)."""
    global _RECORDER, _DROPPED_BANKED
    if _RECORDER is not None:
        _DROPPED_BANKED += _RECORDER.dropped()
    _RECORDER = FlightRecorder(
        capacity, decision_capacity, identity=identity
    )
    return _RECORDER


def disable() -> None:
    global _RECORDER, _DROPPED_BANKED
    if _RECORDER is not None:
        _DROPPED_BANKED += _RECORDER.dropped()
    _RECORDER = None


def dropped_total() -> int:
    """Monotonic count of spans the process-global ring has EVER
    dropped, across enable()/disable()/clear() generations — the value
    nhd_trace_ring_dropped_total exports (a true counter, unlike the
    live ring's dropped() snapshot, which resets with the ring)."""
    rec = _RECORDER
    return _DROPPED_BANKED + (rec.dropped() if rec is not None else 0)


def decisions_view(n: int = 50) -> Dict[str, object]:
    """The recent-decisions payload both query planes serve (HTTP
    /decisions and gRPC GetRecentDecisions) — one definition, so the
    transports cannot drift."""
    rec = _RECORDER
    return {
        "enabled": rec is not None,
        "decisions": rec.recent_decisions(n) if rec is not None else [],
    }


@contextlib.contextmanager
def span(
    name: str,
    *,
    cat: str = "span",
    corr: Optional[str] = None,
    attrs: Optional[dict] = None,
) -> Iterator[None]:
    """Record the block as a span when tracing is on; free no-op when off."""
    rec = _RECORDER
    if rec is None:
        yield
        return
    t0 = time.monotonic()
    try:
        yield
    finally:
        rec.record(
            name, t0, time.monotonic() - t0, cat=cat, corr=corr, attrs=attrs
        )
