"""Schema-versioned observability artifacts: one envelope, many kinds.

Every JSON artifact the observability plane writes — fleet snapshots
(obs/fleet.py), bench telemetry (obs/perf.py, bench.py) — shares ONE
envelope so downstream tooling (tools/bench_diff.py, tools/fleet_top.py,
CI) can route and validate files without per-kind sniffing:

    {
      "kind":            "fleet" | "bench" | ...,
      "schema_version":  int        (per kind; bump on breaking change),
      "created_unix":    float      (wall clock at write),
      "git_rev":         str        ("unknown" outside a work tree),
      "seed":            int|None   (whatever made the run reproducible),
      "payload":         {...}      (the kind-specific body)
    }

Stdlib-only; writes are atomic (tmp + rename) so a reader polling the
artifact directory never sees a torn file. The per-kind payload
validators live with their producers — this module owns exactly the
envelope contract.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
from typing import List, Optional

#: envelope fields every artifact must carry
ENVELOPE_FIELDS = (
    "kind", "schema_version", "created_unix", "git_rev", "seed", "payload"
)


def git_rev(cwd: Optional[str] = None) -> str:
    """Short git revision of *cwd* (or CWD), 'unknown' when unavailable
    — artifacts must still be writable from an installed wheel or a
    tarball checkout with no .git."""
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10, cwd=cwd,
        )
    except (OSError, subprocess.TimeoutExpired):
        return "unknown"
    if proc.returncode != 0:
        return "unknown"
    return proc.stdout.strip() or "unknown"


def make_envelope(
    kind: str,
    schema_version: int,
    payload: dict,
    *,
    seed: Optional[int] = None,
    rev: Optional[str] = None,
    created: Optional[float] = None,
) -> dict:
    """Wrap *payload* in the shared envelope. ``rev``/``created`` are
    injectable so tests produce byte-stable artifacts."""
    return {
        "kind": kind,
        "schema_version": int(schema_version),
        "created_unix": time.time() if created is None else float(created),
        "git_rev": git_rev() if rev is None else rev,
        "seed": seed,
        "payload": payload,
    }


def validate_envelope(
    obj: object, *, kind: Optional[str] = None,
    schema_version: Optional[int] = None,
) -> List[str]:
    """Envelope-level schema errors ([] = valid). Pass ``kind`` /
    ``schema_version`` to additionally pin what the caller expects —
    a reader that can only handle fleet v1 should say so here rather
    than KeyError deep inside its payload walk."""
    errs: List[str] = []
    if not isinstance(obj, dict):
        return [f"artifact must be a JSON object, got {type(obj).__name__}"]
    for field in ENVELOPE_FIELDS:
        if field not in obj:
            errs.append(f"missing envelope field {field!r}")
    if errs:
        return errs
    if not isinstance(obj["kind"], str) or not obj["kind"]:
        errs.append("kind must be a non-empty string")
    if not isinstance(obj["schema_version"], int):
        errs.append("schema_version must be an int")
    if not isinstance(obj["created_unix"], (int, float)):
        errs.append("created_unix must be a number")
    if not isinstance(obj["git_rev"], str):
        errs.append("git_rev must be a string")
    if obj["seed"] is not None and not isinstance(obj["seed"], int):
        errs.append("seed must be an int or null")
    if not isinstance(obj["payload"], dict):
        errs.append("payload must be an object")
    if kind is not None and obj.get("kind") != kind:
        errs.append(f"kind is {obj.get('kind')!r}, expected {kind!r}")
    if (
        schema_version is not None
        and obj.get("schema_version") != schema_version
    ):
        errs.append(
            f"schema_version is {obj.get('schema_version')!r}, "
            f"expected {schema_version}"
        )
    return errs


def write_artifact(obj: dict, out_dir: str, name: str) -> str:
    """Atomically write *obj* as ``out_dir/name`` (mkdir -p'd); returns
    the written path. ``name`` should carry enough context to never
    collide (callers stamp pid/seed/step — this function deliberately
    does not invent entropy, so artifact names stay predictable for the
    Make targets that read them back)."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, name)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(obj, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return path


def load_artifact(path: str) -> dict:
    """Read + envelope-validate one artifact; raises ValueError with the
    full error list on a malformed file (a truncated or foreign JSON
    must fail loud, not produce an empty diff)."""
    with open(path) as fh:
        obj = json.load(fh)
    errs = validate_envelope(obj)
    if errs:
        raise ValueError(f"{path}: " + "; ".join(errs))
    return obj
