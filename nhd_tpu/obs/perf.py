"""Perf-telemetry artifacts: the bench run as a schema-versioned file.

bench.py used to print one JSON line and scroll its per-config detail to
stderr — nothing a later run could be compared against. This module
gives the bench the same artifact discipline the fleet aggregator has
(obs/artifact.py envelope, obs/fleet.py): every run writes
``artifacts/bench/*.json`` carrying the schema version, git rev, seed,
per-config wall/placed/speedup, the per-phase breakdown the overhead war
tracks (encode / materialize / upload / solve / select / assign /
readback ...), and the per-(phase, shape-bucket) attribution table from
the process jit stats (obs/jitstats.py record_phase). tools/bench_diff.py
compares two such artifacts and fails on regression past a threshold —
the continuous-regression gate the bench trajectory needs.

``load_bench_artifact`` also reads the LEGACY driver records the repo
already carries (BENCH_r01–r05: ``{"n", "cmd", "rc", "tail",
"parsed"}``), upgrading them in memory to schema_version 0 with whatever
per-config detail their stderr tail still yields — so the gate can diff
a new run against history that predates the artifact writer.
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Optional

from nhd_tpu.obs.artifact import (
    make_envelope,
    validate_envelope,
    write_artifact,
)

BENCH_KIND = "bench"
BENCH_SCHEMA_VERSION = 1

#: payload sections every (v1) bench artifact carries
BENCH_SECTIONS = ("platform", "configs", "phase_attribution", "headline")

# legacy stderr tail, one line per config:
#   bench[cfg2:1kx256]: 1000 pods x 256 nodes -> placed 1000 in 0.042s
#   (23777 pods/s, rounds=5, solve=0.015s, select=0.003s, assign=0.012s,
#   p99 bind 25ms); ... speedup 301x
_LEGACY_LINE = re.compile(
    r"bench\[(?P<name>[^\]]+)\]:.*?placed (?P<placed>\d+) in "
    r"(?P<wall>[\d.]+)s \((?P<rate>[\d.]+) pods/s, "
    r"rounds=(?P<rounds>\d+), solve=(?P<solve>[\d.]+)s, "
    r"select=(?P<select>[\d.]+)s, assign=(?P<assign>[\d.]+)s"
    r"(?:, p99 bind (?P<p99>[\d.]+)ms)?"
)
_LEGACY_SPEEDUP = re.compile(
    r"bench\[(?P<name>[^\]]+)\]:.*speedup (?P<speedup>[\d.]+)x"
)


#: the coarse HOST round-loop phases — the figure the r14 vectorize+
#: pipeline work drives down, summed per config so the headline artifact
#: shows the solve-vs-host split directly. ``assign`` already contains
#: materialize+final_sync as sub-windows, which is exactly how the r14
#: acceptance metric is defined (the sum is a tracked comparable, not a
#: disjoint partition).
HOST_PHASE_KEYS = ("select", "assign", "materialize", "final_sync")

#: jit-stats attribution phases that are host-side work (the per-shape
#: rollup below; device-side time lives in the solve/select/assign
#: windows of the coarse trio and in the dispatch/readback entries)
HOST_ATTRIBUTION_PHASES = frozenset({
    "prepass", "encode", "fast_join", "native_assign", "materialize",
    "final_sync", "backfill", "spec_expand", "guard_audit",
})


def host_phase_rollup(phase_seconds: Dict[str, float]) -> Dict[str, float]:
    """Roll the jit-stats per-(phase, shape) attribution table up to a
    host-seconds total per shape bucket — keys are ``"phase:shape"``
    (obs/jitstats.py record_phase). The artifact's headline view of
    where the host round loop spends per cluster shape."""
    out: Dict[str, float] = {}
    for key, secs in phase_seconds.items():
        phase, _, shape = key.partition(":")
        if phase in HOST_ATTRIBUTION_PHASES and shape:
            out[shape] = out.get(shape, 0.0) + float(secs)
    return out


def config_record(
    *,
    wall_seconds: float,
    placed: int,
    speedup: float,
    rounds: int = 0,
    phases: Optional[Dict[str, float]] = None,
    p99_bind_ms: Optional[float] = None,
    extra: Optional[dict] = None,
) -> dict:
    """One config's result in the canonical shape (bench.py builds these;
    the legacy upgrader synthesizes the same shape from log lines).
    ``extra``: additional named sections (e.g. the sustained-churn leg's
    ``churn`` figures, gated by tools/bench_diff.py)."""
    phases = dict(phases or {})
    rec = {
        "wall_seconds": wall_seconds,
        "placed": placed,
        "pods_per_sec": (placed / wall_seconds) if wall_seconds > 0 else 0.0,
        "speedup_vs_serial": speedup,
        "rounds": rounds,
        "phases": phases,
        # the solve-vs-host split, precomputed per config (the r14
        # acceptance comparable): host = select+assign+materialize+
        # final_sync as recorded
        "host_phases_seconds": sum(
            float(phases.get(k, 0.0)) for k in HOST_PHASE_KEYS
        ),
        "p99_bind_ms": p99_bind_ms,
    }
    for key, value in (extra or {}).items():
        rec[key] = value
    return rec


def build_bench_artifact(
    configs: Dict[str, dict],
    *,
    headline: dict,
    platform: str,
    phase_attribution: Optional[dict] = None,
    micro: Optional[dict] = None,
    seed: Optional[int] = None,
    rev: Optional[str] = None,
    created: Optional[float] = None,
) -> dict:
    """Payload + envelope in one step (what bench.py writes).
    ``phase_attribution`` is the jit-stats per-(phase, shape) table
    (obs/jitstats.py snapshot: phase_seconds + phase_counts)."""
    attribution = dict(phase_attribution or {})
    if "phase_seconds" in attribution:
        # per-shape host total (host_phase_rollup): the solve-vs-host
        # split per shape bucket, on the artifact's front page
        attribution["host_seconds_by_shape"] = host_phase_rollup(
            attribution["phase_seconds"]
        )
    payload = {
        "platform": platform,
        "configs": {name: dict(rec) for name, rec in configs.items()},
        "phase_attribution": attribution,
        "headline": dict(headline),
    }
    if micro:
        payload["micro"] = dict(micro)
    return make_envelope(
        BENCH_KIND, BENCH_SCHEMA_VERSION, payload,
        seed=seed, rev=rev, created=created,
    )


def validate_bench_artifact(obj: object) -> List[str]:
    """Schema errors ([] = valid). schema_version 0 (upgraded legacy) is
    accepted with the same section contract — the upgrader guarantees
    it."""
    errs = validate_envelope(obj, kind=BENCH_KIND)
    if errs:
        return errs
    if obj["schema_version"] not in (0, BENCH_SCHEMA_VERSION):  # type: ignore[index]
        return [
            f"unsupported bench schema_version "
            f"{obj['schema_version']!r}"  # type: ignore[index]
        ]
    payload = obj["payload"]  # type: ignore[index]
    for section in BENCH_SECTIONS:
        if section not in payload:
            errs.append(f"payload missing section {section!r}")
    if errs:
        return errs
    if not isinstance(payload["configs"], dict):
        errs.append("payload.configs must be an object")
        return errs
    for name, rec in payload["configs"].items():
        for field in ("wall_seconds", "placed", "phases"):
            if field not in rec:
                errs.append(f"configs[{name!r}] missing {field!r}")
    return errs


def write_bench_artifact(
    artifact: dict, out_dir: str = "artifacts/bench",
    *, name: Optional[str] = None,
) -> str:
    """Validate + atomically write; raises ValueError on schema errors."""
    errs = validate_bench_artifact(artifact)
    if errs:
        raise ValueError("invalid bench artifact: " + "; ".join(errs))
    if name is None:
        stamp = int(artifact.get("created_unix", 0))
        name = f"bench-{artifact.get('git_rev', 'unknown')}-{stamp}.json"
    return write_artifact(artifact, out_dir, name)


def _upgrade_legacy(obj: dict, path: str) -> dict:
    """BENCH_rNN driver record → in-memory schema_version-0 artifact.
    Per-config detail is recovered from the stderr tail where its line
    format still parses; the headline JSON is always present."""
    parsed = obj.get("parsed")
    if not isinstance(parsed, dict):
        raise ValueError(f"{path}: legacy record has no 'parsed' headline")
    configs: Dict[str, dict] = {}
    tail = obj.get("tail", "") or ""
    speedups = {
        m.group("name"): float(m.group("speedup"))
        for m in _LEGACY_SPEEDUP.finditer(tail)
    }
    for m in _LEGACY_LINE.finditer(tail):
        name = m.group("name")
        phases = {
            "solve": float(m.group("solve")),
            "select": float(m.group("select")),
            "assign": float(m.group("assign")),
        }
        configs[name] = config_record(
            wall_seconds=float(m.group("wall")),
            placed=int(m.group("placed")),
            speedup=speedups.get(name, 0.0),
            rounds=int(m.group("rounds")),
            phases=phases,
            p99_bind_ms=float(m.group("p99")) if m.group("p99") else None,
        )
    return {
        "kind": BENCH_KIND,
        "schema_version": 0,
        "created_unix": 0.0,
        "git_rev": "unknown",
        "seed": None,
        "payload": {
            "platform": "unknown",
            "configs": configs,
            "phase_attribution": {},
            "headline": dict(parsed),
            "legacy": {"round": obj.get("n"), "rc": obj.get("rc")},
        },
    }


def load_bench_artifact(path: str) -> dict:
    """Read one bench artifact — new format or legacy BENCH_rNN driver
    record — validated; raises ValueError on anything else."""
    with open(path) as fh:
        obj = json.load(fh)
    if isinstance(obj, dict) and "kind" not in obj and "parsed" in obj:
        obj = _upgrade_legacy(obj, path)
    errs = validate_bench_artifact(obj)
    if errs:
        raise ValueError(f"{path}: " + "; ".join(errs))
    return obj
