"""Observability: flight recorder, journal, histograms, JIT accounting.

The scheduler's instrumentation spine (ISSUE 3): correlation IDs thread
every pod's decision path from watch-event receipt to bind commit, spans
land in a bounded ring (recorder.py), latency distributions land in
Prometheus histograms (histo.py), and solver program reuse is counted per
bucket shape (jitstats.py). The record/replay journal (journal.py) is
the lossless complement of the bounded ring: a schema-versioned event
log that captures enough to re-drive a run deterministically
(sim/replay.py) and diff the replayed decisions against the recorded
ones. Export: Chrome trace JSON (chrome.py), the /metrics text plane and
/decisions + /journey + /explain + /trace HTTP views (rpc/metrics.py),
and the gRPC stats service (rpc/server.py).

Everything in this package is stdlib-only and import-light — producers
(scheduler, solver, retry layer) import it unconditionally and pay one
module-global read when tracing and journaling are off.
"""

from nhd_tpu.obs.chrome import (
    chrome_trace,
    chrome_trace_of,
    dump_chrome_trace,
    journey_replicas,
    journey_view,
    merge_chrome_traces,
    pod_journeys,
    scheduled_journeys,
    validate_chrome_trace,
)
from nhd_tpu.obs.histo import HISTOGRAMS, LABELED_HISTOGRAMS, Histogram
from nhd_tpu.obs.jitstats import JIT_STATS
from nhd_tpu.obs.journal import (
    JournalWriter,
    disable_journal,
    enable_journal,
    enable_journal_from_env,
    get_journal,
    journal_view,
    load_journal,
    merge_journals,
    validate_journal,
)
from nhd_tpu.obs.slo import SLO, SloTracker
from nhd_tpu.obs.recorder import (
    FlightRecorder,
    Span,
    correlate,
    current_corr_id,
    decisions_view,
    disable,
    dropped_total,
    enable,
    get_recorder,
    new_corr_id,
    span,
)

__all__ = [
    "FlightRecorder",
    "HISTOGRAMS",
    "Histogram",
    "JIT_STATS",
    "JournalWriter",
    "LABELED_HISTOGRAMS",
    "SLO",
    "SloTracker",
    "Span",
    "chrome_trace",
    "chrome_trace_of",
    "correlate",
    "current_corr_id",
    "decisions_view",
    "disable",
    "disable_journal",
    "dropped_total",
    "dump_chrome_trace",
    "enable",
    "enable_journal",
    "enable_journal_from_env",
    "get_journal",
    "get_recorder",
    "journal_view",
    "journey_replicas",
    "journey_view",
    "load_journal",
    "merge_chrome_traces",
    "merge_journals",
    "new_corr_id",
    "pod_journeys",
    "scheduled_journeys",
    "span",
    "validate_chrome_trace",
    "validate_journal",
]
