"""Observability: flight recorder, histograms, and JIT cache accounting.

The scheduler's instrumentation spine (ISSUE 3): correlation IDs thread
every pod's decision path from watch-event receipt to bind commit, spans
land in a bounded ring (recorder.py), latency distributions land in
Prometheus histograms (histo.py), and solver program reuse is counted per
bucket shape (jitstats.py). Export: Chrome trace JSON (chrome.py), the
/metrics text plane and /decisions + /explain + /trace HTTP views
(rpc/metrics.py), and the gRPC stats service (rpc/server.py).

Everything in this package is stdlib-only and import-light — producers
(scheduler, solver, retry layer) import it unconditionally and pay one
module-global read when tracing is off.
"""

from nhd_tpu.obs.chrome import (
    chrome_trace,
    chrome_trace_of,
    dump_chrome_trace,
    journey_replicas,
    merge_chrome_traces,
    pod_journeys,
    scheduled_journeys,
    validate_chrome_trace,
)
from nhd_tpu.obs.histo import HISTOGRAMS, LABELED_HISTOGRAMS, Histogram
from nhd_tpu.obs.jitstats import JIT_STATS
from nhd_tpu.obs.slo import SLO, SloTracker
from nhd_tpu.obs.recorder import (
    FlightRecorder,
    Span,
    correlate,
    current_corr_id,
    decisions_view,
    disable,
    enable,
    get_recorder,
    new_corr_id,
    span,
)

__all__ = [
    "FlightRecorder",
    "HISTOGRAMS",
    "Histogram",
    "JIT_STATS",
    "LABELED_HISTOGRAMS",
    "SLO",
    "SloTracker",
    "Span",
    "chrome_trace",
    "chrome_trace_of",
    "correlate",
    "current_corr_id",
    "decisions_view",
    "disable",
    "dump_chrome_trace",
    "enable",
    "get_recorder",
    "journey_replicas",
    "merge_chrome_traces",
    "new_corr_id",
    "pod_journeys",
    "scheduled_journeys",
    "span",
    "validate_chrome_trace",
]
