"""Fleet aggregator: N replicas' observability → one federation view.

PR 6 made a pod's life span replicas (spillover hops, shard handoffs,
fenced rejections), so no single replica's /metrics or trace ring can
answer fleet questions — "which shard's binds are slow?", "how many pods
hopped?", "is the error budget burning?". This module merges N replicas'
views into one schema-versioned **fleet artifact**
(``artifacts/fleet/*.json``, envelope in obs/artifact.py):

* per-shard bind-latency histograms (from the replicas' ``bind`` spans,
  which carry their shard + fencing epoch — obs/recorder.py);
* spillover-hop counts and cross-replica journey tallies (the merged
  Chrome trace's per-corr view — obs/chrome.py pod_journeys);
* leadership timeline (per-shard epochs + ownerless-gap high-waters);
* fencing-rejection and spillover counters (k8s/retry.py ApiCounters);
* the SLO plane's burn-rate summary (obs/slo.py), worst-of across
  replicas per window — the page-worthy number.

Two producers feed the same payload builder: **in-process views**
(``replica_view`` — ChaosSim federation replicas, ``make fleet-demo``)
and **scraped views** (``scrape_replica`` — tools/fleet_top.py polling
live replicas' /metrics + /decisions). ChaosSim also calls
``write_fleet_artifact`` automatically around any invariant violation,
so a failed storm leaves the federation's full state on disk next to
the assertion message. Stdlib-only.
"""

from __future__ import annotations

import json
import re
import urllib.request
from typing import Dict, List, Optional, Tuple

from nhd_tpu.obs.artifact import (
    make_envelope,
    validate_envelope,
    write_artifact,
)
from nhd_tpu.obs.chrome import (
    chrome_trace,
    merge_chrome_traces,
    pod_journeys,
    scheduled_journeys,
)
from nhd_tpu.obs.histo import DEFAULT_BUCKETS

FLEET_KIND = "fleet"
FLEET_SCHEMA_VERSION = 1

#: payload sections every fleet artifact carries (validate_fleet_artifact)
FLEET_SECTIONS = (
    "replicas", "per_shard", "spillover", "slo", "fencing",
    "leadership", "violations",
)

# exposition line: name{labels} value  (labels optional; no timestamps —
# our own exporter never emits them)
_SAMPLE = re.compile(
    r"^(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$"
)
_LABEL = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Dict[str, List[Tuple[dict, float]]]:
    """Minimal text-exposition parser: family → [(labels, value)].
    Tolerant of anything it doesn't understand (a scrape target one
    version ahead must degrade, not crash the aggregator)."""
    out: Dict[str, List[Tuple[dict, float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = {
            k: v.replace('\\"', '"')
            for k, v in _LABEL.findall(m.group("labels") or "")
        }
        out.setdefault(m.group("name"), []).append((labels, value))
    return out


# ---------------------------------------------------------------------------
# view producers: one dict per replica, same shape from both paths
# ---------------------------------------------------------------------------


def replica_view(
    identity: str,
    *,
    recorder=None,
    slo=None,
    shards: Optional[Dict[int, int]] = None,
    decisions: Optional[List[dict]] = None,
) -> dict:
    """In-process view of one replica (chaos harness, fleet-demo):
    its trace dump, SLO snapshot, and held shards."""
    return {
        "replica": identity,
        "shards": {str(s): e for s, e in (shards or {}).items()},
        "slo": slo.snapshot() if slo is not None else None,
        "trace": chrome_trace(recorder) if recorder is not None else None,
        "decisions": list(decisions or []),
        "metrics": None,
    }


def scrape_replica(base_url: str, *, timeout: float = 5.0) -> dict:
    """Scraped view of one live replica: GET /metrics + /decisions on
    ``base_url`` (e.g. http://host:9464). The trace ring is NOT pulled —
    journeys come from dump files, not scrapes (a 16k-span ring per poll
    would swamp the replica)."""
    url = base_url.rstrip("/")
    with urllib.request.urlopen(f"{url}/metrics", timeout=timeout) as resp:
        metrics = parse_prometheus(resp.read().decode())
    decisions: List[dict] = []
    try:
        with urllib.request.urlopen(
            f"{url}/decisions?n=200", timeout=timeout
        ) as resp:
            payload = json.load(resp)
        if isinstance(payload, dict) and isinstance(
            payload.get("decisions"), list
        ):
            decisions = payload["decisions"]
    except (OSError, ValueError):
        # decisions are additive detail; metrics alone still merge —
        # a proxy's HTML error page (200, non-JSON) must not kill the
        # whole fleet view over one replica
        pass
    shards = {
        labels.get("shard", "?"): int(value)
        for labels, value in metrics.get("nhd_shard_epoch", [])
    }
    slo_snapshot = None
    if "nhd_slo_bind_observations_total" in metrics:
        burn = {
            labels.get("window", "?"): value
            for labels, value in metrics.get("nhd_slo_bind_burn_rate", [])
        }

        def _scalar(name: str) -> float:
            samples = metrics.get(name, [])
            return samples[0][1] if samples else 0.0

        # per-tenant views (obs/slo.py tenant families): the scraped
        # shape mirrors SloTracker.snapshot()["tenants"], so the payload
        # builder merges both producer paths identically
        tenants: Dict[str, dict] = {}
        for fam, field in (
            ("nhd_slo_tenant_observations_total", "observations_total"),
            ("nhd_slo_tenant_breaches_total", "breaches_total"),
            ("nhd_slo_tenant_max_seconds", "max_seconds"),
            ("nhd_slo_tenant_p99_seconds", "p99_seconds"),
        ):
            for labels, value in metrics.get(fam, []):
                tenants.setdefault(labels.get("tenant", "?"), {})[
                    field
                ] = value
        slo_snapshot = {
            "target_sec": _scalar("nhd_slo_bind_target_seconds"),
            "good_fraction": _scalar("nhd_slo_bind_good_fraction"),
            "observations_total": int(
                _scalar("nhd_slo_bind_observations_total")
            ),
            "breaches_total": int(_scalar("nhd_slo_bind_breaches_total")),
            "max_seconds": _scalar("nhd_slo_bind_max_seconds"),
            "burn_rates": burn,
            "tenants": tenants,
        }
    return {
        "replica": base_url,
        "shards": shards,
        "slo": slo_snapshot,
        "trace": None,
        "decisions": decisions,
        "metrics": metrics,
    }


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------


def _bucketize(durations: List[float]) -> dict:
    """One bind-latency histogram (exact cumulative counts over the
    standard latency ladder, obs/histo.py DEFAULT_BUCKETS) plus the
    interpolated p99 (quantile_from_buckets — raw bucket edges hid
    in-bucket regressions and read edge crossings as cliffs)."""
    from nhd_tpu.obs.histo import quantile_from_buckets

    edges = tuple(DEFAULT_BUCKETS)
    # counts are cumulative by construction: each duration increments
    # EVERY edge it fits under, exactly the le= semantics
    cum = [0] * len(edges)
    for d in durations:
        for i, edge in enumerate(edges):
            if d <= edge:
                cum[i] += 1
    return {
        "count": len(durations),
        "sum_seconds": sum(durations),
        "max_seconds": max(durations, default=0.0),
        "buckets": {str(edge): c for edge, c in zip(edges, cum)},
        "p99_seconds": quantile_from_buckets(
            list(zip(edges, cum)) + [(float("inf"), len(durations))], 0.99
        ),
    }


def build_fleet_payload(
    views: List[dict],
    *,
    leadership: Optional[dict] = None,
    counters: Optional[dict] = None,
    violations: Optional[List[str]] = None,
    extra: Optional[dict] = None,
) -> dict:
    """Merge N replica views (replica_view / scrape_replica shapes) into
    the fleet payload. ``leadership`` carries the producer's gap
    timeline (chaos knows it; scrapes only know current epochs),
    ``counters`` a process ApiCounters snapshot for the fencing /
    spillover totals, ``violations`` whatever invariant failures the
    producer observed."""
    traces = [v["trace"] for v in views if v.get("trace")]
    merged = merge_chrome_traces(traces) if traces else None
    journeys = pod_journeys(merged) if merged else {}

    # per-shard bind latency + spill hops from the merged spans: the
    # bind/spill spans carry their shard stamp (scheduler/core.py)
    bind_durs: Dict[str, List[float]] = {}
    spill_by_shard: Dict[str, int] = {}
    hops_by_corr: Dict[str, int] = {}
    for corr, events in journeys.items():
        for ev in events:
            args = ev.get("args") or {}
            shard = args.get("shard")
            if ev.get("name") == "bind" and ev.get("dur") is not None:
                key = str(shard) if shard is not None else "unsharded"
                bind_durs.setdefault(key, []).append(
                    float(ev["dur"]) / 1e6
                )
            elif ev.get("name") == "spill":
                key = str(shard) if shard is not None else "unsharded"
                spill_by_shard[key] = spill_by_shard.get(key, 0) + 1
                hops_by_corr[corr] = hops_by_corr.get(corr, 0) + 1

    cross_replica = 0
    for corr, events in journeys.items():
        reps = {
            (ev.get("args") or {}).get("replica")
            for ev in events
            if (ev.get("args") or {}).get("replica")
        }
        if len(reps) >= 2:
            cross_replica += 1

    # scrape path: per-replica bind histograms from the exposition (the
    # ring isn't scraped, so shard attribution isn't available there)
    per_replica_bind: Dict[str, dict] = {}
    for v in views:
        fams = v.get("metrics") or {}
        if "nhd_bind_latency_seconds_bucket" in fams:
            from nhd_tpu.obs.histo import quantile_from_buckets

            raw = {
                labels.get("le", "?"): value
                for labels, value in
                fams["nhd_bind_latency_seconds_bucket"]
            }
            per_replica_bind[v["replica"]] = {
                "buckets": raw,
                # interpolated, not the raw covering edge (same fix as
                # the bench churn leg)
                "p99_seconds": quantile_from_buckets(
                    (
                        (float("inf") if le == "+Inf" else float(le), c)
                        for le, c in raw.items()
                        if le != "?"
                    ),
                    0.99,
                ),
            }

    # SLO: per-replica snapshots plus the fleet worst-of per window —
    # one replica's budget on fire IS the fleet's page
    slo_reps = {
        v["replica"]: v["slo"] for v in views if v.get("slo") is not None
    }
    worst_burn: Dict[str, float] = {}
    for snap in slo_reps.values():
        for window, rate in (snap.get("burn_rates") or {}).items():
            worst_burn[window] = max(worst_burn.get(window, 0.0), rate)
    # per-tenant fleet roll-up: totals sum, p99 is worst-of — one
    # tenant's p99 on fire on any replica is that tenant's fleet answer
    tenant_agg: Dict[str, dict] = {}
    for snap in slo_reps.values():
        for t, view in (snap.get("tenants") or {}).items():
            agg = tenant_agg.setdefault(t, {
                "observations_total": 0, "breaches_total": 0,
                "worst_p99_seconds": 0.0,
            })
            agg["observations_total"] += int(
                view.get("observations_total", 0)
            )
            agg["breaches_total"] += int(view.get("breaches_total", 0))
            agg["worst_p99_seconds"] = max(
                agg["worst_p99_seconds"], float(view.get("p99_seconds", 0.0))
            )
    slo_summary = {
        "replicas": slo_reps,
        "observations_total": sum(
            s.get("observations_total", 0) for s in slo_reps.values()
        ),
        "breaches_total": sum(
            s.get("breaches_total", 0) for s in slo_reps.values()
        ),
        "max_seconds": max(
            (s.get("max_seconds", 0.0) for s in slo_reps.values()),
            default=0.0,
        ),
        "worst_burn_rates": worst_burn,
        "tenants": tenant_agg,
    }

    counters = dict(counters or {})
    if not counters:
        # scrape path: no in-process ApiCounters snapshot — source the
        # fencing/spillover totals from each replica's parsed exposition
        # instead of silently reporting zeros (these families are
        # per-replica counters, so the fleet figure is their sum)
        for key in (
            "ha_stale_writes_rejected_total",
            "ha_renewal_failures_total",
            "shard_handoffs_total",
            "shard_spillover_claims_total",
            "shard_spillover_exhausted_total",
            "device_state_events_total",
            "device_state_deltas_total",
            "device_state_rows_uploaded_total",
            "device_state_full_rebuilds_total",
            "mesh_solves_total",
            "mesh_rows_uploaded_total",
            "mesh_wholesale_uploads_total",
            "guard_faults_total",
            "guard_retries_total",
            "guard_degradations_total",
            "guard_promotions_total",
            "guard_audits_total",
            "guard_corruptions_total",
            "guard_repairs_total",
            "policy_preemptions_total",
            "policy_preempt_budget_exhausted_total",
            "admission_admitted_total",
            "admission_deferred_total",
            "admission_readmitted_total",
            "admission_shed_total",
            "admission_requeue_refusals_total",
        ):
            total, seen = 0.0, False
            for v in views:
                fams = v.get("metrics") or {}
                for _labels, value in fams.get("nhd_" + key, []):
                    total += value
                    seen = True
            if seen:
                counters[key] = int(total)
    fencing = {
        "stale_writes_rejected_total": counters.get(
            "ha_stale_writes_rejected_total", 0
        ),
        "renewal_failures_total": counters.get(
            "ha_renewal_failures_total", 0
        ),
        "handoffs_total": counters.get("shard_handoffs_total", 0),
    }
    spillover = {
        "spill_events_total": sum(spill_by_shard.values()),
        "by_shard": spill_by_shard,
        "max_hops_per_pod": max(hops_by_corr.values(), default=0),
        "cross_replica_journeys": cross_replica,
        "claims_total": counters.get("shard_spillover_claims_total", 0),
        "exhausted_total": counters.get(
            "shard_spillover_exhausted_total", 0
        ),
    }

    # incremental device-resident cluster state: the fleet-wide delta
    # economy (how much host/upload work the event stream actually cost
    # vs how often state fell back to a full rebuild)
    device_state = {
        "events_total": counters.get("device_state_events_total", 0),
        "deltas_total": counters.get("device_state_deltas_total", 0),
        "rows_uploaded_total": counters.get(
            "device_state_rows_uploaded_total", 0
        ),
        "full_rebuilds_total": counters.get(
            "device_state_full_rebuilds_total", 0
        ),
        # SPMD mesh posture (ISSUE 11): sharded megarounds dispatched
        # and the per-shard upload economy, fleet-summed like the rest
        "mesh": {
            "solves_total": counters.get("mesh_solves_total", 0),
            "rows_uploaded_total": counters.get(
                "mesh_rows_uploaded_total", 0
            ),
            "wholesale_uploads_total": counters.get(
                "mesh_wholesale_uploads_total", 0
            ),
        },
        # solver data-plane guard (ISSUE 12, solver/guard.py): rung is
        # the in-process degradation floor (the scrape path cannot sum
        # a gauge across replicas, so it stays 0 there — the per-replica
        # nhd_guard_rung series carries it); the _total families sum
        # like every other fleet counter
        "guard": {
            "rung": int(counters.get("guard_rung", 0)),
            "faults_total": counters.get("guard_faults_total", 0),
            "retries_total": counters.get("guard_retries_total", 0),
            "degradations_total": counters.get(
                "guard_degradations_total", 0
            ),
            "promotions_total": counters.get("guard_promotions_total", 0),
            "audits_total": counters.get("guard_audits_total", 0),
            "corruptions_total": counters.get(
                "guard_corruptions_total", 0
            ),
            "repairs_total": counters.get("guard_repairs_total", 0),
        },
    }

    # scheduling-policy engine (nhd_tpu/policy/): the fleet-wide
    # preemption ledger. score_mode is an in-process gauge (the scrape
    # path carries it per replica as nhd_policy_score_mode; summing a
    # mode across replicas is meaningless, so it stays 0 there).
    policy = {
        "preemptions_total": counters.get("policy_preemptions_total", 0),
        "budget_exhausted_total": counters.get(
            "policy_preempt_budget_exhausted_total", 0
        ),
        "score_mode": int(counters.get("policy_score_mode", 0)),
    }

    # ingress admission (nhd_tpu/ingress/): the fleet-wide front-door
    # ledger plus per-replica queue-depth gauges sourced from the SAME
    # exposition families /metrics serves — one backlog number, both
    # surfaces (ISSUE 20 gauge-consistency satellite)
    queue_depth: Dict[str, int] = {}
    queue_depth_max_tenant: Dict[str, int] = {}
    for v in views:
        fams = v.get("metrics") or {}
        for _labels, value in fams.get("nhd_event_queue_depth", []):
            queue_depth[v["replica"]] = int(value)
        for _labels, value in fams.get(
            "nhd_event_queue_depth_max_tenant", []
        ):
            queue_depth_max_tenant[v["replica"]] = int(value)
    ingress = {
        "admitted_total": counters.get("admission_admitted_total", 0),
        "deferred_total": counters.get("admission_deferred_total", 0),
        "readmitted_total": counters.get("admission_readmitted_total", 0),
        "shed_total": counters.get("admission_shed_total", 0),
        "requeue_refusals_total": counters.get(
            "admission_requeue_refusals_total", 0
        ),
        "queue_depth": queue_depth,
        "queue_depth_max_tenant": queue_depth_max_tenant,
    }

    shard_epochs: Dict[str, int] = {}
    for v in views:
        for shard, epoch in (v.get("shards") or {}).items():
            shard_epochs[shard] = max(shard_epochs.get(shard, 0), int(epoch))
    lead = dict(leadership or {})
    lead.setdefault("shard_epochs", shard_epochs)

    payload = {
        "replicas": [
            {
                "replica": v["replica"],
                "shards": v.get("shards") or {},
                "spans": len((v.get("trace") or {}).get("traceEvents", [])),
                "decisions": len(v.get("decisions") or []),
            }
            for v in views
        ],
        "per_shard": {
            "bind_latency": {
                shard: _bucketize(durs)
                for shard, durs in sorted(bind_durs.items())
            },
            "bind_latency_per_replica": per_replica_bind,
        },
        "spillover": spillover,
        "slo": slo_summary,
        "fencing": fencing,
        "device_state": device_state,
        "policy": policy,
        "ingress": ingress,
        "leadership": lead,
        "violations": list(violations or []),
        "journeys": {
            # watch-receipt orphans excluded: standbys mint a corr per
            # event they see, only the scheduling replica's leg re-joins
            "pods_traced": len(scheduled_journeys(journeys)),
            "cross_replica": cross_replica,
        },
    }
    if extra:
        payload.update(extra)
    return payload


def build_fleet_artifact(
    views: List[dict], *, seed: Optional[int] = None, **kwargs
) -> dict:
    """Payload + envelope in one step (the common producer call)."""
    return make_envelope(
        FLEET_KIND, FLEET_SCHEMA_VERSION,
        build_fleet_payload(views, **kwargs), seed=seed,
    )


def validate_fleet_artifact(obj: object) -> List[str]:
    """Schema errors for a fleet artifact ([] = valid): the envelope
    contract plus every payload section the readers depend on."""
    errs = validate_envelope(
        obj, kind=FLEET_KIND, schema_version=FLEET_SCHEMA_VERSION
    )
    if errs:
        return errs
    payload = obj["payload"]  # type: ignore[index]
    for section in FLEET_SECTIONS:
        if section not in payload:
            errs.append(f"payload missing section {section!r}")
    if errs:
        return errs
    if not isinstance(payload["replicas"], list):
        errs.append("payload.replicas must be a list")
    for i, rep in enumerate(payload["replicas"]):
        if not isinstance(rep, dict) or "replica" not in rep:
            errs.append(f"payload.replicas[{i}] missing 'replica'")
    if not isinstance(payload["violations"], list):
        errs.append("payload.violations must be a list")
    slo = payload["slo"]
    if not isinstance(slo, dict) or "worst_burn_rates" not in slo:
        errs.append("payload.slo missing worst_burn_rates")
    for shard, hist in (
        payload["per_shard"].get("bind_latency", {}) or {}
    ).items():
        for field in ("count", "sum_seconds", "buckets"):
            if field not in hist:
                errs.append(
                    f"per_shard.bind_latency[{shard}] missing {field!r}"
                )
    return errs


def write_fleet_artifact(
    artifact: dict, out_dir: str = "artifacts/fleet",
    *, name: Optional[str] = None,
) -> str:
    """Validate + atomically write one fleet artifact; raises ValueError
    on schema errors (a producer must never publish a file the readers
    reject)."""
    errs = validate_fleet_artifact(artifact)
    if errs:
        raise ValueError("invalid fleet artifact: " + "; ".join(errs))
    if name is None:
        seed = artifact.get("seed")
        stamp = int(artifact.get("created_unix", 0))
        name = f"fleet-seed{seed if seed is not None else 'x'}-{stamp}.json"
    return write_artifact(artifact, out_dir, name)
