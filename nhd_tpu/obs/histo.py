"""Prometheus histograms for the latency-shaped scheduler metrics.

The seed's ``last_*`` gauges were lossy by construction: a scrape sees
only the most recent batch, so any batch that lands between scrapes —
i.e. almost all of them — leaves no trace, and a p99 computed inside one
batch says nothing about the fleet over time. Histograms fix both:
cumulative buckets survive scrape gaps (counters never lose events) and
``histogram_quantile()`` gives real percentiles over any window.

Stdlib-only, like the rest of the metrics plane: a fixed ascending bucket
list, one lock per histogram (observes come from scheduler, commit-pool,
and API threads), and exact exposition rendering — bucket counts are
integers printed as integers, sums use ``repr`` (shortest round-trip), so
no ``:g`` precision loss on large counts (the same rule rpc/metrics.py
follows for counters).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, List, Sequence, Tuple

# default latency buckets: 0.5 ms .. 30 s — covers the daemon fast path
# (sub-ms binds, docs/TPU_STATUS.md) through federation gang sweeps
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)
# API round trips are faster-grained: 1 ms .. 15 s (the retry deadline)
API_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 15.0,
)


def _fmt(v: float) -> str:
    """Exact, minimal float rendering for le labels and sums ('0.005',
    not '5e-03'; integers shed their trailing '.0')."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class Histogram:
    """One cumulative-bucket histogram (thread-safe)."""

    def __init__(
        self, name: str, help_text: str,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(
                f"buckets must be a non-empty ascending sequence, "
                f"got {buckets!r}"
            )
        self.name = name
        self.help_text = help_text
        self.buckets: Tuple[float, ...] = tuple(float(b) for b in buckets)
        self._lock = threading.Lock()
        # per-bucket (non-cumulative) counts; index len(buckets) = +Inf
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        # Prometheus buckets are 'le': value exactly on an edge belongs
        # in that edge's bucket, hence bisect_left
        i = bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> Tuple[List[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count)."""
        with self._lock:
            raw = list(self._counts)
            total_sum, total_count = self._sum, self._count
        cum: List[int] = []
        running = 0
        for c in raw:
            running += c
            cum.append(running)
        return cum, total_sum, total_count

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0

    def render(self, prefix: str = "nhd_") -> List[str]:
        """Prometheus text exposition lines for this histogram."""
        cum, total_sum, total_count = self.snapshot()
        full = f"{prefix}{self.name}"
        lines = [
            f"# HELP {full} {self.help_text}",
            f"# TYPE {full} histogram",
        ]
        for edge, c in zip(self.buckets, cum):
            lines.append(f'{full}_bucket{{le="{_fmt(edge)}"}} {c}')
        lines.append(f'{full}_bucket{{le="+Inf"}} {cum[-1]}')
        lines.append(f"{full}_sum {_fmt(total_sum)}")
        lines.append(f"{full}_count {total_count}")
        return lines


class LabeledHistogram:
    """A histogram family with ONE label dimension (e.g. per solver
    round phase). Child histograms materialize on first observe; the
    label set must be bounded by construction at the call sites — phase
    names come from solver code, never from pod/corr identifiers
    (nhdlint NHD603 polices the unbounded-cardinality mistake)."""

    def __init__(
        self, name: str, label: str, help_text: str,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.label = label
        self.help_text = help_text
        self.buckets = tuple(float(b) for b in buckets)
        self._lock = threading.Lock()
        self._children: Dict[str, Histogram] = {}

    def observe(self, label_value: str, value: float) -> None:
        with self._lock:
            child = self._children.get(label_value)
            if child is None:
                child = Histogram(self.name, self.help_text, self.buckets)
                self._children[label_value] = child
        child.observe(value)

    def render(self, prefix: str = "nhd_") -> List[str]:
        full = f"{prefix}{self.name}"
        with self._lock:
            children = sorted(self._children.items())
        if not children:
            return []
        lines = [
            f"# HELP {full} {self.help_text}",
            f"# TYPE {full} histogram",
        ]
        for label_value, child in children:
            cum, total_sum, total_count = child.snapshot()
            sel = f'{self.label}="{label_value}"'
            for edge, c in zip(child.buckets, cum):
                lines.append(
                    f'{full}_bucket{{{sel},le="{_fmt(edge)}"}} {c}'
                )
            lines.append(f'{full}_bucket{{{sel},le="+Inf"}} {cum[-1]}')
            lines.append(f'{full}_sum{{{sel}}} {_fmt(total_sum)}')
            lines.append(f'{full}_count{{{sel}}} {total_count}')
        return lines

    def snapshot(self) -> Dict[str, Tuple[List[int], float, int]]:
        with self._lock:
            children = dict(self._children)
        return {k: child.snapshot() for k, child in children.items()}

    def reset(self) -> None:
        with self._lock:
            self._children.clear()


# ---------------------------------------------------------------------------
# registry: adding a histogram here is all it takes to surface it on
# /metrics (rpc/metrics.py renders the whole table, mirroring the
# ApiCounters.KNOWN convention)
# ---------------------------------------------------------------------------

HISTOGRAMS: Dict[str, Histogram] = {
    h.name: h
    for h in (
        Histogram(
            "bind_latency_seconds",
            "End-to-end per-pod bind latency: batch admission to bound",
        ),
        Histogram(
            "queue_wait_seconds",
            "Watch-event receipt to batch admission (event queue wait)",
        ),
        Histogram(
            "solve_phase_seconds",
            "Per-batch wall seconds in the batched feasibility solve",
        ),
        Histogram(
            "select_phase_seconds",
            "Per-batch wall seconds in candidate selection/packing",
        ),
        Histogram(
            "assign_phase_seconds",
            "Per-batch wall seconds in physical ID assignment",
        ),
        Histogram(
            "api_call_seconds",
            "Retry-layer API call latency (incl. backoff sleeps)",
            API_BUCKETS,
        ),
        Histogram(
            "time_to_bind_seconds",
            "True end-to-end pod creationTimestamp to bound (survives "
            "spillover hops, shard handoffs and replica restarts)",
            # SLO-shaped edges: the default latency ladder plus the
            # minutes range a spilled/orphaned pod can legitimately wait
            (*DEFAULT_BUCKETS, 60.0, 120.0, 300.0, 600.0),
        ),
    )
}

#: labeled families — one label dimension each (bounded label sets)
LABELED_HISTOGRAMS: Dict[str, LabeledHistogram] = {
    h.name: h
    for h in (
        LabeledHistogram(
            "round_phase_seconds", "phase",
            "Per-batch wall seconds by solver round phase (encode / "
            "materialize / upload / solve / select / readback ... — the "
            "fine-grained device-phase attribution, ISSUE 7)",
        ),
    )
}


def quantile_from_buckets(buckets, q: float) -> float:
    """Interpolated quantile from cumulative (edge, count) pairs — the
    PromQL ``histogram_quantile`` estimate, shared by every scrape-side
    percentile render (bench.py churn/daemon legs, obs/fleet.py).

    The old scrape-side p99 reported the raw upper EDGE of the covering
    bucket: any regression inside a bucket was invisible, and crossing
    an edge read as a cliff (a 251 ms p99 reported as 500 ms). Linear
    interpolation within the bucket fixes both. The lower edge of the
    first bucket is 0; a quantile landing in the +Inf bucket reports
    the last finite edge (there is no upper bound to interpolate to —
    PromQL's stance). Returns 0.0 with no observations.

    ``buckets``: iterable of (upper_edge, cumulative_count), ascending,
    +Inf edge last (``float('inf')`` accepted).
    """
    pairs = sorted(
        ((float(e), int(c)) for e, c in buckets), key=lambda p: p[0]
    )
    if not pairs or pairs[-1][1] <= 0:
        return 0.0
    total = pairs[-1][1]
    target = q * total
    prev_edge, prev_cum = 0.0, 0
    for edge, cum in pairs:
        if cum >= target:
            if edge == float("inf"):
                return prev_edge
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return edge
            return prev_edge + (edge - prev_edge) * (
                (target - prev_cum) / in_bucket
            )
        prev_edge, prev_cum = edge, cum
    return prev_edge


def observe(name: str, value: float) -> None:
    """Observe into a registered histogram (KeyError on a typo'd name —
    misspelled instrumentation must fail tests, not vanish)."""
    HISTOGRAMS[name].observe(value)


def observe_labeled(name: str, label_value: str, value: float) -> None:
    """Observe into a registered labeled family (KeyError on a typo)."""
    LABELED_HISTOGRAMS[name].observe(label_value, value)


def render_all(prefix: str = "nhd_") -> List[str]:
    lines: List[str] = []
    for name in HISTOGRAMS:
        lines.extend(HISTOGRAMS[name].render(prefix))
    for name in LABELED_HISTOGRAMS:
        lines.extend(LABELED_HISTOGRAMS[name].render(prefix))
    return lines


def reset_all() -> None:
    """Back to all-zero (test isolation)."""
    from nhd_tpu.obs.slo import SLO

    for h in HISTOGRAMS.values():
        h.reset()
    for lh in LABELED_HISTOGRAMS.values():
        lh.reset()
    # the global SLO tracker rides the same /metrics plane and must not
    # leak observations across reset_all-isolated tests
    SLO.reset()
