"""Chrome trace-viewer export for the flight recorder.

Emits the Trace Event Format's JSON-object form (the one chrome://tracing
and ui.perfetto.dev both load): a ``traceEvents`` list of complete ("X")
events with microsecond timestamps, plus thread-name metadata ("M")
events so the viewer labels rows by producing thread. The correlation ID
rides in ``args.corr``, so selecting any span of a pod surfaces the ID to
filter the rest of its pipeline.

The export is deterministic for deterministic input: events sort by
(ts, tid, name), timestamps are relative to the earliest span, and thread
IDs are assigned in first-seen-sorted order — golden-file tests diff the
serialized form directly.
"""

from __future__ import annotations

import itertools
import json
import os
from typing import Dict, List, Optional

from nhd_tpu.obs.recorder import FlightRecorder, Span

_PID = 1


def chrome_trace(recorder: FlightRecorder) -> dict:
    """Render the recorder's current ring as a Chrome trace dict."""
    return chrome_trace_of(recorder.spans())


def chrome_trace_of(spans: List[Span]) -> dict:
    origin = min((s.t0 for s in spans), default=0.0)
    tids: Dict[str, int] = {}
    for name in sorted({s.thread for s in spans}):
        tids[name] = len(tids) + 1
    events: List[dict] = [
        {
            "ph": "M", "name": "thread_name", "pid": _PID, "tid": tid,
            "args": {"name": tname},
        }
        for tname, tid in tids.items()
    ]
    body: List[dict] = []
    for s in spans:
        args: dict = {"corr": s.corr}
        if s.attrs:
            args.update(s.attrs)
        body.append({
            "ph": "X",
            "name": s.name,
            "cat": s.cat,
            "pid": _PID,
            "tid": tids[s.thread],
            # microseconds, rounded so float noise can't perturb goldens
            "ts": round((s.t0 - origin) * 1e6, 3),
            "dur": round(s.dur * 1e6, 3),
            "args": args,
        })
    body.sort(key=lambda e: (e["ts"], e["tid"], e["name"]))
    events.extend(body)
    return {"displayTimeUnit": "ms", "traceEvents": events}


def validate_chrome_trace(trace: object) -> List[str]:
    """Schema check for an exported trace; returns a list of problems
    (empty = valid). Shared by the test suite and ``make trace-demo`` so
    they cannot drift on what 'loads in the viewer' means."""
    errors: List[str] = []
    if not isinstance(trace, dict):
        return [f"top level must be an object, got {type(trace).__name__}"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents must be a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "M"):
            errors.append(f"{where}: unsupported ph {ph!r}")
            continue
        for field in ("name", "pid", "tid"):
            if field not in ev:
                errors.append(f"{where}: missing {field!r}")
        if ph == "X":
            for field in ("ts", "dur"):
                v = ev.get(field)
                if not isinstance(v, (int, float)) or v < 0:
                    errors.append(f"{where}: {field} must be a number >= 0")
            if not isinstance(ev.get("args", {}), dict):
                errors.append(f"{where}: args must be an object")
        else:  # metadata
            if not isinstance(ev.get("args", {}).get("name"), str):
                errors.append(f"{where}: metadata event needs args.name")
    return errors


# itertools.count: atomic under the GIL, so concurrent dump triggers
# (ThreadingHTTPServer /trace?save=1 racing the CLI exit dump) can never
# draw the same sequence number and clobber each other's file
_dump_seq = itertools.count(1)


def dump_chrome_trace(
    recorder: FlightRecorder, out_dir: str, *, stem: Optional[str] = None
) -> str:
    """Write the current ring to ``out_dir`` as pretty-printed trace JSON;
    returns the written path. Filenames carry pid + a per-process sequence
    so repeated dump triggers never clobber each other."""
    os.makedirs(out_dir, exist_ok=True)
    name = stem or f"nhd-trace-{os.getpid()}-{next(_dump_seq):03d}"
    path = os.path.join(out_dir, f"{name}.json")
    with open(path, "w") as fh:
        json.dump(chrome_trace(recorder), fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path
