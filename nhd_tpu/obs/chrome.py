"""Chrome trace-viewer export for the flight recorder.

Emits the Trace Event Format's JSON-object form (the one chrome://tracing
and ui.perfetto.dev both load): a ``traceEvents`` list of complete ("X")
events with microsecond timestamps, plus thread-name metadata ("M")
events so the viewer labels rows by producing thread. The correlation ID
rides in ``args.corr``, so selecting any span of a pod surfaces the ID to
filter the rest of its pipeline.

The export is deterministic for deterministic input: events sort by
(ts, tid, name), timestamps are relative to the earliest span, and thread
IDs are assigned in first-seen-sorted order — golden-file tests diff the
serialized form directly.
"""

from __future__ import annotations

import itertools
import json
import os
from typing import Dict, List, Optional

from nhd_tpu.obs.recorder import FlightRecorder, Span

_PID = 1


def chrome_trace(recorder: FlightRecorder) -> dict:
    """Render the recorder's current ring as a Chrome trace dict. The
    export carries an ``nhdMeta`` block (replica identity + the
    monotonic→wall anchor) so N replicas' dumps can be merged onto one
    timeline (merge_chrome_traces)."""
    return chrome_trace_of(
        recorder.spans(),
        meta={
            "replica": recorder.identity,
            "epochOffset": recorder.epoch_offset,
        },
    )


def chrome_trace_of(spans: List[Span], *, meta: Optional[dict] = None) -> dict:
    origin = min((s.t0 for s in spans), default=0.0)
    tids: Dict[str, int] = {}
    for name in sorted({s.thread for s in spans}):
        tids[name] = len(tids) + 1
    events: List[dict] = [
        {
            "ph": "M", "name": "thread_name", "pid": _PID, "tid": tid,
            "args": {"name": tname},
        }
        for tname, tid in tids.items()
    ]
    body: List[dict] = []
    for s in spans:
        args: dict = {"corr": s.corr}
        if s.attrs:
            args.update(s.attrs)
        # federation coordinates, only where stamped: which replica
        # produced the span, and which (shard, fencing epoch) covered a
        # commit-path leg — a merged journey shows every leadership a
        # pod's life ran under
        for key in ("replica", "shard", "epoch"):
            v = getattr(s, key, None)
            if v is not None:
                args[key] = v
        body.append({
            "ph": "X",
            "name": s.name,
            "cat": s.cat,
            "pid": _PID,
            "tid": tids[s.thread],
            # microseconds, rounded so float noise can't perturb goldens
            "ts": round((s.t0 - origin) * 1e6, 3),
            "dur": round(s.dur * 1e6, 3),
            "args": args,
        })
    body.sort(key=lambda e: (e["ts"], e["tid"], e["name"]))
    events.extend(body)
    out = {"displayTimeUnit": "ms", "traceEvents": events}
    if meta is not None:
        out["nhdMeta"] = {**meta, "originMono": origin}
    return out


# ---------------------------------------------------------------------------
# cross-replica journey merge (ISSUE 7): N replicas' dumps → one timeline
# ---------------------------------------------------------------------------


def merge_chrome_traces(traces: List[dict]) -> dict:
    """Merge N replicas' trace dumps into ONE Chrome trace: each input
    becomes its own pid (process row) named by its replica identity, and
    timestamps are re-based onto a shared wall clock via each dump's
    ``nhdMeta`` anchor (originMono + epochOffset) — so a pod that spilled
    across shards reads as one journey whose legs line up in real time.

    Re-basing is all-or-none: dumps without an ``nhdMeta`` anchor
    (pre-federation exports) have no wall reference, and mixing one into
    an anchored set would put it ~epoch-seconds away from the rest in
    the viewer — so if ANY input lacks the anchor, every input merges on
    its raw relative timestamps (correct within one process, best effort
    across several). Deterministic for deterministic input: pids are
    assigned in (replica name, input order) order and events sort by
    (ts, pid, tid, name)."""
    keyed = sorted(
        enumerate(traces),
        key=lambda it: (
            str((it[1].get("nhdMeta") or {}).get("replica", "")), it[0]
        ),
    )
    # each dump's absolute wall time at ts=0, or None when unanchored
    wall0: List[Optional[float]] = []
    for _, t in keyed:
        m = t.get("nhdMeta") or {}
        if "originMono" in m:
            wall0.append(
                float(m["originMono"]) + float(m.get("epochOffset", 0.0))
            )
        else:
            wall0.append(None)
    if any(w is None for w in wall0):
        wall0 = [0.0] * len(wall0)
    base = min(wall0, default=0.0)
    events: List[dict] = []
    body: List[dict] = []
    replicas: List[str] = []
    for pid0, ((idx, trace), w0) in enumerate(zip(keyed, wall0), start=1):
        m = trace.get("nhdMeta") or {}
        name = str(m.get("replica") or f"replica-{idx}")
        replicas.append(name)
        events.append({
            "ph": "M", "name": "process_name", "pid": pid0, "tid": 0,
            "args": {"name": name},
        })
        shift = (w0 - base) * 1e6
        for ev in trace.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = pid0
            if ev.get("ph") == "X":
                ev["ts"] = round(float(ev.get("ts", 0.0)) + shift, 3)
                body.append(ev)
            else:
                events.append(ev)
    body.sort(key=lambda e: (e["ts"], e["pid"], e["tid"], e["name"]))
    events.extend(body)
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "nhdMeta": {"merged": True, "replicas": replicas},
    }


def pod_journeys(trace: dict) -> Dict[str, List[dict]]:
    """corr ID → that pod's spans (X events), each journey sorted by
    timestamp. Works on single-replica exports and merged traces alike —
    the fleet aggregator and the federation tests both read journeys
    through this one definition."""
    out: Dict[str, List[dict]] = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        corr = (ev.get("args") or {}).get("corr")
        if not corr:
            continue
        out.setdefault(str(corr), []).append(ev)
    for evs in out.values():
        evs.sort(key=lambda e: (e.get("ts", 0.0), e.get("name", "")))
    return out


def scheduled_journeys(journeys: Dict[str, List[dict]]) -> Dict[str, List[dict]]:
    """Journeys that progressed past watch receipt. EVERY replica
    records a watch_event under its own locally minted corr (standbys
    included), and only the replica that schedules the pod re-aliases
    its receipt leg into the adopted journey — counting the one-span
    receipt orphans as journeys inflates the pod tally roughly
    n_replicas-fold."""
    return {
        corr: evs for corr, evs in journeys.items()
        if any(ev.get("name") != "watch_event" for ev in evs)
    }


def journey_replicas(
    trace: dict, corr: str, journeys: Optional[Dict[str, List[dict]]] = None
) -> List[str]:
    """The distinct replica identities that produced spans for one corr
    ID — ≥2 proves a cross-replica journey (spillover hop, shard
    handoff, fenced rejection + retry on the new owner). Pass the
    precomputed ``pod_journeys(trace)`` dict when iterating many corrs —
    rebuilding the index per corr is quadratic."""
    if journeys is None:
        journeys = pod_journeys(trace)
    seen = []
    for ev in journeys.get(corr, []):
        rep = (ev.get("args") or {}).get("replica")
        if rep and rep not in seen:
            seen.append(rep)
    return seen


def journey_view(corr: str) -> Dict[str, object]:
    """The one-pod journey payload the HTTP ``/journey?corr=`` endpoint
    serves (one definition, like decisions_view, so transports cannot
    drift): the corr's spans from the live ring rendered as trace
    events, its decision records, and — when a journal is recording —
    the journal line seqs indexed for it, so an operator can jump from
    a live journey straight to the replayable evidence."""
    from nhd_tpu.obs.journal import get_journal
    from nhd_tpu.obs.recorder import get_recorder

    rec = get_recorder()
    out: Dict[str, object] = {
        "corr": corr,
        "enabled": rec is not None,
        "spans": [],
        "decisions": [],
        "journal": None,
    }
    if rec is not None:
        out["spans"] = pod_journeys(chrome_trace(rec)).get(corr, [])
        decisions = [
            d for d in rec.recent_decisions(rec.decision_capacity)
            if d.get("corr") == corr
        ]
        decisions.reverse()  # recent_decisions is newest-first
        out["decisions"] = decisions
    jnl = get_journal()
    if jnl is not None:
        out["journal"] = {
            "path": jnl.path, "seqs": jnl.corr_seqs(corr),
        }
    return out


def validate_chrome_trace(trace: object) -> List[str]:
    """Schema check for an exported trace; returns a list of problems
    (empty = valid). Shared by the test suite and ``make trace-demo`` so
    they cannot drift on what 'loads in the viewer' means."""
    errors: List[str] = []
    if not isinstance(trace, dict):
        return [f"top level must be an object, got {type(trace).__name__}"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents must be a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "M"):
            errors.append(f"{where}: unsupported ph {ph!r}")
            continue
        for field in ("name", "pid", "tid"):
            if field not in ev:
                errors.append(f"{where}: missing {field!r}")
        if ph == "X":
            for field in ("ts", "dur"):
                v = ev.get(field)
                if not isinstance(v, (int, float)) or v < 0:
                    errors.append(f"{where}: {field} must be a number >= 0")
            if not isinstance(ev.get("args", {}), dict):
                errors.append(f"{where}: args must be an object")
        else:  # metadata
            if not isinstance(ev.get("args", {}).get("name"), str):
                errors.append(f"{where}: metadata event needs args.name")
    return errors


# itertools.count: atomic under the GIL, so concurrent dump triggers
# (ThreadingHTTPServer /trace?save=1 racing the CLI exit dump) can never
# draw the same sequence number and clobber each other's file
_dump_seq = itertools.count(1)


def dump_chrome_trace(
    recorder: FlightRecorder, out_dir: str, *, stem: Optional[str] = None
) -> str:
    """Write the current ring to ``out_dir`` as pretty-printed trace JSON;
    returns the written path. Filenames carry pid + a per-process sequence
    so repeated dump triggers never clobber each other."""
    os.makedirs(out_dir, exist_ok=True)
    name = stem or f"nhd-trace-{os.getpid()}-{next(_dump_seq):03d}"
    path = os.path.join(out_dir, f"{name}.json")
    with open(path, "w") as fh:
        json.dump(chrome_trace(recorder), fh, indent=2, sort_keys=True)
        fh.write("\n")
    return path
