"""Multi-host bootstrap for federation-scale scheduling.

The 100k-pod × 10k-node federation config (BASELINE config 5) fits one
chip's memory comfortably (node state is ~KBs/row), so multi-host is about
*locality and throughput*, not capacity: each host's devices own a node
shard (its region/cluster of the federation), solves ride ICI within a
slice and DCN across slices, and only the compact per-(type, node)
decisions travel.

The reference's analog is its API-server-centric distribution (SURVEY
§5.8): state in one place, one worker. Here the worker itself scales out.

Usage on each host of a multi-host deployment:

    from nhd_tpu.parallel import multihost
    multihost.initialize(coordinator="host0:9999", num_processes=4,
                         process_id=RANK)
    mine = multihost.local_nodes(all_nodes)   # this host's region
    StreamingScheduler(...).schedule(mine, items)
    # tiles stream within the host; each tile's solve shards over the
    # host's LOCAL devices (BatchScheduler auto-mesh uses
    # jax.local_devices() — per-host solves are independent programs).

Cannot be exercised end-to-end on this single-host dev image; the virtual
8-device CPU mesh (tests/conftest.py) covers the sharded code path and
tests/test_multihost.py covers the shard partitioning with a mocked
process topology.
"""

from __future__ import annotations

from typing import Optional

from nhd_tpu.utils import get_logger


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """jax.distributed.initialize with explicit or env-provided topology.

    With no arguments, defers to JAX's environment auto-detection
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID or the
    cluster plugin). Idempotent: re-initialization is a no-op.
    """
    import jax

    logger = get_logger(__name__)
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as exc:
        if "already initialized" in str(exc).lower():
            logger.warning("jax.distributed already initialized; ignoring")
            return
        raise
    logger.warning(
        f"multihost: process {jax.process_index()}/{jax.process_count()}, "
        f"{jax.local_device_count()} local of {jax.device_count()} devices"
    )


def node_slice(n_nodes: int, process_id: int, process_count: int) -> slice:
    """The contiguous node-index range a given process owns under a 1-D
    nodes mesh (block layout, matching the fused sharded megaround's
    padding — sharding.solve_bucket_ranked_sharded). Exposed by rank so
    a survivor can compute a DEAD rank's
    region for elastic takeover (tests/test_distributed.py failure leg)."""
    per = -(-n_nodes // process_count)  # ceil division
    start = per * process_id
    return slice(start, min(start + per, n_nodes))


def local_node_slice(n_nodes: int) -> slice:
    """node_slice for THIS process."""
    import jax

    return node_slice(n_nodes, jax.process_index(), jax.process_count())


def region_nodes(nodes: dict, process_id: int, process_count: int) -> dict:
    """The node shard rank *process_id* owns. Names are SORTED before
    slicing: each host builds its dict from its own API listing whose
    order is not guaranteed, and the partition must be identical on every
    host (exact cover, no node owned twice)."""
    names = sorted(nodes.keys())
    s = node_slice(len(names), process_id, process_count)
    return {n: nodes[n] for n in names[s]}


def local_nodes(nodes: dict) -> dict:
    """This process's node shard of a federation cluster — the multi-host
    streaming pattern: each host runs a StreamingScheduler over its own
    region (`StreamingScheduler.schedule(local_nodes(all), ...)`), so
    tiles stream within a host while the per-tile solve shards over that
    host's devices."""
    import jax

    return region_nodes(nodes, jax.process_index(), jax.process_count())
