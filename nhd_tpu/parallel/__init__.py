"""Multi-device / multi-host execution of the batched solve.

In an ML framework this package would hold DP/TP/PP shardings; in a
scheduler the data-parallel axis is the *cluster itself* (SURVEY §2): the
feasibility tensor [types × nodes × combos × picks] shards along the node
axis, pod types replicate, and selection is a cross-device reduction.

* sharding  — the fused solve+rank megaround over a 1-D ``nodes`` Mesh
  (single- or multi-host), plus the NHD_MESH operator-knob resolver
* multihost — jax.distributed bootstrap helpers for DCN-spanning meshes
"""

from nhd_tpu.parallel.sharding import (
    make_mesh,
    resolve_mesh_spec,
    solve_bucket_ranked_sharded,
)

__all__ = ["make_mesh", "resolve_mesh_spec", "solve_bucket_ranked_sharded"]
