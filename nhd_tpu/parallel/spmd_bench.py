"""cfg6 SPMD bench probe: the sharded fused megaround, end to end.

Runs in a FRESH subprocess (bench.py spawns it with
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` on CPU CI — the
virtual mesh must not leak into the parent bench's backend, and with >1
visible device the parent's every leg would silently go SPMD). On a real
TPU slice the same probe runs against the physical devices; the shape is
parameterized (``NHD_SPMD_PODS`` / ``NHD_SPMD_NODES`` /
``NHD_SPMD_DEVICES``) so the tunnel can run it full-scale.

Three identical drives of the same workload prove the three SPMD claims:

1. **parity** — every bucket's fused ranked solve over the mesh is
   bit-exact with the single-device fused program (the dryrun-harness
   assertion, now a bench gate);
2. **timed** (jit-warm) — the cfg6 figure: a gang schedule through the
   mesh-sharded device-resident path, then steady churn rounds whose
   per-round upload is asserted O(changed rows) via the
   ``nhd_device_state_*`` / ``nhd_mesh_*`` counters with ZERO wholesale
   fallbacks;
3. **prewarm** — restart-equivalent: live programs dropped, the AOT
   cache alone prewarmed (sharded artifacts included), the same drive
   replayed with the ``solve_ranked`` compile set provably flat.

Prints exactly ONE JSON line (a bench config record with an ``spmd``
section tools/bench_diff.py gates on); any violated claim raises — a
broken mesh path must fail the bench, not ship a numberless artifact.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional


def _drive(sched, nodes, catalog, n_pods: int, churn_rounds: int):
    """One deterministic workload pass: a gang batch through a
    delta-built mesh context, then ``churn_rounds`` steady rounds of
    node churn + small create batches folded in as row deltas. Shape
    stability across drives is the contract (the prewarm leg replays
    this exactly and asserts zero new solve_ranked programs)."""
    from nhd_tpu.solver.batch import BatchItem
    from nhd_tpu.solver.encode import ClusterDelta

    delta = ClusterDelta(nodes, now=0.0, respect_busy=False)
    ctx = sched.make_context(nodes, now=0.0, delta=delta)
    items = [
        BatchItem(("spmd", f"p{i}"), catalog[i % len(catalog)])
        for i in range(n_pods)
    ]
    t0 = time.perf_counter()
    results, stats = sched.schedule(ctx.nodes, items, context=ctx)
    wall = time.perf_counter() - t0
    placed = sum(1 for r in results if r.node)

    names = list(nodes.keys())
    churn_binds = 0
    flip = max(len(names) // 16, 1)
    for r in range(churn_rounds):
        # deterministic node churn: toggle a rolling cordon window
        for name in names[(r * flip) % len(names):][:flip]:
            nodes[name].active = not nodes[name].active
            delta.note(name)
        sched.refresh_context(ctx, now=0.0)
        # the same 64-request slice every round: identical type rows ->
        # identical padded shapes -> one compiled program serves every
        # churn round
        small = [
            BatchItem(("spmd", f"c{r}-{i}"), catalog[i % len(catalog)])
            for i in range(64)
        ]
        sub, _ = sched.schedule(ctx.nodes, small, context=ctx)
        churn_binds += sum(1 for x in sub if x.node)
    return wall, placed, stats, results, churn_binds


def run_probe(
    n_pods: int, n_nodes: int, n_dev: int, churn_rounds: int = 4,
    groups: Optional[List[str]] = None,
) -> dict:
    import shutil
    import tempfile

    import jax
    import numpy as np

    from nhd_tpu.k8s.retry import API_COUNTERS
    from nhd_tpu.obs.jitstats import JIT_STATS
    from nhd_tpu.parallel.sharding import (
        make_mesh, solve_bucket_ranked_sharded,
    )
    from nhd_tpu.sim.workloads import cap_cluster, workload_mix
    from nhd_tpu.solver import aot, kernel
    from nhd_tpu.solver.batch import BatchScheduler
    from nhd_tpu.solver.encode import encode_cluster, encode_pods

    if len(jax.devices()) < n_dev:
        raise RuntimeError(
            f"spmd probe needs {n_dev} devices, host exposes "
            f"{len(jax.devices())} (XLA_FLAGS not forwarded?)"
        )
    groups = groups or ["default", "edge"]
    mesh = make_mesh(jax.devices()[:n_dev])
    catalog = workload_mix(256, groups)
    cache = tempfile.mkdtemp(prefix="nhd-spmd-bench-")
    aot.reset()
    aot.configure(directory=cache, save=True)
    try:
        # ---- 1. parity: mesh fused megaround == single-device ----
        pnodes = cap_cluster(n_nodes, groups)
        cluster = encode_cluster(pnodes, now=0.0)
        R = kernel.rank_budget(1, cluster.n_nodes, accelerator=False)
        for G, pods in sorted(
            encode_pods(catalog[:64], cluster.interner).items()
        ):
            plain = np.asarray(kernel.solve_bucket_ranked(cluster, pods, R))
            shard = solve_bucket_ranked_sharded(cluster, pods, R, mesh)
            if not np.array_equal(plain, shard):
                raise RuntimeError(
                    f"SPMD parity violated: bucket G={G} mesh output "
                    "diverges from the single-device fused program"
                )

        def fresh_sched():
            return BatchScheduler(
                respect_busy=False, register_pods=False,
                device_state=True, mesh=mesh,
            )

        # ---- warm drive (untimed: compiles + AOT exports land) ----
        _drive(fresh_sched(), cap_cluster(n_nodes, groups), catalog,
               n_pods, churn_rounds)

        # ---- 2. timed drive + churn upload economy ----
        c0 = API_COUNTERS.snapshot()
        wall, placed, stats, results, churn_binds = _drive(
            fresh_sched(), cap_cluster(n_nodes, groups), catalog,
            n_pods, churn_rounds,
        )
        c1 = API_COUNTERS.snapshot()
        econ_rounds = stats.rounds  # the economy drive's round count
        # the reported gang figure is the MIN over three identical
        # drives: on CPU CI the mesh is N virtual devices time-slicing
        # few cores, and a single sample's solve wall is dominated by OS
        # scheduling (measured ±37% run-to-run at the cfg6 shape with
        # identical code) — min-of-N is the standard low-noise estimator
        # and keeps the bench_diff solve gate watching the program, not
        # the scheduler. The churn economy above stays single-drive (its
        # counters are deterministic).
        for _ in range(2):
            w2, p2, s2, r2, _cb = _drive(
                fresh_sched(), cap_cluster(n_nodes, groups), catalog,
                n_pods, 0,
            )
            if w2 < wall:
                wall, placed, stats, results = w2, p2, s2, r2
        rows_up = c1["device_state_rows_uploaded_total"] - (
            c0["device_state_rows_uploaded_total"]
        )
        mesh_rows = c1["mesh_rows_uploaded_total"] - (
            c0["mesh_rows_uploaded_total"]
        )
        deltas = c1["device_state_deltas_total"] - (
            c0["device_state_deltas_total"]
        )
        rebuilds = c1["device_state_full_rebuilds_total"] - (
            c0["device_state_full_rebuilds_total"]
        )
        wholesale = c1["mesh_wholesale_uploads_total"] - (
            c0["mesh_wholesale_uploads_total"]
        )
        binds = placed + churn_binds
        # O(changed rows): every uploaded row paid for by a row patch or
        # a staged claim (2x slack for rows changing twice per round),
        # plus any sanctioned rebuild's full rows — a wholesale re-shard
        # per round (rounds x n_nodes regardless of changes) blows this
        # by construction
        rounds_total = econ_rounds + churn_rounds
        budget = 2 * (deltas + binds) + rebuilds * n_nodes + (
            rounds_total * 64
        )
        if rows_up > budget:
            raise RuntimeError(
                f"mesh upload is not O(changed rows): {rows_up:.0f} rows "
                f"uploaded vs budget {budget:.0f} ({deltas:.0f} patches + "
                f"{binds} binds + {rebuilds:.0f} rebuilds)"
            )
        if wholesale:
            raise RuntimeError(
                f"{wholesale:.0f} wholesale mesh re-uploads in a steady "
                "run — the per-shard delta scatter is not engaging"
            )

        # ---- 3. restart-equivalent prewarm, compiles flat ----
        aot.AOT.drain()
        kernel.get_ranked_solver.cache_clear()
        kernel.get_ranked_solver_mesh.cache_clear()
        kernel.get_solver.cache_clear()
        JIT_STATS.reset()
        aot.reset()
        aot.configure(directory=cache, save=False)
        summary = aot.prewarm()
        mesh_loaded = sum(1 for k in summary["keys"] if "_m" in k)
        if summary["loaded"] == 0 or mesh_loaded == 0:
            raise RuntimeError(
                f"prewarm loaded {summary['loaded']} programs "
                f"({mesh_loaded} sharded) — sharded AOT export/prewarm "
                "is not engaging"
            )
        warm = JIT_STATS.snapshot()
        warm_ranked = {
            k for k in warm["shapes"] if k.startswith("solve_ranked:")
        }
        _drive(fresh_sched(), cap_cluster(n_nodes, groups), catalog,
               n_pods, churn_rounds)
        steady = JIT_STATS.snapshot()
        escaped = sorted(
            k for k in steady["shapes"]
            if k.startswith("solve_ranked:") and k not in warm_ranked
        )
        if escaped:
            raise RuntimeError(
                f"sharded programs re-traced after prewarm: {escaped} "
                f"(prewarmed: {sorted(warm_ranked)})"
            )
    finally:
        shutil.rmtree(cache, ignore_errors=True)

    return {
        "wall": wall,
        "placed": placed,
        "speedup": 0.0,
        "rounds": stats.rounds,
        "phases": {
            "solve": stats.solve_seconds,
            "select": stats.select_seconds,
            "assign": stats.assign_seconds,
            **stats.phases,
        },
        "p99_bind_ms": stats.bind_latency_percentile(results, 99) * 1e3,
        "spmd": {
            "devices": n_dev,
            "n_pods": n_pods,
            "n_nodes": n_nodes,
            "parity_ok": True,
            "prewarm_ok": True,
            "prewarm_loaded": summary["loaded"],
            "mesh_programs_loaded": mesh_loaded,
            "rows_uploaded": rows_up,
            "mesh_rows_uploaded": mesh_rows,
            "upload_budget": budget,
            "rows_per_round": round(rows_up / max(rounds_total, 1), 1),
            "wholesale_uploads": wholesale,
            "churn_binds": churn_binds,
        },
    }


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m nhd_tpu.parallel.spmd_bench", description=__doc__,
    )
    ap.add_argument("--pods", type=int, default=512)
    ap.add_argument("--nodes", type=int, default=256)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--churn-rounds", type=int, default=4)
    args = ap.parse_args(argv)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        from nhd_tpu.utils import force_cpu_backend

        force_cpu_backend()
    rec = run_probe(
        args.pods, args.nodes, args.devices, args.churn_rounds,
    )
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    import sys

    # canonical-module main (same dual-module trap as solver/aot.py)
    from nhd_tpu.parallel.spmd_bench import main as _canonical_main

    sys.exit(_canonical_main())
