"""Multi-chip sharding of the batched solve.

The feasibility tensor [T types, N nodes, C combos, A picks] is
embarrassingly parallel along the *node* axis — the natural mesh layout for
a scheduler (SURVEY §2: "data parallelism over pods and nodes"). Node-state
arrays shard along axis 0 of a 1-D ``nodes`` mesh; pod-type arrays are
replicated (they are tiny after gang dedup). Each device evaluates its node
shard; the fused megaround's top-R rank reduction lowers onto the mesh
(one all-gather class collective over ICI) and the packed [9, T, R]
decision tensor comes back replicated.

The production program is kernel.get_ranked_solver_mesh — the SAME fused
solve+rank megaround the single-device path runs, jitted with node-sharded
in/out shardings, reached through the one kernel.dispatch_ranked seam
(which also serves its AOT StableHLO export/prewarm). The legacy unfused
``get_sharded_solver`` + separate-ranker split is gone: intermediate
[T, N] SolveOut tensors no longer materialize between dispatches on a
mesh any more than they do on one chip.

Scaling shape for the 100k federation config (BASELINE config 5): shard
nodes over the mesh, stream pod-type chunks through (solver/streaming.py).
Operator knob: ``NHD_MESH`` / ``nhd-tpu --mesh`` (auto / N / off),
resolved by ``resolve_mesh_spec`` below.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from nhd_tpu.solver.kernel import (
    _ARG_ORDER,
    _pad_pow2,
    dispatch_ranked,
    mesh_shardings,
    pad_nodes,
    padded_args,
)


def make_mesh(devices=None, axis: str = "nodes") -> Mesh:
    """A 1-D device mesh over the node axis."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def resolve_mesh_spec(spec):
    """Operator mesh knob (``NHD_MESH`` / ``--mesh``) → a BatchScheduler
    ``mesh`` argument:

    * ``"auto"`` (default) — shard over every local device whenever more
      than one exists (BatchScheduler._resolve_mesh)
    * ``"off"`` / ``"0"`` / ``"none"`` — force the single-device path
    * ``"N"`` (an integer) — an explicit 1-D ``nodes`` mesh over the
      first N local devices; fewer available devices is a refused
      misconfiguration, not a silent downgrade
    """
    if spec is None:
        return "auto"
    if isinstance(spec, Mesh):
        return spec
    s = str(spec).strip().lower()
    if s in ("", "auto"):
        return "auto"
    if s in ("off", "0", "none"):
        return None
    try:
        n = int(s)
    except ValueError:
        raise ValueError(
            f"mesh spec must be 'auto', 'off'/'0'/'none' or a device "
            f"count, got {spec!r}"
        )
    devices = jax.local_devices()
    if n < 2:
        return None
    if n > len(devices):
        raise ValueError(
            f"mesh spec asks for {n} devices but only {len(devices)} are "
            f"local (JAX_PLATFORMS/XLA_FLAGS decide the device set)"
        )
    return make_mesh(devices[:n])


def _replicated_to_host(out) -> np.ndarray:
    """A replicated mesh output as one OWNED host copy (np.array — a
    zero-copy view would dangle once the jax array is dropped at return,
    the solver/batch.py bucket_out rule). Single-controller arrays are
    fully addressable; in multi-controller SPMD every process still
    holds a full copy per local device — read shard 0 instead of
    demanding global addressability."""
    if getattr(out, "is_fully_addressable", True):
        return np.array(out)
    return np.array(out.addressable_shards[0].data)


def solve_bucket_ranked_sharded(
    cluster, pods, R: Optional[int] = None, mesh: Optional[Mesh] = None,
) -> np.ndarray:
    """Sharded counterpart of kernel.solve_bucket_ranked: the fused
    solve+rank megaround over *mesh*, same packed [9, Tp, R] int32
    contract, node axis split across the mesh devices. ``R`` defaults to
    the padded node count (every node ranked — the parity-harness
    posture; production callers pass their rank budget).

    Bit-exactness with the single-device fused program is the contract
    (tests/test_spmd.py, tests/test_distributed.py): same program text,
    GSPMD only re-partitions it.
    """
    mesh = mesh or make_mesh()
    n_dev = mesh.devices.size
    T, N = pods.n_types, cluster.n_nodes
    Np = pad_nodes(N, n_dev)
    Tp = _pad_pow2(T)
    R = min(R or Np, Np)
    args = padded_args(cluster, pods, Tp, Np)

    multiproc = any(
        d.process_index != jax.process_index() for d in mesh.devices.flat
    )
    if multiproc:
        # multi-controller SPMD: every process holds the SAME global numpy
        # state (the scheduler's host mirror is replicated by contract) and
        # jit cannot shard raw numpy across processes — build global Arrays
        # explicitly before the one fused dispatch
        node_spec, repl_spec = mesh_shardings(mesh)
        n_node = len(_ARG_ORDER)

        def globalize(a, spec):
            return jax.make_array_from_callback(
                a.shape, spec, lambda idx: a[idx]
            )

        args = [
            globalize(a, node_spec if i < n_node else repl_spec)
            for i, a in enumerate(args)
        ]

    out = dispatch_ranked(
        pods.G, cluster.U, cluster.K, R, Tp, Np, args, mesh=mesh
    )
    # np.array (copy): a zero-copy view would dangle once the jax array
    # is dropped at return (see solver/batch.py bucket_out note)
    return _replicated_to_host(out)
