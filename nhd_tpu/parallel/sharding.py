"""Multi-chip sharding of the batched solve.

The feasibility tensor [T types, N nodes, C combos, A picks] is
embarrassingly parallel along the *node* axis — the natural mesh layout for
a scheduler (SURVEY §2: "data parallelism over pods and nodes"). Node-state
arrays shard along axis 0 of a 1-D ``nodes`` mesh; pod-type arrays are
replicated (they are tiny after gang dedup). Each device evaluates its node
shard; the per-(type, node) outputs come back sharded the same way, and the
final argmax-over-nodes selection is a cheap reduction XLA lowers onto the
mesh (an all-gather of [T, N_shard] rows over ICI).

Scaling shape for the 100k federation config (BASELINE config 5): shard
nodes over the mesh, stream pod-type chunks through (solver/streaming.py).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nhd_tpu.solver.combos import get_tables
from nhd_tpu.solver.kernel import SolveOut, _pad_pow2, _solve, pad_nodes


def make_mesh(devices=None, axis: str = "nodes") -> Mesh:
    """A 1-D device mesh over the node axis."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


# sharding layout per solver argument: True → shard along the node axis
_NODE_ARGS = [True] * 14 + [False] * 9


@lru_cache(maxsize=None)
def get_sharded_solver(n_groups: int, n_numa: int, max_nic: int, mesh: Mesh):
    """A pjit-compiled solver with node-sharded inputs/outputs on *mesh*."""
    tables = get_tables(n_groups, n_numa, max_nic)
    node_spec = NamedSharding(mesh, P("nodes"))
    repl_spec = NamedSharding(mesh, P())
    in_shardings = tuple(
        node_spec if is_node else repl_spec for is_node in _NODE_ARGS
    )
    # outputs are [T, N]: sharded along the node axis (dim 1)
    out_sharding = NamedSharding(mesh, P(None, "nodes"))

    def fn(*args):
        return _solve(tables, *args)

    return jax.jit(
        fn,
        in_shardings=in_shardings,
        out_shardings=SolveOut(*([out_sharding] * len(SolveOut._fields))),
    )


def solve_bucket_sharded(cluster, pods, mesh: Optional[Mesh] = None) -> SolveOut:
    """Sharded counterpart of kernel.solve_bucket: same inputs/outputs,
    node axis split across the mesh devices."""
    mesh = mesh or make_mesh()
    n_dev = mesh.devices.size
    T, N = pods.n_types, cluster.n_nodes

    # pad N to a multiple of the mesh size (and a power-of-two bucket so
    # re-solves reuse the jit cache); padded rows are inactive
    Np = pad_nodes(N, n_dev)
    Tp = _pad_pow2(T)

    def pad(a, size):
        if a.shape[0] == size:
            return a
        return np.concatenate(
            [a, np.zeros((size - a.shape[0], *a.shape[1:]), a.dtype)], axis=0
        )

    node_args = [
        pad(cluster.numa_nodes, Np), pad(cluster.smt, Np), pad(cluster.active, Np),
        pad(cluster.maintenance, Np), pad(cluster.busy, Np), pad(cluster.gpuless, Np),
        pad(cluster.group_mask, Np), pad(cluster.hp_free, Np),
        pad(cluster.cpu_free, Np), pad(cluster.gpu_free, Np),
        pad(cluster.nic_count, Np), pad(cluster.nic_free, Np),
        pad(cluster.nic_sw, Np), pad(cluster.gpu_free_sw, Np),
    ]
    pod_args = [
        pad(pods.cpu_dem_smt, Tp), pad(pods.cpu_dem_raw, Tp), pad(pods.gpu_dem, Tp),
        pad(pods.rx, Tp), pad(pods.tx, Tp), pad(pods.hp, Tp),
        pad(pods.needs_gpu, Tp), pad(pods.map_pci, Tp), pad(pods.group_mask, Tp),
    ]

    solver = get_sharded_solver(pods.G, cluster.U, cluster.K, mesh)

    multiproc = any(
        d.process_index != jax.process_index() for d in mesh.devices.flat
    )
    if multiproc:
        # multi-controller SPMD: every process holds the SAME global numpy
        # state (the scheduler's host mirror is replicated by contract) and
        # jit cannot shard raw numpy across processes — build global Arrays
        # explicitly, then gather the compact decision tensors back to
        # every host
        from jax.experimental import multihost_utils

        node_spec = NamedSharding(mesh, P("nodes"))
        repl_spec = NamedSharding(mesh, P())

        def globalize(a, spec):
            return jax.make_array_from_callback(
                a.shape, spec, lambda idx: a[idx]
            )

        out = solver(
            *[globalize(a, node_spec) for a in node_args],
            *[globalize(a, repl_spec) for a in pod_args],
        )
        # one pytree allgather (a single cross-host collective round), and
        # np.array copies per this function's no-dangling-views rule
        gathered = multihost_utils.process_allgather(
            tuple(x[:T, :N] for x in out), tiled=True
        )
        return SolveOut(*(np.array(x) for x in gathered))

    out = solver(*node_args, *pod_args)
    # np.array (copy): a zero-copy view would dangle once the jax arrays
    # are dropped at return (see solver/batch.py bucket_out note)
    return SolveOut(*(np.array(x[:T, :N]) for x in out))
