"""NHD21x — interprocedural lock-graph analysis (project pack 'lockgraph').

PR 1's NHD2xx rules judge one function at a time; the deadlock that cost
the tier-1 budget was a *cross-module* blocking cycle (streaming tile
workers holding solver state while pjit collectives waited forever).
This pack analyzes the whole path set at once:

1. **lock registry** — every ``threading.Lock/RLock/Condition`` bound to
   a module-level name or a ``self.X``/class attribute, with its
   construction site and reentrancy kind (``Condition(self.X)`` aliases
   the lock it wraps, as in rules_locks);
2. **call graph** — module-local calls (``f()``, ``self.m()``,
   ``cls.m()``) plus cross-module edges resolved through ``import`` /
   ``from ... import`` (absolute and relative) against the analyzed set;
3. **per-function summaries** — which locks a function acquires, which
   calls and known-blocking operations it performs, and which locks are
   held at each of those program points (``with <lock>:`` nesting);
4. **transitive facts** — ``may_acquire(f)`` / ``may_block(f)``
   propagated over the call graph to a fixed point, each fact carrying a
   shortest witness chain for the diagnostic.

Rules emitted:

* **NHD210** lock-order inversion: the whole-program lock-order graph
  (edges L→M: M acquired, possibly through calls, while L is held)
  contains both L→M and M→L. Reported at both witness sites.
* **NHD211** blocking call while a lock is held: an unbounded
  ``.get()``/``.join()``/``.wait()``, a socket ``recv``/``accept``, or a
  solver/pjit entry point (``solve_bucket``/``solve_bucket_ranked_sharded``)
  executes — directly or through the call graph — under a held lock.
  ``Condition.wait`` releases *its own* lock, so that lock is subtracted
  before judging.
* **NHD212** re-entrant acquisition of a non-reentrant ``Lock``: a call
  path from a ``with self.X:`` body re-enters ``with self.X:`` (the
  callback-under-lock shape — the scheduler thread invoking a callback
  that takes the lock it already holds deadlocks itself).

Blocking-call heuristics lean on call-shape, not type inference: a
no-positional-arg ``.get()``/``.join()``/``.wait()`` cannot be
``dict.get``/``str.join`` (those require an argument), and a ``timeout=``
keyword (any value) marks the wait bounded, hence not a deadlock.

The same machinery exports the lock graph (``build_lock_graph`` →
JSON-ready dict, ``lock_graph_dot`` → Graphviz) so the runtime witnesses
nhdsan records (``nhd_tpu/sanitizer/``) correlate with static facts by
lock construction site.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from nhd_tpu.analysis.core import Finding, ModuleSource, _dotted

_LOCK_CTORS = {"Lock", "RLock", "Condition"}
# names that dispatch a (potentially unbounded) sharded/pjit solve — the
# scheduler's own "collective rendezvous" entry points
_SOLVER_ENTRYPOINTS = {
    "solve_bucket", "solve_bucket_ranked", "solve_bucket_ranked_sharded",
}
_MAX_CHAIN = 4          # witness chains are truncated for readability


# ---------------------------------------------------------------------------
# small shared helpers
# ---------------------------------------------------------------------------

def _mod_label(path: str) -> str:
    """Stable per-module label: last two path components, extension
    dropped — agrees between the gate's absolute paths and the CLI's
    relative ones (same convention as Finding.fingerprint)."""
    parts = Path(path).with_suffix("").parts
    return "/".join(parts[-2:]) if len(parts) >= 2 else parts[0]


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        if node.value.id in ("self", "cls"):
            return node.attr
    return None


@dataclass(frozen=True)
class Lock:
    key: str            # "mod/label:Class.attr" or "mod/label:NAME"
    name: str           # display: "Class.attr" / "NAME"
    kind: str           # "Lock" | "RLock" | "Condition"
    path: str
    line: int

    @property
    def site(self) -> str:
        return f"{self.path}:{self.line}"

    @property
    def reentrant(self) -> bool:
        # Condition() owns an RLock; Condition(self.X) aliases X and is
        # resolved to X before this is consulted
        return self.kind in ("RLock", "Condition")


@dataclass
class _Event:
    """One program point inside a function: a lock acquisition, a call,
    or a known-blocking operation — with the locks held on entry."""

    kind: str                       # "acquire" | "call" | "block"
    target: object                  # lock key | callee ref | block desc
    held: FrozenSet[str]
    line: int
    col: int


@dataclass
class _Func:
    qual: str           # "mod/label:Class.method[.<locals>.inner]"
    path: str
    line: int
    cls: Optional[str]
    module: object = None           # owning _Module (set at index time)
    parent: Optional["_Func"] = None    # enclosing function, if nested
    nested: Dict[str, "_Func"] = field(default_factory=dict)
    events: List[_Event] = field(default_factory=list)


# callee references, resolved lazily against the project
# ("local", name) / ("method", cls, name) / ("ext", dotted_mod, name)
_CallRef = Tuple


def _direct_nested_defs(fn: ast.AST):
    """Function defs nested directly in *fn*'s body (not inside deeper
    defs or nested classes) — each becomes its own summarized function."""
    stack = list(fn.body)  # type: ignore[attr-defined]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
            continue
        if isinstance(node, ast.ClassDef):
            continue
        stack.extend(ast.iter_child_nodes(node))


class _Module:
    """Per-module facts: locks, aliases, functions, import bindings."""

    def __init__(self, source: ModuleSource):
        self.path = source.path
        self.tree = source.tree
        self.label = _mod_label(source.path)
        self.locks: Dict[str, Lock] = {}        # scoped name -> Lock
        self.alias: Dict[str, str] = {}         # cond key -> lock key
        self.funcs: Dict[str, _Func] = {}       # "func" / "Cls.meth" -> _Func
        self.import_funcs: Dict[str, Tuple[str, str]] = {}  # local -> (mod, name)
        self.import_mods: Dict[str, str] = {}   # local alias -> dotted module

    # -- lock + import discovery ---------------------------------------

    def _lock_ctor_kind(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        d = _dotted(node.func)
        if d is None:
            return None
        tail = d.split(".")[-1]
        return tail if tail in _LOCK_CTORS else None

    def _dotted_of_import(self, node: ast.ImportFrom) -> Optional[str]:
        """Absolute dotted target of an ImportFrom, resolving relative
        levels against this module's path tail."""
        if node.level == 0:
            return node.module
        parts = list(Path(self.path).parts[:-1])  # containing package dirs
        drop = node.level - 1
        if drop:
            parts = parts[:-drop] if drop <= len(parts) else []
        base = [p for p in parts if p not in (".", "/")]
        mod = list(node.module.split(".")) if node.module else []
        return ".".join(base[-3:] + mod) if (base or mod) else None

    def collect(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Assign):
                kind = self._lock_ctor_kind(node.value)
                if kind:
                    cond_arg = None
                    if kind == "Condition" and node.value.args:  # type: ignore[union-attr]
                        arg = node.value.args[0]  # type: ignore[union-attr]
                        if isinstance(arg, ast.Name):
                            # COND = threading.Condition(LOCK) aliases
                            # LOCK, same as the class-level form
                            cond_arg = arg.id
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self._add_lock(tgt.id, kind, node, cond_arg)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    # 'import a.b.c' binds the name 'a' (dotted calls spell
                    # the full path themselves); 'import a.b.c as z' binds
                    # z directly to a.b.c
                    local = a.asname or a.name.split(".")[0]
                    self.import_mods[local] = a.name if a.asname else local
            elif isinstance(node, ast.ImportFrom):
                dotted = self._dotted_of_import(node)
                if dotted is None:
                    continue
                for a in node.names:
                    self.import_funcs[a.asname or a.name] = (dotted, a.name)
            elif isinstance(node, ast.ClassDef):
                self._collect_class_locks(node)
        # resolve Condition(self.X) aliases now that every lock is known
        for cond_key, lock_key in list(self.alias.items()):
            if lock_key not in self.locks and cond_key in self.alias:
                del self.alias[cond_key]

    def _add_lock(self, scoped: str, kind: str, node: ast.AST,
                  cond_arg: Optional[str] = None) -> None:
        key = f"{self.label}:{scoped}"
        if scoped not in self.locks:
            self.locks[scoped] = Lock(key, scoped, kind, self.path, node.lineno)
        if cond_arg is not None:
            self.alias[scoped] = cond_arg

    def _collect_class_locks(self, cls: ast.ClassDef) -> None:
        class_level = {id(n) for n in cls.body}
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            kind = self._lock_ctor_kind(node.value)
            if not kind:
                continue
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if (attr is None and isinstance(tgt, ast.Name)
                        and id(node) in class_level):
                    # bare-name locks only at class level: a function
                    # LOCAL 'lock = threading.Lock()' has no cross-call
                    # identity the AST can track (that's nhdsan's job at
                    # runtime) and must not masquerade as a class lock
                    attr = tgt.id
                if attr is None:
                    continue
                cond_arg = None
                if kind == "Condition" and node.value.args:  # type: ignore[union-attr]
                    inner = _self_attr(node.value.args[0])   # type: ignore[union-attr]
                    if inner is not None:
                        cond_arg = f"{cls.name}.{inner}"
                self._add_lock(f"{cls.name}.{attr}", kind, node, cond_arg)

    # -- lock expression resolution ------------------------------------

    def lock_key_of(self, expr: ast.AST, cls: Optional[str]) -> Optional[str]:
        """Resolve a with-item / receiver expression to a canonical lock
        key (following Condition aliases), or None if untracked."""
        scoped: Optional[str] = None
        attr = _self_attr(expr)
        if attr is not None and cls is not None:
            scoped = f"{cls}.{attr}"
        elif isinstance(expr, ast.Name) and expr.id in self.locks:
            scoped = expr.id
        if scoped is None or scoped not in self.locks:
            return None
        scoped = self.alias.get(scoped, scoped)
        return self.locks[scoped].key if scoped in self.locks else None


# ---------------------------------------------------------------------------
# per-function event extraction
# ---------------------------------------------------------------------------

def _blocking_desc(call: ast.Call) -> Optional[str]:
    """A human description if *call* is a known potentially-unbounded
    blocking operation, else None."""
    kwnames = {k.arg for k in call.keywords}
    if isinstance(call.func, ast.Attribute):
        name = call.func.attr
        no_pos = not call.args
        bounded = "timeout" in kwnames
        if name == "get" and no_pos and not bounded:
            for k in call.keywords:
                if (k.arg == "block" and isinstance(k.value, ast.Constant)
                        and k.value.value is False):
                    return None
            return ".get() with no timeout"
        if name in ("join", "wait") and no_pos and not bounded:
            return f".{name}() with no timeout"
        if name in ("recv", "recv_into", "accept"):
            return f".{name}() on a socket/pipe"
        if name == "communicate" and not bounded:
            return ".communicate() with no timeout"
    d = _dotted(call.func)
    if d is not None and d.split(".")[-1] in _SOLVER_ENTRYPOINTS:
        return f"{d}() (sharded/pjit solve entry)"
    return None


class _FuncWalker:
    """Walk one function body tracking the set of held (tracked) locks;
    record acquire/call/block events in program order."""

    def __init__(self, mod: _Module, func: _Func):
        self.mod = mod
        self.func = func

    def walk(self, fn: ast.AST) -> None:
        for stmt in fn.body:  # type: ignore[attr-defined]
            self._visit(stmt, frozenset())

    def _visit(self, node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, ast.With):
            now = held
            for item in node.items:
                key = self.mod.lock_key_of(item.context_expr, self.func.cls)
                if key is not None:
                    self.func.events.append(_Event(
                        "acquire", key, now, item.context_expr.lineno,
                        item.context_expr.col_offset,
                    ))
                    now = now | {key}
            for child in node.body:
                self._visit(child, now)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, possibly unlocked: it gets its own
            # summary (_index_functions recurses into closures), and the
            # CALL to it — not its definition — inherits the held set
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, held)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _visit_call(self, node: ast.Call, held: FrozenSet[str]) -> None:
        # bare <lock>.acquire() is an ordering fact too (NHD202 already
        # flags the form itself)
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "acquire":
            key = self.mod.lock_key_of(func.value, self.func.cls)
            if key is not None:
                self.func.events.append(_Event(
                    "acquire", key, held, node.lineno, node.col_offset))
                return
        desc = _blocking_desc(node)
        if desc is not None:
            eff = held
            if isinstance(func, ast.Attribute) and func.attr == "wait":
                # Condition.wait releases its own lock while waiting: the
                # condition's (aliased) lock never counts as held across
                # the wait, and a wait on a *tracked* condition with no
                # other lock held is the canonical pattern — not recorded
                # at all, so callers holding the same condition's lock
                # don't inherit a phantom may_block fact
                key = self.mod.lock_key_of(func.value, self.func.cls)
                if key is not None:
                    eff = eff - {key}
                    if not eff:
                        desc = None
            if desc is not None:
                self.func.events.append(_Event(
                    "block", desc, eff, node.lineno, node.col_offset))
                return
        ref = self._callee_ref(node)
        if ref is not None:
            self.func.events.append(_Event(
                "call", ref, held, node.lineno, node.col_offset))

    def _callee_ref(self, node: ast.Call) -> Optional[_CallRef]:
        func = node.func
        if isinstance(func, ast.Name):
            name = func.id
            if name in self.mod.import_funcs:
                return ("ext", *self.mod.import_funcs[name])
            return ("local", name)
        attr = _self_attr(func)
        if attr is not None and self.func.cls is not None:
            return ("method", self.func.cls, attr)
        d = _dotted(func)
        if d is not None and "." in d:
            head, _, rest = d.partition(".")
            mod_part, _, fn_part = d.rpartition(".")
            if head in self.mod.import_mods and rest:
                # import a.b as z; z.f() — or import a.b.c; a.b.c.f()
                real = self.mod.import_mods[head]
                if mod_part == head:
                    mod_part = real
                return ("ext", mod_part, fn_part)
            if head in self.mod.import_funcs and rest:
                # from pkg import mod; mod.f() — the "func" import was a
                # module object
                base, name = self.mod.import_funcs[head]
                if mod_part == head:
                    return ("ext", f"{base}.{name}", fn_part)
        return None


# ---------------------------------------------------------------------------
# the project analysis
# ---------------------------------------------------------------------------

class LockGraphAnalysis:
    # subclasses (ownership.py) swap in a richer walker that records
    # field accesses alongside the acquire/call/block events; every
    # consumer loop here dispatches on ev.kind, so extra kinds are inert
    walker_cls = _FuncWalker

    def __init__(self, modules: Sequence[ModuleSource]):
        self.modules = [_Module(m) for m in modules]
        self.locks: Dict[str, Lock] = {}
        self.funcs: Dict[str, _Func] = {}       # fid -> func
        self._by_suffix: Dict[str, Optional[_Module]] = {}
        # transitive facts: fid -> lock key -> (chain, site)
        self.may_acquire: Dict[str, Dict[str, Tuple[Tuple[str, ...], str]]] = {}
        # fid -> (desc, chain, site) of one reachable blocking op
        self.may_block: Dict[str, Optional[Tuple[str, Tuple[str, ...], str]]] = {}
        # (L, M) -> witness (path, line, col, via-chain, detail)
        self.order_edges: Dict[
            Tuple[str, str], Tuple[str, int, int, Tuple[str, ...]]
        ] = {}
        self._ran = False

    # -- construction ---------------------------------------------------

    def _register_suffixes(self, mod: _Module) -> None:
        parts = Path(mod.path).with_suffix("").parts
        for k in range(1, min(len(parts), 5) + 1):
            suffix = ".".join(parts[-k:])
            if suffix in self._by_suffix and self._by_suffix[suffix] is not mod:
                self._by_suffix[suffix] = None   # ambiguous: refuse to guess
            else:
                self._by_suffix[suffix] = mod

    def _index_functions(self, mod: _Module) -> None:
        def add(fn: ast.AST, cls: Optional[str], parent: Optional[_Func],
                scoped: str) -> None:
            func = _Func(
                qual=f"{mod.label}:{scoped}", path=mod.path,
                line=fn.lineno, cls=cls, module=mod, parent=parent,
            )
            if parent is None:
                mod.funcs.setdefault(scoped, func)
            else:
                parent.nested[fn.name] = func  # type: ignore[attr-defined]
            self.funcs[func.qual] = func
            self.walker_cls(mod, func).walk(fn)
            # closures: the streaming tile workers (the shape of the real
            # deadlock) are nested defs — they need their own summaries
            for sub in _direct_nested_defs(fn):
                add(sub, cls, func, f"{scoped}.<locals>.{sub.name}")

        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add(node, None, None, node.name)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        add(sub, node.name, None, f"{node.name}.{sub.name}")

    def _resolve(self, caller: _Func, ref: _CallRef) -> Optional[_Func]:
        mod: _Module = caller.module  # type: ignore[assignment]
        kind = ref[0]
        if kind == "local":
            # lexical scope chain: own closures first, then siblings via
            # the enclosing function, then module level
            cur: Optional[_Func] = caller
            while cur is not None:
                hit = cur.nested.get(ref[1])
                if hit is not None:
                    return hit
                cur = cur.parent
            return mod.funcs.get(ref[1])
        if kind == "method":
            return mod.funcs.get(f"{ref[1]}.{ref[2]}")
        if kind == "ext":
            dotted, name = ref[1], ref[2]
            target = None
            # longest-suffix match of the dotted module against the set
            parts = dotted.split(".")
            for k in range(len(parts), 0, -1):
                cand = self._by_suffix.get(".".join(parts[-k:]))
                if cand is not None:
                    target = cand
                    break
            if target is None:
                return None
            return target.funcs.get(name)
        return None

    # -- fixed-point propagation ---------------------------------------

    def run(self) -> None:
        if self._ran:
            return
        self._ran = True
        for mod in self.modules:
            mod.collect()
            self._register_suffixes(mod)
            for lock in mod.locks.values():
                # aliased Conditions resolve through lock_key_of; only
                # canonical locks enter the global registry
                self.locks.setdefault(lock.key, lock)
        for mod in self.modules:
            self._index_functions(mod)

        for fid, fn in self.funcs.items():
            acq: Dict[str, Tuple[Tuple[str, ...], str]] = {}
            blk: Optional[Tuple[str, Tuple[str, ...], str]] = None
            for ev in fn.events:
                site = f"{fn.path}:{ev.line}"
                if ev.kind == "acquire" and ev.target not in acq:
                    acq[ev.target] = ((), site)          # type: ignore[index]
                elif ev.kind == "block" and blk is None:
                    blk = (ev.target, (), site)          # type: ignore[assignment]
            self.may_acquire[fid] = acq
            self.may_block[fid] = blk

        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for fid, fn in self.funcs.items():
                for ev in fn.events:
                    if ev.kind != "call":
                        continue
                    callee = self._resolve(fn, ev.target)
                    if callee is None:
                        continue
                    for lk, (chain, site) in self.may_acquire[
                        callee.qual
                    ].items():
                        new_chain = (callee.qual, *chain)[:_MAX_CHAIN]
                        cur = self.may_acquire[fid].get(lk)
                        if cur is None or len(new_chain) < len(cur[0]):
                            self.may_acquire[fid][lk] = (new_chain, site)
                            changed = True
                    cblk = self.may_block[callee.qual]
                    if cblk is not None and self.may_block[fid] is None:
                        desc, chain, site = cblk
                        self.may_block[fid] = (
                            desc, (callee.qual, *chain)[:_MAX_CHAIN], site
                        )
                        changed = True

        # lock-order edges L -> M (M acquired while L held)
        for fid, fn in self.funcs.items():
            for ev in fn.events:
                if ev.kind == "acquire":
                    for l in ev.held:
                        self._edge(l, ev.target, fn, ev, ())  # type: ignore[arg-type]
                elif ev.kind == "call" and ev.held:
                    callee = self._resolve(fn, ev.target)
                    if callee is None:
                        continue
                    for m, (chain, _site) in self.may_acquire[
                        callee.qual
                    ].items():
                        for l in ev.held:
                            self._edge(
                                l, m, fn, ev, (callee.qual, *chain)
                            )

    def _edge(self, l: str, m: str, fn: _Func, ev: _Event,
              via: Tuple[str, ...]) -> None:
        key = (l, m)
        cur = self.order_edges.get(key)
        if cur is None or len(via) < len(cur[3]):
            self.order_edges[key] = (fn.path, ev.line, ev.col, via[:_MAX_CHAIN])

    # -- findings -------------------------------------------------------

    def _name(self, key: str) -> str:
        lock = self.locks.get(key)
        return lock.name if lock else key

    def findings(self) -> List[Finding]:
        self.run()
        out: List[Finding] = []
        seen: Set[Tuple[str, str, int, str]] = set()

        def emit(rule: str, path: str, line: int, col: int, msg: str) -> None:
            k = (rule, path, line, msg)
            if k not in seen:
                seen.add(k)
                out.append(Finding(rule, path, line, col, msg))

        # NHD210: both directions present between two distinct locks
        for (l, m), (path, line, col, via) in sorted(self.order_edges.items()):
            if l >= m:
                continue
            rev = self.order_edges.get((m, l))
            if rev is None:
                continue
            for (a, b), (p, ln, c, chain), other in (
                ((l, m), (path, line, col, via), rev),
                ((m, l), rev, (path, line, col, via)),
            ):
                hop = f" via {' -> '.join(chain)}" if chain else ""
                emit(
                    "NHD210", p, ln, c,
                    f"lock-order inversion: acquires '{self._name(b)}' "
                    f"while holding '{self._name(a)}'{hop}, but "
                    f"{other[0]}:{other[1]} takes them in the opposite "
                    "order — two threads interleaving these paths "
                    "deadlock; pick one global order",
                )

        # NHD212: re-entrant acquisition of a non-reentrant Lock
        for (l, m), (path, line, col, via) in sorted(self.order_edges.items()):
            if l != m:
                continue
            lock = self.locks.get(l)
            if lock is None or lock.reentrant:
                continue
            hop = f" via {' -> '.join(via)}" if via else ""
            emit(
                "NHD212", path, line, col,
                f"re-entrant acquisition of non-reentrant lock "
                f"'{self._name(l)}'{hop}: a callback invoked while the "
                "lock is held re-acquires it and deadlocks the calling "
                "thread — use RLock or move the call outside the lock",
            )

        # NHD211: blocking op (direct or transitive) while a lock is held
        for fid, fn in sorted(self.funcs.items()):
            for ev in fn.events:
                if ev.kind == "block" and ev.held:
                    emit(
                        "NHD211", fn.path, ev.line, ev.col,
                        f"blocking {ev.target} while holding "
                        f"{self._held_names(ev.held)}: every thread "
                        "needing the lock stalls behind this wait (and a "
                        "cycle with the wait's producer deadlocks) — "
                        "release the lock first or bound the wait",
                    )
                elif ev.kind == "call" and ev.held:
                    callee = self._resolve(fn, ev.target)
                    if callee is None:
                        continue
                    blk = self.may_block[callee.qual]
                    if blk is None:
                        continue
                    desc, chain, site = blk
                    path_s = " -> ".join((callee.qual, *chain)[:_MAX_CHAIN])
                    emit(
                        "NHD211", fn.path, ev.line, ev.col,
                        f"call reaches blocking {desc} (at {site} via "
                        f"{path_s}) while holding "
                        f"{self._held_names(ev.held)} — release the lock "
                        "before the call or bound the wait",
                    )
        return out

    def _held_names(self, held: FrozenSet[str]) -> str:
        return ", ".join(f"'{self._name(h)}'" for h in sorted(held))

    # -- export ---------------------------------------------------------

    def graph(self) -> dict:
        """JSON-ready lock graph: nodes keyed like nhdsan keys its
        runtime locks (construction site), so static edges and runtime
        witnesses correlate (docs/OBSERVABILITY.md)."""
        self.run()
        inversions = sorted(
            [l, m] for (l, m) in self.order_edges
            if l < m and (m, l) in self.order_edges
        )
        return {
            "version": 1,
            "locks": [
                {
                    "key": lock.key, "name": lock.name, "kind": lock.kind,
                    "site": lock.site,
                }
                for _, lock in sorted(self.locks.items())
            ],
            "edges": [
                {
                    "from": l, "to": m, "path": path, "line": line,
                    "via": list(via),
                }
                for (l, m), (path, line, _col, via)
                in sorted(self.order_edges.items())
            ],
            "inversions": inversions,
        }


def check_project(modules: Sequence[ModuleSource]) -> List[Finding]:
    return LockGraphAnalysis(modules).findings()


def build_lock_graph(modules: Sequence[ModuleSource]) -> dict:
    return LockGraphAnalysis(modules).graph()


def lock_graph_dot(graph: dict) -> str:
    """Render a build_lock_graph() dict as Graphviz DOT. Inverted pairs
    are drawn red+bold so `dot -Tsvg` makes the deadlock jump out."""
    inverted = {tuple(pair) for pair in graph.get("inversions", [])}
    lines = [
        "digraph nhd_lock_order {",
        "  rankdir=LR;",
        '  node [shape=box, fontname="monospace"];',
    ]
    for lock in graph["locks"]:
        label = f"{lock['name']}\\n[{lock['kind']}] {lock['site']}"
        lines.append(f'  "{lock["key"]}" [label="{label}"];')
    for edge in graph["edges"]:
        l, m = edge["from"], edge["to"]
        hot = (l, m) in inverted or (m, l) in inverted
        style = ' [color=red, penwidth=2.0]' if hot else ""
        lines.append(f'  "{l}" -> "{m}"{style};')
    lines.append("}")
    return "\n".join(lines) + "\n"
